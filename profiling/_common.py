"""Shared setup for the profiling scripts: the bench problem + timers.

Keeps every profile anchored to the same workload as bench.py (10k rows,
5 features, ops {+,-,*,/,exp,abs,cos}, maxsize 30).

Importing this module is ALSO the one sanctioned way a profiling script
makes the repo-root package importable (``import _common`` replaces the
per-script ``sys.path.insert`` preamble that used to be copy-pasted
across profiling/*.py).
"""

from __future__ import annotations

import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root_on_path() -> str:
    """Idempotently put the repo root on ``sys.path`` so
    ``symbolicregression_jl_tpu`` imports from the checkout."""
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    return REPO_ROOT


repo_root_on_path()

N_ROWS = 10_000
N_FEATURES = 5


def make_bench_problem(n_rows: int = N_ROWS, nfeatures: int = N_FEATURES,
                       **options_kw):
    """(options, dataset, engine) on the bench workload."""
    # jax/numpy imported lazily: `import _common` is also the path
    # preamble of host-only scripts (cpu_baseline, the
    # compile_breakdown orchestrator) that must not pay — or trigger —
    # a module-scope jax import just to find the repo root
    import numpy as np

    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine

    kw = dict(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=30,
        save_to_file=False,
    )
    kw.update(options_kw)
    options = Options(**kw)
    rng = np.random.default_rng(0)
    X = rng.uniform(-3.0, 3.0, (n_rows, nfeatures)).astype(np.float32)
    y = (
        np.cos(2.13 * X[:, 0])
        + 0.5 * X[:, 1] * np.abs(X[:, 2]) ** 0.9
        - 0.3 * np.abs(X[:, 3]) ** 1.5
    ).astype(np.float32)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    engine = Engine(options, ds.nfeatures)
    return options, ds, engine


def timeit(fn, *args, n=10, warmup=2):
    """Queue n calls, block once — amortizes the tunnel round trip.

    Only valid for measuring launch *throughput*; per-call latency on the
    tunneled TPU is meaningless (see .claude/skills/verify gotchas).
    """
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n
