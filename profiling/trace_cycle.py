"""Capture a device trace of the cycle scan and aggregate HLO op times."""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import sys
import time
from collections import defaultdict

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax
import jax.numpy as jnp
import numpy as np

from _common import make_bench_problem


def main():
    I = int(sys.argv[1]) if len(sys.argv) > 1 else 40
    NC = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    P = int(sys.argv[3]) if len(sys.argv) > 3 else 128

    from symbolicregression_jl_tpu import search_key

    options, ds, engine = make_bench_problem(
        populations=I, population_size=P, ncycles_per_iteration=NC,
    )

    state = engine.init_state(search_key(0), ds.data, I)
    state = engine.run_iteration(state, ds.data, options.maxsize)  # compile
    jax.block_until_ready(state.pops.cost)

    logdir = "/tmp/sr_trace"
    os.system(f"rm -rf {logdir}")
    with jax.profiler.trace(logdir):
        state = engine.run_iteration(state, ds.data, options.maxsize)
        jax.block_until_ready(state.pops.cost)

    # aggregate trace events
    files = glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True)
    print("trace files:", files)
    agg = defaultdict(float)
    total = 0.0
    for fn in files:
        with gzip.open(fn, "rt") as f:
            data = json.load(f)
        for ev in data.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            name = ev.get("name", "")
            dur = ev.get("dur", 0) / 1e3  # ms
            pid = ev.get("pid", 0)
            # keep only device lanes (XLA ops); heuristically skip python
            args = ev.get("args", {})
            if "long_name" in args or re.match(
                r"^(fusion|copy|dynamic|scatter|gather|while|select|"
                r"convert|broadcast|reduce|transpose|iota|slice|concatenate|"
                r"dot|cumsum|rng|sort|pad|add|mul|custom|tpu)", name):
                key = re.sub(r"[.\d]+$", "", name)
                agg[key] += dur
                total += dur
    items = sorted(agg.items(), key=lambda kv: -kv[1])[:40]
    print(f"total device op time: {total:.1f} ms over {NC} cycles")
    for k, v in items:
        print(f"  {v:10.3f} ms  {k}")


if __name__ == "__main__":
    main()
