"""Weak-scaling bench: islands proportional to devices, evals/s/device.

Ready for real multi-chip hardware (this machine exposes one tunneled
v5e chip). Per scale it runs the bench problem with ``islands =
islands_per_device * n_devices`` sharded over the island mesh axis and
reports full-dataset evals/s and evals/s/device — flat evals/s/device =
ideal weak scaling, since islands are data-independent (migration is
the only ICI traffic; profiling/ici_model.py bounds it in closed form
at <0.2% of iteration time).

CAVEAT for virtual CPU meshes (xla_force_host_platform_device_count):
the virtual devices SHARE the host's cores, so per-device throughput
mechanically drops ~1/n — the numbers validate that the sharded
program compiles and executes at every shard count (and that total
throughput does not COLLAPSE with sharding), not scaling efficiency.
The real-hardware efficiency projection comes from the ICI byte model;
this harness produces the measured curve the day a v5e-8 is attached.

Usage:
  python profiling/weak_scaling.py                 # all device counts 1..N
  python profiling/weak_scaling.py --islands 64    # islands per device
"""

from __future__ import annotations

import argparse
import json
import time

from _common import make_bench_problem  # noqa: F401 (sys.path setup)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--islands", type=int, default=64,
                    help="islands per device")
    ap.add_argument("--population-size", type=int, default=128)
    ap.add_argument("--ncycles", type=int, default=50)
    args = ap.parse_args()

    import os

    import jax

    from symbolicregression_jl_tpu import search_key
    from symbolicregression_jl_tpu.evolve.engine import Engine
    from symbolicregression_jl_tpu.parallel.mesh import (
        make_mesh,
        shard_device_data,
        shard_search_state,
    )

    devices = jax.devices()
    counts = [n for n in (1, 2, 4, 8, 16, 32) if n <= len(devices)]
    results = []
    for n in counts:
        I = args.islands * n
        options, ds, _ = make_bench_problem(
            populations=I, population_size=args.population_size,
            tournament_selection_n=8,
            ncycles_per_iteration=args.ncycles,
        )
        # Build the engine WITH the mesh so the island-sharded paths
        # (shard_map turbo on TPU; GSPMD-partitioned jnp on CPU) engage.
        mesh = make_mesh(devices[:n], n_island_shards=n, n_data_shards=1)
        engine = Engine(options, ds.nfeatures, n_island_shards=n,
                        mesh=mesh)
        data = shard_device_data(ds.data, mesh)
        state = engine.init_state(search_key(0), data, I)
        state = shard_search_state(state, mesh)
        state = engine.run_iteration(state, data, options.maxsize)
        jax.block_until_ready(state.pops.cost)
        ev0 = float(state.num_evals)
        t0 = time.perf_counter()
        for _ in range(2):
            state = engine.run_iteration(state, data, options.maxsize)
        jax.block_until_ready(state.pops.cost)
        dt = time.perf_counter() - t0
        rate = (float(state.num_evals) - ev0) / dt
        results.append({
            "devices": n, "islands": I, "evals_per_sec": round(rate, 1),
            "evals_per_sec_per_device": round(rate / n, 1),
            "turbo": bool(engine.cfg.turbo),
        })
        print(json.dumps(results[-1]), flush=True)

    payload = {"metric": "weak_scaling_islands_per_device",
               "islands_per_device": args.islands,
               "population_size": args.population_size,
               "ncycles": args.ncycles,
               "backend": jax.default_backend(),
               "points": results}
    print(json.dumps(payload))
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       f"weak_scaling_{jax.default_backend()}.json")
    with open(out, "w") as f:
        json.dump(payload, f, indent=1)
    print("wrote", out)


if __name__ == "__main__":
    main()
