"""Per-dispatch cost attribution of the evolve-cycle candidate-eval path.

The round-6 analogue of opt_attrib.py (VERDICT item 7): the optimizer
kernels run ~866k evals/s while the evolve cycle's candidate evals run
~214k on the same chip — this script says where a cycle's time goes,
dispatch by dispatch, and what the in-kernel cost epilogue
(`ops.fused_eval.fused_cost`, options.fuse_cost_epilogue) removes.

Three instruments:

1. **HLO dispatch census** (backend-independent): compile the 1-cycle
   evolve program with the cost epilogue ON vs OFF and count the
   optimized module's instructions by opcode class. The scan body's
   instruction list is the per-cycle dispatch sequence; the ON-OFF
   delta is exactly the [T]-shaped mean/validity/normalization/
   parsimony chain that the epilogue folds into the kernel's final
   grid step.
2. **Marginal cycle cost**: time the evolve chunk program at 1 and
   1+K cycles; the slope is the per-cycle cost, free of per-launch
   fixed overhead.
3. **Eval-only launch**: time the candidate-eval dispatch alone on an
   [islands, B + k2] batch replicating the generation step's launch
   shape. machinery = marginal cycle - eval; the ratio is the honest
   ceiling on any further eval-kernel work.

Usage: python profiling/cycle_attrib.py [I] [P] [NC] [reps]
  Bench config on TPU: 512 256 100. On CPU the fused path runs in
  Pallas interpret mode — use small I/P; the census (instrument 1) is
  backend-independent, the timings are CPU-relative only.
"""

from __future__ import annotations

import math
import re
import sys
import time
from collections import Counter

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax
import jax.numpy as jnp

from _common import make_bench_problem

# Opcodes that lower to (roughly) one executable dispatch each; the
# rest of the census is grouped to keep the table readable.
_CLASSES = (
    "fusion", "custom-call", "sort", "scatter", "gather", "while",
    "reduce", "dot", "convert", "copy", "iota", "broadcast",
)


def _op_census(hlo_text: str) -> Counter:
    """Instruction counts by opcode over an optimized HLO module."""
    ops = Counter()
    for m in re.finditer(r"=\s+\S+\s+([a-z][\w-]*)\(", hlo_text):
        op = m.group(1)
        ops[op if op in _CLASSES else "other"] += 1
    return ops


def _scan_body_census(hlo_text: str) -> Counter:
    """Census restricted to the largest while-body computation — the
    per-cycle dispatch sequence of the scanned generation step. (While
    bodies are anonymous `%region_N` computations; they are resolved
    through the `body=` operand of each `while` instruction.)"""
    comps = {
        m.group(1).lstrip("%"): m.group(2)
        for m in re.finditer(
            r"^(%?[\w.-]+)\s*\([^)]*\)\s*->[^{]*\{(.*?)^\}",
            hlo_text, re.M | re.S)
    }
    best, best_n = Counter(), -1
    for m in re.finditer(r"body=(%?[\w.-]+)", hlo_text):
        c = _op_census(comps.get(m.group(1).lstrip("%"), ""))
        n = sum(c.values())
        if n > best_n:
            best, best_n = c, n
    return best


def _eval_jaxpr_census(eval_fn, cand, data) -> Counter:
    """Top-level jaxpr primitive census of one candidate-eval call —
    the backend-independent dispatch list of the eval launch (the fused
    kernel rides inside a single pjit eqn, so what this counts is
    exactly the post-kernel epilogue chain plus the launch itself)."""
    jaxpr = jax.make_jaxpr(eval_fn)(cand, data)
    ops = Counter()
    for eqn in jaxpr.jaxpr.eqns:
        ops[eqn.primitive.name] += 1
    return ops


def _mk_engine(I, P, NC, fuse):
    opts, ds, eng = make_bench_problem(
        populations=I, population_size=P, ncycles_per_iteration=NC,
        tournament_selection_n=16, turbo=True, fuse_cost_epilogue=fuse,
    )
    return opts, ds, eng


def _chunk_args(eng, ds, state, maxsize):
    cm, key, k_cycle, k_opt, k_mig, batch_idx, carry = eng._prelude_fn(
        state.key, jnp.int32(maxsize), ds.data.y.shape[0],
        state.birth.shape[0], state.pops.cost.dtype)
    return (state.pops, state.birth, state.ref,
            state.stats.normalized_frequencies, ds.data, cm, k_cycle,
            batch_idx, jnp.int32(0), carry)


def _time(fn, args, reps):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    I = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    NC = int(sys.argv[3]) if len(sys.argv) > 3 else 100
    reps = int(sys.argv[4]) if len(sys.argv) > 4 else 3
    K = 4  # extra cycles for the marginal-cost slope

    from symbolicregression_jl_tpu import search_key
    from symbolicregression_jl_tpu.evolve.step import eval_cost_batch

    print(f"backend={jax.default_backend()}  I={I} P={P} NC={NC}")

    runs = {}
    for fuse in (True, False):
        opts, ds, eng = _mk_engine(I, P, NC, fuse)
        cfg = eng.cfg
        state = eng.init_state(search_key(0), ds.data, I)
        state = eng.run_iteration(state, ds.data, jnp.int32(opts.maxsize))
        jax.block_until_ready(state.pops.cost)

        # ---- 1. dispatch census of the 1-cycle program ----
        args = _chunk_args(eng, ds, state, opts.maxsize)
        fn1 = eng._chunk_fn(1, batching=args[7] is not None)
        hlo = fn1.lower(*args).compile().as_text()
        census = _op_census(hlo)
        body = _scan_body_census(hlo)

        # ---- 2. marginal per-cycle cost ----
        fnK = eng._chunk_fn(1 + K, batching=args[7] is not None)
        t1 = _time(fn1, args, reps)
        tK = _time(fnK, args, reps)
        t_cycle = (tK - t1) / K

        # ---- 3. eval-only launch at the generation step's shape ----
        B = cfg.n_slots
        p_x = cfg.crossover_probability
        if p_x <= 0.0:
            k2 = 0
        elif p_x >= 0.5:
            k2 = B
        else:
            k2 = min(B, int(math.ceil(
                B * p_x + 3.0 * math.sqrt(B * p_x * (1.0 - p_x)) + 1.0)))
        nb = B + k2
        cand = jax.tree.map(lambda x: x[:, :nb], state.pops.trees)

        def eval_batch(trees, data):
            return jax.vmap(lambda t: eval_cost_batch(
                t, data, opts.elementwise_loss, eng.tables, cfg.operators,
                cfg.parsimony, turbo=cfg.turbo, interpret=cfg.interpret,
                tree_block=cfg.eval_tree_block,
                tile_rows=cfg.eval_tile_rows, fuse_cost=cfg.fuse_cost,
            ))(trees)

        eval_fn = jax.jit(eval_batch)
        t_eval = _time(eval_fn, (cand, ds.data), reps)
        jx = _eval_jaxpr_census(eval_batch, cand, ds.data)

        evals = I * nb
        runs[fuse] = dict(census=census, body=body, jx=jx, t_cycle=t_cycle,
                          t_eval=t_eval, evals=evals)
        tag = "fused-cost" if fuse else "materializing"
        print(f"\n== {tag} ==")
        print(f"  1-cycle program census (module): "
              f"{sum(census.values())} executable ops")
        print("   ", dict(census.most_common()))
        if body:
            print(f"  scan-body (per-cycle dispatch sequence): "
                  f"{sum(body.values())} ops")
            print("   ", dict(body.most_common()))
        print(f"  eval-launch jaxpr (kernel opaque as one pjit): "
              f"{sum(jx.values())} primitives")
        print("   ", dict(jx.most_common()))
        print(f"  marginal cycle: {t_cycle * 1e3:8.2f} ms  "
              f"({evals} candidate evals -> "
              f"{evals / max(t_cycle, 1e-12):,.0f} evals/s)")
        print(f"  eval-only launch: {t_eval * 1e3:8.2f} ms  "
              f"({evals / max(t_eval, 1e-12):,.0f} evals/s)")
        print(f"  machinery (cycle - eval): "
              f"{(t_cycle - t_eval) * 1e3:8.2f} ms "
              f"({100 * (t_cycle - t_eval) / max(t_cycle, 1e-12):.0f}% "
              f"of the cycle)")

    on, off = runs[True], runs[False]
    d_mod = sum(off["census"].values()) - sum(on["census"].values())
    d_body = sum(off["body"].values()) - sum(on["body"].values())
    d_jx = sum(off["jx"].values()) - sum(on["jx"].values())
    print("\n== epilogue fusion delta (materializing - fused) ==")
    print(f"  eval-launch jaxpr primitives: {d_jx:+d} "
          f"(the post-kernel loss->cost chain)")
    print(f"  module ops: {d_mod:+d}   scan-body ops/cycle: {d_body:+d}")
    print(f"  marginal cycle: {(off['t_cycle'] - on['t_cycle']) * 1e3:+.2f} ms"
          f"   eval launch: {(off['t_eval'] - on['t_eval']) * 1e3:+.2f} ms")
    if jax.default_backend() != "tpu":
        print("\n(note: off-TPU the fused kernel runs in Pallas interpret "
              "mode — HLO/kernel-side counts and all timings are "
              "CPU-relative; the jaxpr delta is backend-independent.)")


if __name__ == "__main__":
    main()
