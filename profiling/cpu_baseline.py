"""Measured CPU baseline: multithreaded full-dataset tree evaluations/sec.

Stands in for the reference's CPU-multithreaded evaluation rate on the
bench problem (10k rows, 5 features, ops {+,-,*,/,exp,abs,cos},
maxsize=30). The reference's hot loop evaluates one expression over the
whole dataset per mutation attempt with a fused SIMD interpreter
(LoopVectorization `turbo`); the closest honest Python-host equivalent is
a recursive numpy evaluator with one vectorized op per node, parallelized
across expressions with a thread pool (numpy releases the GIL).

Prints a JSON line: {"cpu_evals_per_sec": N, "threads": T, "n_trees": K}.
BASELINE.md records the measured number; bench.py's vs_baseline uses it.
"""

from __future__ import annotations

import concurrent.futures as cf
import json
import os
import time

import numpy as np

import _common  # noqa: F401,E402  (repo root on sys.path)


def main():
    import jax

    jax.config.update("jax_platforms", "cpu")

    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.evolve.mutation import (
        MutationContext,
        gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.ops.encoding import decode_population

    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=30,
        save_to_file=False,
    )
    rng = np.random.default_rng(0)
    n_rows = 10_000
    X = rng.uniform(-3.0, 3.0, (n_rows, 5)).astype(np.float32)
    y = np.cos(2.13 * X[:, 0]).astype(np.float32)
    cols = [np.ascontiguousarray(X[:, j]) for j in range(X.shape[1])]

    # population of random trees matching the search's size distribution
    ctx = MutationContext(
        nops=(3, 4), nfeatures=5, max_nodes=30,
        perturbation_factor=0.076, probability_negate_constant=0.01,
    )
    import jax.numpy as jnp
    import jax as _jax

    K = 512
    sizes = _jax.random.randint(_jax.random.PRNGKey(1), (K,), 3, 30)
    batch = _jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, ctx, jnp.float32)
    )(_jax.random.split(_jax.random.PRNGKey(0), K), sizes)
    trees = decode_population(batch, options.operators)

    UN = {
        "exp": np.exp, "abs": np.abs, "cos": np.cos,
    }
    BIN = {
        "+": np.add, "-": np.subtract, "*": np.multiply, "/": np.divide,
    }

    def eval_node(node):
        if node.degree == 0:
            if node.constant:
                return np.full(n_rows, node.val, np.float32)
            return cols[node.feature]
        if node.degree == 1:
            return UN[node.op.name](eval_node(node.children[0]))
        return BIN[node.op.name](
            eval_node(node.children[0]), eval_node(node.children[1])
        )

    def eval_loss(tree):
        with np.errstate(all="ignore"):
            pred = eval_node(tree)
            d = pred - y
            return float(np.mean(d * d))

    threads = os.cpu_count() or 1

    # warmup
    for t in trees[:8]:
        eval_loss(t)

    REPEAT = 4
    t0 = time.perf_counter()
    with cf.ThreadPoolExecutor(max_workers=threads) as ex:
        for _ in range(REPEAT):
            list(ex.map(eval_loss, trees))
    dt = time.perf_counter() - t0
    rate = REPEAT * len(trees) / dt
    print(json.dumps({
        "cpu_evals_per_sec": round(rate, 1),
        "threads": threads,
        "n_trees": len(trees),
        "n_rows": n_rows,
    }))


if __name__ == "__main__":
    main()
