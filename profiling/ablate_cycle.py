"""Ablate one evolution cycle: where does per-cycle time go?

Builds scan-of-N programs (all inside one jit, like s_r_cycle) for:
  full   — the real generation_step
  noeval — generation_step with the eval replaced by a dummy loss
  evalo  — eval-only (fused kernel on the same candidate count)
  struct — tree_structure_arrays on the attempt batch only

Run: python profiling/ablate_cycle.py [islands] [ncycles]
"""

from __future__ import annotations

import dataclasses
import sys
import time
from functools import partial

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax
import jax.numpy as jnp
import numpy as np


def main():
    I = int(sys.argv[1]) if len(sys.argv) > 1 else 15
    NC = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    P = int(sys.argv[3]) if len(sys.argv) > 3 else 33
    ATT = int(sys.argv[4]) if len(sys.argv) > 4 else 5

    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine
    from symbolicregression_jl_tpu.evolve import step as S
    from symbolicregression_jl_tpu.ops.encoding import tree_structure_arrays
    from symbolicregression_jl_tpu.ops.fused_eval import fused_loss

    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=30,
        populations=I,
        population_size=P,
        ncycles_per_iteration=NC,
        mutation_attempts=ATT,
        save_to_file=False,
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-3.0, 3.0, (10_000, 5)).astype(np.float32)
    y = np.cos(2.13 * X[:, 0]).astype(np.float32)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    engine = Engine(options, ds.nfeatures)
    cfg = engine.cfg
    print(f"I={I} P={cfg.population_size} slots={cfg.n_slots} "
          f"attempts={cfg.attempts} NC={NC} turbo={cfg.turbo}")

    state = engine.init_state(jax.random.PRNGKey(0), ds.data, I)
    pops = state.pops
    nf = state.stats.normalized_frequencies

    def one_cycle(pop, c, eval_dummy=False):
        k = jax.random.fold_in(jax.random.PRNGKey(1), c)
        ev = S.eval_cost_batch
        if eval_dummy:
            def ev(trees, data, *a, **kw):
                # same shapes, trivial compute
                cost = jnp.sum(trees.const, axis=-1)
                return cost, cost, jnp.sum(trees.arity, axis=-1)
        orig = S.eval_cost_batch
        S.eval_cost_batch = ev
        try:
            def isl(kk, p, b, r):
                return S.generation_step(
                    kk, p, ds.data, nf, jnp.float32(1.0),
                    jnp.int32(30), b, r, cfg, options, engine.tables,
                    options.elementwise_loss)
            keys = jax.random.split(k, I)
            newpop, nev, b, r = jax.vmap(isl)(
                keys, pop, jnp.zeros((I,), jnp.int32), jnp.zeros((I,), jnp.int32))
        finally:
            S.eval_cost_batch = orig
        return newpop

    def make_scan(eval_dummy):
        def prog(pop):
            def body(p, c):
                return one_cycle(p, c, eval_dummy), None
            pop, _ = jax.lax.scan(body, pop, jnp.arange(NC))
            return pop
        return jax.jit(prog)

    def time_prog(f, arg):
        out = f(arg)
        jax.block_until_ready(jax.tree.leaves(out)[0])
        t0 = time.perf_counter()
        out = f(arg)
        jax.block_until_ready(jax.tree.leaves(out)[0])
        return (time.perf_counter() - t0) / NC

    t_full = time_prog(make_scan(False), pops)
    t_noev = time_prog(make_scan(True), pops)
    print(f"full cycle:   {t_full*1e3:8.2f} ms")
    print(f"no-eval:      {t_noev*1e3:8.2f} ms")

    # eval-only scan on same candidate count (I * slots * 2 trees)
    T = I * cfg.n_slots * 2
    from symbolicregression_jl_tpu.evolve.population import init_population
    trees = init_population(jax.random.PRNGKey(0), T, cfg.mctx, jnp.float32)

    def eval_prog(tr):
        def body(t, c):
            loss, valid = fused_loss(
                t, ds.data.Xt, ds.data.y, None, cfg.operators,
                options.elementwise_loss, interpret=cfg.interpret)
            eps = jnp.nanmin(jnp.where(jnp.isfinite(loss), loss, jnp.inf))
            return dataclasses.replace(t, const=t.const + eps * 1e-12), None
        t, _ = jax.lax.scan(body, tr, jnp.arange(NC))
        return t
    t_eval = time_prog(jax.jit(eval_prog), trees)
    print(f"eval-only({T}): {t_eval*1e3:8.2f} ms")

    # structure-derivation-only scan on the attempt batch [I*slots*A]
    TA = I * cfg.n_slots * cfg.attempts
    atrees = init_population(jax.random.PRNGKey(1), TA, cfg.mctx, jnp.float32)

    def struct_prog(tr):
        def body(t, c):
            ch, sz, dp = tree_structure_arrays(t)
            return dataclasses.replace(
                t, feat=jnp.clip(t.feat + sz % 2, 0, 4)), None
        t, _ = jax.lax.scan(body, tr, jnp.arange(NC))
        return t
    t_struct = time_prog(jax.jit(struct_prog), atrees)
    print(f"struct-only({TA}): {t_struct*1e3:8.2f} ms (one of ~3 calls/cycle)")


if __name__ == "__main__":
    main()
