"""BASELINE.json config-5 bench: TemplateExpression multi-subtree eval.

Measures (a) batched template evaluation throughput (members/s over the
full dataset) and (b) a short template search's evals/s, on the
reference-style structured law  y = f(x1, x2) + g(x3)  with
f = x1*x2, g = 2 cos(x3) (10k rows).

Run on the TPU: python profiling/template_bench.py
"""

from __future__ import annotations

import json
import sys
import time

from _common import N_FEATURES, N_ROWS, make_bench_problem  # noqa: F401  (path setup)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.models import template_spec
    from symbolicregression_jl_tpu.models.template import eval_template_batch
    from symbolicregression_jl_tpu.evolve.population import (
        init_template_population,
    )
    from symbolicregression_jl_tpu.evolve.engine import Engine
    from symbolicregression_jl_tpu.core.dataset import make_dataset

    spec = template_spec(expressions=("f", "g"))(
        lambda f, g, x1, x2, x3: f(x1, x2) + g(x3)
    )
    st = spec.structure
    rng = np.random.default_rng(0)
    X = rng.uniform(-3, 3, (10_000, 3)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 2.0 * np.cos(X[:, 2])).astype(np.float32)

    options = sr.Options(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=20,
        populations=16,
        population_size=33,
        ncycles_per_iteration=40,
        expression_spec=spec,
        save_to_file=False,
    )
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    engine = Engine(options, ds.nfeatures, template=st)

    # (a) raw batched template eval throughput
    T = 512
    trees = init_template_population(
        sr.search_key(0), T, st, engine.cfg.mctx, jnp.float32
    )

    fused = jax.default_backend() == "tpu"

    @jax.jit
    def prog(tr):
        def body(c, _):
            yv, valid = eval_template_batch(tr, ds.data.Xt, st,
                                            options.operators, fused=fused)
            return c + jnp.sum(jnp.where(valid, yv[:, 0], 0.0)), None
        out, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=10)
        return out

    out = prog(trees)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = prog(trees)
    jax.block_until_ready(out)
    eval_rate = T * 10 / (time.perf_counter() - t0)

    # (b) short search evals/s (historic 16x33 config)
    state = engine.init_state(sr.search_key(0), ds.data, options.populations)
    state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    ev0 = float(state.num_evals)
    t0 = time.perf_counter()
    for _ in range(2):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    search_rate = (float(state.num_evals) - ev0) / (time.perf_counter() - t0)

    # (c) template-vs-plain ratio at an IDENTICAL island-scaled config —
    # the round-2 "45% of plain search" number compared a 16x33 template
    # search against the 256x256 plain bench, which mostly measured
    # config scale, not template overhead.
    def search_rate_at(spec_arg):
        opts = sr.Options(
            binary_operators=["+", "-", "*"],
            unary_operators=["cos"],
            maxsize=20, populations=64, population_size=64,
            tournament_selection_n=8, ncycles_per_iteration=40,
            expression_spec=spec_arg, save_to_file=False,
        )
        eng = Engine(opts, ds.nfeatures,
                     template=(st if spec_arg is not None else None))
        s0 = eng.init_state(sr.search_key(0), ds.data, opts.populations)
        s0 = eng.run_iteration(s0, ds.data, opts.maxsize)
        jax.block_until_ready(s0.pops.cost)
        e0 = float(s0.num_evals)
        t0 = time.perf_counter()
        for _ in range(2):
            s0 = eng.run_iteration(s0, ds.data, opts.maxsize)
        jax.block_until_ready(s0.pops.cost)
        return (float(s0.num_evals) - e0) / (time.perf_counter() - t0)

    tmpl_64 = search_rate_at(spec)
    plain_64 = search_rate_at(None)

    print(json.dumps({
        "metric": "template_config5_eval_and_search",
        "template_eval_members_per_sec_10k_rows": round(eval_rate, 1),
        "template_search_evals_per_sec_10k_rows": round(search_rate, 1),
        "template_search_64x64": round(tmpl_64, 1),
        "plain_search_64x64": round(plain_64, 1),
        "template_over_plain_same_config": round(tmpl_64 / plain_64, 3),
    }))


if __name__ == "__main__":
    main()
