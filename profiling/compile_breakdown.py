"""Cold-start compile breakdown for a device-scale quickstart fit.

Runs `SRRegressor(device_scale="auto").fit()` in a FRESH process with
the persistent compile cache disabled and `jax_log_compiles` on, then
aggregates the logged per-module compile times — showing where the
cold-start minutes go (evolve chunk programs, epilogue, init, eval
paths) and what the floor is.

Usage:
  python profiling/compile_breakdown.py          # orchestrates the child
  python profiling/compile_breakdown.py --child  # the measured fit
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
import time

import _common  # noqa: F401,E402  (repo root on sys.path)


def child():
    import logging

    logging.basicConfig(level=logging.WARNING)
    import jax

    jax.config.update("jax_log_compiles", True)
    # SR_XLA_EFFORT is honored by equation_search itself
    # (_apply_compile_effort) before anything compiles.
    logging.getLogger("jax._src.interpreters.pxla").setLevel(logging.DEBUG)
    logging.getLogger("jax._src.dispatch").setLevel(logging.DEBUG)

    import numpy as np

    import symbolicregression_jl_tpu as sr

    rng = np.random.default_rng(0)
    X = rng.uniform(-3, 3, (500, 2)).astype(np.float32)
    y = (2.0 * np.cos(23.5 * X[:, 0]) - X[:, 1] ** 2).astype(np.float32)
    t0 = time.perf_counter()
    model = sr.SRRegressor(niterations=2, binary_operators=["+", "-", "*"],
                           unary_operators=["cos"])
    model.fit(X, y)
    print(f"TOTAL_FIT_SECONDS {time.perf_counter() - t0:.1f}", flush=True)


def main():
    if "--child" in sys.argv:
        child()
        return
    env = dict(os.environ)
    env["SR_NO_COMPILE_CACHE"] = "1"   # cold start
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        capture_output=True, text=True, env=env, timeout=3600)
    wall = time.time() - t0
    txt = proc.stderr + proc.stdout
    # jax_log_compiles lines: "Finished XLA compilation of <name> in <t> sec"
    pat = re.compile(
        r"Finished (?:tracing \+ transforming|XLA compilation) of ([^\n]*?) "
        r"in ([0-9.]+) sec")
    agg = {}
    for m in pat.finditer(txt):
        name, secs = m.group(1), float(m.group(2))
        key = name.strip()[:60]
        agg[key] = agg.get(key, 0.0) + secs
    total_line = next((l for l in txt.splitlines()
                       if l.startswith("TOTAL_FIT_SECONDS")), "?")
    print(f"cold quickstart subprocess wall: {wall:.1f}s   {total_line}")
    print("compile-time aggregation (top 20):")
    for k, v in sorted(agg.items(), key=lambda kv: -kv[1])[:20]:
        print(f"  {v:8.1f} s  {k}")
    print(f"  {sum(agg.values()):8.1f} s  TOTAL logged compile")
    if proc.returncode != 0:
        print("CHILD FAILED:\n", proc.stderr[-2000:])


if __name__ == "__main__":
    main()
