"""Honest wall-clock phase split of a bench-config iteration.

Times three engine configs on the real chip (dependency-chained
iterations, compile excluded):
  A: full bench config            -> t_full
  B: A minus constant optimizer   -> t_noopt   (optimizer = A - B)
  C: B at ncycles=10              -> per-cycle = (B - C) / 90,
                                     fixed epilogue = C - 10*per_cycle

Usage: phase_timing.py [islands] [pop] [ncycles]
"""

from __future__ import annotations

import sys
import time

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax

from _common import make_bench_problem


def time_config(I, P, NC, iters=2, **kw):
    from symbolicregression_jl_tpu import search_key

    options, ds, engine = make_bench_problem(
        populations=I, population_size=P, ncycles_per_iteration=NC,
        tournament_selection_n=16, **kw)
    state = engine.init_state(search_key(0), ds.data, I)
    state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    e0 = float(state.num_evals)
    t0 = time.perf_counter()
    for _ in range(iters):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    dt = (time.perf_counter() - t0) / iters
    ev = (float(state.num_evals) - e0) / iters
    return dt, ev


def main():
    I = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    NC = int(sys.argv[3]) if len(sys.argv) > 3 else 100

    tA, evA = time_config(I, P, NC)
    print(f"A full:      {tA:7.3f} s/iter  {evA:12.0f} evals  "
          f"{evA/tA:10.0f} ev/s")
    tB, evB = time_config(I, P, NC, should_optimize_constants=False)
    print(f"B no-opt:    {tB:7.3f} s/iter  {evB:12.0f} evals  "
          f"{evB/tB:10.0f} ev/s")
    tC, evC = time_config(I, P, 10, should_optimize_constants=False)
    print(f"C no-opt/10c:{tC:7.3f} s/iter  {evC:12.0f} evals")
    per_cycle = (tB - tC) / (NC - 10)
    fixed = tC - 10 * per_cycle
    print(f"optimizer phase:   {tA - tB:7.3f} s/iter "
          f"({evA - evB:12.0f} evals -> {(evA-evB)/max(tA-tB,1e-9):10.0f} ev/s)")
    print(f"evolve cycles:     {per_cycle*1e3:7.2f} ms/cycle x {NC} "
          f"= {per_cycle*NC:7.3f} s/iter "
          f"({evB - evC:12.0f} evals over {NC-10} cycles -> "
          f"{(evB-evC)/((NC-10)*per_cycle):10.0f} ev/s)")
    print(f"fixed epilogue:    {fixed:7.3f} s/iter")


if __name__ == "__main__":
    main()
