"""True per-iteration optimizer cost via shared-start-state A/B.

The A-B phase split in phase_timing.py compares two *chained* runs, so
the with-optimizer and without-optimizer populations diverge and the
difference conflates optimizer cost with evolution divergence. Here
both engines run ONE iteration from the SAME warmed state (copied
first — run_iteration donates its state arg), so the diff is the
optimizer block alone (+ the finalize re-eval's constant values, same
shapes/cost).

Round-5 result (512x256x100c, bench problem): per-iteration optimizer
cost oscillates 1.3-8.3 s with the adaptive-parsimony grow/collapse
cycle of the population (mean tree length swings ~5 <-> ~23); the
no-opt remainder swings only 2.8-5.0 s. The driver of optimizer cost
is the selected trees' program length at epilogue time, not any
kernel-plan inefficiency (see opt_bench.py sweeps: V-chunk, tile
budget, tree_block, pass-count variants all within +-2%).
"""

from __future__ import annotations

import sys
import time

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax
import jax.numpy as jnp

from _common import make_bench_problem


def main():
    I = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    NC = int(sys.argv[3]) if len(sys.argv) > 3 else 100
    iters = int(sys.argv[4]) if len(sys.argv) > 4 else 6

    from symbolicregression_jl_tpu import search_key

    kw = dict(populations=I, population_size=P, ncycles_per_iteration=NC,
              tournament_selection_n=16)
    optA, ds, engA = make_bench_problem(**kw)
    optB, _, engB = make_bench_problem(should_optimize_constants=False, **kw)
    copy = jax.jit(lambda s: jax.tree.map(jnp.copy, s))

    state = engA.init_state(search_key(0), ds.data, I)
    state = engA.run_iteration(copy(state), ds.data, optA.maxsize)
    jax.block_until_ready(state.pops.cost)
    sB = engB.run_iteration(copy(state), ds.data, optB.maxsize)  # warm B
    jax.block_until_ready(sB.pops.cost)

    for it in range(2, 2 + iters):
        ml = float(jnp.mean(state.pops.trees.length))
        sc = copy(state)
        jax.block_until_ready(sc.pops.cost)
        t0 = time.perf_counter()
        sA = engA.run_iteration(sc, ds.data, optA.maxsize)
        jax.block_until_ready(sA.pops.cost)
        tA = time.perf_counter() - t0
        sc = copy(state)
        jax.block_until_ready(sc.pops.cost)
        t0 = time.perf_counter()
        sB = engB.run_iteration(sc, ds.data, optB.maxsize)
        jax.block_until_ready(sB.pops.cost)
        tB = time.perf_counter() - t0
        print(f"iter {it}: A {tA:6.3f}s  B(no-opt) {tB:6.3f}s  "
              f"opt {tA - tB:6.3f}s  (start mean len {ml:5.1f})")
        state = sA


if __name__ == "__main__":
    main()
