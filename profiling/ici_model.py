"""Closed-form per-iteration ICI byte accounting for island sharding.

Islands are data-independent for the whole evolve+optimize+finalize
body (it runs island-local inside shard_map, engine._island_epilogue /
generation_step); cross-device traffic exists ONLY in the epilogue's
global phases (engine._epilogue_part):

1. migration pool all_gather — each island contributes its topn best
   member rows; the pool [I*topn rows] is consumed by every shard
   (src/Migration.jl:15-37 analogue; the reshape under GSPMD lowers to
   an all-gather over the island axis).
2. hall-of-fame merge — update_hof reduces per-complexity argmin over
   the member axis; XLA partitions this as per-shard partial HoFs +
   a cross-shard combine. Upper bound used here: an all-gather of the
   full flattened population (the partitioner never moves more than
   that; the partitioned reduction moves ~maxsize rows * log2 D).
3. hof-migration pool — the merged global HoF (maxsize rows) broadcast.
4. running-stats histogram psum — maxsize f32.

Everything else (cycles, fold, constant optimizer, finalize evals) is
island-local: ZERO ICI bytes by construction.

All quantities are computable from the config; this script prints the
per-iteration byte volumes, the time at an assumed ICI bandwidth, and
the communication-bound weak-scaling efficiency for a v5e-8.

Usage: python profiling/ici_model.py [--islands 512] [--pop 256] ...
(pure host arithmetic: no jax, no device).
"""

from __future__ import annotations

import argparse
import json


def member_row_bytes(L: int, n_params: int = 0, n_classes: int = 0) -> int:
    """One PopulationState member row: TreeBatch fields + metadata."""
    tree = 3 * 4 * L + 4 * L + 4        # arity/op/feat i32, const f32, length
    meta = 6 * 4                        # cost loss complexity birth ref parent
    params = 4 * n_params * max(n_classes, 1 if n_params else 0)
    return tree + meta + params


def model(I, P, L, topn, maxsize, n_devices, iter_seconds,
          ici_gbps, n_params=0, n_classes=0):
    row = member_row_bytes(L, n_params, n_classes)
    D = n_devices
    ag_factor = (D - 1) / D  # per-device bytes moved by an all-gather

    pool_bytes = I * topn * row * ag_factor
    hof_upper = I * P * row * ag_factor          # partitioner worst case
    hof_typical = maxsize * row * max(D - 1, 0)  # partial-HoF combine
    hof_bcast = maxsize * row * ag_factor
    stats = 2 * maxsize * 4

    total_upper = pool_bytes + hof_upper + hof_bcast + stats
    total_typical = pool_bytes + hof_typical + hof_bcast + stats
    bw = ici_gbps * 1e9 / 8  # bytes/s per device
    t_upper = total_upper / bw
    t_typical = total_typical / bw
    return {
        "member_row_bytes": row,
        "migration_pool_MB": round(pool_bytes / 2**20, 3),
        "hof_merge_MB_upper": round(hof_upper / 2**20, 3),
        "hof_merge_MB_typical": round(hof_typical / 2**20, 4),
        "hof_broadcast_MB": round(hof_bcast / 2**20, 4),
        "total_MB_per_iter_upper": round(total_upper / 2**20, 3),
        "total_MB_per_iter_typical": round(total_typical / 2**20, 3),
        "ici_seconds_per_iter_upper": round(t_upper, 6),
        "ici_seconds_per_iter_typical": round(t_typical, 6),
        "iter_seconds": iter_seconds,
        "comm_fraction_upper": round(t_upper / iter_seconds, 8),
        "weak_scaling_comm_efficiency_lower_bound": round(
            1.0 / (1.0 + t_upper / iter_seconds), 6),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--islands", type=int, default=512,
                    help="islands PER DEVICE (weak scaling)")
    ap.add_argument("--pop", type=int, default=256)
    ap.add_argument("--maxsize", type=int, default=30)
    ap.add_argument("--topn", type=int, default=12)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--iter-seconds", type=float, default=8.5,
                    help="measured single-chip bench iteration")
    ap.add_argument("--ici-gbps", type=float, default=400.0,
                    help="effective per-device ICI bandwidth, Gbit/s "
                         "(v5e: 4 links x 400 Gbps raw; 400 effective "
                         "is deliberately conservative ~25%%)")
    args = ap.parse_args()

    # Weak scaling: the GLOBAL island count grows with devices; each
    # device keeps --islands local islands, and the all-gathered pool
    # grows with global I.
    I_global = args.islands * args.devices
    out = model(I_global, args.pop, args.maxsize, args.topn, args.maxsize,
                args.devices, args.iter_seconds, args.ici_gbps)
    out["config"] = {
        "islands_per_device": args.islands, "global_islands": I_global,
        "population_size": args.pop, "maxsize": args.maxsize,
        "topn": args.topn, "devices": args.devices,
        "ici_gbps_assumed": args.ici_gbps,
    }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
