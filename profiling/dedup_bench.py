"""Correctness + perf microbench of fused_loss_dedup vs fused_loss_program.

Captures a REAL evolved candidate batch (same protocol as dup_rate.py),
then times both eval paths on it on the real chip.

Usage: dedup_bench.py [islands] [pop] [V]
"""

from __future__ import annotations

import sys
import time

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax
import jax.numpy as jnp
import numpy as np

from _common import make_bench_problem, timeit


def main():
    I = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    V = int(sys.argv[3]) if len(sys.argv) > 3 else 4

    from symbolicregression_jl_tpu import search_key
    from symbolicregression_jl_tpu.evolve.step import generation_step
    from symbolicregression_jl_tpu.ops.program import compile_program
    from symbolicregression_jl_tpu.ops.fused_eval import (
        fused_loss_dedup, fused_loss_program)

    options, ds, engine = make_bench_problem(
        populations=I, population_size=P, ncycles_per_iteration=100,
        tournament_selection_n=16)
    cfg = engine.cfg
    state = engine.init_state(search_key(0), ds.data, I)
    for _ in range(2):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)

    @jax.jit
    def capture(key, pops, birth, ref, stats_nf, marks):
        def island(k, pop, b, r, m):
            return generation_step(
                k, pop, ds.data, stats_nf, jnp.float32(0.5),
                jnp.int32(options.maxsize), b, r, cfg, options,
                engine.tables, options.elementwise_loss, marks=m,
                return_candidates=True)
        return jax.vmap(island)(key, pops, birth, ref, marks)

    marks = (jnp.zeros((I, P), jnp.bool_), jnp.zeros((I, P), jnp.bool_))
    keys = jax.random.split(state.key, I)
    out = capture(keys, state.pops, state.birth, state.ref,
                  state.stats.normalized_frequencies, marks)
    cand = out[-1]
    flat = jax.tree.map(lambda x: x.reshape((-1,) + x.shape[2:]), cand)
    T = flat.arity.shape[0]
    print(f"captured candidate batch: {T} trees")

    n_binary = len(cfg.operators.binary)
    F = ds.nfeatures
    prog = jax.jit(lambda t: compile_program(t, F, n_binary))(flat)
    prog = jax.block_until_ready(prog)

    X, y, w = ds.data.Xt, ds.data.y, ds.data.weights
    el = options.elementwise_loss

    f_plain = jax.jit(lambda p: fused_loss_program(
        p, X, y, w, F, cfg.operators, el))
    f_dedup = jax.jit(lambda p: fused_loss_dedup(
        p, X, y, w, F, cfg.operators, el))

    la, va = jax.block_until_ready(f_plain(prog))
    lb, vb = jax.block_until_ready(f_dedup(prog))
    la, va, lb, vb = map(np.asarray, (la, va, lb, vb))
    both_finite = np.isfinite(la) & np.isfinite(lb)
    exact = np.mean((la == lb) | (~np.isfinite(la) & ~np.isfinite(lb)))
    if both_finite.any():
        rel = np.abs(la[both_finite] - lb[both_finite]) / np.maximum(
            np.abs(la[both_finite]), 1e-30)
        print(f"agreement: exact {exact:.4f}, max rel diff "
              f"{rel.max():.3e}, valid mismatch {(va != vb).mean():.5f}")
    inf_a, inf_b = (~np.isfinite(la)).mean(), (~np.isfinite(lb)).mean()
    print(f"inf rates: plain {inf_a:.4f} dedup {inf_b:.4f}")

    ta = timeit(f_plain, prog, n=20, warmup=3)
    tb = timeit(f_dedup, prog, n=20, warmup=3)
    print(f"plain : {ta * 1e3:8.3f} ms/launch ({T / ta:,.0f} trees/s)")
    print(f"dedup : {tb * 1e3:8.3f} ms/launch ({T / tb:,.0f} trees/s) "
          f"speedup {ta / tb:.2f}x")


if __name__ == "__main__":
    main()
