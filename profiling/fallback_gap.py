"""Measured cost of the jnp-interpreter fallback vs the fused path.

Row-sharded datasets (n_data_shards > 1) drop turbo: `pl.pallas_call`
has no GSPMD partitioning rule, and the jnp interpreter partitions
cleanly with the loss reduction lowering to a psum over the data axis
(evolve/step.py evolve_config_from_options). This harness quantifies
what that fallback costs at bench scale on ONE chip: the same config
with turbo forced off vs on — the per-device work of a row-sharded
N-chip run is exactly the turbo-off leg on 1/N of the rows, so the
single-chip gap bounds the per-device gap.

Usage: python profiling/fallback_gap.py [islands] [pop] [ncycles]
"""

from __future__ import annotations

import json
import os
import sys
import time

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax

from _common import make_bench_problem


def time_one(turbo, I, P, NC, iters=2):
    from symbolicregression_jl_tpu import search_key

    options, ds, engine = make_bench_problem(
        populations=I, population_size=P, ncycles_per_iteration=NC,
        tournament_selection_n=16, turbo=turbo)
    state = engine.init_state(search_key(0), ds.data, I)
    state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    e0 = float(state.num_evals)
    t0 = time.perf_counter()
    for _ in range(iters):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    dt = (time.perf_counter() - t0) / iters
    ev = (float(state.num_evals) - e0) / iters
    return ev / dt


def main():
    I = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    NC = int(sys.argv[3]) if len(sys.argv) > 3 else 100

    r_turbo = time_one(True, I, P, NC)
    r_jnp = time_one(False, I, P, NC)
    out = {
        "metric": "turbo_vs_jnp_fallback_evals_per_sec",
        "config": {"islands": I, "population_size": P, "ncycles": NC},
        "turbo": round(r_turbo, 1),
        "jnp_fallback": round(r_jnp, 1),
        "gap_x": round(r_turbo / r_jnp, 2),
    }
    print(json.dumps(out))
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "fallback_gap.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
