"""Throughput measurement with forced dependency chains.

Each launch's input depends on the previous launch's output, so the
device must execute them sequentially; host queues all launches and
blocks once. This amortizes the tunnel round-trip latency and defeats
any caching of identical executions.
"""

from __future__ import annotations

import sys
import time

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax
import jax.numpy as jnp
import numpy as np


def main():
    T = int(sys.argv[1])
    TB = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    TILE = int(sys.argv[3]) if len(sys.argv) > 3 else 2048
    N_CHAIN = int(sys.argv[4]) if len(sys.argv) > 4 else 30

    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine
    from symbolicregression_jl_tpu.evolve.population import init_population
    from symbolicregression_jl_tpu.ops.fused_eval import fused_loss

    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=30,
        save_to_file=False,
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-3.0, 3.0, (10_000, 5)).astype(np.float32)
    y = np.cos(2.13 * X[:, 0]).astype(np.float32)
    ds = make_dataset(X, y)
    engine = Engine(options, ds.nfeatures)
    cfg = engine.cfg

    trees = init_population(jax.random.PRNGKey(0), T, cfg.mctx, jnp.float32)

    @jax.jit
    def step(tr):
        loss, valid = fused_loss(
            tr, ds.data.Xt, ds.data.y, None, cfg.operators,
            options.elementwise_loss, tree_block=TB, tile_rows=TILE,
            interpret=cfg.interpret)
        # feed a loss-derived epsilon back into consts -> data dependency
        eps = jnp.nanmin(jnp.where(jnp.isfinite(loss), loss, jnp.inf)) * 1e-12
        import dataclasses
        return dataclasses.replace(tr, const=tr.const + eps)

    tr = step(trees)  # compile
    jax.block_until_ready(tr.const)

    t0 = time.perf_counter()
    for _ in range(N_CHAIN):
        tr = step(tr)
    jax.block_until_ready(tr.const)
    dt = (time.perf_counter() - t0) / N_CHAIN
    print(f"T={T} TB={TB} TILE={TILE}: {dt*1e3:.3f} ms/launch  "
          f"{T/dt:.0f} ev/s")


if __name__ == "__main__":
    main()
