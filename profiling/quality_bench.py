"""Search-QUALITY benchmark: loss-vs-wall-clock Pareto fronts, TPU vs CPU.

Throughput (bench.py) says how fast evals run; this harness asks whether
the searches *find equally good equations per unit wall-clock*. It runs
the same engine (same algorithm, same options) on the TPU backend
(turbo Pallas kernels) and on the multithreaded XLA CPU backend (jnp
interpreter path — the measured-CPU reference point from
profiling/cpu_baseline.py / BASELINE.md), over:

- the reference benchmark problem
  (/root/reference/benchmark/benchmarks.jl:11-33: n=1000 rows, 5
  features, ops {+,-,*,/} ∪ {exp,abs}, maxsize=30, target
  cos(2.13x₁)+0.5x₂|x₃|^0.9−0.3|x₄|^1.5 + 0.1·noise), and
- a 10-problem Feynman-style suite (2-5 variables, physics forms).

Each run gets a fixed wall-clock budget (compile excluded via one warmup
iteration at identical shapes) and N seeds; after every iteration the
harness records (elapsed, best_loss, pareto front). Results aggregate to
``profiling/quality_results.json``; BASELINE.md summarizes.

Usage:
  python profiling/quality_bench.py --run PROBLEM PLATFORM SEED BUDGET
      (single run; prints one JSON line — used via subprocess so each
       run gets a fresh process pinned to its backend)
  python profiling/quality_bench.py --suite [--budget-bench 60]
      [--budget-feynman 40] [--seeds-bench 4] [--seeds-feynman 2]
      (full matrix -> profiling/quality_results.json)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import tempfile
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

DEFAULT_OPS = dict(binary_operators=["+", "-", "*", "/"],
                   unary_operators=["exp", "abs"])
FEYNMAN_OPS = dict(binary_operators=["+", "-", "*", "/"],
                   unary_operators=["sin", "cos", "exp", "sqrt"])


def _bench_problem(rng):
    X = rng.uniform(-3.0, 3.0, (1000, 5)).astype(np.float32)
    y = (np.cos(2.13 * X[:, 0])
         + 0.5 * X[:, 1] * np.abs(X[:, 2]) ** 0.9
         - 0.3 * np.abs(X[:, 3]) ** 1.5
         + 0.1 * rng.standard_normal(1000)).astype(np.float32)
    return X, y, DEFAULT_OPS


# (name, n_vars, fn, sampling range) — Feynman-style physics forms
FEYNMAN = {
    "gauss": (1, lambda x: np.exp(-x[0] ** 2 / 2) / np.sqrt(2 * np.pi),
              (-3, 3)),
    "dist": (4, lambda x: np.sqrt((x[0] - x[1]) ** 2 + (x[2] - x[3]) ** 2),
             (-2, 2)),
    "relmass": (2, lambda x: x[0] / np.sqrt(1 - (0.3 * x[1]) ** 2), (0.1, 2)),
    "lorentz": (5, lambda x: x[0] * (x[1] + x[2] * x[3] * np.sin(x[4])),
                (-1, 1)),
    "gravpot": (4, lambda x: x[0] * x[1] * (1 / x[3] - 1 / x[2]), (0.5, 3)),
    "veladd": (2, lambda x: (x[0] + x[1]) / (1 + x[0] * x[1] * 0.25),
               (-1, 1)),
    "coulomb": (3, lambda x: x[0] * x[1] / (4 * np.pi * x[2] ** 2),
                (0.5, 3)),
    "pendulum": (3, lambda x: x[0] * np.cos(x[1] * x[2]), (0.3, 2)),
    "ideal_gas": (4, lambda x: x[0] * x[1] * x[2] / x[3], (0.5, 3)),
    "decay": (2, lambda x: np.exp(-x[0] * x[1]), (0.1, 2)),
}


def _feynman_problem(name, rng):
    nv, fn, (lo, hi) = FEYNMAN[name]
    X = rng.uniform(lo, hi, (1000, nv)).astype(np.float32)
    y = fn(X.T).astype(np.float32)
    return X, y, FEYNMAN_OPS


def single_run(problem: str, platform: str, seed: int, budget_s: float):
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    # persistent compile cache: seeds/problems share executables, so the
    # per-subprocess compile cost amortizes across the suite (per-user
    # path — a world-shared one breaks on multi-user hosts)
    cache = os.path.join(
        tempfile.gettempdir(), f"jax_quality_cache_{os.getuid()}")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from symbolicregression_jl_tpu import Options, search_key
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine

    rng = np.random.default_rng(1234)  # same data for every seed/platform
    if problem == "bench":
        X, y, ops = _bench_problem(rng)
    else:
        X, y, ops = _feynman_problem(problem, rng)

    options = Options(
        maxsize=30, populations=31, population_size=27,
        ncycles_per_iteration=380, save_to_file=False, **ops,
    )
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    engine = Engine(options, ds.nfeatures)
    state = engine.init_state(search_key(seed), ds.data, options.populations)

    # warmup = compile at final shapes (excluded from the budget: both
    # platforms pay XLA compile once per config, and the comparison is
    # about search progress, not compile latency)
    state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)

    curve = []
    t0 = time.perf_counter()
    while True:
        state = engine.run_iteration(state, ds.data, options.maxsize)
        jax.block_until_ready(state.pops.cost)
        el = time.perf_counter() - t0
        loss = np.asarray(state.pops.loss).ravel()
        cx = np.asarray(state.pops.complexity).ravel()
        finite = np.isfinite(loss)
        best = float(loss[finite].min()) if finite.any() else float("inf")
        curve.append([round(el, 2), best])
        if el >= budget_s:
            break

    # final pareto front: min loss per complexity, dominated points culled
    front = {}
    for c, l in zip(cx[finite], loss[finite]):
        c = int(c)
        if c not in front or l < front[c]:
            front[c] = float(l)
    pareto, best_so_far = [], float("inf")
    for c in sorted(front):
        if front[c] < best_so_far:
            best_so_far = front[c]
            pareto.append([c, front[c]])

    print(json.dumps({
        "problem": problem, "platform": platform, "seed": seed,
        "budget_s": budget_s, "iters": len(curve),
        "num_evals": float(state.num_evals),
        "best_loss": curve[-1][1] if curve else float("inf"),
        "curve": curve, "front": pareto,
    }))


def _run_one(problem, plat, seed, budget):
    """Launch one run subprocess and parse its JSON line (shared by
    suite() and repair()); timeouts and parse failures come back as
    error records instead of raising."""
    here = os.path.abspath(__file__)
    cmd = [sys.executable, here, "--run", problem, plat, str(seed),
           str(budget)]
    t0 = time.time()
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=budget * 6 + 600)
        line = (out.stdout.strip().splitlines()[-1]
                if out.stdout.strip() else "")
        rec = json.loads(line)
    except subprocess.TimeoutExpired:
        rec = {"problem": problem, "platform": plat, "seed": seed,
               "error": f"timeout after {budget * 6 + 600:.0f}s"}
    except json.JSONDecodeError:
        rec = {"problem": problem, "platform": plat, "seed": seed,
               "error": out.stderr[-500:]}
    rec["wall_s"] = round(time.time() - t0, 1)
    return rec


def suite(args):
    here = os.path.abspath(__file__)
    runs = []
    for seed in range(args.seeds_bench):
        for plat in ("tpu", "cpu"):
            runs.append(("bench", plat, seed, args.budget_bench))
    for name in FEYNMAN:
        for seed in range(args.seeds_feynman):
            for plat in ("tpu", "cpu"):
                runs.append((name, plat, seed, args.budget_feynman))

    results = []
    for problem, plat, seed, budget in runs:
        rec = _run_one(problem, plat, seed, budget)
        results.append(rec)
        print(f"{problem:10s} {plat:4s} seed={seed}: "
              f"best={rec.get('best_loss', 'ERR')}", flush=True)

    out_path = os.path.join(os.path.dirname(here), "quality_results.json")
    summary = summarize(results)
    with open(out_path, "w") as f:
        json.dump({"runs": results, "summary": summary,
                   "config": vars(args)}, f, indent=1)
    print("wrote", out_path)
    _print_summary(summary)


SOLVED = 1e-8  # below this, a law is exactly recovered (f32 noise floor)


def summarize(results):
    """Per problem: median best loss per platform and a not-worse count.

    Losses below SOLVED are exact recoveries — when both platforms
    solve a problem, residual epsilons (1e-13 vs 1e-16) are noise, not
    a quality difference, and count as not-worse.
    """
    summary = {}
    for problem in ["bench"] + list(FEYNMAN):
        rows = [r for r in results if r.get("problem") == problem
                and "best_loss" in r]
        med = {}
        for plat in ("tpu", "cpu"):
            ls = sorted(r["best_loss"] for r in rows
                        if r["platform"] == plat)
            med[plat] = ls[len(ls) // 2] if ls else None
        wins = 0
        seeds = {r["seed"] for r in rows}
        for sd in seeds:
            t = next((r["best_loss"] for r in rows
                      if r["platform"] == "tpu" and r["seed"] == sd), None)
            c = next((r["best_loss"] for r in rows
                      if r["platform"] == "cpu" and r["seed"] == sd), None)
            if t is None or c is None:
                continue
            if (t < SOLVED and c < SOLVED) or t <= c * 1.05:
                wins += 1
        summary[problem] = {"median_best": med,
                            "tpu_not_worse": wins, "n_seeds": len(seeds)}
    return summary


def _print_summary(summary):
    for k, v in summary.items():
        print(f"  {k:10s} median tpu={v['median_best']['tpu']} "
              f"cpu={v['median_best']['cpu']} "
              f"tpu_not_worse={v['tpu_not_worse']}/{v['n_seeds']}")


def repair(args):
    """Re-run errored records in quality_results.json and re-summarize."""
    here = os.path.abspath(__file__)
    out_path = os.path.join(os.path.dirname(here), "quality_results.json")
    with open(out_path) as f:
        payload = json.load(f)
    results = payload["runs"]
    for i, r in enumerate(results):
        if "best_loss" in r:
            continue
        problem, plat, seed = r["problem"], r["platform"], r["seed"]
        budget = (payload["config"]["budget_bench"] if problem == "bench"
                  else payload["config"]["budget_feynman"])
        print(f"re-running {problem} {plat} seed={seed}", flush=True)
        results[i] = _run_one(problem, plat, seed, budget)
    payload["summary"] = summarize(results)
    with open(out_path, "w") as f:
        json.dump(payload, f, indent=1)
    print("rewrote", out_path)
    _print_summary(payload["summary"])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--run", nargs=4, metavar=("PROBLEM", "PLplatform",
                                               "SEED", "BUDGET"))
    ap.add_argument("--suite", action="store_true")
    ap.add_argument("--repair", action="store_true",
                    help="re-run errored records in quality_results.json")
    ap.add_argument("--budget-bench", type=float, default=60.0)
    ap.add_argument("--budget-feynman", type=float, default=40.0)
    ap.add_argument("--seeds-bench", type=int, default=4)
    ap.add_argument("--seeds-feynman", type=int, default=2)
    args = ap.parse_args()
    if args.run:
        problem, plat, seed, budget = args.run
        single_run(problem, plat, int(seed), float(budget))
    elif args.repair:
        repair(args)
    elif args.suite:
        suite(args)
    else:
        print(__doc__)


if __name__ == "__main__":
    main()
