"""Search-QUALITY benchmark: wall-clock-to-quality, honest comparator.

Throughput (bench.py) says how fast evals run; this harness asks whether
the search *finds equally good equations per unit wall-clock*. Three
legs per (problem, seed):

- ``refproxy`` — the rate-matched reference stand-in (round-3 verdict
  item 1a). Julia is not installed here, so the reference cannot be
  run directly; BASELINE.md's measured CPU rate (8,097 evals/s/core ->
  ~6.5e4 evals/s for an 8-core multithreaded host, measured by
  profiling/cpu_baseline.py on this host's cores) anchors a proxy: the
  SAME algorithm at the reference's own config (populations=31,
  population_size=27, ncycles=380 — /root/reference/src/Options.jl:
  1161-1208) is given an eval budget of 6.5e4 x wall_budget and its
  curve is recorded against VIRTUAL wall-clock = cum_evals / 6.5e4.
  This replaces round 3's XLA-CPU leg, which ran 50-100x slower than
  the real reference and made the comparison a strawman. Caveat
  (documented, unavoidable): the proxy executes THIS engine's
  bulk-synchronous variant of the algorithm, not the reference's exact
  async scheduler — quality-per-eval was validated distributionally
  equal across backends in rounds 2-3.
- ``tpu31`` — this engine at the reference's config, REAL wall-clock.
  Honest matched-config comparison; at 31x27 the chip idles
  (~36k evals/s) and this leg is expected to lose to the proxy.
- ``tpunative`` — the TPU-native config (populations=512,
  population_size=256, ncycles=100 — profiling/config_sweep.py), REAL
  wall-clock, iterations chunked so the budget is actually respected
  (round-3 verdict weak #5: a "budget" that admits one 343 s iteration
  is not a budget).

Summary adds wall-clock-to-target ratios (verdict item 1c): per seed,
target = the proxy's final best loss; speedup = proxy virtual budget /
tpunative's real time to reach the target (within 5%, or SOLVED).

Usage:
  python profiling/quality_bench.py --run PROBLEM LEG SEED BUDGET
  python profiling/quality_bench.py --suite [--budget-bench 75]
      [--budget-feynman 45] [--seeds-bench 3] [--seeds-feynman 2]
  python profiling/quality_bench.py --repair
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import tempfile
import sys
import time

import _common  # noqa: F401,E402  (repo root on sys.path)

import numpy as np

# Measured: profiling/cpu_baseline.py (8,097 evals/s/core on this host,
# transcendental-bound numpy per-node evaluator) x 8 cores. See
# BASELINE.md "Measured CPU baseline".
REF_RATE = 6.5e4

LEGS = ("refproxy", "tpu31", "tpunative")

DEFAULT_OPS = dict(binary_operators=["+", "-", "*", "/"],
                   unary_operators=["exp", "abs"])
FEYNMAN_OPS = dict(binary_operators=["+", "-", "*", "/"],
                   unary_operators=["sin", "cos", "exp", "sqrt"])


def _bench_problem(rng):
    X = rng.uniform(-3.0, 3.0, (1000, 5)).astype(np.float32)
    y = (np.cos(2.13 * X[:, 0])
         + 0.5 * X[:, 1] * np.abs(X[:, 2]) ** 0.9
         - 0.3 * np.abs(X[:, 3]) ** 1.5
         + 0.1 * rng.standard_normal(1000)).astype(np.float32)
    return X, y, DEFAULT_OPS


# (name, n_vars, fn, sampling range) — Feynman-style physics forms
FEYNMAN = {
    "gauss": (1, lambda x: np.exp(-x[0] ** 2 / 2) / np.sqrt(2 * np.pi),
              (-3, 3)),
    "dist": (4, lambda x: np.sqrt((x[0] - x[1]) ** 2 + (x[2] - x[3]) ** 2),
             (-2, 2)),
    "relmass": (2, lambda x: x[0] / np.sqrt(1 - (0.3 * x[1]) ** 2), (0.1, 2)),
    "lorentz": (5, lambda x: x[0] * (x[1] + x[2] * x[3] * np.sin(x[4])),
                (-1, 1)),
    "gravpot": (4, lambda x: x[0] * x[1] * (1 / x[3] - 1 / x[2]), (0.5, 3)),
    "veladd": (2, lambda x: (x[0] + x[1]) / (1 + x[0] * x[1] * 0.25),
               (-1, 1)),
    "coulomb": (3, lambda x: x[0] * x[1] / (4 * np.pi * x[2] ** 2),
                (0.5, 3)),
    "pendulum": (3, lambda x: x[0] * np.cos(x[1] * x[2]), (0.3, 2)),
    "ideal_gas": (4, lambda x: x[0] * x[1] * x[2] / x[3], (0.5, 3)),
    "decay": (2, lambda x: np.exp(-x[0] * x[1]), (0.1, 2)),
}


def _feynman_problem(name, rng):
    nv, fn, (lo, hi) = FEYNMAN[name]
    X = rng.uniform(lo, hi, (1000, nv)).astype(np.float32)
    y = fn(X.T).astype(np.float32)
    return X, y, FEYNMAN_OPS


# Real Feynman-benchmark equations WITH SI units (round-4 verdict item 6:
# dimensional analysis through the full pipeline — ops/dims_eval.py +
# core/units.py in anger, /root/reference/src/DimensionalAnalysis.jl:
# 223-275). (X_units, y_unit, fn, range); Feynman numbering in comments.
FEYNMAN_SI = {
    # I.12.2  F = q1 q2 / (4 pi eps r^2)
    "si_coulomb": ((["A*s", "A*s", "kg^-1*m^-3*s^4*A^2", "m"], "kg*m*s^-2",
                    lambda x: x[0] * x[1] / (4 * np.pi * x[2] * x[3] ** 2),
                    (0.5, 2.0))),
    # I.14.3  U = m g z
    "si_grav_pe": ((["kg", "m/s^2", "m"], "kg*m^2/s^2",
                    lambda x: x[0] * x[1] * x[2], (0.5, 2.0))),
    # I.29.4  k = omega / c
    "si_wavenum": ((["1/s", "m/s"], "1/m",
                    lambda x: x[0] / x[1], (0.5, 2.0))),
    # I.39.1  E = 3/2 p V
    "si_gas_energy": ((["kg*m^-1*s^-2", "m^3"], "kg*m^2/s^2",
                       lambda x: 1.5 * x[0] * x[1], (0.5, 2.0))),
    # I.34.8  omega = q v B / p
    "si_cyclotron": ((["A*s", "m/s", "kg*A^-1*s^-2", "kg*m/s"], "1/s",
                     lambda x: x[0] * x[1] * x[2] / x[3], (0.5, 2.0))),
    # II.3.24 h = P / (4 pi r^2)
    "si_flux": ((["kg*m^2*s^-3", "m"], "kg/s^3",
                 lambda x: x[0] / (4 * np.pi * x[1] ** 2), (0.5, 2.0))),
    # I.18.12 tau = r F sin(theta)
    "si_torque": ((["m", "kg*m/s^2", ""], "kg*m^2/s^2",
                   lambda x: x[0] * x[1] * np.sin(x[2]), (0.3, 1.5))),
    # I.25.13 V = q / C
    "si_capacitor": ((["A*s", "kg^-1*m^-2*s^4*A^2"], "kg*m^2*A^-1*s^-3",
                      lambda x: x[0] / x[1], (0.5, 2.0))),
}


def _feynman_si_problem(name, rng):
    x_units, y_unit, fn, (lo, hi) = FEYNMAN_SI[name]
    nv = len(x_units)
    X = rng.uniform(lo, hi, (1000, nv)).astype(np.float32)
    y = fn(X.T).astype(np.float32)
    ops = dict(binary_operators=["+", "-", "*", "/"],
               unary_operators=["sin", "sqrt"])
    return X, y, ops, x_units, y_unit


def single_run(problem: str, leg: str, seed: int, budget_s: float):
    import jax
    cache = os.path.join(
        tempfile.gettempdir(), f"jax_quality_cache_{os.getuid()}")
    jax.config.update("jax_compilation_cache_dir", cache)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from symbolicregression_jl_tpu import Options, search_key
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine

    rng = np.random.default_rng(1234)  # same data for every seed/leg
    x_units = y_unit = None
    if problem == "bench":
        X, y, ops = _bench_problem(rng)
    elif problem in FEYNMAN_SI:
        X, y, ops, x_units, y_unit = _feynman_si_problem(problem, rng)
    else:
        X, y, ops = _feynman_problem(problem, rng)

    if leg == "tpunative":
        options = Options(
            maxsize=30, populations=512, population_size=256,
            tournament_selection_n=16, ncycles_per_iteration=100,
            save_to_file=False, **ops,
        )
        chunks = [20] * 5
    else:  # refproxy / tpu31: the reference's own configuration
        options = Options(
            maxsize=30, populations=31, population_size=27,
            ncycles_per_iteration=380, save_to_file=False, **ops,
        )
        # Single-launch iterations: at 31x27 an iteration is ~0.5-0.8 s,
        # so per-iteration stop granularity already respects a 45-75 s
        # budget — while each mid-iteration chunk poll costs a ~0.1 s
        # blocking tunnel round trip (4 polls/iteration measured ~0.3
        # s/iter, dragging the leg from ~95k to ~62k evals/s in round
        # 5's first bench pass).
        chunks = None
    ds = make_dataset(X, y, X_units=x_units, y_units=y_unit)
    ds.update_baseline_loss(options.elementwise_loss)
    engine = Engine(options, ds.nfeatures)
    state = engine.init_state(search_key(seed), ds.data, options.populations)

    eval_budget = REF_RATE * budget_s if leg == "refproxy" else None

    # warmup = compile at final shapes (excluded from the budget: every
    # leg pays XLA compile once per config, and the comparison is about
    # search progress, not compile latency). Uses the same chunked form
    # as the measured loop so all chunk lengths compile here.
    state = engine.run_iteration(state, ds.data, options.maxsize,
                                 chunk_sizes=chunks)
    jax.block_until_ready(state.pops.cost)
    evals0 = float(state.num_evals)

    curve = []
    t0 = time.perf_counter()

    def elapsed():
        return time.perf_counter() - t0

    def budget_left():
        if eval_budget is not None:
            return (float(state.num_evals) - evals0) < eval_budget
        return elapsed() < budget_s

    while True:
        # tpunative runs chunked with a budget check between chunks: a
        # wall budget can stop its ~10 s iterations mid-flight (verdict
        # weak #5). The 31x27 legs run single-launch (chunks=None, the
        # stop callback is not consulted) — their sub-second iterations
        # make per-iteration granularity sufficient, see the chunks
        # comment above.
        stop = (None if (eval_budget is not None or chunks is None)
                else (lambda pending: elapsed() >= budget_s))
        state = engine.run_iteration(state, ds.data, options.maxsize,
                                     chunk_sizes=chunks, should_stop=stop)
        jax.block_until_ready(state.pops.cost)
        evals = float(state.num_evals) - evals0
        # x-axis: real seconds, except the proxy's virtual clock
        xval = evals / REF_RATE if eval_budget is not None else elapsed()
        loss = np.asarray(state.pops.loss).ravel()
        cx = np.asarray(state.pops.complexity).ravel()
        finite = np.isfinite(loss)
        best = float(loss[finite].min()) if finite.any() else float("inf")
        curve.append([round(xval, 2), best])
        if not budget_left():
            break

    # final pareto front: min loss per complexity, dominated points culled
    front = {}
    for c, l in zip(cx[finite], loss[finite]):
        c = int(c)
        if c not in front or l < front[c]:
            front[c] = float(l)
    pareto, best_so_far = [], float("inf")
    for c in sorted(front):
        if front[c] < best_so_far:
            best_so_far = front[c]
            pareto.append([c, front[c]])

    print(json.dumps({
        "problem": problem, "leg": leg, "seed": seed,
        "budget_s": budget_s, "iters": len(curve),
        "num_evals": float(state.num_evals) - evals0,
        "real_wall_s": round(elapsed(), 1),
        "best_loss": curve[-1][1] if curve else float("inf"),
        "curve": curve, "front": pareto,
    }))


def _run_one(problem, leg, seed, budget):
    """Launch one run subprocess and parse its JSON line; timeouts and
    parse failures come back as error records instead of raising."""
    here = os.path.abspath(__file__)
    cmd = [sys.executable, here, "--run", problem, leg, str(seed),
           str(budget)]
    t0 = time.time()
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=budget * 10 + 900)
        line = (out.stdout.strip().splitlines()[-1]
                if out.stdout.strip() else "")
        rec = json.loads(line)
    except subprocess.TimeoutExpired:
        rec = {"problem": problem, "leg": leg, "seed": seed,
               "error": f"timeout after {budget * 10 + 900:.0f}s"}
    except json.JSONDecodeError:
        rec = {"problem": problem, "leg": leg, "seed": seed,
               "error": out.stderr[-500:]}
    rec["wall_s"] = round(time.time() - t0, 1)
    return rec


def suite(args):
    here = os.path.abspath(__file__)
    runs = []
    if getattr(args, "suite_si", False):
        # SI-united Feynman tier: dimensional analysis active end-to-end.
        # All three legs since round 5 (the round-4 verdict flagged the
        # tpu31 leg as null here): the SI tier now also measures the
        # config-sensitivity story with units active.
        for name in FEYNMAN_SI:
            for seed in range(args.seeds_feynman):
                for leg in LEGS:
                    runs.append((name, leg, seed, args.budget_feynman))
    else:
        for seed in range(args.seeds_bench):
            for leg in LEGS:
                runs.append(("bench", leg, seed, args.budget_bench))
        # The unitless Feynman tier carries the native-vs-proxy claim;
        # its tpu31 legs are optional (--legs-feynman 2 drops them —
        # the matched-config story is carried by the bench problem and
        # the SI tier, where the verdict asks for it explicitly).
        fey_legs = (LEGS if getattr(args, "legs_feynman", 3) >= 3
                    else ("refproxy", "tpunative"))
        for name in FEYNMAN:
            for seed in range(args.seeds_feynman):
                for leg in fey_legs:
                    runs.append((name, leg, seed, args.budget_feynman))

    out_path = os.path.join(
        os.path.dirname(here),
        "quality_si_results.json" if getattr(args, "suite_si", False)
        else "quality_results.json")

    def save(results):
        with open(out_path, "w") as f:
            json.dump({"runs": results, "summary": summarize(results),
                       "config": vars(args), "ref_rate": REF_RATE},
                      f, indent=1)

    results = []
    done = set()
    if getattr(args, "resume", False) and os.path.exists(out_path):
        with open(out_path) as f:
            prior = json.load(f).get("runs", [])
        results = [r for r in prior if "best_loss" in r]
        # budget is part of the identity: resuming with a different
        # budget must re-run, not silently pool mixed-budget records.
        done = {(r["problem"], r["leg"], r["seed"], r.get("budget_s"))
                for r in results}
        print(f"resuming: {len(results)} prior runs kept", flush=True)
    runs = [r for r in runs if (r[0], r[1], r[2], r[3]) not in done]
    for problem, leg, seed, budget in runs:
        rec = _run_one(problem, leg, seed, budget)
        results.append(rec)
        print(f"{problem:10s} {leg:9s} seed={seed}: "
              f"best={rec.get('best_loss', 'ERR')} "
              f"(real {rec.get('real_wall_s', '?')}s)", flush=True)
        save(results)  # incremental: a crash keeps partial results
    # Always rewrite at the end: a resume with nothing left still
    # re-applies the current summarize() to the stored runs.
    save(results)
    print("wrote", out_path)
    _print_summary(summarize(results))


SOLVED = 1e-8  # below this, a law is exactly recovered (f32 noise floor)


def _time_to(curve, target):
    """First x with best <= max(target * 1.05, SOLVED); None if never."""
    thr = max(target * 1.05, SOLVED)
    for x, b in curve:
        if b <= thr:
            return x
    return None


def _best_env(r):
    """Best-so-far envelope: the minimum loss the search EVER held —
    what the user-facing hall of fame retains (update_hof runs every
    cycle) — rather than the final population's min, which can regress
    past the budget point with adaptive parsimony (the round-5 bench
    pass showed identical-trajectory legs differing only by where the
    clock stopped mid-oscillation)."""
    if r.get("curve"):
        return min(b for _, b in r["curve"])
    return r["best_loss"]


def summarize(results):
    """Per problem: median best-so-far loss per leg + wall-to-target
    ratios.

    ``speedup_vs_ref``: per seed, proxy virtual budget / tpunative real
    time-to-(proxy's final loss); >1 means the TPU-native config reaches
    rate-matched-reference quality in less wall-clock.
    """
    summary = {}
    problems = []
    for r in results:
        if r.get("problem") not in problems:
            problems.append(r.get("problem"))
    for problem in problems:
        rows = [r for r in results if r.get("problem") == problem
                and "best_loss" in r]
        med = {}
        for leg in LEGS:
            ls = sorted(_best_env(r) for r in rows if r["leg"] == leg)
            med[leg] = ls[len(ls) // 2] if ls else None
        def nw(a, b):
            return (a < SOLVED and b < SOLVED) or a <= b * 1.05

        per_seed = []
        not_worse = 0
        t31_nw = t31_n = 0
        seeds = sorted({r["seed"] for r in rows})
        for sd in seeds:
            proxy = next((r for r in rows
                          if r["leg"] == "refproxy" and r["seed"] == sd), None)
            native = next((r for r in rows
                           if r["leg"] == "tpunative" and r["seed"] == sd),
                          None)
            t31 = next((r for r in rows
                        if r["leg"] == "tpu31" and r["seed"] == sd), None)
            if proxy is None:
                continue
            t_p = _best_env(proxy)
            if t31 is not None:
                # Matched-config leg: tpu31 (same algorithm + config,
                # REAL wall-clock) vs the rate-matched proxy.
                t31_n += 1
                t31_nw += nw(_best_env(t31), t_p)
            if native is None:
                continue
            t_n = _best_env(native)
            not_worse += nw(t_n, t_p)
            tt = _time_to(native["curve"], t_p)
            # Symmetric accounting: the proxy is charged its OWN virtual
            # time to first reach its best-so-far (not the full budget —
            # with the envelope metric it may hit its best early).
            proxy_time = _time_to(proxy["curve"], t_p)
            # Granularity flag: when the native leg already meets the
            # target at its FIRST recorded point, its true
            # time-to-target is only upper-bounded by one full
            # device-scale iteration (~10 s) — the speedup is then a
            # LOWER bound quantized by the iteration, not a measurement
            # (trivially-solved problems land here; the tpu31 leg
            # carries the latency story for those).
            first_pt = (native["curve"][0] if native.get("curve") else None)
            quantized = bool(
                first_pt is not None and tt is not None
                and tt <= first_pt[0])
            per_seed.append({
                "seed": sd, "proxy_final": t_p, "native_final": t_n,
                "native_time_to_proxy_final": tt,
                "proxy_time_to_own_best": proxy_time,
                "native_first_point_quantized": quantized,
                "speedup_vs_ref": (round(proxy_time / tt, 2)
                                   if (tt and proxy_time) else None),
            })
        sp = sorted(s["speedup_vs_ref"] for s in per_seed
                    if s["speedup_vs_ref"] is not None)
        n_quant = sum(1 for s in per_seed
                      if s["native_first_point_quantized"])
        summary[problem] = {
            "median_best": med,
            "native_not_worse_than_proxy": f"{not_worse}/{len(seeds)}",
            "tpu31_not_worse_than_proxy": (
                f"{t31_nw}/{t31_n}" if t31_n else None),
            "median_speedup_vs_ref": sp[len(sp) // 2] if sp else None,
            "speedup_quantized_seeds": f"{n_quant}/{len(per_seed)}",
            "per_seed": per_seed,
        }
    return summary


def _print_summary(summary):
    for k, v in summary.items():
        m = v["median_best"]
        print(f"  {k:10s} proxy={m.get('refproxy')} "
              f"tpu31={m.get('tpu31')} native={m.get('tpunative')} "
              f"not_worse={v['native_not_worse_than_proxy']} "
              f"tpu31_nw={v.get('tpu31_not_worse_than_proxy')} "
              f"speedup={v['median_speedup_vs_ref']} "
              f"(quantized {v.get('speedup_quantized_seeds')})")


def repair(args):
    """Re-run errored records in quality_results.json and re-summarize."""
    here = os.path.abspath(__file__)
    out_path = os.path.join(os.path.dirname(here), "quality_results.json")
    with open(out_path) as f:
        payload = json.load(f)
    results = payload["runs"]
    for i, r in enumerate(results):
        if "best_loss" in r:
            continue
        problem, leg, seed = r["problem"], r["leg"], r["seed"]
        budget = (payload["config"]["budget_bench"] if problem == "bench"
                  else payload["config"]["budget_feynman"])
        print(f"re-running {problem} {leg} seed={seed}", flush=True)
        results[i] = _run_one(problem, leg, seed, budget)
    payload["summary"] = summarize(results)
    with open(out_path, "w") as f:
        json.dump(payload, f, indent=1)
    print("rewrote", out_path)
    _print_summary(payload["summary"])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--run", nargs=4, metavar=("PROBLEM", "LEG", "SEED",
                                               "BUDGET"))
    ap.add_argument("--suite", action="store_true")
    ap.add_argument("--suite-si", action="store_true",
                    help="SI-united Feynman tier (dimensional analysis on)")
    ap.add_argument("--repair", action="store_true",
                    help="re-run errored records in quality_results.json")
    ap.add_argument("--budget-bench", type=float, default=75.0)
    ap.add_argument("--budget-feynman", type=float, default=45.0)
    ap.add_argument("--seeds-bench", type=int, default=3)
    ap.add_argument("--seeds-feynman", type=int, default=2)
    ap.add_argument("--legs-feynman", type=int, default=3,
                    help="3 = all legs; 2 = drop tpu31 from the unitless "
                         "Feynman tier (kept in bench + SI)")
    ap.add_argument("--resume", action="store_true",
                    help="keep completed runs from the existing results "
                         "file; run only missing (problem, leg, seed)")
    args = ap.parse_args()
    if args.run:
        problem, leg, seed, budget = args.run
        single_run(problem, leg, int(seed), float(budget))
    elif args.repair:
        repair(args)
    elif args.suite or args.suite_si:
        suite(args)
    else:
        print(__doc__)


if __name__ == "__main__":
    main()
