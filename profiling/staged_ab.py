"""graftstage A/B on the 10k-row headline problem (docs/PRECISION.md).

Runs the bench.py headline problem (10k rows, 5 features, the reference
target) twice at a CPU-feasible scale — staged eval OFF then ON — and
prints per-run evals/s plus the screen/rescore device counters, so the
round-7 claim ("the plateau moves because fewer full-dataset rows are
launched per cycle") is measured, not modeled. The scale knobs default
small enough for a CPU workstation; on a chip, crank them toward the
headline 512x256 config:

    python profiling/staged_ab.py [islands] [pop] [ncycles] [iters]

Candidate-eval accounting: ``num_evals`` counts CANDIDATE evaluations
(each screened candidate counts once — the row-sample discount is what
staging banks as throughput; the graftbench quality gate bounds what
that trade may cost). The counters printed alongside make the row
accounting explicit: screen_rows/rescore_rows are candidates through
each launch, eval launch count doubles per staged cycle.
"""

from __future__ import annotations

import json
import sys
import time

import _common  # noqa: F401  (repo root on sys.path)
import numpy as np

N_ROWS = 10_000
N_FEATURES = 5


def _make_data():
    rng = np.random.default_rng(0)
    X = rng.uniform(-3.0, 3.0, (N_ROWS, N_FEATURES)).astype(np.float32)
    y = (
        np.cos(2.13 * X[:, 0])
        + 0.5 * X[:, 1] * np.abs(X[:, 2]) ** 0.9
        - 0.3 * np.abs(X[:, 3]) ** 1.5
        + 1e-1 * rng.standard_normal(N_ROWS)
    ).astype(np.float32)
    return X, y


def _run(staged: bool, islands: int, pop: int, ncycles: int,
         iters: int) -> dict:
    import jax

    from symbolicregression_jl_tpu import Options, search_key
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine

    X, y = _make_data()
    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=30,
        populations=islands,
        population_size=pop,
        tournament_selection_n=min(16, pop // 2),
        ncycles_per_iteration=ncycles,
        save_to_file=False,
        staged_eval=staged,
        telemetry=True,
    )
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    engine = Engine(options, ds.nfeatures)
    state = engine.init_state(search_key(0), ds.data, islands)

    # warmup/compile iteration, excluded from timing
    state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    ev0 = float(state.num_evals)

    t0 = time.perf_counter()
    for _ in range(iters):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    dt = time.perf_counter() - t0

    t = state.telem.cycle
    return {
        "staged": staged,
        "evals": float(state.num_evals) - ev0,
        "elapsed_s": round(dt, 3),
        "evals_per_sec": round((float(state.num_evals) - ev0) / dt, 1),
        # per-iteration device counters (last iteration's snapshot)
        "screen_rows": int(t.screen_rows),
        "rescore_rows": int(t.rescore_rows),
        "eval_rows": int(t.eval_rows),
        "eval_launches": int(t.eval_launches),
        "best_loss": float(jax.numpy.min(state.hof.loss)),
    }


def main() -> None:
    argv = sys.argv[1:]
    islands = int(argv[0]) if len(argv) > 0 else 8
    pop = int(argv[1]) if len(argv) > 1 else 32
    ncycles = int(argv[2]) if len(argv) > 2 else 10
    iters = int(argv[3]) if len(argv) > 3 else 2

    off = _run(False, islands, pop, ncycles, iters)
    on = _run(True, islands, pop, ncycles, iters)
    ratio = on["evals_per_sec"] / max(off["evals_per_sec"], 1e-9)
    print(json.dumps({"plain": off, "staged": on,
                      "staged_over_plain": round(ratio, 3)}))


if __name__ == "__main__":
    main()
