"""Measured shards-vs-evals/s scaling curve for the graftmesh runtime.

The headline multi-chip number was a closed-form ICI projection for five
rounds (profiling/ici_model.py; ROADMAP item 1 called it "the single
biggest credibility gap"). This harness commits a MEASURED curve
instead: for each shard count it runs the SAME fixed-size search
(strong scaling — islands constant, islands-per-shard shrinking) on the
mesh runtime (mesh/MeshEngine: shard_map iteration, explicit
collectives, per-shard finalize-dedup) and reports warm-iteration
evals/s plus the cross-shard dedup-key exchange stats.

Each point runs in a SUBPROCESS so the device count is set before jax
imports (``--xla_force_host_platform_device_count``), exactly like the
graftbench sharded cells.

CAVEAT for virtual CPU meshes (the default tier, committed as
profiling/MESH_SCALING.json): the virtual devices SHARE the host's
cores, so the curve measures that sharded execution works at every
shard count and what the collectives COST on one core — not speedup.
Run with ``--full`` on real hardware for the chip-shaped curve the day
a v5e-8 is attached (same JSON schema; bench trend folds either in).

Usage:
  python profiling/mesh_scaling.py                  # mini shapes, CPU mesh
  python profiling/mesh_scaling.py --full           # chip shapes
  python profiling/mesh_scaling.py --shards 1 2 4   # subset
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from _common import REPO_ROOT  # noqa: F401 (sys.path setup)

SCHEMA = "graftmesh.scaling.v1"
POINT_SENTINEL = "MESH_SCALING_POINT"

# mini: sized so the 4-point curve fits a CI-adjacent budget on one CPU
# core; full: the bench.py headline shapes.
MINI = dict(rows=512, islands=8, population_size=32, ncycles=8,
            maxsize=10, tournament_selection_n=8, iterations=2)
FULL = dict(rows=10_000, islands=512, population_size=256, ncycles=100,
            maxsize=30, tournament_selection_n=16, iterations=2)


def _run_point(shards: int, shape: dict) -> dict:
    """Child entry: measure one shard count (devices already forced)."""
    import jax

    from symbolicregression_jl_tpu import Options, search_key
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.mesh import MeshEngine, MeshPlan
    from symbolicregression_jl_tpu.mesh.dryrun import make_dryrun_problem

    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=int(shape["maxsize"]),
        populations=int(shape["islands"]),
        population_size=int(shape["population_size"]),
        ncycles_per_iteration=int(shape["ncycles"]),
        tournament_selection_n=int(shape["tournament_selection_n"]),
        optimizer_probability=0.0,
        # turbo=True (the committed curve's default): the fused path is
        # the flagship runtime AND the only dedup-ELIGIBLE one — a
        # non-turbo curve would measure a path that forfeits the
        # per-shard dedup the mesh runtime exists to re-enable.
        turbo=bool(shape.get("turbo", True)),
        save_to_file=False,
    )
    X, y = make_dryrun_problem(int(shape["rows"]))
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)

    plan = MeshPlan.build(jax.devices()[:shards], n_island_shards=shards)
    engine = MeshEngine(options, ds.nfeatures, plan)
    data = plan.place_data(ds.data)
    state = engine.init_state(search_key(0), data, options.populations)
    state = plan.place_state(state)
    # warm (compile) iteration, then the measured ones
    state = engine.run_iteration(state, data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    ev0 = float(state.num_evals)
    iters = int(shape["iterations"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state = engine.run_iteration(state, data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    dt = time.perf_counter() - t0
    rate = (float(state.num_evals) - ev0) / dt
    ex = engine.dedup_exchange(state)
    return {
        "shards": shards,
        "islands": int(shape["islands"]),
        "evals_per_sec": round(rate, 1),
        "evals_per_sec_per_shard": round(rate / shards, 1),
        "iter_seconds": round(dt / iters, 3),
        "turbo": bool(engine.cfg.turbo),
        "sharded_dedup": engine._use_dedup(sharded=shards > 1),
        "dedup_exchange": {
            k: ex[k] for k in ("rows", "shard_unique", "global_unique",
                               "cross_shard_dup", "exchanged_bytes")
        },
        "backend": jax.default_backend(),
    }


def _spawn_point(shards: int, shape: dict, budget_s: float) -> dict:
    from symbolicregression_jl_tpu.mesh.dryrun import virtual_cpu_mesh_env

    env = virtual_cpu_mesh_env(shards)
    env.setdefault("SYMBOLIC_REGRESSION_IS_TESTING", "true")
    cmd = [sys.executable, os.path.abspath(__file__),
           "--point", str(shards), "--shape-json", json.dumps(shape)]
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=budget_s)
    except subprocess.TimeoutExpired:
        return {"shards": shards,
                "error": f"point timeout after {budget_s:.0f}s"}
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith(POINT_SENTINEL + " ")), None)
    if proc.returncode != 0 or line is None:
        return {"shards": shards,
                "error": f"rc={proc.returncode}: {proc.stderr[-400:]}"}
    return json.loads(line[len(POINT_SENTINEL) + 1:])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", nargs="+", type=int, default=[1, 2, 4, 8])
    ap.add_argument("--full", action="store_true",
                    help="chip shapes (real hardware)")
    ap.add_argument("--no-turbo", action="store_true",
                    help="measure the jnp-interpreter path instead of "
                         "the fused (dedup-eligible) flagship path")
    ap.add_argument("--budget", type=float,
                    default=float(os.environ.get(
                        "SR_MESH_POINT_BUDGET", "600")))
    ap.add_argument("--out", default=None)
    ap.add_argument("--point", type=int, default=None,
                    help=argparse.SUPPRESS)  # internal child entry
    ap.add_argument("--shape-json", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.point is not None:
        shape = json.loads(args.shape_json)
        rec = _run_point(args.point, shape)
        print(f"{POINT_SENTINEL} {json.dumps(rec)}", flush=True)
        return 0

    shape = dict(FULL if args.full else MINI)
    shape["turbo"] = not args.no_turbo
    points = []
    for shards in args.shards:
        rec = _spawn_point(shards, shape, args.budget)
        points.append(rec)
        print(json.dumps(rec), flush=True)
    import platform

    payload = {
        "schema": SCHEMA,
        "matrix": "full" if args.full else "mini",
        "t": time.time(),
        "host": {"machine": platform.machine(),
                 "cpus": os.cpu_count()},
        "shape": shape,
        # the virtual-CPU caveat travels WITH the data so trend/readers
        # can't mistake the one-core curve for scaling efficiency
        "virtual_cpu_mesh": not args.full,
        "points": points,
    }
    print(json.dumps(payload))
    out = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "MESH_SCALING.json" if not args.full
        else "MESH_SCALING_full.json")
    with open(out, "w") as f:
        json.dump(payload, f, indent=1)
    print("wrote", out)
    return 1 if any("error" in p for p in points) else 0


if __name__ == "__main__":
    sys.exit(main())
