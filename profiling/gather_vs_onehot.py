"""Is batched fancy-index gather the TPU bottleneck vs one-hot contraction?"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def chain_time(f, x, n=50):
    x = f(x)
    jax.block_until_ready(x)
    t0 = time.perf_counter()
    for _ in range(n):
        x = f(x)
    jax.block_until_ready(x)
    return (time.perf_counter() - t0) / n


def main():
    B, L, S = 1800, 30, 96
    key = jax.random.PRNGKey(0)
    src = jax.random.randint(key, (B, L), 0, S)
    table = jax.random.normal(jax.random.fold_in(key, 1), (B, S))

    @jax.jit
    def gather_step(t):
        out = jnp.take_along_axis(t, jnp.clip(src, 0, S - 1)[:, :L], axis=1)
        # feed back to keep a chain
        return t.at[:, :L].add(out * 1e-6)

    @jax.jit
    def vmap_gather_step(t):
        out = jax.vmap(lambda row, idx: row[idx])(t, src)
        return t.at[:, :L].add(out * 1e-6)

    @jax.jit
    def onehot_step(t):
        oh = (src[..., None] == jnp.arange(S)).astype(t.dtype)  # [B, L, S]
        out = jnp.sum(oh * t[:, None, :], axis=-1)
        return t.at[:, :L].add(out * 1e-6)

    # scatter variants: write one element per row
    idx1 = jax.random.randint(jax.random.fold_in(key, 2), (B,), 0, L)

    @jax.jit
    def scatter_step(t):
        return t.at[jnp.arange(B), idx1].multiply(1.0 + 1e-6)

    @jax.jit
    def where_step(t):
        hit = jnp.arange(S) == idx1[:, None]
        return jnp.where(hit, t * (1.0 + 1e-6), t)

    for name, f in [("take_along_axis", gather_step),
                    ("vmap row[idx]", vmap_gather_step),
                    ("onehot mul-reduce", onehot_step),
                    ("scatter 1/row", scatter_step),
                    ("where 1/row", where_step)]:
        t = chain_time(f, table)
        print(f"{name:20s}: {t*1e6:9.1f} us")


if __name__ == "__main__":
    main()
