"""Measure structural duplication in the candidate-eval batch.

Round-3 verdict: the roofline note dismissed "batch structurally
identical trees" without measuring the duplicate-structure rate in
evolved populations. This harness measures it directly on the bench
config: warm the engine with real iterations, then step single
generation cycles with `generation_step(..., return_candidates=True)`
and count, per cycle:

  - per-island candidate dup rate: fraction of the island's eval batch
    whose compiled (code, src1, src2)[:nsteps] rows duplicate another
    row of the same island (constants free) — this is the rate a
    per-island (inside the island vmap) dedup can exploit;
  - global candidate dup rate: same, across all islands — the ceiling
    for a flattened-batch dedup;
  - full-identity rates: structure AND constants identical (these
    rows wouldn't even need a variants axis);
  - the same four numbers for the population itself (the finalize-eval
    batch [I, P]).

Usage: dup_rate.py [islands] [pop] [cycles_to_sample] [warm_iters]
"""

from __future__ import annotations

import sys

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax
import jax.numpy as jnp
import numpy as np

from _common import make_bench_problem


def dup_stats(code, src1, src2, nsteps, cvals=None, nconst=None):
    """(dup_rate, groups>1 mean size) for [T, L] program structure rows.

    Slots past nsteps are masked (the kernel never reads them; their
    residual leaf-address content must not split groups).
    """
    T, L = code.shape
    step = np.arange(L)[None, :]
    live = step < nsteps[:, None]
    rows = [np.where(live, code, 0), np.where(live, src1, 0),
            np.where(live, src2, 0), nsteps[:, None]]
    if cvals is not None:
        cused = np.arange(cvals.shape[1])[None, :] < nconst[:, None]
        rows.append(np.where(cused, cvals, 0.0).view(np.int32))
    mat = np.concatenate(rows, axis=1)
    uniq, counts = np.unique(mat, axis=0, return_counts=True)
    dup_rate = 1.0 - len(uniq) / T
    big = counts[counts > 1]
    mean_group = float(big.mean()) if len(big) else 0.0
    return dup_rate, mean_group, counts


def main():
    I = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    P = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    NCAP = int(sys.argv[3]) if len(sys.argv) > 3 else 20
    WARM = int(sys.argv[4]) if len(sys.argv) > 4 else 2

    from symbolicregression_jl_tpu import search_key
    from symbolicregression_jl_tpu.evolve.step import generation_step
    from symbolicregression_jl_tpu.ops.program import compile_program

    options, ds, engine = make_bench_problem(
        populations=I, population_size=P, ncycles_per_iteration=100,
        tournament_selection_n=16)
    cfg = engine.cfg
    state = engine.init_state(search_key(0), ds.data, I)
    for _ in range(WARM):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    print(f"warmed {WARM} iterations; sampling {NCAP} cycles")

    n_binary = len(cfg.operators.binary)
    F = ds.nfeatures

    @jax.jit
    def one_cycle(key, pops, birth, ref, stats_nf, temperature, marks):
        def island(k, pop, b, r, m):
            return generation_step(
                k, pop, ds.data, stats_nf, temperature,
                jnp.int32(options.maxsize), b, r, cfg, options,
                engine.tables, options.elementwise_loss, marks=m,
                return_candidates=True)
        return jax.vmap(island)(key, pops, birth, ref, marks)

    @jax.jit
    def progify(trees):
        flat = jax.tree.map(
            lambda x: x.reshape((-1,) + x.shape[2:]), trees)
        return compile_program(flat, F, n_binary)

    pops, birth, ref = state.pops, state.birth, state.ref
    marks = (jnp.zeros((I, P), jnp.bool_), jnp.zeros((I, P), jnp.bool_))
    stats_nf = state.stats.normalized_frequencies
    base = jax.random.fold_in(state.key, 12345)

    agg = {k: [] for k in [
        "cand_island_struct", "cand_global_struct",
        "cand_island_full", "cand_global_full",
        "pop_island_struct", "pop_global_struct"]}
    group_sizes = []

    for c in range(NCAP):
        temperature = jnp.float32(1.0 - c / max(NCAP - 1, 1))
        keys = jax.random.split(jax.random.fold_in(base, c), I)
        pops, nev, birth, ref, marks, cand = one_cycle(
            keys, pops, birth, ref, stats_nf, temperature, marks)
        prog = progify(cand)
        Tb = cand.arity.shape[1]
        code = np.asarray(prog.code)
        src1 = np.asarray(prog.src1)
        src2 = np.asarray(prog.src2)
        nst = np.asarray(prog.nsteps)
        cv = np.asarray(prog.cvals)
        nc = np.asarray(prog.nconst)

        # global over the flat batch
        g_s, _, counts = dup_stats(code, src1, src2, nst)
        g_f, _, _ = dup_stats(code, src1, src2, nst, cv, nc)
        group_sizes.append(counts)
        # per island: mean over islands
        i_s, i_f = [], []
        for i in range(I):
            s = slice(i * Tb, (i + 1) * Tb)
            r, _, _ = dup_stats(code[s], src1[s], src2[s], nst[s])
            rf, _, _ = dup_stats(code[s], src1[s], src2[s], nst[s],
                                 cv[s], nc[s])
            i_s.append(r)
            i_f.append(rf)
        agg["cand_island_struct"].append(float(np.mean(i_s)))
        agg["cand_island_full"].append(float(np.mean(i_f)))
        agg["cand_global_struct"].append(g_s)
        agg["cand_global_full"].append(g_f)

        if c in (0, NCAP - 1):
            pprog = progify(pops.trees)
            pc, p1, p2, pn = (np.asarray(pprog.code), np.asarray(pprog.src1),
                              np.asarray(pprog.src2), np.asarray(pprog.nsteps))
            pg, _, _ = dup_stats(pc, p1, p2, pn)
            ps = []
            for i in range(I):
                s = slice(i * P, (i + 1) * P)
                r, _, _ = dup_stats(pc[s], p1[s], p2[s], pn[s])
                ps.append(r)
            agg["pop_island_struct"].append(float(np.mean(ps)))
            agg["pop_global_struct"].append(pg)

    print(f"\nconfig: {I} islands x {P} members, eval batch/island = "
          f"{Tb} trees, {NCAP} cycles sampled after {WARM} warm iters")
    for k, v in agg.items():
        if v:
            print(f"{k:24s} mean {np.mean(v):.3f}  min {np.min(v):.3f}  "
                  f"max {np.max(v):.3f}")
    counts = np.concatenate(group_sizes)
    big = counts[counts > 1]
    if len(big):
        print(f"global dup groups: {len(big)} groups >1, mean size "
              f"{big.mean():.1f}, p90 {np.percentile(big, 90):.0f}, "
              f"max {big.max()}")
        for V in (2, 4, 8):
            # dispatch rows if each group packs into ceil(c/V) variant rows
            rows = np.ceil(counts / V).sum()
            print(f"  V={V}: dispatch rows {rows / counts.sum():.2%} of "
                  f"per-tree baseline (global packing)")


if __name__ == "__main__":
    main()
