"""Sweep (tree_block, tile_rows) for the fused kernel on the bench shape."""

from __future__ import annotations

import itertools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=10, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine
    from symbolicregression_jl_tpu.evolve.population import init_population
    from symbolicregression_jl_tpu.ops.fused_eval import fused_loss

    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=30,
        save_to_file=False,
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-3.0, 3.0, (10_000, 5)).astype(np.float32)
    y = np.cos(2.13 * X[:, 0]).astype(np.float32)
    ds = make_dataset(X, y)
    engine = Engine(options, ds.nfeatures)
    cfg = engine.cfg

    for T in (96, 1024, 4096):
        trees = init_population(jax.random.PRNGKey(0), T, cfg.mctx, jnp.float32)
        for TB, TILE in itertools.product((8, 16, 32), (2048, 5120, 10240)):
            try:
                f = jax.jit(lambda tr: fused_loss(
                    tr, ds.data.Xt, ds.data.y, None, cfg.operators,
                    options.elementwise_loss, tree_block=TB, tile_rows=TILE,
                    interpret=cfg.interpret))
                t = timeit(f, trees)
                print(f"T={T:5d} TB={TB:3d} TILE={TILE:6d}: "
                      f"{t*1e3:8.3f} ms  {T/t:10.0f} ev/s")
            except Exception as e:
                print(f"T={T:5d} TB={TB:3d} TILE={TILE:6d}: FAIL {type(e).__name__}")


if __name__ == "__main__":
    main()
