"""Sweep bench-config knobs (islands, attempts, tournament) for evals/s.

The bench metric counts full-dataset evals/s; machinery cost per cycle is
partly per-op overhead on small tensors, so larger island counts amortize
it. Run on the TPU: python profiling/config_sweep.py
"""

from __future__ import annotations

import sys
import time

from _common import make_bench_problem


def run(cfg_kw):
    import jax

    from symbolicregression_jl_tpu import search_key

    options, ds, engine = make_bench_problem(ncycles_per_iteration=100, **cfg_kw)
    state = engine.init_state(search_key(0), ds.data, options.populations)
    state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    ev0 = float(state.num_evals)
    t0 = time.perf_counter()
    N = 3
    for _ in range(N):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    dt = time.perf_counter() - t0
    ev = float(state.num_evals) - ev0
    print(f"{cfg_kw}: {ev / dt:10.0f} evals/s ({dt / N * 1e3:.0f} ms/iter)",
          flush=True)


def main():
    configs = [
        dict(populations=128, population_size=128, tournament_selection_n=8),
        dict(populations=256, population_size=128, tournament_selection_n=8),
        dict(populations=512, population_size=128, tournament_selection_n=8),
        dict(populations=256, population_size=128, tournament_selection_n=8,
             mutation_attempts=3),
        dict(populations=512, population_size=128, tournament_selection_n=8,
             mutation_attempts=3),
        dict(populations=256, population_size=256, tournament_selection_n=16),
        dict(populations=512, population_size=256, tournament_selection_n=16),
        dict(populations=384, population_size=256, tournament_selection_n=16),
        dict(populations=512, population_size=192, tournament_selection_n=16),
        dict(populations=256, population_size=256, tournament_selection_n=16,
             optimizer_probability=0.2),
        dict(populations=768, population_size=256, tournament_selection_n=16),
        dict(populations=1024, population_size=256, tournament_selection_n=16),
        dict(populations=1024, population_size=128, tournament_selection_n=16),
        dict(populations=512, population_size=256, tournament_selection_n=16,
             optimizer_probability=0.2),
        dict(populations=512, population_size=256, tournament_selection_n=16,
             optimizer_probability=0.3),
    ]
    if len(sys.argv) > 1:  # subset by index
        configs = [configs[int(i)] for i in sys.argv[1:]]
    for kw in configs:
        try:
            run(kw)
        except Exception as e:  # noqa: BLE001
            print(f"{kw}: FAIL {type(e).__name__}: {str(e)[:160]}", flush=True)


if __name__ == "__main__":
    main()
