"""Standalone optimizer-phase bench: times `optimize_constants_fused` on
the realistic selected batch of the bench config (512 islands x 256
members, k_sel=36 -> 18,432 trees), sweeping the kernel launch plan
(V-chunks, VMEM tile budgets, tree_block).

The trees come from one real evolved iteration so program lengths and
constant counts match what the engine actually optimizes. Timing is
dependency-chained (each call's new constants feed the next call);
evals/s uses the same f_calls accounting as the engine.

Usage: opt_bench.py [n_iters] [n_chain] [--exact]
  n_iters: evolution iterations before selecting the batch (tree length
           grows/oscillates with this; 1 -> mean len ~9, 4 -> ~16)
  n_chain: timed dependency-chained launches per config
  --exact: also compare early_exit on/off outputs (NOT expected to be
           bit-identical: a failed row's zero history pair resets the
           two-loop gamma, so the un-frozen row can recover; this mode
           measures how far the trajectories drift and the live-row
           decay)
"""

from __future__ import annotations

import sys
import time

import _common  # noqa: F401,E402  (repo root on sys.path)

import dataclasses

import jax
import jax.numpy as jnp

from _common import make_bench_problem


def build_selected_batch(I=512, P=256, NC=100, n_iters=3):
    """A few evolved iterations (steady-state tree lengths), then the
    epilogue's top-k selection."""
    from symbolicregression_jl_tpu import search_key

    options, ds, engine = make_bench_problem(
        populations=I, population_size=P, ncycles_per_iteration=NC,
        tournament_selection_n=16)
    state = engine.init_state(search_key(0), ds.data, I)
    for _ in range(n_iters):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)

    k_sel = max(1, round(P * options.optimizer_probability))
    scores = jax.random.uniform(jax.random.PRNGKey(7), (I, P))
    _, sel_idx = jax.lax.top_k(scores, k_sel)
    sub = jax.vmap(
        lambda t, i: jax.tree.map(lambda x: jnp.take(x, i, axis=0), t)
    )(state.pops.trees, sel_idx)
    flat = jax.tree.map(
        lambda x: x.reshape((I * k_sel,) + x.shape[2:]), sub)
    return options, ds, engine, flat


def time_cfg(options, ds, engine, flat, cfg, n_chain=3):
    from symbolicregression_jl_tpu.evolve.constant_opt import (
        optimize_constants_fused)

    M = flat.arity.shape[0]
    do_opt = jnp.ones((M,), bool)
    key = jax.random.PRNGKey(3)

    @jax.jit
    def one(const):
        t = dataclasses.replace(flat, const=const)
        new_const, improved, new_loss, f_calls = optimize_constants_fused(
            key, t, do_opt, ds.data, options.elementwise_loss,
            engine.cfg.operators, cfg)
        return new_const, f_calls

    const = flat.const
    new_const, f_calls = one(const)          # compile + warmup
    jax.block_until_ready(new_const)
    t0 = time.perf_counter()
    c = new_const
    for _ in range(n_chain):
        c, f_calls = one(c)
    jax.block_until_ready(c)
    dt = (time.perf_counter() - t0) / n_chain
    ev = float(jnp.sum(f_calls))
    return dt, ev


def check_exact(options, ds, engine, flat):
    """Compare early_exit on/off outputs and print the live-row decay.

    NOT expected to be bit-identical (see module docstring); the
    interesting outputs are how many rows stay live per iteration and
    how much the frozen trajectories drift."""
    from symbolicregression_jl_tpu.evolve.constant_opt import (
        OptimizerConfig, optimize_constants_fused)

    M = flat.arity.shape[0]
    do_opt = jnp.ones((M,), bool)
    key = jax.random.PRNGKey(3)
    outs = {}
    for name, cfg in (("off", OptimizerConfig(early_exit=False)),
                      ("on", OptimizerConfig(early_exit=True))):
        outs[name] = optimize_constants_fused(
            key, flat, do_opt, ds.data, options.elementwise_loss,
            engine.cfg.operators, cfg, return_diag=True)
    c_eq = bool(jnp.array_equal(outs["off"][0], outs["on"][0]))
    i_eq = bool(jnp.array_equal(outs["off"][1], outs["on"][1]))
    l_eq = bool(jnp.array_equal(outs["off"][2], outs["on"][2]))
    tr = [int(v) for v in outs["on"][4]]
    print(f"outputs equal (drift check): const={c_eq} improved={i_eq} "
          f"loss={l_eq}")
    print(f"live rows/iteration (of {3 * M}): {tr}")
    print(f"f_calls: off {float(jnp.sum(outs['off'][3])):.0f}  "
          f"on {float(jnp.sum(outs['on'][3])):.0f}")
    return c_eq and i_eq and l_eq


def main():
    pos = [a for a in sys.argv[1:] if not a.startswith("-")]
    n_iters = int(pos[0]) if len(pos) > 0 else 3
    n_chain = int(pos[1]) if len(pos) > 1 else 3
    from symbolicregression_jl_tpu.evolve.constant_opt import OptimizerConfig

    options, ds, engine, flat = build_selected_batch(n_iters=n_iters)
    M = flat.arity.shape[0]
    print(f"selected batch: {M} trees, "
          f"mean length {float(jnp.mean(flat.length)):.1f}")

    if "--exact" in sys.argv:
        check_exact(options, ds, engine, flat)

    MB = 2**20
    configs = [
        ("baseline (ls 3x2=6 passes)", OptimizerConfig()),
        ("early_exit on", OptimizerConfig(early_exit=True)),
        ("ls V24 @12.5MB (1x4=4 passes)", OptimizerConfig(
            ls_v_chunk=24, ls_tile_budget=int(12.5 * MB))),
        ("TB16", OptimizerConfig(tree_block=16)),
        ("TB32", OptimizerConfig(tree_block=32)),
        ("ls V24 + TB16", OptimizerConfig(
            ls_v_chunk=24, ls_tile_budget=int(12.5 * MB), tree_block=16)),
        ("gr @9MB", OptimizerConfig(grad_tile_budget=9 * MB)),
    ]

    results = []
    for name, cfg in configs:
        try:
            dt, ev = time_cfg(options, ds, engine, flat, cfg, n_chain)
            rate = ev / dt
            results.append((name, dt, rate))
            print(f"{name:42s} {dt:7.3f} s/launch  {rate:10.0f} ev/s")
        except Exception as e:  # VMEM OOM etc.
            print(f"{name:42s} FAILED: {type(e).__name__}: "
                  f"{str(e)[:200]}")
    if not results:
        print("\nall configs failed")
        return
    best = min(results, key=lambda r: r[1])
    print(f"\nbest: {best[0]}  {best[1]:.3f} s/launch ({best[2]:.0f} ev/s)")


if __name__ == "__main__":
    main()
