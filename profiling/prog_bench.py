"""A/B the program kernel: compile cost vs kernel cost vs old-style call.

Usage: prog_bench.py [T] [TB] [avg_len]
"""

from __future__ import annotations

import dataclasses
import sys
import time

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax
import jax.numpy as jnp
import numpy as np


def main():
    T = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    TB = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    from _common import make_bench_problem, timeit
    from symbolicregression_jl_tpu.ops.fused_eval import (
        fused_loss, fused_loss_program, fused_grad_program)
    from symbolicregression_jl_tpu.ops.program import compile_program
    from symbolicregression_jl_tpu.evolve.population import init_population

    options, ds, engine = make_bench_problem()
    cfg = engine.cfg
    X, y = ds.data.Xt, ds.data.y
    F = X.shape[0]
    nB = len(cfg.operators.binary)

    trees = init_population(jax.random.PRNGKey(0), T, cfg.mctx, jnp.float32)
    lens = np.asarray(trees.length)
    prog0 = jax.jit(lambda tr: compile_program(tr, F, nB),
                    static_argnums=())(trees)
    steps = np.asarray(prog0.nsteps)
    print(f"tree len: mean {lens.mean():.1f} max {lens.max()}  "
          f"steps: mean {steps.mean():.1f} max {steps.max()}")

    compile_fn = jax.jit(lambda tr: compile_program(tr, F, nB))

    def chain(fn, x0, n=30):
        out = fn(x0)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(out) if isinstance(out, type(x0)) else fn(x0)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / n

    # 1. compile alone (chained via const feedback)
    @jax.jit
    def compile_step(tr):
        p = compile_program(tr, F, nB)
        eps = jnp.sum(p.cvals) * 1e-30
        return dataclasses.replace(tr, const=tr.const + eps)

    dt = chain(compile_step, trees)
    print(f"compile_program:      {dt*1e3:7.3f} ms/launch  {T/dt:>9.0f} tree/s")

    # 2. kernel alone (program precompiled; chained via cvals feedback)
    @jax.jit
    def kernel_step(p):
        loss, valid = fused_loss_program(
            p, X, y, None, F, cfg.operators, options.elementwise_loss,
            tree_block=TB)
        eps = jnp.nanmin(jnp.where(jnp.isfinite(loss), loss, jnp.inf)) * 1e-30
        return dataclasses.replace(p, cvals=p.cvals + eps)

    dt = chain(kernel_step, prog0)
    print(f"fused_loss_program:   {dt*1e3:7.3f} ms/launch  {T/dt:>9.0f} ev/s")

    # 3. full fused_loss (compile + kernel)
    @jax.jit
    def full_step(tr):
        loss, valid = fused_loss(
            tr, X, y, None, cfg.operators, options.elementwise_loss,
            tree_block=TB)
        eps = jnp.nanmin(jnp.where(jnp.isfinite(loss), loss, jnp.inf)) * 1e-30
        return dataclasses.replace(tr, const=tr.const + eps)

    dt = chain(full_step, trees)
    print(f"fused_loss (full):    {dt*1e3:7.3f} ms/launch  {T/dt:>9.0f} ev/s")

    # 4. grad kernel (program precompiled)
    @jax.jit
    def grad_step(p):
        loss, valid, g = fused_grad_program(
            p, X, y, None, F, cfg.operators, options.elementwise_loss,
            tree_block=TB)
        eps = jnp.nanmin(jnp.where(jnp.isfinite(loss), loss, jnp.inf)) * 1e-30
        return dataclasses.replace(p, cvals=p.cvals + eps)

    dt = chain(grad_step, prog0)
    print(f"fused_grad_program:   {dt*1e3:7.3f} ms/launch  {T/dt:>9.0f} ev/s")


if __name__ == "__main__":
    main()
