"""Per-fusion breakdown of the evolve-cycle machinery.

Runs a no-optimizer iteration (the cycle scan dominates it) under the
profiler and aggregates device events by EXACT op name, printing each
top op's long_name snippet — fine-grained enough to attribute the
mutation/selection machinery, unlike trace_cycle's prefix buckets.

Usage: trace_machinery.py [islands] [ncycles] [pop]
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
from collections import defaultdict

import _common  # noqa: F401,E402  (repo root on sys.path)

import jax

from _common import make_bench_problem


def main():
    I = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    NC = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    P = int(sys.argv[3]) if len(sys.argv) > 3 else 256

    from symbolicregression_jl_tpu import search_key

    options, ds, engine = make_bench_problem(
        populations=I, population_size=P, ncycles_per_iteration=NC,
        tournament_selection_n=16, should_optimize_constants=False,
    )
    state = engine.init_state(search_key(0), ds.data, I)
    state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)

    logdir = "/tmp/sr_trace_m"
    os.system(f"rm -rf {logdir}")
    with jax.profiler.trace(logdir):
        state = engine.run_iteration(state, ds.data, options.maxsize)
        jax.block_until_ready(state.pops.cost)

    files = glob.glob(f"{logdir}/**/*.trace.json.gz", recursive=True)
    agg = defaultdict(float)
    names = {}
    total = 0.0
    for fn in files:
        with gzip.open(fn, "rt") as f:
            data = json.load(f)
        for ev in data.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            name = ev.get("name", "")
            args = ev.get("args", {})
            if "long_name" not in args:
                continue
            dur = ev.get("dur", 0) / 1e3
            if name.startswith("while"):
                continue  # scan wrappers double-count their bodies
            agg[name] += dur
            names[name] = args.get("long_name", "")[:160]
            total += dur
    print(f"total attributed device op time: {total:.1f} ms over {NC} cycles"
          f" ({total/NC:.2f} ms/cycle incl. epilogue)")
    for k, v in sorted(agg.items(), key=lambda kv: -kv[1])[:30]:
        print(f"  {v:9.3f} ms  {k:28s} {names[k]}")


if __name__ == "__main__":
    main()
