"""Measure run_iteration throughput (evals/s) across search configs."""

from __future__ import annotations

import time

import jax

from _common import make_bench_problem


def main():
    configs = [
        dict(populations=15, population_size=33),
        dict(populations=40, population_size=33),
        dict(populations=64, population_size=128, tournament_selection_n=8),
        dict(populations=128, population_size=128, tournament_selection_n=8),
    ]
    for cfg_kw in configs:
        options, ds, engine = make_bench_problem(
            ncycles_per_iteration=100, **cfg_kw
        )
        try:
            from symbolicregression_jl_tpu import search_key

            state = engine.init_state(search_key(0), ds.data,
                                      options.populations)
            state = engine.run_iteration(state, ds.data, options.maxsize)
            jax.block_until_ready(state.pops.cost)
            ev0 = float(state.num_evals)
            t0 = time.perf_counter()
            N = 3
            for _ in range(N):
                state = engine.run_iteration(state, ds.data, options.maxsize)
            jax.block_until_ready(state.pops.cost)
            dt = time.perf_counter() - t0
            ev = float(state.num_evals) - ev0
            print(f"{cfg_kw}: {ev/dt:10.0f} evals/s   "
                  f"({dt/N*1e3:.0f} ms/iter, {ev/N:.0f} evals/iter)")
        except Exception as e:
            print(f"{cfg_kw}: FAIL {type(e).__name__}: {str(e)[:120]}")


if __name__ == "__main__":
    main()
