"""A/B kernel-structure variants of the program-loss kernel.

Measures where the per-step scalar-dispatch cost goes by timing
semantically-degraded or restructured copies of the interpreter:

  base       — the shipped fused_loss_program
  noswitch   — every step computes binary_op[0] (floor: loop + reads +
               store + vmask, no dispatch)
  novmask    — shipped dispatch, but no per-step finiteness tracking
  cond2      — two-level dispatch: class cond (identity/binary/unary)
               with an inner per-class switch
  signmerge  — {+,-} merged into ONE branch via a sign bit packed in the
               instruction word (val = a + sgn*b, one FMA)
  muldiv     — signmerge PLUS {*,/} merged via reciprocal-select (a
               div bit picks b vs 1/b, then one multiply). NOTE: a/b
               vs a*(1/b) differ in the last bit (two roundings), so
               this merge trades bit-exactness for a branch — checked
               here with allclose, not equality.
  opgroup    — muldiv PLUS all unary transcendentals grouped into ONE
               branch: compute every unary fn of the operand and
               select by a 3-bit unary index. Trades dispatch branches
               for unconditional transcendental FLOPs.
  nounroll   — no 2x pair unroll
  tb16/tb32  — tree_block 16/32 (X-copy + grid fixed costs amortized)

On a non-TPU backend the pallas kernels run in interpret mode: timings
are then meaningless, but the variant-vs-base loss checks still run —
that is how the muldiv/opgroup merges are validated on CPU CI while
the dispatch-cost verdict comes from the round-3 branch-cost model
(profiling/RESULTS.md).

Round-7 graftstage rows (docs/PRECISION.md) — these run the SHIPPED
fused_loss_program, not the legacy A/B copy above:

  prod       — production kernel, full dataset, f32
  prodbf16   — production kernel, full dataset, bf16 row tiles
               (`Options(eval_precision="bf16")` path)
  screen[D]  — production kernel on the staged screening sample: the
               strided 1/D row subset (default D=8, i.e. the default
               staged_sample_fraction=0.125), f32. screen vs prod is
               the measured screen:rescore per-launch cost ratio that
               RESULTS.md round 7 holds against the dispatch-floor
               model.

Usage: kernel_variants.py [T] [which...]
"""

from __future__ import annotations

import functools
import os
import sys
import time

import _common  # noqa: F401,E402  (repo root on sys.path)

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from _common import make_bench_problem

from symbolicregression_jl_tpu.ops.fused_eval import (
    _merged_branches, _pick_tile, _round_up, _unpack, fused_loss_program,
    strided_sample_indices)


def _pack_instr(prog):
    """Round-3 legacy pack (identity + per-op codes) — the variants here
    A/B the legacy dispatch layout; the shipped kernels now use the
    plan-aware pack in ops/fused_eval.py."""
    return (prog.code << 24) | (prog.src1 << 12) | prog.src2
from symbolicregression_jl_tpu.ops.program import compile_program


def _make_kernel(operators, loss_fn, tree_block, nfeat, cmax, variant):
    BASE = nfeat + cmax
    binary_fns = tuple(o.fn for o in operators.binary)
    unary_fns = tuple(o.fn for o in operators.unary)
    B = len(binary_fns)

    def kernel(instr_ref, nstep_ref, nconst_ref, cvals_ref, ok_ref,
               x_ref, y_ref, w_ref, mask_ref, loss_ref, valid_ref, buf_ref):
        j = pl.program_id(1)
        y_row = y_ref[0, :]
        mask_row = mask_ref[0, :] > 0
        w_row = w_ref[0, :] * mask_ref[0, :]
        tile = y_row.shape[0]
        L = instr_ref.shape[-1]

        buf_ref[0:nfeat, :] = x_ref[...]
        read = lambda i: buf_ref[i, :]

        for t in range(tree_block):
            bdt = buf_ref.dtype

            if variant == "cvec":
                # const preload as ONE vectorized broadcast store from a
                # VMEM cvals block (vs the dynamic scalar fori_loop)
                buf_ref[nfeat:nfeat + cmax, :] = jnp.broadcast_to(
                    cvals_ref[t, :][:, None], (cmax, tile)).astype(bdt)
            elif variant == "custatic":
                # static unrolled preload: no scalar-loop bookkeeping,
                # CMAX unconditional stores
                for c in range(cmax):
                    buf_ref[nfeat + c, :] = jnp.full(
                        (tile,), cvals_ref[t, c], dtype=bdt)
            else:
                def cbody(c, _):
                    buf_ref[nfeat + c, :] = jnp.full(
                        (tile,), cvals_ref[t, c], dtype=bdt)
                    return 0

                jax.lax.fori_loop(0, nconst_ref[t, 0], cbody, 0)

            def step(k, vmask):
                w_ = instr_ref[t, k]
                o, i1, i2 = _unpack(w_)
                if variant == "noswitch":
                    val = binary_fns[0](read(i1), read(i2))
                elif variant == "static":
                    val = binary_fns[0](read(0), read(1))
                elif variant == "nostore":
                    val = binary_fns[0](read(i1), read(i2))
                    buf_ref[BASE, :] = val
                    return vmask * jnp.isfinite(val).astype(vmask.dtype)
                elif variant == "cond2":
                    def class_bin():
                        return jax.lax.switch(
                            o - 1, [lambda f=f: f(read(i1), read(i2))
                                    for f in binary_fns])

                    def class_un():
                        return jax.lax.switch(
                            o - 1 - B, [lambda f=f: f(read(i1))
                                        for f in unary_fns])

                    val = jax.lax.cond(
                        o == 0, lambda: read(i1),
                        lambda: jax.lax.cond(o <= B, class_bin, class_un))
                elif variant in ("signmerge", "combo"):
                    # codes: 0 id, 1 addsub (sign bit 30), 2 mul, 3 div,
                    # then unary
                    s = (w_ >> 30) & 1
                    o2 = (w_ >> 24) & 0x3F
                    sgn = (1 - 2 * s).astype(bdt)
                    branches = [
                        lambda: read(i1),
                        lambda: read(i1) + sgn * read(i2),
                        lambda: binary_fns[2](read(i1), read(i2)),
                        lambda: binary_fns[3](read(i1), read(i2)),
                    ] + [lambda f=f: f(read(i1)) for f in unary_fns]
                    val = jax.lax.switch(o2, branches)
                elif variant in ("muldiv", "opgroup"):
                    # codes: 0 id, 1 addsub (sign bit 30), 2 muldiv
                    # (div bit 29 -> reciprocal-select), then unary —
                    # individually for "muldiv", as ONE grouped branch
                    # selected by a 3-bit unary index (bits 26-28) for
                    # "opgroup"
                    s = (w_ >> 30) & 1
                    dflag = (w_ >> 29) & 1
                    sgn = (1 - 2 * s).astype(bdt)
                    if variant == "muldiv":
                        o2 = (w_ >> 24) & 0x1F
                        uidx = 0
                    else:
                        o2 = (w_ >> 24) & 0x3
                        uidx = (w_ >> 26) & 0x7

                    def _muldiv():
                        b_ = read(i2)
                        b_ = jnp.where(
                            dflag > 0,
                            jnp.asarray(1.0, bdt) / b_, b_)
                        return read(i1) * b_

                    branches = [
                        lambda: read(i1),
                        lambda: read(i1) + sgn * read(i2),
                        _muldiv,
                    ]
                    if variant == "opgroup" and unary_fns:
                        def _ungrouped():
                            a_ = read(i1)
                            val = unary_fns[0](a_)
                            for u, f in enumerate(unary_fns[1:], 1):
                                val = jnp.where(uidx == u, f(a_), val)
                            return val

                        branches.append(_ungrouped)
                    else:
                        branches += [lambda f=f: f(read(i1))
                                     for f in unary_fns]
                    val = jax.lax.switch(o2, branches)
                else:
                    val = jax.lax.switch(
                        o, _merged_branches(operators, read, i1, i2))
                buf_ref[BASE + k, :] = val
                if variant == "novmask":
                    return vmask
                if val.dtype == jnp.bfloat16:
                    # Mosaic has no bf16 isfinite (tpu.weird is F32-only);
                    # bf16 shares f32's exponent range, so a magnitude
                    # compare is equivalent (NaN compares false).
                    fin = jnp.abs(val) <= jnp.asarray(3.38e38, val.dtype)
                    return vmask * fin.astype(vmask.dtype)
                return vmask * jnp.isfinite(val).astype(vmask.dtype)

            m = nstep_ref[t, 0]
            vmask0 = jnp.ones((tile,), bdt)
            if variant in ("nounroll", "combo", "bf16"):
                vmask = jax.lax.fori_loop(0, m, step, vmask0)
            else:
                def pair(k2, vmask):
                    vmask = step(2 * k2, vmask)
                    return step(jnp.minimum(2 * k2 + 1, L - 1), vmask)

                vmask = jax.lax.fori_loop(0, (m + 1) >> 1, pair, vmask0)
            valid = jnp.all((vmask > 0) | jnp.logical_not(mask_row))
            pred = buf_ref[BASE + m - 1, :].astype(y_row.dtype)
            elt = loss_fn(pred, y_row)
            elt = jnp.where(w_row > 0, elt, 0.0)
            partial = jnp.sum(elt * w_row)
            partial_ok = jnp.int32(valid & jnp.isfinite(partial)) * ok_ref[t, 0]

            @pl.when(j == 0)
            def _():
                loss_ref[t, 0] = partial
                valid_ref[t, 0] = partial_ok

            @pl.when(j != 0)
            def _():
                loss_ref[t, 0] = loss_ref[t, 0] + partial
                valid_ref[t, 0] = valid_ref[t, 0] & partial_ok

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "nfeatures", "operators", "loss_fn", "tree_block", "variant",
    "interpret"))
def loss_variant(prog, X, y, nfeatures, operators, loss_fn,
                 tree_block=8, variant="base", interpret=False):
    T, L = prog.code.shape
    CMAX = prog.cmax
    F, n = X.shape
    dtype = X.dtype
    BASE = nfeatures + CMAX

    buf_dtype = jnp.bfloat16 if variant == "bf16" else dtype
    TB = tree_block
    bytes_per = jnp.dtype(buf_dtype).itemsize
    TILE = _pick_tile(n, 16384, BASE + L, bytes_per)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_t(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    instr_w = _pack_instr(prog)
    if variant in ("signmerge", "combo"):
        # remap codes: 1:+ 2:- -> code 1 (+ sign bit), 3:* -> 2, 4:/ -> 3,
        # unary 5.. -> 4..
        o = prog.code
        is_sub = o == 2
        o2 = jnp.where(o <= 2, jnp.minimum(o, 1),
                       jnp.where(o <= 4, o - 1, o - 1))
        instr_w = ((is_sub.astype(jnp.int32) << 30) | (o2 << 24)
                   | (prog.src1 << 12) | prog.src2)
    elif variant in ("muldiv", "opgroup"):
        # remap codes: 1:+ 2:- -> 1 (+ sign bit 30); 3:* 4:/ -> 2
        # (+ div bit 29); unary 5.. -> 3.. individually ("muldiv") or
        # all -> 3 with the unary index in bits 26-28 ("opgroup")
        o = prog.code
        is_sub = (o == 2).astype(jnp.int32)
        is_div = (o == 4).astype(jnp.int32)
        if variant == "muldiv":
            o2 = jnp.where(o <= 2, jnp.minimum(o, 1),
                           jnp.where(o <= 4, 2, o - 2))
            uidx = jnp.zeros_like(o)
        else:
            o2 = jnp.where(o <= 2, jnp.minimum(o, 1),
                           jnp.where(o <= 4, 2, 3))
            uidx = jnp.maximum(o - 5, 0)
        instr_w = ((is_sub << 30) | (is_div << 29) | (uidx << 26)
                   | (o2 << 24) | (prog.src1 << 12) | prog.src2)
    instr = pad_t(instr_w)
    nsteps = pad_t(prog.nsteps.reshape(-1, 1), fill=1)
    nconst = pad_t(prog.nconst.reshape(-1, 1))
    cvals = pad_t(prog.cvals).astype(dtype)
    ok = pad_t(prog.const_ok.astype(jnp.int32).reshape(-1, 1), fill=1)

    Xp = jnp.pad(X.astype(buf_dtype), ((0, 0), (0, n_pad - n)))
    yp = jnp.pad(y.reshape(1, n), ((0, 0), (0, n_pad - n)))
    w = jnp.ones((1, n), dtype)
    wp = jnp.pad(w, ((0, 0), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_kernel(operators, loss_fn, TB, nfeatures, CMAX, variant)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM)
    row_spec = pl.BlockSpec((1, TILE), lambda i, j: (0, j))

    loss_sum, valid = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, instr.shape[-1])), smem_i32((TB, 1)),
            smem_i32((TB, 1)),
            (pl.BlockSpec((TB, CMAX), lambda i, j: (i, 0))
             if variant == "cvec" else
             pl.BlockSpec((TB, CMAX), lambda i, j: (i, 0),
                          memory_space=pltpu.SMEM)),
            smem_i32((TB, 1)),
            pl.BlockSpec((F, TILE), lambda i, j: (0, j)),
            row_spec, row_spec, row_spec,
        ],
        out_specs=[
            pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, 1), dtype),
            jax.ShapeDtypeStruct((T_pad, 1), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((BASE + instr.shape[-1], TILE), buf_dtype)],
        interpret=interpret,
    )(instr, nsteps, nconst, cvals, ok, Xp, yp, wp, maskp)
    return loss_sum[:T, 0], valid[:T, 0]


def synth_program(T, S, L, F, CMAX, n_codes, seed=0):
    """Random valid TreeProgram with exactly S steps per tree."""
    from symbolicregression_jl_tpu.ops.program import TreeProgram

    rng = np.random.default_rng(seed)
    BASE = F + CMAX
    code = np.zeros((T, L), np.int32)
    src1 = np.zeros((T, L), np.int32)
    src2 = np.zeros((T, L), np.int32)
    code[:, :S] = rng.integers(1, n_codes, (T, S))
    for k in range(S):
        hi = BASE + k
        src1[:, k] = rng.integers(0, hi, T)
        src2[:, k] = rng.integers(0, hi, T)
    ncon = np.full((T,), CMAX, np.int32)
    cvals = rng.uniform(0.5, 1.5, (T, CMAX)).astype(np.float32)
    cslot = np.tile(np.arange(CMAX, dtype=np.int32), (T, 1))
    return TreeProgram(
        code=jnp.asarray(code), src1=jnp.asarray(src1),
        src2=jnp.asarray(src2),
        nsteps=jnp.full((T,), S, jnp.int32),
        cvals=jnp.asarray(cvals), cslot=jnp.asarray(cslot),
        nconst=jnp.asarray(ncon),
        const_ok=jnp.ones((T,), bool))


def main():
    T = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    S = int(os.environ.get("STEPS", "8"))
    which = sys.argv[2:] or ["base", "noswitch", "novmask", "cond2",
                             "signmerge", "muldiv", "opgroup",
                             "nounroll", "tb16", "tb32",
                             "prod", "prodbf16", "screen"]

    options, ds, engine = make_bench_problem()
    cfg = engine.cfg
    X, y = ds.data.Xt, ds.data.y
    F = X.shape[0]
    nB = len(cfg.operators.binary)

    n_codes = 1 + len(cfg.operators.binary) + len(cfg.operators.unary)
    prog = synth_program(T, S, 30, F, 15, n_codes)
    steps = np.asarray(prog.nsteps)
    print(f"T={T} steps: mean {steps.mean():.2f} max {steps.max()}")

    interp_all = jax.default_backend() != "tpu"
    base_loss = None
    for v in which:
        tb = 8
        vv = v
        if v.startswith("tb"):
            tb = int(v[2:])
            vv = "base"
        elif v == "combo":
            tb = 16

        if v in ("prod", "prodbf16") or v.startswith("screen"):
            # Shipped-kernel rows (round 7): full-row f32 / bf16 tiles,
            # and the staged screening launch on the strided row sample.
            Xv, yv = X, y
            if v.startswith("screen"):
                denom = int(v[len("screen"):] or "8")
                n = int(X.shape[1])
                k = max(64, n // denom)
                idx = jnp.asarray(strided_sample_indices(n, k))
                Xv = jnp.take(X, idx, axis=1)
                yv = jnp.take(y, idx)

            interp = jax.default_backend() != "tpu"

            @jax.jit
            def step_fn(p, Xv=Xv, yv=yv, bf=(v == "prodbf16"),
                        interp=interp):
                loss, valid = fused_loss_program(
                    p, Xv, yv, None, F, cfg.operators,
                    options.elementwise_loss, bf16=bf,
                    interpret=interp)
                eps = jnp.nanmin(
                    jnp.where(jnp.isfinite(loss), loss, jnp.inf))
                return dataclasses.replace(
                    p, cvals=p.cvals + eps * 1e-30), loss
        else:
            @jax.jit
            def step_fn(p, tb=tb, vv=vv, interp=interp_all):
                loss, valid = loss_variant(
                    p, X, y, F, cfg.operators, options.elementwise_loss,
                    tree_block=tb, variant=vv, interpret=interp)
                eps = jnp.nanmin(
                    jnp.where(jnp.isfinite(loss), loss, jnp.inf))
                return dataclasses.replace(
                    p, cvals=p.cvals + eps * 1e-30), loss

        p2, loss = step_fn(prog)
        jax.block_until_ready(loss)
        t0 = time.perf_counter()
        N = 30
        p2 = prog
        for _ in range(N):
            p2, loss = step_fn(p2)
        jax.block_until_ready(loss)
        dt = (time.perf_counter() - t0) / N
        ok = ""
        if vv in ("base", "cond2", "signmerge", "muldiv", "opgroup",
                  "nounroll", "cvec", "custatic") or v.startswith("tb"):
            if base_loss is None and v == "base":
                base_loss = np.asarray(loss)
            elif base_loss is not None:
                # reciprocal-select (a/b -> a*(1/b)) is a last-bit
                # rewrite that exp/log chains amplify to ~1e-3 relative
                # on rare trees — the merged variants get a loose band
                # and an honest label, everything else stays tight
                rtol = 1e-3 if vv in ("muldiv", "opgroup") else 1e-6
                match = np.allclose(np.asarray(loss), base_loss,
                                    rtol=rtol, equal_nan=True)
                tag = ("loss~=base@1e-3"
                       if vv in ("muldiv", "opgroup") else "loss==base")
                ok = f"  {tag}" if match else "  LOSS MISMATCH"
        print(f"{v:10s} {dt*1e3:8.3f} ms/launch  {T/dt:>10.0f} trees/s"
              f"  {dt/T/steps.mean()*1e9:6.1f} ns/step{ok}")


if __name__ == "__main__":
    main()
