"""Time one fused config in a fresh process and validate the result."""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main():
    T = int(sys.argv[1])
    TB = int(sys.argv[2])
    TILE = int(sys.argv[3])

    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine
    from symbolicregression_jl_tpu.evolve.population import init_population
    from symbolicregression_jl_tpu.ops.fused_eval import fused_loss

    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=30,
        save_to_file=False,
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-3.0, 3.0, (10_000, 5)).astype(np.float32)
    y = np.cos(2.13 * X[:, 0]).astype(np.float32)
    ds = make_dataset(X, y)
    engine = Engine(options, ds.nfeatures)
    cfg = engine.cfg

    trees = init_population(jax.random.PRNGKey(0), T, cfg.mctx, jnp.float32)
    f = jax.jit(lambda tr: fused_loss(
        tr, ds.data.Xt, ds.data.y, None, cfg.operators,
        options.elementwise_loss, tree_block=TB, tile_rows=TILE,
        interpret=cfg.interpret))
    loss, valid = f(trees)
    jax.block_until_ready(loss)
    n_valid = int(jnp.sum(valid))
    mean_finite = float(jnp.nanmean(jnp.where(jnp.isfinite(loss), loss, jnp.nan)))

    times = []
    for _ in range(10):
        t0 = time.perf_counter()
        out = f(trees)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    t = float(np.median(times))
    print(f"T={T} TB={TB} TILE={TILE}: {t*1e3:.3f} ms  {T/t:.0f} ev/s  "
          f"valid={n_valid}/{T} meanloss={mean_finite:.4f}  "
          f"min={min(times)*1e3:.3f} max={max(times)*1e3:.3f}")


if __name__ == "__main__":
    main()
