"""Microbenchmarks for the eval kernels and the iteration hot loop.

Run on the target backend (TPU) to get the breakdown the perf work is
driven by; results are recorded in profiling/RESULTS.md.

Usage: python profiling/profile_eval.py [--trees 90 512 2048] [--rows 10000]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=10, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--trees", type=int, nargs="+",
                    default=[90, 256, 1024, 4096])
    ap.add_argument("--rows", type=int, default=10_000)
    ap.add_argument("--maxsize", type=int, default=30)
    args = ap.parse_args()

    from symbolicregression_jl_tpu import Options
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine
    from symbolicregression_jl_tpu.evolve.population import init_population
    from symbolicregression_jl_tpu.ops.eval import eval_tree_batch
    from symbolicregression_jl_tpu.ops.fused_eval import fused_loss
    from symbolicregression_jl_tpu.core.losses import aggregate_loss

    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=args.maxsize,
        populations=15,
        population_size=33,
        ncycles_per_iteration=100,
        save_to_file=False,
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-3.0, 3.0, (args.rows, 5)).astype(np.float32)
    y = np.cos(2.13 * X[:, 0]).astype(np.float32)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    engine = Engine(options, ds.nfeatures)
    cfg = engine.cfg

    print(f"backend={jax.default_backend()} rows={args.rows} L={args.maxsize}")

    for T in args.trees:
        key = jax.random.PRNGKey(0)
        trees = init_population(key, T, cfg.mctx, jnp.float32)

        f_fused = jax.jit(lambda tr: fused_loss(
            tr, ds.data.Xt, ds.data.y, None, cfg.operators,
            options.elementwise_loss, interpret=cfg.interpret))
        t_fused = timeit(f_fused, trees)

        def jnp_loss(tr):
            pred, valid = eval_tree_batch(tr, ds.data.Xt, cfg.operators)
            return aggregate_loss(options.elementwise_loss, pred, ds.data.y,
                                  valid, None)
        f_jnp = jax.jit(jnp_loss)
        t_jnp = timeit(f_jnp, trees)

        print(f"T={T:6d}  fused={t_fused*1e3:8.3f} ms ({T/t_fused:10.0f} ev/s)"
              f"  jnp={t_jnp*1e3:8.3f} ms ({T/t_jnp:10.0f} ev/s)")

    # full iteration breakdown
    state = engine.init_state(jax.random.PRNGKey(0), ds.data,
                              options.populations)
    t_iter = timeit(
        lambda s: engine.run_iteration(s, ds.data, options.maxsize),
        state, n=3, warmup=1)
    evals_per_iter = (options.populations * cfg.n_slots * 2 * cfg.ncycles
                      + options.populations * options.population_size)
    print(f"run_iteration: {t_iter*1e3:.1f} ms  "
          f"(~{evals_per_iter} evals -> {evals_per_iter/t_iter:.0f} ev/s)"
          f"  per-cycle: {t_iter/cfg.ncycles*1e3:.2f} ms")


if __name__ == "__main__":
    main()
