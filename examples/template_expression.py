"""Template expressions: constrain the functional form, search the parts.

Mirrors the reference's examples/template_expression.jl: the model is
forced into the shape ``f(x1) * f(x1) + g(x2)`` — the search only
evolves the subexpressions ``f`` and ``g``; the combiner is fixed
Python (traced once and fused into the device program). Combiners may
also differentiate subexpressions with ``sr.D`` (see README).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import symbolicregression_jl_tpu as sr  # noqa: E402
from symbolicregression_jl_tpu.models import template_spec  # noqa: E402


def main(niterations: int = 8, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2.0, 2.0, (400, 2)).astype(np.float32)
    # truth: f(v) = 1.5*v, g(v) = cos(2v)  =>  y = f(x1)^2 + g(x2)
    y = (1.5 * X[:, 0]) ** 2 + np.cos(2.0 * X[:, 1])

    spec = template_spec(expressions=("f", "g"))(
        lambda f, g, x1, x2: f(x1) * f(x1) + g(x2)
    )

    model = sr.SRRegressor(
        niterations=niterations,
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        expression_spec=spec,
        populations=8,
        population_size=33,
        ncycles_per_iteration=60,
        maxsize=16,
        save_to_file=False,
    )
    model.fit(X, y)

    best = model.equations_[model.best_idx_]
    print("best template instance:")
    print(best.equation)
    print("loss:", best.loss)


if __name__ == "__main__":
    main()
