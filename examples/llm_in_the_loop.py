"""LLM-in-the-loop search: propose -> seed -> refine, with any proposer.

The fork's examples/custom_population_llm*.jl loop is: run some
iterations, show the pareto front to an LLM over an OpenAI-compatible
chat API, parse its proposed expressions, seed a fresh population, and
resume. The library hooks that make this work are exactly three —
``initial_population`` / ``guesses`` seeding, ``parse_expression``,
and warm starting via ``saved_state`` — so this example factors the
LLM behind a plain callable: plug in any proposer (an HTTP client, a
local model, a heuristic) without changing the loop.
"""

import os
import sys
from typing import List, Sequence

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import symbolicregression_jl_tpu as sr  # noqa: E402


def heuristic_proposer(pareto: Sequence, nvars: int) -> List[str]:
    """Stand-in for an LLM call: takes the current pareto front rows
    [(complexity, loss, equation_string)], returns new expression
    strings. A real deployment would format these into a prompt and
    POST to a chat API, then return the parsed reply lines."""
    props = []
    for _, _, eq in pareto[-2:]:
        # naive "creativity": perturb the best forms structurally
        props.append(f"({eq}) + 0.1 * x{nvars}")
        props.append(f"1.1 * ({eq})")
    return props or ["x1"]


def main(rounds: int = 3, niterations: int = 8, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2.0, 2.0, (300, 2)).astype(np.float32)
    y = 2.0 * np.cos(2.3 * X[:, 0]) - X[:, 1] ** 2

    options = sr.Options(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=16,
        populations=8,
        population_size=25,
        ncycles_per_iteration=80,
        save_to_file=False,
    )

    state = None
    guesses = None
    for r in range(rounds):
        state, hof = sr.equation_search(
            X, y,
            options=options,
            niterations=niterations,
            saved_state=state,
            guesses=guesses,
            return_state=True,
            seed=seed + r,
            verbosity=0,
        )
        front = [(e.complexity, e.loss, e.equation_string())
                 for e in hof.pareto_frontier()]
        best = min(e.loss for e in hof.pareto_frontier())
        print(f"round {r}: best loss {best:.4g}, front size {len(front)}")
        # the "LLM" sees the front and proposes the next seeds
        guesses = heuristic_proposer(front, nvars=2)

    print("final best:", min(e.loss for e in hof.pareto_frontier()))


if __name__ == "__main__":
    main()
