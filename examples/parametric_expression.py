"""Parametric expressions: one functional form, per-class constants.

Mirrors the reference's examples/parameterized_function.jl: every data
class shares the evolved structure, but each class fits its own
parameter values (here: a per-class amplitude on the cosine term). The
per-class parameter banks ride the fused eval kernel and are optimized
jointly with the expression constants.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import symbolicregression_jl_tpu as sr  # noqa: E402


def main(niterations: int = 12, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    n = 600
    X = rng.uniform(-2.0, 2.0, (n, 2)).astype(np.float32)
    category = rng.integers(0, 3, n)
    amp = np.array([1.0, 2.0, 3.0], np.float32)[category]
    y = amp * np.cos(X[:, 0]) + X[:, 1]

    model = sr.SRRegressor(
        niterations=niterations,
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        expression_spec=sr.ParametricExpressionSpec(max_parameters=1),
        populations=8,
        population_size=33,
        ncycles_per_iteration=80,
        maxsize=12,
        save_to_file=False,
    )
    model.fit(X, y, category=category)

    best = model.equations_[model.best_idx_]
    print("best parametric form:", best.equation)
    print("loss:", best.loss)
    # Per-class fitted parameter banks, shape (n_params, n_classes):
    # the amplitude parameter should recover ~[1, 2, 3] per class.
    print("fitted per-class parameters:")
    print(np.round(best.params, 3))


if __name__ == "__main__":
    main()
