"""Quickstart: fit a symbolic expression with the sklearn-style API.

Mirrors the reference's README quickstart (SRRegressor via MLJ).
On a TPU backend, ``device_scale="auto"`` (the default) picks the
chip-native search scale; this example pins a small scale so it runs
in seconds anywhere (CPU included).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import symbolicregression_jl_tpu as sr  # noqa: E402


def main(niterations: int = 10, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    X = rng.uniform(-3.0, 3.0, (500, 2)).astype(np.float32)
    y = 2.0 * np.cos(2.3 * X[:, 0]) - X[:, 1] ** 2

    model = sr.SRRegressor(
        niterations=niterations,
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        # Small, CPU-friendly scale; drop these three lines on a TPU
        # to get the device-native 512x256 configuration.
        populations=8,
        population_size=33,
        ncycles_per_iteration=100,
        maxsize=20,
        save_to_file=False,
    )
    model.fit(X, y)

    print("best:", model.equations_[model.best_idx_].equation)
    print("pareto front (complexity, loss, equation):")
    for row in model.equations_:
        print(f"  {row.complexity:3d}  {row.loss:10.4g}  {row.equation}")

    y_hat = model.predict(X)
    print("train MSE:", float(np.mean((y_hat - y) ** 2)))


if __name__ == "__main__":
    main()
