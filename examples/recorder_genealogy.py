"""Record the full mutation genealogy of a search.

Mirrors the reference's Recorder (src/Recorder.jl + JSON3 ext): with
``use_recorder=True`` every accepted mutation/crossover becomes an
event (kind, parents, child, the member that died, cost delta), and
``recorder_verbosity=2`` additionally records every rejected candidate
with its reason (constraint / invalid / annealing). The stream is
written as JSON at teardown — here we also reconstruct a lineage chain
from it.
"""

import json
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import symbolicregression_jl_tpu as sr  # noqa: E402


def main(niterations: int = 3, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2.0, 2.0, (200, 2)).astype(np.float32)
    y = np.cos(2.0 * X[:, 0]) + X[:, 1]

    rec_path = os.path.join(tempfile.mkdtemp(), "recorder.json")
    options = sr.Options(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=12,
        populations=4,
        population_size=16,
        ncycles_per_iteration=30,
        use_recorder=True,
        recorder_file=rec_path,
        save_to_file=False,
    )
    sr.equation_search(X, y, options=options, niterations=niterations,
                       seed=seed, verbosity=0)

    with open(rec_path) as f:
        record = json.load(f)
    events = [ev for it in record["iterations"]
              for ev in it["events"][0]["accepted"]]
    kinds = {}
    for ev in events:
        kinds[ev["type"]] = kinds.get(ev["type"], 0) + 1
    print(f"{len(events)} accepted events across "
          f"{len(record['iterations'])} iterations; by kind:")
    for k, c in sorted(kinds.items(), key=lambda kv: -kv[1]):
        print(f"  {k:24s} {c}")

    # walk one lineage: pick the last event and chase parents backwards
    # (a tiny run may accept nothing — print gracefully instead of
    # raising on events[-1])
    if events:
        by_child = {ev["child"]: ev for ev in events}
        ev = events[-1]
        chain = []
        while ev is not None and len(chain) < 10:
            chain.append(ev)
            ev = by_child.get(ev["parent"])
        print("lineage of the last child (most recent first):")
        for ev in chain:
            print(f"  {ev['type']:20s} parent={ev['parent']} "
                  f"child={ev['child']} d_cost={ev['cost_delta']:+.3g}"
                  if isinstance(ev['cost_delta'], float) else ev)
    else:
        print("no accepted events in this run (try more iterations "
              "or larger populations); skipping the lineage walk")


if __name__ == "__main__":
    main()
