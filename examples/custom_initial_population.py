"""Seed the search with a custom initial population.

Mirrors the fork's examples/custom_initial_population.jl: build
expressions yourself (domain knowledge, a previous run, or any
external generator), parse them, and hand them to ``equation_search``
via ``initial_population``. Seeds fill the initial islands (tiled if
fewer than islands × population_size); the search refines them.

``guesses=`` is the lighter-weight variant: guesses are evaluated,
optimized, and injected into the starting hall of fame.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import symbolicregression_jl_tpu as sr  # noqa: E402


def main(niterations: int = 6, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2.0, 2.0, (300, 2)).astype(np.float32)
    y = 1.8 * np.cos(2.0 * X[:, 0]) + 0.5 * X[:, 1]

    options = sr.Options(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=16,
        populations=6,
        population_size=25,
        ncycles_per_iteration=60,
        save_to_file=False,
    )

    # Hand-built starting points — e.g. near-miss forms from theory.
    seeds = [
        "1.0 * cos(x1) + x2",
        "cos(2.0 * x1)",
        "x1 + x2",
    ]

    hof = sr.equation_search(
        X, y,
        options=options,
        niterations=niterations,
        initial_population=seeds,
        guesses=["2.0 * cos(2.0 * x1) + 0.5 * x2"],
        seed=seed,
        verbosity=0,
    )
    for e in hof.pareto_frontier():
        print(f"  {e.complexity:3d}  {e.loss:10.4g}  {e.equation_string()}")


if __name__ == "__main__":
    main()
