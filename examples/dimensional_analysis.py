"""Dimensional analysis: search with SI units enforced.

Mirrors the reference's units feature (src/DimensionalAnalysis.jl):
X/y carry physical units; candidates whose dimensions cannot be made
consistent pay ``dimensional_constraint_penalty``, steering the search
toward physically meaningful laws. Here: Newtonian gravity
F = G*m1*m2/r^2 from noisy measurements.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import symbolicregression_jl_tpu as sr  # noqa: E402


def main(niterations: int = 16, seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    n = 400
    m1 = rng.uniform(1.0, 5.0, n).astype(np.float32)
    m2 = rng.uniform(1.0, 5.0, n).astype(np.float32)
    r = rng.uniform(0.5, 2.0, n).astype(np.float32)
    G = 6.674e-2  # rescaled for conditioning
    F = G * m1 * m2 / r**2

    X = np.stack([m1, m2, r], axis=1)
    model = sr.SRRegressor(
        niterations=niterations,
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["square"],
        populations=12,
        population_size=33,
        ncycles_per_iteration=80,
        maxsize=12,
        dimensional_constraint_penalty=1000.0,
        save_to_file=False,
    )
    model.fit(X, F, X_units=["kg", "kg", "m"], y_units="kg*m/s^2")

    best = model.equations_[model.best_idx_]
    print("best dimensionally-consistent law:", best.equation)
    print("loss:", best.loss)


if __name__ == "__main__":
    main()
