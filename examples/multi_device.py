"""Multi-device search: shard the island axis over a TPU mesh.

On real hardware nothing is required: when multiple devices are
visible, the engine shards islands automatically and the fused Pallas
path runs island-local inside shard_map (migration's pool all-gather
is the only cross-chip traffic — profiling/ici_model.py bounds it at
<0.2% of iteration time on a v5e-8). This example demonstrates the
same program on a virtual 8-device CPU mesh, the standard way to
validate sharding without chips.

Run:
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python examples/multi_device.py
"""

import os
import sys

# Append (don't setdefault): a pre-set XLA_FLAGS would otherwise swallow
# the flag and the example silently runs on 1 device. XLA takes the last
# occurrence of a repeated flag, so appending also wins over a
# conflicting pre-set device count.
_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", "").split():
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG
    ).strip()

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(niterations: int = 3, seed: int = 0) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")  # virtual mesh demo
    import symbolicregression_jl_tpu as sr
    from symbolicregression_jl_tpu.api.search import RuntimeOptions

    print(f"devices: {jax.devices()}")

    rng = np.random.default_rng(seed)
    X = rng.uniform(-2.0, 2.0, (256, 2)).astype(np.float32)
    y = np.cos(2.0 * X[:, 0]) + 0.5 * X[:, 1]

    options = sr.Options(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=12,
        populations=8,   # 1 island per virtual device
        population_size=16,
        ncycles_per_iteration=20,
        save_to_file=False,
    )
    hof = sr.equation_search(
        X, y,
        options=options,
        niterations=niterations,
        runtime_options=RuntimeOptions(
            niterations=niterations, verbosity=0, seed=seed,
            devices=jax.devices(),
        ),
    )
    for e in hof.pareto_frontier()[-3:]:
        print(f"  {e.complexity:3d}  {e.loss:10.4g}  {e.equation_string()}")


if __name__ == "__main__":
    main()
