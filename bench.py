"""Headline benchmark: full-dataset expression evaluations per second.

Mirrors the reference's primary live metric — "full dataset evaluations
per second" (Δnum_evals/Δt, /root/reference/src/SymbolicRegression.jl:1158-1171)
— on the reference benchmark problem (benchmarks.jl: 5 features, ops
{+,-,*,/} ∪ {exp,abs}, maxsize=30, target
cos(2.13x₁)+0.5x₂|x₃|^0.9−0.3|x₄|^1.5) scaled to the BASELINE.json
north-star 10k-row dataset.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

`vs_baseline` compares against the MEASURED CPU-multithreaded rate:
profiling/cpu_baseline.py measures a per-node-vectorized numpy
evaluator at 8.1e3 evals/s *per core* on this host
(transcendental-dominated, within a small factor of the reference's
fused LoopVectorization interpreter per core), i.e. ~6.5e4 evals/s for
an 8-core multithreaded host. Rounds 1-3 reported against a 1e4
round-1 estimate (a 1-2-core rate); that legacy ratio is demoted to
the `vs_baseline_legacy_1e4` field for cross-round continuity
(BENCH_r01-r03 used it).
"""

from __future__ import annotations

import json
import time

import numpy as np

MEASURED_CPU_EVALS_PER_SEC = 6.5e4   # 8-core extrapolation, BASELINE.md
LEGACY_CPU_EVALS_PER_SEC = 1.0e4     # round-1 estimate (1-2 cores)

N_ROWS = 10_000
N_FEATURES = 5
WARMUP_ITERS = 1
MEASURE_ITERS = 3


def _cpu_mesh_scaling_efficiency() -> "tuple[float, dict] | None":
    """Measured weak-scaling efficiency at the largest virtual-CPU-mesh
    point (profiling/weak_scaling_cpu.json, produced by
    profiling/weak_scaling.py on the 8-device host mesh), as
    rate_per_device(N) / rate_per_device(1).

    The file's config is validated (a real sweep, not an exploratory
    tiny run) and echoed in the bench record so the projection's
    provenance is visible."""
    import json as _json
    import os as _os

    path = _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                         "profiling", "weak_scaling_cpu.json")
    if not _os.path.exists(path):
        return None
    with open(path) as f:
        payload = _json.load(f)
    pts = payload.get("points", [])
    cfg = {
        "islands_per_device": payload.get("islands_per_device"),
        "population_size": payload.get("population_size"),
        "ncycles": payload.get("ncycles"),
        "max_devices": max((p["devices"] for p in pts), default=0),
    }
    # Guard against projecting from a noise-dominated exploratory run.
    if (len(pts) < 2 or cfg["max_devices"] < 8
            or (cfg["islands_per_device"] or 0) < 32
            or (cfg["population_size"] or 0) < 64):
        return None
    base = pts[0]["evals_per_sec_per_device"]
    last = max(pts, key=lambda p: p["devices"])
    if not base:
        return None
    return last["evals_per_sec_per_device"] / base, cfg


def main() -> None:
    import jax

    from symbolicregression_jl_tpu import Options, search_key
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine

    rng = np.random.default_rng(0)
    X = rng.uniform(-3.0, 3.0, (N_ROWS, N_FEATURES)).astype(np.float32)
    y = (
        np.cos(2.13 * X[:, 0])
        + 0.5 * X[:, 1] * np.abs(X[:, 2]) ** 0.9
        - 0.3 * np.abs(X[:, 3]) ** 1.5
        + 1e-1 * rng.standard_normal(N_ROWS)
    ).astype(np.float32)

    # Island count is the TPU-native scaling axis (SURVEY.md §2.4): more
    # islands amortize the per-cycle machinery over more concurrent
    # evaluations in the same launches (profiling/config_sweep.py picks
    # the per-chip config); with multiple devices visible the island
    # axis shards over them — the multi-chip number is one
    # `python bench.py` away, with 512 LOCAL islands per chip.
    n_dev = len(jax.devices())
    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=30,
        populations=512 * n_dev,  # island count peaks at 512 on v5e-1
        population_size=256,  # (profiling/config_sweep.py, round 3)
        tournament_selection_n=16,
        ncycles_per_iteration=100,
        save_to_file=False,
    )
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)

    mesh = None
    if n_dev > 1:
        from symbolicregression_jl_tpu.parallel.mesh import (
            make_mesh, shard_device_data, shard_search_state)

        mesh = make_mesh(jax.devices(), n_island_shards=n_dev)
        engine = Engine(options, ds.nfeatures, n_island_shards=n_dev,
                        mesh=mesh)
        data = shard_device_data(ds.data, mesh)
    else:
        engine = Engine(options, ds.nfeatures)
        data = ds.data

    state = engine.init_state(
        search_key(0), data, options.populations
    )
    if mesh is not None:
        state = shard_search_state(state, mesh)

    # Warmup (compile) iterations, excluded from timing.
    for _ in range(WARMUP_ITERS):
        state = engine.run_iteration(state, data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    evals_before = float(state.num_evals)

    t0 = time.perf_counter()
    for _ in range(MEASURE_ITERS):
        state = engine.run_iteration(state, data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    elapsed = time.perf_counter() - t0

    evals = float(state.num_evals) - evals_before
    rate = evals / elapsed
    rec = {
        "metric": "full_dataset_expr_evals_per_sec_10k_rows",
        "value": round(rate, 1),
        "unit": "evals/s",
        "vs_baseline": round(rate / MEASURED_CPU_EVALS_PER_SEC, 3),
        "vs_baseline_legacy_1e4": round(
            rate / LEGACY_CPU_EVALS_PER_SEC, 3),
        "n_devices": n_dev,
    }
    if n_dev == 1:
        # Projected v5e-8: measured single-chip rate x 8 devices x the
        # MEASURED virtual-CPU-mesh weak-scaling efficiency (islands are
        # data-independent; the only ICI traffic is the migration pool
        # all-gather + HoF merge, < 0.2% of iteration time even at the
        # partitioner's worst-case bound — profiling/ici_model.py).
        scaling = _cpu_mesh_scaling_efficiency()
        if scaling is not None:
            eff, scfg = scaling
            proj = rate * 8 * min(eff, 1.0)
            rec["projected_v5e8"] = round(proj, 1)
            rec["projected_v5e8_vs_baseline"] = round(
                proj / MEASURED_CPU_EVALS_PER_SEC, 2)
            rec["projection_scaling_efficiency"] = round(min(eff, 1.0), 4)
            rec["projection_scaling_source"] = scfg
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
