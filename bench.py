"""Headline benchmark: full-dataset expression evaluations per second.

Thin wrapper over :mod:`symbolicregression_jl_tpu.bench.headline` (the
graftbench subsystem, docs/BENCHMARKING.md) kept at the repo root for
the driver's round artifact (``python bench.py`` -> BENCH_r0N.json).
Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} —
the contract ``python -m symbolicregression_jl_tpu.bench trend`` parses
back out of the committed history.

The full benchmark matrix, regression gate, serve load benchmark, and
trajectory report live in the subsystem CLI::

    python -m symbolicregression_jl_tpu.bench run|gate|load|trend
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from symbolicregression_jl_tpu.bench.headline import main  # noqa: E402

if __name__ == "__main__":
    main()
