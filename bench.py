"""Headline benchmark: full-dataset expression evaluations per second.

Mirrors the reference's primary live metric — "full dataset evaluations
per second" (Δnum_evals/Δt, /root/reference/src/SymbolicRegression.jl:1158-1171)
— on the reference benchmark problem (benchmarks.jl: 5 features, ops
{+,-,*,/} ∪ {exp,abs}, maxsize=30, target
cos(2.13x₁)+0.5x₂|x₃|^0.9−0.3|x₄|^1.5) scaled to the BASELINE.json
north-star 10k-row dataset.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

`vs_baseline` compares against the MEASURED CPU-multithreaded rate:
profiling/cpu_baseline.py measures a per-node-vectorized numpy
evaluator at 8.1e3 evals/s *per core* on this host
(transcendental-dominated, within a small factor of the reference's
fused LoopVectorization interpreter per core), i.e. ~6.5e4 evals/s for
an 8-core multithreaded host. Rounds 1-3 reported against a 1e4
round-1 estimate (a 1-2-core rate); that legacy ratio is demoted to
the `vs_baseline_legacy_1e4` field for cross-round continuity
(BENCH_r01-r03 used it).
"""

from __future__ import annotations

import json
import time

import numpy as np

MEASURED_CPU_EVALS_PER_SEC = 6.5e4   # 8-core extrapolation, BASELINE.md
LEGACY_CPU_EVALS_PER_SEC = 1.0e4     # round-1 estimate (1-2 cores)

N_ROWS = 10_000
N_FEATURES = 5
WARMUP_ITERS = 1
MEASURE_ITERS = 3


def main() -> None:
    import jax

    from symbolicregression_jl_tpu import Options, search_key
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine

    rng = np.random.default_rng(0)
    X = rng.uniform(-3.0, 3.0, (N_ROWS, N_FEATURES)).astype(np.float32)
    y = (
        np.cos(2.13 * X[:, 0])
        + 0.5 * X[:, 1] * np.abs(X[:, 2]) ** 0.9
        - 0.3 * np.abs(X[:, 3]) ** 1.5
        + 1e-1 * rng.standard_normal(N_ROWS)
    ).astype(np.float32)

    # Island count is the TPU-native scaling axis (SURVEY.md §2.4): more
    # islands amortize the per-cycle machinery over more concurrent
    # evaluations in the same launches (profiling/config_sweep.py picks
    # the config).
    options = Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["exp", "abs", "cos"],
        maxsize=30,
        populations=512,   # island count peaks at 512 on v5e-1
        population_size=256,  # (profiling/config_sweep.py, round 3)
        tournament_selection_n=16,
        ncycles_per_iteration=100,
        save_to_file=False,
    )
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    engine = Engine(options, ds.nfeatures)

    state = engine.init_state(
        search_key(0), ds.data, options.populations
    )

    # Warmup (compile) iterations, excluded from timing.
    for _ in range(WARMUP_ITERS):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    evals_before = float(state.num_evals)

    t0 = time.perf_counter()
    for _ in range(MEASURE_ITERS):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    elapsed = time.perf_counter() - t0

    evals = float(state.num_evals) - evals_before
    rate = evals / elapsed
    print(
        json.dumps(
            {
                "metric": "full_dataset_expr_evals_per_sec_10k_rows",
                "value": round(rate, 1),
                "unit": "evals/s",
                "vs_baseline": round(rate / MEASURED_CPU_EVALS_PER_SEC, 3),
                "vs_baseline_legacy_1e4": round(
                    rate / LEGACY_CPU_EVALS_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
