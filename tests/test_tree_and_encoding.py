"""Host tree parse/print/simplify + postfix encode/decode round trips."""

import numpy as np
import pytest

from symbolicregression_jl_tpu.ops.encoding import (
    TreeBatch,
    decode_tree,
    encode_population,
    encode_tree,
    tree_structure_arrays,
)
from symbolicregression_jl_tpu.ops.operators import OperatorSet
from symbolicregression_jl_tpu.ops.tree import (
    Node,
    combine_operators,
    parse_expression,
    simplify_tree,
    string_tree,
)

OPS = OperatorSet(binary_operators=["+", "-", "*", "/", "^"],
                  unary_operators=["sin", "cos", "exp", "log"])


class TestParsePrint:
    @pytest.mark.parametrize(
        "expr",
        [
            "x1 + x2",
            "x1 * x2 + 3.0",
            "sin(x1)",
            "(x1 + x2) * x3",
            "x1 - x2 - x3",
            "x1 / (x2 + 1.5)",
            "sin(cos(x1 + 2.0)) * 3.5",
            "x1 ^ 2.0",
            "exp(log(x1))",
        ],
    )
    def test_roundtrip(self, expr):
        t = parse_expression(expr, OPS)
        s = string_tree(t)
        t2 = parse_expression(s, OPS)
        assert t == t2, (expr, s)

    def test_unary_minus(self):
        t = parse_expression("-x1 + 2.0", OPS)
        assert t.count_nodes() == 4  # neg(x1) + 2.0

    def test_negative_constant(self):
        t = parse_expression("-2.5 * x1", OPS)
        consts = t.get_scalar_constants()
        assert consts == [-2.5]

    def test_variable_names(self):
        t = parse_expression("alpha + beta", OPS, variable_names=["alpha", "beta"])
        assert string_tree(t, ["alpha", "beta"]) == "alpha + beta"
        assert t.children[0].feature == 0
        assert t.children[1].feature == 1

    def test_precedence_printing(self):
        t = parse_expression("(x1 + x2) * x3", OPS)
        assert string_tree(t) == "(x1 + x2) * x3"
        t = parse_expression("x1 + x2 * x3", OPS)
        assert string_tree(t) == "x1 + x2 * x3"

    def test_eval_scalar(self):
        t = parse_expression("sin(x1) + x2 * 2.0", OPS)
        got = t.eval_scalar([0.5, 3.0])
        assert got == pytest.approx(np.sin(0.5) + 6.0)

    def test_scalar_constants_api(self):
        t = parse_expression("x1 * 2.0 + 3.0", OPS)
        assert t.get_scalar_constants() == [2.0, 3.0]
        t.set_scalar_constants([5.0, 7.0])
        assert string_tree(t) == "x1 * 5.0 + 7.0"


class TestSimplify:
    def test_constant_folding(self):
        t = parse_expression("x1 + (2.0 + 3.0)", OPS)
        s = simplify_tree(t, OPS)
        assert string_tree(s) == "x1 + 5.0"

    def test_fold_nested(self):
        t = parse_expression("sin(2.0 * 3.0) + x1", OPS)
        s = simplify_tree(t, OPS)
        assert s.children[0].constant
        assert s.children[0].val == pytest.approx(np.sin(6.0))

    def test_combine_operators(self):
        t = parse_expression("(x1 + 1.5) + 2.5", OPS)
        c = combine_operators(simplify_tree(t))
        assert string_tree(c) == "x1 + 4.0"

    def test_combine_mult(self):
        t = parse_expression("(x1 * 2.0) * 3.0", OPS)
        c = combine_operators(simplify_tree(t))
        assert string_tree(c) == "x1 * 6.0"

    def test_combine_sub(self):
        t = parse_expression("(x1 - 1.0) - 2.0", OPS)
        c = combine_operators(simplify_tree(t))
        assert string_tree(c) == "x1 - 3.0"


class TestEncoding:
    @pytest.mark.parametrize(
        "expr",
        [
            "1.5",
            "x3",
            "x1 + x2",
            "sin(x1) * (x2 - 0.5)",
            "exp(x1 / x2) + cos(x3 ^ 2.0)",
            "((x1 + x2) * (x3 + x4)) / (x5 - 1.0)",
        ],
    )
    def test_roundtrip(self, expr):
        t = parse_expression(expr, OPS)
        enc = encode_tree(t, 31, OPS)
        t2 = decode_tree(*enc, OPS)
        assert t == t2

    def test_length(self):
        t = parse_expression("sin(x1) + 2.0", OPS)
        arity, op, feat, const, length = encode_tree(t, 31, OPS)
        assert int(length) == 4
        # postfix: x1, sin, 2.0, +
        assert list(arity[:4]) == [0, 1, 0, 2]

    def test_too_large_raises(self):
        t = parse_expression("x1 + x2 + x3 + x4", OPS)
        with pytest.raises(ValueError):
            encode_tree(t, 4, OPS)

    def test_structure_arrays(self):
        t = parse_expression("sin(x1) + (x2 * 3.0)", OPS)
        batch = encode_population([t], 16, OPS)
        child, size, depth = tree_structure_arrays(batch)
        child, size, depth = np.asarray(child)[0], np.asarray(size)[0], np.asarray(depth)[0]
        n = int(batch.length[0])
        assert n == 6  # x1 sin x2 3.0 * +
        # root is slot 5: children sin@1 and *@4
        assert list(child[5][:2]) == [1, 4]
        assert size[5] == 6
        assert size[1] == 2  # sin(x1)
        assert size[4] == 3  # x2*3
        assert depth[5] == 3

    def test_population_roundtrip(self):
        from symbolicregression_jl_tpu.ops.encoding import decode_population

        exprs = ["x1 + 1.0", "sin(x2)", "x1 * x2 / x3"]
        trees = [parse_expression(e, OPS) for e in exprs]
        batch = encode_population(trees, 16, OPS)
        back = decode_population(batch, OPS)
        assert all(a == b for a, b in zip(trees, back))


class TestDeviceFold:
    """Batched device-side constant folding (evolve.simplify) — the
    whole-population analogue of simplify_tree! (SingleIteration.jl:79-85).
    Pinned directly: a span/cover off-by-one would corrupt trees while
    engine-level tests still pass statistically."""

    def _pop(self, n=256, seed=0):
        import jax
        import jax.numpy as jnp

        from symbolicregression_jl_tpu.evolve.mutation import MutationContext
        from symbolicregression_jl_tpu.evolve.population import init_population

        ops = OperatorSet(binary_operators=["+", "-", "*", "/"],
                          unary_operators=["exp", "cos"])
        mctx = MutationContext(
            nops=ops.nops_tuple(), nfeatures=3, max_nodes=21,
            perturbation_factor=0.076, probability_negate_constant=0.01)
        trees = init_population(
            jax.random.PRNGKey(seed), n, mctx, jnp.float32)
        return ops, trees

    @pytest.mark.slow
    def test_fold_eval_equivalence_and_idempotence(self):
        import jax
        import jax.numpy as jnp

        from symbolicregression_jl_tpu.evolve.simplify import (
            fold_constants_batch)
        from symbolicregression_jl_tpu.ops.eval import eval_tree_batch

        ops, trees = self._pop()
        rng = np.random.default_rng(0)
        X = jnp.asarray(rng.uniform(-2, 2, (3, 64)).astype(np.float32))
        folded = fold_constants_batch(trees, ops)
        y0, v0 = eval_tree_batch(trees, X, ops)
        y1, v1 = eval_tree_batch(folded, X, ops)
        a, b = np.asarray(y0), np.asarray(y1)
        va, vb = np.asarray(v0), np.asarray(v1)
        # folding never grows trees, and lengths stay positive
        assert np.all(np.asarray(folded.length) >= 1)
        assert np.all(np.asarray(folded.length) <= np.asarray(trees.length))
        both = va & vb
        assert np.allclose(a[both], b[both], rtol=1e-5, atol=1e-5)
        # a fold can only change validity via rounding at the folded
        # constant; on this population none should flip
        assert (va == vb).mean() > 0.99
        # idempotence: folding a folded population is a no-op
        again = fold_constants_batch(folded, ops)
        for f in ("arity", "op", "feat", "length"):
            assert np.array_equal(
                np.asarray(getattr(again, f)), np.asarray(getattr(folded, f))
            ), f
        assert np.allclose(np.asarray(again.const), np.asarray(folded.const),
                           equal_nan=True)

    def test_fold_collapses_known_shapes(self):
        import jax.numpy as jnp

        from symbolicregression_jl_tpu.evolve.simplify import (
            fold_constants_batch)
        from symbolicregression_jl_tpu.ops.encoding import (
            decode_tree, encode_tree)

        ops = OperatorSet(binary_operators=["+", "-", "*", "/"],
                          unary_operators=["exp", "cos"])
        t = parse_expression("x1 + (2.0 + 3.0)", ops)
        enc = encode_tree(t, 15, ops)
        batch = TreeBatch(*[jnp.asarray(f)[None] for f in enc])
        folded = fold_constants_batch(batch, ops)
        out = decode_tree(
            *[np.asarray(getattr(folded, f))[0]
              for f in ("arity", "op", "feat", "const", "length")], ops)
        assert string_tree(out) == "x1 + 5.0"
