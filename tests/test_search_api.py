"""End-to-end tests of equation_search and the driver entry points.

Mirrors the reference's evaluation-group tests (test_evaluation.jl,
test_early_stop.jl, test_migration.jl — SURVEY.md §4): a short search on
an easy analytic target must drive loss well below the baseline, early
stopping must trigger, and the multi-chip dry run must compile and run
on the virtual 8-device CPU mesh.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from symbolicregression_jl_tpu import Options
from symbolicregression_jl_tpu.api.hall_of_fame import (
    HallOfFameEntry,
    calculate_pareto_frontier,
    compute_scores,
    load_hall_of_fame_csv,
    save_hall_of_fame_csv,
    HallOfFame,
)
from symbolicregression_jl_tpu.api.search import equation_search, get_cur_maxsize
from symbolicregression_jl_tpu.ops.tree import Node, parse_expression, string_tree


def small_options(**kw):
    defaults = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=12,
        populations=4,
        population_size=16,
        ncycles_per_iteration=20,
        tournament_selection_n=6,
        save_to_file=False,
    )
    defaults.update(kw)
    return Options(**defaults)


@pytest.fixture(scope="module")
def linear_problem():
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (128, 2)).astype(np.float32)
    y = 2.0 * X[:, 0] + X[:, 1]
    return X, y


@pytest.mark.slow
def test_search_improves_over_baseline(linear_problem):
    X, y = linear_problem
    hof = equation_search(
        X, y, options=small_options(), niterations=3, seed=1, verbosity=0
    )
    frontier = hof.pareto_frontier()
    assert len(frontier) >= 1
    best = min(e.loss for e in frontier)
    baseline = float(np.var(y))
    assert best < 0.5 * baseline  # must strongly beat the constant predictor


def test_search_early_stop(linear_problem):
    X, y = linear_problem
    # Huge threshold: any first iteration already satisfies it.
    hof = equation_search(
        X, y,
        options=small_options(early_stop_condition=1e6),
        niterations=50, seed=2, verbosity=0,
    )
    assert len(hof.entries) >= 1


@pytest.mark.slow
def test_search_return_state_and_warm_start(linear_problem):
    X, y = linear_problem
    opts = small_options()
    state, hof = equation_search(
        X, y, options=opts, niterations=2, seed=3, verbosity=0,
        return_state=True,
    )
    best1 = min(e.loss for e in hof.entries)
    state2, hof2 = equation_search(
        X, y, options=opts, niterations=2, seed=4, verbosity=0,
        saved_state=state, return_state=True,
    )
    best2 = min(e.loss for e in hof2.entries)
    assert best2 <= best1 + 1e-6  # warm start can only improve the HoF


def test_warm_start_with_guesses(linear_problem):
    """Resuming from a host SearchState AND seeding guesses in the same
    call must work: the saved state's population arrays are numpy
    (device_get'ed), and guess seeding does indexed updates on them —
    regression test for the .at[] on numpy crash (the fork's
    LLM-in-the-loop propose->seed->refine pattern does exactly this
    every round)."""
    X, y = linear_problem
    opts = small_options()
    state, hof = equation_search(
        X, y, options=opts, niterations=1, seed=3, verbosity=0,
        return_state=True,
    )
    best1 = min(e.loss for e in hof.entries)
    _, hof2 = equation_search(
        X, y, options=opts, niterations=1, seed=4, verbosity=0,
        saved_state=state, guesses=["2.0 * x1 + x2"], return_state=True,
    )
    best2 = min(e.loss for e in hof2.entries)
    assert best2 <= best1 + 1e-6


def test_oversized_guess_skipped_with_warning(linear_problem):
    """A guess longer than maxsize must not abort the search — it is
    skipped with a warning (reference precedent: invalid seed
    populations fall back to random with a warning)."""
    X, y = linear_problem
    big = " + ".join(["x1 * x2"] * 8)  # far beyond maxsize=12
    with pytest.warns(UserWarning, match="skipping"):
        hof = equation_search(
            X, y, options=small_options(), niterations=1, seed=6,
            verbosity=0, guesses=[big, "2.0 * x1 + x2"],
        )
    assert len(hof.entries) > 0


def test_warm_start_rejects_incompatible_options(linear_problem):
    X, y = linear_problem
    state, _ = equation_search(
        X, y, options=small_options(), niterations=1, seed=5, verbosity=0,
        return_state=True,
    )
    with pytest.raises(ValueError, match="maxsize"):
        equation_search(
            X, y, options=small_options(maxsize=20), niterations=1,
            verbosity=0, saved_state=state,
        )


def test_multioutput_search(linear_problem):
    X, _ = linear_problem
    Y = np.stack([X[:, 0] * 2.0, X[:, 1] - 1.0])
    hofs = equation_search(
        X, Y, options=small_options(), niterations=2, seed=6, verbosity=0
    )
    assert isinstance(hofs, list) and len(hofs) == 2
    for h in hofs:
        assert len(h.entries) >= 1


def test_guess_seeding_injects_solution(linear_problem):
    X, y = linear_problem
    opts = small_options()
    hof = equation_search(
        X, y, options=opts, niterations=1, seed=7, verbosity=0,
        guesses=["2.0 * x1 + x2"],
    )
    best = min(e.loss for e in hof.entries)
    assert best < 1e-6  # exact solution seeded


def test_initial_population(linear_problem):
    X, y = linear_problem
    hof = equation_search(
        X, y, options=small_options(), niterations=1, seed=8, verbosity=0,
        initial_population=["x1 + x2", "x1 * x2", "cos(x1)"],
    )
    assert len(hof.entries) >= 1


# ---------------------------------------------------------------------------
# Hall of fame host logic
# ---------------------------------------------------------------------------


def _entry(c, loss):
    return HallOfFameEntry(tree=Node.const(1.0), loss=loss, cost=loss, complexity=c)


def test_pareto_frontier_dominance():
    entries = [_entry(1, 1.0), _entry(2, 2.0), _entry(3, 0.5), _entry(4, 0.4)]
    frontier = calculate_pareto_frontier(entries)
    assert [e.complexity for e in frontier] == [1, 3, 4]


def test_scores_log_scale():
    frontier = [_entry(1, 1.0), _entry(3, np.exp(-2.0))]
    scored = compute_scores(frontier, "log")
    assert scored[0].score == 0.0
    assert scored[1].score == pytest.approx(1.0)  # -(-2 - 0)/2


def test_hof_csv_roundtrip(tmp_path):
    opts = small_options()
    e1 = HallOfFameEntry(
        tree=parse_expression("2.0 * x1 + cos(x2)", opts.operators),
        loss=0.5, cost=0.5, complexity=6,
    )
    hof = HallOfFame(entries=[e1])
    path = str(tmp_path / "hall_of_fame.csv")
    save_hall_of_fame_csv(path, hof, opts.operators)
    trees = load_hall_of_fame_csv(path, opts.operators)
    assert len(trees) == 1
    assert string_tree(trees[0]) == string_tree(e1.tree)


def test_cur_maxsize_warmup():
    # ramp 3 -> maxsize over first half of cycles
    assert get_cur_maxsize(20, 0.5, 100, 100) == 3
    assert get_cur_maxsize(20, 0.5, 100, 50) == 20
    assert get_cur_maxsize(20, 0.5, 100, 75) == 11
    assert get_cur_maxsize(20, 0.0, 100, 100) == 20


# ---------------------------------------------------------------------------
# Driver entry points on the virtual multi-device CPU mesh
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_dryrun_multichip_8_devices():
    import jax

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import __graft_entry__ as ge

    # Demand the full 8-device mesh: in-process when the conftest's
    # virtual CPU mesh is live, else via the dryrun's own subprocess
    # self-provisioning.
    assert len(jax.devices()) == 8, "conftest virtual mesh not engaged"
    ge.dryrun_multichip(8)


def test_entry_compiles():
    import jax

    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (256,)
    assert bool(np.isfinite(np.asarray(out)).any())


def test_warmup_precompiles_quietly(capsys, tmp_path, monkeypatch):
    """sr.warmup runs a tiny silent search to populate the compile
    cache for a (config, shape) pair; it must not print, return, or
    write anything to the working directory."""
    import symbolicregression_jl_tpu as sr

    monkeypatch.chdir(tmp_path)
    # save_to_file=True (the Options default) must be overridden on a
    # copy inside warmup — a pre-compile must never write equations
    # fit to random noise into outputs/.
    opts = small_options(ncycles_per_iteration=4, save_to_file=True)
    out = sr.warmup(opts, nfeatures=2, n_rows=64, niterations=1)
    assert out is None
    assert opts.save_to_file is True  # caller's Options untouched
    captured = capsys.readouterr()
    assert captured.out == ""
    assert captured.err == ""
    assert list(tmp_path.iterdir()) == []


def test_multihost_helpers_single_host():
    """initialize_multihost is an idempotent no-op on a single host
    (the SPMD design needs no worker bring-up — SURVEY.md §5.8)."""
    from symbolicregression_jl_tpu.parallel import (
        initialize_multihost,
        is_multihost,
        process_index,
    )

    initialize_multihost()  # no cluster env: returns quietly
    assert not is_multihost()
    assert process_index() == 0
