"""ParametricExpression support: per-class parameter banks.

Mirrors the reference's parametric tests (test/unit/… parametric cases and
test/integration/ext/mlj/parametric_search): eval with class-gathered
parameters, search recovering per-class offsets, regressor round trip.
Reference behavior: /root/reference/src/ParametricExpression.jl.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu import Options
from symbolicregression_jl_tpu.models import ParametricExpressionSpec
from symbolicregression_jl_tpu.ops.encoding import encode_population
from symbolicregression_jl_tpu.ops.eval import eval_tree_batch
from symbolicregression_jl_tpu.ops.operators import OperatorSet
from symbolicregression_jl_tpu.ops.tree import Node, parse_expression, string_tree


@pytest.fixture(scope="module")
def ops():
    return OperatorSet(binary_operators=["+", "*"], unary_operators=["cos"])


def test_parameter_leaf_eval(ops):
    # p1 + x1 * p2 over 3 classes
    tree = parse_expression("p1 + (x1 * p2)", ops)
    enc = encode_population([tree], 8, ops)
    n = 6
    X = np.linspace(-1, 1, n).astype(np.float32)[None, :]  # [F=1, n]
    cls = np.array([0, 1, 2, 0, 1, 2])
    params = np.array([[0.5, -1.0, 2.0], [1.0, 2.0, 3.0]], np.float32)  # [K=2, C=3]
    p_rows = jnp.asarray(params[:, cls])[None]  # [1, K, n]
    y, valid = eval_tree_batch(enc, jnp.asarray(X), ops, params=p_rows)
    expected = params[0, cls] + X[0] * params[1, cls]
    np.testing.assert_allclose(np.asarray(y[0]), expected, rtol=1e-6)
    assert bool(valid[0])


def test_parameter_leaf_without_params_is_invalid(ops):
    tree = parse_expression("p1 + x1", ops)
    enc = encode_population([tree], 8, ops)
    X = jnp.ones((1, 4), jnp.float32)
    y, valid = eval_tree_batch(enc, X, ops)
    assert not bool(valid[0])


def test_parameter_string_and_parse_roundtrip(ops):
    tree = Node(op=ops.binary[0], children=[Node.param(0), Node.var(1)])
    s = string_tree(tree)
    assert "p1" in s
    back = parse_expression(s, ops)
    assert back == tree


@pytest.mark.slow
def test_parametric_search_recovers_per_class_offsets():
    rng = np.random.default_rng(0)
    n = 128
    X = rng.uniform(-2, 2, (n, 2)).astype(np.float32)
    cls = rng.integers(0, 3, n)
    offsets = np.array([0.5, -1.0, 2.0])
    y = (X[:, 0] * 1.5 + offsets[cls]).astype(np.float32)

    from symbolicregression_jl_tpu.api.search import equation_search

    opts = Options(
        binary_operators=["+", "*"], unary_operators=[],
        maxsize=8, populations=2, population_size=12,
        ncycles_per_iteration=10, tournament_selection_n=4,
        expression_spec=ParametricExpressionSpec(max_parameters=1),
        optimizer_probability=0.5, optimizer_iterations=4,
        save_to_file=False,
    )
    hof = equation_search(
        X, y, options=opts, niterations=12, verbosity=0, seed=0,
        extra={"class": cls},
    )
    best = min(hof.entries, key=lambda e: e.loss)
    assert best.loss < 0.05
    assert best.params is not None and best.params.shape == (1, 3)


def test_parametric_search_requires_class_column():
    from symbolicregression_jl_tpu.api.search import equation_search

    opts = Options(
        binary_operators=["+"], unary_operators=[], maxsize=8,
        populations=2, population_size=8, ncycles_per_iteration=2,
        tournament_selection_n=4,
        expression_spec=ParametricExpressionSpec(max_parameters=1),
        save_to_file=False,
    )
    X = np.ones((8, 1), np.float32)
    y = np.ones((8,), np.float32)
    with pytest.raises(ValueError, match="class"):
        equation_search(X, y, options=opts, niterations=1, verbosity=0)


@pytest.mark.slow
def test_parametric_regressor_fit_predict():
    from symbolicregression_jl_tpu.api.regressor import SRRegressor

    rng = np.random.default_rng(1)
    n = 96
    X = rng.uniform(-2, 2, (n, 1)).astype(np.float32)
    cls = rng.integers(0, 2, n)
    offsets = np.array([1.0, -2.0])
    y = (2.0 * X[:, 0] + offsets[cls]).astype(np.float32)

    model = SRRegressor(
        niterations=4,
        binary_operators=["+", "*"], unary_operators=[],
        maxsize=8, populations=2, population_size=12,
        ncycles_per_iteration=10, tournament_selection_n=4,
        expression_spec=ParametricExpressionSpec(max_parameters=1),
        optimizer_probability=0.5, optimizer_iterations=4,
        save_to_file=False, seed=0,
    )
    model.fit(X, y, category=cls)
    pred = model.predict(X, category=cls)
    assert np.mean((pred - y) ** 2) < 0.1
    # predict without category must fail when best equation is parametric
    if model.get_best().params is not None:
        with pytest.raises(ValueError, match="category"):
            model.predict(X)


def test_mutation_context_samples_parameter_leaves():
    from symbolicregression_jl_tpu.evolve.mutation import (
        MutationContext, gen_random_tree_fixed_size,
    )
    from symbolicregression_jl_tpu.ops.encoding import LEAF_PARAM

    ctx = MutationContext(
        nops=(1, 2), nfeatures=2, max_nodes=16,
        perturbation_factor=0.1, probability_negate_constant=0.01,
        n_params=2,
    )
    found_param = False
    for s in range(20):
        t = gen_random_tree_fixed_size(jax.random.PRNGKey(s), 9, ctx, jnp.float32)
        arity = np.asarray(t.arity)
        op = np.asarray(t.op)
        ln = int(t.length)
        leaf_param = (arity[:ln] == 0) & (op[:ln] == LEAF_PARAM)
        if leaf_param.any():
            found_param = True
            # parameter indices within range
            feat = np.asarray(t.feat)[:ln][leaf_param]
            assert (feat >= 0).all() and (feat < 2).all()
            break
    assert found_param


def test_parameter_row_mutation():
    from symbolicregression_jl_tpu.evolve.mutation import (
        MutationContext, mutate_parameter_row,
    )

    ctx = MutationContext(
        nops=(1, 2), nfeatures=2, max_nodes=16,
        perturbation_factor=0.5, probability_negate_constant=0.0,
        n_params=3,
    )
    params = jnp.ones((3, 4), jnp.float32)
    out = mutate_parameter_row(
        jax.random.uniform(jax.random.PRNGKey(0), (4,)), params,
        jnp.float32(1.0), ctx
    )
    out = np.asarray(out)
    changed_rows = np.unique(np.where(out != 1.0)[0])
    assert changed_rows.shape[0] == 1  # exactly one row scaled
    row = out[changed_rows[0]]
    assert np.allclose(row, row[0])  # whole row scaled by one factor


def test_hof_csv_params_roundtrip_seeds_guesses(tmp_path, ops):
    """Fitted parameter banks survive the CSV round trip: saved in the
    Parameters column, loaded with return_params=True, and injected via
    guesses=(expr, params) instead of randn reseeding."""
    import jax

    from symbolicregression_jl_tpu import Options, equation_search
    from symbolicregression_jl_tpu.api.hall_of_fame import (
        HallOfFame,
        HallOfFameEntry,
        load_hall_of_fame_csv,
        save_hall_of_fame_csv,
    )
    from symbolicregression_jl_tpu.api.search import RuntimeOptions
    from symbolicregression_jl_tpu.models import ParametricExpressionSpec

    tree = parse_expression("p1 + (x1 * p2)", ops)
    bank = np.asarray([[0.5, -1.0], [2.0, 3.0]], np.float32)  # [K=2, C=2]
    hof = HallOfFame(entries=[
        HallOfFameEntry(tree=tree, loss=0.1, cost=0.1, complexity=5,
                        params=bank),
    ])
    path = str(tmp_path / "hof.csv")
    save_hall_of_fame_csv(path, hof, ops)
    trees, params = load_hall_of_fame_csv(path, ops, return_params=True)
    assert len(trees) == 1 and params[0] is not None
    np.testing.assert_allclose(params[0].reshape(2, 2), bank)

    # Seed a parametric search with the loaded (tree, params) pair and
    # check the bank lands in the population verbatim.
    rng = np.random.default_rng(0)
    X = rng.uniform(-1, 1, (50, 1)).astype(np.float32)
    cls = np.tile(np.array([0, 1]), 25)
    y = (bank[0, cls] + X[:, 0] * bank[1, cls]).astype(np.float32)
    options = Options(
        binary_operators=["+", "*"], unary_operators=[], maxsize=8,
        populations=2, population_size=10, tournament_selection_n=4,
        ncycles_per_iteration=2, save_to_file=False,
        expression_spec=ParametricExpressionSpec(max_parameters=2),
    )
    # niterations=0: inspect the seeded state before evolution moves it.
    state, _ = equation_search(
        X, y, options=options, extra={"class": cls},
        guesses=list(zip(trees, params)),
        runtime_options=RuntimeOptions(niterations=0, seed=0, verbosity=0,
                                       return_state=True),
    )
    pops_params = np.asarray(state.device_states[0].pops.params)
    flat = pops_params.reshape(-1, 4)
    assert any(
        np.allclose(row, bank.reshape(-1), atol=1e-5) for row in flat
    ), "seeded parameter bank not found in the population"


def test_fused_parametric_loss_matches_interpreter(ops):
    """Turbo parametric eval: LEAF_PARAM leaves read the fused kernel's
    parameter buffer region (class one-hot contraction) — must agree
    with the class-gathered jnp interpreter."""
    from symbolicregression_jl_tpu.core.losses import (
        aggregate_loss, l2_dist_loss)
    from symbolicregression_jl_tpu.ops.fused_eval import fused_loss

    rng = np.random.default_rng(0)
    n = 257
    X = jnp.asarray(rng.uniform(-2, 2, (2, n)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=n).astype(np.float32))
    cls = jnp.asarray(rng.integers(0, 3, n), jnp.int32)
    trees = encode_population([
        parse_expression("p1 + (x1 * p2)", ops),
        parse_expression("cos(p2) * x2", ops),
        parse_expression("p1", ops),  # single-leaf param tree
        parse_expression("x1 + 1.5", ops),  # no params at all
    ], 10, ops)
    params = jnp.asarray(
        rng.normal(size=(4, 2, 3)).astype(np.float32))  # [T, NP, NC]

    p_rows = jnp.take(params, cls, axis=-1)  # [T, NP, n]
    pred, v_ref = eval_tree_batch(trees, X, ops, params=p_rows)
    l_ref = aggregate_loss(l2_dist_loss, pred, y, v_ref)

    l_fused, v_fused = fused_loss(
        trees, X, y, None, ops, l2_dist_loss,
        params=params, class_idx=cls, interpret=True,
    )
    assert np.array_equal(np.asarray(v_ref), np.asarray(v_fused))
    ok = np.isfinite(np.asarray(l_ref))
    np.testing.assert_allclose(
        np.asarray(l_ref)[ok], np.asarray(l_fused)[ok], rtol=1e-5)


@pytest.mark.slow
def test_parametric_search_with_turbo_recovers():
    """Full parametric search on the fused eval path (turbo=True)."""
    rng = np.random.default_rng(1)
    n = 240
    X = rng.uniform(-2, 2, (n, 1)).astype(np.float32)
    cls = rng.integers(0, 2, n)
    offsets = np.array([1.0, -2.0], np.float32)
    y = (2.0 * X[:, 0] + offsets[cls]).astype(np.float32)

    from symbolicregression_jl_tpu import equation_search
    from symbolicregression_jl_tpu.api.search import RuntimeOptions

    options = Options(
        binary_operators=["+", "*"], unary_operators=[],
        maxsize=8, populations=4, population_size=20,
        ncycles_per_iteration=25,
        expression_spec=ParametricExpressionSpec(max_parameters=1),
        turbo=True, save_to_file=False,
    )
    hof = equation_search(
        X, y, options=options, extra={"class": cls},
        runtime_options=RuntimeOptions(niterations=10, seed=0, verbosity=0),
    )
    best = min(hof.pareto_frontier(), key=lambda m: m.loss)
    assert float(best.loss) < 0.1
