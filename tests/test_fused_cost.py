"""In-kernel cost epilogue (ops.fused_eval.fused_cost) parity tests.

The round-6 hot path returns (cost, loss, valid) straight from the
candidate-eval kernel's final grid step. The contract: BIT-identical to
the materializing path (fused_loss + loss_to_cost outside the kernel),
fp-tolerance agreement with the jnp interpreter, and unchanged
NaN/invalid => inf semantics — at the kernel, eval_cost_batch, and
whole-engine levels.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.core.losses import (
    aggregate_loss,
    l2_dist_loss,
    loss_to_cost,
)
from symbolicregression_jl_tpu.evolve.population import init_population
from symbolicregression_jl_tpu.evolve.step import (
    eval_cost_batch,
    evolve_config_from_options,
)
from symbolicregression_jl_tpu.ops.complexity import (
    build_complexity_tables,
    compute_complexity_batch,
)
from symbolicregression_jl_tpu.ops.encoding import encode_population
from symbolicregression_jl_tpu.ops.eval import eval_tree_batch
from symbolicregression_jl_tpu.ops.fused_eval import fused_cost, fused_loss


@pytest.fixture(scope="module")
def setup():
    opts = sr.Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "abs", "exp"],
        maxsize=20,
        save_to_file=False,
    )
    cfg = evolve_config_from_options(opts, 3)
    tables = build_complexity_tables(opts, 3)
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.uniform(-3, 3, (3, 257)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=257).astype(np.float32))
    return opts, cfg, tables, X, y


BASELINE = dict(baseline_loss=jnp.float32(1.7), use_baseline=jnp.bool_(True),
                parsimony=0.0032)


def test_fused_cost_bit_equal_to_materializing_path(setup):
    """cost/loss from the epilogue == fused_loss + loss_to_cost, to the
    bit (same kernel partials, same op order for the epilogue math)."""
    opts, cfg, tables, X, y = setup
    trees = init_population(jax.random.PRNGKey(3), 24, cfg.mctx, jnp.float32)
    cx = compute_complexity_batch(trees, tables)
    l_ref, v_ref = fused_loss(
        trees, X, y, None, cfg.operators, l2_dist_loss, interpret=True)
    c_ref = loss_to_cost(l_ref, BASELINE["baseline_loss"],
                         BASELINE["use_baseline"], cx, BASELINE["parsimony"])
    c, l, v = fused_cost(
        trees, X, y, None, cx, cfg.operators, l2_dist_loss,
        interpret=True, **BASELINE)
    assert np.array_equal(np.asarray(v), np.asarray(v_ref))
    assert np.array_equal(np.asarray(l), np.asarray(l_ref))
    assert np.array_equal(np.asarray(c), np.asarray(c_ref))


def test_fused_cost_matches_interpreter_with_invalids(setup):
    """Agreement with the jnp interpreter incl. the invalid => inf
    contract (1/0 domain failure) and leaf-only trees."""
    opts, cfg, tables, X, y = setup
    opset = cfg.operators
    exprs = [
        sr.parse_expression("cos(2.13 * x1) + 0.5 * x2", opset),
        sr.parse_expression("x1 * x2 - exp(x3 / 2.0)", opset),
        sr.parse_expression("abs(x3) / (x1 - x1)", opset),  # 1/0 -> invalid
        sr.parse_expression("1.5", opset),
        sr.parse_expression("x1", opset),
    ]
    batch = encode_population(exprs, opts.maxsize, opset)
    cx = compute_complexity_batch(batch, tables)
    pred, v_ref = eval_tree_batch(batch, X, opset)
    l_ref = aggregate_loss(l2_dist_loss, pred, y, v_ref)
    c_ref = loss_to_cost(l_ref, BASELINE["baseline_loss"],
                         BASELINE["use_baseline"], cx, BASELINE["parsimony"])
    c, l, v = fused_cost(
        batch, X, y, None, cx, opset, l2_dist_loss, interpret=True,
        **BASELINE)
    assert np.array_equal(np.asarray(v), np.asarray(v_ref))
    ok = np.isfinite(np.asarray(l_ref))
    assert np.allclose(np.asarray(l)[ok], np.asarray(l_ref)[ok], rtol=1e-5)
    assert np.all(np.isinf(np.asarray(l)[~ok]))
    assert np.allclose(np.asarray(c)[ok], np.asarray(c_ref)[ok], rtol=1e-5)
    assert np.all(np.isinf(np.asarray(c)[~ok]))


@pytest.mark.slow
def test_fused_cost_weighted(setup):
    opts, cfg, tables, X, y = setup
    n = X.shape[1]
    w = jnp.asarray(
        np.random.default_rng(1).uniform(0.5, 2.0, n).astype(np.float32))
    trees = init_population(jax.random.PRNGKey(9), 8, cfg.mctx, jnp.float32)
    cx = compute_complexity_batch(trees, tables)
    l_ref, _ = fused_loss(
        trees, X, y, w, cfg.operators, l2_dist_loss, interpret=True)
    c_ref = loss_to_cost(l_ref, BASELINE["baseline_loss"],
                         BASELINE["use_baseline"], cx, BASELINE["parsimony"])
    c, l, _ = fused_cost(
        trees, X, y, w, cx, cfg.operators, l2_dist_loss, interpret=True,
        **BASELINE)
    assert np.array_equal(np.asarray(l), np.asarray(l_ref))
    assert np.array_equal(np.asarray(c), np.asarray(c_ref))


@pytest.mark.slow
def test_fused_cost_batch_dims_and_vmap(setup):
    """Leading batch dims reshape correctly, and the engine-style vmap
    over islands produces identical values."""
    opts, cfg, tables, X, y = setup
    trees = init_population(jax.random.PRNGKey(5), 12, cfg.mctx, jnp.float32)
    cx = compute_complexity_batch(trees, tables)
    c_flat, l_flat, _ = fused_cost(
        trees, X, y, None, cx, cfg.operators, l2_dist_loss, interpret=True,
        **BASELINE)
    nested = jax.tree.map(lambda x: x.reshape((3, 4) + x.shape[1:]), trees)
    c_nest, l_nest, _ = fused_cost(
        nested, X, y, None, cx.reshape(3, 4), cfg.operators, l2_dist_loss,
        interpret=True, **BASELINE)
    assert c_nest.shape == (3, 4)
    assert np.array_equal(np.asarray(c_nest).reshape(-1), np.asarray(c_flat),
                          equal_nan=True)
    c_vm, _, _ = jax.vmap(
        lambda t, x: fused_cost(
            t, X, y, None, x, cfg.operators, l2_dist_loss, interpret=True,
            **BASELINE)
    )(nested, cx.reshape(3, 4))
    assert np.array_equal(np.asarray(c_vm).reshape(-1), np.asarray(c_flat),
                          equal_nan=True)


def test_eval_cost_batch_fuse_cost_route_bit_equal(setup):
    """eval_cost_batch with fuse_cost=True == the materializing route,
    and the eval_tree_block / eval_tile_rows overrides don't change
    values (per-tree results are launch-geometry independent)."""
    opts, cfg, tables, X, y = setup
    trees = init_population(jax.random.PRNGKey(21), 24, cfg.mctx, jnp.float32)

    from types import SimpleNamespace

    D = SimpleNamespace(
        Xt=X, y=y, weights=None, class_idx=None, x_dims=None, y_dims=None,
        baseline_loss=BASELINE["baseline_loss"],
        use_baseline=BASELINE["use_baseline"],
    )
    kw = dict(turbo=True, interpret=True, loss_function=None)
    base = eval_cost_batch(trees, D, l2_dist_loss, tables, cfg.operators,
                           BASELINE["parsimony"], **kw)
    fused = eval_cost_batch(trees, D, l2_dist_loss, tables, cfg.operators,
                            BASELINE["parsimony"], fuse_cost=True, **kw)
    tuned = eval_cost_batch(trees, D, l2_dist_loss, tables, cfg.operators,
                            BASELINE["parsimony"], fuse_cost=True,
                            tree_block=4, tile_rows=4096, **kw)
    for a, b in zip(base, fused):
        assert np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True)
    for a, b in zip(base, tuned):
        assert np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True)


def _run_engine(fuse, tree_block=None, debug_checks=False):
    from symbolicregression_jl_tpu import make_dataset, search_key
    from symbolicregression_jl_tpu.evolve.engine import Engine

    opts = sr.Options(
        binary_operators=["+", "*"], unary_operators=["cos"], maxsize=10,
        populations=2, population_size=12, tournament_selection_n=4,
        ncycles_per_iteration=3, save_to_file=False, turbo=True,
        fuse_cost_epilogue=fuse, eval_tree_block=tree_block,
        debug_checks=debug_checks,
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (64, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 1.0).astype(np.float32)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(opts.elementwise_loss)
    eng = Engine(opts, ds.nfeatures)
    state = eng.init_state(search_key(0), ds.data, 2)
    for _ in range(2):
        state = eng.run_iteration(state, ds.data, jnp.int32(opts.maxsize))
    return eng, state


@pytest.fixture(scope="module")
def fused_engine_run():
    """One fused-cost engine run shared by the engine-level tests;
    debug_checks=True runs the graftlint validate_programs audit over
    every state the fused path produces."""
    return _run_engine(True, debug_checks=True)


# Engine-level A/B runs compile three full evolve programs — slow tier.
# The fast tier still pins the fused path end-to-end through
# test_hot_loop_guards.py's turbo-fused engine (debug_checks audit +
# 0-traces/0-transfers) and the kernel-level parity tests above.
@pytest.mark.slow
def test_engine_fuse_cost_bit_identical_and_audited(fused_engine_run):
    """Two warm iterations of the full engine: the fused-cost search
    trajectory is bit-identical to the materializing one; debug_checks
    runs the graftlint validate_programs audit over the fused path's
    populations (raises on any postfix-invariant violation)."""
    eng_a, a = fused_engine_run
    assert eng_a.cfg.fuse_cost
    eng_b, b = _run_engine(False)
    assert not eng_b.cfg.fuse_cost
    for name in ("cost", "loss", "complexity", "birth", "ref"):
        assert np.array_equal(
            np.asarray(getattr(a.pops, name)),
            np.asarray(getattr(b.pops, name)), equal_nan=True), name
    for leaf_a, leaf_b in zip(jax.tree.leaves(a.pops.trees),
                              jax.tree.leaves(b.pops.trees)):
        assert np.array_equal(np.asarray(leaf_a), np.asarray(leaf_b),
                              equal_nan=True)
    assert np.array_equal(np.asarray(a.hof.cost), np.asarray(b.hof.cost),
                          equal_nan=True)


@pytest.mark.slow
def test_engine_eval_tree_block_option_plumbs_and_matches(fused_engine_run):
    """options.eval_tree_block reaches the kernel launch (different
    padding/blocking) without changing any per-tree result."""
    eng_a, a = fused_engine_run
    eng_b, b = _run_engine(True, tree_block=4)
    assert eng_b.cfg.eval_tree_block == 4
    assert np.array_equal(np.asarray(a.pops.cost), np.asarray(b.pops.cost),
                          equal_nan=True)
    assert np.array_equal(np.asarray(a.pops.loss), np.asarray(b.pops.loss),
                          equal_nan=True)


def test_custom_loss_function_keeps_materializing_path(setup):
    """The custom whole-prediction loss hook must keep the jnp fallback:
    turbo/fuse_cost are force-disabled by the options gate."""
    opts = sr.Options(
        binary_operators=["+", "*"], unary_operators=["cos"], maxsize=10,
        population_size=12, tournament_selection_n=4, save_to_file=False,
        turbo=True,
        loss_function=lambda pred, y, w, valid: jnp.mean((pred - y) ** 2),
    )
    cfg = evolve_config_from_options(opts, 2)
    assert not cfg.turbo
    assert not cfg.fuse_cost
