"""Fused Pallas eval kernel vs the jnp interpreter (interpret mode on CPU).

Mirrors the reference's LoopVectorization-extension tests — turbo SIMD
correctness incl. NaN handling (test/integration/ext/loopvectorization/,
SURVEY.md §4): the fast path must agree with the reference interpreter on
values, validity, and NaN/Inf domain failures.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.core.losses import aggregate_loss, l2_dist_loss, l1_dist_loss
from symbolicregression_jl_tpu.evolve.population import init_population
from symbolicregression_jl_tpu.evolve.step import evolve_config_from_options
from symbolicregression_jl_tpu.ops.encoding import encode_population
from symbolicregression_jl_tpu.ops.eval import eval_tree_batch
from symbolicregression_jl_tpu.ops.fused_eval import fused_loss


@pytest.fixture(scope="module")
def setup():
    opts = sr.Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "abs", "exp"],
        maxsize=20,
        save_to_file=False,
    )
    cfg = evolve_config_from_options(opts, 3)
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.uniform(-3, 3, (3, 257)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=257).astype(np.float32))
    return opts, cfg, X, y


def test_fused_matches_interpreter_on_exprs(setup):
    opts, cfg, X, y = setup
    opset = cfg.operators
    exprs = [
        sr.parse_expression("cos(2.13 * x1) + 0.5 * x2", opset),
        sr.parse_expression("x1 * x2 - exp(x3 / 2.0)", opset),
        sr.parse_expression("abs(x3) / (x1 - x1)", opset),  # 1/0 -> invalid
        sr.parse_expression("1.5", opset),
        sr.parse_expression("x1", opset),
    ]
    batch = encode_population(exprs, opts.maxsize, opset)
    pred, v_ref = eval_tree_batch(batch, X, opset)
    l_ref = aggregate_loss(l2_dist_loss, pred, y, v_ref)
    l_fused, v_fused = fused_loss(
        batch, X, y, None, opset, l2_dist_loss, interpret=True
    )
    assert np.array_equal(np.asarray(v_ref), np.asarray(v_fused))
    ok = np.isfinite(np.asarray(l_ref))
    assert np.allclose(
        np.asarray(l_ref)[ok], np.asarray(l_fused)[ok], rtol=1e-5
    )
    assert np.all(np.isinf(np.asarray(l_fused)[~ok]))


def test_fused_matches_on_random_population(setup):
    opts, cfg, X, y = setup
    trees = init_population(jax.random.PRNGKey(3), 64, cfg.mctx, jnp.float32)
    pred, v_ref = eval_tree_batch(trees, X, cfg.operators)
    l_ref = aggregate_loss(l2_dist_loss, pred, y, v_ref)
    l_fused, v_fused = fused_loss(
        trees, X, y, None, cfg.operators, l2_dist_loss, interpret=True
    )
    v_ref, v_fused = np.asarray(v_ref), np.asarray(v_fused)
    assert (v_ref == v_fused).mean() >= 0.98  # fp-order edge cases allowed
    both = v_ref & v_fused
    assert np.allclose(
        np.asarray(l_ref)[both], np.asarray(l_fused)[both], rtol=1e-4
    )


def test_fused_weighted_loss(setup):
    opts, cfg, X, y = setup
    opset = cfg.operators
    n = X.shape[1]
    w = jnp.asarray(
        np.random.default_rng(1).uniform(0.5, 2.0, n).astype(np.float32)
    )
    batch = encode_population(
        [sr.parse_expression("x1 + x2", opset)], opts.maxsize, opset
    )
    pred, v = eval_tree_batch(batch, X, opset)
    l_ref = aggregate_loss(l1_dist_loss, pred, y, v, w)
    l_fused, _ = fused_loss(
        batch, X, y, w, opset, l1_dist_loss, interpret=True
    )
    assert np.allclose(float(l_ref[0]), float(l_fused[0]), rtol=1e-5)


def test_fused_batch_dims(setup):
    """Leading batch dims (islands) reshape correctly."""
    opts, cfg, X, y = setup
    trees = init_population(jax.random.PRNGKey(5), 12, cfg.mctx, jnp.float32)
    nested = jax.tree.map(lambda x: x.reshape((3, 4) + x.shape[1:]), trees)
    l_flat, v_flat = fused_loss(
        trees, X, y, None, cfg.operators, l2_dist_loss, interpret=True
    )
    l_nest, v_nest = fused_loss(
        nested, X, y, None, cfg.operators, l2_dist_loss, interpret=True
    )
    assert l_nest.shape == (3, 4)
    assert np.allclose(
        np.asarray(l_flat), np.asarray(l_nest).reshape(-1), equal_nan=True
    )


def test_fused_loss_dedup_bit_equal(setup):
    """Identical-program dedup must be BIT-equal to the plain path:
    duplicates copy their leader's result, structure-only duplicates
    (same shape, different constants) must NOT merge."""
    import dataclasses as dc

    opts, cfg, X, y = setup
    rng = np.random.default_rng(4)
    base = init_population(jax.random.PRNGKey(17), 48, cfg.mctx, jnp.float32)
    # Build a batch with heavy duplication: 3 copies of each member in a
    # shuffled order; one copy of each gets its constants perturbed
    # (structure dup, full non-dup).
    pert = dc.replace(
        base,
        const=base.const * jnp.asarray(
            1.0 + 0.3 * rng.normal(size=base.const.shape).astype(np.float32)),
    )
    cat = jax.tree.map(
        lambda a, b, c: jnp.concatenate([a, b, c], axis=0), base, base, pert)
    perm = jnp.asarray(rng.permutation(3 * 48))
    batch = jax.tree.map(lambda x: jnp.take(x, perm, axis=0), cat)

    l_plain, v_plain = fused_loss(
        batch, X, y, None, cfg.operators, l2_dist_loss, interpret=True)
    l_dedup, v_dedup = fused_loss(
        batch, X, y, None, cfg.operators, l2_dist_loss, interpret=True,
        dedup=True)
    lp, ld = np.asarray(l_plain), np.asarray(l_dedup)
    assert np.array_equal(np.asarray(v_plain), np.asarray(v_dedup))
    assert np.array_equal(np.isfinite(lp), np.isfinite(ld))
    assert np.array_equal(lp[np.isfinite(lp)], ld[np.isfinite(ld)])


def test_fused_loss_dedup_nonfinite_constants(setup):
    """A member with a non-finite constant stays invalid through dedup,
    and does not poison distinct members that share its structure."""
    opts, cfg, X, y = setup
    opset = cfg.operators
    exprs = [
        sr.parse_expression("2.0 * x1 + 1.0", opset),
        sr.parse_expression("2.0 * x1 + 1.0", opset),   # exact duplicate
        sr.parse_expression("3.0 * x1 + 1.0", opset),   # structure dup only
        sr.parse_expression("x2", opset),
    ]
    import dataclasses as dc
    batch = encode_population(exprs, opts.maxsize, opset)
    # poison every const leaf of member 0
    cleaf0 = (batch.arity[0] == 0) & (batch.op[0] == 0)  # LEAF_CONST
    const = batch.const.at[0].set(
        jnp.where(cleaf0, jnp.inf, batch.const[0]))
    bad = dc.replace(batch, const=const)
    l, v = fused_loss(bad, X, y, None, opset, l2_dist_loss, interpret=True,
                      dedup=True)
    l2, v2 = fused_loss(bad, X, y, None, opset, l2_dist_loss, interpret=True)
    assert np.array_equal(np.asarray(v), np.asarray(v2))
    assert not bool(v[0])
    assert bool(v[1]) and bool(v[2]) and bool(v[3])
    assert np.isinf(float(l[0]))
    fin = np.isfinite(np.asarray(l2))
    assert np.array_equal(np.asarray(l)[fin], np.asarray(l2)[fin])


def test_fused_loss_multi_matches_replication(setup):
    """The multi-variant kernel == fused_loss on per-variant replicas
    (the line-search fast path must not change any loss value)."""
    from symbolicregression_jl_tpu.ops.fused_eval import fused_loss_multi
    from symbolicregression_jl_tpu.ops.program import compile_program

    opts, cfg, X, y = setup
    opset = cfg.operators
    trees = init_population(jax.random.PRNGKey(7), 6, cfg.mctx, jnp.float32)
    F = X.shape[0]
    prog = compile_program(trees, F, len(opset.binary))
    V = 5
    rng = np.random.default_rng(2)
    cvals_v = jnp.asarray(
        np.asarray(prog.cvals)[:, None, :]
        * (1.0 + rng.normal(0, 0.7, (6, V, prog.cmax)).astype(np.float32))
    )
    # one variant gets a non-finite constant -> must come back invalid
    # (only if that tree actually has constants)
    cvals_v = cvals_v.at[0, 2, 0].set(jnp.inf)
    l_multi, v_multi = fused_loss_multi(
        prog, cvals_v, X, y, None, F, opset, l2_dist_loss, interpret=True
    )
    assert l_multi.shape == (6, V)
    # reference: plain fused_loss on trees with constants scattered back
    import dataclasses as dc
    for v in range(V):
        const_v = trees.const.at[
            jnp.arange(6)[:, None], prog.cslot
        ].set(cvals_v[:, v, :], mode="drop")
        tr_v = dc.replace(trees, const=const_v)
        l_ref, v_ref = fused_loss(
            tr_v, X, y, None, opset, l2_dist_loss, interpret=True
        )
        assert np.array_equal(np.asarray(v_ref), np.asarray(v_multi[:, v]))
        ok = np.isfinite(np.asarray(l_ref))
        assert np.allclose(np.asarray(l_ref)[ok],
                           np.asarray(l_multi[:, v])[ok], rtol=1e-5)
        assert np.all(np.isinf(np.asarray(l_multi[:, v])[~ok]))


def test_fused_loss_multi_bf16_ranks_like_f32(setup):
    """bf16 line-search mode: ~3-digit losses, identical inf pattern,
    and (well-separated) variants rank the same as f32 — the contract
    the BFGS step-size selection relies on."""
    from symbolicregression_jl_tpu.ops.fused_eval import fused_loss_multi
    from symbolicregression_jl_tpu.ops.program import compile_program

    opts, cfg, X, y = setup
    opset = cfg.operators
    trees = init_population(jax.random.PRNGKey(11), 8, cfg.mctx, jnp.float32)
    F = X.shape[0]
    prog = compile_program(trees, F, len(opset.binary))
    V = 20  # exercises the bf16 V-chunking (16 + remainder)
    rng = np.random.default_rng(3)
    cvals_v = jnp.asarray(
        np.asarray(prog.cvals)[:, None, :]
        * (1.0 + rng.normal(0, 0.5, (8, V, prog.cmax)).astype(np.float32))
    )
    cvals_v = cvals_v.at[1, 3, 0].set(jnp.nan)
    l32, v32 = fused_loss_multi(
        prog, cvals_v, X, y, None, F, opset, l2_dist_loss, interpret=True)
    l16, v16 = fused_loss_multi(
        prog, cvals_v, X, y, None, F, opset, l2_dist_loss, bf16=True,
        interpret=True)
    assert l16.shape == (8, V)
    assert np.array_equal(np.asarray(v32), np.asarray(v16))
    a, b = np.asarray(l32), np.asarray(l16)
    assert np.array_equal(np.isfinite(a), np.isfinite(b))
    fin = np.isfinite(a)
    rel = np.abs(a[fin] - b[fin]) / (1e-6 + np.abs(a[fin]))
    # bf16 evals track f32 to ~3 digits in the typical case; individual
    # cancellation-heavy trees (x - 0.99x chains) can diverge by large
    # factors — that is exactly why acceptance re-verifies at f32.
    assert np.median(rel) < 0.02, np.median(rel)
    # the argmin variant agrees whenever f32 separates it clearly (2x)
    am = a.argmin(axis=1)
    for t in range(8):
        srt = np.sort(a[t][np.isfinite(a[t])])
        if len(srt) >= 2 and srt[1] > srt[0] * 2.0:
            assert b[t].argmin() == am[t]


def test_fused_optimizer_bf16_linesearch_still_descends(setup):
    """ls_bf16 BFGS: the f32 descent guard keeps accepted losses at or
    below the baseline, and constants still converge on a recoverable
    problem."""
    from symbolicregression_jl_tpu.evolve.constant_opt import (
        OptimizerConfig, optimize_constants_fused)

    opts, cfg, X, y = setup
    data = type("D", (), {"Xt": X, "y": y, "weights": None})()
    trees = init_population(jax.random.PRNGKey(13), 16, cfg.mctx, jnp.float32)
    do_opt = jnp.ones((16,), bool)
    base_cfg = OptimizerConfig(iterations=4, nrestarts=1)
    new_c, improved, new_loss, calls = optimize_constants_fused(
        jax.random.PRNGKey(0), trees, do_opt, data, l2_dist_loss,
        cfg.operators, base_cfg._replace(ls_bf16=True), interpret=True)
    l0, _ = fused_loss(trees, X, y, None, cfg.operators, l2_dist_loss,
                       interpret=True)
    l0 = np.where(np.isfinite(np.asarray(l0)), np.asarray(l0), np.inf)
    # accepted losses never exceed the pre-optimization baseline
    nl = np.asarray(new_loss)
    ok = np.isfinite(l0)
    assert np.all(nl[ok] <= l0[ok] + 1e-5)
    assert bool(np.any(np.asarray(improved)))


def test_fused_constant_optimizer(setup):
    """Fused batched-line-search BFGS recovers known constants
    (optimize_constants semantics, src/ConstantOptimization.jl:29-113)."""
    from symbolicregression_jl_tpu.evolve.constant_opt import (
        OptimizerConfig,
        optimize_constants_fused,
    )
    from symbolicregression_jl_tpu.core.dataset import make_dataset

    opts, cfg, X, y = setup
    opset = cfg.operators
    # target: y = 2.5*x1 - 1.25 ; start from wrong constants
    Xh = np.asarray(X).T  # (n, 3)
    yh = 2.5 * Xh[:, 0] - 1.25
    ds = make_dataset(Xh, yh)
    exprs = [
        sr.parse_expression("1.0 * x1 - 0.1", opset),
        sr.parse_expression("x2", opset),  # no constants: must be untouched
    ]
    batch = encode_population(exprs, opts.maxsize, opset)
    new_const, improved, new_loss, f_calls = optimize_constants_fused(
        jax.random.PRNGKey(0), batch, jnp.ones((2,), bool), ds.data,
        l2_dist_loss, opset, OptimizerConfig(iterations=20, nrestarts=1),
        interpret=True,
    )
    assert bool(improved[0])
    assert float(new_loss[0]) < 1e-3
    consts = np.asarray(new_const[0])
    live = np.asarray(batch.arity[0]) == 0
    got = sorted(np.round(consts[np.asarray(batch.op[0]) == 0][:2], 2).tolist())
    assert not bool(improved[1])  # nothing to optimize
    assert float(f_calls[0]) > 0
