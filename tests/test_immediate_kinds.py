"""simplify / optimize mutation kinds take real (deferred) effect.

The reference applies these inline inside mutate!
(/root/reference/src/Mutate.jl:571-658); the TPU engine marks the member
during the cycle and applies folding / constant optimization at the
iteration boundary (see generation_step's docstring).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu import Options
from symbolicregression_jl_tpu.core.dataset import make_dataset
from symbolicregression_jl_tpu.evolve.engine import Engine
from symbolicregression_jl_tpu.ops.encoding import encode_population
from symbolicregression_jl_tpu.ops.tree import parse_expression


def _mk_data(n=64, nf=2, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, (n, nf)).astype(np.float32)
    y = (3.0 * X[:, 0]).astype(np.float32)
    return X, y


def _weights_only(**kw):
    base = {k: 0.0 for k in (
        "mutate_constant", "mutate_operator", "mutate_feature",
        "swap_operands", "rotate_tree", "add_node", "insert_node",
        "delete_node", "simplify", "randomize", "do_nothing", "optimize",
    )}
    base.update(kw)
    return base


def test_simplify_kind_folds_marked_members():
    X, y = _mk_data()
    opts = Options(
        binary_operators=["+", "*"], unary_operators=[],
        maxsize=15, populations=1, population_size=8,
        ncycles_per_iteration=30, tournament_selection_n=2,
        crossover_probability=0.0,
        should_simplify=False,             # only the mutation kind folds
        should_optimize_constants=False,
        migration=False, hof_migration=False,
        mutation_weights=_weights_only(simplify=1.0),
        save_to_file=False,
    )
    ds = make_dataset(X, y)
    ds.update_baseline_loss(opts.elementwise_loss)
    engine = Engine(opts, ds.nfeatures)

    # all members: (1.0 + 2.0) * x1 — a foldable constant subtree
    tree = parse_expression("(1.0 + 2.0) * x1", opts.operators)
    trees = encode_population([tree] * 8, opts.maxsize, opts.operators)
    trees = jax.tree.map(lambda x: x[None], trees)  # island axis
    state = engine.init_state(jax.random.PRNGKey(0), ds.data, 1,
                              initial_trees=trees)
    assert int(jnp.max(state.pops.trees.length)) == 5

    state = engine.run_iteration(state, ds.data, opts.maxsize)
    lengths = np.asarray(state.pops.trees.length)[0]
    # With simplify the only sampled kind and 30 cycles over 8 members,
    # essentially every member should have been marked and folded to
    # 3.0 * x1 (3 nodes).
    assert (lengths == 3).sum() >= 6, lengths


@pytest.mark.slow
def test_optimize_kind_tunes_constants():
    X, y = _mk_data()
    opts = Options(
        binary_operators=["+", "*"], unary_operators=[],
        maxsize=15, populations=1, population_size=8,
        ncycles_per_iteration=30, tournament_selection_n=2,
        crossover_probability=0.0,
        should_simplify=False,
        optimizer_probability=0.0,          # only the mutation kind optimizes
        optimizer_iterations=6,
        mutation_weights=_weights_only(optimize=1.0),
        save_to_file=False,
    )
    ds = make_dataset(X, y)
    ds.update_baseline_loss(opts.elementwise_loss)
    engine = Engine(opts, ds.nfeatures)

    tree = parse_expression("1.1 * x1", opts.operators)  # true coef is 3.0
    trees = encode_population([tree] * 8, opts.maxsize, opts.operators)
    trees = jax.tree.map(lambda x: x[None], trees)
    state = engine.init_state(jax.random.PRNGKey(0), ds.data, 1,
                              initial_trees=trees)
    loss_before = float(jnp.min(state.pops.loss))

    state = engine.run_iteration(state, ds.data, opts.maxsize)
    loss_after = float(jnp.min(state.pops.loss))
    assert loss_after < 1e-6, (loss_before, loss_after)
    # the tuned constant should be ~3.0
    consts = np.asarray(state.pops.trees.const)[0]
    assert np.any(np.isclose(consts, 3.0, atol=1e-3)), consts[:, :3]
