"""graftbench host-side units: regression-gate logic over synthetic
fixtures (docs/BENCHMARKING.md) — baseline diff, noise-band edges,
missing cells, schema-version mismatch, an injected regression that
must exit nonzero, band calibration, telemetry metric extraction, the
trend report's red-artifact flagging, and the load-report percentile.

Pure host-side JSON processing: no search runs here (the real matrix
is exercised by the tools/check.sh graftbench step and the CI
bench-gate job).
"""

import copy
import json
import os

import pytest

from symbolicregression_jl_tpu.bench import __main__ as bench_cli
from symbolicregression_jl_tpu.bench.extract import extract_metrics
from symbolicregression_jl_tpu.bench.gate import (
    BASELINE_SCHEMA,
    GATED_METRICS,
    calibrate_bands,
    diff_result,
    gate_failed,
    load_baseline,
    make_baseline,
)
from symbolicregression_jl_tpu.bench.load import percentile
from symbolicregression_jl_tpu.bench.matrix import (
    RESULT_SCHEMA,
    matrix_cells,
)
from symbolicregression_jl_tpu.bench.trend import build_trend, format_trend
from symbolicregression_jl_tpu.telemetry.schema import validate_lines


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

BASE_METRICS = {
    "evals_per_sec": 100.0,
    "best_loss": 0.5,
    "pareto_volume": 0.2,
    "host_fraction": 0.01,
    "recompiles": 700,
}


def synth_result(metrics_by_cell=None, matrix="mini", platform="cpu"):
    cells = {}
    for cid, variant, seed in matrix_cells(["plain", "template"], [0, 1]):
        m = dict(BASE_METRICS)
        m.update((metrics_by_cell or {}).get(cid, {}))
        cells[cid] = {"cell_id": cid, "variant": variant, "seed": seed,
                      "metrics": m}
    return {"schema": RESULT_SCHEMA, "matrix": matrix,
            "platform": platform, "cells": cells, "failures": {}}


@pytest.fixture()
def baseline():
    return make_baseline([synth_result()])


# ---------------------------------------------------------------------------
# gate: pass / regression directions / band edges
# ---------------------------------------------------------------------------

def test_identical_result_passes(baseline):
    findings = diff_result(synth_result(), baseline)
    assert not gate_failed(findings)
    assert all(f.status == "ok" for f in findings)


def test_quality_regression_fails_hard(baseline):
    # best_loss is direction="higher": a big increase must fail even on
    # CPU (quality bands never widen with the platform)
    res = synth_result({"plain/seed0": {"best_loss": 0.7}})
    findings = diff_result(res, baseline)
    assert gate_failed(findings)
    bad = [f for f in findings if f.status == "regression"]
    assert [(f.cell, f.metric) for f in bad] == [("plain/seed0",
                                                 "best_loss")]


def test_pareto_volume_lower_is_regression(baseline):
    res = synth_result({"template/seed1": {"pareto_volume": 0.1}})
    findings = diff_result(res, baseline)
    assert gate_failed(findings)
    assert any(f.metric == "pareto_volume" and f.cell == "template/seed1"
               and f.status == "regression" for f in findings)


def test_band_edges_quality():
    # rel=0.05, abs=1e-7 around best_loss=0.5: 0.525 is the boundary —
    # just inside passes, just outside fails
    base = make_baseline([synth_result()])
    inside = synth_result({"plain/seed0": {"best_loss": 0.525}})
    assert not gate_failed(diff_result(inside, base))
    outside = synth_result({"plain/seed0": {"best_loss": 0.5251}})
    assert gate_failed(diff_result(outside, base))


def test_throughput_band_widens_on_cpu(baseline):
    # evals_per_sec band rel=0.30 x cpu factor 2.0 = 0.60: a 50% drop
    # passes on CPU but the same result on a device platform fails
    drop = {"plain/seed0": {"evals_per_sec": 50.0}}
    assert not gate_failed(diff_result(synth_result(drop), baseline))
    on_device = synth_result(drop, platform="device")
    assert gate_failed(diff_result(on_device, baseline))


def test_cpu_throughput_excursion_is_soft_not_failing(baseline):
    # a CPU band excursion above the collapse floor is a SOFT finding
    # (reported, non-failing): absolute CPU wall-clock does not
    # transfer across hosts — only the backstops fail a CPU gate
    res = synth_result({"plain/seed0": {"evals_per_sec": 25.0}})
    findings = diff_result(res, baseline)
    assert not gate_failed(findings)
    soft = [f for f in findings if f.status == "soft"]
    assert [(f.cell, f.metric) for f in soft] == [("plain/seed0",
                                                  "evals_per_sec")]
    from symbolicregression_jl_tpu.bench.gate import format_findings

    assert "soft (non-failing)" in format_findings(findings)
    # the SAME excursion on a device platform is a hard failure
    on_device = synth_result({"plain/seed0": {"evals_per_sec": 25.0}},
                             platform="device")
    assert gate_failed(diff_result(on_device, baseline))


def test_throughput_collapse_fails_even_on_cpu(baseline):
    res = synth_result({"plain/seed0": {"evals_per_sec": 9.0}})
    assert gate_failed(diff_result(res, baseline))


def test_collapse_floor_survives_vacuous_band(baseline):
    # a noisy calibration can push the evals/s band past rel=1.0 (base
    # - margin < 0 — the gate would never fire); the collapse floor
    # must still catch a fresh value below 10% of baseline
    wide = copy.deepcopy(baseline)
    wide["bands"]["evals_per_sec"]["rel"] = 5.0
    ok = synth_result({"plain/seed0": {"evals_per_sec": 11.0}})
    assert not gate_failed(diff_result(ok, wide))
    collapsed = synth_result({"plain/seed0": {"evals_per_sec": 9.0}})
    findings = diff_result(collapsed, wide)
    assert gate_failed(findings)
    assert any(f.metric == "evals_per_sec"
               and f.status == "regression" for f in findings)


def test_quality_backstops_survive_vacuous_band(baseline):
    # the backstops cover quality too: a calibration-widened quality
    # band (rel > 1.0) must not disable hard quality gating
    wide = copy.deepcopy(baseline)
    wide["bands"]["pareto_volume"]["rel"] = 5.0
    wide["bands"]["best_loss"]["rel"] = 50.0
    collapsed = synth_result({"plain/seed0": {"pareto_volume": 0.0}})
    findings = diff_result(collapsed, wide)
    assert gate_failed(findings)  # below 10% of base 0.2
    assert any(f.metric == "pareto_volume"
               and f.status == "regression" for f in findings)
    blown = synth_result({"plain/seed0": {"best_loss": 5.1}})
    assert gate_failed(diff_result(blown, wide))  # above 10x base 0.5
    assert not gate_failed(diff_result(synth_result(), wide))


def test_nan_metric_is_a_regression(baseline):
    # every NaN comparison is False: without an explicit finiteness
    # check a quality collapse to NaN would gate as "ok"
    res = synth_result({"plain/seed0": {"best_loss": float("nan")}})
    findings = diff_result(res, baseline)
    assert gate_failed(findings)
    bad = [f for f in findings if f.status == "regression"]
    assert bad and "non-finite" in bad[0].note
    res = synth_result({"plain/seed0": {"evals_per_sec": float("inf")}})
    assert gate_failed(diff_result(res, baseline))


def test_nan_baseline_value_is_a_regression(baseline):
    # a NaN pinned into the baseline (json.dump writes it) would make
    # margin NaN and silently disable the metric forever
    bad_base = copy.deepcopy(baseline)
    bad_base["cells"]["plain/seed0"]["metrics"]["best_loss"] = float(
        "nan")
    findings = diff_result(synth_result(), bad_base)
    assert gate_failed(findings)
    assert any("non-finite" in f.note for f in findings
               if f.status == "regression")
    # findings must still format without crashing on the None allowed
    from symbolicregression_jl_tpu.bench.gate import format_findings

    assert "non-finite" in format_findings(findings)


def test_blowup_ceiling_survives_vacuous_higher_band(baseline):
    # the symmetric backstop to the collapse floor: a recompile storm
    # or host-fraction blow-up beyond 10x baseline must fail even when
    # a noisy calibration made the band effectively unbounded
    wide = copy.deepcopy(baseline)
    wide["bands"]["recompiles"]["rel"] = 50.0
    wide["bands"]["host_fraction"]["rel"] = 500.0
    storm = synth_result({"plain/seed0": {"recompiles": 700 * 11}})
    findings = diff_result(storm, wide)
    assert gate_failed(findings)
    assert any(f.metric == "recompiles" and f.status == "regression"
               for f in findings)
    hot = synth_result({"plain/seed0": {"host_fraction": 0.9}})
    assert gate_failed(diff_result(hot, wide))
    # near-baseline values still pass under the same wide bands
    assert not gate_failed(diff_result(synth_result(), wide))


def test_improvement_is_not_failure(baseline):
    res = synth_result({"plain/seed0": {"best_loss": 0.1,
                                        "evals_per_sec": 1000.0}})
    findings = diff_result(res, baseline)
    assert not gate_failed(findings)
    assert any(f.status == "improvement" for f in findings)


# ---------------------------------------------------------------------------
# gate: structural failures
# ---------------------------------------------------------------------------

def test_missing_cell_fails(baseline):
    res = synth_result()
    del res["cells"]["template/seed0"]
    res["failures"]["template/seed0"] = {"error": "cell crashed rc=1"}
    findings = diff_result(res, baseline)
    assert gate_failed(findings)
    miss = [f for f in findings if f.status == "missing_cell"]
    assert len(miss) == 1 and miss[0].cell == "template/seed0"
    assert "rc=1" in miss[0].note


def test_missing_metric_fails(baseline):
    res = synth_result()
    del res["cells"]["plain/seed1"]["metrics"]["best_loss"]
    assert gate_failed(diff_result(res, baseline))


def test_schema_mismatch_fails(baseline):
    res = synth_result()
    res["schema"] = "graftbench.result.v999"
    findings = diff_result(res, baseline)
    assert gate_failed(findings)
    assert findings[0].status == "schema"

    bad_base = copy.deepcopy(baseline)
    bad_base["schema"] = "graftbench.baseline.v999"
    findings = diff_result(synth_result(), bad_base)
    assert gate_failed(findings) and findings[0].status == "schema"


def test_matrix_kind_mismatch_fails(baseline):
    res = synth_result(matrix="full", platform="device")
    findings = diff_result(res, baseline)
    assert gate_failed(findings)
    assert findings[0].metric == "matrix"


def test_load_baseline_rejects_wrong_schema(tmp_path):
    p = tmp_path / "baseline.json"
    p.write_text(json.dumps({"schema": "graftbench.baseline.v999"}))
    with pytest.raises(ValueError, match="regenerate"):
        load_baseline(str(p))
    good = tmp_path / "good.json"
    good.write_text(json.dumps(make_baseline([synth_result()])))
    assert load_baseline(str(good))["schema"] == BASELINE_SCHEMA


# ---------------------------------------------------------------------------
# injected regression through the CLI: must exit nonzero
# ---------------------------------------------------------------------------

def test_injected_regression_exits_nonzero(tmp_path, capsys):
    base_path = tmp_path / "baseline.json"
    base_path.write_text(json.dumps(make_baseline([synth_result()])))
    res_path = tmp_path / "result.json"
    res_path.write_text(json.dumps(
        synth_result({"plain/seed0": {"best_loss": 5.0}})))
    out_path = tmp_path / "gated.json"
    rc = bench_cli.main([
        "gate", "--baseline", str(base_path),
        "--result", str(res_path), "--out", str(out_path)])
    assert rc == 1
    assert "FAIL" in capsys.readouterr().out
    gated = json.loads(out_path.read_text())
    assert gated["gate"]["failed"] is True
    assert any(f["status"] == "regression"
               for f in gated["gate"]["findings"])


def test_clean_result_exits_zero(tmp_path, capsys):
    base_path = tmp_path / "baseline.json"
    base_path.write_text(json.dumps(make_baseline([synth_result()])))
    res_path = tmp_path / "result.json"
    res_path.write_text(json.dumps(synth_result()))
    rc = bench_cli.main([
        "gate", "--baseline", str(base_path), "--result", str(res_path)])
    assert rc == 0
    assert "PASS" in capsys.readouterr().out


def test_gate_result_file_respects_slice_flags(tmp_path, capsys):
    # gating a precomputed SLICED result with the matching flags must
    # not hard-fail the deliberately excluded cells
    base_path = tmp_path / "baseline.json"
    base_path.write_text(json.dumps(make_baseline([synth_result()])))
    sliced = synth_result()
    for cid in list(sliced["cells"]):
        if not cid.startswith("plain/"):
            del sliced["cells"][cid]
    res_path = tmp_path / "sliced.json"
    res_path.write_text(json.dumps(sliced))
    rc = bench_cli.main([
        "gate", "--baseline", str(base_path), "--result", str(res_path),
        "--variants", "plain"])
    out = capsys.readouterr().out
    assert rc == 0 and "PARTIAL" in out and "PASS" in out
    # without the flags the excluded cells ARE missing — hard fail
    rc = bench_cli.main([
        "gate", "--baseline", str(base_path),
        "--result", str(res_path)])
    assert rc == 1
    capsys.readouterr()


def test_partial_gate_slices_baseline_cells(baseline):
    # a sliced dev run diffs only what it ran: the cells it was asked
    # to skip are not "missing"
    res = synth_result()
    for cid in list(res["cells"]):
        if not cid.startswith("plain/"):
            del res["cells"][cid]
    assert gate_failed(diff_result(res, baseline))  # unfiltered: missing
    findings = diff_result(
        res, baseline, cells_filter=["plain/seed0", "plain/seed1"])
    assert not gate_failed(findings)
    assert {f.cell for f in findings} == {"plain/seed0", "plain/seed1"}


def test_fresh_cell_missing_from_baseline_is_noted(baseline):
    # a newly added variant has no baseline cell: it must not gate
    # silently green — an ungated-coverage note is emitted
    res = synth_result()
    res["cells"]["bf16/seed0"] = {"cell_id": "bf16/seed0",
                                  "variant": "bf16", "seed": 0,
                                  "metrics": dict(BASE_METRICS)}
    findings = diff_result(res, baseline)
    assert not gate_failed(findings)
    notes = [f for f in findings if f.status == "note"]
    assert [f.cell for f in notes] == ["bf16/seed0"]
    assert "ungated" in notes[0].note


def test_provenance_mismatch_is_note_not_failure(baseline):
    noted = copy.deepcopy(baseline)
    noted["provenance"] = {"jax": "0.0.1", "numpy": "1.0"}
    res = synth_result()
    res["provenance"] = {"jax": "9.9.9", "numpy": "1.0"}
    findings = diff_result(res, noted)
    assert not gate_failed(findings)
    notes = [f for f in findings if f.status == "note"]
    assert len(notes) == 1 and "re-pin" in notes[0].note
    from symbolicregression_jl_tpu.bench.gate import format_findings

    assert "9.9.9" in format_findings(findings)


def test_quality_excursion_gates_soft_under_version_drift(baseline):
    # on an unpinned dev machine a jax release legitimately moves the
    # trajectory: quality band excursions downgrade to soft under
    # provenance drift (CI pins versions, so there the gate stays
    # hard) — but the quality BACKSTOPS stay hard even under drift
    drifted = copy.deepcopy(baseline)
    drifted["provenance"] = {"jax": "0.0.1", "numpy": "1.0"}
    res = synth_result({"plain/seed0": {"best_loss": 0.7}})
    res["provenance"] = {"jax": "9.9.9", "numpy": "1.0"}
    findings = diff_result(res, drifted)
    assert not gate_failed(findings)
    assert any(f.metric == "best_loss" and f.status == "soft"
               for f in findings)
    # the same excursion without drift is a hard failure
    assert gate_failed(diff_result(
        synth_result({"plain/seed0": {"best_loss": 0.7}}), baseline))
    # a 10x quality blow-up fails even under drift (backstop)
    blown = synth_result({"plain/seed0": {"best_loss": 6.0}})
    blown["provenance"] = {"jax": "9.9.9", "numpy": "1.0"}
    assert gate_failed(diff_result(blown, drifted))


def test_run_refuses_baseline_pin_on_any_repeat_failure(
        tmp_path, monkeypatch, capsys):
    from symbolicregression_jl_tpu.bench import matrix as matrix_mod

    results = [synth_result(), synth_result()]
    del results[0]["cells"]["plain/seed0"]
    results[0]["failures"]["plain/seed0"] = {"error": "boom"}
    it = iter(results)
    monkeypatch.setattr(matrix_mod, "run_matrix",
                        lambda **kw: next(it))
    out = tmp_path / "baseline.json"
    rc = bench_cli.main(["run", "--repeats", "2",
                         "--baseline-out", str(out)])
    assert rc == 1
    assert not out.exists()
    assert "refusing to pin" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# band calibration from repeated runs
# ---------------------------------------------------------------------------

def test_calibrate_bands_widens_to_observed_spread():
    # two repeats with 40% evals/s spread on one cell: the calibrated
    # band must cover 2x that, above the 0.30 floor
    r1 = synth_result({"plain/seed0": {"evals_per_sec": 100.0}})
    r2 = synth_result({"plain/seed0": {"evals_per_sec": 140.0}})
    bands = calibrate_bands([r1, r2])
    assert bands["evals_per_sec"].rel > GATED_METRICS[
        "evals_per_sec"].rel
    # quality spread of zero keeps the tight floor band
    assert bands["best_loss"].rel == GATED_METRICS["best_loss"].rel


def test_calibrate_bands_never_narrows():
    bands = calibrate_bands([synth_result(), synth_result()])
    for m, b in bands.items():
        assert b.rel >= GATED_METRICS[m].rel
        assert b.abs >= GATED_METRICS[m].abs


def test_make_baseline_refuses_non_finite_gated_metric():
    # a diverged calibration repeat must fail the pin, not bake a NaN
    # into the committed baseline (which would fail every later gate)
    bad = synth_result({"plain/seed0": {"best_loss": float("nan")}})
    with pytest.raises(ValueError, match="non-finite best_loss"):
        make_baseline([synth_result(), bad])


def test_make_baseline_medians_and_mixed_matrix():
    r1 = synth_result({"plain/seed0": {"evals_per_sec": 90.0}})
    r2 = synth_result({"plain/seed0": {"evals_per_sec": 110.0}})
    r3 = synth_result({"plain/seed0": {"evals_per_sec": 100.0}})
    base = make_baseline([r1, r2, r3])
    assert base["cells"]["plain/seed0"]["metrics"][
        "evals_per_sec"] == 100.0
    with pytest.raises(ValueError, match="mixed matrix"):
        make_baseline([synth_result(), synth_result(matrix="full")])


# ---------------------------------------------------------------------------
# metric extraction from (synthetic, schema-valid) graftscope JSONL
# ---------------------------------------------------------------------------

def _iter_event(i, evals_per_sec, traces, min_loss, pareto_volume):
    return {
        "schema": "graftscope.v1", "event": "iteration", "t": 100.0 + i,
        "iteration": i, "num_evals": 100.0 * i,
        "evals_per_sec": evals_per_sec, "elapsed_s": 1.0,
        "device_s": 0.9, "host_s": 0.1, "host_fraction": 0.1,
        "recompiles": {"traces": traces, "backend_compiles": 0},
        "transfer_guard_hits": 0,
        "outputs": [{"output": 1, "min_loss": min_loss,
                     "pareto_volume": pareto_volume, "counters": None,
                     "loss_hist": None, "complexity_hist": None}],
    }


def synth_events():
    events = [
        {"schema": "graftscope.v1", "event": "run_start", "t": 100.0,
         "run_id": "cell", "backend": "cpu", "n_devices": 1, "nout": 1,
         "niterations": 3, "telemetry_interval": 1, "options": {},
         "engines": []},
        _iter_event(1, 50.0, 800, 0.9, 0.05),   # cold: traces
        _iter_event(2, 200.0, 0, 0.6, 0.10),    # warm
        _iter_event(3, 100.0, 0, 0.5, 0.20),    # warm
        {"schema": "graftscope.v1", "event": "run_end", "t": 104.0,
         "stop_reason": "niterations", "iterations": 3,
         "num_evals": 300.0, "elapsed_s": 3.0,
         "recompiles_total": {"traces": 800, "backend_compiles": 0}},
    ]
    # the fixture must stay schema-valid or extract tests prove nothing
    assert not validate_lines([json.dumps(e) for e in events])
    return events


def test_extract_metrics_warm_mean_and_quality():
    m = extract_metrics(synth_events())
    assert m["evals_per_sec"] == pytest.approx(150.0)  # mean of warm
    assert m["best_loss"] == pytest.approx(0.5)
    assert m["pareto_volume"] == pytest.approx(0.20)
    assert m["recompiles"] == 800
    assert m["host_fraction"] == pytest.approx(0.1)
    assert m["num_evals"] == 300.0
    assert m["stop_reason"] == "niterations"


def test_extract_metrics_excludes_midrun_retrace():
    # a retrace-slowed mid-run iteration (traces > 0) must not leak
    # into the gated warm mean — only genuinely warm iterations count
    events = synth_events()
    events.insert(4, _iter_event(4, 1000.0, 7, 0.5, 0.20))  # retraced
    m = extract_metrics(events)
    assert m["evals_per_sec"] == pytest.approx(150.0)  # 200, 100 only


def test_extract_metrics_falls_back_to_peak_without_warm():
    events = synth_events()
    for e in events:
        if e["event"] == "iteration":
            e["recompiles"] = {"traces": 10, "backend_compiles": 0}
    m = extract_metrics(events)
    assert m["evals_per_sec"] == pytest.approx(200.0)  # peak fallback


def test_report_cli_metrics_flag(tmp_path, capsys):
    from symbolicregression_jl_tpu.telemetry.report import main as rmain

    p = tmp_path / "run.jsonl"
    p.write_text("".join(json.dumps(e) + "\n" for e in synth_events()))
    assert rmain(["report", str(p), "--metrics"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["evals_per_sec"] == pytest.approx(150.0)


# ---------------------------------------------------------------------------
# trend: red artifacts flagged, never dropped
# ---------------------------------------------------------------------------

def _write(path, payload):
    with open(path, "w") as f:
        json.dump(payload, f)


def test_trend_marks_red_multichip_with_rc(tmp_path):
    bench_line = json.dumps({
        "metric": "full_dataset_expr_evals_per_sec_10k_rows",
        "value": 507284.7, "unit": "evals/s", "vs_baseline": 7.8})
    _write(tmp_path / "BENCH_r05.json",
           {"n": 5, "rc": 0, "tail": "warning noise\n" + bench_line + "\n"})
    _write(tmp_path / "MULTICHIP_r04.json",
           {"n_devices": 8, "rc": 0, "ok": True, "skipped": False})
    _write(tmp_path / "MULTICHIP_r05.json",
           {"n_devices": 8, "rc": 124, "ok": False, "skipped": False})
    trend = build_trend(str(tmp_path))

    rows = {r["round"]: r for r in trend["multichip"]}
    assert rows[4]["red"] is False
    assert rows[5]["red"] is True and rows[5]["rc"] == 124
    assert trend["red_count"] == 1
    assert trend["bench"][0]["evals_per_sec"] == 507284.7

    text = format_trend(trend)
    assert "RED rc=124" in text
    assert "r05" in text


def test_trend_red_bench_round_not_dropped(tmp_path):
    _write(tmp_path / "BENCH_r02.json",
           {"n": 2, "rc": 1, "tail": "Traceback ...\n"})
    trend = build_trend(str(tmp_path))
    assert trend["bench"][0]["red"] is True
    assert trend["bench"][0]["rc"] == 1
    assert trend["red_count"] == 1


def test_trend_unparseable_green_tail_is_red(tmp_path):
    _write(tmp_path / "BENCH_r03.json",
           {"n": 3, "rc": 0, "tail": "no json here\n"})
    trend = build_trend(str(tmp_path))
    assert trend["bench"][0]["red"] is True
    assert "no parseable" in trend["bench"][0]["note"]


def test_trend_flags_flat_headline(tmp_path):
    for n, v in ((4, 500000.0), (5, 507000.0)):
        line = json.dumps({"value": v, "vs_baseline": 7.8})
        _write(tmp_path / f"BENCH_r0{n}.json",
               {"n": n, "rc": 0, "tail": line + "\n"})
    trend = build_trend(str(tmp_path))
    assert trend["flat_note"] and "r04->r05" in trend["flat_note"]


def test_trend_folds_gate_results(tmp_path):
    hist = tmp_path / "benchmarks" / "history"
    os.makedirs(hist)
    _write(hist / "gate_r06.json", synth_result())
    bad = synth_result()
    del bad["cells"]["plain/seed0"]
    bad["failures"]["plain/seed0"] = {"error": "boom"}
    _write(hist / "gate_r07.json", bad)
    trend = build_trend(str(tmp_path))
    assert len(trend["gates"]) == 2
    green = {g["file"]: g for g in trend["gates"]}
    assert green["gate_r06.json"]["red"] is False
    assert green["gate_r07.json"]["red"] is True
    assert "1 matrix cell(s) failed" in green["gate_r07.json"]["note"]


def test_trend_marks_failed_gate_verdict_red(tmp_path):
    # a gate artifact whose cells all ran but whose embedded verdict
    # FAILED (band regression) must be a red row, not a green one
    hist = tmp_path / "benchmarks" / "history"
    os.makedirs(hist)
    failed = synth_result()
    failed["gate"] = {
        "failed": True,
        "findings": [{"cell": "plain/seed0", "metric": "best_loss",
                      "status": "regression"}],
    }
    _write(hist / "gate_r08.json", failed)
    trend = build_trend(str(tmp_path))
    row = trend["gates"][0]
    assert row["red"] is True
    assert "gate FAILED (1 finding(s))" in row["note"]
    assert trend["red_count"] == 1
    assert "RED" in format_trend(trend)


def test_trend_cli_strict_exit(tmp_path, capsys):
    _write(tmp_path / "MULTICHIP_r05.json",
           {"n_devices": 8, "rc": 124, "ok": False, "skipped": False})
    assert bench_cli.main(["trend", "--root", str(tmp_path)]) == 0
    assert bench_cli.main(
        ["trend", "--root", str(tmp_path), "--strict"]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# load report aggregation
# ---------------------------------------------------------------------------

def test_percentile_nearest_rank():
    assert percentile([], 99) is None
    assert percentile([1.0], 99) == 1.0
    xs = [float(i) for i in range(1, 101)]
    assert percentile(xs, 50) == pytest.approx(50.0, abs=1.0)
    assert percentile(xs, 99) == pytest.approx(99.0, abs=1.0)
    assert percentile(xs, 100) == 100.0


# ---------------------------------------------------------------------------
# projection satellite: the ici-model bridge out of bench.py
# ---------------------------------------------------------------------------

def test_projection_matches_committed_headline():
    from symbolicregression_jl_tpu.bench.projection import (
        v5e8_comm_efficiency,
    )

    # BENCH_r05's committed projection inputs: 9.77 s/iteration at the
    # bench config must reproduce the recorded efficiency + byte volume
    eff, src = v5e8_comm_efficiency(9.77)
    assert eff == pytest.approx(0.999, abs=5e-4)
    assert src["total_MB_per_iter_upper"] == pytest.approx(
        465.349, abs=1e-3)
    assert src["measured_iter_seconds"] == 9.77
