"""Deterministic mode (test_deterministic.jl analogue, SURVEY.md §4):
deterministic=True requires a seed, and two seeded runs produce identical
halls of fame.
"""

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options, equation_search


def _problem(n=150):
    rng = np.random.default_rng(7)
    X = rng.uniform(-2, 2, (n, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 1.5).astype(np.float32)
    return X, y


def _options(**kw):
    base = dict(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=4,
        save_to_file=False,
        deterministic=True,
    )
    base.update(kw)
    return Options(**base)


def test_deterministic_requires_seed():
    X, y = _problem()
    with pytest.raises(ValueError, match="seed"):
        equation_search(X, y, options=_options(), niterations=1, verbosity=0)


@pytest.mark.slow
def test_two_deterministic_runs_identical():
    X, y = _problem()
    hofs = []
    for _ in range(2):
        hofs.append(
            equation_search(
                X, y, options=_options(seed=11), niterations=3, verbosity=0
            )
        )
    a, b = hofs
    assert len(a.entries) == len(b.entries)
    for ea, eb in zip(a.entries, b.entries):
        assert ea.complexity == eb.complexity
        assert ea.loss == eb.loss
        assert ea.equation_string() == eb.equation_string()
