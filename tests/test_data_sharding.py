"""Data-axis sharding (n_data_shards > 1): dataset rows sharded over the
mesh's data axis with the loss reduction as a cross-shard psum.

The fused Pallas kernel path is documented to fall back to the jnp
interpreter under row sharding (evolve/step.py
evolve_config_from_options); these tests exercise the full search on
4x2 and 2x4 virtual meshes (conftest provisions 8 CPU devices).
"""

import numpy as np
import pytest

import jax

from symbolicregression_jl_tpu import Options, equation_search
from symbolicregression_jl_tpu.api.search import RuntimeOptions
from symbolicregression_jl_tpu.core.dataset import make_dataset
from symbolicregression_jl_tpu.parallel.mesh import (
    DATA_AXIS,
    make_mesh,
    shard_device_data,
)


def _problem(n=256):
    rng = np.random.default_rng(5)
    X = rng.uniform(-2, 2, (n, 3)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2]).astype(np.float32)
    return X, y


def test_shard_device_data_places_rows_on_data_axis():
    assert len(jax.devices()) == 8, "conftest virtual mesh not engaged"
    mesh = make_mesh(jax.devices(), n_island_shards=4, n_data_shards=2)
    X, y = _problem()
    ds = make_dataset(X, y)
    data = shard_device_data(ds.data, mesh)
    spec = data.Xt.sharding.spec
    assert spec[1] == DATA_AXIS  # rows sharded
    assert data.y.sharding.spec[0] == DATA_AXIS


@pytest.mark.slow
@pytest.mark.parametrize("n_data_shards", [2, 4])
def test_search_with_data_sharding(n_data_shards):
    X, y = _problem()
    options = Options(
        binary_operators=["+", "-", "*"],
        unary_operators=[],
        maxsize=8,
        populations=4,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=4,
        save_to_file=False,
    )
    hof = equation_search(
        X, y, options=options,
        runtime_options=RuntimeOptions(
            niterations=3, seed=0, verbosity=0, n_data_shards=n_data_shards
        ),
    )
    best = min(e.loss for e in hof.entries)
    assert np.isfinite(best)
    assert best < 2.0  # search made real progress under row sharding


@pytest.mark.slow
def test_sharded_matches_unsharded_loss():
    # Same seed, 1 vs 2 data shards: losses must agree (the psum
    # reduction is exact up to float reassociation).
    X, y = _problem(128)
    options = Options(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=6,
        populations=2,
        population_size=10,
        tournament_selection_n=4,
        ncycles_per_iteration=2,
        save_to_file=False,
    )
    losses = []
    for shards in (1, 2):
        hof = equation_search(
            X, y, options=options,
            runtime_options=RuntimeOptions(
                niterations=2, seed=9, verbosity=0, n_data_shards=shards
            ),
        )
        losses.append(sorted((e.complexity, e.loss) for e in hof.entries))
    a, b = losses
    assert [c for c, _ in a] == [c for c, _ in b]
    for (_, la), (_, lb) in zip(a, b):
        np.testing.assert_allclose(la, lb, rtol=1e-4)
