"""graftpack: multi-tenant packing correctness (docs/SERVING.md
"Packed tenancy").

The packing contract is *co-tenancy independence*: with packing ON,
every tenant's result is bit-identical to the same request run alone on
a pack-enabled server (a cohort of one — identical padding, identical
numerics). That plus the padding-inertness guarantees (pack/padding.py)
is what lets the scheduler coalesce, late-join, and peel tenants freely
without any tenant being able to observe its neighbours.

Layers pinned here:

- padding unit semantics (cyclic/edge fills, weights, error cases);
- kernel-level bit-identity: padded zero-weight replica rows leave the
  fused kernel's per-tree loss sums and validity bits untouched;
- pad-content invariance: two different fills produce bit-identical
  full searches (masking completeness — pad values CANNOT leak in);
- packed-vs-solo bit-identity at 2 and 4 tenants with mixed
  niterations (peel-off mid-flight);
- journaled padding provenance surviving replay (the journal records
  the *effective* padded request, like overload's sample_rows);
- preempt-restart-replay of a packed server (slow tier).
"""

import os
import time

import numpy as np
import pytest

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu.api.search import equation_search
from symbolicregression_jl_tpu.pack import (PackPolicy, pack_group_key,
                                            packable, pad_to_bucket,
                                            slot_cap)
from symbolicregression_jl_tpu.serve import SearchServer
from symbolicregression_jl_tpu.serve.server import result_fingerprint
from symbolicregression_jl_tpu.telemetry.report import summarize
from symbolicregression_jl_tpu.telemetry.schema import load_events


def _problem(n=100, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2.0, 2.0, (n, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(**kw):
    base = dict(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
    )
    base.update(kw)
    return base


# ---------------------------------------------------------------- unit


def test_pad_to_bucket_cyclic_and_edge():
    X, y = _problem(5)
    Xp, yp, w = pad_to_bucket(X, y, rows=8)
    assert Xp.shape == (8, 2) and yp.shape == (8,) and w.shape == (8,)
    assert np.array_equal(Xp[:5], X) and np.array_equal(yp[:5], y)
    # cyclic: pad row j is real row j % n, bit-for-bit
    for j, src in enumerate([0, 1, 2]):
        assert np.array_equal(Xp[5 + j], X[src])
        assert yp[5 + j] == y[src]
    assert np.array_equal(w, [1, 1, 1, 1, 1, 0, 0, 0])
    assert w.dtype == X.dtype

    Xe, ye, we = pad_to_bucket(X, y, rows=8, fill="edge")
    assert all(np.array_equal(Xe[5 + j], X[2]) for j in range(3))
    assert np.array_equal(we, w)

    # rows == n: copies, all-ones weights
    Xs, ys, ws = pad_to_bucket(X, y, rows=5)
    assert np.array_equal(Xs, X) and np.all(ws == 1.0)

    with pytest.raises(ValueError):
        pad_to_bucket(X, y, rows=3)
    with pytest.raises(ValueError):
        pad_to_bucket(X[:0], y[:0], rows=4)
    with pytest.raises(ValueError):
        pad_to_bucket(X, y, rows=8, fill="zeros")


def test_scheduler_grouping_and_capacity():
    assert packable(None) and packable({}) and packable({"maxsize": 8})
    assert not packable({"batching": True})

    k1 = pack_group_key((256, 2, 1), {"a": 1, "b": 2})
    k2 = pack_group_key((256, 2, 1), {"b": 2, "a": 1})
    assert k1 == k2  # canonical: insertion order must not matter
    assert k1 != pack_group_key((512, 2, 1), {"a": 1, "b": 2})
    assert k1 != pack_group_key((256, 2, 1), {"a": 1})

    pol = PackPolicy(max_tenants=4)
    assert slot_cap(pol, None) == 4
    assert slot_cap(pol, {}) == 4  # advisory absent -> policy cap
    assert slot_cap(
        pol, {"predicted_bytes": 100, "headroom_bytes": 250}) == 3
    assert slot_cap(
        pol, {"predicted_bytes": 100, "headroom_bytes": -50}) == 1
    assert slot_cap(
        pol, {"predicted_bytes": 1, "headroom_bytes": 10**9}) == 4
    assert slot_cap(pol, {"predicted_bytes": None}) == 4


# ------------------------------------------------- kernel bit-identity


def test_padded_rows_leave_kernel_loss_bit_identical():
    """Zero-weight replica rows must not move the fused kernel's
    per-tree loss sums by a single bit, nor flip any validity bit —
    the foundation of the packed-tenancy bit-identity contract."""
    from symbolicregression_jl_tpu.core.losses import l2_dist_loss
    from symbolicregression_jl_tpu.evolve.step import (
        evolve_config_from_options)
    from symbolicregression_jl_tpu.ops.encoding import encode_population
    from symbolicregression_jl_tpu.ops.fused_eval import fused_loss

    opts = sr.Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "exp"],
        maxsize=12, save_to_file=False,
    )
    cfg = evolve_config_from_options(opts, 2)
    opset = cfg.operators
    X, y = _problem(100)
    Xp, yp, w = pad_to_bucket(X, y, rows=256)

    exprs = [
        sr.parse_expression("cos(2.13 * x1) + 0.5 * x2", opset),
        sr.parse_expression("x1 * x2 - exp(x2 / 2.0)", opset),
        sr.parse_expression("x1 / (x1 - x1)", opset),  # 1/0 -> invalid
        sr.parse_expression("1.5", opset),
    ]
    batch = encode_population(exprs, opts.maxsize, opset)
    base_l, base_v = fused_loss(
        batch, X.T, y, None, opset, l2_dist_loss, interpret=True)
    for fill in ("cyclic", "edge"):
        Xf, yf, wf = pad_to_bucket(X, y, rows=256, fill=fill)
        pad_l, pad_v = fused_loss(
            batch, Xf.T, yf, wf, opset, l2_dist_loss, interpret=True)
        assert np.array_equal(np.asarray(base_v), np.asarray(pad_v)), fill
        assert np.array_equal(
            np.asarray(base_l), np.asarray(pad_l)), fill


@pytest.mark.slow
def test_pad_content_invariance_full_search():
    """Two different pad fills (cyclic vs edge replicas) must produce a
    bit-identical full search: if pad VALUES could influence any part
    of the search — loss, gradients, validity, baselines — the two
    fills would diverge."""
    X, y = _problem(100)
    fps = []
    for fill in ("cyclic", "edge"):
        Xp, yp, w = pad_to_bucket(X, y, rows=256, fill=fill)
        state, _hof = equation_search(
            Xp, yp, weights=w, options=sr.Options(**_options(),
                                                  save_to_file=False),
            niterations=2, seed=7, verbosity=0, return_state=True)
        fps.append(result_fingerprint(state))
    assert fps[0] == fps[1]


# --------------------------------------------------- packed-vs-solo


def _solo_fingerprint(root, X, y, niter, seed, rid):
    """The contract's 'solo run': the SAME pack-enabled server config
    with only this request — a cohort of one, identical padding."""
    srv = SearchServer(str(root), capacity=4, workers=1,
                       pack=PackPolicy())
    srv.submit(X, y, options=_options(), niterations=niter, seed=seed,
               request_id=rid)
    srv.start()
    try:
        snap = srv.wait(rid, timeout=600)
    finally:
        srv.stop(drain=True)
    assert snap["state"] == "done", snap
    return snap["result"]["fingerprint"]


@pytest.mark.slow
def test_packed_two_tenants_bit_identical_to_solo(tmp_path):
    tenants = [  # mixed rows AND niterations: peel-off mid-flight
        dict(rows=100, niter=2, seed=11),
        dict(rows=120, niter=3, seed=22),
    ]
    datas = [_problem(t["rows"], seed=i) for i, t in enumerate(tenants)]

    root = str(tmp_path / "packed")
    srv = SearchServer(root, capacity=4, workers=1, pack=PackPolicy())
    rids = []
    for i, (t, (X, y)) in enumerate(zip(tenants, datas)):
        rids.append(srv.submit(
            X, y, options=_options(), niterations=t["niter"],
            seed=t["seed"], request_id=f"tenant-{i}"))
    srv.start()  # both queued before the worker runs -> one cohort
    try:
        packed = {rid: srv.wait(rid, timeout=600) for rid in rids}
    finally:
        srv.stop(drain=True)
    assert srv.admission.depth == 0  # no leaked capacity
    for rid in rids:
        assert packed[rid]["state"] == "done", packed[rid]
        assert packed[rid]["pad_rows"] > 0  # really ran padded

    # the launch was genuinely multi-tenant, not two solo runs
    events = load_events(os.path.join(root, "serve_telemetry.jsonl"))
    launches = [e for e in events if e.get("kind") == "pack_launch"]
    assert any(len((e.get("detail") or {}).get("tenants", [])) == 2
               for e in launches), launches
    peels = [e for e in events if e.get("kind") == "pack_peel"]
    assert len(peels) == 2

    for i, (t, (X, y)) in enumerate(zip(tenants, datas)):
        fp = _solo_fingerprint(tmp_path / f"solo{i}", X, y,
                               t["niter"], t["seed"], f"tenant-{i}")
        assert packed[rids[i]]["result"]["fingerprint"] == fp, (
            f"tenant-{i}: packed result differs from solo run")


def test_journal_provenance_roundtrip(tmp_path):
    """bucket_rows/pad_rows are journaled effective configuration:
    a replaying server reads them back (never re-derives from its own
    pack setting) and the report audits them per request."""
    X, y = _problem(100)
    root = str(tmp_path / "root")
    srv = SearchServer(root, capacity=4, workers=0, pack=PackPolicy())
    rid = srv.submit(X, y, options=_options(), niterations=2, seed=1)
    snap = srv.poll(rid)
    assert snap["bucket_rows"] == 256 and snap["pad_rows"] == 156

    # a recovered server WITHOUT pack still carries the provenance —
    # the padded search is the journaled request's meaning
    recovered = SearchServer(root, capacity=4, workers=0)
    rsnap = recovered.poll(rid)
    assert rsnap["bucket_rows"] == 256 and rsnap["pad_rows"] == 156

    # batching=True requests are not packable: no padding recorded
    rid2 = srv.submit(X, y, options=_options(batching=True,
                                             batch_size=32),
                      niterations=2, seed=2)
    snap2 = srv.poll(rid2)
    assert snap2["bucket_rows"] == 0 and snap2["pad_rows"] == 0

    # report audit: the accept event carries the padding block
    summary = summarize(load_events(
        os.path.join(root, "serve_telemetry.jsonl")))
    pad = summary["requests"][rid]["padding"]
    assert pad["bucket_rows"] == 256 and pad["pad_rows"] == 156
    assert summary["requests"][rid2]["padding"] is None


@pytest.mark.slow
def test_packed_four_tenants_bit_identical_to_solo(tmp_path):
    tenants = [
        dict(rows=100, niter=2, seed=11),
        dict(rows=110, niter=4, seed=22),
        dict(rows=120, niter=3, seed=33),
        dict(rows=130, niter=2, seed=44),
    ]
    datas = [_problem(t["rows"], seed=i) for i, t in enumerate(tenants)]

    root = str(tmp_path / "packed")
    srv = SearchServer(root, capacity=8, workers=1, pack=PackPolicy())
    rids = []
    for i, (t, (X, y)) in enumerate(zip(tenants, datas)):
        rids.append(srv.submit(
            X, y, options=_options(), niterations=t["niter"],
            seed=t["seed"], request_id=f"tenant-{i}"))
    srv.start()
    try:
        packed = {rid: srv.wait(rid, timeout=600) for rid in rids}
    finally:
        srv.stop(drain=True)
    assert srv.admission.depth == 0
    events = load_events(os.path.join(root, "serve_telemetry.jsonl"))
    launches = [e for e in events if e.get("kind") == "pack_launch"]
    assert any(len((e.get("detail") or {}).get("tenants", [])) >= 2
               for e in launches)

    for i, (t, (X, y)) in enumerate(zip(tenants, datas)):
        assert packed[rids[i]]["state"] == "done"
        fp = _solo_fingerprint(tmp_path / f"solo{i}", X, y,
                               t["niter"], t["seed"], f"tenant-{i}")
        assert packed[rids[i]]["result"]["fingerprint"] == fp, (
            f"tenant-{i}: packed result differs from solo run")


@pytest.mark.slow
def test_packed_preempt_restart_replay_bit_identity(tmp_path):
    """Kill (in-process preempt) a PACKED server mid-cohort; the
    restarted server must finish every tenant bit-identical to an
    unkilled packed server over the same requests."""
    tenants = [
        dict(rows=100, niter=4, seed=5),
        dict(rows=120, niter=4, seed=7),
    ]
    datas = [_problem(t["rows"], seed=i) for i, t in enumerate(tenants)]

    def _submit_all(srv):
        return [
            srv.submit(X, y, options=_options(), niterations=t["niter"],
                       seed=t["seed"], request_id=f"tenant-{i}")
            for i, (t, (X, y)) in enumerate(zip(tenants, datas))
        ]

    ref_root = str(tmp_path / "ref")
    srv = SearchServer(ref_root, capacity=4, workers=1,
                       pack=PackPolicy())
    rids = _submit_all(srv)
    srv.start()
    ref = {}
    try:
        for rid in rids:
            ref[rid] = srv.wait(rid, timeout=600)
            assert ref[rid]["state"] == "done"
    finally:
        srv.stop(drain=True)

    kill_root = str(tmp_path / "kill")
    srv = SearchServer(kill_root, capacity=4, workers=1,
                       pack=PackPolicy())
    rids = _submit_all(srv)
    srv.start()
    ck = os.path.join(kill_root, "requests", rids[0], rids[0],
                      "search_state.pkl")
    deadline = time.monotonic() + 300
    while not os.path.exists(ck) and time.monotonic() < deadline:
        time.sleep(0.05)
    srv.stop(drain=False)
    states = {rid: srv.poll(rid)["state"] for rid in rids}
    assert any(s != "done" for s in states.values()), states

    # restart: interrupted tenants resume from checkpoints, padding
    # read back from the journal, cohort re-forms from the queue
    srv.start()
    try:
        for rid in rids:
            snap = srv.wait(rid, timeout=600)
            assert snap["state"] == "done", snap
            assert snap["result"]["fingerprint"] == (
                ref[rid]["result"]["fingerprint"]
            ), f"{rid}: resumed packed result differs from unkilled run"
    finally:
        srv.stop(drain=True)
    assert srv.admission.depth == 0
