"""graftshield recovery paths, pinned via the fault-injection harness.

The headline contract (ISSUE 9 acceptance): kill -TERM mid-search, then
``equation_search(resume="auto")`` → final hall of fame **bit-identical**
to the uninterrupted run. Plus: watchdog deadlines fire with a
diagnostic dump, transient failures retry with backoff, OOM-shaped
failures step the eval launch geometry down, and a NaN-storm-collapsed
island is quarantined and reseeded from the hall of fame.
"""

import json
import os

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options, equation_search
from symbolicregression_jl_tpu.api.search import RuntimeOptions
from symbolicregression_jl_tpu.shield import faults
from symbolicregression_jl_tpu.shield.degrade import (
    ShieldRunner,
    is_transient_failure,
)
from symbolicregression_jl_tpu.shield.watchdog import Watchdog, WatchdogTimeout


def _problem(n=200, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, (n, 2)).astype(np.float32)
    y = (2.0 * X[:, 0] + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(tmp_path, **kw):
    # Same shapes as tests/test_checkpoint.py (shared compile cache).
    base = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=[],
        maxsize=10,
        populations=2,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=4,
        save_to_file=True,
        output_directory=str(tmp_path),
    )
    base.update(kw)
    return Options(**base)


@pytest.fixture(autouse=True)
def _clear_injector():
    yield
    faults.clear()


def _faults_in(run_dir):
    path = os.path.join(run_dir, "telemetry.jsonl")
    with open(path) as f:
        return [json.loads(l) for l in f if '"fault"' in l]


# ---------------------------------------------------------------------------
# preemption: SIGTERM -> emergency checkpoint -> resume bit-identical
# ---------------------------------------------------------------------------


@pytest.mark.slow  # 3 full searches; CI replays this exact scenario in
# the fault-injection-smoke job (tools/fault_smoke.py scenario 1)
def test_sigterm_resume_auto_bit_identical(tmp_path):
    X, y = _problem()
    ropt = lambda run_id, seed=7: RuntimeOptions(  # noqa: E731
        niterations=4, run_id=run_id, seed=seed, verbosity=0)

    # A: uninterrupted 4-iteration reference
    dir_a = tmp_path / "a"
    sA, _ = equation_search(
        X, y, options=_options(dir_a), runtime_options=ropt("ref"),
        return_state=True)

    # B: a real SIGTERM lands at the end of iteration 2 -> graceful stop
    dir_b = tmp_path / "b"
    faults.install(faults.FaultInjector(
        faults.FaultPlan(sigterm_at_iteration=2)))
    equation_search(X, y, options=_options(dir_b, telemetry=True),
                    runtime_options=ropt("pre"))
    faults.clear()
    evs = _faults_in(os.path.join(dir_b, "pre"))
    kinds = {e["kind"] for e in evs}
    assert {"injected", "preempt_signal", "emergency_checkpoint"} <= kinds
    tel = [json.loads(l) for l in open(
        os.path.join(dir_b, "pre", "telemetry.jsonl"))]
    end = next(e for e in tel if e["event"] == "run_end")
    assert end["stop_reason"] == "preempted"
    assert end["iterations"] == 2

    # C: resume="auto" discovers B's checkpoint, runs iterations 3..4
    sC, _ = equation_search(
        X, y, options=_options(dir_b), resume="auto",
        runtime_options=ropt("res", seed=99),  # seed must NOT matter
        return_state=True)
    assert sC.iterations_done == 4

    a0, c0 = sA.device_states[0], sC.device_states[0]
    for f in ("arity", "op", "feat", "const", "length"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a0.hof.trees, f)),
            np.asarray(getattr(c0.hof.trees, f)), err_msg=f"hof {f}")
    np.testing.assert_array_equal(np.asarray(a0.hof.cost),
                                  np.asarray(c0.hof.cost))
    np.testing.assert_array_equal(np.asarray(a0.pops.cost),
                                  np.asarray(c0.pops.cost))
    assert sC.num_evals == pytest.approx(sA.num_evals, rel=1e-6)


def test_resume_auto_without_checkpoint_starts_fresh(tmp_path, capsys):
    X, y = _problem()
    hof = equation_search(
        X, y, options=_options(tmp_path / "empty", save_to_file=False),
        resume="auto",
        runtime_options=RuntimeOptions(niterations=1, seed=0, verbosity=1),
    )
    assert len(hof.entries) > 0
    assert "starting fresh" in capsys.readouterr().out


def test_resume_and_saved_state_are_mutually_exclusive(tmp_path):
    X, y = _problem()
    with pytest.raises(ValueError, match="not both"):
        equation_search(
            X, y, options=_options(tmp_path, save_to_file=False),
            resume="auto", saved_state="whatever.pkl",
            runtime_options=RuntimeOptions(niterations=1, verbosity=0),
        )


# ---------------------------------------------------------------------------
# retry / degradation
# ---------------------------------------------------------------------------


@pytest.mark.slow  # full-search variant; the retry/degrade mechanics are
# pinned fast by test_retry_exhaustion_degrades_eval_tile_rows below
def test_transient_dispatch_failure_retries_and_recovers(tmp_path):
    X, y = _problem()
    faults.install(faults.FaultInjector(
        faults.FaultPlan(raise_on_dispatch=2)))
    hof = equation_search(
        X, y,
        options=_options(tmp_path, telemetry=True, retry_backoff=0.01),
        runtime_options=RuntimeOptions(
            niterations=2, run_id="retry", seed=1, verbosity=0),
    )
    assert len(hof.entries) > 0
    evs = _faults_in(os.path.join(tmp_path, "retry"))
    retries = [e for e in evs if e["kind"] == "retry"]
    assert len(retries) == 1
    assert retries[0]["detail"]["attempt"] == 1


def test_nontransient_failure_raises_immediately(tmp_path):
    X, y = _problem()
    faults.install(faults.FaultInjector(faults.FaultPlan(
        raise_on_dispatch=1,
        raise_message="INVALID_ARGUMENT: genuinely broken")))
    with pytest.raises(faults.InjectedFault, match="INVALID_ARGUMENT"):
        equation_search(
            X, y, options=_options(tmp_path, save_to_file=False),
            runtime_options=RuntimeOptions(niterations=1, seed=1,
                                           verbosity=0),
        )


def test_retry_exhaustion_degrades_eval_tile_rows():
    from symbolicregression_jl_tpu import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine

    X, y = _problem(64)
    opts = Options(binary_operators=["+", "*"], unary_operators=[],
                   maxsize=8, populations=2, population_size=8,
                   tournament_selection_n=4, ncycles_per_iteration=2,
                   eval_tile_rows=2048, save_to_file=False)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(opts.elementwise_loss)
    engine = Engine(opts, ds.nfeatures)

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 3:  # 1 try + 2 retries all OOM -> degrade
            raise RuntimeError("RESOURCE_EXHAUSTED: injected")
        return "ok"

    runner = ShieldRunner(max_retries=2, backoff=0.0)
    assert runner.run(flaky, engine=engine) == "ok"
    assert runner.retries_total == 2
    assert runner.degrades_total == 1
    assert engine.cfg.eval_tile_rows == 1024

    # Ladder floor: a persistent OOM eventually surfaces.
    runner2 = ShieldRunner(max_retries=0, backoff=0.0)

    def always_oom():
        raise RuntimeError("RESOURCE_EXHAUSTED: injected")

    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        runner2.run(always_oom, engine=engine)
    assert engine.cfg.eval_tile_rows == 512  # degraded to the floor first


def test_transient_classifier():
    assert is_transient_failure(RuntimeError("RESOURCE_EXHAUSTED: oom"))
    assert is_transient_failure(RuntimeError("UNAVAILABLE: link down"))
    assert is_transient_failure(
        RuntimeError("Failed to deserialize cache entry"))
    assert not is_transient_failure(RuntimeError("INVALID_ARGUMENT: shape"))
    assert not is_transient_failure(
        RuntimeError("Array has been deleted (donated)"))


# ---------------------------------------------------------------------------
# quarantine
# ---------------------------------------------------------------------------


def test_nan_storm_island_is_quarantined(tmp_path):
    X, y = _problem()
    faults.install(faults.FaultInjector(
        faults.FaultPlan(nan_poison_island=(0, 1))))
    state, hof = equation_search(
        X, y, options=_options(tmp_path, telemetry=True),
        runtime_options=RuntimeOptions(
            niterations=3, run_id="qrt", seed=1, verbosity=0),
        return_state=True)
    evs = _faults_in(os.path.join(tmp_path, "qrt"))
    q = [e for e in evs if e["kind"] == "quarantine"]
    assert q and q[0]["detail"]["islands"] == [0]
    # The reseeded island is alive again: finite members exist and the
    # search kept going to the target.
    loss = np.asarray(state.device_states[0].pops.loss)
    assert np.isfinite(loss[0]).mean() > 0.5
    assert len(hof.entries) > 0


@pytest.mark.slow  # negative-control search; the positive quarantine
# path stays in the fast tier above
def test_quarantine_off_leaves_storm_alone(tmp_path):
    X, y = _problem()
    faults.install(faults.FaultInjector(
        faults.FaultPlan(nan_poison_island=(0, 2))))
    state, _ = equation_search(
        X, y,
        options=_options(tmp_path, save_to_file=False,
                         island_quarantine=False),
        runtime_options=RuntimeOptions(niterations=2, seed=1, verbosity=0),
        return_state=True)
    loss = np.asarray(state.device_states[0].pops.loss)
    assert not np.isfinite(loss[0]).any()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------


def test_watchdog_fires_with_diagnostic_dump(tmp_path):
    import time

    dumps = []
    wd = Watchdog(on_timeout=dumps.append, poll_interval=0.02,
                  dump_path=str(tmp_path / "dump.txt"))
    with pytest.raises(WatchdogTimeout, match="hang-phase"):
        with wd.phase("hang-phase", budget=0.05, iteration=3):
            time.sleep(0.5)
    wd.stop()
    assert len(dumps) == 1
    dump = dumps[0]
    assert "hang-phase" in dump and "iteration  : 3" in dump
    assert "(main)" in dump  # the blocked thread's stack is attributed
    assert "test_watchdog_fires_with_diagnostic_dump" in dump
    assert os.path.exists(tmp_path / "dump.txt")


def test_watchdog_quiet_within_budget():
    wd = Watchdog(on_timeout=lambda d: pytest.fail("fired"),
                  poll_interval=0.02)
    for i in range(3):
        with wd.phase("fast", budget=5.0, iteration=i):
            pass
    wd.stop()
    assert not wd.fired


def test_watchdog_unbudgeted_phase_is_noop():
    import time

    wd = Watchdog(on_timeout=lambda d: pytest.fail("fired"))
    with wd.phase("unsupervised", budget=None):
        time.sleep(0.05)
    wd.stop()


# ---------------------------------------------------------------------------
# signals / plan plumbing
# ---------------------------------------------------------------------------


def test_preemption_guard_sets_flag_and_restores_handlers():
    import signal

    from symbolicregression_jl_tpu.shield.signals import PreemptionGuard

    before = signal.getsignal(signal.SIGTERM)
    with PreemptionGuard() as g:
        assert g.installed
        assert not g.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert g.requested
        assert g.signal_name == "SIGTERM"
    assert signal.getsignal(signal.SIGTERM) is before


def test_preemption_guard_refcounted_nesting():
    """Nested/concurrent guards in one process (the multi-tenant serve
    worker case): inner install/uninstall must not clobber the outer
    handlers; one signal is observed by every attached guard; the LAST
    detach restores the original handlers."""
    import signal

    from symbolicregression_jl_tpu.shield.signals import PreemptionGuard

    before = signal.getsignal(signal.SIGTERM)
    outer = PreemptionGuard().install()
    ours = signal.getsignal(signal.SIGTERM)
    inner = PreemptionGuard().install()
    assert signal.getsignal(signal.SIGTERM) is ours  # not re-wrapped
    inner.uninstall()
    assert signal.getsignal(signal.SIGTERM) is ours  # outer still live
    inner2 = PreemptionGuard().install()
    os.kill(os.getpid(), signal.SIGTERM)
    assert outer.requested and inner2.requested  # shared observation
    inner2.uninstall()
    assert outer.requested  # flag survives a partial detach
    outer.uninstall()
    assert signal.getsignal(signal.SIGTERM) is before
    # a fresh attach cycle starts clean (no stale preempt flag)
    with PreemptionGuard() as g:
        assert not g.requested


def test_preemption_guard_worker_thread_observes_main_install():
    """A guard attached from a worker thread (where Python forbids
    signal.signal) still sees a signal captured by the main thread's
    installation — how a search inside a serve worker learns about the
    server's SIGTERM."""
    import signal
    import threading

    from symbolicregression_jl_tpu.shield.signals import PreemptionGuard

    seen = {}

    def worker(ready, fired):
        g = PreemptionGuard().install()
        seen["installed_handlers"] = g.installed
        ready.set()
        fired.wait(timeout=5)
        seen["requested"] = g.requested
        g.uninstall()

    with PreemptionGuard():
        ready, fired = threading.Event(), threading.Event()
        t = threading.Thread(target=worker, args=(ready, fired))
        t.start()
        assert ready.wait(timeout=5)
        os.kill(os.getpid(), signal.SIGTERM)
        fired.set()
        t.join(timeout=5)
    assert seen["requested"] is True


def test_unattended_signal_chains_to_original_disposition():
    """When the LAST detach runs on a worker thread, handler restore is
    deferred (Python forbids signal.signal off the main thread) — our
    handlers stay installed with zero guards attached. A signal landing
    in that window must NOT be silently swallowed by the flag-only
    handler: it restores the original disposition and re-delivers, so
    e.g. an operator's SIGINT/SIGTERM of an idle server still works."""
    import signal
    import threading
    import time

    import pytest

    from symbolicregression_jl_tpu.shield.signals import PreemptionGuard

    before = signal.getsignal(signal.SIGINT)
    g = PreemptionGuard().install()
    t = threading.Thread(target=g.uninstall)
    t.start()
    t.join(timeout=5)
    # deferred restore: our handler is still installed, nobody attached
    assert signal.getsignal(signal.SIGINT) is not before
    with pytest.raises(KeyboardInterrupt):
        os.kill(os.getpid(), signal.SIGINT)
        for _ in range(100):  # let the re-delivered signal land
            time.sleep(0.01)
    assert signal.getsignal(signal.SIGINT) is before
    # a fresh attach cycle after the chained restore starts clean
    with PreemptionGuard() as g2:
        assert not g2.requested
    assert signal.getsignal(signal.SIGINT) is before


def test_fault_plan_env_roundtrip(monkeypatch):
    plan = faults.FaultPlan(raise_on_dispatch=3, raise_count=2,
                            nan_poison_island=(1, 4))
    text = json.dumps({
        "raise_on_dispatch": 3, "raise_count": 2,
        "nan_poison_island": [1, 4],
    })
    assert faults.FaultPlan.from_json(text) == plan
    monkeypatch.setenv("SR_FAULT_PLAN", text)
    inj = faults.active_injector()
    assert inj is not None and inj.plan == plan
