"""`telemetry report` per-request view (docs/SERVING.md): graftscope.v1
records grouped by run_id/request_id, serve-event aggregates, and the
executable-cache hit-rate accounting — over a hand-built two-request
journal fixture (no jax involved)."""

import json

from symbolicregression_jl_tpu.telemetry.report import (
    format_report,
    summarize,
    summarize_requests,
)
from symbolicregression_jl_tpu.telemetry.schema import (
    SCHEMA_VERSION,
    validate_lines,
)


def _ev(event, t, **fields):
    return {"schema": SCHEMA_VERSION, "t": t, "event": event, **fields}


def _serve(t, kind, rid, **detail):
    return _ev("serve", t, kind=kind, request_id=rid, detail=detail)


def _fixture_events():
    """A serve stream for two requests: req-a completes with a cache
    miss; req-b completes with a cache hit after a retry fault."""
    return [
        _serve(1.0, "accept", "req-a", bucket=[256, 2, 1], priority=0),
        _serve(1.1, "accept", "req-b", bucket=[256, 2, 1], priority=0),
        _serve(1.2, "start", "req-a"),
        _serve(1.3, "cache_miss", "req-a", bucket=[256, 2, 1]),
        _serve(5.0, "done", "req-a"),
        _serve(5.1, "start", "req-b"),
        _serve(5.2, "cache_hit", "req-b", bucket=[256, 2, 1]),
        _ev("fault", 6.0, kind="retry", iteration=2,
            detail={"request_id": "req-b", "attempt": 1}),
        _serve(8.0, "done", "req-b"),
        _serve(9.0, "reject", "req-c", retry_after_s=5.0,
               queue_depth=2),
    ]


def test_fixture_validates_against_schema():
    lines = [json.dumps(e) for e in _fixture_events()]
    assert validate_lines(lines) == []


def test_requests_grouped_by_request_id():
    groups = summarize_requests(_fixture_events())
    assert set(groups) == {"req-a", "req-b", "req-c"}
    a, b = groups["req-a"], groups["req-b"]
    assert a["state"] == "done" and b["state"] == "done"
    assert a["serve"] == {"accept": 1, "start": 1, "cache_miss": 1,
                          "done": 1}
    assert b["serve"]["cache_hit"] == 1
    # the fault event reaches its request through detail.request_id
    assert b["faults"] == {"retry": 1}
    assert a["span_s"] == 4.0
    assert groups["req-c"]["serve"] == {"reject": 1}


def test_summary_serve_section_and_cache_hit_rate():
    summary = summarize(_fixture_events())
    sv = summary["serve"]
    assert sv["accepted"] == 2 and sv["rejected"] == 1
    assert sv["cache"] == {
        "hits": 1, "misses": 1, "hit_rate": 0.5,
        "by_bucket": {"[256, 2, 1]": {"hits": 1, "misses": 1,
                                      "hit_rate": 0.5}},
    }
    assert set(summary["requests"]) == {"req-a", "req-b", "req-c"}


def test_format_report_renders_per_request_lines():
    text = format_report(summarize(_fixture_events()))
    assert "serve: 2 accepted, 1 rejected" in text
    assert "requests: 3" in text
    assert "req-a: done" in text
    assert "req-b: done" in text
    assert "cache-hit" in text
    assert "faults[retry=1]" in text


def test_plain_search_stream_groups_by_run_id():
    """Concatenated per-search streams (run_id on every event, hub.py)
    group per run even without serve events."""
    events = []
    for rid, n in (("run-1", 2), ("run-2", 3)):
        for i in range(1, n + 1):
            events.append(_ev(
                "iteration", float(i), run_id=rid, iteration=i,
                num_evals=100.0 * i, evals_per_sec=1.0, elapsed_s=1.0,
                device_s=0.5, host_s=0.1, host_fraction=0.1,
                recompiles={"traces": 0, "backend_compiles": 0},
                transfer_guard_hits=0, outputs=[]))
        events.append(_ev(
            "run_end", 99.0, run_id=rid, stop_reason="niterations",
            iterations=n, num_evals=100.0 * n, elapsed_s=9.0,
            recompiles_total={}))
    summary = summarize(events)
    groups = summary["requests"]
    assert set(groups) == {"run-1", "run-2"}
    assert groups["run-1"]["iterations"] == 2
    assert groups["run-2"]["iterations"] == 3
    assert groups["run-2"]["stop_reason"] == "niterations"


def test_single_run_stream_has_no_requests_section():
    events = [_ev(
        "iteration", 1.0, run_id="solo", iteration=1, num_evals=1.0,
        evals_per_sec=1.0, elapsed_s=1.0, device_s=0.5, host_s=0.1,
        host_fraction=0.1,
        recompiles={"traces": 0, "backend_compiles": 0},
        transfer_guard_hits=0, outputs=[])]
    assert "requests" not in summarize(events)


def test_mixed_v1_v2_directory_groups_by_trace_then_ids():
    """A directory holding pre-graftledger (v1, no trace) runs next to
    v2 runs still groups every event: v2 events join on trace_id even
    when their human ids differ (serve stream request_id vs search
    stream run_id), v1 events fall back to request_id/run_id, and the
    group keys stay human-readable."""
    trace = {"trace_id": "a" * 32, "span_id": "b" * 16,
             "parent_id": None}
    events = [
        # v2 request: serve events carry request_id, the search stream
        # a DIFFERENT run_id — only the shared trace joins them
        _serve(1.0, "accept", "req-new"),
        {**_ev("iteration", 2.0, run_id="run-of-req-new", iteration=1,
               num_evals=10.0, evals_per_sec=1.0, elapsed_s=1.0,
               device_s=0.5, host_s=0.1, host_fraction=0.1,
               recompiles={"traces": 0, "backend_compiles": 0},
               transfer_guard_hits=0, outputs=[]),
         "trace": trace},
        _serve(3.0, "done", "req-new"),
        # v1 request: no trace field at all, old schema string
        {"schema": "graftscope.v1", "t": 4.0, "event": "serve",
         "kind": "accept", "request_id": "req-old", "detail": {}},
        {"schema": "graftscope.v1", "t": 5.0, "event": "serve",
         "kind": "done", "request_id": "req-old", "detail": {}},
    ]
    events[0]["trace"] = trace
    events[2]["trace"] = trace
    assert validate_lines([json.dumps(e) for e in events]) == []
    groups = summarize_requests(events)
    assert set(groups) == {"req-new", "req-old"}
    new = groups["req-new"]
    # the search stream's iteration folded into the serve group
    assert new["iterations"] == 1
    assert new["serve"] == {"accept": 1, "done": 1}
    assert new["trace_id"] == "a" * 32
    assert groups["req-old"]["trace_id"] is None
