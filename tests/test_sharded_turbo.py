"""Turbo (fused Pallas kernels) under an island-sharded mesh.

Round-3 verdict Missing #2: the fused eval path had never compiled
under a sharded mesh — `pl.pallas_call` has no GSPMD partitioning rule,
so the engine now runs its island-local phases (cycles, fold, constant
optimizer, finalize) inside `shard_map` over the island axis when
turbo is on and the island axis is sharded (engine._shard_islands).

These tests force turbo=True on the virtual 8-device CPU mesh
(interpret-mode kernels) and pin the strongest property available
without real multi-chip hardware: with the constant optimizer off, the
island-sharded shard_map run is BIT-IDENTICAL to the unsharded turbo
run (all RNG is drawn island-major before the shard boundary; no
cross-island ops exist inside the shard_map regions).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu import Options, search_key
from symbolicregression_jl_tpu.core.dataset import make_dataset
from symbolicregression_jl_tpu.evolve.engine import Engine
from symbolicregression_jl_tpu.parallel.mesh import (
    make_mesh,
    shard_search_state,
)

I = 8  # islands == devices


def _problem():
    rng = np.random.default_rng(7)
    X = rng.uniform(-2, 2, (64, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 0]).astype(np.float32)
    return X, y


def _options(**kw):
    base = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=10,
        populations=I,
        population_size=8,
        ncycles_per_iteration=3,
        tournament_selection_n=4,
        turbo=True,           # force the Pallas (interpret-mode) path
        save_to_file=False,
    )
    base.update(kw)
    return Options(**base)


def _run(options, n_island_shards, n_iters=2):
    X, y = _problem()
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    mesh = None
    if n_island_shards > 1:
        mesh = make_mesh(jax.devices()[:I], n_island_shards=n_island_shards)
    engine = Engine(options, ds.nfeatures,
                    n_island_shards=n_island_shards, mesh=mesh)
    assert engine.cfg.turbo, "test must exercise the fused path"
    state = engine.init_state(search_key(11), ds.data, I)
    if mesh is not None:
        assert engine._shard_islands
        state = shard_search_state(state, mesh)
    for _ in range(n_iters):
        out = engine.run_iteration(state, ds.data, options.maxsize)
        state = out[0] if isinstance(out, tuple) else out
    return jax.device_get(state)


def test_sharded_turbo_smoke_fast():
    """Fast-tier canary (round-4 verdict Weak #5): the flagship
    composition — Pallas (interpret) kernels inside shard_map over the
    island axis — must compile and run ONE tiny iteration in the
    default ``-m "not slow"`` loop, so regressions surface before the
    once-per-round slow run. The slow tier below carries the
    bit-exactness pair."""
    options = _options(maxsize=8, population_size=6,
                       ncycles_per_iteration=2, tournament_selection_n=3,
                       optimizer_probability=0.0)
    s = _run(options, I, n_iters=1)
    assert np.isfinite(np.asarray(s.pops.cost)).any()
    assert float(s.num_evals) > 0


@pytest.mark.slow
def test_sharded_turbo_bit_identical_to_unsharded():
    """No optimizer: the shard_map turbo iteration must produce the
    exact state the unsharded turbo iteration does."""
    options = _options(optimizer_probability=0.0)
    s1 = _run(options, 1)
    s8 = _run(options, I)
    np.testing.assert_array_equal(np.asarray(s1.pops.cost),
                                  np.asarray(s8.pops.cost))
    np.testing.assert_array_equal(np.asarray(s1.pops.trees.op),
                                  np.asarray(s8.pops.trees.op))
    np.testing.assert_array_equal(np.asarray(s1.pops.trees.const),
                                  np.asarray(s8.pops.trees.const))
    np.testing.assert_array_equal(np.asarray(s1.hof.cost),
                                  np.asarray(s8.hof.cost))
    assert float(s1.num_evals) == float(s8.num_evals)


def _run_template(options, spec, n_island_shards, n_iters=1):
    X, y = _problem()
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    mesh = None
    if n_island_shards > 1:
        mesh = make_mesh(jax.devices()[:I], n_island_shards=n_island_shards)
    engine = Engine(options, ds.nfeatures, template=spec.structure,
                    n_island_shards=n_island_shards, mesh=mesh)
    assert engine.cfg.turbo, "template turbo must survive island sharding"
    state = engine.init_state(search_key(11), ds.data, I)
    if mesh is not None:
        assert engine._shard_islands
        state = shard_search_state(state, mesh)
    for _ in range(n_iters):
        out = engine.run_iteration(state, ds.data, options.maxsize)
        state = out[0] if isinstance(out, tuple) else out
    return jax.device_get(state)


@pytest.mark.slow
def test_sharded_turbo_template_bit_identical():
    """Round-4 verdict item 8: template searches keep the fused path
    under island sharding. With the optimizer off, the island-sharded
    shard_map run must be bit-identical to the unsharded turbo run."""
    from symbolicregression_jl_tpu.models import template_spec

    spec = template_spec(expressions=("f", "g"))(
        lambda f, g, x1, x2: f(x1) + g(x2))
    options = _options(optimizer_probability=0.0, expression_spec=spec)
    s1 = _run_template(options, spec, 1)
    s8 = _run_template(options, spec, I)
    np.testing.assert_array_equal(np.asarray(s1.pops.cost),
                                  np.asarray(s8.pops.cost))
    np.testing.assert_array_equal(np.asarray(s1.pops.trees.op),
                                  np.asarray(s8.pops.trees.op))
    np.testing.assert_array_equal(np.asarray(s1.pops.trees.const),
                                  np.asarray(s8.pops.trees.const))
    assert float(s1.num_evals) == float(s8.num_evals)


def _run_parametric(options, n_island_shards, n_iters=1):
    rng = np.random.default_rng(7)
    X = rng.uniform(-2, 2, (64, 2)).astype(np.float32)
    cls = rng.integers(0, 2, 64)
    y = (X[:, 0] * X[:, 1] + np.where(cls == 0, 0.5, -0.25)).astype(
        np.float32)
    ds = make_dataset(X, y, extra={"class": cls})
    ds.update_baseline_loss(options.elementwise_loss)
    mesh = None
    if n_island_shards > 1:
        mesh = make_mesh(jax.devices()[:I], n_island_shards=n_island_shards)
    engine = Engine(options, ds.nfeatures, n_params=1,
                    n_classes=ds.n_classes,
                    n_island_shards=n_island_shards, mesh=mesh)
    assert engine.cfg.turbo, "parametric turbo must survive island sharding"
    state = engine.init_state(search_key(11), ds.data, I)
    if mesh is not None:
        assert engine._shard_islands
        state = shard_search_state(state, mesh)
    for _ in range(n_iters):
        out = engine.run_iteration(state, ds.data, options.maxsize)
        state = out[0] if isinstance(out, tuple) else out
    return jax.device_get(state)


@pytest.mark.slow
def test_sharded_turbo_parametric_bit_identical():
    """Parametric members (LEAF_PARAM on the fused kernel's buffer
    region) under island sharding: bit-identical to unsharded with the
    optimizer off, parameter banks sharding with the population."""
    options = _options(optimizer_probability=0.0)
    s1 = _run_parametric(options, 1)
    s8 = _run_parametric(options, I)
    np.testing.assert_array_equal(np.asarray(s1.pops.cost),
                                  np.asarray(s8.pops.cost))
    np.testing.assert_array_equal(np.asarray(s1.pops.params),
                                  np.asarray(s8.pops.params))
    np.testing.assert_array_equal(np.asarray(s1.pops.trees.const),
                                  np.asarray(s8.pops.trees.const))
    assert float(s1.num_evals) == float(s8.num_evals)


@pytest.mark.slow
def test_sharded_turbo_with_optimizer_runs_sane():
    """Optimizer on: the fused BFGS launches inside shard_map (its
    restart key is decorrelated per shard, so bit-equality is not
    expected) — the run must stay finite and improve the HoF."""
    options = _options(optimizer_probability=0.5)
    s8 = _run(options, I)
    cost = np.asarray(s8.pops.cost)
    assert np.isfinite(cost).mean() > 0.5
    hof_cost = np.asarray(s8.hof.cost)
    exists = np.asarray(s8.hof.exists)
    assert exists.any()
    assert np.isfinite(hof_cost[exists]).all()
    # evals were counted (cycles + finalize + optimizer f-calls)
    assert float(s8.num_evals) > I * 8
