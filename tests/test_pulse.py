"""graftpulse active diagnostics: flight recorder, anomaly detector,
triggered capture, live metrics, torn-tail tolerance.

Pins the contracts docs/OBSERVABILITY.md promises for the pulse layer:

- the new ``anomaly`` / ``pulse`` graftscope events validate (and the
  validator still rejects malformed ones);
- the flight recorder's ring is bounded, a real hub ``fault`` event
  triggers its dump, and the bundle's deterministic view is
  byte-stable across two identical fault-injected runs;
- the detector's z/absolute rules fire exactly when documented
  (log-space rate, warmup, cooldown, event budget, compile exclusion);
- capture windows respect budget + rate limit and a broken profiler
  disables them instead of failing the run;
- pulse on vs off is bit-neutral to the search;
- ``report`` tolerates a crash-torn final line but still refuses
  mid-file corruption; ``telemetry tail`` folds a live stream
  incrementally;
- serve's ``/metrics`` renders valid Prometheus text; ``bench trend``
  marks an otherwise-green gate artifact carrying anomalies as RED.
"""

import json
import os
import signal
import urllib.request

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options, equation_search
from symbolicregression_jl_tpu.api.search import RuntimeOptions
from symbolicregression_jl_tpu.pulse import (
    AnomalyDetector,
    AnomalyThresholds,
    BUNDLE_SCHEMA,
    FlightRecorder,
    PromText,
    SignalArm,
    TraceCapture,
    bundle_fingerprint,
    deterministic_view,
    validate_bundle,
)
from symbolicregression_jl_tpu.shield import faults
from symbolicregression_jl_tpu.telemetry.hub import Telemetry
from symbolicregression_jl_tpu.telemetry.report import main as report_main
from symbolicregression_jl_tpu.telemetry.schema import (
    load_events_tolerant,
    validate_event,
)
from symbolicregression_jl_tpu.telemetry.tail import TailFollower, TailState


@pytest.fixture(autouse=True)
def _clear_injector():
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# schema: the anomaly / pulse event kinds
# ---------------------------------------------------------------------------


def _base(event, **kw):
    e = {"schema": "graftscope.v1", "t": 1.0, "run_id": "r",
         "event": event}
    e.update(kw)
    return e


@pytest.mark.parametrize("event", [
    _base("anomaly", metric="evals_per_sec", iteration=3,
          detail={"value": 6.5, "mean": 4970.0, "zscore": -15.6,
                  "threshold": 4.0, "armed_capture": True}),
    _base("anomaly", metric="invalid_fraction", iteration=1,
          detail={"value": 1.0, "threshold": 0.5}),
    _base("pulse", kind="capture_stop", iteration=12,
          detail={"reason": "evals_per_sec", "trace_dir": "/x",
                  "iterations": 2, "files": 3, "bytes": 1}),
    _base("pulse", kind="bundle_dump", iteration=2,
          detail={"reason": "fault", "trigger_kind": "quarantine",
                  "path": "/x/pulse_bundle.json"}),
    _base("pulse", kind="profiler_unusable", iteration=0,
          detail={"error": "RuntimeError: nope"}),
])
def test_pulse_events_validate(event):
    assert validate_event(event) == []


@pytest.mark.parametrize("event,fragment", [
    (_base("anomaly", iteration=3, detail={}), "metric"),
    (_base("anomaly", metric="evals_per_sec", iteration="3", detail={}),
     "iteration"),
    (_base("pulse", iteration=1, detail={}), "kind"),
    (_base("pulse", kind="capture_start", iteration=1, detail=[]),
     "detail"),
])
def test_malformed_pulse_events_rejected(event, fragment):
    errors = validate_event(event)
    assert errors and any(fragment in e for e in errors), errors


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class _Ctx:
    """Minimal IterationContext stand-in for sink unit tests."""

    def __init__(self, iteration, *, num_evals=100.0, elapsed=1.0,
                 best_loss=0.5, evals_per_sec=100.0, device_s=0.9,
                 host_s=0.1, host_fraction=0.1, counters=()):
        self.iteration = iteration
        self.num_evals = num_evals
        self.elapsed = elapsed
        self.best_loss = best_loss
        self.evals_per_sec = evals_per_sec
        self.device_s = device_s
        self.host_s = host_s
        self.host_fraction = host_fraction
        self.counters = counters


def test_recorder_ring_is_bounded(tmp_path):
    rec = FlightRecorder(capacity=4, path=str(tmp_path / "b.json"))
    for i in range(1, 11):
        rec.on_iteration(_Ctx(i))
    bundle = rec.snapshot(trigger={"reason": "manual"})
    assert [r["iteration"] for r in bundle["iterations"]] == [7, 8, 9, 10]
    assert bundle["schema"] == BUNDLE_SCHEMA
    assert validate_bundle(bundle) == []


def test_recorder_dump_never_raises_and_budgets(tmp_path):
    rec = FlightRecorder(capacity=2, path=str(tmp_path / "b.json"),
                         max_dumps=2)
    rec.on_iteration(_Ctx(1))
    assert rec.dump(trigger={"reason": "manual"}) is not None
    assert rec.dump(trigger={"reason": "manual"}) is not None
    # over budget: declined, not raised
    assert rec.dump(trigger={"reason": "manual"}) is None
    # pathless recorder: declined, not raised
    assert FlightRecorder().dump(trigger={"reason": "manual"}) is None


def test_fault_event_triggers_dump_through_real_hub(tmp_path):
    """The wiring contract: recorder as hub watcher, a fault event →
    bundle on disk + a bundle_dump pulse event in the stream."""
    hub = Telemetry(
        Options(telemetry=True, save_to_file=False),
        run_id="hubtest", out_dir=str(tmp_path), niterations=4, nout=1)
    path = tmp_path / "pulse_bundle.json"
    rec = FlightRecorder(path=str(path), run_id="hubtest", hub=hub)
    hub.add_sink(rec)
    hub.add_watcher(rec.on_event)

    hub.fault("watchdog_timeout", iteration=3, phase="iteration")
    assert path.exists()
    bundle = json.loads(path.read_text())
    assert validate_bundle(bundle) == []
    assert bundle["trigger"] == {
        "iteration": 3, "kind": "watchdog_timeout", "reason": "fault"}
    with open(hub.path) as f:
        events = [json.loads(l) for l in f]
    kinds = [(e["event"], e.get("kind")) for e in events]
    assert ("fault", "watchdog_timeout") in kinds
    assert ("pulse", "bundle_dump") in kinds


def test_deterministic_view_drops_wall_and_seq(tmp_path):
    rec = FlightRecorder(capacity=2, path=str(tmp_path / "b.json"))
    rec.on_iteration(_Ctx(1))
    rec.dump(trigger={"reason": "manual"})
    bundle = json.loads((tmp_path / "b.json").read_text())
    view = deterministic_view(bundle)
    assert "wall" not in view and "dump_seq" not in view
    assert view["iterations"][0]["iteration"] == 1
    # wall-clock numbers live only in the wall subtree
    assert "evals_per_sec" not in view["iterations"][0]
    assert bundle["wall"]["iterations"][0]["evals_per_sec"] == 100.0


def test_validate_bundle_catches_malformed():
    assert validate_bundle([]) == ["bundle is list, expected object"]
    errors = validate_bundle({"schema": "nope", "run_id": 3})
    assert any("schema" in e for e in errors)
    assert any("run_id" in e for e in errors)
    assert any("missing field" in e for e in errors)


# ---------------------------------------------------------------------------
# anomaly detector (synthetic hub)
# ---------------------------------------------------------------------------


class _FakeHub:
    def __init__(self, traces=0):
        self.anomalies = []
        self.traces = traces

    def anomaly(self, metric, *, iteration, **detail):
        self.anomalies.append((metric, iteration, detail))

    def compile_snapshot(self):
        return {"traces": self.traces}


def _feed_rate(det, iterations, rate, start=1, dt=1.0):
    """Feed iterations at a constant per-iteration eval rate."""
    for k in range(iterations):
        it = start + k
        det.on_iteration(_Ctx(
            it, num_evals=rate * dt * it, elapsed=dt * it,
            host_fraction=0.1))


def test_rate_collapse_fires_after_warmup():
    hub = _FakeHub()
    armed = []
    det = AnomalyDetector(
        hub, on_anomaly=lambda m, i: armed.append(m) or True)
    _feed_rate(det, 7, 1000.0)
    assert hub.anomalies == []
    # 100x collapse at iteration 8: decisive in log space
    det.on_iteration(_Ctx(8, num_evals=7010.0, elapsed=8.0))
    metrics = [m for m, _, _ in hub.anomalies]
    assert metrics == ["evals_per_sec"]
    detail = hub.anomalies[0][2]
    assert detail["zscore"] < -4.0
    assert detail["value"] == pytest.approx(10.0)
    assert detail["armed_capture"] is True
    assert armed == ["evals_per_sec"]


def test_warmup_suppresses_early_firing():
    hub = _FakeHub()
    det = AnomalyDetector(hub)
    _feed_rate(det, 3, 1000.0)
    det.on_iteration(_Ctx(4, num_evals=3010.0, elapsed=4.0))
    assert hub.anomalies == []


def test_cooldown_and_event_budget():
    hub = _FakeHub()
    t = AnomalyThresholds(cooldown=8, max_events=2)
    det = AnomalyDetector(hub, thresholds=t)
    counters = ({"candidates": 100, "invalid": 90},)
    det.on_iteration(_Ctx(1, counters=counters))
    det.on_iteration(_Ctx(2, counters=counters))   # cooled down
    det.on_iteration(_Ctx(9, counters=counters))   # past cooldown
    det.on_iteration(_Ctx(30, counters=counters))  # over budget
    assert [(m, i) for m, i, _ in hub.anomalies] == [
        ("invalid_fraction", 1), ("invalid_fraction", 9)]


def test_compile_bearing_iterations_excluded_from_rate():
    """A legitimately slow compile iteration must not poison the
    rolling stats, and a warm recompile past warmup fires the
    absolute rule."""
    hub = _FakeHub(traces=1)
    det = AnomalyDetector(hub)
    _feed_rate(det, 6, 1000.0)
    # iteration 7: a recompile AND a 100x-slow iteration — excluded
    # from the rate stats, fired as a recompile anomaly instead
    hub.traces += 1
    det.on_iteration(_Ctx(7, num_evals=6010.0, elapsed=7.0))
    assert [m for m, _, _ in hub.anomalies] == ["recompiles"]
    # back to the normal rate: no evals_per_sec anomaly (the slow
    # sample never entered the stats, so the mean is still 1000)
    hub.traces += 0
    det.on_iteration(_Ctx(8, num_evals=7010.0, elapsed=8.0))
    assert [m for m, _, _ in hub.anomalies] == ["recompiles"]


def test_host_fraction_drift_fires():
    hub = _FakeHub()
    det = AnomalyDetector(hub)
    for it in range(1, 8):
        det.on_iteration(_Ctx(it, num_evals=float(it), elapsed=float(it),
                              host_fraction=0.10))
    det.on_iteration(_Ctx(8, num_evals=8.0, elapsed=8.0,
                          host_fraction=0.95))
    assert ("host_fraction" in [m for m, _, _ in hub.anomalies])


# ---------------------------------------------------------------------------
# capture windows (stubbed profiler via hub audit, no jax tracing)
# ---------------------------------------------------------------------------


class _PulseLog:
    def __init__(self):
        self.events = []

    def pulse(self, kind, *, iteration, **detail):
        self.events.append((kind, iteration, detail))


def _stub_profiler(monkeypatch, fail_start=False):
    import jax.profiler

    calls = {"start": 0, "stop": 0}

    def start_trace(d, create_perfetto_trace=True):
        calls["start"] += 1
        if fail_start:
            raise RuntimeError("profiler broken")

    def stop_trace():
        calls["stop"] += 1

    monkeypatch.setattr(jax.profiler, "start_trace", start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", stop_trace)
    return calls


def test_capture_window_lifecycle_and_budget(tmp_path, monkeypatch):
    calls = _stub_profiler(monkeypatch)
    log = _PulseLog()
    clock = {"t": 0.0}
    cap = TraceCapture(str(tmp_path), hub=log, window_iterations=2,
                       max_captures=1, min_interval_s=30.0,
                       clock=lambda: clock["t"])
    assert cap.arm("anomaly", 3)
    assert not cap.arm("sigusr2", 3)      # already armed
    assert cap.maybe_start(4)
    assert not cap.maybe_stop(4)          # covered 1 < window 2
    assert cap.maybe_stop(5)              # covered 2
    assert calls == {"start": 1, "stop": 1}
    assert not cap.arm("anomaly", 6)      # budget exhausted
    assert [k for k, _, _ in log.events] == [
        "capture_armed", "capture_start", "capture_stop"]
    stop_detail = log.events[-1][2]
    assert stop_detail["iterations"] == 2
    assert stop_detail["trace_dir"].endswith("capture01")


def test_capture_rate_limit_spaces_windows(tmp_path, monkeypatch):
    _stub_profiler(monkeypatch)
    clock = {"t": 0.0}
    cap = TraceCapture(str(tmp_path), window_iterations=1,
                       max_captures=5, min_interval_s=30.0,
                       clock=lambda: clock["t"])
    assert cap.arm("a", 1) and cap.maybe_start(1) and cap.maybe_stop(1)
    assert not cap.arm("b", 2)            # inside the 30s window
    clock["t"] = 31.0
    assert cap.arm("b", 2)


def test_broken_profiler_disables_not_raises(tmp_path, monkeypatch):
    _stub_profiler(monkeypatch, fail_start=True)
    log = _PulseLog()
    cap = TraceCapture(str(tmp_path), hub=log)
    assert cap.arm("anomaly", 1)
    assert not cap.maybe_start(2)
    assert cap.disabled
    assert not cap.arm("anomaly", 3)      # stays off for the run
    kinds = [k for k, _, _ in log.events]
    assert kinds == ["capture_armed", "capture_failed"]
    assert "profiler broken" in log.events[-1][2]["error"]


def test_signal_arm_consumes_once():
    arm = SignalArm().install()
    try:
        assert arm.installed
        assert not arm.consume()
        os.kill(os.getpid(), signal.SIGUSR2)
        # signal delivery is synchronous to this thread on the kill
        assert arm.consume()
        assert not arm.consume()
    finally:
        arm.uninstall()
    assert not arm.installed


def test_spans_one_time_profiler_warning(monkeypatch):
    from symbolicregression_jl_tpu.telemetry import spans

    monkeypatch.setattr(spans, "_warned", False)
    seen = []
    spans.set_profiler_warning_hook(seen.append)
    try:
        spans._note_profiler_unusable(RuntimeError("no profiler"))
        spans._note_profiler_unusable(RuntimeError("again"))
        assert seen == ["RuntimeError: no profiler"]
    finally:
        spans.set_profiler_warning_hook(None)


# ---------------------------------------------------------------------------
# full-search contracts: determinism + bit-neutrality (3 tiny searches,
# shared compile cache with tests/test_shield.py shapes)
# ---------------------------------------------------------------------------


def _problem(n=200, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, (n, 2)).astype(np.float32)
    y = (2.0 * X[:, 0] + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(tmp_path, **kw):
    base = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=[],
        maxsize=10,
        populations=2,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=4,
        save_to_file=True,
        output_directory=str(tmp_path),
        telemetry=True,
    )
    base.update(kw)
    return Options(**base)


def _fault_run(tmp_path, sub, *, pulse=True):
    X, y = _problem()
    faults.install(faults.FaultInjector(
        faults.FaultPlan(nan_poison_island=(0, 2))))
    try:
        state, _ = equation_search(
            X, y, options=_options(tmp_path / sub),
            runtime_options=RuntimeOptions(
                niterations=3, run_id="det", seed=7, verbosity=0,
                pulse=pulse),
            return_state=True)
    finally:
        faults.clear()
    return state, os.path.join(tmp_path, sub, "det")


@pytest.mark.slow  # 3 full searches; CI's pulse-smoke job covers the
# fault->anomaly->capture->bundle path end-to-end on every push
def test_bundle_deterministic_and_pulse_bit_neutral(tmp_path):
    """Two identical fault-injected runs dump byte-identical
    deterministic views (same fingerprint); a third with pulse OFF
    produces a bit-identical hall of fame — recorder + detector read
    only what the loop already computed."""
    s1, dir1 = _fault_run(tmp_path, "a", pulse=True)
    s2, dir2 = _fault_run(tmp_path, "b", pulse=True)
    b1 = os.path.join(dir1, "pulse_bundle.json")
    b2 = os.path.join(dir2, "pulse_bundle.json")
    assert os.path.exists(b1) and os.path.exists(b2)
    with open(b1) as f:
        bundle1 = json.load(f)
    with open(b2) as f:
        bundle2 = json.load(f)
    assert validate_bundle(bundle1) == []
    assert bundle1["trigger"]["kind"] == "quarantine"
    blob1 = json.dumps(deterministic_view(bundle1), sort_keys=True)
    blob2 = json.dumps(deterministic_view(bundle2), sort_keys=True)
    assert blob1 == blob2
    assert bundle_fingerprint(b1) == bundle_fingerprint(b2)
    # device counters made it into the ring (stream pulled them)
    assert bundle1["iterations"][-1]["counters"] is not None

    s3, dir3 = _fault_run(tmp_path, "c", pulse=False)
    assert not os.path.exists(os.path.join(dir3, "pulse_bundle.json"))
    a, c = s1.device_states[0], s3.device_states[0]
    for f in ("arity", "op", "feat", "const", "length"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.hof.trees, f)),
            np.asarray(getattr(c.hof.trees, f)))
    np.testing.assert_array_equal(np.asarray(a.hof.cost),
                                  np.asarray(c.hof.cost))
    np.testing.assert_array_equal(np.asarray(a.pops.cost),
                                  np.asarray(c.pops.cost))


# ---------------------------------------------------------------------------
# torn-tail tolerance (report) + live tail follower
# ---------------------------------------------------------------------------


def _write_stream(path, events, tail=""):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
        f.write(tail)


def _mini_events():
    return [
        _base("run_start", run_id="torn", backend="cpu", n_devices=1,
              nout=1, niterations=4, telemetry_interval=1, options={},
              engines=[]),
        _base("anomaly", metric="evals_per_sec", iteration=2,
              detail={"value": 1.0, "zscore": -9.9, "threshold": 4.0}),
        _base("pulse", kind="capture_armed", iteration=2,
              detail={"reason": "evals_per_sec"}),
    ]


def test_report_tolerates_torn_tail(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    _write_stream(path, _mini_events(), tail='{"schema": "graftsco')
    events, notes = load_events_tolerant(path)
    assert len(events) == 3
    assert [n["torn_tail"] for n in notes] == [True]
    assert report_main(["report", path, "--json"]) == 0
    captured = capsys.readouterr()
    assert "skipped torn line 4" in captured.err
    summary = json.loads(captured.out)
    assert summary["anomalies"]["count"] == 1
    assert summary["pulse"]["by_kind"] == {"capture_armed": 1}
    # the gate metrics view carries the anomaly count
    assert report_main(["report", path, "--metrics"]) == 0
    metrics = json.loads(capsys.readouterr().out)
    assert metrics["anomalies"] == 1


def test_report_still_refuses_midfile_corruption(tmp_path, capsys):
    path = str(tmp_path / "run.jsonl")
    evs = _mini_events()
    with open(path, "w") as f:
        f.write(json.dumps(evs[0]) + "\n")
        f.write("garbage not json\n")
        f.write(json.dumps(evs[1]) + "\n")
    assert report_main(["report", path]) == 1
    assert "unreadable" in capsys.readouterr().err


def test_tail_follower_incremental_with_torn_tail(tmp_path):
    path = str(tmp_path / "live.jsonl")
    evs = _mini_events()
    _write_stream(path, evs[:1], tail='{"partial')
    fol = TailFollower(path)
    assert fol.poll() == 1
    assert fol.state.run["run_id"] == "torn"
    # the writer's next flush abandons the torn line and appends more
    with open(path, "a") as f:
        f.write("\n")
        f.write(json.dumps(_base(
            "iteration", iteration=2, num_evals=500.0, elapsed=1.0,
            evals_per_sec=500.0, best_loss=0.25, host_fraction=0.05,
            outputs=[])) + "\n")
        f.write(json.dumps(_base(
            "run_end", stop_reason="niterations", iterations=2,
            num_evals=500.0, elapsed_s=1.0)) + "\n")
    n = fol.poll()
    assert n == 2  # the completed partial line is skipped, counted
    assert fol.state.skipped == 1
    assert fol.state.iterations == 2
    assert fol.state.end is not None
    screen = fol.state.render()
    assert "run END: niterations" in screen
    assert "torn/skipped" in screen


def test_tail_state_renders_counters():
    st = TailState()
    for e in _mini_events():
        st.update(e)
    st.update(_base("fault", kind="retry", iteration=2, detail={}))
    screen = st.render()
    assert "anomalies: evals_per_sec=1" in screen
    assert "pulse: capture_armed=1" in screen
    assert "faults: retry=1" in screen
    assert "run live..." in screen


# ---------------------------------------------------------------------------
# live metrics: PromText + the serve /metrics endpoint
# ---------------------------------------------------------------------------


def test_promtext_format():
    p = PromText("graftserve")
    p.gauge("queue_depth", 3, "Requests queued or running")
    p.gauge("bucket_in_flight", 2, "per bucket",
            labels={"bucket": '256x2x1"esc\\'})
    p.gauge("bucket_in_flight", 1, "per bucket", labels={"bucket": "b"})
    p.counter("cache_hits_total", 7.0, "hits")
    p.gauge("hit_rate", 0.875, "ratio")
    text = p.render()
    lines = text.splitlines()
    # HELP/TYPE once per family, even with two label sets
    assert lines.count("# TYPE graftserve_bucket_in_flight gauge") == 1
    assert "graftserve_queue_depth 3" in lines
    assert ('graftserve_bucket_in_flight{bucket="256x2x1\\"esc\\\\"} 2'
            in lines)
    assert "graftserve_cache_hits_total 7" in lines  # int, no .0
    assert "graftserve_hit_rate 0.875" in lines
    assert text.endswith("\n")


def test_server_metrics_text_and_http(tmp_path):
    from symbolicregression_jl_tpu.serve.metrics import (
        CONTENT_TYPE,
        MetricsServer,
    )
    from symbolicregression_jl_tpu.serve.server import SearchServer

    server = SearchServer(str(tmp_path / "root"), capacity=3,
                          telemetry=False)
    text = server.metrics_text()
    for family in ("graftserve_queue_depth", "graftserve_queue_capacity",
                   "graftserve_cache_hit_rate",
                   'graftserve_requests{state="running"}'):
        assert family in text
    assert "graftserve_queue_capacity 3" in text

    ms = MetricsServer(server.metrics_text, port=0).start()
    try:
        base = f"http://127.0.0.1:{ms.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == CONTENT_TYPE
            assert b"graftserve_queue_depth" in r.read()
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert r.read() == b"ok\n"
        try:
            urllib.request.urlopen(base + "/nope", timeout=5)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        ms.stop()


def test_metrics_server_lifecycle(tmp_path):
    from symbolicregression_jl_tpu.serve.metrics import MetricsServer
    from symbolicregression_jl_tpu.serve.server import SearchServer

    server = SearchServer(str(tmp_path / "root"), capacity=3,
                          telemetry=False)
    ms = MetricsServer(server.metrics_text, port=0).start()
    assert ms.running
    first_port = ms.port
    # a second start() must refuse instead of leaking a second
    # ThreadingHTTPServer on another port behind the caller's back
    with pytest.raises(RuntimeError, match="already serving"):
        ms.start()
    assert ms.port == first_port
    # stop() joins the serving thread and is idempotent
    ms.stop()
    assert not ms.running and ms.port is None
    ms.stop()  # second stop: no-op, no raise
    # a full stop->start cycle rebinds cleanly
    ms.start()
    assert ms.running
    ms.stop()
    assert not ms.running


# ---------------------------------------------------------------------------
# bench trend: anomalies in a green run make the row red
# ---------------------------------------------------------------------------


def _gate_artifact(anomalies):
    return {
        "schema": "graftbench.result.v1",
        "matrix": "cpu-mini",
        "platform": "cpu",
        "cells": {
            "plain/s0": {"metrics": {"evals_per_sec": 1000.0,
                                     "anomalies": anomalies}},
        },
        "failures": {},
        "gate": {"failed": False, "findings": []},
    }


@pytest.mark.parametrize("anomalies,red", [(0, False), (2, True)])
def test_trend_flags_anomalous_green_gate(tmp_path, anomalies, red):
    from symbolicregression_jl_tpu.bench.trend import (
        build_trend,
        format_trend,
    )

    hist = tmp_path / "benchmarks" / "history"
    hist.mkdir(parents=True)
    with open(hist / "gate_r07.json", "w") as f:
        json.dump(_gate_artifact(anomalies), f)
    trend = build_trend(str(tmp_path))
    row = trend["gates"][0]
    assert row["anomalies"] == anomalies
    assert row["red"] is red
    text = format_trend(trend)
    assert f"anomalies={anomalies}" in text
    if red:
        assert "anomaly event(s) in a green run" in row["note"]
        assert "RED" in text
