"""Checkpoint round-trips under adversity (graftshield, docs/ROBUSTNESS.md).

Extends the corruption-mode precedent of tests/test_encoding_invariants.py
to the on-disk state: truncation at several points, flipped bytes, stale
format versions, rolling-K pruning, newest-valid fallback, and the
multi-host rank-shard reassembly helpers — every failure must surface as
:class:`CheckpointCorruptError` (never a raw unpickling crash), and the
fallback machinery must recover whenever ANY valid generation survives.
"""

import dataclasses
import os
import pickle

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options, equation_search
from symbolicregression_jl_tpu.api.checkpoint import (
    CheckpointCorruptError,
    load_search_state,
    save_search_state,
)
from symbolicregression_jl_tpu.api.search import RuntimeOptions, SearchState
from symbolicregression_jl_tpu.shield import faults
from symbolicregression_jl_tpu.shield.checkpoints import (
    RollingCheckpointer,
    discover_resume_path,
    load_newest_valid,
    rolled_paths,
)


def _problem(n=200, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, (n, 2)).astype(np.float32)
    y = (2.0 * X[:, 0] + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(tmp_path, **kw):
    # Same shapes as tests/test_checkpoint.py so the compiled programs
    # are shared across both files via the persistent test cache.
    base = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=[],
        maxsize=10,
        populations=2,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=4,
        save_to_file=True,
        output_directory=str(tmp_path),
    )
    base.update(kw)
    return Options(**base)


@pytest.fixture(scope="module")
def fitted_state(tmp_path_factory):
    """One tiny fitted SearchState shared by every corruption test."""
    tmp = tmp_path_factory.mktemp("shield_ckpt")
    X, y = _problem()
    options = _options(tmp, save_to_file=False)
    state, _ = equation_search(
        X, y, options=options,
        runtime_options=RuntimeOptions(niterations=1, seed=3, verbosity=0,
                                       return_state=True),
    )
    return state, options


# ---------------------------------------------------------------------------
# corruption modes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("keep_fraction", [0.0, 0.1, 0.5, 0.95])
def test_truncated_checkpoint_raises_corrupt(tmp_path, fitted_state,
                                             keep_fraction):
    state, options = fitted_state
    p = str(tmp_path / "state.pkl")
    save_search_state(p, state)
    faults.truncate_file(p, keep_fraction)
    with pytest.raises(CheckpointCorruptError):
        load_search_state(p, options)


@pytest.mark.parametrize("offset", [-64, -1024, 64, 200])
def test_flipped_byte_fails_digest(tmp_path, fitted_state, offset):
    state, options = fitted_state
    p = str(tmp_path / "state.pkl")
    save_search_state(p, state)
    faults.flip_byte(p, offset)
    with pytest.raises(CheckpointCorruptError):
        load_search_state(p, options)


def test_stale_format_version_raises_corrupt(tmp_path, fitted_state):
    state, options = fitted_state
    p = str(tmp_path / "state.pkl")
    # A v1-style bare payload with a future format_version.
    with open(p, "wb") as f:
        pickle.dump({"format_version": 99, "compat": {}}, f)
    with pytest.raises(CheckpointCorruptError, match="format_version"):
        load_search_state(p, options)


def test_non_dict_pickle_raises_corrupt(tmp_path, fitted_state):
    _, options = fitted_state
    p = str(tmp_path / "state.pkl")
    with open(p, "wb") as f:
        pickle.dump([1, 2, 3], f)
    with pytest.raises(CheckpointCorruptError):
        load_search_state(p, options)


def test_missing_file_is_not_corrupt(tmp_path, fitted_state):
    _, options = fitted_state
    with pytest.raises(FileNotFoundError):
        load_search_state(str(tmp_path / "nope.pkl"), options)


def test_clean_roundtrip_preserves_iterations_done(tmp_path, fitted_state):
    state, options = fitted_state
    st = dataclasses.replace(state, iterations_done=7)
    p = str(tmp_path / "state.pkl")
    save_search_state(p, st)
    loaded = load_search_state(p, options)
    assert loaded.iterations_done == 7
    np.testing.assert_array_equal(
        np.asarray(st.device_states[0].pops.trees.arity),
        np.asarray(loaded.device_states[0].pops.trees.arity),
    )


# ---------------------------------------------------------------------------
# rolling-K + newest-valid fallback
# ---------------------------------------------------------------------------


def test_rolling_keeps_last_k_and_prunes(tmp_path, fitted_state):
    state, options = fitted_state
    base = str(tmp_path / "search_state.pkl")
    ck = RollingCheckpointer(base, keep=3)
    for n in range(5):
        st = dataclasses.replace(state, iterations_done=n)
        ck.save(st)
    paths = rolled_paths(base, 3)
    assert [os.path.exists(p) for p in paths] == [True, True, True]
    assert not os.path.exists(base + ".3"), "pruning failed: kept > K"
    # newest-first content: iterations_done 4, 3, 2
    got = [load_search_state(p, options).iterations_done for p in paths]
    assert got == [4, 3, 2]


def test_newest_valid_falls_back_past_corruption(tmp_path, fitted_state):
    state, options = fitted_state
    base = str(tmp_path / "search_state.pkl")
    ck = RollingCheckpointer(base, keep=3)
    for n in range(3):
        ck.save(dataclasses.replace(state, iterations_done=n))
    faults.flip_byte(base)          # newest corrupt
    faults.truncate_file(base + ".1", 0.2)  # middle corrupt too
    with pytest.warns(UserWarning, match="corrupt"):
        loaded, used = load_newest_valid(rolled_paths(base, 3), options)
    assert used == base + ".2"
    assert loaded.iterations_done == 0


def test_all_corrupt_raises_with_context(tmp_path, fitted_state):
    state, options = fitted_state
    base = str(tmp_path / "search_state.pkl")
    ck = RollingCheckpointer(base, keep=2)
    ck.save(state)
    ck.save(state)
    faults.flip_byte(base)
    faults.flip_byte(base + ".1")
    with pytest.warns(UserWarning, match="corrupt"):
        with pytest.raises(CheckpointCorruptError, match="all 2"):
            load_newest_valid(rolled_paths(base, 2), options)


def test_discover_resume_path_picks_newest_run(tmp_path, fitted_state):
    state, _ = fitted_state
    for run, stamp in (("run_a", 1), ("run_b", 2)):
        d = tmp_path / run
        d.mkdir()
        p = str(d / "search_state.pkl")
        save_search_state(p, state)
        os.utime(p, (stamp, stamp))
    cands = discover_resume_path(str(tmp_path))
    assert cands is not None and "run_b" in cands[0]
    assert discover_resume_path(str(tmp_path / "missing")) is None


# ---------------------------------------------------------------------------
# multi-host rank shards (unit-level: the container has one process, so
# the shard/reassemble helpers are driven directly on fake rank sets)
# ---------------------------------------------------------------------------


def test_multihost_rank_reassembly_roundtrip(tmp_path, fitted_state):
    from symbolicregression_jl_tpu.api.checkpoint import (
        _ShardRec,
        _base_payload,
        _to_numpy_state,
        _write_envelope,
    )

    state, options = fitted_state
    full = _to_numpy_state(state.device_states[0])
    I = full.pops.cost.shape[0]
    assert I >= 2, "need >= 2 islands to fake a 2-rank shard split"

    def rank_view(ds, rank, nranks):
        """Pretend the island axis was sharded over nranks hosts: every
        [I, ...] population leaf becomes a _ShardRec carrying only this
        rank's island slice; replicated leaves stay full."""
        lo, hi = rank * (I // nranks), (rank + 1) * (I // nranks)

        def rec(x):
            x = np.asarray(x)
            if x.ndim >= 1 and x.shape[0] == I:
                idx = (slice(lo, hi),) + tuple(
                    slice(0, s) for s in x.shape[1:]
                )
                return _ShardRec(x.shape, x.dtype, [(idx, x[lo:hi])])
            return x

        import jax

        return jax.tree.map(rec, ds)

    for rank in range(2):
        payload = dict(_base_payload(state))
        payload["multihost"] = {"process_index": rank, "process_count": 2}
        payload["device_states"] = [rank_view(full, rank, 2)]
        _write_envelope(str(tmp_path / f"state.pkl.rank{rank}"), payload)

    loaded = load_search_state(str(tmp_path / "state.pkl"), options)
    np.testing.assert_array_equal(
        np.asarray(loaded.device_states[0].pops.trees.arity),
        np.asarray(state.device_states[0].pops.trees.arity),
    )
    np.testing.assert_array_equal(
        np.asarray(loaded.device_states[0].pops.cost),
        np.asarray(state.device_states[0].pops.cost),
    )


def test_multihost_mixed_generation_raises(tmp_path, fitted_state):
    # Rank files written at different iterations (one host died later
    # than the other) must refuse to reassemble into a chimera state.
    from symbolicregression_jl_tpu.api.checkpoint import (
        _base_payload,
        _to_numpy_state,
        _write_envelope,
    )

    state, options = fitted_state
    full = _to_numpy_state(state.device_states[0])
    for rank, it_done in ((0, 5), (1, 10)):
        payload = dict(_base_payload(state))
        payload["iterations_done"] = it_done
        payload["multihost"] = {"process_index": rank, "process_count": 2}
        payload["device_states"] = [full]
        _write_envelope(str(tmp_path / f"state.pkl.rank{rank}"), payload)
    with pytest.raises(CheckpointCorruptError, match="generations"):
        load_search_state(str(tmp_path / "state.pkl"), options)


def test_rank_glob_ignores_torn_write_leftovers(tmp_path):
    from symbolicregression_jl_tpu.api.checkpoint import rank_shard_paths

    base = str(tmp_path / "state.pkl")
    for name in ("state.pkl.rank0", "state.pkl.rank1",
                 "state.pkl.rank2.bak", "state.pkl.rank10"):
        (tmp_path / name).write_bytes(b"x")
    assert rank_shard_paths(base) == [
        base + ".rank0", base + ".rank1", base + ".rank10"
    ]


def test_multihost_missing_rank_raises(tmp_path, fitted_state):
    from symbolicregression_jl_tpu.api.checkpoint import (
        _base_payload,
        _to_numpy_state,
        _write_envelope,
    )

    state, options = fitted_state
    payload = dict(_base_payload(state))
    payload["multihost"] = {"process_index": 0, "process_count": 2}
    payload["device_states"] = [_to_numpy_state(state.device_states[0])]
    _write_envelope(str(tmp_path / "state.pkl.rank0"), payload)
    with pytest.raises(CheckpointCorruptError, match="rank"):
        load_search_state(str(tmp_path / "state.pkl"), options)
