"""Tests for the public eval/diff API and the symbolic D operator.

Mirrors the reference's AD integration tests
(/root/reference/test/integration/ad/) at unit scale: forward derivatives,
constant gradients, and symbolic differentiation golden values.
"""

import numpy as np
import pytest

from symbolicregression_jl_tpu import (
    Node,
    OperatorSet,
    parse_expression,
)
from symbolicregression_jl_tpu.ops.diff import (
    D,
    eval_diff_tree_array,
    eval_grad_tree_array,
    eval_tree_array,
)

OPS = OperatorSet(
    binary_operators=["+", "-", "*", "/", "^"],
    unary_operators=["sin", "cos", "exp", "log", "sqrt", "abs"],
)


@pytest.fixture(scope="module")
def X():
    rng = np.random.default_rng(0)
    return rng.uniform(0.5, 2.0, (64, 3)).astype(np.float32)


def _parse(s):
    return parse_expression(s, OPS, variable_names=["x1", "x2", "x3"])


def test_eval_tree_array_golden(X):
    tree = _parse("2.0 * x1 + cos(x2)")
    y, valid = eval_tree_array(tree, X, OPS)
    np.testing.assert_allclose(
        np.asarray(y), 2.0 * X[:, 0] + np.cos(X[:, 1]), rtol=1e-5
    )
    assert bool(valid)


def test_eval_tree_array_invalid(X):
    tree = _parse("log(x1 - 5.0)")  # all rows < 5 => NaN domain
    _, valid = eval_tree_array(tree, X, OPS)
    assert not bool(valid)


def test_eval_diff_tree_array(X):
    tree = _parse("sin(x1 * x2) + x3")
    y, dy, valid = eval_diff_tree_array(tree, X, OPS, direction=0)
    expected = np.cos(X[:, 0] * X[:, 1]) * X[:, 1]
    np.testing.assert_allclose(np.asarray(dy), expected, rtol=1e-4, atol=1e-5)
    assert bool(valid)


def test_eval_grad_tree_array_variables(X):
    tree = _parse("x1 * x2 + exp(x3)")
    y, grad, valid = eval_grad_tree_array(tree, X, OPS, variable=True)
    assert grad.shape == (3, X.shape[0])
    np.testing.assert_allclose(np.asarray(grad[0]), X[:, 1], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad[1]), X[:, 0], rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grad[2]), np.exp(X[:, 2]), rtol=1e-4
    )


def test_eval_grad_tree_array_constants(X):
    tree = _parse("3.0 * x1 + 1.5")
    y, grad, valid = eval_grad_tree_array(tree, X, OPS, variable=False)
    # Constants in postfix order: 3.0 then 1.5.
    assert grad.shape == (2, X.shape[0])
    np.testing.assert_allclose(np.asarray(grad[0]), X[:, 0], rtol=1e-5)
    np.testing.assert_allclose(np.asarray(grad[1]), 1.0, rtol=1e-5)


def test_eval_grad_no_constants(X):
    tree = _parse("x1 + x2")
    _, grad, _ = eval_grad_tree_array(tree, X, OPS, variable=False)
    assert grad.shape == (0, X.shape[0])


@pytest.mark.parametrize(
    "expr,feature",
    [
        ("sin(x1 * x2)", 0),
        ("exp(x1) / x2", 1),
        ("sqrt(x1) + x1 ^ 3.0", 0),
        ("log(x2 * x2)", 1),
        ("abs(x1 - x3)", 2),
    ],
)
def test_D_matches_jvp(X, expr, feature):
    """Symbolic derivative evaluates identically to forward-mode AD."""
    tree = _parse(expr)
    dtree = D(tree, feature)
    y_sym, valid_sym = eval_tree_array(dtree, X, OPS)
    _, dy_ad, _ = eval_diff_tree_array(tree, X, OPS, direction=feature)
    np.testing.assert_allclose(
        np.asarray(y_sym), np.asarray(dy_ad), rtol=1e-4, atol=1e-5
    )


def test_D_of_constant_is_zero():
    assert D(Node.const(3.0), 0).val == 0.0
    assert D(Node.var(1), 0).val == 0.0
    assert D(Node.var(0), 0).val == 1.0


def test_D_simplifies():
    # d/dx1 (x1 + 5) = 1 exactly, as a single constant node.
    tree = _parse("x1 + 5.0")
    d = D(tree, 0)
    assert d.degree == 0 and d.val == 1.0


def test_D_unknown_operator_raises():
    ops = OperatorSet(binary_operators=["+"], unary_operators=["gamma"])
    tree = parse_expression("gamma(x1)", ops, variable_names=["x1"])
    with pytest.raises(ValueError):
        D(tree, 0)
