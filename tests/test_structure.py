"""Closed-form tree_structure_arrays vs a reference host implementation."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from symbolicregression_jl_tpu.evolve.mutation import (
    MutationContext,
    gen_random_tree_fixed_size,
)
from symbolicregression_jl_tpu.ops.encoding import (
    MAX_ARITY,
    tree_structure_arrays,
)


def host_structure(arity, length):
    """Straightforward stack walk on host (the pre-rewrite semantics)."""
    L = len(arity)
    child = np.zeros((L, MAX_ARITY), np.int32)
    size = np.ones(L, np.int32)
    depth = np.ones(L, np.int32)
    stack = []
    for k in range(L):
        a = int(arity[k])
        kids = stack[len(stack) - a:] if a else []
        del stack[len(stack) - a:]
        for j, c in enumerate(kids):
            child[k, j] = c
            size[k] += size[c]
            depth[k] = max(depth[k], depth[c] + 1)
        stack.append(k)
    return child, size, depth


@pytest.mark.parametrize("seed", range(8))
def test_structure_matches_host_walk(seed):
    ctx = MutationContext(
        nops=(3, 4), nfeatures=5, max_nodes=31,
        perturbation_factor=0.076, probability_negate_constant=0.01,
    )
    key = jax.random.PRNGKey(seed)
    sizes = jax.random.randint(jax.random.fold_in(key, 1), (16,), 1, 31)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, ctx, jnp.float32)
    )(jax.random.split(key, 16), sizes)

    child, size, depth = jax.tree.map(np.asarray, tree_structure_arrays(trees))
    arity = np.asarray(trees.arity)
    length = np.asarray(trees.length)

    for i in range(16):
        Lh = int(length[i])
        # generated trees are valid postfix: stack heights check out
        D = np.cumsum(1 - arity[i][:Lh])
        assert (D >= 1).all() and D[-1] == 1, f"invalid postfix {arity[i][:Lh]}"
        ch, sz, dp = host_structure(arity[i], Lh)
        np.testing.assert_array_equal(size[i][:Lh], sz[:Lh])
        np.testing.assert_array_equal(depth[i][:Lh], dp[:Lh])
        np.testing.assert_array_equal(child[i][:Lh], ch[:Lh])


def test_gen_random_tree_fixed_size_hits_target():
    ctx = MutationContext(
        nops=(2, 4), nfeatures=3, max_nodes=25,
        perturbation_factor=0.076, probability_negate_constant=0.01,
    )
    for seed in range(6):
        for target in (1, 2, 5, 12, 25):
            t = gen_random_tree_fixed_size(
                jax.random.PRNGKey(seed * 100 + target), target, ctx,
                jnp.float32)
            m = int(t.length)
            assert 1 <= m <= target
            a = np.asarray(t.arity)
            assert (a[m:] == 0).all()
            D = np.cumsum(1 - a[:m])
            assert (D >= 1).all() and D[-1] == 1


def test_gen_random_tree_unary_only_and_binary_only():
    for nops, tgt in (((3, 0), 9), ((0, 2), 9)):
        ctx = MutationContext(
            nops=nops, nfeatures=2, max_nodes=15,
            perturbation_factor=0.076, probability_negate_constant=0.01,
        )
        t = gen_random_tree_fixed_size(jax.random.PRNGKey(0), tgt, ctx,
                                       jnp.float32)
        m = int(t.length)
        a = np.asarray(t.arity)[:m]
        D = np.cumsum(1 - a)
        assert (D >= 1).all() and D[-1] == 1
        if nops[1] == 0:
            assert (a != 2).all()
        if nops[0] == 0:
            assert (a != 1).all()
