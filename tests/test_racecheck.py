"""graftwarden runtime auditor: instrumented locks must be transparent,
actual lock-order inversions must raise against the blessed manifest,
and the three PR-6 races must replay deterministically under
SR_RACE_PLAN — passing on current code, failing on a reverted shim
(the shim legs prove each replay actually lands on the fixed line).

The cancel-vs-submit replay needs no search (workers=0) and runs in the
fast tier; the two search-driven replays are `slow` (tools/race_smoke.py
runs all three in CI's warden-smoke job).
"""

import threading

import numpy as np
import pytest

from symbolicregression_jl_tpu.lint.racecheck import (
    InstrumentedLock,
    LockOrderViolation,
    LockRecorder,
    RacePlan,
    clear_race_plan,
    global_recorder,
    install_race_plan,
    instrument_server,
    replay_scenario,
)
from symbolicregression_jl_tpu.lint.lock_order import (
    BLESSED_EDGES,
    blessed_closure,
)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    clear_race_plan()
    yield
    clear_race_plan()


# ---------------------------------------------------------------------------
# instrumented lock semantics
# ---------------------------------------------------------------------------


def test_instrumented_lock_is_a_context_manager_and_reentrant():
    lk = InstrumentedLock("SearchServer._lock")
    with lk:
        with lk:  # RLock reentrancy passes through
            pass
    assert global_recorder().held() == []


def test_blessed_nesting_passes_and_inversion_raises():
    # a dedicated recorder: the deliberate inversion below must not
    # pollute the process-global edge/violation log other tests check
    rec = LockRecorder()
    srv = InstrumentedLock("SearchServer._lock", recorder=rec)
    adm = InstrumentedLock("AdmissionController._lock", recorder=rec)
    with srv:
        with adm:  # the sanctioned direction
            pass
    with pytest.raises(LockOrderViolation):
        with adm:
            with srv:  # inverts the manifest
                pass
    # the raise happened BEFORE the inner acquire: nothing stays held
    assert rec.held() == []


def test_transitive_inversion_raises():
    rec = LockRecorder()
    log = InstrumentedLock("ServeLog._lock", recorder=rec)
    srv = InstrumentedLock("SearchServer._lock", recorder=rec)
    # ServeLog is reachable from SearchServer through the manifest, so
    # holding it while taking the server lock is an inversion too
    with pytest.raises(LockOrderViolation):
        with log:
            with srv:
                pass


def test_unordered_locks_do_not_raise():
    cache = InstrumentedLock("ExecutableCache._lock")
    metrics = InstrumentedLock("MetricsServer._state_lock")
    with cache:
        with metrics:
            pass
    with metrics:
        with cache:
            pass  # partial order: unrelated pairs are unordered


def test_condition_over_instrumented_lock():
    lk = InstrumentedLock("SearchServer._lock")
    cond = threading.Condition(lk)
    state = {"go": False}

    def _setter():
        with cond:
            state["go"] = True
            cond.notify_all()

    t = threading.Timer(0.05, _setter)
    t.start()
    with cond:
        with lk:  # reentrant hold across the wait
            while not state["go"]:
                cond.wait(timeout=1.0)
    t.join()
    assert global_recorder().held() == []


def test_race_plan_window_pauses_nth_matching_acquire():
    lk = InstrumentedLock("RequestJournal._lock")
    plan = install_race_plan(RacePlan.from_dict({"windows": [{
        "lock": "RequestJournal._lock", "op": "acquire",
        "caller": "target_fn", "nth": 2, "pause_s": 0.05}]}))
    window = plan.windows[0]

    def target_fn():
        with lk:
            pass

    def other_fn():
        with lk:
            pass

    other_fn()  # wrong caller: not counted
    target_fn()  # nth=1
    assert not window.entered.is_set()
    target_fn()  # nth=2: fires
    assert window.entered.is_set()
    target_fn()  # one-shot: no re-fire, no hang


# ---------------------------------------------------------------------------
# server instrumentation transparency
# ---------------------------------------------------------------------------


def test_instrumented_server_serves_normally(tmp_path):
    from symbolicregression_jl_tpu.serve.server import SearchServer

    srv = SearchServer(str(tmp_path / "root"), capacity=4, workers=0,
                       debug_checks=True)
    assert isinstance(srv._lock, InstrumentedLock)
    assert isinstance(srv.journal._lock, InstrumentedLock)
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 2)).astype(np.float32)
    y = X[:, 0].astype(np.float32)
    rid = srv.submit(X, y, options=dict(
        binary_operators=["+", "*"], unary_operators=[], maxsize=8,
        populations=2, population_size=8, ncycles_per_iteration=2,
        tournament_selection_n=4, optimizer_probability=0.0,
    ), niterations=1)
    assert srv.poll(rid)["state"] == "queued"
    assert srv.cancel(rid) is True
    assert srv.poll(rid)["state"] == "cancelled"
    # every edge the instrumented run observed is blessed (directly or
    # by being unordered) — no inversions were recorded
    assert global_recorder().violations == []
    closure = blessed_closure(BLESSED_EDGES)
    for (a, b) in global_recorder().edges:
        assert a not in closure.get(b, ()), f"inverted edge {a} -> {b}"


def test_instrument_server_is_idempotent(tmp_path):
    from symbolicregression_jl_tpu.serve.server import SearchServer

    srv = SearchServer(str(tmp_path / "root"), capacity=4, workers=0,
                       debug_checks=True)
    inner = srv._lock.inner
    instrument_server(srv)  # second call must not double-wrap
    assert srv._lock.inner is inner


# ---------------------------------------------------------------------------
# the three PR-6 races, replayed
# ---------------------------------------------------------------------------


def test_replay_cancel_vs_submit_passes_on_current_code(tmp_path):
    r = replay_scenario("cancel_vs_submit", str(tmp_path / "cur"))
    assert r["ok"], r


def test_replay_cancel_vs_submit_detects_reverted_fix(tmp_path):
    r = replay_scenario("cancel_vs_submit", str(tmp_path / "shim"),
                        shim=True)
    assert not r["ok"], r
    # the shim's journal holds the cancel BEFORE its submit — the exact
    # resurrection signature the fix closed
    assert r["detail"]["replayed_state"] == "queued"


@pytest.mark.slow
def test_replay_cancel_overlapping_preemption(tmp_path):
    r = replay_scenario("cancel_overlapping_preemption",
                        str(tmp_path / "cur"))
    assert r["ok"], r
    r2 = replay_scenario("cancel_overlapping_preemption",
                         str(tmp_path / "shim"), shim=True)
    assert not r2["ok"], r2
    assert r2["detail"]["state"] == "queued"  # resurrection signature


@pytest.mark.slow
def test_replay_stale_guard_restart(tmp_path):
    r = replay_scenario("stale_guard_restart", str(tmp_path / "cur"))
    assert r["ok"], r
    r2 = replay_scenario("stale_guard_restart", str(tmp_path / "shim"),
                         shim=True)
    assert not r2["ok"], r2
    assert r2["detail"]["state"] == "queued"  # workers died instantly


def test_unknown_scenario_raises(tmp_path):
    with pytest.raises(KeyError):
        replay_scenario("nope", str(tmp_path))
