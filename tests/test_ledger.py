"""graftledger: deterministic trace minting, v2 schema round-trips,
cost-account validation/folding, the rollup, the Chrome-trace timeline
export, tail rotation handling, and the ledger on/off A/B bit-identity
pin (docs/OBSERVABILITY.md "Cost attribution & tracing")."""

import json
import os
import types

import numpy as np
import pytest

from symbolicregression_jl_tpu.ledger import (
    LATENCY_BUCKETS_S,
    LEDGER_SCHEMA,
    CostLedger,
    TraceContext,
    build_rollup,
    build_timeline,
    fold_accounts,
    ledger_fingerprint,
    load_accounts,
    load_rollup,
    mint_run_trace,
    mint_trace,
    validate_account,
    validate_chrome_trace,
    write_rollup,
    write_timeline,
)
from symbolicregression_jl_tpu.ledger.ledger import bucket_latency
from symbolicregression_jl_tpu.telemetry.schema import (
    EVENT_SPECS,
    SCHEMA_VERSION,
    validate_event,
)

# ---------------------------------------------------------------------------
# trace context minting
# ---------------------------------------------------------------------------


def test_mint_trace_is_deterministic_and_content_addressed():
    a = mint_trace("req-1", seed=7, niterations=4)
    b = mint_trace("req-1", seed=7, niterations=4)
    assert a == b  # same content -> same ids (kill-restart-replay)
    assert len(a.trace_id) == 32 and len(a.span_id) == 16
    assert a.parent_id is None
    # any content change moves the whole tree
    assert mint_trace("req-2", seed=7, niterations=4).trace_id != a.trace_id
    assert mint_trace("req-1", seed=8, niterations=4).trace_id != a.trace_id
    assert mint_trace("req-1", seed=7, niterations=5).trace_id != a.trace_id


def test_child_span_derivation_and_round_trip():
    root = mint_trace("req-1", seed=7, niterations=4)
    search = root.child("search")
    assert search.trace_id == root.trace_id
    assert search.parent_id == root.span_id
    assert search.span_id != root.span_id
    assert root.child("search") == search  # deterministic
    assert root.child("replay") != search
    assert TraceContext.from_dict(search.to_dict()) == search
    assert TraceContext.from_dict(None) is None
    assert TraceContext.from_dict({"trace_id": 1}) is None


def test_run_trace_differs_from_request_trace():
    assert (mint_run_trace("req-1").trace_id
            != mint_trace("req-1", seed=0, niterations=1).trace_id)


# ---------------------------------------------------------------------------
# graftscope.v2 round-trip: every event kind carries the trace context
# ---------------------------------------------------------------------------

_MINIMAL_FIELDS = {
    "run_start": dict(run_id="r", backend="cpu", n_devices=1, nout=1,
                      niterations=2, telemetry_interval=1, options={},
                      engines=[]),
    "iteration": dict(iteration=1, num_evals=10.0, evals_per_sec=1.0,
                      elapsed_s=1.0, device_s=0.5, host_s=0.1,
                      host_fraction=0.1,
                      recompiles={"traces": 0, "backend_compiles": 0},
                      transfer_guard_hits=0, outputs=[]),
    "run_end": dict(stop_reason="niterations", iterations=2,
                    num_evals=20.0, elapsed_s=2.0, recompiles_total={}),
    "fault": dict(kind="retry", iteration=1, detail={}),
    "serve": dict(kind="accept", request_id="req-1", detail={}),
    "mesh": dict(iteration=1, shards=2, detail={}),
    "anomaly": dict(metric="evals_per_sec", iteration=1, detail={}),
    "pulse": dict(kind="capture_armed", iteration=1, detail={}),
    "gauge": dict(kind="memory", iteration=1, detail={}),
}


@pytest.mark.parametrize("kind", sorted(EVENT_SPECS))
def test_every_event_kind_accepts_and_preserves_trace(kind):
    trace = mint_trace("req-1", seed=7, niterations=4).child("search")
    ev = {"schema": SCHEMA_VERSION, "event": kind, "t": 1.0,
          "trace": trace.to_dict(), **_MINIMAL_FIELDS[kind]}
    assert validate_event(ev) == []
    back = json.loads(json.dumps(ev))  # JSONL wire round-trip
    assert back["trace"] == trace.to_dict()
    assert TraceContext.from_dict(back["trace"]) == trace


@pytest.mark.parametrize("kind", sorted(EVENT_SPECS))
def test_v1_events_without_trace_still_validate(kind):
    ev = {"schema": "graftscope.v1", "event": kind, "t": 1.0,
          **_MINIMAL_FIELDS[kind]}
    assert validate_event(ev) == []


def test_malformed_trace_rejected():
    ev = {"schema": SCHEMA_VERSION, "event": "pulse", "t": 1.0,
          "trace": {"trace_id": 5, "span_id": "x"},
          **_MINIMAL_FIELDS["pulse"]}
    errs = validate_event(ev)
    assert any("trace" in e for e in errs)


# ---------------------------------------------------------------------------
# cost accounts: accumulate, validate, fold, fingerprint
# ---------------------------------------------------------------------------


def _iter_ctx(i, *, device_s=0.5, host_s=0.1):
    return types.SimpleNamespace(
        iteration=i, num_evals=100.0 * i, elapsed=1.0 * i,
        device_s=device_s, host_s=host_s)


def _run_segment(path, trace, *, iters, stop="niterations",
                 request_id="req-1"):
    led = CostLedger(path, run_id="det", trace=trace,
                     request_id=request_id)
    for i in iters:
        led.on_iteration(_iter_ctx(i))
    led.note_phase("checkpoint", 0.01)
    led.note_phase("checkpoint", 0.02)
    led.note_checkpoint(1024)
    led.on_end({"stop_reason": stop, "elapsed_s": 9.0,
                "num_evals": 100.0 * max(iters)})
    return led


def test_account_validates_and_buckets_latency(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    led = _run_segment(path, mint_run_trace("det"), iters=[1, 2, 3])
    acct = led.account()
    assert validate_account(acct) == []
    assert acct["schema"] == LEDGER_SCHEMA
    assert acct["deterministic"]["iterations"] == 3
    assert acct["wall"]["device_s"] == pytest.approx(1.5)
    assert acct["wall"]["phases"]["checkpoint"] == {
        "count": 2, "seconds": pytest.approx(0.03)}
    assert acct["wall"]["checkpoints"] == {"count": 1, "bytes": 1024}
    counts = acct["wall"]["iteration_latency"]["counts"]
    assert len(counts) == len(LATENCY_BUCKETS_S) + 1
    assert sum(counts) == 3  # one sample per iteration
    # 0.6s lands in the le=1.0 bucket
    assert counts[LATENCY_BUCKETS_S.index(1.0)] == 3
    assert validate_account({"schema": "nope"})  # malformed -> errors


def test_bucket_latency_overflow_bucket():
    counts = bucket_latency(120.0)
    assert counts[-1] == 1 and sum(counts) == 1


def test_fold_resumed_segments_matches_uninterrupted_twin(tmp_path):
    trace = mint_trace("req-1", seed=7, niterations=4)
    solo = str(tmp_path / "solo" / "ledger.jsonl")
    _run_segment(solo, trace, iters=[1, 2, 3, 4])
    resumed = str(tmp_path / "resumed" / "ledger.jsonl")
    # killed after 2 iterations, then resumed: two segments, same file
    _run_segment(resumed, trace, iters=[1, 2], stop="preempted")
    _run_segment(resumed, trace, iters=[3, 4])
    assert len(load_accounts(resumed)) == 2  # append, not truncate
    assert fold_accounts(load_accounts(resumed)) == fold_accounts(
        load_accounts(solo))
    assert ledger_fingerprint(resumed) == ledger_fingerprint(solo)


def test_fingerprint_ignores_wall_but_sees_content(tmp_path):
    trace = mint_run_trace("det")
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    _run_segment(a, trace, iters=[1, 2])
    led = CostLedger(b, run_id="det", trace=trace, request_id="req-1")
    for i in (1, 2):
        led.on_iteration(_iter_ctx(i, device_s=9.0))  # wall-only change
    led.on_end({"stop_reason": "niterations", "elapsed_s": 99.0,
                "num_evals": 200.0})
    assert ledger_fingerprint(a) == ledger_fingerprint(b)
    c = str(tmp_path / "c.jsonl")
    _run_segment(c, trace, iters=[1, 2, 3])  # content change
    assert ledger_fingerprint(a) != ledger_fingerprint(c)


def test_load_accounts_refuses_corruption(tmp_path):
    p = tmp_path / "ledger.jsonl"
    p.write_text('{"schema": "wrong"}\n')
    with pytest.raises(ValueError):
        load_accounts(str(p))
    p.write_text("")
    with pytest.raises(ValueError):
        load_accounts(str(p))


# ---------------------------------------------------------------------------
# rollup
# ---------------------------------------------------------------------------


def _serve_root_fixture(tmp_path):
    root = tmp_path / "root"
    for rid, iters in (("req-a", [1, 2]), ("req-b", [1, 2, 3])):
        d = root / "requests" / rid / rid
        d.mkdir(parents=True)
        _run_segment(
            str(d / "ledger.jsonl"),
            mint_trace(rid, seed=0, niterations=len(iters)),
            iters=iters, request_id=rid)
    return str(root)


def test_rollup_builds_persists_and_loads(tmp_path):
    root = _serve_root_fixture(tmp_path)
    rollup = build_rollup(root)
    assert rollup["errors"] == []
    assert set(rollup["requests"]) == {"req-a", "req-b"}
    a = rollup["requests"]["req-a"]
    assert a["iterations"] == 2 and a["segments"] == 1
    assert a["device_s"] == pytest.approx(1.0)
    assert rollup["totals"]["device_s"] == pytest.approx(2.5)
    assert rollup["totals"]["iterations"] == 5
    assert sum(rollup["iteration_latency"]["counts"]) == 5
    path = write_rollup(root)
    assert path and os.path.exists(path)
    loaded = load_rollup(root)
    assert loaded is not None
    assert loaded["requests"]["req-b"]["fingerprint"] == \
        rollup["requests"]["req-b"]["fingerprint"]
    assert load_rollup(str(tmp_path / "nowhere")) is None


def test_rollup_reports_bad_files_instead_of_raising(tmp_path):
    root = tmp_path / "root"
    d = root / "requests" / "req-x" / "req-x"
    d.mkdir(parents=True)
    (d / "ledger.jsonl").write_text("not json\n")
    rollup = build_rollup(str(root))
    assert rollup["requests"] == {}
    assert len(rollup["errors"]) == 1


# ---------------------------------------------------------------------------
# unified timeline -> Chrome trace JSON (golden shape for Perfetto)
# ---------------------------------------------------------------------------


def _timeline_root(tmp_path):
    root = _serve_root_fixture(tmp_path)
    trace_a = mint_trace("req-a", seed=0, niterations=2)
    for rid, trace in (("req-a", trace_a),):
        stream = os.path.join(root, "requests", rid, rid,
                              "telemetry.jsonl")
        events = [
            {"schema": SCHEMA_VERSION, "event": "run_start", "t": 10.0,
             "trace": trace.child("search").to_dict(),
             **_MINIMAL_FIELDS["run_start"]},
            {"schema": SCHEMA_VERSION, "event": "iteration", "t": 11.0,
             "trace": trace.child("search").to_dict(),
             **_MINIMAL_FIELDS["iteration"]},
            {"schema": SCHEMA_VERSION, "event": "pulse", "t": 11.5,
             "trace": trace.child("search").to_dict(),
             **_MINIMAL_FIELDS["pulse"]},
            {"schema": SCHEMA_VERSION, "event": "run_end", "t": 12.0,
             "trace": trace.child("search").to_dict(),
             **_MINIMAL_FIELDS["run_end"]},
        ]
        with open(stream, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
    with open(os.path.join(root, "serve_telemetry.jsonl"), "w") as f:
        for kind, t in (("accept", 9.0), ("start", 9.5), ("done", 13.0)):
            f.write(json.dumps({
                "schema": SCHEMA_VERSION, "event": "serve", "t": t,
                "kind": kind, "request_id": "req-a",
                "trace": trace_a.to_dict(), "detail": {}}) + "\n")
    return root


def test_timeline_is_valid_chrome_trace(tmp_path):
    root = _timeline_root(tmp_path)
    doc = build_timeline(root)
    assert validate_chrome_trace(doc) == []
    events = doc["traceEvents"]
    assert doc["displayTimeUnit"] == "ms"
    # Perfetto-required keys on every event
    for e in events:
        assert isinstance(e["ph"], str) and isinstance(e["name"], str)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float))
    by_name = {e["name"] for e in events}
    assert {"process_name", "thread_name", "serve:accept",
            "iteration 1", "device", "host",
            "ledger segment 0"} <= by_name
    # iteration slices are complete ("X") with microsecond dur
    it = next(e for e in events if e["name"] == "iteration 1")
    assert it["ph"] == "X" and it["dur"] == pytest.approx(0.6e6)
    assert it["args"]["trace_id"] == mint_trace(
        "req-a", seed=0, niterations=2).trace_id
    # causal order: non-meta events sorted by ts
    ts = [e["ts"] for e in events if e["ph"] != "M"]
    assert ts == sorted(ts)


def test_timeline_cli_writes_parseable_file(tmp_path, capsys):
    from symbolicregression_jl_tpu.telemetry.report import main

    root = _timeline_root(tmp_path)
    out = str(tmp_path / "t.json")
    assert main(["timeline", root, "--out", out]) == 0
    with open(out) as f:
        doc = json.load(f)
    assert validate_chrome_trace(doc) == []
    assert "trace events" in capsys.readouterr().out
    # empty root -> error, not an empty-but-"valid" file
    empty = str(tmp_path / "empty")
    os.makedirs(empty)
    assert main(["timeline", empty, "--out",
                 str(tmp_path / "e.json")]) == 1
    assert main(["timeline"]) == 2  # usage


def test_validate_chrome_trace_catches_malformed():
    assert validate_chrome_trace([]) != []
    bad = {"traceEvents": [
        {"ph": "Z", "name": 3, "pid": "x"},
        {"ph": "X", "name": "ok", "pid": 1, "tid": 0, "ts": 1.0},
    ]}
    errs = validate_chrome_trace(bad)
    assert any("bad ph" in e for e in errs)
    assert any("missing dur" in e for e in errs)


# ---------------------------------------------------------------------------
# tail rotation / truncation (telemetry/tail.py)
# ---------------------------------------------------------------------------


def _tail_event(run_id, i):
    return json.dumps({
        "schema": SCHEMA_VERSION, "event": "iteration", "t": float(i),
        "run_id": run_id, **_MINIMAL_FIELDS["iteration"]}) + "\n"


def test_tail_follower_reopens_on_rotation_and_truncation(tmp_path):
    from symbolicregression_jl_tpu.telemetry.tail import TailFollower

    path = str(tmp_path / "run.jsonl")
    with open(path, "w") as f:
        f.write(_tail_event("one", 1) + _tail_event("one", 2))
    fol = TailFollower(path)
    assert fol.poll() == 2 and fol.state.events == 2

    # rotation: rename-and-recreate swaps the inode; the new file is
    # LARGER than the old offset, so a size check alone would misread
    # from a stale position mid-file
    os.replace(path, path + ".1")
    with open(path, "w") as f:
        f.write(_tail_event("two", 1) * 3)
    assert fol.poll() == 3
    assert fol.state.events == 3  # restarted, not 5

    # truncation in place (same inode, smaller size)
    with open(path, "w") as f:
        f.write(_tail_event("three", 1))
    assert fol.poll() == 1
    assert fol.state.events == 1

    os.remove(path)
    assert fol.poll() == 0  # gone = writer not up yet, no crash


# ---------------------------------------------------------------------------
# bit-neutrality pin: ledger on/off produces identical search results
# ---------------------------------------------------------------------------


@pytest.mark.slow  # two full searches; tools/ledger_smoke.py covers the
# serve-path ledger end-to-end in CI on every push
def test_ledger_on_off_hof_bit_identical(tmp_path):
    from symbolicregression_jl_tpu import Options, equation_search
    from symbolicregression_jl_tpu.api.search import RuntimeOptions

    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (160, 2)).astype(np.float32)
    y = (2.0 * X[:, 0] + X[:, 1] * X[:, 1]).astype(np.float32)

    def run(sub, ledger):
        state, _ = equation_search(
            X, y,
            options=Options(
                binary_operators=["+", "*"], unary_operators=[],
                maxsize=8, populations=2, population_size=8,
                ncycles_per_iteration=2, tournament_selection_n=4,
                save_to_file=True, output_directory=str(tmp_path / sub),
                telemetry=True),
            runtime_options=RuntimeOptions(
                niterations=2, run_id="ab", seed=11, verbosity=0,
                ledger=ledger),
            return_state=True)
        return state

    s_on = run("on", True)
    s_off = run("off", False)
    on_path = tmp_path / "on" / "ab" / "ledger.jsonl"
    assert on_path.exists()
    accounts = load_accounts(str(on_path))
    assert validate_account(accounts[-1]) == []
    assert not (tmp_path / "off" / "ab" / "ledger.jsonl").exists()
    a, b = s_on.device_states[0], s_off.device_states[0]
    for f in ("arity", "op", "feat", "const", "length"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.hof.trees, f)),
            np.asarray(getattr(b.hof.trees, f)))
    np.testing.assert_array_equal(np.asarray(a.hof.cost),
                                  np.asarray(b.hof.cost))
    np.testing.assert_array_equal(np.asarray(a.pops.cost),
                                  np.asarray(b.pops.cost))
