"""graftserve integration: submit/poll/cancel lifecycle, executable
cache sharing, structured backpressure, deadline/cancel semantics, and
the kill-restart-replay bit-identity contract (docs/SERVING.md).

The full SIGTERM-a-real-process variant runs in tools/serve_smoke.py
(CI serve-smoke job); here the preemption is driven in-process through
``stop(drain=False)``, which exercises the same boundary-stop +
journal-replay + resume="auto" machinery.
"""

import os
import threading
import time

import numpy as np
import pytest

from symbolicregression_jl_tpu.serve import SearchServer, ServerSaturated
from symbolicregression_jl_tpu.telemetry.report import summarize
from symbolicregression_jl_tpu.telemetry.schema import load_events


def _problem():
    rng = np.random.default_rng(0)
    X = rng.uniform(-2.0, 2.0, (128, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(**kw):
    base = dict(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
    )
    base.update(kw)
    return base


def test_submit_poll_done_shares_engine_and_audits(tmp_path):
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=4, workers=1)
    srv.start()
    try:
        r1 = srv.submit(X, y, options=_options(), niterations=2, seed=5)
        r2 = srv.submit(X, y, options=_options(), niterations=2, seed=7)
        s1 = srv.wait(r1, timeout=300)
        s2 = srv.wait(r2, timeout=300)
    finally:
        srv.stop(drain=True)
    assert s1["state"] == "done" and s2["state"] == "done"
    for s in (s1, s2):
        res = s["result"]
        assert res["iterations"] == 2
        assert res["equations"] and all(
            "equation" in e and "loss" in e for e in res["equations"])
        assert len(res["fingerprint"]) == 64
    # different seeds → different searches, one shared compiled engine
    assert s1["result"]["fingerprint"] != s2["result"]["fingerprint"]
    stats = srv.cache.stats()
    assert stats["misses"] == 1 and stats["hits"] == 1, stats

    # the serve stream validates against graftscope.v1 and the report
    # groups it per request with the cache counters
    events = load_events(str(tmp_path / "root" / "serve_telemetry.jsonl"))
    summary = summarize(events)
    assert summary["serve"]["accepted"] == 2
    assert summary["serve"]["cache"]["hits"] == 1
    assert {r1, r2} <= set(summary["requests"])
    assert summary["requests"][r1]["state"] == "done"
    # per-request search stream exists and validates too
    # run_id == request_id, so the stream is attributable when merged
    run_stream = str(
        tmp_path / "root" / "requests" / r1 / r1 / "telemetry.jsonl")
    run_events = load_events(run_stream)
    assert all(e.get("run_id") == r1 for e in run_events)


def test_saturated_queue_rejects_structured_without_running(tmp_path):
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=2, workers=0)
    srv.submit(X, y, options=_options(), niterations=2, seed=0)
    srv.submit(X, y, options=_options(), niterations=2, seed=1)
    with pytest.raises(ServerSaturated) as ei:
        srv.submit(X, y, options=_options(), niterations=2, seed=2)
    e = ei.value
    assert e.retry_after_s > 0 and e.queue_depth == 2
    d = e.to_dict()
    assert d["error"] == "server_saturated" and d["bucket"] == [256, 2, 1]
    with open(str(tmp_path / "root" / "serve_telemetry.jsonl")) as f:
        assert any('"kind": "reject"' in l for l in f)


def test_overload_ladder_sheds_rows_into_journal(tmp_path):
    from symbolicregression_jl_tpu.shield.degrade import OverloadLadder

    X, y = _problem()
    srv = SearchServer(
        str(tmp_path / "root"), capacity=4, workers=0,
        ladder=OverloadLadder(shed_sample_at=0.25, min_sample_rows=16),
    )
    srv.submit(X, y, options=_options(), niterations=2, seed=0)
    rid = srv.submit(X, y, options=_options(), niterations=2, seed=1)
    snap = srv.poll(rid)
    assert snap["sample_rows"] == 64  # 50% of 128 shed at >=25% util
    # the shed is part of the journaled effective request → a replay
    # after a crash re-runs the identical degraded search
    recovered = SearchServer(str(tmp_path / "root"), workers=0)
    assert recovered.poll(rid)["sample_rows"] == 64


def test_wait_idle_ignores_lazily_cancelled_queue_entries(tmp_path):
    """A queued cancel leaves its heap tuple for lazy removal; that
    stale entry must not make an idle server look busy or
    stop(drain=True) hangs forever with no worker to pop it."""
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=4, workers=0)
    rid = srv.submit(X, y, options=_options(), niterations=1, seed=0)
    assert srv.cancel(rid)
    assert srv.wait_idle(timeout=2.0) is True
    srv.stop(drain=True, timeout=5.0)  # must not hang


def test_submitted_arrays_are_snapshotted(tmp_path):
    """A caller reusing its buffer after submit must not mutate the
    queued request — the in-memory search has to match what the journal
    would replay (bit-identity)."""
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=4, workers=0)
    rid = srv.submit(X, y, options=_options(), niterations=1, seed=0)
    X[:] = 0.0
    y[:] = 0.0
    req = srv._records[rid].request
    assert req.X.any() and req.y.any()
    # and the journaled snapshot agrees with the in-memory one
    records, _ = srv.journal.replay()
    from symbolicregression_jl_tpu.serve.journal import decode_array
    np.testing.assert_array_equal(
        decode_array(records[0]["detail"]["X"]), req.X)


def test_nonnumeric_payload_rejected_and_poison_replay_skipped(tmp_path):
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=4, workers=0)
    # submit-side guard: an object-dtype array would journal cleanly
    # (tobytes succeeds) but could never be decoded on replay
    with pytest.raises(ValueError):
        srv.submit(np.array([[1, "x"], [2, 3]], dtype=object),
                   y[:2], options=_options(), niterations=1, seed=0)
    good = srv.submit(X, y, options=_options(), niterations=1, seed=0)
    # replay-side guard: a digest-valid submit record whose payload
    # cannot be reconstructed must not brick recovery of the root
    srv.journal.append("submit", "poison", {
        "X": {"dtype": "object", "shape": [1], "data": ""},
        "y": {"dtype": "float32", "shape": [1], "data": ""},
        "niterations": 1, "seed": 0,
    })
    srv2 = SearchServer(str(tmp_path / "root"), capacity=4, workers=0)
    assert srv2.poll(good)["state"] == "queued"
    with pytest.raises(KeyError):
        srv2.poll("poison")
    with open(str(tmp_path / "root" / "serve_telemetry.jsonl")) as f:
        assert any('"journal_replay_failed"' in line for line in f)


def test_auto_request_ids_skip_client_chosen_collisions(tmp_path):
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=8, workers=0)
    # a client explicitly claims the id an auto-generator would mint
    srv.submit(X, y, options=_options(), niterations=1, seed=0,
               request_id="req00002")
    a = srv.submit(X, y, options=_options(), niterations=1, seed=1)
    b = srv.submit(X, y, options=_options(), niterations=1, seed=2)
    assert a == "req00001"
    assert b == "req00003"  # skips the client-claimed req00002
    with pytest.raises(ValueError):
        srv.submit(X, y, options=_options(), niterations=1, seed=3,
                   request_id="req00001")


def test_cancel_racing_submit_journal_keeps_order(tmp_path, monkeypatch):
    """A cancel that lands while submit() is still journaling (outside
    the server lock) must not write its record FIRST — replay drops
    lifecycle records preceding their submit, which would resurrect a
    cancelled request after a crash."""
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=4, workers=0)
    orig = srv.journal.append
    in_submit, release = threading.Event(), threading.Event()

    def slow_append(event, request_id, detail=None):
        if event == "submit":
            in_submit.set()
            assert release.wait(timeout=10)
        return orig(event, request_id, detail)

    monkeypatch.setattr(srv.journal, "append", slow_append)
    t = threading.Thread(
        target=srv.submit, args=(X, y),
        kwargs=dict(options=_options(), niterations=2, seed=0,
                    request_id="r1"))
    t.start()
    assert in_submit.wait(timeout=10)
    assert srv.cancel("r1") is True  # deferred: submit not durable yet
    release.set()
    t.join(timeout=10)
    assert srv.poll("r1")["state"] == "cancelled"
    records, corrupt = srv.journal.replay()
    assert not corrupt
    assert [r["event"] for r in records] == ["submit", "cancel"]
    # crash-replay: the cancelled request stays cancelled
    srv2 = SearchServer(str(tmp_path / "root"), capacity=4, workers=0)
    assert srv2.poll("r1")["state"] == "cancelled"
    assert srv2.admission.depth == 0


def test_cancel_queued_request_without_workers(tmp_path):
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=4, workers=0)
    rid = srv.submit(X, y, options=_options(), niterations=2, seed=0)
    assert srv.cancel(rid)
    assert srv.poll(rid)["state"] == "cancelled"
    assert not srv.cancel(rid)  # already terminal
    # the admission slot was released
    assert srv.admission.depth == 0
    # cancellation is durable: a restart does not resurrect the request
    recovered = SearchServer(str(tmp_path / "root"), workers=0)
    assert recovered.poll(rid)["state"] == "cancelled"


def test_rejects_malformed_payloads(tmp_path):
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=4, workers=0)
    with pytest.raises(ValueError):
        srv.submit(X[:, 0], y, options=_options())  # X not 2-D
    with pytest.raises(ValueError):
        srv.submit(X, y[:-1], options=_options())  # length mismatch
    with pytest.raises(ValueError):
        # non-JSON-able options cannot be journaled/replayed
        srv.submit(X, y, options={"early_stop_condition": lambda l, c: False})


def test_unknown_request_id_raises(tmp_path):
    srv = SearchServer(str(tmp_path / "root"), workers=0)
    with pytest.raises(KeyError):
        srv.poll("nope")
    with pytest.raises(KeyError):
        srv.cancel("nope")


@pytest.mark.slow
def test_preempt_restart_replay_bit_identity(tmp_path):
    """Kill (in-process preempt) a server mid-request; a fresh server
    over the same root must finish every accepted request with
    fingerprints bit-identical to an unkilled server's."""
    X, y = _problem()
    seeds = (5, 7)

    ref_root = str(tmp_path / "ref")
    srv = SearchServer(ref_root, capacity=4, workers=1).start()
    ref = {}
    try:
        rids = [
            srv.submit(X, y, options=_options(), niterations=4, seed=s,
                       request_id=f"req-seed{s}")
            for s in seeds
        ]
        for rid in rids:
            ref[rid] = srv.wait(rid, timeout=600)
            assert ref[rid]["state"] == "done"
    finally:
        srv.stop(drain=True)

    kill_root = str(tmp_path / "kill")
    srv = SearchServer(kill_root, capacity=4, workers=1)
    rids = [
        srv.submit(X, y, options=_options(), niterations=4, seed=s,
                   request_id=f"req-seed{s}")
        for s in seeds
    ]
    srv.start()
    # preempt once the first request has a checkpoint on disk (so the
    # restart exercises resume, not just replay-from-scratch)
    ck = os.path.join(kill_root, "requests", rids[0], rids[0],
                      "search_state.pkl")
    deadline = time.monotonic() + 300
    while not os.path.exists(ck) and time.monotonic() < deadline:
        time.sleep(0.05)
    srv.stop(drain=False)
    states = {rid: srv.poll(rid)["state"] for rid in rids}
    assert any(s != "done" for s in states.values()), states

    # a fresh server constructed over the root AT THIS POINT (journal
    # still has unfinished requests) must replay them: re-queued as
    # pending work, audited as `replay` serve events. workers=0 keeps
    # the probe passive — the same-instance restart below does the work.
    probe = SearchServer(kill_root, capacity=4, workers=0)
    replayed = [r for r in probe.requests() if r["state"] == "queued"]
    assert replayed, "journal replay found no unfinished requests"

    # same-instance restart: interrupted work was re-queued in process
    # (admission slots intact), resumes from its checkpoints
    srv.start()
    try:
        for rid in rids:
            snap = srv.wait(rid, timeout=600)
            assert snap["state"] == "done", snap
            assert snap["result"]["fingerprint"] == (
                ref[rid]["result"]["fingerprint"]
            ), f"{rid}: resumed result differs from unkilled run"
    finally:
        srv.stop(drain=True)
    assert srv.admission.depth == 0  # no leaked capacity

    # fresh server over the same root: journal replay returns the
    # journaled results without re-running anything
    srv2 = SearchServer(kill_root, capacity=4, workers=0)
    for rid in rids:
        snap = srv2.poll(rid)
        assert snap["state"] == "done"
        assert snap["result"]["fingerprint"] == (
            ref[rid]["result"]["fingerprint"])
    # recovery audited: replay events in the serve stream
    events = load_events(os.path.join(kill_root, "serve_telemetry.jsonl"))
    kinds = summarize(events)["serve"]["by_kind"]
    assert kinds.get("replay", 0) >= 1


@pytest.mark.slow
def test_deadline_cancels_at_boundary(tmp_path):
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=2, workers=1)
    srv.start()
    try:
        rid = srv.submit(X, y, options=_options(), niterations=200,
                         seed=3, deadline_s=0.5)
        snap = srv.wait(rid, timeout=600)
    finally:
        srv.stop(drain=False)
    assert snap["state"] == "cancelled"
    assert snap["cancel_reason"] == "deadline"


@pytest.mark.slow
def test_cancel_running_with_custom_reason(tmp_path):
    """A free-form cancel reason must terminate as 'cancelled' — a
    partial result must never be journaled as done."""
    X, y = _problem()
    srv = SearchServer(str(tmp_path / "root"), capacity=2, workers=1)
    srv.start()
    try:
        rid = srv.submit(X, y, options=_options(), niterations=200,
                         seed=3)
        deadline = time.monotonic() + 300
        while (srv.poll(rid)["state"] != "running"
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert srv.cancel(rid, reason="user-abort")
        snap = srv.wait(rid, timeout=600)
    finally:
        srv.stop(drain=False)
    assert snap["state"] == "cancelled"
    assert snap["cancel_reason"] == "user-abort"


@pytest.mark.slow
def test_cancel_running_request_mid_iteration(tmp_path):
    from symbolicregression_jl_tpu.shield import faults

    X, y = _problem()
    faults.install_serve(faults.ServeFaultInjector(
        faults.ServeFaultPlan(cancel_request_at_iteration=(1, 2))))
    try:
        srv = SearchServer(str(tmp_path / "root"), capacity=2, workers=1)
        srv.start()
        try:
            rid = srv.submit(X, y, options=_options(), niterations=100,
                             seed=3)
            snap = srv.wait(rid, timeout=600)
        finally:
            srv.stop(drain=False)
    finally:
        faults.clear_serve()
    assert snap["state"] == "cancelled"
    # honored at the next boundary: far fewer than the requested 100
    with open(str(tmp_path / "root" / "serve_telemetry.jsonl")) as f:
        text = f.read()
    assert '"fault": "cancel_request"' in text or "cancel" in text
