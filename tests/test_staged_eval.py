"""graftstage tests: staged sample-then-rescore eval + bf16 row tiles.

Covers the docs/PRECISION.md contract — with both modes off the engine
is bit-identical to the pre-graftstage defaults; with staging on, only
fully-rescored costs enter the population (unrescored candidates reject
via NaN); sample geometry respects the shield degrade ladder's
tile-rows step-down; and the new Options knobs reach
``options_fingerprint`` so serve's executable cache and mesh AOT
serialization can never cross-serve precisions.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import symbolicregression_jl_tpu as sr
from symbolicregression_jl_tpu import make_dataset, search_key
from symbolicregression_jl_tpu.api.checkpoint import options_fingerprint
from symbolicregression_jl_tpu.core.losses import l2_dist_loss
from symbolicregression_jl_tpu.evolve.engine import Engine
from symbolicregression_jl_tpu.evolve.population import init_population
from symbolicregression_jl_tpu.evolve.step import (
    MIN_SAMPLE_ROWS,
    evolve_config_from_options,
    rescore_count,
    resolve_sample_rows,
)
from symbolicregression_jl_tpu.ops.complexity import (
    build_complexity_tables,
    compute_complexity_batch,
)
from symbolicregression_jl_tpu.ops.fused_eval import (
    fused_cost,
    strided_sample_indices,
)


# ---------------------------------------------------------------------------
# strided sampling + sample-size resolution
# ---------------------------------------------------------------------------


def test_strided_sample_indices_deterministic_and_bounded():
    idx = strided_sample_indices(10_000, 1250)
    assert idx.dtype == np.int32
    assert idx.shape == (1250,)
    assert idx[0] == 0 and idx[-1] < 10_000
    assert np.all(np.diff(idx) > 0)  # strictly increasing: no dup rows
    # replay-stable: same inputs, same rows (no RNG anywhere)
    assert np.array_equal(idx, strided_sample_indices(10_000, 1250))


def test_strided_sample_indices_degenerate():
    # sample >= dataset: every row, once
    assert np.array_equal(strided_sample_indices(7, 100), np.arange(7))
    with pytest.raises(ValueError):
        strided_sample_indices(100, 0)


def _cfg(**kw):
    opts = sr.Options(
        binary_operators=["+", "*"], unary_operators=["cos"], maxsize=10,
        save_to_file=False, **kw)
    return evolve_config_from_options(opts, 2)


def test_resolve_sample_rows_fraction_and_floor():
    cfg = _cfg(staged_eval=True, staged_sample_fraction=0.125)
    assert resolve_sample_rows(cfg, 10_000) == 1250
    # floor: tiny datasets screen at least MIN_SAMPLE_ROWS rows
    assert resolve_sample_rows(cfg, 100) == min(100, MIN_SAMPLE_ROWS)
    # never more rows than the dataset has
    assert resolve_sample_rows(cfg, 32) == 32


def test_resolve_sample_rows_explicit_override():
    cfg = _cfg(staged_eval=True, staged_sample_rows=777)
    assert resolve_sample_rows(cfg, 10_000) == 777


def test_resolve_sample_rows_capped_by_tile_rows():
    cfg = _cfg(staged_eval=True, staged_sample_rows=8192,
               eval_tile_rows=2048)
    assert resolve_sample_rows(cfg, 100_000) == 2048


def test_rescore_count():
    cfg = _cfg(staged_eval=True, rescore_fraction=0.25)
    assert rescore_count(cfg, 100) == 25
    assert rescore_count(cfg, 101) == 26   # ceil
    assert rescore_count(cfg, 1) == 1      # at least one rescore
    cfg1 = _cfg(staged_eval=True, rescore_fraction=1.0)
    assert rescore_count(cfg1, 64) == 64


def test_degrade_tile_rows_keeps_sample_inside_tile():
    """The graftshield OOM step-down halves eval_tile_rows; the staged
    screening sample must follow it down (sample_rows <= tile_rows at
    every rung), or the screen launch would span multiple row tiles of
    a geometry the shield just shrank to relieve memory pressure."""
    opts = sr.Options(
        binary_operators=["+", "*"], unary_operators=["cos"], maxsize=8,
        populations=2, population_size=8, tournament_selection_n=4,
        ncycles_per_iteration=2, save_to_file=False,
        staged_eval=True, staged_sample_rows=4096,
    )
    eng = Engine(opts, 2)
    n_rows = 1_000_000  # big enough that only the tile cap binds
    assert resolve_sample_rows(eng.cfg, n_rows) == 4096
    while True:
        new = eng.degrade_eval_tile_rows(floor=512)
        if new is None:
            break
        assert resolve_sample_rows(eng.cfg, n_rows) <= new
    assert eng.cfg.eval_tile_rows == 512
    assert resolve_sample_rows(eng.cfg, n_rows) == 512


# ---------------------------------------------------------------------------
# options_fingerprint x graftstage knobs
# ---------------------------------------------------------------------------


def _fp(**kw):
    return options_fingerprint(sr.Options(
        binary_operators=["+", "*"], unary_operators=["cos"],
        maxsize=10, save_to_file=False, **kw))


def test_fingerprint_distinguishes_precision_and_staging():
    """serve's ExecutableCache and mesh/aot.py key executables by
    options_fingerprint — two configs differing only in eval precision
    or staging knobs must never share a compiled program."""
    base = _fp()
    assert base is not None
    fps = {
        "base": base,
        "bf16": _fp(eval_precision="bf16"),
        "staged": _fp(staged_eval=True),
        "staged_rows": _fp(staged_eval=True, staged_sample_rows=512),
        "staged_frac": _fp(staged_eval=True, staged_sample_fraction=0.5),
        "rescore": _fp(staged_eval=True, rescore_fraction=0.5),
    }
    assert len(set(fps.values())) == len(fps), fps
    # explicit defaults == implicit defaults (no spurious cache split)
    assert _fp(eval_precision="f32", staged_eval=False) == base


def test_options_validate_graftstage_knobs():
    with pytest.raises(ValueError):
        sr.Options(binary_operators=["+"], eval_precision="f16",
                   save_to_file=False)
    with pytest.raises(ValueError):
        sr.Options(binary_operators=["+"], rescore_fraction=0.0,
                   save_to_file=False)
    with pytest.raises(ValueError):
        sr.Options(binary_operators=["+"], staged_sample_fraction=1.5,
                   save_to_file=False)
    with pytest.raises(ValueError):
        sr.Options(binary_operators=["+"], staged_sample_rows=-4,
                   save_to_file=False)


# ---------------------------------------------------------------------------
# bf16 kernel path: rank-reliable, f32 untouched
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def kernel_setup():
    opts = sr.Options(
        binary_operators=["+", "-", "*", "/"],
        unary_operators=["cos", "abs", "exp"],
        maxsize=20, save_to_file=False)
    cfg = evolve_config_from_options(opts, 3)
    tables = build_complexity_tables(opts, 3)
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.uniform(-3, 3, (3, 257)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=257).astype(np.float32))
    trees = init_population(jax.random.PRNGKey(3), 64, cfg.mctx,
                            jnp.float32)
    cx = compute_complexity_batch(trees, tables)
    kw = dict(baseline_loss=jnp.float32(1.7),
              use_baseline=jnp.bool_(True), parsimony=0.0032)
    return cfg, trees, cx, X, y, kw


def test_fused_cost_bf16_rank_reliable(kernel_setup):
    """bf16 row tiles keep an f32 reduction spine: losses agree with
    f32 to bf16 rounding, and the cost RANKING — the only thing the
    staged screen consumes — matches on the candidates that matter."""
    cfg, trees, cx, X, y, kw = kernel_setup
    c32, l32, v32 = fused_cost(
        trees, X, y, None, cx, cfg.operators, l2_dist_loss,
        interpret=True, **kw)
    c16, l16, v16 = fused_cost(
        trees, X, y, None, cx, cfg.operators, l2_dist_loss,
        interpret=True, bf16=True, **kw)
    assert l16.dtype == jnp.float32 and c16.dtype == jnp.float32
    a, b = np.asarray(c32), np.asarray(c16)
    ok = np.isfinite(a) & np.isfinite(b)
    assert ok.sum() >= 0.9 * len(a)  # finiteness verdicts mostly agree
    rel = np.abs(b[ok] - a[ok]) / (np.abs(a[ok]) + 1e-6)
    assert np.median(rel) < 0.02
    # top-quartile overlap: the screen's promotion set is stable
    k = max(1, int(ok.sum()) // 4)
    top32 = set(np.argsort(np.where(ok, a, np.inf))[:k])
    top16 = set(np.argsort(np.where(ok, b, np.inf))[:k])
    assert len(top32 & top16) >= 0.75 * k


def test_fused_cost_f32_default_unchanged_by_bf16_kwarg(kernel_setup):
    """bf16=False is the default and must be a no-op — same bits."""
    cfg, trees, cx, X, y, kw = kernel_setup
    c_a, l_a, _ = fused_cost(
        trees, X, y, None, cx, cfg.operators, l2_dist_loss,
        interpret=True, **kw)
    c_b, l_b, _ = fused_cost(
        trees, X, y, None, cx, cfg.operators, l2_dist_loss,
        interpret=True, bf16=False, **kw)
    assert np.array_equal(np.asarray(c_a), np.asarray(c_b))
    assert np.array_equal(np.asarray(l_a), np.asarray(l_b))


# ---------------------------------------------------------------------------
# engine level: defaults-off bit-identity + staged semantics
# ---------------------------------------------------------------------------
# slow tier: each _run_engine traces+compiles a full turbo engine
# (~1-2 min each on the 1-core CI box); the fast loop keeps the kernel
# and unit layers above, and CI's mesh-staged dryrun leg + the
# graftbench staged cells drive the engine path end-to-end.


def _run_engine(**kw):
    opts = sr.Options(
        binary_operators=["+", "*"], unary_operators=["cos"], maxsize=10,
        populations=2, population_size=12, tournament_selection_n=4,
        ncycles_per_iteration=3, save_to_file=False, turbo=True,
        telemetry=True, **kw)
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (300, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 1.0).astype(np.float32)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(opts.elementwise_loss)
    eng = Engine(opts, ds.nfeatures)
    state = eng.init_state(search_key(0), ds.data, 2)
    for _ in range(2):
        state = eng.run_iteration(state, ds.data, jnp.int32(opts.maxsize))
    return eng, state


@pytest.fixture(scope="module")
def default_engine_run():
    return _run_engine()


@pytest.fixture(scope="module")
def staged_engine_run():
    return _run_engine(staged_eval=True, staged_sample_fraction=0.25,
                       rescore_fraction=0.3)


@pytest.mark.slow
def test_engine_defaults_off_bit_identical(default_engine_run):
    """The graftstage A/B pin: Options that never mention the new knobs
    and Options passing their explicit defaults trace the SAME program
    and produce bit-identical search trajectories."""
    eng_a, a = default_engine_run
    assert not eng_a.cfg.staged_eval and not eng_a.cfg.eval_bf16
    eng_b, b = _run_engine(eval_precision="f32", staged_eval=False)
    for name in ("cost", "loss", "complexity", "birth", "ref"):
        assert np.array_equal(
            np.asarray(getattr(a.pops, name)),
            np.asarray(getattr(b.pops, name)), equal_nan=True), name
    for la, lb in zip(jax.tree.leaves(a.pops.trees),
                      jax.tree.leaves(b.pops.trees)):
        assert np.array_equal(np.asarray(la), np.asarray(lb),
                              equal_nan=True)
    assert np.array_equal(np.asarray(a.hof.cost), np.asarray(b.hof.cost),
                          equal_nan=True)


@pytest.mark.slow
def test_engine_staged_population_costs_are_full_data(staged_engine_run):
    """Staged acceptance consumes only fully-rescored costs: every
    population cost must equal a from-scratch FULL-dataset re-eval of
    that member (no sample-estimated cost ever survives into state)."""
    eng, s = staged_engine_run
    assert eng.cfg.staged_eval
    cost = np.asarray(s.pops.cost)
    assert np.all(np.isfinite(cost))
    # recompute costs of the final population on the full dataset via
    # the engine's own (unstaged) finalize evaluator
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (300, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 1.0).astype(np.float32)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(eng.options.elementwise_loss)
    flat = jax.tree.map(
        lambda x: x.reshape((-1,) + x.shape[2:]), s.pops.trees)
    c_ref, _, _ = eng._eval_cost(flat, ds.data)
    assert np.allclose(cost.reshape(-1), np.asarray(c_ref), rtol=1e-5,
                       atol=1e-6)


@pytest.mark.slow
def test_engine_staged_telemetry_counters(staged_engine_run):
    """screen/rescore counters expose the mechanism: every candidate is
    screened, only the configured fraction is rescored, and the
    full-eval row volume drops accordingly."""
    eng, s = staged_engine_run
    t = s.telem.cycle
    screen, rescore = int(t.screen_rows), int(t.rescore_rows)
    assert screen > 0 and 0 < rescore < screen
    # per-launch ceil(N * fraction): observed fraction is within one
    # candidate per launch of the configured one
    launches = int(t.screen_launches)
    assert launches == int(t.rescore_launches) > 0
    lo = eng.cfg.rescore_fraction
    hi = eng.cfg.rescore_fraction + launches / screen
    assert lo <= rescore / screen <= hi + 1e-9
    # the staged path adds the screen launch on top of the rescore one
    assert int(t.eval_launches) >= 2 * launches


@pytest.mark.slow
def test_unstaged_telemetry_counters_zero(default_engine_run):
    _, s = default_engine_run
    t = s.telem.cycle
    assert int(t.screen_rows) == 0 and int(t.rescore_rows) == 0
    assert int(t.screen_launches) == 0 and int(t.rescore_launches) == 0


# ---------------------------------------------------------------------------
# pulse: rescore_fraction drift rule
# ---------------------------------------------------------------------------


class _Hub:
    def __init__(self):
        self.anomalies = []

    def anomaly(self, metric, *, iteration, **detail):
        self.anomalies.append((metric, iteration, detail))

    def compile_snapshot(self):
        return {"traces": 0}


class _Ctx:
    def __init__(self, iteration, counters):
        self.iteration = iteration
        self.num_evals = 100.0 * iteration
        self.elapsed = float(iteration)
        self.host_fraction = 0.1
        self.counters = counters


def test_rescore_drift_rule_fires_and_stays_quiet():
    from symbolicregression_jl_tpu.pulse.anomaly import AnomalyDetector

    hub = _Hub()
    det = AnomalyDetector(hub, expected_rescore_fraction=0.25)
    # observed fraction matches the config: quiet
    det.on_iteration(_Ctx(1, ({"screen_rows": 400, "rescore_rows": 100},)))
    assert hub.anomalies == []
    # a program built from different knobs serves this search: fire
    det.on_iteration(_Ctx(2, ({"screen_rows": 400, "rescore_rows": 300},)))
    assert [(m, i) for m, i, _ in hub.anomalies] == [
        ("rescore_fraction_drift", 2)]
    detail = hub.anomalies[0][2]
    assert detail["value"] == 0.75 and detail["expected"] == 0.25


def test_rescore_drift_rule_dormant_without_config():
    from symbolicregression_jl_tpu.pulse.anomaly import AnomalyDetector

    hub = _Hub()
    det = AnomalyDetector(hub)  # staging off: no expected fraction
    det.on_iteration(_Ctx(1, ({"screen_rows": 400, "rescore_rows": 300},)))
    assert hub.anomalies == []


def test_invalid_fraction_rule_ignores_unrescored_nan_floor():
    """Staged runs count every unrescored candidate invalid (NaN cost by
    contract) — the structural floor must not read as a NaN storm."""
    from symbolicregression_jl_tpu.pulse.anomaly import AnomalyDetector

    hub = _Hub()
    det = AnomalyDetector(hub, expected_rescore_fraction=0.25)
    # 400 screened, 100 rescored -> 300 invalid are the structural
    # floor; 10/100 rescored invalid is healthy. Raw 310/400 = 0.775
    # would breach the 0.5 threshold; the adjusted rule stays quiet.
    det.on_iteration(_Ctx(1, ({
        "candidates": 400, "invalid": 310,
        "screen_rows": 400, "rescore_rows": 100},)))
    assert hub.anomalies == []
    # A genuine storm poisons the rescored candidates too: 95/100.
    det.on_iteration(_Ctx(2, ({
        "candidates": 400, "invalid": 395,
        "screen_rows": 400, "rescore_rows": 100},)))
    assert ("invalid_fraction", 2) in [
        (m, i) for m, i, _ in hub.anomalies]


def test_invalid_fraction_rule_unchanged_when_unstaged():
    from symbolicregression_jl_tpu.pulse.anomaly import AnomalyDetector

    hub = _Hub()
    det = AnomalyDetector(hub)
    det.on_iteration(_Ctx(1, ({"candidates": 100, "invalid": 80},)))
    assert [(m, i) for m, i, _ in hub.anomalies] == [
        ("invalid_fraction", 1)]
