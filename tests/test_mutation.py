"""Direct property tests for the structural mutation primitives.

Mirrors the reference's evolution-core suite (SURVEY.md §4:
test_rotation.jl, test_crossover.jl, test_feature_mutation.jl, ...):
every mutation output must be a valid postfix encoding (decode ->
re-encode round trip), rotate preserves node count
(/root/reference/src/MutationFunctions.jl:594-633), delete removes the
node and its non-carried children (:336-356), insert/append respect the
slot budget, and value mutations touch only their own fields.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu.evolve.mutation import (
    MutationContext,
    add_node,
    branch_nu,
    crossover_trees,
    delete_node,
    gen_random_tree_fixed_size,
    gen_tree_nu,
    insert_random_op,
    mutate_constant,
    mutate_feature,
    mutate_operator,
    randomize_tree,
    rotate_tree,
    swap_operands,
)
from symbolicregression_jl_tpu.ops.encoding import (
    LEAF_CONST,
    LEAF_VAR,
    TreeBatch,
    decode_tree,
    encode_tree,
)
from symbolicregression_jl_tpu.ops.operators import OperatorSet

L = 15
NFEAT = 3


@pytest.fixture(scope="module")
def ops():
    return OperatorSet(
        binary_operators=["+", "-", "*", "/"], unary_operators=["cos", "exp"]
    )


@pytest.fixture(scope="module")
def ctx(ops):
    return MutationContext(
        nops=ops.nops_tuple(),
        nfeatures=NFEAT,
        max_nodes=L,
        perturbation_factor=0.076,
        probability_negate_constant=0.01,
    )


def _random_trees(ctx, n, seed=0, min_size=3):
    """n random single trees ([L] TreeBatch each) of assorted sizes."""
    out = []
    key = jax.random.key(seed)
    i = 0
    while len(out) < n:
        key, k1, k2 = jax.random.split(key, 3)
        size = int(jax.random.randint(k1, (), min_size, L))
        t = gen_random_tree_fixed_size(k2, size, ctx, jnp.float32)
        out.append(t)
        i += 1
    return out


def _assert_valid_postfix(tree, ops, what):
    """Round-trip decode -> re-encode must reproduce the used slots."""
    arity = np.asarray(tree.arity)
    op = np.asarray(tree.op)
    feat = np.asarray(tree.feat)
    const = np.asarray(tree.const)
    length = int(tree.length)
    assert 1 <= length <= L, f"{what}: length {length} out of range"
    node = decode_tree(arity, op, feat, const, length, ops)  # raises if malformed
    re_a, re_o, re_f, re_c, re_len = encode_tree(node, L, ops)
    assert re_len == length, f"{what}: re-encode length mismatch"
    np.testing.assert_array_equal(re_a[:length], arity[:length], err_msg=what)
    np.testing.assert_array_equal(re_o[:length], op[:length], err_msg=what)
    np.testing.assert_array_equal(re_f[:length], feat[:length], err_msg=what)
    np.testing.assert_allclose(re_c[:length], const[:length], err_msg=what)
    return node


def _u(budget, seed):
    return jax.random.uniform(jax.random.key(seed), (budget,))


N_TRIALS = 25


def test_rotate_preserves_node_multiset(ctx, ops):
    budget = branch_nu(ctx)["rotate_tree"]
    for i, t in enumerate(_random_trees(ctx, N_TRIALS, seed=1)):
        new, ok = rotate_tree(_u(budget, i), t, ctx)
        assert bool(ok), f"trial {i}"
        _assert_valid_postfix(new, ops, f"rotate {i}")
        # rotation permutes spans: node count and the multiset of
        # (arity, op, feat, const) rows are both preserved
        assert int(new.length) == int(t.length)
        old_rows = sorted(
            (int(a), int(o), int(f), round(float(c), 5))
            for a, o, f, c in zip(
                np.asarray(t.arity)[: int(t.length)],
                np.asarray(t.op)[: int(t.length)],
                np.asarray(t.feat)[: int(t.length)],
                np.asarray(t.const)[: int(t.length)],
            )
        )
        new_rows = sorted(
            (int(a), int(o), int(f), round(float(c), 5))
            for a, o, f, c in zip(
                np.asarray(new.arity)[: int(new.length)],
                np.asarray(new.op)[: int(new.length)],
                np.asarray(new.feat)[: int(new.length)],
                np.asarray(new.const)[: int(new.length)],
            )
        )
        assert old_rows == new_rows, f"trial {i}"


def test_swap_operands_preserves_count_and_plus_semantics(ops):
    # Commutative root: swapping operands must not change the value.
    plus_ops = OperatorSet(binary_operators=["+"], unary_operators=["cos"])
    ctx2 = MutationContext(
        nops=plus_ops.nops_tuple(), nfeatures=NFEAT, max_nodes=L,
        perturbation_factor=0.076, probability_negate_constant=0.01,
    )
    from symbolicregression_jl_tpu.ops.eval import eval_tree_batch

    X = jnp.asarray(
        np.random.default_rng(0).normal(size=(NFEAT, 16)).astype(np.float32)
    )
    for i, t in enumerate(_random_trees(ctx2, N_TRIALS, seed=2)):
        new, ok = swap_operands(_u(ctx2.max_nodes, i), t, ctx2)
        assert bool(ok)
        _assert_valid_postfix(new, plus_ops, f"swap {i}")
        assert int(new.length) == int(t.length)
        batched = jax.tree.map(lambda a, b: jnp.stack([jnp.asarray(a), jnp.asarray(b)]), t, new)
        y, valid = eval_tree_batch(batched, X, plus_ops)
        np.testing.assert_allclose(
            np.asarray(y[0]), np.asarray(y[1]), rtol=1e-5, atol=1e-5
        )


def test_delete_removes_op_and_non_carried_children(ctx, ops):
    budget = branch_nu(ctx)["delete_node"]
    shrunk = 0
    for i, t in enumerate(_random_trees(ctx, N_TRIALS, seed=3)):
        has_op = bool(np.any(np.asarray(t.arity)[: int(t.length)] > 0))
        new, ok = delete_node(_u(budget, i), t, ctx)
        assert bool(ok)
        _assert_valid_postfix(new, ops, f"delete {i}")
        if has_op:
            assert int(new.length) < int(t.length)
            # op count drops by >= 1 (the deleted node, plus any ops in
            # dropped sibling spans)
            n_ops_old = int(np.sum(np.asarray(t.arity)[: int(t.length)] > 0))
            n_ops_new = int(np.sum(np.asarray(new.arity)[: int(new.length)] > 0))
            assert n_ops_new <= n_ops_old - 1
            shrunk += 1
    assert shrunk > 0


def test_delete_on_unary_chain_removes_exactly_one(ops):
    # cos(cos(x1)): deleting either op removes exactly one node.
    un_ops = OperatorSet(binary_operators=[], unary_operators=["cos"])
    ctxu = MutationContext(
        nops=un_ops.nops_tuple(), nfeatures=1, max_nodes=L,
        perturbation_factor=0.076, probability_negate_constant=0.01,
    )
    from symbolicregression_jl_tpu.ops.tree import parse_expression
    from symbolicregression_jl_tpu.ops.encoding import encode_population

    t = encode_population(
        [parse_expression("cos(cos(x1))", un_ops)], L, un_ops
    )[0]
    budget = branch_nu(ctxu)["delete_node"]
    for i in range(8):
        new, ok = delete_node(_u(budget, 100 + i), t, ctxu)
        assert bool(ok)
        assert int(new.length) == int(t.length) - 1
        _assert_valid_postfix(new, un_ops, f"unary delete {i}")


def test_insert_and_add_respect_slot_budget(ctx, ops):
    bi = branch_nu(ctx)["insert_node"]
    ba = branch_nu(ctx)["add_node"]
    grew = 0
    for i, t in enumerate(_random_trees(ctx, N_TRIALS, seed=4)):
        for name, fn, budget in (
            ("insert", insert_random_op, bi),
            ("add", add_node, ba),
        ):
            new, ok = fn(_u(budget, 10 * i + len(name)), t, ctx)
            # ok=False marks the attempt as failed — the generation step
            # discards it (first-valid selection), so only ok=True
            # results must be valid trees.
            if bool(ok):
                _assert_valid_postfix(new, ops, f"{name} {i}")
                assert int(new.length) <= L
                if int(new.length) > int(t.length):
                    grew += 1
    assert grew > 0


def test_insert_overflow_rejected(ctx, ops):
    # A tree already at the slot limit cannot grow: ok must be False.
    budget = branch_nu(ctx)["insert_node"]
    key = jax.random.key(7)
    t = gen_random_tree_fixed_size(key, L, ctx, jnp.float32)
    if int(t.length) < L - 1:
        pytest.skip("generator did not fill the slots")
    hit_reject = False
    for i in range(10):
        new, ok = insert_random_op(_u(budget, 200 + i), t, ctx)
        if not bool(ok):
            hit_reject = True
        else:
            # accepted results must still fit the slot budget
            assert int(new.length) <= L
    assert hit_reject


def test_crossover_produces_valid_children(ctx, ops):
    trees = _random_trees(ctx, 2 * N_TRIALS, seed=5)
    budget = 2 * ctx.max_nodes
    exchanged = 0
    for i in range(N_TRIALS):
        t1, t2 = trees[2 * i], trees[2 * i + 1]
        c1, c2, ok1, ok2 = crossover_trees(_u(budget, i), t1, t2, ctx)
        if bool(ok1):
            _assert_valid_postfix(c1, ops, f"xover child1 {i}")
            assert int(c1.length) <= L
        if bool(ok2):
            _assert_valid_postfix(c2, ops, f"xover child2 {i}")
            assert int(c2.length) <= L
        if bool(ok1) and int(c1.length) != int(t1.length):
            exchanged += 1
    assert exchanged > 0, "crossover never exchanged different-size subtrees"


def test_mutate_constant_touches_only_constants(ctx, ops):
    budget = branch_nu(ctx)["mutate_constant"]
    changed = 0
    for i, t in enumerate(_random_trees(ctx, N_TRIALS, seed=6)):
        new, ok = mutate_constant(_u(budget, i), t, jnp.float32(1.0), ctx)
        assert bool(ok)
        np.testing.assert_array_equal(np.asarray(new.arity), np.asarray(t.arity))
        np.testing.assert_array_equal(np.asarray(new.op), np.asarray(t.op))
        np.testing.assert_array_equal(np.asarray(new.feat), np.asarray(t.feat))
        assert int(new.length) == int(t.length)
        diff = np.asarray(new.const) != np.asarray(t.const)
        has_const = np.any(
            (np.asarray(t.arity)[: int(t.length)] == 0)
            & (np.asarray(t.op)[: int(t.length)] == LEAF_CONST)
        )
        if has_const and np.any(diff):
            # exactly one slot, and it is a constant leaf
            assert np.sum(diff) == 1
            k = int(np.argmax(diff))
            assert np.asarray(t.arity)[k] == 0
            assert np.asarray(t.op)[k] == LEAF_CONST
            changed += 1
    assert changed > 0


def test_mutate_operator_changes_one_op_same_arity(ctx, ops):
    budget = branch_nu(ctx)["mutate_operator"]
    for i, t in enumerate(_random_trees(ctx, N_TRIALS, seed=7)):
        new, ok = mutate_operator(_u(budget, i), t, ctx)
        assert bool(ok)
        _assert_valid_postfix(new, ops, f"mutate_operator {i}")
        np.testing.assert_array_equal(np.asarray(new.arity), np.asarray(t.arity))
        diff = np.asarray(new.op) != np.asarray(t.op)
        assert np.sum(diff) <= 1
        if np.any(diff):
            k = int(np.argmax(diff))
            assert np.asarray(t.arity)[k] > 0  # only operator slots change


def test_mutate_feature_stays_in_range(ctx, ops):
    budget = branch_nu(ctx)["mutate_feature"]
    changed = 0
    for i, t in enumerate(_random_trees(ctx, N_TRIALS, seed=8)):
        new, ok = mutate_feature(_u(budget, i), t, ctx)
        assert bool(ok)
        feats = np.asarray(new.feat)[: int(new.length)]
        leaves = (
            (np.asarray(new.arity)[: int(new.length)] == 0)
            & (np.asarray(new.op)[: int(new.length)] == LEAF_VAR)
        )
        assert np.all(feats[leaves] < NFEAT)
        diff = np.asarray(new.feat) != np.asarray(t.feat)
        if np.any(diff):
            assert np.sum(diff) == 1
            k = int(np.argmax(diff))
            # the changed leaf moved to a *different* feature
            assert np.asarray(t.op)[k] == LEAF_VAR
            changed += 1
    assert changed > 0


def test_mutate_feature_traced_nfeatures(ctx, ops):
    # templates pass a traced per-key feature count; n=1 must be a no-op
    budget = branch_nu(ctx)["mutate_feature"]
    t = _random_trees(ctx, 1, seed=9)[0]
    ctx_dyn = ctx._replace(nfeatures=jnp.int32(1))
    new, ok = mutate_feature(_u(budget, 0), t, ctx_dyn)
    np.testing.assert_array_equal(np.asarray(new.feat), np.asarray(t.feat))
    ctx_dyn2 = ctx._replace(nfeatures=jnp.int32(2))
    for i in range(10):
        new, _ = mutate_feature(_u(budget, i), t, ctx_dyn2)
        leaves = (
            (np.asarray(new.arity)[: int(new.length)] == 0)
            & (np.asarray(new.op)[: int(new.length)] == LEAF_VAR)
        )
        assert np.all(np.asarray(new.feat)[: int(new.length)][leaves] < 2)


def test_randomize_tree_valid_and_bounded(ctx, ops):
    budget = 1 + 8 * ctx.max_nodes
    for i, t in enumerate(_random_trees(ctx, N_TRIALS, seed=10)):
        new, ok = randomize_tree(_u(budget, i), t, jnp.int32(8), ctx)
        assert bool(ok)
        _assert_valid_postfix(new, ops, f"randomize {i}")
        assert int(new.length) <= L


def test_gen_random_tree_fixed_size_hits_target(ctx, ops):
    for seed in range(15):
        for target in (1, 3, 5, 8, 12):
            t = gen_random_tree_fixed_size(
                jax.random.key(seed * 31 + target), target, ctx, jnp.float32
            )
            _assert_valid_postfix(t, ops, f"gen {seed}/{target}")
            # generator fills the remaining budget with a unary op when
            # possible, so the size lands within 1 of the target
            assert abs(int(t.length) - target) <= 1
