"""Test configuration: force CPU JAX with 8 virtual devices.

Multi-chip sharding is tested on a virtual CPU mesh
(xla_force_host_platform_device_count), standing in for real TPU chips as
in SURVEY.md §4's implication notes.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = flags + " --xla_force_host_platform_device_count=8"
# The CPU backend's fast-math lowers transcendentals (log, gamma) with
# ~1e-5 relative error, failing golden-value tests that pass on TPU.
if "xla_cpu_enable_fast_math" not in flags:
    flags = flags + " --xla_cpu_enable_fast_math=false"
os.environ["XLA_FLAGS"] = flags.strip()
os.environ.setdefault("SYMBOLIC_REGRESSION_IS_TESTING", "true")

# The env var alone is not enough: this machine's sitecustomize registers
# an accelerator PJRT plugin and force-sets jax_platforms at interpreter
# start (before conftest runs), silently routing "CPU" tests to a remote
# chip and defeating the virtual 8-device mesh. Re-pin it after the fact.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Persistent compilation cache makes repeated test runs much faster.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
