"""Test configuration: force CPU JAX with 8 virtual devices.

Multi-chip sharding is tested on a virtual CPU mesh
(xla_force_host_platform_device_count), standing in for real TPU chips as
in SURVEY.md §4's implication notes.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("SYMBOLIC_REGRESSION_IS_TESTING", "true")
# Persistent compilation cache makes repeated test runs much faster.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
