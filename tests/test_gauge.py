"""graftgauge capacity observability: footprint ledger, memory sampler,
dispatch-latency histograms, headroom/proactive degrade.

Pins the contracts docs/OBSERVABILITY.md ("Capacity & memory") promises:

- the new ``gauge`` graftscope event validates (and the validator still
  rejects malformed ones);
- ``summarize_compiled`` flattens a real CPU executable's analyses and
  the ledger's record/lookup/predict answer shape queries;
- the dispatch-latency histogram buckets, quantiles, and Prometheus
  render behave (empty render is a no-op);
- the memory sampler degrades gracefully when ``memory_stats()`` is
  absent (CPU), feeds the leak tripwire, and hands the flight recorder
  a BASELINE-RELATIVE snapshot;
- the detector's ``live_bytes_growth`` rule fires exactly when
  documented and triggers a recorder bundle dump;
- the proactive degrader steps down from a watermark (never from an
  exception), honors cooldown, and records exhaustion;
- the AOT envelope carries the analysis summary so a loaded replica
  still reports footprint (satellite: mesh/aot.py);
- ``_is_oom`` recognizes every documented jaxlib RESOURCE_EXHAUSTED
  spelling (satellite: shield/degrade.py);
- ``telemetry report``'s metrics_view exposes ``peak_live_bytes``;
- gauge on vs off is bit-neutral to the search.
"""

import json
import os

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options, equation_search
from symbolicregression_jl_tpu.api.search import RuntimeOptions
from symbolicregression_jl_tpu.gauge import (
    DEFAULT_LE_BOUNDS,
    DispatchLatency,
    FootprintLedger,
    HeadroomModel,
    MemorySampler,
    ProactiveDegrader,
    geometry_key,
    global_ledger,
    summarize_compiled,
)
from symbolicregression_jl_tpu.pulse import (
    AnomalyDetector,
    AnomalyThresholds,
    FlightRecorder,
    PromText,
)
from symbolicregression_jl_tpu.pulse.metrics import histogram_quantile
from symbolicregression_jl_tpu.telemetry.hub import Telemetry
from symbolicregression_jl_tpu.telemetry.report import (
    metrics_view,
    summarize,
)
from symbolicregression_jl_tpu.telemetry.schema import validate_event


# ---------------------------------------------------------------------------
# schema: the gauge event kind
# ---------------------------------------------------------------------------


def _base(event, **kw):
    e = {"schema": "graftscope.v2", "t": 1.0, "run_id": "r",
         "event": event}
    e.update(kw)
    return e


@pytest.mark.parametrize("event", [
    _base("gauge", kind="memory", iteration=3,
          detail={"live_bytes": 4096, "live_arrays": 7,
                  "peak_live_bytes": 8192, "bytes_in_use": None}),
    _base("gauge", kind="watermark", iteration=9,
          detail={"peak_live_bytes": 8192, "baseline_bytes": 1024,
                  "phase_peaks": {"finalize": 2048}}),
    _base("gauge", kind="footprint", iteration=0,
          detail={"fingerprint": "ab12", "geometry": "r64xf2xo1",
                  "summary": {"total_bytes": 1234}}),
    _base("gauge", kind="dispatch_latency", iteration=3,
          detail={"count": 12, "sum_s": 0.5, "max_s": 0.2,
                  "buckets": {"0.001": 3, "inf": 1}}),
])
def test_gauge_events_validate(event):
    assert validate_event(event) == []


@pytest.mark.parametrize("event,fragment", [
    (_base("gauge", iteration=1, detail={}), "kind"),
    (_base("gauge", kind="memory", iteration="1", detail={}),
     "iteration"),
    (_base("gauge", kind="memory", iteration=1, detail=[]), "detail"),
])
def test_malformed_gauge_events_rejected(event, fragment):
    errors = validate_event(event)
    assert errors and any(fragment in e for e in errors), errors


# ---------------------------------------------------------------------------
# dispatch-latency histogram
# ---------------------------------------------------------------------------


def test_latency_buckets_and_quantiles():
    lat = DispatchLatency(le_bounds=(0.001, 0.01, 0.1))
    for s in (0.0005, 0.0007, 0.005, 0.05, 5.0):
        lat.observe(s)
    snap = lat.snapshot()
    assert snap["count"] == 5
    assert snap["counts"] == [2, 1, 1, 1]  # +Inf overflow slot
    assert snap["max_s"] == 5.0
    assert snap["sum_s"] == pytest.approx(0.0562 + 5.0)
    # p50 lands in the second bucket (upper bound 0.01); quantiles are
    # clamped so a wide-bucket estimate can never exceed the max
    assert snap["p50_s"] == 0.01
    assert snap["p99_s"] <= snap["max_s"]
    detail = lat.to_detail()
    assert detail["count"] == 5
    assert detail["buckets"] == {"0.001": 2, "0.01": 1, "0.1": 1,
                                 "inf": 1}


def test_latency_negative_clamped_and_default_bounds():
    lat = DispatchLatency()
    lat.observe(-1.0)  # clock skew: clamped to 0, first bucket
    assert lat.count == 1
    assert lat.snapshot()["counts"][0] == 1
    assert len(DEFAULT_LE_BOUNDS) == 20


def test_latency_render_promtext_and_empty_noop():
    p = PromText("graftserve")
    DispatchLatency().render(p)  # empty: no family at all
    assert p.render().strip() == ""
    lat = DispatchLatency(le_bounds=(0.001, 0.1))
    lat.observe(0.0005)
    lat.observe(0.05)
    lat.render(p)
    text = p.render()
    assert ('graftserve_dispatch_latency_seconds_bucket{le="0.001"} 1'
            in text)
    # cumulative: the 0.1 bucket includes the 0.001 one
    assert ('graftserve_dispatch_latency_seconds_bucket{le="0.1"} 2'
            in text)
    assert ('graftserve_dispatch_latency_seconds_bucket{le="+Inf"} 2'
            in text)
    assert "graftserve_dispatch_latency_seconds_count 2" in text


def test_histogram_quantile_edges():
    assert histogram_quantile((1.0, 2.0), [0, 0, 0], 0.5) is None
    assert histogram_quantile((1.0, 2.0), [4, 0, 0], 0.5) == 1.0
    assert histogram_quantile((1.0, 2.0), [1, 3, 0], 0.75) == 2.0


# ---------------------------------------------------------------------------
# footprint ledger
# ---------------------------------------------------------------------------


def test_geometry_key():
    assert geometry_key(rows=64, nfeatures=2) == "r64xf2xo1"
    assert geometry_key(rows=8, nfeatures=3, nout=2) == "r8xf3xo2"


def test_ledger_record_lookup_predict():
    led = FootprintLedger()
    assert led.record("fp", "g", None) is None  # nothing to store
    e = led.record("fp", "r64xf2xo1", {"total_bytes": 100},
                   source="test", rows=64, nfeatures=2, nout=1)
    assert e["compiles"] == 1 and len(led) == 1
    # re-record refreshes and bumps the compile count
    e = led.record("fp", "r64xf2xo1", {"total_bytes": 120},
                   source="test", rows=64, nfeatures=2, nout=1)
    assert e["compiles"] == 2
    led.record("fp", "r256xf2xo1", {"total_bytes": 900},
               source="test", rows=256, nfeatures=2, nout=1)
    assert led.known("fp", "r64xf2xo1")
    assert not led.known("fp", "r1xf1xo1")
    assert led.lookup("fp", "r64xf2xo1")["summary"]["total_bytes"] == 120
    # geometry=None -> largest-footprint entry for the fingerprint
    assert led.lookup("fp")["geometry"] == "r256xf2xo1"
    assert led.lookup("nope") is None
    # rows matches entries at or below the request (floor estimate)
    assert led.predict_bytes(rows=64, nfeatures=2) == 120
    assert led.predict_bytes(rows=500, nfeatures=2) == 900
    assert led.predict_bytes(rows=64, nfeatures=9) is None
    assert [e["geometry"] for e in led.entries()] == [
        "r256xf2xo1", "r64xf2xo1"]
    led.clear()
    assert len(led) == 0


def test_summarize_compiled_real_executable():
    import jax
    import jax.numpy as jnp

    compiled = jax.jit(lambda x: (x * 2.0).sum()).lower(
        jnp.ones((16,), jnp.float32)).compile()
    summary = summarize_compiled(compiled)
    assert summary is not None
    assert summary["total_bytes"] >= summary.get(
        "argument_size_in_bytes", 0)
    json.dumps(summary)  # JSON-able by contract


def test_summarize_compiled_tolerates_broken_analysis():
    class _Broken:
        def memory_analysis(self):
            raise RuntimeError("backend says no")

        def cost_analysis(self):
            raise RuntimeError("backend says no")

    assert summarize_compiled(_Broken()) is None


# ---------------------------------------------------------------------------
# memory sampler (CPU degrade path, recorder snapshot, leak feed)
# ---------------------------------------------------------------------------


class _FakeHub:
    def __init__(self):
        self.gauges = []

    def gauge(self, kind, *, iteration=0, **detail):
        self.gauges.append((kind, iteration, detail))


class _Ctx:
    """Minimal IterationContext stand-in for sink unit tests."""

    def __init__(self, iteration, *, num_evals=100.0, elapsed=1.0,
                 best_loss=0.5, evals_per_sec=100.0, device_s=0.9,
                 host_s=0.1, host_fraction=0.1, counters=()):
        self.iteration = iteration
        self.num_evals = num_evals
        self.elapsed = elapsed
        self.best_loss = best_loss
        self.evals_per_sec = evals_per_sec
        self.device_s = device_s
        self.host_s = host_s
        self.host_fraction = host_fraction
        self.counters = counters


def test_sampler_emits_and_degrades_without_memory_stats(monkeypatch):
    from symbolicregression_jl_tpu.gauge import sampler as mod

    # force the CPU degrade path regardless of backend
    monkeypatch.setattr(mod, "device_memory_stats", lambda: None)
    hub = _FakeHub()
    smp = MemorySampler(hub, emit_every=2)
    smp.on_iteration(_Ctx(1))
    smp.on_iteration(_Ctx(2))
    # emit_every=2: only iteration 2 emitted
    assert [g[0] for g in hub.gauges] == ["memory"]
    kind, it, detail = hub.gauges[0]
    assert it == 2
    assert detail["live_bytes"] >= 0
    assert detail["bytes_in_use"] is None  # degraded, not fabricated
    # recorder snapshot is baseline-relative
    snap = smp.deterministic_snapshot()
    assert set(snap) == {"live_bytes_delta", "live_arrays_delta"}
    smp.note_phase("finalize", 0.1)
    smp.emit_final(iteration=2)
    kind, it, detail = hub.gauges[-1]
    assert kind == "watermark"
    assert detail["peak_live_bytes"] >= detail["baseline_bytes"]
    assert "finalize" in detail["phase_peaks"]


def test_sampler_feeds_detector_and_degrader(monkeypatch):
    from symbolicregression_jl_tpu.gauge import sampler as mod

    monkeypatch.setattr(mod, "device_memory_stats",
                        lambda: {"bytes_in_use": 900, "bytes_limit": 1000})
    fed, checked = [], []

    class _Det:
        def observe_live_bytes(self, it, b):
            fed.append((it, b))

    class _Deg:
        def check(self, it, *, watermark_bytes, limit_bytes=None):
            checked.append((it, watermark_bytes, limit_bytes))
            return False

    smp = MemorySampler(_FakeHub(), detector=_Det(), degrader=_Deg())
    smp.on_iteration(_Ctx(5))
    assert fed and fed[0][0] == 5
    # allocator watermark preferred over live-array bytes
    assert checked == [(5, 900, 1000)]


# ---------------------------------------------------------------------------
# leak tripwire + recorder anomaly-triggered dump
# ---------------------------------------------------------------------------


def _tripwire_detector(hub, **kw):
    t = AnomalyThresholds(leak_window=3, leak_min_bytes=100, **kw)
    return AnomalyDetector(hub, thresholds=t)


def test_leak_tripwire_fires_and_resets(tmp_path):
    hub = Telemetry(
        Options(telemetry=True, save_to_file=False),
        run_id="leak", out_dir=str(tmp_path), niterations=20, nout=1)
    seen = []
    hub.add_watcher(seen.append)
    det = _tripwire_detector(hub)
    # strictly increasing but below min growth: silent
    for it, b in enumerate([0, 10, 20, 30]):
        det.observe_live_bytes(it, b)
    assert not [e for e in seen if e["event"] == "anomaly"]
    # a non-increase resets the streak and the base
    det.observe_live_bytes(4, 5)
    for it, b in enumerate([50, 120, 400, 900], start=5):
        det.observe_live_bytes(it, b)
    anomalies = [e for e in seen if e["event"] == "anomaly"]
    assert len(anomalies) == 1
    a = anomalies[0]
    assert a["metric"] == "live_bytes_growth"
    assert a["detail"]["growth_bytes"] >= 100


def test_leak_anomaly_triggers_recorder_dump(tmp_path):
    hub = Telemetry(
        Options(telemetry=True, save_to_file=False),
        run_id="leak", out_dir=str(tmp_path), niterations=20, nout=1)
    path = tmp_path / "pulse_bundle.json"
    rec = FlightRecorder(path=str(path), run_id="leak", hub=hub)
    hub.add_sink(rec)
    hub.add_watcher(rec.on_event)
    det = _tripwire_detector(hub)
    smp = MemorySampler(hub, detector=det, recorder=rec)
    for it, b in enumerate([0, 200, 400, 600, 800]):
        rec.on_iteration(_Ctx(it))
        det.observe_live_bytes(it, b)
    assert path.exists()
    with open(path) as f:
        bundle = json.load(f)
    assert bundle["trigger"]["reason"] == "anomaly"
    assert bundle["trigger"]["kind"] == "live_bytes_growth"
    # the sampler's provider put the baseline-relative snapshot in the
    # deterministic per-iteration record
    smp.on_iteration(_Ctx(9))
    rec.on_iteration(_Ctx(9))
    bundle = rec.snapshot(trigger={"reason": "manual"})
    assert bundle["iterations"][-1]["memory"] is not None
    assert "live_bytes_delta" in bundle["iterations"][-1]["memory"]


# ---------------------------------------------------------------------------
# headroom model + proactive degrader
# ---------------------------------------------------------------------------


def test_headroom_advise_requires_history(monkeypatch):
    led = FootprintLedger()
    model = HeadroomModel(led)
    assert model.advise(bucket=(64, 2, 1)) is None  # no history
    led.record("fp", "r64xf2xo1", {"total_bytes": 400},
               rows=64, nfeatures=2, nout=1)
    adv = model.advise(bucket=(64, 2, 1), limit_bytes=1000,
                       in_use_bytes=500)
    assert adv == {"predicted_bytes": 400, "limit_bytes": 1000,
                   "in_use_bytes": 500, "headroom_bytes": 500,
                   "fits": True}
    adv = model.advise(bucket=(64, 2, 1), limit_bytes=700,
                       in_use_bytes=500)
    assert adv["fits"] is False
    # no limit known (CPU): prediction reported, fits unknowable
    from symbolicregression_jl_tpu.gauge import capacity as mod

    monkeypatch.setattr(mod, "device_memory_stats", lambda: None)
    adv = model.advise(bucket=(64, 2, 1))
    assert adv["predicted_bytes"] == 400 and adv["fits"] is None


def test_proactive_degrader_steps_down_with_cooldown():
    steps = [512, 256, None]

    class _Hub(_FakeHub):
        def __init__(self):
            super().__init__()
            self.faults = []

        def fault(self, kind, *, iteration=0, **detail):
            self.faults.append((kind, iteration, detail))

    hub = _Hub()
    deg = ProactiveDegrader(lambda: steps.pop(0),
                            headroom_fraction=0.5, limit_bytes=1000,
                            hub=hub, cooldown=2)
    assert not deg.check(0, watermark_bytes=400)  # under threshold
    assert deg.check(1, watermark_bytes=600)      # fires: 512
    # cooldown: iterations 2..3 are skipped even above threshold
    assert not deg.check(2, watermark_bytes=999)
    assert not deg.check(3, watermark_bytes=999)
    assert deg.check(4, watermark_bytes=800)      # fires: 256
    assert deg.degrades == 2
    # floor reached: records exhaustion once, then stays quiet
    assert not deg.check(7, watermark_bytes=999)
    assert deg.exhausted
    assert not deg.check(10, watermark_bytes=999)
    kinds = [k for k, _, _ in hub.faults]
    assert kinds == ["proactive_degrade"] * 3
    assert hub.faults[-1][2]["exhausted"] is True
    assert hub.faults[0][2]["eval_tile_rows"] == 512


def test_proactive_degrader_dormant_without_limit_and_never_raises():
    deg = ProactiveDegrader(lambda: 1 / 0, headroom_fraction=0.5)
    assert not deg.check(0, watermark_bytes=10**12)  # no limit: dormant
    deg2 = ProactiveDegrader(lambda: 1 / 0, headroom_fraction=0.5,
                             limit_bytes=10)
    assert not deg2.check(0, watermark_bytes=100)  # degrade raised
    with pytest.raises(ValueError):
        ProactiveDegrader(lambda: None, headroom_fraction=1.5)


# ---------------------------------------------------------------------------
# satellite: mesh AOT envelope carries the analysis summary
# ---------------------------------------------------------------------------


def test_aot_envelope_carries_analysis(tmp_path):
    import jax

    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.mesh import MeshEngine, MeshPlan
    from symbolicregression_jl_tpu.mesh.aot import (
        aot_serialization_supported,
        compile_iteration,
        load_executable,
        save_executable,
    )
    from symbolicregression_jl_tpu import search_key

    rng = np.random.default_rng(7)
    X = rng.uniform(-2, 2, (48, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1]).astype(np.float32)
    ds = make_dataset(X, y)
    options = Options(
        binary_operators=["+", "-", "*"], unary_operators=[],
        maxsize=8, populations=2, population_size=8,
        ncycles_per_iteration=2, tournament_selection_n=4,
        optimizer_probability=0.0, save_to_file=False)
    plan = MeshPlan.build(jax.devices()[:1], n_island_shards=1)
    engine = MeshEngine(options, ds.nfeatures, plan)
    state = plan.place_state(
        engine.init_state(search_key(11), ds.data, options.populations))

    global_ledger().clear()
    ex = compile_iteration(engine, state, ds.data)
    assert ex.analysis is not None
    assert ex.analysis["geometry"] == geometry_key(rows=48, nfeatures=2)
    assert ex.memory_analysis() is not None
    # compile recorded into the process ledger (source mesh_aot)
    entry = global_ledger().lookup(ex.analysis["fingerprint"],
                                   ex.analysis["geometry"])
    assert entry is not None and entry["source"] == "mesh_aot"

    if not aot_serialization_supported():
        pytest.skip("jax build cannot serialize executables")
    from jax.lib import xla_client

    try:
        path = save_executable(ex, os.fspath(tmp_path / "iter.aotx"))
        global_ledger().clear()
        ex2 = load_executable(path, expect_key=ex.cache_key)
    except xla_client.XlaRuntimeError as e:  # pragma: no cover
        # some backends/sessions refuse (de)serializing particular
        # executables; the gauge-smoke CI job pins the round-trip in a
        # clean process either way
        global_ledger().clear()
        pytest.skip(f"backend refused executable serialization: {e}")
    # the loaded replica reports footprint WITHOUT recompiling: the
    # envelope's stamped analysis backs both accessors and the ledger
    assert ex2.analysis == ex.analysis
    # a live analysis object where the backend re-exposes one, the
    # stamped-envelope dict otherwise — either way, not None
    assert ex2.memory_analysis() is not None
    entry = global_ledger().lookup(ex.analysis["fingerprint"],
                                   ex.analysis["geometry"])
    assert entry is not None and entry["source"] == "aot_load"
    global_ledger().clear()


# ---------------------------------------------------------------------------
# satellite: OOM marker spellings (shield/degrade.py)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("message", [
    "RESOURCE_EXHAUSTED: Out of memory while trying to allocate",
    "Resource exhausted: Out of memory allocating 1073741824 bytes",
    "Out of memory allocating 8589934592 bytes.",
    "error: out of memory trying to allocate a buffer",
    "Failed to allocate request for 2.00GiB (2147483648B) on device",
])
def test_is_oom_accepts_jaxlib_spellings(message):
    from symbolicregression_jl_tpu.shield.degrade import (
        _is_oom,
        is_transient_failure,
    )

    exc = RuntimeError(message)
    assert _is_oom(exc)
    # every OOM marker must also classify transient, or the ShieldRunner
    # re-raises before the degrade ladder ever runs
    assert is_transient_failure(exc)


@pytest.mark.parametrize("message", [
    "INVALID_ARGUMENT: shapes do not match",
    "UNAVAILABLE: link down",  # transient, but not an OOM
    "DEADLINE_EXCEEDED: collective timed out",
])
def test_is_oom_rejects_non_oom(message):
    from symbolicregression_jl_tpu.shield.degrade import _is_oom

    assert not _is_oom(RuntimeError(message))


# ---------------------------------------------------------------------------
# report / metrics_view / serve scrape / timeline surfaces
# ---------------------------------------------------------------------------


def _gauge_stream():
    return [
        _base("run_start", niterations=3, nout=1, backend="cpu",
              n_devices=1, log_interval=1),
        _base("gauge", kind="memory", iteration=1,
              detail={"live_bytes": 1000, "live_arrays": 5,
                      "peak_live_bytes": 1000}),
        _base("gauge", kind="memory", iteration=2,
              detail={"live_bytes": 3000, "live_arrays": 6,
                      "peak_live_bytes": 3000, "bytes_in_use": 4096}),
        _base("gauge", kind="watermark", iteration=2,
              detail={"peak_live_bytes": 3000, "baseline_bytes": 200}),
        _base("gauge", kind="dispatch_latency", iteration=2,
              detail={"count": 4, "sum_s": 0.4, "max_s": 0.2,
                      "p50_s": 0.05, "p99_s": 0.2}),
        _base("gauge", kind="footprint", iteration=0,
              detail={"fingerprint": "ab", "geometry": "r64xf2xo1",
                      "summary": {"total_bytes": 777}}),
        _base("run_end", stop_reason="niterations", iterations=2,
              num_evals=10.0, elapsed_s=1.0),
    ]


def test_report_and_metrics_view_gauge_section():
    s = summarize(_gauge_stream())
    g = s["gauge"]
    assert g["peak_live_bytes"] == 3000
    assert g["by_kind"]["memory"] == 2
    assert g["dispatch_latency"]["count"] == 4
    assert g["footprint_max_bytes"] == 777
    assert metrics_view(s)["peak_live_bytes"] == 3000
    from symbolicregression_jl_tpu.telemetry.report import format_report

    text = format_report(s)
    assert "peak live 3,000 B" in text
    assert "dispatch latency" in text


def test_tail_folds_gauge_events():
    from symbolicregression_jl_tpu.telemetry.tail import TailState

    st = TailState()
    for e in _gauge_stream():
        st.update(e)
    assert st.gauge["memory"] == 2
    assert st.last_memory["peak_live_bytes"] == 3000
    assert "memory: peak 3,000 B" in st.render()


def test_timeline_renders_memory_counter_track(tmp_path):
    from symbolicregression_jl_tpu.ledger.timeline import (
        build_timeline,
        validate_chrome_trace,
    )

    run = tmp_path / "run"
    run.mkdir()
    with open(run / "telemetry.jsonl", "w") as f:
        for e in _gauge_stream():
            f.write(json.dumps(e) + "\n")
    doc = build_timeline(str(run))
    assert validate_chrome_trace(doc) == []
    counters = [e for e in doc["traceEvents"]
                if e["ph"] == "C" and e["name"] == "memory"]
    assert len(counters) == 2
    assert counters[1]["args"]["bytes_in_use"] == 4096
    instants = [e["name"] for e in doc["traceEvents"] if e["ph"] == "i"]
    assert "gauge:footprint" in instants


def test_serve_metrics_render_gauge(tmp_path):
    from symbolicregression_jl_tpu.serve.metrics import (
        render_gauge_metrics,
    )
    from symbolicregression_jl_tpu.gauge.latency import global_latency

    global_ledger().clear()
    global_ledger().record("fingerprint123", "r64xf2xo1",
                           {"total_bytes": 555}, source="test",
                           rows=64, nfeatures=2, nout=1)
    global_latency().observe(0.005)
    p = PromText("graftserve")
    render_gauge_metrics(p)
    text = p.render()
    assert "graftserve_process_peak_live_bytes" in text
    # fingerprint label is truncated to 12 chars (cardinality hygiene)
    assert ('graftserve_footprint_bytes{fingerprint="fingerprint1"'
            in text)
    assert "555" in text
    assert "graftserve_dispatch_latency_seconds_bucket" in text
    global_ledger().clear()


def test_admission_attaches_memory_advisory():
    from symbolicregression_jl_tpu.serve.admission import (
        AdmissionController,
    )

    led = FootprintLedger()
    led.record("fp", "r64xf2xo1", {"total_bytes": 400},
               rows=64, nfeatures=2, nout=1)
    ctrl = AdmissionController(
        capacity=2, headroom=HeadroomModel(led),
        memory_limit_bytes=1000)
    d = ctrl.admit(n_rows=64, nfeatures=2, request_id="r1")
    assert d.memory is not None
    assert d.memory["predicted_bytes"] == 400
    assert d.memory["fits"] is True
    # advisory only: a non-fitting prediction still admits
    ctrl2 = AdmissionController(
        capacity=2, headroom=HeadroomModel(led), memory_limit_bytes=10)
    d2 = ctrl2.admit(n_rows=64, nfeatures=2, request_id="r2")
    assert d2.memory["fits"] is False


# ---------------------------------------------------------------------------
# full-search contract: gauge on/off bit-neutrality
# ---------------------------------------------------------------------------


def _problem(n=200, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, (n, 2)).astype(np.float32)
    y = (2.0 * X[:, 0] + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(tmp_path):
    return Options(
        binary_operators=["+", "-", "*"],
        unary_operators=[],
        maxsize=10,
        populations=2,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=4,
        save_to_file=True,
        output_directory=str(tmp_path),
        telemetry=True,
    )


def _gauge_run(tmp_path, sub, *, gauge=True):
    X, y = _problem()
    state, _ = equation_search(
        X, y, options=_options(tmp_path / sub),
        runtime_options=RuntimeOptions(
            niterations=3, run_id="det", seed=7, verbosity=0,
            gauge=gauge),
        return_state=True)
    return state, os.path.join(tmp_path, sub, "det")


@pytest.mark.slow  # 2 full searches; CI's gauge-smoke job covers the
# leak->anomaly->bundle and watermark->degrade paths on every push
def test_gauge_bit_neutral_and_stream_has_gauge_events(tmp_path):
    """Gauge ON vs OFF produces a bit-identical hall of fame — the
    sampler and latency timer read only the wall clock and the live
    array registry, never the search state."""
    from symbolicregression_jl_tpu.telemetry.schema import load_events

    s1, dir1 = _gauge_run(tmp_path, "a", gauge=True)
    events = load_events(os.path.join(dir1, "telemetry.jsonl"))
    kinds = {e["kind"] for e in events if e["event"] == "gauge"}
    assert {"memory", "watermark"} <= kinds

    s2, dir2 = _gauge_run(tmp_path, "b", gauge=False)
    events = load_events(os.path.join(dir2, "telemetry.jsonl"))
    assert not [e for e in events if e["event"] == "gauge"]
    a, b = s1.device_states[0], s2.device_states[0]
    for f in ("arity", "op", "feat", "const", "length"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.hof.trees, f)),
            np.asarray(getattr(b.hof.trees, f)))
    np.testing.assert_array_equal(np.asarray(a.hof.cost),
                                  np.asarray(b.hof.cost))
    np.testing.assert_array_equal(np.asarray(a.pops.cost),
                                  np.asarray(b.pops.cost))
