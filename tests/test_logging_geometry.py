"""SRLogger geometry: convex_hull / pareto_volume on degenerate inputs
plus a golden-value check against a hand-computed hull
(src/Logging.jl:157-215 analogues)."""

import math

import numpy as np
import pytest

from symbolicregression_jl_tpu.utils.logging import convex_hull, pareto_volume


def _shoelace(pts):
    area = 0.0
    n = len(pts)
    for i in range(n):
        x1, y1 = pts[i]
        x2, y2 = pts[(i + 1) % n]
        area += x1 * y2 - x2 * y1
    return abs(area) / 2.0


# ---------------------------------------------------------------------------
# convex_hull
# ---------------------------------------------------------------------------


def test_hull_fewer_than_three_points_returned_verbatim():
    one = np.array([[1.0, 2.0]])
    np.testing.assert_array_equal(convex_hull(one), one)
    two = np.array([[0.0, 0.0], [1.0, 1.0]])
    np.testing.assert_array_equal(convex_hull(two), two)


def test_hull_golden_square_with_interior_and_duplicate_points():
    """Hand-computed golden: the hull of a unit square + an interior
    point + a duplicated corner is exactly the four corners, area 1."""
    pts = np.array([
        [0.0, 0.0], [1.0, 0.0], [1.0, 1.0], [0.0, 1.0],
        [0.5, 0.5],              # interior: must not be on the hull
        [0.0, 0.0],              # duplicate corner: must not break it
    ])
    hull = convex_hull(pts)
    corners = {(0.0, 0.0), (1.0, 0.0), (1.0, 1.0), (0.0, 1.0)}
    assert {tuple(p) for p in hull} == corners
    assert _shoelace(hull) == pytest.approx(1.0)


def test_hull_collinear_points_terminate():
    pts = np.array([[0.0, 0.0], [1.0, 1.0], [2.0, 2.0]])
    hull = convex_hull(pts)
    # gift wrapping keeps collinear points but must terminate with a
    # zero-area (degenerate) polygon
    assert 2 <= hull.shape[0] <= 3
    assert _shoelace(hull) == pytest.approx(0.0)


def test_hull_all_identical_points_terminate():
    pts = np.tile(np.array([[3.0, -1.0]]), (5, 1))
    hull = convex_hull(pts)
    assert hull.shape[1] == 2
    assert _shoelace(hull) == pytest.approx(0.0)


# ---------------------------------------------------------------------------
# pareto_volume
# ---------------------------------------------------------------------------


def test_pareto_volume_golden_two_point_front():
    """Hand-computed: losses [1, 0.1] at complexities [1, 3], maxsize 7.

    In (log10 cx, log10 loss) space the front is (0, 0) -> (log10 3, -1);
    the closure adds (log10 8, 0) and (0, 0) [min-x at max-y], so the
    hull is the triangle (0,0), (log10 3, -1), (log10 8, 0) with area
    log10(8) * 1 / 2.
    """
    vol = pareto_volume([1.0, 0.1], [1, 3], maxsize=7)
    assert vol == pytest.approx(math.log10(8.0) / 2.0, rel=1e-9)


def test_pareto_volume_empty_and_nonpositive():
    assert pareto_volume([], [], maxsize=10) == 0.0
    # log scaling drops non-positive losses entirely
    assert pareto_volume([0.0, -1.0], [1, 2], maxsize=10) == 0.0
    # inf / nan losses are filtered, not propagated
    assert pareto_volume([np.inf, np.nan], [1, 2], maxsize=10) == 0.0


def test_pareto_volume_single_point_is_finite():
    vol = pareto_volume([1.0], [1], maxsize=7)
    # degenerate y-range is widened by 1 decade: triangle
    # (0,0)-(log10 8, 1)-(0, 1), area log10(8)/2
    assert vol == pytest.approx(math.log10(8.0) / 2.0, rel=1e-9)
    assert np.isfinite(vol)


def test_pareto_volume_single_complexity_front():
    # duplicate complexities: the front collapses to one x; volume is the
    # closure triangle, finite and positive
    vol = pareto_volume([1.0, 0.5], [2, 2], maxsize=7)
    expected = (math.log10(8.0) - math.log10(2.0)) * math.log10(2.0) / 2.0
    assert vol == pytest.approx(expected, rel=1e-9)


def test_pareto_volume_duplicate_points_match_unique():
    a = pareto_volume([1.0, 0.1, 0.1], [1, 3, 3], maxsize=7)
    b = pareto_volume([1.0, 0.1], [1, 3], maxsize=7)
    assert a == pytest.approx(b, rel=1e-9)


def test_pareto_volume_linear_scaling_keeps_nonpositive():
    vol = pareto_volume([1.0, 0.0], [1, 3], maxsize=7,
                        use_linear_scaling=True)
    assert np.isfinite(vol) and vol > 0.0
