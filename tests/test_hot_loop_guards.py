"""Hot-loop budget regression: one full evolve iteration, run warm, must
neither recompile nor perform implicit host↔device transfers.

This pins the two properties that silently rot in a JAX codebase:

- recompilation (a shape / static-arg / weak-type drift in any of the
  iteration's jitted programs) — caught by graftlint's
  ``compile_count_guard`` via jax.monitoring trace events;
- hidden host syncs in the iteration path (e.g. a Python scalar
  uploaded per call, or a traced value pulled to host) — caught by
  ``jax.transfer_guard("disallow")`` via graftlint's ``no_transfer``.

The engine audit hook (`options.debug_checks`) is exercised on the
warm-up iterations so the postfix invariants are also re-checked on real
engine output here.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu import Options, make_dataset
from symbolicregression_jl_tpu.evolve.engine import Engine
from symbolicregression_jl_tpu.lint.runtime import (
    CompileBudgetExceeded,
    compile_count_guard,
    no_transfer,
)


@pytest.fixture(scope="module",
                params=["jnp", "turbo-fused", "turbo-telemetry"])
def engine_and_state(request):
    # "turbo-fused" pins the round-6 hot path: the fused Pallas eval
    # with the in-kernel cost epilogue (interpret mode off-TPU) must be
    # exactly as trace- and transfer-free as the jnp fallback.
    # "turbo-telemetry" additionally turns on the graftscope device
    # counters (round 7): the accumulators ride the scan carry and the
    # engine state, so a warm iteration must STILL show 0 traces and 0
    # implicit transfers with them enabled.
    turbo = request.param != "jnp"
    opts = Options(
        binary_operators=["+", "*"],
        unary_operators=["cos"],
        maxsize=10,
        populations=2,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=3,
        save_to_file=False,
        debug_checks=True,  # postfix-invariant audit on warm-up output
        turbo=turbo,
        telemetry=request.param == "turbo-telemetry",
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (64, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 1.0).astype(np.float32)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(opts.elementwise_loss)
    eng = Engine(opts, ds.nfeatures)
    state = eng.init_state(jax.random.key(0), ds.data, 2)
    return opts, eng, ds, state


def test_warm_evolve_cycle_is_sync_and_recompile_free(engine_and_state):
    opts, eng, ds, state = engine_and_state
    # Device-resident cur_maxsize, uploaded once outside the guarded
    # region (the search loop does the same; a host int here would be a
    # per-iteration host→device transfer).
    cm = jnp.int32(opts.maxsize)

    # Warm-up: compiles the iteration programs, audits the outputs
    # (options.debug_checks=True -> validate_programs on every state).
    state = eng.run_iteration(state, ds.data, cm)
    state = eng.run_iteration(state, ds.data, cm)

    # The audit itself pulls tables to host — not part of the budget.
    opts.debug_checks = False
    try:
        with no_transfer():
            with compile_count_guard(
                max_compiles=1, what="warm evolve iteration"
            ) as stats:
                state = eng.run_iteration(state, ds.data, cm)
            jax.block_until_ready(state.pops.cost)
    finally:
        opts.debug_checks = True
    # the pin observed on CPU and TPU backends alike: a warm iteration
    # compiles NOTHING (budget 1 above leaves headroom for backend quirks)
    assert stats.traces <= 1, (
        f"warm iteration traced {stats.traces} programs "
        f"({stats.backend_compiles} backend compiles)"
    )


def test_compile_count_guard_catches_fresh_compiles():
    with pytest.raises(CompileBudgetExceeded):
        with compile_count_guard(max_compiles=0, what="fresh jit"):
            # fresh lambda => guaranteed fresh trace + compile
            jax.jit(lambda x: x * 2 + 1)(jnp.ones(11)).block_until_ready()


def test_compile_count_guard_allows_cached_calls():
    f = jax.jit(lambda x: x * 3)
    x = jnp.ones(13)
    f(x).block_until_ready()  # compile outside the guard
    with compile_count_guard(max_compiles=0, what="cached jit"):
        f(x).block_until_ready()


def test_transfer_guard_catches_implicit_host_upload():
    # Note: on the CPU backend device->host pulls are free (shared
    # memory) and never trip the guard, so the reliable cross-backend
    # probe is the host->device direction: a numpy operand silently
    # uploaded into a device computation.
    x = jnp.arange(8.0)
    jax.block_until_ready(x + 1)  # warm the kernel outside the guard
    with pytest.raises(Exception, match="[Dd]isallowed"):
        with no_transfer():
            jax.block_until_ready(x + np.arange(8.0))
