"""graftscope telemetry: device counters, JSONL hub, report CLI.

Pins the three contracts docs/OBSERVABILITY.md promises:

- counter identities — the in-graph accumulators agree with the static
  launch arithmetic of the evolve cycle (proposed slots, eval rows,
  launch counts) and with each other (accepted <= proposed, invalid <=
  candidates);
- zero perturbation — a search with ``telemetry=True`` is bit-identical
  to the same search with it off (the counters only read values the
  step already computed);
- the JSONL stream validates against graftscope.v1 and the report CLI
  summarizes it without error.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu import Options, equation_search, make_dataset
from symbolicregression_jl_tpu.evolve.engine import Engine
from symbolicregression_jl_tpu.telemetry.report import (
    format_report,
    main as report_main,
    summarize,
)
from symbolicregression_jl_tpu.telemetry.schema import (
    SCHEMA_VERSION,
    validate_event,
    validate_lines,
)


def _opts(**kw):
    base = dict(
        binary_operators=["+", "*"],
        unary_operators=["cos"],
        maxsize=10,
        populations=2,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=3,
        save_to_file=False,
    )
    base.update(kw)
    return Options(**base)


def _dataset():
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (64, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 1.0).astype(np.float32)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(Options().elementwise_loss)
    return ds


@pytest.fixture(scope="module")
def telemetry_iteration():
    """One engine iteration with counters on; returns (opts, eng, telem)."""
    opts = _opts(telemetry=True)
    ds = _dataset()
    eng = Engine(opts, ds.nfeatures)
    state = eng.init_state(jax.random.key(0), ds.data, 2)
    state = eng.run_iteration(state, ds.data, jnp.int32(opts.maxsize))
    return opts, eng, ds, jax.device_get(state.telem)


def test_counter_identities(telemetry_iteration):
    opts, eng, ds, t = telemetry_iteration
    I = opts.populations
    P = opts.population_size
    B = eng.cfg.n_slots
    C = opts.ncycles_per_iteration
    # every slot proposes exactly once per cycle per island
    assert int(t.cycle.proposed.sum()) == I * B * C
    assert (np.asarray(t.cycle.accepted) <= np.asarray(t.cycle.proposed)).all()
    # reject reasons partition the proposals
    assert int(t.cycle.reject_reasons.sum()) == I * B * C
    assert 0 <= int(t.cycle.invalid) <= int(t.cycle.candidates)
    # one candidate-eval launch per island per cycle + the finalize
    assert int(t.cycle.eval_launches) == I * C + 1
    # in-cycle rows are static per step; finalize adds I*P
    per_step = (int(t.cycle.eval_rows) - I * P) // (I * C)
    assert per_step * I * C + I * P == int(t.cycle.eval_rows)
    assert B <= per_step <= 2 * B
    # finalize dup stats cover the whole member axis
    assert int(t.finalize_rows) == I * P
    assert 1 <= int(t.finalize_unique) <= I * P
    # histograms cover at most the population (non-finite losses drop out)
    assert int(t.loss_hist.sum()) <= I * P
    assert int(t.cx_hist.sum()) <= I * P
    assert t.cx_hist.shape == (opts.maxsize,)


def test_chunked_iteration_same_counters(telemetry_iteration):
    opts, eng, ds, t = telemetry_iteration
    state = eng.init_state(jax.random.key(0), ds.data, 2)
    state = eng.run_iteration(
        state, ds.data, jnp.int32(opts.maxsize), chunk_sizes=[1, 1, 1]
    )
    t2 = jax.device_get(state.telem)
    np.testing.assert_array_equal(
        np.asarray(t.cycle.proposed), np.asarray(t2.cycle.proposed)
    )
    np.testing.assert_array_equal(
        np.asarray(t.cycle.accepted), np.asarray(t2.cycle.accepted)
    )
    assert int(t.cycle.eval_rows) == int(t2.cycle.eval_rows)


def test_search_bit_identical_with_telemetry_on_off():
    """Acceptance pin: 2-iteration engine A/B produces bit-identical
    HoF (and population) with telemetry on vs off."""
    ds = _dataset()
    cm = jnp.int32(10)
    states = {}
    for tel in (False, True):
        eng = Engine(_opts(telemetry=tel), ds.nfeatures)
        s = eng.init_state(jax.random.key(0), ds.data, 2)
        s = eng.run_iteration(s, ds.data, cm)
        s = eng.run_iteration(s, ds.data, cm)
        states[tel] = s
    for field in ("cost", "loss", "complexity"):
        np.testing.assert_array_equal(
            np.asarray(getattr(states[False].hof, field)),
            np.asarray(getattr(states[True].hof, field)),
        )
    for field in ("arity", "op", "feat", "const", "length"):
        np.testing.assert_array_equal(
            np.asarray(getattr(states[False].hof.trees, field)),
            np.asarray(getattr(states[True].hof.trees, field)),
        )
        np.testing.assert_array_equal(
            np.asarray(getattr(states[False].pops.trees, field)),
            np.asarray(getattr(states[True].pops.trees, field)),
        )


# ---------------------------------------------------------------------------
# JSONL stream + CLI
# ---------------------------------------------------------------------------


def _run_search(tmp_path, run_id, niterations=2, **opt_kw):
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (64, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1]).astype(np.float32)
    opts = Options(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        output_directory=str(tmp_path),
        telemetry=True,
        **opt_kw,
    )
    equation_search(
        X, y, options=opts, niterations=niterations, verbosity=0,
        run_id=run_id, seed=0,
    )
    return os.path.join(str(tmp_path), run_id, "telemetry.jsonl")


def test_search_emits_valid_jsonl_and_report(tmp_path, capsys):
    path = _run_search(tmp_path, "telrun")
    with open(path) as f:
        lines = f.readlines()
    assert validate_lines(lines) == []
    events = [json.loads(l) for l in lines]
    assert [e["event"] for e in events] == [
        "run_start", "iteration", "iteration", "run_end"
    ]
    assert events[0]["schema"] == SCHEMA_VERSION
    assert events[0]["engines"][0]["collect_telemetry"] is True
    it1 = events[1]
    counters = it1["outputs"][0]["counters"]
    # per-kind dicts name every mutation kind + crossover
    from symbolicregression_jl_tpu.core.options import MUTATION_KINDS

    assert set(counters["proposed"]) == set(MUTATION_KINDS) | {"crossover"}
    assert sum(counters["proposed"].values()) == 2 * 2 * 2  # I * B * C
    # under the conftest's 8-device virtual mesh the island axis shards,
    # where dup stats are documented zeros; unsharded they cover I*P
    shards = events[0]["engines"][0]["n_island_shards"]
    assert counters["dedup"]["rows"] == (0 if shards > 1 else 2 * 8)
    assert it1["outputs"][0]["complexity_hist"] is not None
    assert events[3]["stop_reason"] == "niterations"

    # CLI: validate + report + report --json all succeed on the file
    assert report_main(["validate", path]) == 0
    assert report_main(["report", path]) == 0
    out = capsys.readouterr().out
    assert "acceptance by kind" in out
    assert "host-fraction" in out
    assert report_main(["report", path, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["iterations"]["count"] == 2
    assert summary["outputs"][0]["candidates"] > 0
    assert summary["end"]["stop_reason"] == "niterations"


def test_telemetry_interval_accumulates(tmp_path):
    path = _run_search(
        tmp_path, "telint", niterations=3, telemetry_interval=2
    )
    with open(path) as f:
        events = [json.loads(l) for l in f if l.strip()]
    iters = [e for e in events if e["event"] == "iteration"]
    # emit at iteration 2 (interval) and 3 (end-of-run flush)
    assert [e["iteration"] for e in iters] == [2, 3]
    # first event carries BOTH iterations' counters summed
    assert sum(iters[0]["outputs"][0]["counters"]["proposed"].values()) \
        == 2 * (2 * 2 * 2)
    assert sum(iters[1]["outputs"][0]["counters"]["proposed"].values()) \
        == 2 * 2 * 2


def test_validator_catches_malformed_events():
    good = {
        "schema": SCHEMA_VERSION, "event": "run_end", "t": 0.0,
        "stop_reason": "niterations", "iterations": 1, "num_evals": 1.0,
        "elapsed_s": 0.1, "recompiles_total": {},
    }
    assert validate_event(good) == []
    assert validate_event({**good, "schema": "graftscope.v0"})
    assert validate_event({**good, "event": "nope"})
    missing = dict(good)
    del missing["stop_reason"]
    assert any("stop_reason" in e for e in validate_event(missing))
    assert any(
        "iterations" in e
        for e in validate_event({**good, "iterations": "one"})
    )
    assert validate_lines(["not json\n"])
    assert validate_lines([])  # empty file is a violation


def test_report_summarize_synthetic():
    counters = {
        "proposed": {"add_node": 4, "crossover": 2},
        "accepted": {"add_node": 1, "crossover": 2},
        "reject_reasons": {"constraint": 3, "invalid": 0, "annealing": 0},
        "candidates": 6, "invalid": 1, "eval_rows": 24, "eval_launches": 3,
        "dedup": {"rows": 16, "unique": 12, "hits": 4},
    }
    events = [
        {"schema": SCHEMA_VERSION, "event": "run_start", "t": 0.0,
         "run_id": "r", "backend": "cpu", "n_devices": 1, "nout": 1,
         "niterations": 1, "telemetry_interval": 1, "options": {},
         "engines": []},
        {"schema": SCHEMA_VERSION, "event": "iteration", "t": 1.0,
         "iteration": 1, "num_evals": 10.0, "evals_per_sec": 10.0,
         "elapsed_s": 1.0, "device_s": 0.9, "host_s": 0.1,
         "host_fraction": 0.1,
         "recompiles": {"traces": 5, "backend_compiles": 1},
         "transfer_guard_hits": 0,
         "outputs": [{"output": 1, "min_loss": 0.5, "pareto_volume": 1.0,
                      "counters": counters, "loss_hist": [1],
                      "complexity_hist": [1]}]},
        {"schema": SCHEMA_VERSION, "event": "run_end", "t": 2.0,
         "stop_reason": "niterations", "iterations": 1, "num_evals": 10.0,
         "elapsed_s": 2.0, "recompiles_total": {}},
    ]
    assert all(validate_event(e) == [] for e in events)
    s = summarize(events)
    out = s["outputs"][0]
    assert out["acceptance_rate"]["add_node"] == 0.25
    assert out["acceptance_rate"]["crossover"] == 1.0
    assert out["invalid_fraction"] == pytest.approx(1 / 6)
    assert out["dedup_hit_rate"] == 0.25
    assert s["iterations"]["recompiles"]["traces"] == 5
    text = format_report(s)
    assert "add_node" in text and "25.0%" in text
