"""Smoke tests for examples/: each script's main() must run end-to-end
at a minimal budget. Keeps the documented walkthroughs from rotting as
the API evolves (each was also verified converging at its full budget
when written — see the round-5 log)."""

import importlib.util
import os
import sys

import pytest

EXAMPLES = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"
)


def _load(name):
    spec = importlib.util.spec_from_file_location(
        f"examples_{name}", os.path.join(EXAMPLES, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_quickstart_smoke(capsys):
    _load("quickstart").main(niterations=1)
    assert "best:" in capsys.readouterr().out


def test_custom_initial_population_smoke(capsys):
    _load("custom_initial_population").main(niterations=1)
    assert capsys.readouterr().out.strip()


def test_llm_in_the_loop_smoke(capsys):
    _load("llm_in_the_loop").main(rounds=2, niterations=1)
    assert "final best:" in capsys.readouterr().out


@pytest.mark.slow
def test_remaining_examples_smoke(capsys):
    _load("recorder_genealogy").main(niterations=1)
    _load("template_expression").main(niterations=1)
    _load("parametric_expression").main(niterations=1)
    _load("dimensional_analysis").main(niterations=1)
    # multi_device rides the conftest's 8-device virtual CPU mesh.
    _load("multi_device").main(niterations=1)
    assert capsys.readouterr().out.strip()
