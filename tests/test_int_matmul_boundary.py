"""Pin the `_INT_MATMUL` lowering boundary (VERDICT round-5 item 6).

`concat_pieces`' integer-field takes route through a one-hot MXU matmul
below `_INT_MATMUL_MAX_ROWS` mutation-batch rows and through the
masked-sum lowering above it (evolve/step.py). The two lowerings claim
bit-identical search trajectories — this makes that claim
regression-proof: the same seed/config runs with the matmul forced ON
vs forced OFF and the final population state must match to the bit.
"""

import numpy as np

import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu import Options, make_dataset, search_key
from symbolicregression_jl_tpu.evolve import step as step_mod
from symbolicregression_jl_tpu.evolve.engine import Engine


def _run(monkeypatch, limit: int):
    # limit=0 forces the masked-sum lowering for every batch size;
    # a large limit forces the one-hot matmul for this config's
    # 2 islands x 3 slots x 5 attempts = 30 rows.
    monkeypatch.setattr(step_mod, "_INT_MATMUL_MAX_ROWS", limit)
    opts = Options(
        binary_operators=["+", "*"],
        unary_operators=["cos"],
        maxsize=10,
        populations=2,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=4,
        save_to_file=False,
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (64, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 1.0).astype(np.float32)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(opts.elementwise_loss)
    eng = Engine(opts, ds.nfeatures)
    cfg = eng.cfg
    rows = cfg.n_islands * cfg.n_slots * cfg.attempts
    assert cfg.mctx.int_take_matmul == (0 < rows <= limit)
    state = eng.init_state(search_key(0), ds.data, 2)
    # one iteration = 4 evolve cycles — enough trajectory for any
    # lowering divergence to surface as a bit difference, and it keeps
    # the test inside the fast tier's time budget
    state = eng.run_iteration(state, ds.data, jnp.int32(opts.maxsize))
    return state


def test_int_matmul_on_vs_off_bit_identical(monkeypatch):
    on = _run(monkeypatch, 512)       # 30 rows <= 512: matmul lowering
    off = _run(monkeypatch, 0)        # forced masked-sum lowering
    for name in ("cost", "loss", "complexity", "birth", "ref", "parent"):
        assert np.array_equal(
            np.asarray(getattr(on.pops, name)),
            np.asarray(getattr(off.pops, name)), equal_nan=True), name
    for a, b in zip(jax.tree.leaves(on.pops.trees),
                    jax.tree.leaves(off.pops.trees)):
        assert np.array_equal(np.asarray(a), np.asarray(b), equal_nan=True)
    assert np.array_equal(np.asarray(on.hof.cost), np.asarray(off.hof.cost),
                          equal_nan=True)
