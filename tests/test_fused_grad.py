"""fused_loss_and_const_grad vs jax.grad through the jnp interpreter."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from symbolicregression_jl_tpu.core.losses import aggregate_loss
from symbolicregression_jl_tpu.evolve.mutation import (
    MutationContext,
    gen_random_tree_fixed_size,
)
from symbolicregression_jl_tpu.ops.encoding import tree_structure_arrays
from symbolicregression_jl_tpu.ops.eval import eval_tree_batch
from symbolicregression_jl_tpu.ops.fused_eval import (
    fused_loss_and_const_grad,
)
from symbolicregression_jl_tpu.ops.operators import OperatorSet

L2 = lambda p, y: (p - y) ** 2


def make_problem(seed, T=24, L=24, n=257, nf=3, ops=None):
    ops = ops or OperatorSet(("+", "-", "*", "/"), ("cos", "exp", "abs"))
    nops = ops.nops_tuple()
    ctx = MutationContext(
        nops=nops, nfeatures=nf, max_nodes=L,
        perturbation_factor=0.076, probability_negate_constant=0.01,
    )
    key = jax.random.PRNGKey(seed)
    sizes = jax.random.randint(jax.random.fold_in(key, 1), (T,), 1, L)
    trees = jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, ctx, jnp.float32)
    )(jax.random.split(key, T), sizes)
    rng = np.random.default_rng(seed)
    X = jnp.asarray(rng.uniform(-2, 2, (nf, n)).astype(np.float32))
    y = jnp.asarray(rng.uniform(-2, 2, n).astype(np.float32))
    return ops, trees, X, y


def reference_loss_and_grad(trees, X, y, w, ops):
    def loss_of_const(const):
        import dataclasses
        t = dataclasses.replace(trees, const=const)
        pred, valid = eval_tree_batch(t, X, ops)
        return jax.vmap(
            lambda p, v: aggregate_loss(L2, p[None], y, v[None], w)[0]
        )(pred, valid)

    loss = loss_of_const(trees.const)
    grad = jax.jacrev(lambda c: jnp.sum(loss_of_const(c)))(trees.const)
    return loss, grad


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fused_grad_matches_autodiff(seed):
    ops, trees, X, y = make_problem(seed)
    child, _, _ = tree_structure_arrays(trees, need_depth=False)
    loss, valid, grad = fused_loss_and_const_grad(
        trees, child, X, y, None, ops, L2, interpret=True)
    ref_loss, ref_grad = reference_loss_and_grad(trees, X, y, None, ops)

    both_finite = np.isfinite(np.asarray(ref_loss))
    np.testing.assert_allclose(
        np.asarray(loss)[both_finite], np.asarray(ref_loss)[both_finite],
        rtol=2e-4, atol=1e-5)
    assert (np.isinf(np.asarray(loss)) == ~both_finite).all()
    # gradients: compare only valid trees (invalid => fused returns 0)
    # and only slots where reference autodiff itself is finite — jax.grad
    # through `where`-guarded safe ops yields NaN at some slots where the
    # true derivative exists (the kernel's direct vjp is correct there).
    g = np.asarray(grad)
    rg = np.asarray(ref_grad)
    for i in range(g.shape[0]):
        if not both_finite[i]:
            assert (g[i] == 0).all()
            continue
        m = np.isfinite(rg[i])
        denom = np.maximum(np.abs(rg[i][m]), 1.0)
        np.testing.assert_allclose(g[i][m] / denom, rg[i][m] / denom,
                                   rtol=3e-3, atol=3e-4)


def test_fused_grad_weighted():
    ops, trees, X, y = make_problem(3, T=8)
    n = y.shape[0]
    w = jnp.asarray(np.random.default_rng(0).uniform(0.5, 2.0, n)
                    .astype(np.float32))
    child, _, _ = tree_structure_arrays(trees, need_depth=False)
    loss, valid, grad = fused_loss_and_const_grad(
        trees, child, X, y, w, ops, L2, interpret=True)
    ref_loss, ref_grad = reference_loss_and_grad(trees, X, y, w, ops)
    fin = np.isfinite(np.asarray(ref_loss))
    np.testing.assert_allclose(np.asarray(loss)[fin],
                               np.asarray(ref_loss)[fin], rtol=2e-4, atol=1e-5)
    g, rg = np.asarray(grad), np.asarray(ref_grad)
    for i in range(g.shape[0]):
        if fin[i]:
            m = np.isfinite(rg[i])
            denom = np.maximum(np.abs(rg[i][m]), 1.0)
            np.testing.assert_allclose(g[i][m] / denom, rg[i][m] / denom,
                                       rtol=3e-3, atol=3e-4)
