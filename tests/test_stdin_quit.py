"""Interactive quit + budget-check granularity (test_early_stop /
stop-on-clock analogues, SURVEY.md §4; reference StdinReader,
/root/reference/src/SearchUtils.jl:336-385).
"""

import io
import time

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options, equation_search
from symbolicregression_jl_tpu.api.search import RuntimeOptions
from symbolicregression_jl_tpu.utils.stdin_quit import StdinQuitWatcher


def _problem(n=100):
    rng = np.random.default_rng(3)
    X = rng.uniform(-2, 2, (n, 2)).astype(np.float32)
    y = (X[:, 0] + X[:, 1]).astype(np.float32)
    return X, y


def _options(**kw):
    base = dict(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=10,
        tournament_selection_n=4,
        ncycles_per_iteration=8,
        save_to_file=False,
    )
    base.update(kw)
    return Options(**base)


def test_watcher_reads_q():
    w = StdinQuitWatcher(io.StringIO("q"), force=True)
    deadline = time.time() + 5
    while not w.check() and time.time() < deadline:
        time.sleep(0.01)
    assert w.check()


def test_watcher_inactive_on_non_tty():
    w = StdinQuitWatcher()  # pytest stdin is not a tty
    assert not w.active
    assert not w.check()


def test_disabled_watcher_never_touches_stdin_or_threads():
    import threading

    n_threads = threading.active_count()
    w = StdinQuitWatcher.disabled()
    assert not w.active
    assert not w.check()
    assert w.stream is None
    assert threading.active_count() == n_threads
    w.stop()  # no-op, must not raise


def test_interactive_quit_flag_disables_watcher_construction():
    """Options(interactive_quit=False) — the graftserve setting — must
    route equation_search to the disabled watcher; an explicit injected
    input_stream still wins (tests rely on it)."""
    import io
    from unittest import mock

    X, y = _problem(50)
    built = []
    real_disabled = StdinQuitWatcher.disabled.__func__

    def spy_disabled(cls):
        built.append("disabled")
        return real_disabled(cls)

    with mock.patch.object(
            StdinQuitWatcher, "disabled", classmethod(spy_disabled)):
        equation_search(
            X, y, options=_options(interactive_quit=False,
                                   save_to_file=False),
            runtime_options=RuntimeOptions(niterations=1, verbosity=0,
                                           seed=0),
        )
    assert built == ["disabled"]

    # force path: injected stream engages the watcher regardless
    hofq = equation_search(
        X, y, options=_options(interactive_quit=False, save_to_file=False),
        runtime_options=RuntimeOptions(
            niterations=30, verbosity=0, seed=0,
            input_stream=io.StringIO("q")),
    )
    assert hofq is not None


@pytest.mark.slow
def test_user_quit_stops_search(capsys):
    X, y = _problem()
    hof = equation_search(
        X, y, options=_options(),
        runtime_options=RuntimeOptions(
            niterations=50, verbosity=1, seed=0,
            input_stream=io.StringIO("q"),
        ),
    )
    out = capsys.readouterr().out
    assert "user_quit" in out
    # results so far are preserved
    assert len(hof.entries) > 0


def test_timeout_checked_mid_iteration():
    X, y = _problem()
    t0 = time.time()
    equation_search(
        X, y,
        options=_options(timeout_in_seconds=0.0, ncycles_per_iteration=64),
        runtime_options=RuntimeOptions(niterations=1000, verbosity=0, seed=0),
    )
    # with a 0-second budget the search must stop within the very first
    # chunk round, not run 1000 iterations
    assert time.time() - t0 < 120


@pytest.mark.slow
def test_chunked_iteration_bit_identical():
    """Chunked and single-launch iterations must produce identical
    results: global cycle indices drive the annealing ramp and RNG
    fold-ins, and the epilogue runs exactly once either way."""
    import jax
    import jax.numpy as jnp

    from symbolicregression_jl_tpu import search_key
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.evolve.engine import Engine

    X, y = _problem()
    options = _options(ncycles_per_iteration=8)
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    engine = Engine(options, ds.nfeatures)

    s1 = engine.init_state(search_key(7), ds.data, options.populations)
    s1 = engine.run_iteration(s1, ds.data, options.maxsize)
    s2 = engine.init_state(search_key(7), ds.data, options.populations)
    s2 = engine.run_iteration(s2, ds.data, options.maxsize,
                              chunk_sizes=[3, 3, 2])

    np.testing.assert_array_equal(
        np.asarray(s1.pops.trees.arity), np.asarray(s2.pops.trees.arity)
    )
    np.testing.assert_array_equal(
        np.asarray(s1.pops.trees.op), np.asarray(s2.pops.trees.op)
    )
    np.testing.assert_allclose(
        np.asarray(s1.pops.cost), np.asarray(s2.pops.cost), rtol=1e-6
    )
    assert float(s1.num_evals) == float(s2.num_evals)
    np.testing.assert_array_equal(
        jax.random.key_data(s1.key), jax.random.key_data(s2.key)
    )


@pytest.mark.slow
def test_default_search_is_chunked(monkeypatch):
    """Stop checks run mid-iteration EVEN WITHOUT a configured budget:
    the evolve phase is always chunked (adaptive count, ~1 s stop
    latency target), so a later 'q'/timeout can interrupt promptly."""
    from symbolicregression_jl_tpu.evolve.engine import Engine

    seen = []
    orig = Engine.run_iteration

    def spy(self, state, data, cur_maxsize, chunk_sizes=None,
            should_stop=None):
        seen.append(chunk_sizes)
        return orig(self, state, data, cur_maxsize,
                    chunk_sizes=chunk_sizes, should_stop=should_stop)

    monkeypatch.setattr(Engine, "run_iteration", spy)
    X, y = _problem()
    equation_search(
        X, y, options=_options(ncycles_per_iteration=8),
        runtime_options=RuntimeOptions(niterations=2, verbosity=0, seed=0),
    )
    assert seen and seen[0] is not None and len(seen[0]) > 1
