"""SRRegressor / MultitargetSRRegressor sklearn-contract tests.

Mirrors the reference MLJ interface tests (test/integration/ext/mlj/,
SURVEY.md §4): fit/predict/report flows, selection rule, warm-start
refits, multi-target routing.
"""

import numpy as np
import pytest

from symbolicregression_jl_tpu.api.regressor import (
    MultitargetSRRegressor,
    SRRegressor,
    choose_best,
)


def _opts(**kw):
    base = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=12,
        populations=4,
        population_size=16,
        ncycles_per_iteration=20,
        tournament_selection_n=6,
        save_to_file=False,
    )
    base.update(kw)
    return base


@pytest.fixture(scope="module")
def problem():
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (128, 2)).astype(np.float32)
    y = 3.0 * X[:, 0] - X[:, 1]
    return X, y


def test_device_scale_auto(monkeypatch):
    """device_scale='auto': TPU backends get the config-sweep optimum
    unless the user pins a scale knob; CPU and device_scale=False keep
    the reference defaults (round-4 verdict item 4)."""
    import jax

    from symbolicregression_jl_tpu.api.regressor import SRRegressor

    r = SRRegressor()
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    opts = r._make_options()
    assert r.device_scaled_
    assert opts.populations == 512 and opts.population_size == 256
    assert opts.tournament_selection_n == 16
    assert opts.ncycles_per_iteration == 100

    # user pins any scale knob -> no auto-scaling at all
    r2 = SRRegressor(populations=10)
    opts2 = r2._make_options()
    assert not r2.device_scaled_
    assert opts2.populations == 10
    assert opts2.population_size != 256  # reference default preserved

    # explicit off
    r3 = SRRegressor(device_scale=False)
    opts3 = r3._make_options()
    assert not r3.device_scaled_ and opts3.populations != 512

    # CPU backend -> reference defaults
    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    r4 = SRRegressor()
    opts4 = r4._make_options()
    assert not r4.device_scaled_ and opts4.populations != 512


@pytest.mark.slow
def test_fit_predict_score(problem):
    X, y = problem
    model = SRRegressor(niterations=3, seed=0, **_opts())
    model.fit(X, y)
    assert model.equations_ is not None and len(model.equations_) >= 1
    pred = model.predict(X)
    assert pred.shape == (X.shape[0],)
    assert model.score(X, y) > 0.5
    rec = model.get_best()
    assert rec.complexity >= 1 and np.isfinite(rec.loss)


def test_predict_with_idx(problem):
    X, y = problem
    model = SRRegressor(niterations=2, seed=1, **_opts())
    model.fit(X, y)
    p0 = model.predict(X, idx=0)  # simplest frontier equation
    assert p0.shape == (X.shape[0],)


def test_unfitted_raises(problem):
    X, y = problem
    with pytest.raises(RuntimeError, match="not fitted"):
        SRRegressor(**_opts()).predict(X)


def test_warm_start_refit_continues(problem):
    """Warm-start refits run only the delta iterations
    (src/MLJInterface.jl:292-294): same niterations => no extra work;
    raising niterations runs the difference."""
    X, y = problem
    model = SRRegressor(niterations=2, seed=2, **_opts())
    model.fit(X, y)
    loss1 = model.get_best().loss
    model.fit(X, y)  # same niterations: already fitted, runs 0 more
    assert model.fitted_iterations_ == 2
    model.niterations = 4
    model.fit(X, y)  # delta: runs 2 more iterations from saved state
    assert model.fitted_iterations_ == 4
    assert model.get_best().loss <= loss1 + 1e-6


@pytest.mark.slow
def test_multitarget(problem):
    X, _ = problem
    Y = np.stack([2.0 * X[:, 0], X[:, 1] + 1.0], axis=1)  # (n, 2)
    model = MultitargetSRRegressor(niterations=2, seed=3, **_opts())
    model.fit(X, Y)
    assert len(model.equations_) == 2
    pred = model.predict(X)
    assert pred.shape == Y.shape
    assert model.score(X, Y) > -1.0


def test_choose_best_rule():
    # max score among losses <= 1.5*min
    idx = choose_best(
        trees=[None] * 4,
        losses=[10.0, 1.0, 0.9, 0.8],
        scores=[0.0, 5.0, 1.0, 0.5],
        complexities=[1, 3, 5, 7],
    )
    assert idx == 1  # loss 1.0 <= 1.2 threshold, highest score


def test_latex_and_export(problem):
    X, y = problem
    model = SRRegressor(niterations=1, seed=4, **_opts())
    model.fit(X, y)
    tex = model.latex()
    assert isinstance(tex, str) and len(tex) > 0
    try:
        import sympy  # noqa: F401

        expr = model.sympy()
        assert expr is not None
    except ImportError:
        pass


def test_dataframe_inputs_and_column_names(problem):
    """MLJ-style column tables: a pandas DataFrame fits directly, its
    column names become the variable names, and predict reorders a
    permuted-column frame by them (src/MLJInterface.jl:366-380)."""
    pd = pytest.importorskip("pandas")
    X, y = problem
    df = pd.DataFrame({"alpha": X[:, 0], "beta": X[:, 1]})
    model = SRRegressor(niterations=2, seed=0, **_opts())
    model.fit(df, y)
    assert model.variable_names_ == ["alpha", "beta"]
    pred = model.predict(df)
    # permuted columns must give the same predictions
    pred_permuted = model.predict(df[["beta", "alpha"]])
    np.testing.assert_allclose(pred, pred_permuted)
    # dict-of-columns tables work too
    pred_dict = model.predict({"beta": X[:, 1], "alpha": X[:, 0]})
    np.testing.assert_allclose(pred, pred_dict)


@pytest.mark.slow
def test_units_echo_through_predict(problem):
    """y_units given at fit echo on predictions with with_units=True —
    the reference's unit-typed predict round-trip."""
    from symbolicregression_jl_tpu.core.units import QuantityArray

    X, y = problem
    model = SRRegressor(niterations=2, seed=0, **_opts())
    model.fit(X, y, X_units=["m", "s"], y_units="m/s")
    out = model.predict(X, with_units=True)
    assert isinstance(out, QuantityArray)
    assert out.unit == "m/s"
    plain = model.predict(X)
    np.testing.assert_allclose(np.asarray(out), plain)
    assert not isinstance(plain, QuantityArray)
