"""graftlint rule fixtures: every rule must flag its hazard snippet and
stay quiet on the matching clean snippet (false-positive regression
suite), suppressions must work, and the real package tree must stay
lint-clean (the property CI enforces).

Pure AST tests — no JAX tracing happens here.
"""

import os
import textwrap

import pytest

from symbolicregression_jl_tpu.lint import RULES, lint_paths, lint_source
from symbolicregression_jl_tpu.lint.cli import main as lint_main


def _lint(src: str, path: str = "pkg/evolve/mod.py"):
    return lint_source(textwrap.dedent(src), path)


def _ids(findings):
    return sorted({f.rule_id for f in findings})


# ---------------------------------------------------------------------------
# registry sanity
# ---------------------------------------------------------------------------


def test_registry_has_at_least_six_documented_rules():
    assert len(RULES) >= 6
    for rid, r in RULES.items():
        assert rid == r.id
        assert r.summary and r.rationale, f"{rid} missing catalog text"
        assert r.name


# ---------------------------------------------------------------------------
# GL001 key-reuse
# ---------------------------------------------------------------------------


def test_gl001_flags_plain_reuse():
    findings = _lint(
        """
        import jax

        def f(key):
            a = jax.random.uniform(key, (3,))
            b = jax.random.normal(key, (3,))
            return a + b
        """
    )
    assert "GL001" in _ids(findings)


def test_gl001_flags_parent_key_used_after_split():
    findings = _lint(
        """
        import jax

        def f(key):
            k1, k2 = jax.random.split(key)
            leak = jax.random.uniform(key, (3,))
            return leak + jax.random.uniform(k1, (3,)) + jax.random.normal(k2, ())
        """
    )
    assert "GL001" in _ids(findings)


def test_gl001_flags_reuse_across_loop_iterations():
    findings = _lint(
        """
        import jax

        def f(key, n):
            out = 0.0
            for i in range(n):
                out = out + jax.random.uniform(key, ())
            return out
        """
    )
    assert "GL001" in _ids(findings)


def test_gl001_clean_split_and_branches():
    findings = _lint(
        """
        import jax

        def f(key, flag):
            k1, k2 = jax.random.split(key)
            if flag:
                a = jax.random.uniform(k1, (3,))
            else:
                a = jax.random.normal(k1, (3,))
            return a + jax.random.uniform(k2, (3,))
        """
    )
    assert "GL001" not in _ids(findings)


def test_gl001_clean_fold_in_loop():
    # fold_in(key, i) from one base key is the canonical stream-derivation
    # idiom, not reuse.
    findings = _lint(
        """
        import jax

        def f(key, n):
            out = 0.0
            for i in range(n):
                k = jax.random.fold_in(key, i)
                out = out + jax.random.uniform(k, ())
            return out
        """
    )
    assert "GL001" not in _ids(findings)


def test_gl001_stdlib_random_is_not_a_key():
    # `import random` is the stdlib module: repeated first args are not
    # PRNG keys (only `from jax import random` makes bare `random.` jax)
    findings = _lint(
        """
        import random

        def shuffle_twice(idx):
            random.shuffle(idx)
            return random.sample(idx, 3) + random.sample(idx, 2)
        """,
        path="pkg/api/util.py",
    )
    assert "GL001" not in _ids(findings)


def test_gl001_from_jax_import_random_is_tracked():
    findings = _lint(
        """
        from jax import random

        def f(key):
            a = random.uniform(key, (3,))
            b = random.normal(key, (3,))
            return a + b
        """
    )
    assert "GL001" in _ids(findings)


def test_gl001_clean_rebind_in_loop():
    findings = _lint(
        """
        import jax

        def f(key, n):
            out = 0.0
            for i in range(n):
                key, k = jax.random.split(key)
                out = out + jax.random.uniform(k, ())
            return out
        """
    )
    assert "GL001" not in _ids(findings)


# ---------------------------------------------------------------------------
# GL002 host-rng (scoped to evolve/ and ops/ paths)
# ---------------------------------------------------------------------------


def test_gl002_flags_np_random_and_stdlib_random_in_evolve():
    src = """
    import random
    import numpy as np

    def noise(n):
        return np.random.rand(n) + random.random()
    """
    findings = _lint(src, path="pkg/evolve/mutation.py")
    gl002 = [f for f in findings if f.rule_id == "GL002"]
    assert len(gl002) == 2


def test_gl002_out_of_scope_path_is_clean():
    src = """
    import numpy as np

    def seed_fallback():
        return np.random.randint(0, 2**31 - 1)
    """
    assert "GL002" not in _ids(_lint(src, path="pkg/api/search.py"))


def test_gl002_jax_random_is_clean():
    src = """
    import jax

    def draw(key, n):
        return jax.random.uniform(key, (n,))
    """
    assert "GL002" not in _ids(_lint(src, path="pkg/ops/eval.py"))


# ---------------------------------------------------------------------------
# GL003 traced-sync
# ---------------------------------------------------------------------------


def test_gl003_flags_float_cast_in_jit():
    findings = _lint(
        """
        import jax

        @jax.jit
        def f(x):
            return float(x.sum())
        """
    )
    assert "GL003" in _ids(findings)


def test_gl003_flags_item_in_scan_body():
    findings = _lint(
        """
        import jax

        def run(xs):
            def step(c, x):
                v = x.item()
                return c + v, v
            return jax.lax.scan(step, 0.0, xs)
        """
    )
    assert "GL003" in _ids(findings)


def test_gl003_flags_np_asarray_on_traced_value():
    findings = _lint(
        """
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x) * 2
        """
    )
    assert "GL003" in _ids(findings)


def test_gl003_clean_outside_trace_and_on_host_values():
    findings = _lint(
        """
        import jax
        import numpy as np

        def host_driver(jitted, x):
            return float(jitted(x).sum())

        @jax.jit
        def f(x):
            pad = float("nan")
            table = np.asarray([1.0, 2.0])
            n = float(len(table))
            return x + table[0] + pad + n
        """
    )
    assert "GL003" not in _ids(findings)


def test_gl003_propagates_through_local_calls():
    # helper is only reachable from the jitted entry point
    findings = _lint(
        """
        import jax

        def helper(x):
            return int(x[0])

        @jax.jit
        def f(x):
            return helper(x)
        """
    )
    assert "GL003" in _ids(findings)


# ---------------------------------------------------------------------------
# GL004 recompile-hazard
# ---------------------------------------------------------------------------


def test_gl004_flags_inline_jit_invocation():
    findings = _lint(
        """
        import jax

        def f(g, x):
            return jax.jit(g)(x)
        """
    )
    assert "GL004" in _ids(findings)


def test_gl004_flags_jit_built_in_loop():
    findings = _lint(
        """
        import jax

        def f(xs):
            out = []
            for x in xs:
                g = jax.jit(lambda v: v * 2)
                out.append(g)
            return out
        """
    )
    assert "GL004" in _ids(findings)


def test_gl004_flags_unhashable_static_arg():
    findings = _lint(
        """
        import jax

        def inner(x, cfg):
            return x * cfg[0]

        g = jax.jit(inner, static_argnums=(1,))

        def run(x):
            return g(x, [1, 2, 3])
        """
    )
    assert "GL004" in _ids(findings)


def test_gl004_clean_module_level_jit_and_hashable_statics():
    findings = _lint(
        """
        import jax

        def inner(x, cfg):
            return x * cfg[0]

        g = jax.jit(inner, static_argnums=(1,))

        def run(x):
            return g(x, (1, 2, 3))
        """
    )
    assert "GL004" not in _ids(findings)


# ---------------------------------------------------------------------------
# GL005 captured-mutation
# ---------------------------------------------------------------------------


def test_gl005_flags_closure_append_in_jit():
    findings = _lint(
        """
        import jax

        acc = []

        @jax.jit
        def f(x):
            acc.append(x)
            return x * 2
        """
    )
    assert "GL005" in _ids(findings)


def test_gl005_flags_subscript_store_on_parameter():
    findings = _lint(
        """
        import jax

        @jax.jit
        def f(x, buf):
            buf[0] = x
            return x
        """
    )
    assert "GL005" in _ids(findings)


def test_gl005_clean_local_staging_and_library_calls():
    findings = _lint(
        """
        import jax

        @jax.jit
        def f(x):
            tmp = []
            tmp.append(x * 2)
            y = jax.lax.sort([x], dimension=0, num_keys=1)
            return tmp[0] + y[0]
        """
    )
    assert "GL005" not in _ids(findings)


def test_gl005_clean_pallas_ref_stores():
    findings = _lint(
        """
        import jax
        from jax.experimental import pallas as pl

        def kernel(x_ref, o_ref):
            o_ref[...] = x_ref[...] * 2

        def call(x):
            return pl.pallas_call(kernel, out_shape=x)(x)
        """
    )
    assert "GL005" not in _ids(findings)


# ---------------------------------------------------------------------------
# GL006 stray-debug
# ---------------------------------------------------------------------------


def test_gl006_flags_bare_debug_print():
    findings = _lint(
        """
        import jax

        @jax.jit
        def f(x):
            jax.debug.print("x = {}", x)
            return x * 2
        """
    )
    assert "GL006" in _ids(findings)


def test_gl006_clean_behind_debug_guard_or_debug_function():
    findings = _lint(
        """
        import jax

        DEBUG_CHECKS = False

        @jax.jit
        def f(x):
            if DEBUG_CHECKS:
                jax.debug.print("x = {}", x)
            return x * 2

        def debug_dump(x):
            jax.debug.print("x = {}", x)
        """
    )
    assert "GL006" not in _ids(findings)


# ---------------------------------------------------------------------------
# GL007 signal-unsafe-handler (graftshield emergency-checkpoint path)
# ---------------------------------------------------------------------------


def test_gl007_flags_device_sync_in_signal_handler():
    findings = _lint(
        """
        import signal
        import jax

        class Guard:
            def _on_sigterm(self, signum, frame):
                jax.device_get(self.state)

            def install(self):
                signal.signal(signal.SIGTERM, self._on_sigterm)
        """,
        path="pkg/shield/bad_signals.py",
    )
    assert "GL007" in _ids(findings)


def test_gl007_flags_checkpoint_write_in_handler():
    findings = _lint(
        """
        import signal

        def _handler(signum, frame):
            save_search_state("out.pkl", STATE)

        signal.signal(signal.SIGTERM, _handler)
        """,
        path="pkg/shield/bad2.py",
    )
    assert "GL007" in _ids(findings)


def test_gl007_clean_flag_only_handler():
    findings = _lint(
        """
        import signal
        import threading

        class Guard:
            def __init__(self):
                self._event = threading.Event()
                self._signum = None

            def _on_sigterm(self, signum, frame):
                self._signum = signum
                self._event.set()

            def install(self):
                signal.signal(signal.SIGTERM, self._on_sigterm)
        """,
        path="pkg/shield/good_signals.py",
    )
    assert "GL007" not in _ids(findings)


def test_gl007_nonhandler_functions_untouched():
    # The same hazardous calls OUTSIDE a registered handler are fine
    # (GL007 is about signal context, not the calls themselves).
    findings = _lint(
        """
        import signal
        import jax

        def _handler(signum, frame):
            FLAG.append(signum)

        def checkpoint(state):
            return jax.device_get(state)

        signal.signal(signal.SIGTERM, _handler)
        """,
        path="pkg/shield/mixed.py",
    )
    assert "GL007" not in _ids(findings)


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def test_per_line_suppression_single_rule():
    src = """
    import jax

    @jax.jit
    def f(x):
        return float(x.sum())  # graftlint: disable=GL003
    """
    assert _lint(src) == []


def test_per_line_suppression_bare_disables_all():
    src = """
    import jax

    @jax.jit
    def f(x):
        return float(x.sum())  # graftlint: disable
    """
    assert _lint(src) == []


def test_suppression_of_other_rule_does_not_hide_finding():
    src = """
    import jax

    @jax.jit
    def f(x):
        return float(x.sum())  # graftlint: disable=GL001
    """
    assert "GL003" in _ids(_lint(src))


# ---------------------------------------------------------------------------
# the real tree + CLI wiring
# ---------------------------------------------------------------------------


def _package_dir():
    import symbolicregression_jl_tpu

    return os.path.dirname(symbolicregression_jl_tpu.__file__)


def test_package_tree_is_lint_clean():
    """The property CI enforces: graftlint exits 0 on the real package."""
    findings = lint_paths([_package_dir()])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_cli_exit_codes(tmp_path, capsys):
    assert lint_main([_package_dir()]) == 0
    bad = tmp_path / "evolve" / "bad.py"
    bad.parent.mkdir()
    bad.write_text(
        "import numpy as np\n\n"
        "def f(n):\n"
        "    return np.random.rand(n)\n"
    )
    assert lint_main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "GL002" in out


def test_cli_survives_undecodable_and_null_byte_files(tmp_path, capsys):
    (tmp_path / "latin.py").write_bytes(b"# caf\xe9\nx = 1\n")
    (tmp_path / "nul.py").write_bytes(b"x = 1\x00\n")
    (tmp_path / "ok.py").write_text("x = 1\n")
    assert lint_main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert out.count("GL000") == 2


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULES:
        assert rid in out


# ---------------------------------------------------------------------------
# GL008 shard-map-hazard (graftmesh shard_map bodies)
# ---------------------------------------------------------------------------


def test_gl008_flags_host_calls_in_shard_map_body():
    findings = _lint(
        """
        import jax
        from jax.experimental.shard_map import shard_map

        def body(x):
            v = jax.device_get(x)
            print(v)
            return x

        def run(mesh, x):
            return shard_map(body, mesh=mesh, in_specs=(None,),
                             out_specs=None)(x)
        """,
        path="pkg/mesh/bad_host.py",
    )
    assert "GL008" in _ids(findings)
    assert sum(1 for f in findings if f.rule_id == "GL008") >= 2


def test_gl008_flags_item_sync_in_shard_map_body():
    findings = _lint(
        """
        from jax.experimental.shard_map import shard_map

        def body(x):
            n = x.sum().item()
            return x + n

        def run(mesh, x):
            return shard_map(body, mesh=mesh, in_specs=(None,),
                             out_specs=None)(x)
        """,
        path="pkg/mesh/bad_item.py",
    )
    assert "GL008" in _ids(findings)


def test_gl008_flags_axisless_collectives():
    findings = _lint(
        """
        import jax
        from jax.experimental.shard_map import shard_map

        def body(x):
            total = jax.lax.psum(x)
            gathered = jax.lax.all_gather(x)
            idx = jax.lax.axis_index()
            return total + gathered.sum() + idx

        def run(mesh, x):
            return shard_map(body, mesh=mesh, in_specs=(None,),
                             out_specs=None)(x)
        """,
        path="pkg/mesh/bad_axis.py",
    )
    gl8 = [f for f in findings if f.rule_id == "GL008"]
    assert len(gl8) == 3


def test_gl008_clean_named_axes_and_host_work_outside():
    # collectives WITH their axis + host syncs OUTSIDE the mapped body
    # (including transitively-called helpers) stay quiet
    findings = _lint(
        """
        import jax
        from jax.experimental.shard_map import shard_map

        def helper(x):
            return jax.lax.psum(x, "island")

        def body(x):
            total = helper(x)
            gathered = jax.lax.all_gather(x, "island", tiled=True)
            idx = jax.lax.axis_index("island")
            return total + gathered.sum() + idx

        def run(mesh, x):
            out = shard_map(body, mesh=mesh, in_specs=(None,),
                            out_specs=None)(x)
            host = jax.device_get(out)
            print(host)
            return out
        """,
        path="pkg/mesh/good.py",
    )
    assert "GL008" not in _ids(findings)


def test_gl008_ignores_modules_without_shard_map():
    # the same calls in a module with NO shard_map are out of scope
    # (GL003's traced-sync rule owns the generic cases)
    findings = _lint(
        """
        import jax

        def f(x):
            return jax.lax.psum(x)
        """,
        path="pkg/mesh/no_smap.py",
    )
    assert "GL008" not in _ids(findings)


# ---------------------------------------------------------------------------
# graftwarden concurrency rules (GL009-GL014) — fixture paths use a
# serve/ component so the scope matches; the roots don't exist on disk,
# so each fixture is analyzed in single-module mode
# ---------------------------------------------------------------------------


def _lint_serve(src: str):
    return _lint(src, path="pkg/serve/mod.py")


def test_warden_registry_has_concurrency_rules():
    for rid in ("GL009", "GL010", "GL011", "GL012", "GL013", "GL014"):
        assert rid in RULES, f"{rid} not registered"


def test_gl009_flags_direct_and_transitive_blocking_io_under_lock():
    findings = _lint_serve(
        """
        import os
        import threading

        class Journal:
            def __init__(self):
                self._lock = threading.Lock()
                self.path = "j.jsonl"

            def _append(self, line):
                with open(self.path, "a") as f:
                    f.write(line)
                    os.fsync(f.fileno())

            def direct(self, line):
                with self._lock:
                    with open(self.path, "a") as f:
                        f.write(line)

            def transitive(self, line):
                with self._lock:
                    self._append(line)
        """
    )
    gl009 = [f for f in findings if f.rule_id == "GL009"]
    assert len(gl009) >= 2  # the direct open AND the call into _append


def test_gl009_clean_io_outside_lock():
    findings = _lint_serve(
        """
        import os
        import threading

        class Journal:
            def __init__(self):
                self._lock = threading.Lock()
                self.path = "j.jsonl"

            def append(self, line):
                with self._lock:
                    self._seq = getattr(self, "_seq", 0) + 1
                with open(self.path, "a") as f:
                    f.write(line)
                    os.fsync(f.fileno())
        """
    )
    assert "GL009" not in _ids(findings)


def test_gl010_flags_opposite_order_cycle():
    findings = _lint_serve(
        """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """
    )
    assert "GL010" in _ids(findings)


def test_gl010_flags_blessed_manifest_inversion_through_call():
    # AdmissionController holding its own lock calls into a method that
    # takes the server lock: the manifest sanctions SearchServer._lock
    # BEFORE AdmissionController._lock, so this derived edge inverts it
    findings = _lint_serve(
        """
        import threading

        class SearchServer:
            def __init__(self):
                self._lock = threading.RLock()

            def poke(self):
                with self._lock:
                    return 1

        class AdmissionController:
            def __init__(self):
                self._lock = threading.Lock()
                self.server = SearchServer()

            def admit(self):
                with self._lock:
                    return self.server.poke()
        """
    )
    assert "GL010" in _ids(findings)


def test_gl010_clean_consistent_order():
    findings = _lint_serve(
        """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
        """
    )
    assert "GL010" not in _ids(findings)


def test_gl011_flags_unguarded_write_across_thread_boundary():
    findings = _lint_serve(
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._thread = threading.Thread(target=self._run)

            def _run(self):
                self.count += 1

            def bump(self):
                self.count += 1
        """
    )
    assert "GL011" in _ids(findings)


def test_gl011_clean_when_every_write_holds_the_lock():
    findings = _lint_serve(
        """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._thread = threading.Thread(target=self._run)

            def _run(self):
                with self._lock:
                    self.count += 1

            def bump(self):
                with self._lock:
                    self.count += 1
        """
    )
    assert "GL011" not in _ids(findings)


def test_gl011_thread_confined_attr_is_clean():
    findings = _lint_serve(
        """
        import threading

        class Worker:
            def __init__(self):
                self._thread = threading.Thread(target=self._run)

            def _run(self):
                self.progress = 1  # only the worker writes it
        """
    )
    assert "GL011" not in _ids(findings)


def test_gl012_flags_wait_outside_while():
    findings = _lint_serve(
        """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self.ready = False

            def get(self):
                with self._cond:
                    if not self.ready:
                        self._cond.wait()
                    return 1
        """
    )
    assert "GL012" in _ids(findings)


def test_gl012_clean_wait_in_while_and_event_wait():
    findings = _lint_serve(
        """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)
                self._stop = threading.Event()
                self.ready = False

            def get(self):
                with self._cond:
                    while not self.ready:
                        self._cond.wait(timeout=0.5)
                    return 1

            def pause(self):
                self._stop.wait(1.0)  # Event.wait is level-triggered
        """
    )
    assert "GL012" not in _ids(findings)


def test_gl013_flags_jax_dispatch_under_lock():
    findings = _lint_serve(
        """
        import threading
        import jax

        class Server:
            def __init__(self):
                self._lock = threading.RLock()

            def publish(self, x):
                with self._lock:
                    self.result = jax.block_until_ready(x)
        """
    )
    assert "GL013" in _ids(findings)


def test_gl013_clean_dispatch_outside_lock():
    findings = _lint_serve(
        """
        import threading
        import jax

        class Server:
            def __init__(self):
                self._lock = threading.RLock()

            def publish(self, x):
                r = jax.block_until_ready(x)
                with self._lock:
                    self.result = r
        """
    )
    assert "GL013" not in _ids(findings)


def test_gl014_flags_hazard_reachable_from_handler():
    # the handler body itself is flag-only (GL007 stays quiet); the
    # hazard is two calls deep — only the interprocedural closure sees it
    findings = _lint(
        """
        import json
        import signal

        def _save(state):
            with open("ckpt.json", "w") as f:
                json.dump(state, f)

        def _flag(state):
            _save(state)

        def _handler(signum, frame):
            _flag({"signum": signum})

        def install():
            signal.signal(signal.SIGTERM, _handler)
        """,
        path="pkg/shield/mod.py",
    )
    ids = _ids(findings)
    assert "GL014" in ids
    assert "GL007" not in ids


def test_gl014_clean_flag_only_closure():
    findings = _lint(
        """
        import signal
        import threading

        _EVENT = threading.Event()

        def _note():
            _EVENT.set()

        def _handler(signum, frame):
            _note()

        def install():
            signal.signal(signal.SIGTERM, _handler)
        """,
        path="pkg/shield/mod.py",
    )
    assert "GL014" not in _ids(findings)


def test_warden_rules_respect_suppression():
    findings = _lint_serve(
        """
        import os
        import threading

        class Journal:
            def __init__(self):
                self._lock = threading.Lock()

            def append(self, line):
                with self._lock:
                    with open("j", "a") as f:  # graftlint: disable=GL009
                        f.write(line)
        """
    )
    assert "GL009" not in _ids(findings)


def test_warden_rules_out_of_scope_path_is_clean():
    findings = _lint(
        """
        import threading

        class Pair:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
        """,
        path="pkg/evolve/mod.py",
    )
    assert "GL010" not in _ids(findings)


# ---------------------------------------------------------------------------
# lock-order manifest
# ---------------------------------------------------------------------------


def test_lock_order_manifest_is_acyclic():
    from symbolicregression_jl_tpu.lint.lock_order import (
        BLESSED_EDGES, check_manifest_acyclic)

    check_manifest_acyclic(BLESSED_EDGES)  # must not raise


def test_lock_order_manifest_drift_cycle_fails():
    from symbolicregression_jl_tpu.lint.lock_order import (
        BLESSED_EDGES, check_manifest_acyclic)

    bad = BLESSED_EDGES + (
        ("AdmissionController._lock", "SearchServer._lock"),)
    with pytest.raises(ValueError, match="cycle"):
        check_manifest_acyclic(bad)


def test_lock_order_violates_is_a_partial_order():
    from symbolicregression_jl_tpu.lint.lock_order import violates

    # the sanctioned direction and unrelated pairs are fine
    assert not violates("SearchServer._lock", "AdmissionController._lock")
    assert not violates("ExecutableCache._lock", "MetricsServer._state_lock")
    assert not violates("SearchServer._lock", "SearchServer._lock")
    # the reverse of a blessed edge (direct or transitive) violates
    assert violates("AdmissionController._lock", "SearchServer._lock")
    assert violates("ServeLog._lock", "SearchServer._lock")  # transitive
