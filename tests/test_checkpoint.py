"""On-disk checkpoint/resume of the full search state.

Kill-and-resume contract (the cross-process analogue of the reference's
saved-output reload, /root/reference/src/SymbolicRegression.jl:760-821):
a search writes `search_state.pkl` next to the hall-of-fame CSVs; a
*fresh* `equation_search(..., saved_state=<path>)` continues it, and an
incompatible option change errors out before touching the state.
"""

import os

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options, equation_search
from symbolicregression_jl_tpu.api.checkpoint import (
    load_search_state,
    save_search_state,
)
from symbolicregression_jl_tpu.api.search import RuntimeOptions


def _problem(n=200, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2, 2, (n, 2)).astype(np.float32)
    y = (2.0 * X[:, 0] + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(tmp_path, **kw):
    base = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=[],
        maxsize=10,
        populations=2,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=4,
        save_to_file=True,
        output_directory=str(tmp_path),
    )
    base.update(kw)
    return Options(**base)


@pytest.mark.slow
def test_checkpoint_write_and_resume(tmp_path):
    X, y = _problem()
    options = _options(tmp_path)
    ropt = RuntimeOptions(niterations=2, run_id="ckpt_run", seed=0, verbosity=0)
    hof1 = equation_search(X, y, options=options, runtime_options=ropt)
    ckpt = os.path.join(str(tmp_path), "ckpt_run", "search_state.pkl")
    assert os.path.exists(ckpt)
    best1 = min(e.loss for e in hof1.entries)

    # Resume from disk with fresh Options (same config) — simulates a new
    # process; the search continues rather than restarting.
    options2 = _options(tmp_path)
    ropt2 = RuntimeOptions(niterations=2, run_id="ckpt_run2", seed=1, verbosity=0)
    hof2 = equation_search(
        X, y, options=options2, saved_state=ckpt, runtime_options=ropt2
    )
    best2 = min(e.loss for e in hof2.entries)
    assert best2 <= best1 + 1e-6, "resume lost progress"


def test_checkpoint_incompatible_options_raise(tmp_path):
    X, y = _problem()
    options = _options(tmp_path)
    ropt = RuntimeOptions(niterations=1, run_id="ckpt_bad", seed=0, verbosity=0)
    equation_search(X, y, options=options, runtime_options=ropt)
    ckpt = os.path.join(str(tmp_path), "ckpt_bad", "search_state.pkl")

    with pytest.raises(ValueError, match="maxsize"):
        equation_search(
            X, y, options=_options(tmp_path, maxsize=16), saved_state=ckpt,
            runtime_options=RuntimeOptions(niterations=1, verbosity=0),
        )
    with pytest.raises(ValueError, match="operators"):
        equation_search(
            X, y,
            options=_options(tmp_path, binary_operators=["+", "*", "/"]),
            saved_state=ckpt,
            runtime_options=RuntimeOptions(niterations=1, verbosity=0),
        )


def test_save_load_roundtrip_preserves_state(tmp_path):
    X, y = _problem()
    options = _options(tmp_path, save_to_file=False)
    state, _ = equation_search(
        X, y, options=options,
        runtime_options=RuntimeOptions(niterations=1, seed=3, verbosity=0,
                                       return_state=True),
    )
    p = str(tmp_path / "state.pkl")
    save_search_state(p, state)
    loaded = load_search_state(p, options)
    assert loaded.num_evals == pytest.approx(state.num_evals)
    ds0, ld0 = state.device_states[0], loaded.device_states[0]
    np.testing.assert_array_equal(
        np.asarray(ds0.pops.trees.arity), np.asarray(ld0.pops.trees.arity)
    )
    np.testing.assert_allclose(
        np.asarray(ds0.pops.cost), np.asarray(ld0.pops.cost), rtol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(ds0.hof.exists), np.asarray(ld0.hof.exists)
    )


@pytest.mark.slow
def test_resume_num_evals_not_double_counted(tmp_path):
    # fresh 2-iteration run vs (1 iteration -> resume -> 1 iteration):
    # identical seed => identical totals; double-counting would inflate
    # the resumed total by the first run's evals.
    X, y = _problem()
    options = _options(tmp_path, save_to_file=False)
    from symbolicregression_jl_tpu import equation_search as es

    s2, _ = es(X, y, options=options,
               runtime_options=RuntimeOptions(niterations=2, seed=5,
                                              verbosity=0, return_state=True))
    s1, _ = es(X, y, options=options,
               runtime_options=RuntimeOptions(niterations=1, seed=5,
                                              verbosity=0, return_state=True))
    sr, _ = es(X, y, options=options, saved_state=s1,
               runtime_options=RuntimeOptions(niterations=1, seed=5,
                                              verbosity=0, return_state=True))
    assert sr.num_evals == pytest.approx(s2.num_evals, rel=1e-6)


def test_resume_rejects_different_feature_count(tmp_path):
    X, y = _problem()
    options = _options(tmp_path, save_to_file=False)
    from symbolicregression_jl_tpu import equation_search as es

    s1, _ = es(X, y, options=options,
               runtime_options=RuntimeOptions(niterations=1, seed=0,
                                              verbosity=0, return_state=True))
    X3 = np.concatenate([X, X[:, :1]], axis=1)  # 3 features
    with pytest.raises(ValueError, match="features"):
        es(X3, y, options=options, saved_state=s1,
           runtime_options=RuntimeOptions(niterations=1, verbosity=0))


def test_checkpoint_written_on_early_stop(tmp_path):
    # early_stop_condition fires after iteration 1 (checkpoint_every_n=5
    # would otherwise skip it) — the final write must still happen.
    X, y = _problem()
    options = _options(tmp_path, early_stop_condition=1e9)
    ropt = RuntimeOptions(niterations=7, run_id="ckpt_es", seed=0,
                          verbosity=0, checkpoint_every_n=5)
    equation_search(X, y, options=options, runtime_options=ropt)
    ckpt = os.path.join(str(tmp_path), "ckpt_es", "search_state.pkl")
    assert os.path.exists(ckpt)
    from symbolicregression_jl_tpu.api.checkpoint import load_search_state

    st = load_search_state(ckpt, _options(tmp_path, early_stop_condition=1e9))
    assert st.num_evals > 0


def test_multioutput_tuple_guesses_not_misnested(tmp_path):
    # A flat list of (expr, params) pair guesses on a 2-output search must
    # seed BOTH outputs with both guesses, not be split per output.
    X, y = _problem()
    Y = np.stack([y, -y], axis=0)  # equation_search takes [nout, n]
    options = _options(tmp_path, save_to_file=False)
    hofs = equation_search(
        X, Y, options=options,
        guesses=[("x1 + x2", None), ("x1 * x2", None)],
        runtime_options=RuntimeOptions(niterations=1, seed=0, verbosity=0),
    )
    assert len(hofs) == 2
    for h in hofs:
        assert len(h.entries) > 0
