"""Island sharding must not change the search: identical seeds on a
1-device layout and an 8-device island-sharded mesh must produce the
same populations and hall of fame.

Islands are data-independent (migration and frequency statistics are the
only cross-island couplings, and both reduce integer-valued quantities,
which sum exactly in f32 regardless of shard-induced reduction order),
so tree STRUCTURES, hall-of-fame contents, and eval counts must agree
bit-exactly on the virtual CPU mesh the conftest provisions. Constants
are compared to 1e-5: XLA fuses elementwise chains differently for
different layouts, which moves optimizer arithmetic by ~1 ULP.
"""

import numpy as np
import pytest

import jax

from symbolicregression_jl_tpu import Options, search_key
from symbolicregression_jl_tpu.core.dataset import make_dataset
from symbolicregression_jl_tpu.evolve.engine import Engine
from symbolicregression_jl_tpu.parallel.mesh import (
    make_mesh,
    shard_device_data,
    shard_search_state,
)


def _run(n_island_shards: int):
    rng = np.random.default_rng(7)
    X = rng.uniform(-2, 2, (256, 3)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.5 * np.cos(X[:, 2])).astype(np.float32)
    options = Options(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=10,
        populations=8,
        population_size=16,
        tournament_selection_n=4,
        ncycles_per_iteration=4,
        optimizer_probability=0.3,
        optimizer_iterations=2,
        optimizer_nrestarts=1,
        fraction_replaced=0.1,
        save_to_file=False,
    )
    ds = make_dataset(X, y)
    ds.update_baseline_loss(options.elementwise_loss)
    mesh = make_mesh(
        jax.devices()[:n_island_shards],
        n_island_shards=n_island_shards, n_data_shards=1,
    )
    engine = Engine(options, ds.nfeatures)
    data = shard_device_data(ds.data, mesh)
    state = engine.init_state(search_key(123), data, options.populations)
    state = shard_search_state(state, mesh)
    for _ in range(2):
        state = engine.run_iteration(state, data, options.maxsize)
    jax.block_until_ready(state.pops.cost)
    return jax.device_get(state)


@pytest.mark.slow
def test_island_sharding_is_bit_exact():
    assert len(jax.devices()) == 8, "conftest virtual mesh not engaged"
    s1 = _run(1)
    s8 = _run(8)

    for field in ("arity", "op", "feat", "length"):
        a = np.asarray(getattr(s1.pops.trees, field))
        b = np.asarray(getattr(s8.pops.trees, field))
        assert np.array_equal(a, b), f"pops.trees.{field} diverged"
    np.testing.assert_allclose(
        np.asarray(s1.pops.trees.const), np.asarray(s8.pops.trees.const),
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(s1.pops.cost), np.asarray(s8.pops.cost),
        rtol=1e-5, atol=1e-6)

    assert np.array_equal(np.asarray(s1.hof.exists),
                          np.asarray(s8.hof.exists))
    np.testing.assert_allclose(
        np.asarray(s1.hof.cost), np.asarray(s8.hof.cost),
        rtol=1e-5, atol=1e-6)
    for field in ("arity", "op", "feat", "length"):
        assert np.array_equal(
            np.asarray(getattr(s1.hof.trees, field)),
            np.asarray(getattr(s8.hof.trees, field)),
        ), f"hof.trees.{field} diverged"
    assert float(s1.num_evals) == float(s8.num_evals)
