"""Multi-tenancy at the API layer: two ``equation_search`` calls running
concurrently in threads of ONE process (distinct seeds/options) must not
interfere through any shared global — each result must be bit-identical
to its own solo-run reference. This is the contract the graftserve
worker pool stands on (docs/SERVING.md); the refcounted PreemptionGuard
(shield/signals.py) and the per-request StdinQuitWatcher guard are what
make it hold."""

import threading

import numpy as np

from symbolicregression_jl_tpu import Options, equation_search
from symbolicregression_jl_tpu.api.search import RuntimeOptions


def _problem(seed):
    rng = np.random.default_rng(seed)
    X = rng.uniform(-2.0, 2.0, (128, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(**kw):
    base = dict(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        save_to_file=False,
        interactive_quit=False,
    )
    base.update(kw)
    return Options(**base)


def _hof_arrays(state):
    ds = state.device_states[0]
    return {
        **{f: np.asarray(getattr(ds.hof.trees, f))
           for f in ("arity", "op", "feat", "const", "length")},
        "cost": np.asarray(ds.hof.cost),
        "loss": np.asarray(ds.hof.loss),
    }


def _run(spec):
    X, y = _problem(spec["data_seed"])
    state, _ = equation_search(
        X, y, options=spec["options"](),
        runtime_options=RuntimeOptions(
            niterations=spec["niterations"], seed=spec["seed"],
            verbosity=0, return_state=True),
    )
    return _hof_arrays(state)


def test_concurrent_searches_match_solo_references():
    # distinct seeds AND distinct options (different annealing/parsimony
    # host params; same tensor shapes so the test shares compiles)
    specs = {
        "a": dict(data_seed=0, seed=11, niterations=3,
                  options=lambda: _options(parsimony=0.0)),
        "b": dict(data_seed=1, seed=22, niterations=4,
                  options=lambda: _options(parsimony=0.01,
                                           annealing=False)),
    }
    solo = {k: _run(s) for k, s in specs.items()}

    results, errors = {}, {}

    def worker(name, spec):
        try:
            results[name] = _run(spec)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors[name] = e

    threads = [
        threading.Thread(target=worker, args=(k, s), name=f"search-{k}")
        for k, s in specs.items()
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert not errors, errors
    assert set(results) == set(specs)
    for name in specs:
        for field, ref in solo[name].items():
            np.testing.assert_array_equal(
                results[name][field], ref,
                err_msg=f"search {name!r} field {field!r} diverged when "
                        f"run concurrently with another tenant",
            )
