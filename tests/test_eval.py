"""Interpreter golden tests: tensorized eval vs host-side evaluation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.core.losses import aggregate_loss, loss_to_cost, resolve_loss
from symbolicregression_jl_tpu.ops.encoding import encode_population
from symbolicregression_jl_tpu.ops.eval import eval_tree_batch
from symbolicregression_jl_tpu.ops.operators import OperatorSet
from symbolicregression_jl_tpu.ops.tree import parse_expression

OPS = OperatorSet(binary_operators=["+", "-", "*", "/", "^"],
                  unary_operators=["sin", "cos", "exp", "log", "sqrt", "abs"])


def host_eval(tree, X):
    # X: [n, F]
    return np.array([tree.eval_scalar(row) for row in X])


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 5)).astype(np.float32)
    return X


EXPRS = [
    "x1 + x2",
    "3.5",
    "x4",
    "sin(x1) * cos(x2)",
    "exp(x1 / 2.0) - x3 * x4",
    "abs(x2) ^ 0.9",
    "(x1 + x2) * (x3 - 1.5) / (x5 + 10.0)",
    "sqrt(abs(x1)) + log(abs(x2) + 1.0)",
]


def test_eval_matches_host(data):
    X = data
    trees = [parse_expression(e, OPS) for e in EXPRS]
    batch = encode_population(trees, 31, OPS)
    y, valid = eval_tree_batch(batch, jnp.asarray(X.T), OPS)
    y = np.asarray(y)
    for i, t in enumerate(trees):
        expected = host_eval(t, X)
        assert np.asarray(valid)[i], EXPRS[i]
        np.testing.assert_allclose(y[i], expected, rtol=2e-5, atol=1e-5, err_msg=EXPRS[i])


def test_invalid_detection(data):
    X = data  # contains negatives
    trees = [
        parse_expression("log(x1)", OPS),     # invalid (negative args)
        parse_expression("sqrt(x2)", OPS),    # invalid
        parse_expression("x1 / 0.0", OPS),    # inf -> invalid
        parse_expression("x1 + 1.0", OPS),    # valid
    ]
    batch = encode_population(trees, 15, OPS)
    _, valid = eval_tree_batch(batch, jnp.asarray(X.T), OPS)
    assert list(np.asarray(valid)) == [False, False, False, True]


def test_intermediate_inf_is_invalid():
    # exp overflows to inf at an intermediate node, then 1/inf = 0 would be
    # finite — the reference's early-exit still flags it.
    X = np.full((4, 1), 200.0, np.float32)
    t = parse_expression("1.0 / exp(x1)", OPS)
    batch = encode_population([t], 15, OPS)
    _, valid = eval_tree_batch(batch, jnp.asarray(X.T), OPS)
    assert not bool(np.asarray(valid)[0])


def test_batched_shapes(data):
    X = jnp.asarray(data.T)
    trees = [parse_expression(e, OPS) for e in EXPRS[:6]]
    batch = encode_population(trees, 31, OPS).reshape(2, 3)
    y, valid = eval_tree_batch(batch, X, OPS)
    assert y.shape == (2, 3, 64)
    assert valid.shape == (2, 3)


def test_grad_through_interpreter(data):
    """jax.grad wrt constants matches finite differences."""
    X = jnp.asarray(data.T)
    t = parse_expression("x1 * 2.0 + sin(x2) * 0.5", OPS)
    batch = encode_population([t], 15, OPS)
    y_target = jnp.asarray(host_eval(parse_expression("x1 * 1.7 + sin(x2) * 0.9", OPS), data))
    loss_fn_el = resolve_loss(None)

    def loss_of_consts(const):
        import dataclasses

        b = dataclasses.replace(batch, const=const)
        pred, valid = eval_tree_batch(b, X, OPS)
        return aggregate_loss(loss_fn_el, pred[0], y_target, valid[0])

    g = jax.grad(loss_of_consts)(batch.const)
    g = np.asarray(g)[0]
    # finite differences on the two used constant slots
    const0 = np.asarray(batch.const)[0]
    used = [i for i in range(15) if const0[i] != 0.0]
    eps = 1e-3
    for i in used:
        cp = const0.copy(); cp[i] += eps
        cm = const0.copy(); cm[i] -= eps
        fp = float(loss_of_consts(jnp.asarray(cp)[None]))
        fm = float(loss_of_consts(jnp.asarray(cm)[None]))
        fd = (fp - fm) / (2 * eps)
        assert g[i] == pytest.approx(fd, rel=1e-2, abs=1e-3)


class TestLosses:
    def test_weighted(self):
        pred = jnp.asarray([1.0, 2.0, 3.0])
        y = jnp.asarray([0.0, 0.0, 0.0])
        w = jnp.asarray([1.0, 1.0, 2.0])
        loss = aggregate_loss(resolve_loss("L2DistLoss"), pred, y, jnp.bool_(True), w)
        assert float(loss) == pytest.approx((1 + 4 + 2 * 9) / 4)

    def test_invalid_inf(self):
        pred = jnp.asarray([1.0, jnp.nan])
        y = jnp.zeros(2)
        loss = aggregate_loss(resolve_loss(None), pred, y, jnp.bool_(False))
        assert np.isinf(float(loss))

    def test_loss_to_cost(self):
        cost = loss_to_cost(
            jnp.asarray(2.0), jnp.asarray(4.0), jnp.bool_(True),
            jnp.asarray(10, jnp.int32), 0.01,
        )
        assert float(cost) == pytest.approx(0.5 + 0.1)

    def test_loss_to_cost_floor(self):
        cost = loss_to_cost(
            jnp.asarray(2.0), jnp.asarray(0.001), jnp.bool_(True),
            jnp.asarray(0, jnp.int32), 0.0,
        )
        assert float(cost) == pytest.approx(200.0)


def test_complexity_and_constraints():
    from symbolicregression_jl_tpu.core.options import Options
    from symbolicregression_jl_tpu.ops.complexity import (
        build_complexity_tables,
        check_constraints_batch,
        compute_complexity_batch,
    )
    from symbolicregression_jl_tpu.ops.encoding import tree_structure_arrays

    opts = Options(
        binary_operators=["+", "*", "^"],
        unary_operators=["sin", "exp"],
        maxsize=10,
        maxdepth=4,
        constraints={"^": (-1, 2)},
        nested_constraints={"sin": {"sin": 0}},
        complexity_of_operators={"exp": 3},
    )
    tables = build_complexity_tables(opts, 5)
    trees = [
        parse_expression("x1 + x2", opts.operators),            # cx 3, ok
        parse_expression("exp(x1)", opts.operators),            # cx 1+3=4, ok
        parse_expression("x1 ^ (x2 + x3)", opts.operators),     # ^ arg2 size 3 > 2 -> bad
        parse_expression("sin(sin(x1))", opts.operators),       # nested sin -> bad
        parse_expression("sin(x1 * sin(x2)) + sin(x3)", opts.operators),  # nested -> bad
        parse_expression("sin(x1) + sin(x2)", opts.operators),  # ok
        parse_expression("x1 * x2 * x3 * x4 * x5 * x1", opts.operators),  # cx 11 > 10 -> bad
        parse_expression("((x1 + x2) + x3) + ((x4 + x5) + (x1 + x2))", opts.operators),  # 13 nodes > 10 -> bad
    ]
    batch = encode_population(trees, 16, opts.operators)
    cx = np.asarray(compute_complexity_batch(batch, tables))
    assert cx[0] == 3
    assert cx[1] == 4
    child, size, depth = tree_structure_arrays(batch)
    ok = np.asarray(
        check_constraints_batch(batch, opts, tables, jnp.asarray(10), child, size, depth)
    )
    assert list(ok) == [True, True, False, False, False, True, False, False]
