"""Safe-operator NaN semantics (parity with src/Operators.jl:35-124)."""

import jax.numpy as jnp
import numpy as np
import pytest

from symbolicregression_jl_tpu.ops import operators as ops


def arr(*vals):
    return jnp.asarray(vals, jnp.float32)


class TestSafePow:
    def test_positive_base(self):
        out = ops.safe_pow(arr(2.0), arr(3.0))
        assert np.allclose(out, 8.0)

    def test_negative_base_integer_exponent(self):
        assert np.allclose(ops.safe_pow(arr(-2.0), arr(3.0)), -8.0)
        assert np.allclose(ops.safe_pow(arr(-2.0), arr(2.0)), 4.0)

    def test_negative_base_noninteger_exponent_nan(self):
        assert np.isnan(ops.safe_pow(arr(-2.0), arr(0.5)))

    def test_zero_base_negative_integer_exponent_nan(self):
        assert np.isnan(ops.safe_pow(arr(0.0), arr(-2.0)))

    def test_zero_base_negative_noninteger_exponent_nan(self):
        assert np.isnan(ops.safe_pow(arr(0.0), arr(-0.5)))

    def test_negative_base_negative_noninteger_nan(self):
        assert np.isnan(ops.safe_pow(arr(-1.0), arr(-0.5)))

    def test_zero_zero_is_one(self):
        assert np.allclose(ops.safe_pow(arr(0.0), arr(0.0)), 1.0)

    def test_negative_integer_exponent(self):
        assert np.allclose(ops.safe_pow(arr(-2.0), arr(-2.0)), 0.25)
        assert np.allclose(ops.safe_pow(arr(-2.0), arr(-3.0)), -0.125)


@pytest.mark.parametrize(
    "fn,good,good_val,bad",
    [
        (ops.safe_log, 1.0, 0.0, -1.0),
        (ops.safe_log, np.e, 1.0, 0.0),
        (ops.safe_log2, 8.0, 3.0, -2.0),
        (ops.safe_log10, 100.0, 2.0, 0.0),
        (ops.safe_log1p, 0.0, 0.0, -1.5),
        (ops.safe_sqrt, 4.0, 2.0, -1.0),
        (ops.safe_asin, 1.0, np.pi / 2, 1.5),
        (ops.safe_acos, 1.0, 0.0, -1.5),
        (ops.safe_acosh, 1.0, 0.0, 0.5),
        (ops.safe_atanh, 0.0, 0.0, 1.5),
    ],
)
def test_safe_unary_domains(fn, good, good_val, bad):
    # float32 transcendentals on XLA backends (CPU fast-math, TPU) carry
    # ~1e-5 relative error; exact-value parity is not the contract here.
    assert np.allclose(fn(arr(good)), good_val, rtol=1e-4, atol=1e-5)
    assert np.isnan(fn(arr(bad)))


def test_comparison_ops_return_float():
    assert float(ops.greater(arr(2.0), arr(1.0))[0]) == 1.0
    assert float(ops.less(arr(2.0), arr(1.0))[0]) == 0.0
    assert float(ops.cond(arr(1.0), arr(5.0))[0]) == 5.0
    assert float(ops.cond(arr(-1.0), arr(5.0))[0]) == 0.0
    assert float(ops.logical_or(arr(-1.0), arr(2.0))[0]) == 1.0
    assert float(ops.logical_and(arr(-1.0), arr(2.0))[0]) == 0.0


def test_gamma_matches_scipy_and_poles():
    from math import gamma as pygamma

    for x in (0.5, 1.0, 2.5, 4.0, -0.5, -1.5):
        got = float(ops.gamma(jnp.asarray([x], jnp.float32))[0])
        assert got == pytest.approx(pygamma(x), rel=2e-3), x
    assert np.isnan(ops.gamma(arr(0.0)))  # pole -> inf -> NaN


def test_operator_set_basics():
    s = ops.OperatorSet(binary_operators=["+", "-", "*", "/"],
                        unary_operators=["sin", "exp"])
    assert s.nops == {1: 2, 2: 4}
    assert s.nops_tuple() == (2, 4)
    d, i = s.index_of("sin")
    assert (d, i) == (1, 0)
    assert s == ops.OperatorSet(binary_operators=("+", "-", "*", "/"),
                                unary_operators=("sin", "exp"))


def test_alias_resolution():
    assert ops.resolve_operator("plus").name == "+"
    assert ops.resolve_operator("safe_log").name == "log"
    assert ops.resolve_operator("pow").name == "^"


def test_custom_callable_operator():
    import jax.numpy as jnp

    def myop(x, y):
        return x * y + 1

    op = ops.resolve_operator(myop, 2)
    assert op.arity == 2
    assert np.allclose(op.fn(jnp.asarray(2.0), jnp.asarray(3.0)), 7.0)
