"""Dimensional analysis tests.

Mirrors the reference's unit-handling test coverage
(test/integration/ext/dynamicquantities_units — 484 LoC of cases):
unit parsing, wildcard-constant semantics, per-operator propagation,
and the cost penalty inside the search.
"""

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options, make_dataset, parse_expression
from symbolicregression_jl_tpu.core.units import (
    DIMENSIONLESS,
    Dimensions,
    dims_to_array,
    parse_unit,
    pretty_dims,
)
from symbolicregression_jl_tpu.ops.dims_eval import (
    violates_dimensional_constraints,
)


def dims(**kw):
    idx = {"m": 0, "kg": 1, "s": 2, "A": 3, "K": 4, "cd": 5, "mol": 6}
    e = [0.0] * 7
    for k, v in kw.items():
        e[idx[k]] = v
    return np.asarray(e, np.float32)


class TestUnitParsing:
    def test_base_units(self):
        assert np.allclose(dims_to_array(parse_unit("m").dims), dims(m=1))
        assert np.allclose(dims_to_array(parse_unit("s").dims), dims(s=1))
        assert np.allclose(dims_to_array(parse_unit("kg").dims), dims(kg=1))

    def test_compound(self):
        q = parse_unit("m/s^2")
        assert np.allclose(dims_to_array(q.dims), dims(m=1, s=-2))
        q = parse_unit("kg*m^2/s^2")  # joule
        assert np.allclose(dims_to_array(q.dims), dims(kg=1, m=2, s=-2))

    def test_space_multiplication(self):
        q = parse_unit("kg m s^-2")  # newton
        assert np.allclose(dims_to_array(q.dims), dims(kg=1, m=1, s=-2))

    def test_derived_units(self):
        assert np.allclose(
            dims_to_array(parse_unit("N").dims), dims(kg=1, m=1, s=-2)
        )
        assert np.allclose(
            dims_to_array(parse_unit("J").dims), dims(kg=1, m=2, s=-2)
        )
        assert np.allclose(dims_to_array(parse_unit("Hz").dims), dims(s=-1))

    def test_prefixes(self):
        km = parse_unit("km")
        assert km.scale == pytest.approx(1000.0)
        assert np.allclose(dims_to_array(km.dims), dims(m=1))
        mg = parse_unit("mg")
        assert mg.scale == pytest.approx(1e-6)
        assert np.allclose(dims_to_array(mg.dims), dims(kg=1))

    def test_dimensionless(self):
        for spec in (None, "", "1"):
            assert parse_unit(spec).dims.is_dimensionless

    def test_fractional_exponent(self):
        q = parse_unit("m^0.5")
        assert np.allclose(dims_to_array(q.dims), dims(m=0.5))

    def test_unknown_unit_raises(self):
        with pytest.raises(ValueError):
            parse_unit("furlong")

    def test_pretty(self):
        assert pretty_dims(parse_unit("m/s^2").dims) == "m s⁻²"
        assert pretty_dims(DIMENSIONLESS) == ""

    def test_dimensions_algebra(self):
        a = Dimensions.base(0)  # m
        b = Dimensions.base(2)  # s
        assert (a / b).exps[0] == 1 and (a / b).exps[2] == -1
        assert (a ** 2).exps[0] == 2


def _ds(X_units, y_units, nfeat=2):
    rng = np.random.default_rng(0)
    X = rng.uniform(1.0, 2.0, (16, nfeat))
    y = rng.uniform(1.0, 2.0, 16)
    return make_dataset(X, y, X_units=X_units, y_units=y_units)


@pytest.fixture(scope="module")
def opts():
    return Options(
        binary_operators=["+", "-", "*", "/", "^"],
        unary_operators=["sin", "sqrt", "square", "neg", "abs"],
    )


def _viol(expr, ds, options):
    tree = parse_expression(expr, options.operators,
                            variable_names=ds.variable_names)
    return violates_dimensional_constraints(tree, ds, options)


class TestDimensionalConstraints:
    def test_no_units_never_violates(self, opts):
        ds = make_dataset(np.ones((4, 2)), np.ones(4))
        assert not _viol("x1 + x2", ds, opts)

    def test_matching_division(self, opts):
        ds = _ds(["m", "s"], "m/s")
        assert not _viol("x1 / x2", ds, opts)

    def test_mismatched_addition(self, opts):
        ds = _ds(["m", "s"], "m")
        assert _viol("x1 + x2", ds, opts)

    def test_addition_same_units(self, opts):
        ds = _ds(["m", "m"], "m")
        assert not _viol("x1 + x2", ds, opts)

    def test_y_mismatch(self, opts):
        ds = _ds(["m", "s"], "kg")
        assert _viol("x1 / x2", ds, opts)

    def test_wildcard_constant_absorbs_units(self, opts):
        # c * x1 can match any output unit: c's dims are free
        ds = _ds(["m", "s"], "kg")
        assert not _viol("3.2 * x1", ds, opts)

    def test_wildcard_inside_transcendental(self, opts):
        # sin(c * x1) is fine: c absorbs x1's dims
        ds = _ds(["m", "s"], "1")
        assert not _viol("sin(1.5 * x1)", ds, opts)

    def test_transcendental_of_dimensional_violates(self, opts):
        ds = _ds(["m", "s"], "1")
        assert _viol("sin(x1)", ds, opts)
        # x1/x2 still carries m/s here, so sin of it also violates
        assert _viol("sin(x1 / x2)", ds, opts)

    def test_transcendental_of_ratio(self, opts):
        ds = _ds(["m", "m"], "1")
        assert not _viol("sin(x1 / x2)", ds, opts)

    def test_sqrt_and_square(self, opts):
        ds = _ds(["m^2", "s"], "m")
        assert not _viol("sqrt(x1)", ds, opts)
        ds2 = _ds(["m", "s"], "m^2")
        assert not _viol("square(x1)", ds2, opts)
        assert _viol("sqrt(x1)", ds2, opts)

    def test_pow_integer_constant(self, opts):
        ds = _ds(["m", "s"], "m^2")
        assert not _viol("x1 ^ 2.0", ds, opts)
        assert _viol("x1 ^ 3.0", ds, opts)

    def test_pow_dimensional_exponent_violates(self, opts):
        ds = _ds(["m", "s"], "1")
        # exponent carrying units is illegal even though base is wildcard
        assert _viol("2.0 ^ x2", ds, opts)

    def test_neg_abs_preserve(self, opts):
        ds = _ds(["m", "s"], "m")
        assert not _viol("neg(x1)", ds, opts)
        assert not _viol("abs(x1)", ds, opts)

    def test_missing_y_units_accepts_any_output_dims(self, opts):
        # X units given, y units absent: output dims unconstrained
        # (src/DimensionalAnalysis.jl:250-255)
        ds = _ds(["m", "s"], None)
        assert not _viol("x1 / x2", ds, opts)
        assert not _viol("x1", ds, opts)
        # internal violations still count
        assert _viol("x1 + x2", ds, opts)

    def test_dimensionless_constants_only(self):
        options = Options(
            binary_operators=["+", "*"],
            unary_operators=["sin"],
            dimensionless_constants_only=True,
        )
        ds = _ds(["m", "s"], "1")
        # with rigid constants, c * x1 cannot match dimensionless y
        assert _viol("3.2 * x1", ds, options)
        ds2 = _ds(["1", "1"], "1")
        assert not _viol("3.2 * x1", ds2, options)


class TestSearchWithUnits:
    @pytest.mark.slow
    def test_search_respects_units(self):
        # y = x1/x2 with units m, s -> m/s; the penalty should steer the
        # search to unit-consistent expressions.
        rng = np.random.default_rng(42)
        X = rng.uniform(0.5, 2.0, (128, 2))
        y = X[:, 0] / X[:, 1]
        from symbolicregression_jl_tpu import equation_search

        options = Options(
            binary_operators=["+", "-", "*", "/"],
            populations=2,
            population_size=20,
            ncycles_per_iteration=20,
            maxsize=12,
            save_to_file=False,
        )
        # Short searches are seed-sensitive (the reference's benchmark runs
        # 3 seeds for the same reason, benchmark/benchmarks.jl:11-81); pass
        # if any of a fixed seed set recovers the target.
        best_loss = np.inf
        for seed in (0, 1, 2):
            hof = equation_search(
                X, y, options=options, niterations=4,
                X_units=["m", "s"], y_units="m/s",
                verbosity=0, seed=seed,
            )
            best = min(hof.entries, key=lambda e: e.loss)
            best_loss = min(best_loss, best.loss)
            if best_loss < 1e-2:
                break
        assert best_loss < 1e-2

    def test_unit_annotated_display_names(self):
        ds = _ds(["m", "s"], "m/s")
        assert ds.display_variable_names[0].endswith("[m]")
        assert ds.display_variable_names[1].endswith("[s]")
