"""Recorder genealogy + progress bar integration tests
(src/Recorder.jl + ext/SymbolicRegressionJSON3Ext.jl analogues)."""

import json
import os

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options, equation_search


def _problem(n=64):
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (n, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1]).astype(np.float32)
    return X, y


def _options(tmp_path, **kw):
    return Options(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        output_directory=str(tmp_path),
        **kw,
    )


@pytest.mark.slow
def test_recorder_writes_genealogy(tmp_path):
    X, y = _problem()
    options = _options(tmp_path, use_recorder=True, recorder_file="rec.json")
    equation_search(
        X, y, options=options, niterations=2, verbosity=0, run_id="recrun",
        seed=0,
    )
    path = os.path.join(str(tmp_path), "recrun", "rec.json")
    assert os.path.exists(path)
    with open(path) as f:
        rec = json.load(f)
    assert rec["final_state"]["stop_reason"] == "niterations"
    assert len(rec["iterations"]) == 2
    first = rec["iterations"][0]
    assert len(first["islands"]) == 2
    isl = first["islands"][0]
    # lineage arrays cover every member
    assert len(isl["ref"]) == 8 and len(isl["parent"]) == 8
    assert all(isinstance(e["equation"], str) for e in first["hall_of_fame"])


def test_progress_bar_smoke(tmp_path, capsys):
    X, y = _problem()
    options = _options(tmp_path, save_to_file=False)
    # SYMBOLIC_REGRESSION_IS_TESTING redirects the bar to devnull; this
    # just exercises the code path.
    equation_search(
        X, y, options=options, niterations=1, verbosity=0, progress=True,
        seed=0,
    )


def test_resource_monitor_fraction_and_warning(capsys):
    """ResourceMonitor analogue (src/SearchUtils.jl:411-438): host
    fraction estimate and the one-shot pacing warning."""
    from symbolicregression_jl_tpu.utils.monitor import ResourceMonitor

    m = ResourceMonitor(window=4, warn_fraction=0.2)
    for _ in range(4):
        m.record(device_seconds=1.0, host_seconds=1.0)
    assert abs(m.estimate_work_fraction() - 0.5) < 1e-9
    assert m.check_and_warn(verbosity=1)
    assert "host bookkeeping" in capsys.readouterr().out
    # one-shot: does not warn twice
    assert not m.check_and_warn(verbosity=1)

    fast = ResourceMonitor(window=2, warn_fraction=0.2)
    fast.record(1.0, 0.01)
    fast.record(1.0, 0.01)
    assert not fast.check_and_warn(verbosity=0)
