"""Recorder genealogy + progress bar integration tests
(src/Recorder.jl + ext/SymbolicRegressionJSON3Ext.jl analogues)."""

import json
import os

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options, equation_search


def _problem(n=64):
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (n, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1]).astype(np.float32)
    return X, y


def _options(tmp_path, **kw):
    return Options(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        output_directory=str(tmp_path),
        **kw,
    )


@pytest.mark.slow
def test_recorder_writes_genealogy(tmp_path):
    X, y = _problem()
    options = _options(tmp_path, use_recorder=True, recorder_file="rec.json")
    equation_search(
        X, y, options=options, niterations=2, verbosity=0, run_id="recrun",
        seed=0,
    )
    path = os.path.join(str(tmp_path), "recrun", "rec.json")
    assert os.path.exists(path)
    with open(path) as f:
        rec = json.load(f)
    assert rec["final_state"]["stop_reason"] == "niterations"
    assert len(rec["iterations"]) == 2
    first = rec["iterations"][0]
    assert len(first["islands"]) == 2
    isl = first["islands"][0]
    # lineage arrays cover every member
    assert len(isl["ref"]) == 8 and len(isl["parent"]) == 8
    assert all(isinstance(e["equation"], str) for e in first["hall_of_fame"])


def test_recorder_event_stream_reconstructs_lineage(tmp_path):
    """Per-mutation events (src/RegularizedEvolution.jl:47-149 analogue):
    every accepted event names a parent/child/died ref, kinds resolve to
    real names, and parent refs chain onto earlier children within the
    iteration (the genealogy DAG is reconstructible from events alone)."""
    X, y = _problem()
    options = Options(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=16,
        ncycles_per_iteration=30,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        output_directory=str(tmp_path),
        use_recorder=True,
        recorder_file="rec.json",
    )
    equation_search(
        X, y, options=options, niterations=1, verbosity=0, run_id="evrun",
        seed=3,
    )
    with open(os.path.join(str(tmp_path), "evrun", "rec.json")) as f:
        rec = json.load(f)
    ev_block = rec["iterations"][0]["events"][0]
    acc = ev_block["accepted"]
    assert len(acc) > 10
    from symbolicregression_jl_tpu.core.options import MUTATION_KINDS

    names = set(MUTATION_KINDS) | {"crossover"}
    per_island_children = {}
    for e in acc:
        assert e["type"] in names
        assert e["child"] >= 0 and e["died"] >= 0
        per_island_children.setdefault(e["island"], set())
        if e["type"] == "crossover":
            assert "parent2" in e
    # Chain: some later event's parent is an earlier event's child of the
    # same island (cycle order is recorded, so "earlier" is checkable).
    chained = 0
    for isl in per_island_children:
        evs = sorted((e for e in acc if e["island"] == isl),
                     key=lambda e: e["cycle"])
        seen = set()
        for e in evs:
            if e["parent"] in seen:
                chained += 1
            seen.add(e["child"])
    assert chained > 0, "no parent->child chains found across cycles"
    # Death bookkeeping: a replaced (died) member is either one of the
    # initial population (refs carry the island*1e6 tagging scheme from
    # Engine.init_state) or an earlier counter-minted child, whose refs
    # grow monotonically — so non-initial died refs strictly precede
    # their replacement's ref.
    for e in acc:
        is_initial = e["died"] >= 1_000_000 or e["died"] < 16  # P=16
        assert is_initial or e["died"] < e["child"], e
    assert isinstance(ev_block["rejected_counts"], dict)
    # verbosity 1 (default): no per-event rejection records
    assert "rejected" not in ev_block


def test_recorder_verbosity2_rejection_events(tmp_path):
    """recorder_verbosity >= 2 emits every rejected candidate as its own
    event with a reason (constraint / invalid / annealing), matching the
    reference's per-mutation tmp_recorder detail
    (src/RegularizedEvolution.jl:47-75, src/Mutate.jl:270-355)."""
    X, y = _problem()
    options = Options(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=16,
        ncycles_per_iteration=30,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        output_directory=str(tmp_path),
        use_recorder=True,
        recorder_file="rec.json",
        recorder_verbosity=2,
    )
    equation_search(
        X, y, options=options, niterations=1, verbosity=0, run_id="evrun2",
        seed=3,
    )
    with open(os.path.join(str(tmp_path), "evrun2", "rec.json")) as f:
        rec = json.load(f)
    ev_block = rec["iterations"][0]["events"][0]
    rej = ev_block["rejected"]
    assert len(rej) > 0
    from symbolicregression_jl_tpu.core.options import MUTATION_KINDS

    names = set(MUTATION_KINDS) | {"crossover"}
    reasons = {"constraint", "invalid", "annealing", "none"}
    for e in rej:
        assert e["type"] in names
        assert e["reason"] in reasons
        assert isinstance(e["parent"], int)
    # the aggregate counts agree with the per-event stream
    assert sum(ev_block["rejected_counts"].values()) == len(rej)
    # same seed, same search: verbosity only changes the log detail
    accs = ev_block["accepted"]
    assert len(accs) > 10
    # verbosity-2 iteration records stream to <recorder_file>.stream as
    # they are assembled (memory cap); write() merges them back into the
    # reference layout (asserted above) and removes the spill file
    assert not os.path.exists(
        os.path.join(str(tmp_path), "evrun2", "rec.json.stream")
    )


def test_progress_bar_smoke(tmp_path, capsys):
    X, y = _problem()
    options = _options(tmp_path, save_to_file=False)
    # SYMBOLIC_REGRESSION_IS_TESTING redirects the bar to devnull; this
    # just exercises the code path.
    equation_search(
        X, y, options=options, niterations=1, verbosity=0, progress=True,
        seed=0,
    )


def test_resource_monitor_fraction_and_warning(capsys):
    """ResourceMonitor analogue (src/SearchUtils.jl:411-438): host
    fraction estimate and the edge-triggered pacing warning."""
    from symbolicregression_jl_tpu.utils.monitor import ResourceMonitor

    m = ResourceMonitor(window=4, warn_fraction=0.2)
    for _ in range(4):
        m.record(device_seconds=1.0, host_seconds=1.0)
    assert abs(m.estimate_work_fraction() - 0.5) < 1e-9
    assert m.check_and_warn(verbosity=1)
    assert "host bookkeeping" in capsys.readouterr().out
    # edge-triggered: does not warn twice while still over threshold
    assert not m.check_and_warn(verbosity=1)

    fast = ResourceMonitor(window=2, warn_fraction=0.2)
    fast.record(1.0, 0.01)
    fast.record(1.0, 0.01)
    assert not fast.check_and_warn(verbosity=0)


def test_resource_monitor_rearms_after_recovery(capsys):
    """A host-overhead regression AFTER a recovery must warn again —
    the old one-shot latch never reset (silent regression)."""
    from symbolicregression_jl_tpu.utils.monitor import ResourceMonitor

    m = ResourceMonitor(window=2, warn_fraction=0.2)
    m.record(1.0, 1.0)
    m.record(1.0, 1.0)
    assert m.check_and_warn(verbosity=1)          # first excursion warns
    assert not m.check_and_warn(verbosity=1)      # latched while high
    capsys.readouterr()
    m.record(1.0, 0.01)
    m.record(1.0, 0.01)
    assert not m.check_and_warn(verbosity=1)      # recovered: re-arms
    assert "recovered" in capsys.readouterr().out
    m.record(1.0, 1.0)
    m.record(1.0, 1.0)
    assert m.check_and_warn(verbosity=1)          # regression warns AGAIN
    assert "host bookkeeping" in capsys.readouterr().out
