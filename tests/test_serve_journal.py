"""graftserve host-side units: journal durability/corruption semantics,
shape-bucketed admission + overload ladder, canonical options
fingerprint, result encoding (docs/SERVING.md)."""

import json
import os

import numpy as np
import pytest

from symbolicregression_jl_tpu import Options
from symbolicregression_jl_tpu.api.checkpoint import options_fingerprint
from symbolicregression_jl_tpu.serve.admission import (
    AdmissionController,
    ServerSaturated,
    shape_bucket,
)
from symbolicregression_jl_tpu.serve.journal import (
    JOURNAL_SCHEMA,
    RequestJournal,
    decode_array,
    encode_array,
)
from symbolicregression_jl_tpu.shield.degrade import OverloadLadder
from symbolicregression_jl_tpu.shield.faults import flip_byte


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------


def test_journal_roundtrip_and_array_bit_exactness(tmp_path):
    j = RequestJournal(str(tmp_path / "j.jsonl"))
    X = np.random.default_rng(0).normal(size=(7, 3)).astype(np.float32)
    j.append("submit", "r1", {"X": encode_array(X), "n": 4})
    j.append("start", "r1", {})
    j.append("done", "r1", {"result": {"fingerprint": "abc"}})
    records, corrupt = j.replay()
    assert not corrupt
    assert [r["event"] for r in records] == ["submit", "start", "done"]
    assert all(r["schema"] == JOURNAL_SCHEMA for r in records)
    assert [r["seq"] for r in records] == [1, 2, 3]
    back = decode_array(records[0]["detail"]["X"])
    assert back.dtype == X.dtype
    np.testing.assert_array_equal(back, X)  # bit-exact round trip


def test_journal_torn_tail_is_dropped_and_noted(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path)
    j.append("submit", "r1", {})
    j.append("start", "r1", {})
    # crash mid-append: chop the last record in half
    with open(path, "rb+") as f:
        data = f.read()
        f.truncate(len(data) - len(data.splitlines()[-1]) // 2 - 1)
    records, corrupt = RequestJournal(path).replay()
    assert [r["event"] for r in records] == ["submit"]
    assert len(corrupt) == 1 and corrupt[0]["torn_tail"]


def test_journal_corrupt_middle_record_skipped_and_reported(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path)
    j.append("submit", "r1", {"payload": "x" * 64})
    j.append("submit", "r2", {})
    j.append("start", "r1", {})
    with open(path, "rb") as f:
        first_len = len(f.readline())
    flip_byte(path, first_len // 2)  # corrupt record 1 in place
    records, corrupt = RequestJournal(path).replay()
    assert [r["request_id"] for r in records] == ["r2", "r1"]
    assert len(corrupt) == 1
    assert not corrupt[0]["torn_tail"]
    assert corrupt[0]["line"] == 1


def test_journal_append_after_torn_tail_stays_readable(tmp_path):
    """A post-restart append must not be glued onto a torn final line:
    the acknowledged (fsync'd) new record has to survive a SECOND
    crash-replay, or the durability contract is broken."""
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path)
    j.append("submit", "r1", {})
    j.append("start", "r1", {})
    # crash mid-append: partial final record, no trailing newline
    with open(path, "rb+") as f:
        data = f.read()
        f.truncate(len(data) - len(data.splitlines()[-1]) // 2 - 1)
    j2 = RequestJournal(path)
    j2.append("submit", "r2", {})
    records, corrupt = RequestJournal(path).replay()
    assert [r["request_id"] for r in records] == ["r1", "r2"]
    assert len(corrupt) == 1  # the sealed torn line, still audited


def test_journal_seq_continues_after_reopen(tmp_path):
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path)
    j.append("submit", "r1", {})
    j2 = RequestJournal(path)
    assert j2.append("start", "r1", {}) == 2


def test_journal_rejects_unknown_event(tmp_path):
    j = RequestJournal(str(tmp_path / "j.jsonl"))
    with pytest.raises(ValueError):
        j.append("launch", "r1", {})


# ---------------------------------------------------------------------------
# admission + ladder
# ---------------------------------------------------------------------------


def test_shape_bucket_rounds_up_power_of_two():
    assert shape_bucket(10, 2) == (256, 2, 1)
    assert shape_bucket(256, 2) == (256, 2, 1)
    assert shape_bucket(257, 3, nout=2) == (512, 3, 2)
    assert shape_bucket(5000, 1) == (8192, 1, 1)


def test_admission_rejects_when_full_with_structured_error():
    ac = AdmissionController(capacity=2)
    d1 = ac.admit(n_rows=100, nfeatures=2)
    ac.admit(n_rows=100, nfeatures=2)
    with pytest.raises(ServerSaturated) as ei:
        ac.admit(n_rows=100, nfeatures=2)
    e = ei.value
    assert e.retry_after_s > 0
    assert e.queue_depth == 2 and e.capacity == 2
    assert tuple(e.bucket) == (256, 2, 1)
    assert e.to_dict()["error"] == "server_saturated"
    ac.release(d1.bucket)
    ac.admit(n_rows=100, nfeatures=2)  # slot freed


def test_admission_bucket_class_bound():
    ac = AdmissionController(capacity=4, bucket_capacity=1)
    ac.admit(n_rows=100, nfeatures=2)
    with pytest.raises(ServerSaturated):
        ac.admit(n_rows=100, nfeatures=2)  # same class full
    ac.admit(n_rows=5000, nfeatures=2)  # different class admitted


def test_admission_retry_after_tracks_service_time():
    ac = AdmissionController(capacity=2)
    ac.admit(n_rows=10, nfeatures=1)
    ac.admit(n_rows=10, nfeatures=1)
    ac.observe_service_time(10.0)
    with pytest.raises(ServerSaturated) as ei:
        ac.admit(n_rows=10, nfeatures=1)
    assert ei.value.retry_after_s >= 10.0


def test_overload_ladder_levels_and_shed():
    lad = OverloadLadder(shed_sample_at=0.5, shed_priority_at=0.75,
                         reject_at=1.0, sample_fraction=0.5,
                         min_sample_rows=16)
    assert lad.level(0.0) == "normal"
    assert lad.level(0.5) == "shed_sample"
    assert lad.level(0.75) == "shed_priority"
    assert lad.level(1.0) == "reject"
    d = lad.apply(0.6, n_rows=1000, priority=0)
    assert d["admit"] and d["sample_rows"] == 500 and d["priority"] == 0
    d = lad.apply(0.8, n_rows=1000, priority=0)
    assert d["admit"] and d["sample_rows"] == 500 and d["priority"] == 1
    d = lad.apply(1.0, n_rows=1000, priority=0)
    assert not d["admit"]
    # the floor protects tiny datasets from being shredded
    d = lad.apply(0.6, n_rows=20, priority=0)
    assert d["sample_rows"] is None or d["sample_rows"] >= 16


def test_ladder_audits_only_real_sheds():
    lad = OverloadLadder(shed_sample_at=0.5, sample_fraction=0.5,
                         min_sample_rows=64)
    # at shed level but the dataset is already at/below the floor and
    # priority is untouched: admitted unchanged → NOT a shed
    d = lad.apply(0.6, n_rows=32, priority=0)
    assert d["admit"] and d["sample_rows"] is None and d["priority"] == 0
    assert lad.sheds_total == 0
    # a real shed still counts
    d = lad.apply(0.6, n_rows=1000, priority=0)
    assert d["sample_rows"] == 500
    assert lad.sheds_total == 1


def test_ladder_threshold_validation():
    with pytest.raises(ValueError):
        OverloadLadder(shed_sample_at=0.9, shed_priority_at=0.5)
    with pytest.raises(ValueError):
        OverloadLadder(sample_fraction=0.0)


def test_admission_readmit_bypasses_bounds():
    ac = AdmissionController(capacity=1)
    ac.admit(n_rows=10, nfeatures=1)
    # journal-replayed acceptances must never be refused
    ac.readmit((256, 1, 1))
    assert ac.depth == 2
    with pytest.raises(ServerSaturated):
        ac.admit(n_rows=10, nfeatures=1)


# ---------------------------------------------------------------------------
# canonical options fingerprint (executable-cache key)
# ---------------------------------------------------------------------------


def _opts(**kw):
    base = dict(binary_operators=["+", "*"], unary_operators=[],
                maxsize=8, populations=2, population_size=8,
                tournament_selection_n=4)
    base.update(kw)
    return Options(**base)


def test_options_fingerprint_stable_across_instances():
    assert options_fingerprint(_opts()) == options_fingerprint(_opts())


def test_options_fingerprint_ignores_host_only_fields():
    a = _opts()
    b = _opts(output_directory="/elsewhere", telemetry=True, verbosity=2,
              seed=99, max_retries=7, interactive_quit=False)
    assert options_fingerprint(a) == options_fingerprint(b)


def test_options_fingerprint_sees_numeric_and_operator_changes():
    base = options_fingerprint(_opts())
    assert options_fingerprint(_opts(maxsize=10)) != base
    assert options_fingerprint(_opts(parsimony=0.1)) != base
    assert options_fingerprint(
        _opts(binary_operators=["+", "-"])) != base


def test_options_fingerprint_uncacheable_for_opaque_callables():
    # a C callable has no __code__ → must refuse to fingerprint rather
    # than risk a silent hyperparameter collision
    assert options_fingerprint(_opts(elementwise_loss=abs)) is None


def test_options_fingerprint_library_operator_callables_cacheable():
    # jnp-backed operator callables (e.g. unary "cos" resolving to
    # jnp.cos) carry no __code__ but are process-stable by dotted name
    # — configs using them must stay cacheable (the serve executable
    # cache and the mesh AOT key both consume this), and different
    # operators must not collide
    a = options_fingerprint(_opts(unary_operators=["cos"]))
    b = options_fingerprint(_opts(unary_operators=["exp"]))
    assert a is not None and b is not None and a != b


def test_options_fingerprint_rejects_library_instance_callables():
    # np.vectorize instances report __module__='numpy' but carry
    # per-instance behavior — two different vectorized lambdas must NOT
    # collide on a 'lib:' name digest (they'd silently share a compiled
    # engine); the dotted name fails to resolve back to the instance,
    # so the config is uncacheable
    import numpy as np

    f1 = np.vectorize(lambda p, t: (p - t) ** 2)
    f2 = np.vectorize(lambda p, t: abs(p - t) ** 3)
    assert options_fingerprint(_opts(elementwise_loss=f1)) is None
    assert options_fingerprint(_opts(elementwise_loss=f2)) is None


def test_options_fingerprint_distinguishes_loss_closures():
    a = options_fingerprint(_opts(elementwise_loss="huber"))
    from symbolicregression_jl_tpu.core.losses import huber_loss

    b = options_fingerprint(_opts(elementwise_loss=huber_loss(2.0)))
    assert a is not None and b is not None and a != b


def test_options_fingerprint_distinguishes_kwonly_defaults():
    # identical co_code + empty closure/defaults, differing only in
    # __kwdefaults__ — must not collide (and share a cached engine)
    def make(delta):
        def loss(p, t, *, d=delta):
            return abs(p - t) * d
        return loss

    a = options_fingerprint(_opts(elementwise_loss=make(1.0)))
    b = options_fingerprint(_opts(elementwise_loss=make(2.0)))
    assert a is not None and b is not None and a != b


def test_options_fingerprint_bound_method_receiver_state():
    # a bound method's behavior depends on its receiver; arbitrary
    # receiver state has no canonical form → uncacheable, not a digest
    class Scaler:
        def __init__(self, s):
            self.s = s

        def loss(self, p, t):
            return abs(p - t) * self.s

    assert options_fingerprint(
        _opts(elementwise_loss=Scaler(2.0).loss)) is None


_FP_GLOBAL_SCALE = 2.0


def _loss_reading_global(p, t):
    return _FP_GLOBAL_SCALE * abs(p - t)


def test_options_fingerprint_rejects_nonmodule_global_reads():
    # a module-level constant can be rebound without changing co_code —
    # no process-stable canonical form → uncacheable, not a collision
    assert options_fingerprint(
        _opts(elementwise_loss=_loss_reading_global)) is None


def _loss_reading_global_in_genexpr(p, t):
    return sum(_FP_GLOBAL_SCALE * x for x in [abs(p - t)])


def test_options_fingerprint_rejects_global_reads_in_nested_code():
    # the global read happens inside the genexpr's own code object —
    # the guard must recurse into co_consts, not just scan the outer
    # co_names
    assert options_fingerprint(
        _opts(elementwise_loss=_loss_reading_global_in_genexpr)) is None


# ---------------------------------------------------------------------------
# serve fault plan plumbing
# ---------------------------------------------------------------------------


def test_serve_fault_plan_env_roundtrip(monkeypatch):
    from symbolicregression_jl_tpu.shield import faults

    plan = faults.ServeFaultPlan(
        kill_server_at_request=2, corrupt_journal_record=3,
        cancel_request_at_iteration=(1, 2))
    text = json.dumps({
        "kill_server_at_request": 2, "corrupt_journal_record": 3,
        "cancel_request_at_iteration": [1, 2],
    })
    assert faults.ServeFaultPlan.from_json(text) == plan
    monkeypatch.setenv("SR_SERVE_FAULT_PLAN", text)
    inj = faults.active_serve_injector()
    assert inj is not None and inj.plan == plan


def test_serve_injector_audits_injections_with_request_id():
    """Every injection (incl. those carrying a request_id) must reach
    the telemetry sink — a dropped audit makes the fault trail lie."""
    from symbolicregression_jl_tpu.shield import faults

    class Sink:
        def __init__(self):
            self.events = []

        def serve(self, kind, request_id, **detail):
            self.events.append((kind, request_id, detail))

    sink = Sink()
    inj = faults.ServeFaultInjector(
        faults.ServeFaultPlan(cancel_request_at_iteration=(1, 2)),
        telemetry=sink)
    assert inj.should_cancel(1, 2, "rX")
    assert sink.events == [
        ("injected", "rX",
         {"fault": "cancel_request", "index": 1, "iteration": 2})]


def test_serve_injector_corrupts_exact_journal_record(tmp_path):
    from symbolicregression_jl_tpu.shield import faults

    inj = faults.ServeFaultInjector(
        faults.ServeFaultPlan(corrupt_journal_record=2))
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path, injector=inj)
    j.append("submit", "r1", {})
    j.append("submit", "r2", {})
    j.append("submit", "r3", {})
    records, corrupt = RequestJournal(path).replay()
    assert [r["request_id"] for r in records] == ["r1", "r3"]
    assert len(corrupt) == 1 and corrupt[0]["line"] == 2
    assert inj.injected and inj.injected[0][0] == "corrupt_journal"
