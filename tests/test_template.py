"""Template/Composable expressions: ValidVector algebra, structure
inference, batched template eval, evolution integration, and recovery of
structured laws.

Mirrors the reference's template suite (test/unit/expressions:
test_composable_expression.jl, test_template_macro.jl,
test_template_expression_mutation.jl, test_template_expression_string.jl
and the templates MLJ integration group). Reference behavior:
/root/reference/src/TemplateExpression.jl, ComposableExpression.jl,
TemplateExpressionMacro.jl.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu import Options, equation_search
from symbolicregression_jl_tpu.models import (
    ComposableExpression,
    TemplateExpressionSpec,
    ValidVector,
    make_template_structure,
    template_spec,
)
from symbolicregression_jl_tpu.models.template import (
    TemplateReturnError,
    eval_template_batch,
)
from symbolicregression_jl_tpu.ops.encoding import TreeBatch, encode_population
from symbolicregression_jl_tpu.ops.operators import OperatorSet
from symbolicregression_jl_tpu.ops.tree import parse_expression


@pytest.fixture(scope="module")
def ops():
    return OperatorSet(
        binary_operators=["+", "-", "*", "/"], unary_operators=["cos", "sin"]
    )


# ---------------------------------------------------------------------------
# ValidVector algebra (reference ComposableExpression.jl:263-289, :353-388)
# ---------------------------------------------------------------------------


def test_validvector_arithmetic_and_validity():
    a = ValidVector(jnp.asarray([1.0, 4.0]), jnp.bool_(True))
    b = ValidVector(jnp.asarray([2.0, 0.5]), jnp.bool_(True))
    out = a * b + 1.0
    np.testing.assert_allclose(np.asarray(out.x), [3.0, 3.0])
    assert bool(out.valid)
    # division producing inf invalidates
    z = a / ValidVector(jnp.asarray([0.0, 1.0]), jnp.bool_(True))
    assert not bool(z.valid)
    # invalidity propagates through later ops
    assert not bool((z + 1.0).valid)


def test_validvector_named_fns_safe_domains():
    from symbolicregression_jl_tpu.models.composable import log, sqrt

    ok = log(ValidVector(jnp.asarray([1.0, 2.0]), jnp.bool_(True)))
    assert bool(ok.valid)
    bad = sqrt(ValidVector(jnp.asarray([-1.0, 4.0]), jnp.bool_(True)))
    assert not bool(bad.valid)


# ---------------------------------------------------------------------------
# ComposableExpression host semantics (reference :198-256)
# ---------------------------------------------------------------------------


def test_composable_call_evaluates(ops):
    f = ComposableExpression(
        parse_expression("x1 * x2", ops, variable_names=["x1", "x2"]), ops, 2
    )
    x = np.asarray([1.0, 2.0, 3.0], np.float32)
    out = f(x, 2.0 * x)
    np.testing.assert_allclose(np.asarray(out), 2.0 * x * x, rtol=1e-6)


def test_composable_composition_splices_trees(ops):
    f = ComposableExpression(
        parse_expression("x1 * x2", ops, variable_names=["x1", "x2"]), ops, 2
    )
    g = ComposableExpression(
        parse_expression("cos(x1)", ops, variable_names=["x1"]), ops, 1
    )
    h = f(g, g)  # cos(#1)^2
    assert h.string() == "cos(#1) * cos(#1)"
    val = h(np.float32(0.3))
    assert abs(val - np.cos(0.3) ** 2) < 1e-5


# ---------------------------------------------------------------------------
# Structure building / inference (reference TemplateExpression.jl:213-241,
# TemplateExpressionMacro.jl:34-151)
# ---------------------------------------------------------------------------


def test_template_spec_infers_arities():
    spec = template_spec(expressions=("f", "g"))(
        lambda f, g, x1, x2, x3: f(x1, x2) + g(x3)
    )
    st = spec.structure
    assert st.expr_keys == ("f", "g")
    assert st.num_features == (2, 1)
    assert st.n_variables == 3
    assert not st.has_params


def test_template_spec_with_parameters():
    spec = template_spec(expressions=("f",), parameters={"p": 3})(
        lambda f, x1, p: f(x1) * p[0] + p[1] - p[2]
    )
    st = spec.structure
    assert st.param_keys == ("p",)
    assert st.num_params == (3,)
    assert st.total_params == 3


def test_inconsistent_arity_raises():
    with pytest.raises(ValueError, match="Inconsistent"):
        template_spec(expressions=("f",))(
            lambda f, x1, x2: f(x1) + f(x1, x2)
        )


def test_uncalled_subexpression_raises():
    with pytest.raises(ValueError, match="never called|Failed to infer"):
        template_spec(expressions=("f", "g"))(lambda f, g, x1: f(x1))


def test_make_template_structure_reference_style():
    st = make_template_structure(
        lambda exprs, xs: exprs.f(xs[0], xs[1]) + exprs.g(xs[2]),
        expressions=("f", "g"),
        n_variables=3,
    )
    assert st.num_features == (2, 1)


# ---------------------------------------------------------------------------
# Batched template evaluation (reference :684-711)
# ---------------------------------------------------------------------------


def _encode_template(ops, exprs, L=8):
    encs = encode_population(exprs, L, ops)
    return TreeBatch(
        arity=encs.arity[None], op=encs.op[None], feat=encs.feat[None],
        const=encs.const[None], length=encs.length[None],
    )


def test_eval_template_batch_matches_numpy(ops):
    spec = template_spec(expressions=("f", "g"))(
        lambda f, g, x1, x2, x3: f(x1, x2) + g(x3) * 2.0
    )
    st = spec.structure
    trees = _encode_template(ops, [
        parse_expression("x1 * x2", ops, variable_names=["x1", "x2"]),
        parse_expression("cos(x1)", ops, variable_names=["x1"]),
    ])
    X = np.random.default_rng(0).normal(size=(3, 40)).astype(np.float32)
    y, valid = eval_template_batch(trees, jnp.asarray(X), st, ops)
    assert bool(valid[0])
    np.testing.assert_allclose(
        np.asarray(y[0]), X[0] * X[1] + np.cos(X[2]) * 2.0, rtol=1e-5
    )


def test_eval_template_invalid_propagates(ops):
    # g = 1/#1 on data containing 0 -> invalid member
    spec = template_spec(expressions=("g",))(lambda g, x1: g(x1))
    trees = _encode_template(ops, [
        parse_expression("1.0 / x1", ops, variable_names=["x1"]),
    ])
    X = np.asarray([[0.0, 1.0]], np.float32)
    y, valid = eval_template_batch(trees, jnp.asarray(X), spec.structure, ops)
    assert not bool(valid[0])


def test_combiner_must_return_validvector():
    with pytest.raises(TemplateReturnError):
        template_spec(expressions=("f",))(lambda f, x1: np.float32(1.0))


def test_template_nested_composition_eval(ops):
    # combiner may feed one subexpression's output into another
    # (reference :94-98: `f(x1 + g(x2)) - g(x1)` style reuse)
    spec = template_spec(expressions=("f", "g"))(
        lambda f, g, x1, x2: f(g(x1), x2) + g(x2)
    )
    trees = _encode_template(ops, [
        parse_expression("x1 + x2", ops, variable_names=["x1", "x2"]),
        parse_expression("sin(x1)", ops, variable_names=["x1"]),
    ])
    X = np.random.default_rng(1).normal(size=(2, 30)).astype(np.float32)
    y, valid = eval_template_batch(trees, jnp.asarray(X), spec.structure, ops)
    expect = (np.sin(X[0]) + X[1]) + np.sin(X[1])
    assert bool(valid[0])
    np.testing.assert_allclose(np.asarray(y[0]), expect, rtol=1e-5)


# ---------------------------------------------------------------------------
# Search integration
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_template_search_recovers_structured_law():
    spec = template_spec(expressions=("f", "g"))(
        lambda f, g, x1, x2, x3: f(x1, x2) + g(x3)
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (300, 3)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 2.0 * np.cos(X[:, 2])).astype(np.float32)
    options = Options(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=14,
        populations=6,
        population_size=24,
        ncycles_per_iteration=12,
        optimizer_probability=0.2,
        expression_spec=spec,
        save_to_file=False,
    )
    hof = equation_search(X, y, options=options, niterations=12, seed=2,
                          verbosity=0)
    best = min(e.loss for e in hof.entries)
    assert best < 0.1, f"template search did not converge (loss={best})"
    # every decoded entry respects per-key feature arities
    for e in hof.entries:
        st = e.template_expr.structure
        for k, key in enumerate(st.expr_keys):
            tree = e.template_expr.trees[key]
            feats = [
                n.feature for n in tree.nodes()
                if n.degree == 0 and not n.constant and not n.is_parameter
            ]
            assert all(f < st.num_features[k] for f in feats)


@pytest.mark.slow
def test_template_search_with_parameters_recovers_exact():
    spec = template_spec(expressions=("f",), parameters={"p": 2})(
        lambda f, x1, x2, p: f(x1) + p[0] * x2 + p[1]
    )
    rng = np.random.default_rng(1)
    X = rng.uniform(-2, 2, (200, 2)).astype(np.float32)
    y = (X[:, 0] ** 2 + 3.0 * X[:, 1] - 0.5).astype(np.float32)
    options = Options(
        binary_operators=["+", "-", "*"],
        unary_operators=[],
        maxsize=8,
        populations=4,
        population_size=20,
        ncycles_per_iteration=8,
        optimizer_probability=0.3,
        expression_spec=spec,
        save_to_file=False,
    )
    hof = equation_search(X, y, options=options, niterations=8, seed=0,
                          verbosity=0)
    best = min(hof.entries, key=lambda e: e.loss)
    assert best.loss < 1e-6
    # fitted parameters should be ~[3, -0.5]
    params = best.template_expr.params
    assert params is not None
    np.testing.assert_allclose(sorted(params), [-0.5, 3.0], atol=1e-2)
    # host prediction matches data
    pred = best.template_expr(X)
    np.testing.assert_allclose(pred, y, atol=1e-2)


def test_template_hof_string_and_spec_validation(ops):
    spec = template_spec(expressions=("f",))(lambda f, x1: f(x1))
    with pytest.raises(ValueError, match="variables"):
        X = np.zeros((10, 3), np.float32)
        equation_search(
            X, np.zeros(10, np.float32),
            options=Options(expression_spec=spec, save_to_file=False,
                            populations=2, population_size=8,
                            tournament_selection_n=4,
                            ncycles_per_iteration=2),
            niterations=1, verbosity=0,
        )
    with pytest.raises(ValueError, match="TemplateStructure"):
        TemplateExpressionSpec(structure="not a structure")


def test_parse_template_expression_roundtrip(ops):
    from symbolicregression_jl_tpu.models.template import (
        HostTemplateExpression,
        parse_template_expression,
    )

    spec = template_spec(expressions=("f", "g"), parameters={"p": 2})(
        lambda f, g, x1, x2, x3, p: f(x1, x2) + g(x3) * p[0] + p[1]
    )
    st = spec.structure
    s = "f = #1 * #2 + 0.5; g = cos(#1); p = [2, -1.5]"
    h = parse_template_expression(s, st, ops)
    assert isinstance(h, HostTemplateExpression)
    np.testing.assert_allclose(h.params, [2.0, -1.5])
    # round trip through string()
    h2 = parse_template_expression(h.string(), st, ops)
    assert h2.string() == h.string()
    # evaluation matches the structure semantics
    X = np.random.default_rng(0).normal(size=(20, 3)).astype(np.float32)
    pred = h(X)
    expect = (X[:, 0] * X[:, 1] + 0.5) + np.cos(X[:, 2]) * 2.0 - 1.5
    np.testing.assert_allclose(pred, expect, rtol=1e-5)


@pytest.mark.slow
def test_template_guess_seeding_injects_solution():
    spec = template_spec(expressions=("f", "g"))(
        lambda f, g, x1, x2, x3: f(x1, x2) + g(x3)
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (200, 3)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 2.0 * np.cos(X[:, 2])).astype(np.float32)
    options = Options(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=14,
        populations=2,
        population_size=12,
        tournament_selection_n=4,
        ncycles_per_iteration=2,
        expression_spec=spec,
        save_to_file=False,
    )
    # The exact law as a guess: one iteration must lock onto it.
    hof = equation_search(
        X, y, options=options, niterations=1, seed=0, verbosity=0,
        guesses=["f = #1 * #2; g = cos(#1) + cos(#1)"],
    )
    best = min(e.loss for e in hof.entries)
    assert best < 1e-10, f"seeded exact law lost (loss={best})"


def test_parse_template_params_omitted_or_partial(ops):
    from symbolicregression_jl_tpu.models.template import (
        parse_template_expression,
    )

    spec = template_spec(expressions=("f",), parameters={"p": 2, "q": 1})(
        lambda f, x1, p, q: f(x1) * p[0] + p[1] + q[0]
    )
    st = spec.structure
    # no parameter components at all -> params stays unset (randn seeding)
    h = parse_template_expression("f = #1 + 1", st, ops)
    assert h.params is None
    # partial parameter components -> explicit error
    with pytest.raises(ValueError, match="missing parameter"):
        parse_template_expression("f = #1; p = [1, 2]", st, ops)


@pytest.mark.slow
def test_template_dict_guess_with_params_and_validation():
    spec = template_spec(expressions=("f",), parameters={"p": 1})(
        lambda f, x1, x2, p: f(x1) + p[0] * x2
    )
    rng = np.random.default_rng(1)
    X = rng.uniform(-2, 2, (100, 2)).astype(np.float32)
    y = (X[:, 0] ** 2 + 3.0 * X[:, 1]).astype(np.float32)
    options = Options(
        binary_operators=["+", "-", "*"], unary_operators=[],
        maxsize=8, populations=2, population_size=10,
        tournament_selection_n=4, ncycles_per_iteration=2,
        expression_spec=spec, save_to_file=False,
    )
    hof = equation_search(
        X, y, options=options, niterations=1, seed=0, verbosity=0,
        guesses=[{"f": "#1 * #1", "p": [3.0]}],
    )
    assert min(e.loss for e in hof.entries) < 1e-8
    with pytest.raises(ValueError, match="missing subexpressions"):
        equation_search(
            X, y, options=options, niterations=1, verbosity=0,
            guesses=[{"p": [3.0]}],
        )


def test_eval_template_batch_fused_matches_unfused(ops):
    """The fused (Pallas) batched evaluator and the vmapped interpreter
    path must agree, including validity."""
    spec = template_spec(expressions=("f", "g"), parameters={"p": 1})(
        lambda f, g, x1, x2, x3, p: f(x1, x2) + g(x3) * p[0]
    )
    st = spec.structure
    exprs = [
        parse_expression("x1 * x2 + 0.5", ops, variable_names=["x1", "x2"]),
        parse_expression("cos(x1)", ops, variable_names=["x1"]),
        parse_expression("x1 - x2", ops, variable_names=["x1", "x2"]),
        parse_expression("1.0 / x1", ops, variable_names=["x1"]),  # invalid on 0
    ]
    enc = encode_population(exprs, 8, ops)
    trees = TreeBatch(  # 2 members: [2, K=2, L]
        arity=enc.arity.reshape(2, 2, -1), op=enc.op.reshape(2, 2, -1),
        feat=enc.feat.reshape(2, 2, -1), const=enc.const.reshape(2, 2, -1),
        length=enc.length.reshape(2, 2),
    )
    X = np.concatenate([
        np.zeros((3, 1), np.float32),  # row with x=0 -> 1/x1 invalid
        np.random.default_rng(0).normal(size=(3, 30)).astype(np.float32),
    ], axis=1)
    params = jnp.asarray([[2.0], [3.0]], jnp.float32)
    y1, v1 = eval_template_batch(trees, jnp.asarray(X), st, ops, params,
                                 fused=False)
    y2, v2 = eval_template_batch(trees, jnp.asarray(X), st, ops, params,
                                 fused=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(v1), np.asarray(v2))
    m = np.asarray(v1)
    np.testing.assert_allclose(
        np.asarray(y1)[m], np.asarray(y2)[m], rtol=1e-5
    )
    assert bool(v1[0]) and not bool(v1[1])


@pytest.mark.slow
def test_template_search_fused_path_runs():
    """Force turbo on CPU (interpret kernels) through a short template
    search to cover the fused engine path end-to-end."""
    spec = template_spec(expressions=("f",))(lambda f, x1, x2: f(x1, x2))
    rng = np.random.default_rng(2)
    X = rng.uniform(-2, 2, (80, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1]).astype(np.float32)
    options = Options(
        binary_operators=["+", "*"], unary_operators=[],
        maxsize=8, populations=2, population_size=10,
        tournament_selection_n=4, ncycles_per_iteration=2,
        expression_spec=spec, save_to_file=False, turbo=True,
    )
    hof = equation_search(X, y, options=options, niterations=2, seed=0,
                          verbosity=0)
    assert np.isfinite(min(e.loss for e in hof.entries))


def test_batched_param_as_subexpression_argument(ops):
    """p[i] may be passed INTO a subexpression (reference combiners do
    this); the batched evaluator must broadcast the [M, 1] column."""
    spec = template_spec(expressions=("f",), parameters={"p": 1})(
        lambda f, x1, p: f(x1, p[0])
    )
    st = spec.structure
    enc = encode_population(
        [parse_expression("x1 * x2", ops, variable_names=["x1", "x2"])], 8, ops
    )
    trees = TreeBatch(
        arity=enc.arity[None], op=enc.op[None], feat=enc.feat[None],
        const=enc.const[None], length=enc.length[None],
    )
    X = np.random.default_rng(0).normal(size=(1, 25)).astype(np.float32)
    params = jnp.asarray([[3.0]], jnp.float32)
    for fused in (False, True):
        y, valid = eval_template_batch(
            trees, jnp.asarray(X), st, ops, params,
            fused=fused, interpret=fused,
        )
        assert bool(valid[0])
        np.testing.assert_allclose(np.asarray(y[0]), X[0] * 3.0, rtol=1e-5)


def test_batched_param_member_dependent_gather(ops):
    """p[idx] with a subexpression-produced index gathers per member."""
    spec = template_spec(expressions=("f",), parameters={"p": 2})(
        lambda f, x1, p: p[f(x1)]
    )
    st = spec.structure
    enc = encode_population(
        [parse_expression("x1", ops, variable_names=["x1"]),
         parse_expression("x1 + 1.0", ops, variable_names=["x1"])], 8, ops
    )
    trees = TreeBatch(  # member 0: idx = x1; member 1: idx = x1 + 1
        arity=enc.arity[:, None], op=enc.op[:, None], feat=enc.feat[:, None],
        const=enc.const[:, None], length=enc.length[:, None],
    )
    X = np.asarray([[0.0, 1.0, 0.0, 1.0]], np.float32)
    params = jnp.asarray([[10.0, 20.0], [30.0, 40.0]], jnp.float32)
    y, valid = eval_template_batch(trees, jnp.asarray(X), st, ops, params)
    np.testing.assert_allclose(np.asarray(y[0]), [10.0, 20.0, 10.0, 20.0])
    np.testing.assert_allclose(np.asarray(y[1]), [40.0, 40.0, 40.0, 40.0])


def test_batched_param_iteration_terminates(ops):
    """`for v in p` must iterate len(p) elements (legacy sequence
    iteration over a bounds-checked __getitem__ would loop forever
    without __iter__)."""
    spec = template_spec(expressions=("f",), parameters={"p": 3})(
        lambda f, x1, p: f(x1) + sum(v for v in p)
    )
    st = spec.structure
    enc = encode_population(
        [parse_expression("x1", ops, variable_names=["x1"])], 8, ops
    )
    trees = TreeBatch(
        arity=enc.arity[None], op=enc.op[None], feat=enc.feat[None],
        const=enc.const[None], length=enc.length[None],
    )
    X = np.ones((1, 5), np.float32)
    params = jnp.asarray([[1.0, 2.0, 3.0]], jnp.float32)
    y, valid = eval_template_batch(trees, jnp.asarray(X), st, ops, params)
    np.testing.assert_allclose(np.asarray(y[0]), np.full(5, 7.0), rtol=1e-6)


def test_template_latex_export(ops):
    from symbolicregression_jl_tpu.models.template import (
        parse_template_expression,
    )
    from symbolicregression_jl_tpu.utils.export import template_to_latex

    spec = template_spec(expressions=("f", "g"), parameters={"p": 2})(
        lambda f, g, x1, x2, x3, p: f(x1, x2) + g(x3) * p[0] + p[1]
    )
    h = parse_template_expression(
        "f = #1 * #2; g = cos(#1); p = [2, -1.5]", spec.structure, ops
    )
    tex = template_to_latex(h)
    assert tex.startswith("\\begin{aligned}")
    assert "f &=" in tex and "g &=" in tex and "p &= [2, -1.5]" in tex
    assert "\\cos" in tex


@pytest.mark.slow
def test_fused_template_gradients_match_interpreter(ops):
    """Gradient parity of fused_predict_ad's hand-written VJP kernel vs
    jax.grad through the interpreter path — the load-bearing piece of the
    fused template constant optimizer. Covers plain call sites, nested
    composition (jnp fallback), and parameter columns."""
    import dataclasses

    spec = template_spec(expressions=("f", "g"), parameters={"p": 1})(
        lambda f, g, x1, x2, p: f(x1, x2) * p[0] + g(f(x1, x2), x1)
    )
    st = spec.structure
    enc = encode_population([
        parse_expression("1.5 * x1 + cos(x2 * 0.7)", ops,
                         variable_names=["x1", "x2"]),
        parse_expression("x1 * 0.3 - x2", ops, variable_names=["x1", "x2"]),
    ], 10, ops)
    trees = TreeBatch(
        arity=enc.arity[None], op=enc.op[None], feat=enc.feat[None],
        const=enc.const[None], length=enc.length[None],
    )
    X = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 48)).astype(np.float32)
    )
    params = jnp.asarray([[1.3]], jnp.float32)

    def loss(const, p, fused):
        t = dataclasses.replace(trees, const=const)
        pred, valid = eval_template_batch(
            t, X, st, ops, p, fused=fused, interpret=fused
        )
        return jnp.sum(pred ** 2)

    gc_f, gp_f = jax.grad(lambda c, p: loss(c, p, True), argnums=(0, 1))(
        trees.const, params
    )
    gc_r, gp_r = jax.grad(lambda c, p: loss(c, p, False), argnums=(0, 1))(
        trees.const, params
    )
    np.testing.assert_allclose(np.asarray(gc_f), np.asarray(gc_r),
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gp_f), np.asarray(gp_r),
                               rtol=1e-3, atol=1e-4)
    # gradients are nonzero (the test would pass trivially otherwise)
    assert float(jnp.max(jnp.abs(gc_r))) > 1e-3


def test_validvector_remaining_dunders():
    """Right-operand and unary dunders (reference overloads ~80 Base
    operators; these are the Python-dunder subset)."""
    a = ValidVector(jnp.asarray([1.0, 2.0]), jnp.bool_(True))
    np.testing.assert_allclose(np.asarray((3.0 - a).x), [2.0, 1.0])
    np.testing.assert_allclose(np.asarray((2.0 / a).x), [2.0, 1.0])
    np.testing.assert_allclose(np.asarray((a ** 2).x), [1.0, 4.0])
    np.testing.assert_allclose(np.asarray((2.0 ** a).x), [2.0, 4.0])
    np.testing.assert_allclose(np.asarray((-a).x), [-1.0, -2.0])
    np.testing.assert_allclose(np.asarray(abs(-a).x), [1.0, 2.0])
    np.testing.assert_allclose(np.asarray((a % 2.0).x), [1.0, 0.0])
    # safe_pow: negative base with fractional exponent invalidates
    neg = ValidVector(jnp.asarray([-2.0, 1.0]), jnp.bool_(True))
    assert not bool((neg ** 0.5).valid)


# ---------------------------------------------------------------------------
# D: derivatives of subexpressions inside combiners
# (reference exports DynamicDiff.D for templates, src/SymbolicRegression.jl:172)
# ---------------------------------------------------------------------------


def test_D_marks_structure_and_infers(ops):
    from symbolicregression_jl_tpu.models.template import D

    st = make_template_structure(
        lambda exprs, xs: -D(exprs.V, 1)(xs[0]),
        expressions=("V",),
    )
    assert st.uses_deriv
    assert st.num_features == (1,)
    # explicit num_features path detects D via the secondary probe
    st2 = make_template_structure(
        lambda exprs, xs: -D(exprs.V, 1)(xs[0]),
        expressions=("V",), num_features={"V": 1}, n_variables=1,
    )
    assert st2.uses_deriv
    st3 = make_template_structure(
        lambda exprs, xs: exprs.V(xs[0]),
        expressions=("V",),
    )
    assert not st3.uses_deriv


@pytest.mark.parametrize("fused", [False, True])
def test_D_derivative_matches_analytic(ops, fused):
    from symbolicregression_jl_tpu.models.template import D

    # V(u) = u*u + cos(u);  D(V,1)(x) = 2x - sin(x)
    spec = template_spec(expressions=("V",))(
        lambda V, x1: D(V, 1)(x1)
    )
    trees = _encode_template(ops, [
        parse_expression("x1 * x1 + cos(x1)", ops, variable_names=["x1"]),
    ])
    X = np.random.default_rng(2).normal(size=(1, 50)).astype(np.float32)
    y, valid = eval_template_batch(
        trees, jnp.asarray(X), spec.structure, ops,
        fused=fused, interpret=True,
    )
    assert bool(valid[0])
    np.testing.assert_allclose(
        np.asarray(y[0]), 2 * X[0] - np.sin(X[0]), rtol=2e-4, atol=2e-4
    )


def test_D_gradient_flows_to_constants(ops):
    """d/dc of D(V,1)(x) with V = c*x*x is 2x — constant optimization
    through a D structure needs this (jvp-composable interpreter path)."""
    from symbolicregression_jl_tpu.models.template import D

    spec = template_spec(expressions=("V",))(lambda V, x1: D(V, 1)(x1))
    trees = _encode_template(ops, [
        parse_expression("1.5 * (x1 * x1)", ops, variable_names=["x1"]),
    ])
    X = np.random.default_rng(3).normal(size=(1, 16)).astype(np.float32)
    Xj = jnp.asarray(X)

    def loss(const):
        tr = TreeBatch(trees.arity, trees.op, trees.feat, const,
                       trees.length)
        y, _ = eval_template_batch(tr, Xj, spec.structure, ops, fused=False)
        return jnp.sum(y)

    g = jax.grad(loss)(trees.const)
    # d/dc sum(2*c*x) = sum(2x) at the const slot
    expected = float(2 * X[0].sum())
    assert np.isclose(float(np.asarray(g).sum()), expected, rtol=1e-4)


def test_D_host_composable_symbolic(ops):
    ce = ComposableExpression(
        parse_expression("#1 * #1 + cos(#1)", ops, variable_names=["#1"]),
        ops, 1,
    )
    d = ce.derivative(1)
    x = np.linspace(-2, 2, 21).astype(np.float32)
    out = d(ValidVector(jnp.asarray(x), jnp.bool_(True)))
    np.testing.assert_allclose(
        np.asarray(out.x), 2 * x - np.sin(x), rtol=1e-5, atol=1e-5
    )


@pytest.mark.slow
def test_template_search_recovers_force_law():
    """Physics idiom: fit force = -D(V, 1)(x) and recover the potential's
    derivative matching y = -3x (V ~ 1.5 x^2 + const)."""
    from symbolicregression_jl_tpu.models.template import D

    spec = template_spec(expressions=("V",))(
        lambda V, x1: -D(V, 1)(x1)
    )
    rng = np.random.default_rng(0)
    X = rng.uniform(-2, 2, (200, 1)).astype(np.float32)
    y = (-3.0 * X[:, 0]).astype(np.float32)
    opts = Options(
        binary_operators=["+", "-", "*"], unary_operators=[],
        maxsize=8, populations=4, population_size=20,
        ncycles_per_iteration=30, expression_spec=spec,
        save_to_file=False, progress=False, verbosity=0,
    )
    hof = equation_search(X, y, options=opts, niterations=6, seed=0)
    best = min(hof.pareto_frontier(), key=lambda m: m.loss)
    assert float(best.loss) < 1e-2
