"""graftmesh correctness: the shard_map mesh runtime vs the legacy engine.

Determinism tiers (docs/SCALING.md):

- 1-shard MeshEngine == legacy Engine, BIT-identical (same draws, no
  collectives in play — the mesh runtime may never change an unsharded
  search).
- At a FIXED sharded layout, per-shard finalize-dedup on/off is
  BIT-identical (duplicates copy their group leader's result).
- On the turbo path, the mesh runtime's explicit collectives ==
  GSPMD's inferred collectives at the same layout, BIT-identical.
- Across DIFFERENT layouts the jnp-interpreter path is only
  quality-equivalent (XLA fuses the per-shard programs differently —
  the same ~1 ULP caveat test_multichip_equiv documents); the turbo
  path is pinned bit-exact by tests/test_sharded_turbo.py.
- Kill-then-resume under the mesh runtime is bit-identical to an
  uninterrupted run (the graftshield contract extends to the mesh).
"""

import dataclasses
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu import Options, search_key
from symbolicregression_jl_tpu.core.dataset import make_dataset
from symbolicregression_jl_tpu.evolve.engine import Engine
from symbolicregression_jl_tpu.mesh import MeshEngine, MeshPlan
from symbolicregression_jl_tpu.parallel.mesh import (
    DATA_AXIS,
    ISLAND_AXIS,
    make_mesh,
    shard_search_state,
)


def _problem(rows=48):
    rng = np.random.default_rng(7)
    X = rng.uniform(-2, 2, (rows, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 0]).astype(np.float32)
    ds = make_dataset(X, y)
    return ds


def _options(**kw):
    base = dict(
        binary_operators=["+", "-", "*"],
        unary_operators=["cos"],
        maxsize=8,
        populations=4,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        fraction_replaced=0.3,
        save_to_file=False,
    )
    base.update(kw)
    return Options(**base)


def _run_mesh(options, ds, n_shards, n_iters=2, sharded_dedup=True):
    plan = MeshPlan.build(
        jax.devices()[:n_shards], n_island_shards=n_shards,
        sharded_dedup=sharded_dedup,
    )
    engine = MeshEngine(options, ds.nfeatures, plan)
    data = plan.place_data(ds.data)
    state = engine.init_state(search_key(11), data, options.populations)
    state = plan.place_state(state)
    for _ in range(n_iters):
        state = engine.run_iteration(state, data, options.maxsize)
    return jax.device_get(state), engine


def _run_legacy(options, ds, n_shards=1, n_iters=2):
    mesh = (make_mesh(jax.devices()[:n_shards], n_island_shards=n_shards)
            if n_shards > 1 else None)
    engine = Engine(options, ds.nfeatures, n_island_shards=n_shards,
                    mesh=mesh)
    state = engine.init_state(search_key(11), ds.data, options.populations)
    if mesh is not None:
        state = shard_search_state(state, mesh)
    for _ in range(n_iters):
        state = engine.run_iteration(state, ds.data, options.maxsize)
    return jax.device_get(state)


def _assert_states_bit_identical(a, b):
    fa = jax.tree_util.tree_flatten_with_path(
        (a.pops, a.hof, a.birth, a.ref, a.stats, a.num_evals))[0]
    fb = jax.tree.leaves(
        (b.pops, b.hof, b.birth, b.ref, b.stats, b.num_evals))
    assert len(fa) == len(fb)
    for (path, xa), xb in zip(fa, fb):
        np.testing.assert_array_equal(
            np.asarray(xa), np.asarray(xb),
            err_msg=f"leaf {jax.tree_util.keystr(path)} diverged")


# ---------------------------------------------------------------------------
# MeshPlan (host-side, instant)
# ---------------------------------------------------------------------------


def test_mesh_plan_specs_and_placement():
    from jax.sharding import PartitionSpec as P

    ds = _problem()
    options = _options()
    plan = MeshPlan.build(jax.devices()[:2], n_island_shards=2)
    assert plan.describe()["axes"] == {ISLAND_AXIS: 2, DATA_AXIS: 1}

    engine = MeshEngine(options, ds.nfeatures, plan)
    state = engine.init_state(search_key(0), ds.data, options.populations)
    specs = plan.state_specs(state)
    assert specs.birth == P(ISLAND_AXIS)
    assert specs.num_evals == P()
    assert all(s == P(ISLAND_AXIS) for s in jax.tree.leaves(specs.pops))
    assert all(s == P() for s in jax.tree.leaves(specs.hof))

    placed = plan.place_state(state)
    shardings = {
        str(x.sharding.spec) for x in jax.tree.leaves(placed.pops)
    }
    assert shardings == {str(P(ISLAND_AXIS))}
    # data replicated on a 1-data-shard mesh
    dplaced = plan.place_data(ds.data)
    assert str(dplaced.Xt.sharding.spec) == str(P())
    # exchange-volume estimate is nonzero under >1 shard
    vol = plan.exchange_bytes(state)
    assert vol["pops_bytes"] > 0 and vol["best_seen_bytes"] > 0


def test_mesh_engine_rejects_data_sharding():
    ds = _problem()
    plan = MeshPlan.build(jax.devices()[:2], n_island_shards=1,
                          n_data_shards=2)
    with pytest.raises(NotImplementedError):
        MeshEngine(_options(), ds.nfeatures, plan)


# ---------------------------------------------------------------------------
# 1-shard mesh == legacy engine, bit-identical
# ---------------------------------------------------------------------------


def test_mesh_1shard_bit_identical_to_legacy_engine():
    ds = _problem()
    options = _options()
    base = _run_legacy(options, ds, n_shards=1)
    meshed, _ = _run_mesh(options, ds, n_shards=1)
    _assert_states_bit_identical(base, meshed)


# ---------------------------------------------------------------------------
# Sharded finalize-dedup: enabled, and exactly result-neutral
# ---------------------------------------------------------------------------


def test_mesh_sharded_dedup_enabled_bit_neutral_and_exchange():
    """The mesh runtime keeps finalize-dedup ON under a 2-shard island
    mesh (no use_dedup=False forcing), dedup on/off is bit-identical —
    per-shard dedup is a pure perf toggle — and the cross-shard
    dedup-key exchange holds its invariants (one test so the two
    2-shard turbo engines are built once; tier-1 budget)."""
    ds = _problem()
    options = _options(turbo=True)
    on, eng_on = _run_mesh(options, ds, 2, n_iters=2, sharded_dedup=True)
    off, eng_off = _run_mesh(options, ds, 2, n_iters=2,
                             sharded_dedup=False)
    assert eng_on._use_dedup(sharded=True), (
        "mesh runtime must keep dedup enabled under sharding")
    assert not eng_off._use_dedup(sharded=True)
    # the legacy engine forfeits it at the same layout
    legacy = Engine(options, ds.nfeatures, n_island_shards=2,
                    mesh=make_mesh(jax.devices()[:2], n_island_shards=2))
    assert not legacy._use_dedup(sharded=True)
    _assert_states_bit_identical(on, off)

    # ---- exchange invariants on the evolved (on-mesh) state ----
    dev_state = eng_on.plan.place_state(on)
    ex = eng_on.dedup_exchange(dev_state)
    P = options.population_size
    assert ex["rows"] == options.populations * P
    assert 1 <= ex["global_unique"] <= ex["shard_unique"] <= ex["rows"]
    assert ex["cross_shard_dup"] == ex["shard_unique"] - ex["global_unique"]
    assert ex["exchanged_bytes"] == 3 * 4 * ex["rows"]  # S=2: (S-1)=1
    assert len(ex["per_shard_unique"]) == 2
    assert ex["shard_imbalance"] >= 1.0


# ---------------------------------------------------------------------------
# Explicit collectives == GSPMD-inferred collectives (same layout)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_mesh_turbo_2shard_bit_identical_to_legacy_sharded():
    """At the SAME 2-shard layout on the fused path, the mesh runtime's
    explicit all-gather/psum epilogue must reproduce the legacy GSPMD
    epilogue bit-for-bit (dedup off for an exact apples-to-apples: the
    legacy path forfeits it under sharding)."""
    ds = _problem(rows=64)
    options = _options(turbo=True)
    legacy = _run_legacy(options, ds, n_shards=2)
    meshed, _ = _run_mesh(options, ds, 2, sharded_dedup=False)
    _assert_states_bit_identical(legacy, meshed)


@pytest.mark.slow
def test_mesh_2shard_quality_matches_unsharded_jnp():
    """Across layouts the jnp path is quality-equivalent (not bitwise —
    XLA fuses per-shard programs differently): the sharded mesh HoF
    must reach the unsharded HoF's quality on the same problem."""
    ds = _problem(rows=64)
    options = _options(populations=8, ncycles_per_iteration=4)
    base = _run_legacy(options, ds, n_shards=1, n_iters=3)
    meshed, _ = _run_mesh(options, ds, 4, n_iters=3)
    def best(s):
        cost = np.asarray(s.hof.cost)[np.asarray(s.hof.exists)]
        return float(cost.min()) if cost.size else np.inf
    assert np.isfinite(best(meshed))
    assert best(meshed) <= best(base) * 1.5 + 1e-6
    assert float(meshed.num_evals) == float(base.num_evals)


# ---------------------------------------------------------------------------
# AOT executables
# ---------------------------------------------------------------------------


def test_mesh_aot_compile_and_roundtrip(tmp_path):
    from symbolicregression_jl_tpu.mesh.aot import (
        aot_serialization_supported,
        compile_iteration,
        load_executable,
        save_executable,
    )

    ds = _problem()
    options = _options()
    plan = MeshPlan.build(jax.devices()[:1], n_island_shards=1)
    engine = MeshEngine(options, ds.nfeatures, plan)

    def fresh_state():
        s = engine.init_state(search_key(11), ds.data,
                              options.populations)
        return plan.place_state(s)

    # the jit path's result is the reference
    ref = jax.device_get(engine.run_iteration(
        fresh_state(), ds.data, options.maxsize))
    ex = compile_iteration(engine, fresh_state(), ds.data)
    got = jax.device_get(ex.run(fresh_state(), ds.data,
                                jnp.int32(options.maxsize)))
    _assert_states_bit_identical(ref, got)

    if not aot_serialization_supported():
        pytest.skip("jax build cannot serialize executables")
    from jax.lib import xla_client

    try:
        path = save_executable(ex, os.fspath(tmp_path / "iter.aotx"))
        ex2 = load_executable(path, expect_key=ex.cache_key)
    except xla_client.XlaRuntimeError as e:  # pragma: no cover
        # some backends/sessions refuse (de)serializing particular
        # executables (e.g. ones loaded from the persistent compile
        # cache); the dryrun's mesh-aot leg pins the round-trip in a
        # clean process either way
        pytest.skip(f"backend refused executable serialization: {e}")
    got2 = jax.device_get(ex2.run(fresh_state(), ds.data,
                                  jnp.int32(options.maxsize)))
    _assert_states_bit_identical(ref, got2)
    with pytest.raises(ValueError):
        load_executable(path, expect_key="deadbeef")


# ---------------------------------------------------------------------------
# Kill-then-resume under the mesh runtime (graftshield contract)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_mesh_kill_then_resume_bit_identical(tmp_path):
    """A mesh-runtime search stopped at an iteration boundary and
    resumed with resume='auto' must finish bit-identical to an
    uninterrupted run — the shield checkpoint round-trips the
    mesh-sharded state (device_get of addressable shards on save,
    plan re-placement on resume)."""
    from symbolicregression_jl_tpu.api.search import (
        RuntimeOptions,
        equation_search,
    )

    rng = np.random.default_rng(3)
    X = rng.uniform(-2, 2, (48, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 0]).astype(np.float32)

    def opts(root):
        return _options(
            output_directory=os.fspath(root), checkpoint_keep=3,
        )

    def ro(**kw):
        return RuntimeOptions(
            niterations=4, mesh_runtime=True, checkpoint_every_n=1,
            devices=jax.devices()[:2], **kw,
        )

    # uninterrupted reference
    ref_root = tmp_path / "ref"
    _, ref_hof = equation_search(
        X, y, options=opts(ref_root), runtime_options=ro(),
        return_state=True, verbosity=0, run_id="meshrun", seed=5)

    # interrupted at iteration 2 (boundary stop), then resumed to 4
    kill_root = tmp_path / "kill"
    calls = {"n": 0}

    def stop_after_2():
        calls["n"] += 1
        return "preempted" if calls["n"] >= 2 else None

    equation_search(
        X, y, options=opts(kill_root),
        runtime_options=ro(stop_hook=stop_after_2),
        verbosity=0, run_id="meshrun", seed=5)
    res_state, res_hof = equation_search(
        X, y, options=opts(kill_root), runtime_options=ro(),
        resume="auto", return_state=True, verbosity=0,
        run_id="meshrun", seed=5)

    assert res_state.iterations_done == 4
    ref_entries = [(e.complexity, e.loss, e.cost, str(e.tree))
                   for e in ref_hof.entries]
    res_entries = [(e.complexity, e.loss, e.cost, str(e.tree))
                   for e in res_hof.entries]
    assert ref_entries == res_entries


# ---------------------------------------------------------------------------
# Trend surfacing of the measured scaling curve
# ---------------------------------------------------------------------------


def test_trend_folds_mesh_scaling_artifact(tmp_path):
    import json

    from symbolicregression_jl_tpu.bench.trend import (
        build_trend,
        format_trend,
    )

    prof = tmp_path / "profiling"
    prof.mkdir()
    good = {
        "schema": "graftmesh.scaling.v1", "matrix": "mini",
        "virtual_cpu_mesh": True,
        "points": [
            {"shards": 1, "evals_per_sec": 100.0,
             "evals_per_sec_per_shard": 100.0},
            {"shards": 2, "evals_per_sec": 90.0,
             "evals_per_sec_per_shard": 45.0},
        ],
    }
    (prof / "MESH_SCALING.json").write_text(json.dumps(good))
    trend = build_trend(os.fspath(tmp_path))
    assert len(trend["mesh_scaling"]) == 1
    row = trend["mesh_scaling"][0]
    assert not row["red"] and len(row["points"]) == 2
    text = format_trend(trend)
    assert "measured mesh scaling" in text
    assert "virtual CPU mesh" in text

    # a failed point goes RED, never silently dropped
    bad = dict(good)
    bad["points"] = [good["points"][0], {"shards": 8, "error": "boom"}]
    (prof / "MESH_SCALING_full.json").write_text(json.dumps(bad))
    trend = build_trend(os.fspath(tmp_path))
    reds = [r for r in trend["mesh_scaling"] if r["red"]]
    assert len(reds) == 1 and "shards=8" in reds[0]["note"]
    assert trend["red_count"] >= 1
