"""Property-style tests for lint.runtime.validate_programs: thousands of
randomly generated, mutated and crossed-over programs must satisfy every
postfix-table invariant (the machinery-correctness property the ISSUE
pins), and hand-corrupted tables must each be caught with a specific
diagnosis.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from symbolicregression_jl_tpu.evolve.mutation import (
    MutationContext,
    add_node,
    branch_nu,
    crossover_trees,
    delete_node,
    gen_random_tree_fixed_size,
    mutate_constant,
    mutate_operator,
    rotate_tree,
    swap_operands,
)
from symbolicregression_jl_tpu.lint.runtime import (
    ProgramInvariantError,
    check_programs,
    validate_programs,
)
from symbolicregression_jl_tpu.ops.encoding import TreeBatch, postfix_valid
from symbolicregression_jl_tpu.ops.operators import OperatorSet


@pytest.fixture(scope="module")
def ops():
    return OperatorSet(
        binary_operators=["+", "-", "*", "/"], unary_operators=["cos", "exp"]
    )


def _ctx(ops, L):
    return MutationContext(
        nops=ops.nops_tuple(),
        nfeatures=3,
        max_nodes=L,
        perturbation_factor=0.076,
        probability_negate_constant=0.01,
    )


def _random_population(key, n, ctx, min_size=1):
    """[n] batch of random trees of assorted sizes (vmapped generator)."""
    k_size, k_gen = jax.random.split(key)
    sizes = jax.random.randint(k_size, (n,), min_size, ctx.max_nodes)
    keys = jax.random.split(k_gen, n)
    return jax.vmap(
        lambda k, s: gen_random_tree_fixed_size(k, s, ctx, jnp.float32)
    )(keys, sizes)


def _mutate_population(key, trees, ctx):
    """One round of every structural mutation, each applied to the whole
    population. Kernels return (tree, ok); per their contract an
    ``ok=False`` attempt's output is garbage and the generation step
    discards it — mirror that by selecting the original tree there."""
    budgets = branch_nu(ctx)
    out = {}
    k = key
    for name, fn in (
        ("mutate_constant",
         lambda u, t: mutate_constant(u, t, jnp.float32(1.0), ctx)),
        ("mutate_operator", lambda u, t: mutate_operator(u, t, ctx)),
        ("swap_operands", lambda u, t: swap_operands(u, t, ctx)),
        ("rotate_tree", lambda u, t: rotate_tree(u, t, ctx)),
        ("add_node", lambda u, t: add_node(u, t, ctx)),
        ("delete_node", lambda u, t: delete_node(u, t, ctx)),
    ):
        k, ku = jax.random.split(k)
        n = trees.length.shape[0]
        u = jax.random.uniform(ku, (n, budgets[name]))
        mutated, ok = jax.vmap(lambda uu, t: fn(uu, t))(u, trees)
        out[name] = jax.tree.map(
            lambda new, old: jnp.where(
                ok.reshape(ok.shape + (1,) * (new.ndim - 1)), new, old
            ),
            mutated, trees,
        )
    return out


@pytest.mark.parametrize("seed,maxsize", [(0, 15), (1, 15), (2, 31), (3, 8)])
def test_evolved_programs_satisfy_invariants(ops, seed, maxsize):
    """1000+ programs per config: generation + a round of every
    structural mutation + crossover all preserve the postfix invariants."""
    ctx = _ctx(ops, maxsize)
    key = jax.random.key(seed)
    k_pop, k_mut, k_x = jax.random.split(key, 3)

    P = 160
    trees = _random_population(k_pop, P, ctx)
    total = validate_programs(
        trees, ops, nfeatures=3, n_params=0,
        where=f"generated seed={seed} L={maxsize}",
    )
    assert total == P

    checked = P
    for name, mutated in _mutate_population(k_mut, trees, ctx).items():
        checked += validate_programs(
            mutated, ops, nfeatures=3, n_params=0,
            where=f"{name} seed={seed} L={maxsize}",
        )

    # crossover: pair each tree with a rolled copy of the population
    partner = jax.tree.map(lambda x: jnp.roll(x, 1, axis=0), trees)
    u = jax.random.uniform(k_x, (P, 2 * ctx.max_nodes))
    c1, c2, ok1, ok2 = jax.vmap(
        lambda uu, a, b: crossover_trees(uu, a, b, ctx)
    )(u, trees, partner)

    def sel(new, old, ok):
        return jax.tree.map(
            lambda n_, o_: jnp.where(
                ok.reshape(ok.shape + (1,) * (n_.ndim - 1)), n_, o_
            ), new, old,
        )

    checked += validate_programs(
        sel(c1, trees, ok1), ops, nfeatures=3, where="crossover-1")
    checked += validate_programs(
        sel(c2, partner, ok2), ops, nfeatures=3, where="crossover-2")

    # the acceptance floor: >1000 programs validated per config
    assert checked == 9 * P and checked >= 1000


# ---------------------------------------------------------------------------
# hand-corrupted tables must each be caught
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def clean_pop(ops):
    ctx = _ctx(ops, 15)
    return _random_population(jax.random.key(42), 32, ctx), ctx


def _expect_violation(trees, ops, fragment, **kw):
    msgs = check_programs(trees, ops, **kw)
    assert msgs, "corruption not detected"
    assert any(fragment in m for m in msgs), msgs
    with pytest.raises(ProgramInvariantError):
        validate_programs(trees, ops, **kw)


def test_catches_stack_underflow(clean_pop, ops):
    trees, _ = clean_pop
    # arity-2 operator in slot 0 consumes operands that don't exist
    bad = dataclasses.replace(
        trees,
        arity=trees.arity.at[:, 0].set(2),
        length=jnp.maximum(trees.length, 2),
    )
    _expect_violation(bad, ops, "underflow")


def test_catches_unrooted_forest(clean_pop, ops):
    trees, ctx = clean_pop
    # two stacked leaves with no operator: stack ends at height 2
    bad = TreeBatch.empty((4,), ctx.max_nodes)
    bad = dataclasses.replace(bad, length=jnp.full((4,), 2, jnp.int32))
    _expect_violation(bad, ops, "unrooted")


def test_catches_arity_out_of_range(clean_pop, ops):
    trees, _ = clean_pop
    bad = dataclasses.replace(trees, arity=trees.arity.at[:, 0].set(7))
    msgs = check_programs(bad, ops)
    assert any("arity outside" in m for m in msgs), msgs


def test_catches_operator_index_out_of_range(clean_pop, ops):
    trees, _ = clean_pop
    # find a tree whose root is a binary op and corrupt its op index
    arity = np.asarray(trees.arity)
    length = np.asarray(trees.length)
    roots = length - 1
    cand = [
        i for i in range(arity.shape[0]) if arity[i, roots[i]] == 2
    ]
    assert cand, "fixture needs at least one binary-rooted tree"
    i = cand[0]
    bad = dataclasses.replace(
        trees, op=trees.op.at[i, int(roots[i])].set(99)
    )
    _expect_violation(bad, ops, "op index outside")


def test_catches_bad_leaf_code(clean_pop, ops):
    trees, _ = clean_pop
    bad = dataclasses.replace(trees, op=trees.op.at[:, 0].set(11))
    _expect_violation(bad, ops, "leaf op code")


def test_catches_length_out_of_bounds(clean_pop, ops):
    trees, ctx = clean_pop
    bad = dataclasses.replace(
        trees, length=trees.length.at[0].set(ctx.max_nodes + 5)
    )
    _expect_violation(bad, ops, "length")
    bad0 = dataclasses.replace(trees, length=trees.length.at[0].set(0))
    _expect_violation(bad0, ops, "length")


def test_catches_dirty_padding_arity(clean_pop, ops):
    trees, ctx = clean_pop
    # an operator arity in a padding slot corrupts the full-axis
    # structural prefix sums even though `length` excludes it
    arity = np.asarray(trees.arity)
    length = np.asarray(trees.length)
    short = [i for i in range(arity.shape[0]) if length[i] <= ctx.max_nodes - 1]
    assert short
    i = short[0]
    bad = dataclasses.replace(
        trees, arity=trees.arity.at[i, ctx.max_nodes - 1].set(2)
    )
    _expect_violation(bad, ops, "padding")


def test_catches_feature_out_of_range(clean_pop, ops):
    trees, _ = clean_pop
    # force a variable leaf with a feature index beyond nfeatures
    bad = dataclasses.replace(
        trees,
        op=trees.op.at[:, 0].set(1),      # LEAF_VAR
        arity=trees.arity.at[:, 0].set(0),
        feat=trees.feat.at[:, 0].set(17),
    )
    msgs = check_programs(bad, ops, nfeatures=3)
    assert any("feature outside" in m for m in msgs), msgs


def test_strict_padding_mode(clean_pop, ops):
    trees, ctx = clean_pop
    canon = dataclasses.replace(
        TreeBatch.empty(trees.batch_shape, ctx.max_nodes),
        length=jnp.ones_like(trees.length),
    )
    assert check_programs(canon, ops, strict_padding=True) == []
    dirty = dataclasses.replace(
        canon, const=canon.const.at[:, ctx.max_nodes - 1].set(3.5)
    )
    msgs = check_programs(dirty, ops, strict_padding=True)
    assert any("not zeroed" in m for m in msgs), msgs
    # non-strict mode tolerates non-canonical payload padding
    assert check_programs(dirty, ops) == []


def test_clean_population_passes_all_optional_checks(clean_pop, ops):
    trees, _ = clean_pop
    assert check_programs(trees, ops, nfeatures=3, n_params=0) == []


def test_device_predicate_agrees_with_host_checker(clean_pop, ops):
    """ops.encoding.postfix_valid (jit-usable, structural subset) must
    agree per-tree with the host checker on clean AND corrupted trees."""
    trees, ctx = clean_pop
    n = int(trees.length.shape[0])
    # corrupt a scattering of trees in structurally different ways
    bad = dataclasses.replace(
        trees,
        arity=trees.arity.at[0, 0].set(2)            # underflow at root
        .at[3, ctx.max_nodes - 1].set(1),            # dirty padding arity
        length=trees.length.at[5].set(0),            # length out of bounds
    )
    dev = np.asarray(jax.jit(postfix_valid)(bad.arity, bad.length))
    for i in range(n):
        host_msgs = check_programs(bad[i : i + 1], ops)
        # the device predicate covers the structural subset; no op-code
        # corruption is present here, so the verdicts must match exactly
        assert bool(dev[i]) == (host_msgs == []), (i, host_msgs)
    assert not dev[0] and not dev[3] and not dev[5]
    assert dev.sum() >= n - 3
