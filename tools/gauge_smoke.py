"""CI graftgauge smoke: capacity observability end to end on CPU
(docs/OBSERVABILITY.md "Capacity & memory"; tools/check.sh and the CI
``gauge-smoke`` job)::

    python tools/gauge_smoke.py [out_dir]

Four scenarios:

1. **leak→anomaly→bundle**: a full ``equation_search`` whose logger
   hook leaks one growing device array per iteration (the synthetic
   leak). The memory sampler's per-iteration live-byte samples must
   trip the detector's ``live_bytes_growth`` rule, which must dump a
   flight-recorder bundle (trigger reason ``anomaly``) whose
   deterministic view carries the baseline-relative memory snapshot;
   the stream must still validate and ``metrics_view`` must expose
   ``peak_live_bytes``.
2. **AOT footprint round-trip**: ``compile_iteration`` must harvest
   the executable's memory/cost analysis into the footprint ledger and
   stamp it into the saved envelope; after clearing the ledger,
   ``load_executable`` must report the same analysis WITHOUT
   recompiling and re-record it (source ``aot_load``).
3. **proactive degrade from the watermark**: a search with
   ``gauge_headroom_fraction=0.5`` and a deliberately tiny
   ``gauge_limit_bytes`` must step ``eval_tile_rows`` down via
   ``proactive_degrade`` fault events and still finish cleanly — the
   degrade fires from the watermark, never from an OOM exception.
4. **/metrics scrape**: a serve scrape must render the process-wide
   dispatch-latency histogram (fed by scenarios 1 and 3), the
   ``process_peak_live_bytes`` gauge, and one ``footprint_bytes``
   entry per ledger record (fed by scenario 2).

Exits nonzero on the first failed scenario; telemetry JSONL and the
bundle are left under ``<out_dir>`` as the CI artifact either way.
"""

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SYMBOLIC_REGRESSION_IS_TESTING", "true")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def _problem():
    import numpy as np

    rng = np.random.default_rng(0)
    X = rng.uniform(-2.0, 2.0, (128, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(out_base, **kw):
    from symbolicregression_jl_tpu import Options

    base = dict(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        output_directory=out_base,
        telemetry=True,
    )
    base.update(kw)
    return Options(**base)


def _events(out_base, run_id, event):
    path = os.path.join(out_base, run_id, "telemetry.jsonl")
    with open(path) as f:
        return [json.loads(l) for l in f
                if f'"event": "{event}"' in l]


class _LeakLogger:
    """SRLogger-compatible hook that leaks one growing device array per
    iteration: strictly increasing live bytes, > the detector's
    ``leak_min_bytes`` (1 MiB) within its ``leak_window`` (8)."""

    def __init__(self):
        self.sink = []

    def log_iteration(self, *, iteration, hofs, states, options,
                      num_evals, elapsed, **kw):
        import jax.numpy as jnp

        # 256 KiB, growing per iteration so the walk is strictly
        # increasing even if something else frees memory between samples
        n = 65536 + iteration * 1024
        self.sink.append(jnp.ones((n,), jnp.float32) * iteration)


def scenario_leak_anomaly_bundle(out_base) -> None:
    from symbolicregression_jl_tpu import equation_search
    from symbolicregression_jl_tpu.api.search import RuntimeOptions
    from symbolicregression_jl_tpu.pulse import validate_bundle
    from symbolicregression_jl_tpu.telemetry.report import (
        metrics_view,
        summarize,
    )
    from symbolicregression_jl_tpu.telemetry.schema import load_events

    X, y = _problem()
    leak = _LeakLogger()
    equation_search(
        X, y, options=_options(out_base),
        runtime_options=RuntimeOptions(
            niterations=14, run_id="smoke-leak", seed=5, verbosity=0,
            logger=leak))
    assert leak.sink, "leak hook never ran"

    run_dir = os.path.join(out_base, "smoke-leak")
    events = load_events(os.path.join(run_dir, "telemetry.jsonl"))

    kinds = {e["kind"] for e in events if e["event"] == "gauge"}
    assert {"memory", "watermark"} <= kinds, kinds

    anomalies = [e for e in events if e["event"] == "anomaly"
                 and e["metric"] == "live_bytes_growth"]
    assert anomalies, "synthetic leak never tripped live_bytes_growth"
    assert anomalies[0]["detail"]["growth_bytes"] >= 1 << 20

    bundle_path = os.path.join(run_dir, "pulse_bundle.json")
    assert os.path.exists(bundle_path), f"no bundle at {bundle_path}"
    with open(bundle_path) as f:
        bundle = json.load(f)
    errors = validate_bundle(bundle)
    assert not errors, f"bundle failed validation: {errors}"
    trig = bundle["trigger"]
    assert trig["reason"] == "anomaly", trig
    assert trig["kind"] == "live_bytes_growth", trig
    memory = bundle["iterations"][-1]["memory"]
    assert memory is not None, "bundle iteration lacks memory snapshot"
    assert memory["live_bytes_delta"] > 0, memory

    # the bench layer's ride-along metric comes from the same stream
    mv = metrics_view(summarize(events))
    assert mv.get("peak_live_bytes"), mv.get("peak_live_bytes")


def scenario_aot_footprint_roundtrip(out_base) -> None:
    import numpy as np
    import jax

    from symbolicregression_jl_tpu import Options, search_key
    from symbolicregression_jl_tpu.core.dataset import make_dataset
    from symbolicregression_jl_tpu.gauge import global_ledger
    from symbolicregression_jl_tpu.mesh import MeshEngine, MeshPlan
    from symbolicregression_jl_tpu.mesh.aot import (
        aot_serialization_supported,
        compile_iteration,
        load_executable,
        save_executable,
    )

    rng = np.random.default_rng(7)
    X = rng.uniform(-2, 2, (48, 2)).astype(np.float32)
    y = (X[:, 0] * X[:, 1]).astype(np.float32)
    ds = make_dataset(X, y)
    options = Options(
        binary_operators=["+", "-", "*"], unary_operators=[],
        maxsize=8, populations=2, population_size=8,
        ncycles_per_iteration=2, tournament_selection_n=4,
        optimizer_probability=0.0, save_to_file=False)
    plan = MeshPlan.build(jax.devices()[:1], n_island_shards=1)
    engine = MeshEngine(options, ds.nfeatures, plan)
    state = plan.place_state(
        engine.init_state(search_key(11), ds.data, options.populations))

    ex = compile_iteration(engine, state, ds.data)
    assert ex.analysis is not None, "compile harvested no analysis"
    assert ex.analysis["summary"].get("total_bytes") is not None
    entry = global_ledger().lookup(ex.analysis["fingerprint"],
                                   ex.analysis["geometry"])
    assert entry is not None and entry["source"] == "mesh_aot", entry

    if not aot_serialization_supported():
        print("     (aot serialization unsupported on this jax build; "
              "round-trip leg skipped)")
        return
    path = save_executable(ex, os.path.join(out_base, "iter.aotx"))
    global_ledger().clear()
    ex2 = load_executable(path, expect_key=ex.cache_key)
    # the loaded replica reports footprint from the stamped envelope —
    # no engine, no recompile
    assert ex2.analysis == ex.analysis
    assert ex2.memory_analysis() is not None
    entry = global_ledger().lookup(ex.analysis["fingerprint"],
                                   ex.analysis["geometry"])
    assert entry is not None and entry["source"] == "aot_load", entry


def scenario_proactive_degrade(out_base) -> None:
    from symbolicregression_jl_tpu import equation_search
    from symbolicregression_jl_tpu.api.search import RuntimeOptions

    X, y = _problem()
    # eval_tile_rows starts at 2048 (two halvings above the 512 floor);
    # a 1-byte limit with headroom_fraction=0.5 makes EVERY watermark
    # cross the threshold, so the ladder steps down on iteration 1 and
    # again after the 2-iteration cooldown — all from the watermark, no
    # exception anywhere in the run.
    equation_search(
        X, y, options=_options(out_base, eval_tile_rows=2048),
        runtime_options=RuntimeOptions(
            niterations=8, run_id="smoke-degrade", seed=5, verbosity=0,
            gauge_headroom_fraction=0.5, gauge_limit_bytes=1))

    faults = [e for e in _events(out_base, "smoke-degrade", "fault")
              if e["kind"] == "proactive_degrade"]
    assert faults, "watermark never fired a proactive_degrade"
    first = faults[0]["detail"]
    assert first["eval_tile_rows"] == 1024, first
    assert first["watermark_bytes"] > first["limit_bytes"], first
    # run_end proves the search FINISHED after degrading — the step-down
    # was proactive, not an OOM crash-recovery
    assert _events(out_base, "smoke-degrade", "run_end")


def scenario_metrics_scrape(out_base) -> None:
    from symbolicregression_jl_tpu.serve.server import SearchServer

    server = SearchServer(os.path.join(out_base, "serve_root"),
                          capacity=2, telemetry=False)
    text = server.metrics_text()
    # scenarios 1/3 fed the process-wide latency aggregate; scenario 2
    # left a ledger entry; the sampler tracked the process peak
    assert "graftserve_dispatch_latency_seconds_bucket" in text, (
        "no dispatch-latency histogram in /metrics")
    assert "graftserve_dispatch_latency_seconds_count" in text
    assert "graftserve_process_peak_live_bytes" in text
    assert "graftserve_footprint_bytes{" in text, (
        "no footprint gauge in /metrics")


def main() -> int:
    out_base = sys.argv[1] if len(sys.argv) > 1 else "/tmp/sr_gauge_smoke"
    scenarios = [
        ("leak-anomaly-bundle", scenario_leak_anomaly_bundle),
        ("aot-footprint-roundtrip", scenario_aot_footprint_roundtrip),
        ("proactive-degrade", scenario_proactive_degrade),
        ("metrics-scrape", scenario_metrics_scrape),
    ]
    for name, fn in scenarios:
        try:
            fn(out_base)
        except Exception as e:  # noqa: BLE001 - report and fail the job
            print(f"FAIL [{name}]: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
        print(f"OK   [{name}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
