"""CI graftpulse smoke: the active-diagnostics layer end to end on CPU
(docs/OBSERVABILITY.md; tools/check.sh and the CI ``pulse-smoke`` job)::

    python tools/pulse_smoke.py [out_dir]

Two scenarios, each a full ``equation_search`` with ``pulse`` left at
its zero-config default and the deterministic fault harness
(shield/faults.py) providing the trouble:

1. **anomaly+capture+bundle**: dispatch 10 fails 3 consecutive times
   (→ retry backoff sleeps ≈3.5s → the per-iteration evals/s collapses
   → the EWMA z-score anomaly detector fires → a profiler capture is
   armed, started, and stopped), then island 0 is NaN-poisoned at
   iteration 11 (→ quarantine fault → flight-recorder dump). Asserts
   the ``anomaly`` event, a schema-valid ``pulse_bundle.json``, the
   ``capture_armed``/``capture_start``/``capture_stop`` pulse events,
   a non-empty perfetto trace on disk, and that the whole stream still
   validates against graftscope.v1.
2. **watchdog-trip bundle**: a child process (re-invoking this script
   with ``--watchdog-child``) hangs dispatch 5 for 30s
   (``FaultPlan(hang_on_dispatch=...)``) under a 0.5s
   ``iteration_deadline``, so the shield watchdog trips, emits the
   ``watchdog_timeout`` fault and then aborts with ``os._exit(124)``.
   The parent asserts rc 124 AND that the flight recorder's
   fault-watcher dump landed a valid bundle with that trigger BEFORE
   the abort — the "evidence survives the kill" guarantee.

Exits nonzero on the first failed scenario; telemetry JSONL, bundle,
and trace files are left under ``<out_dir>`` as the CI artifact either
way.
"""

import glob
import json
import os
import subprocess
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SYMBOLIC_REGRESSION_IS_TESTING", "true")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def _problem():
    import numpy as np

    rng = np.random.default_rng(0)
    X = rng.uniform(-2.0, 2.0, (128, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(out_base, **kw):
    from symbolicregression_jl_tpu import Options

    base = dict(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        output_directory=out_base,
        telemetry=True,
    )
    base.update(kw)
    return Options(**base)


def _events(out_base, run_id, event):
    path = os.path.join(out_base, run_id, "telemetry.jsonl")
    with open(path) as f:
        return [json.loads(l) for l in f
                if f'"event": "{event}"' in l]


def _load_bundle(out_base, run_id):
    from symbolicregression_jl_tpu.pulse import validate_bundle

    path = os.path.join(out_base, run_id, "pulse_bundle.json")
    assert os.path.exists(path), f"no flight-recorder bundle at {path}"
    with open(path) as f:
        bundle = json.load(f)
    errors = validate_bundle(bundle)
    assert not errors, f"bundle failed validation: {errors}"
    return bundle


def scenario_anomaly_capture(out_base) -> None:
    from symbolicregression_jl_tpu import equation_search
    from symbolicregression_jl_tpu.api.search import RuntimeOptions
    from symbolicregression_jl_tpu.shield import faults
    from symbolicregression_jl_tpu.telemetry.schema import load_events

    X, y = _problem()
    # 3 consecutive dispatch failures at dispatch 10 stall the loop
    # behind the shield's 0.5+1+2s backoff, collapsing the
    # per-iteration evals/s far past the detector's 4-sigma band (the
    # 5-sample warmup is fed by the clean warm iterations before it);
    # the NaN storm at iteration 11 then exercises quarantine → the
    # fault-triggered flight-recorder dump.
    faults.install(faults.FaultInjector(faults.FaultPlan(
        nan_poison_island=(0, 11), raise_on_dispatch=10, raise_count=3)))
    try:
        equation_search(
            X, y, options=_options(out_base),
            runtime_options=RuntimeOptions(
                niterations=13, run_id="smoke-pulse", seed=5, verbosity=0))
    finally:
        faults.clear()

    # the whole stream — including the new anomaly/pulse kinds — still
    # validates against graftscope.v1
    run_dir = os.path.join(out_base, "smoke-pulse")
    load_events(os.path.join(run_dir, "telemetry.jsonl"))

    anomalies = _events(out_base, "smoke-pulse", "anomaly")
    assert anomalies, "no anomaly event in the stream"
    metrics = {e["metric"] for e in anomalies}
    assert "evals_per_sec" in metrics, metrics

    pulse_kinds = {e["kind"] for e in _events(out_base, "smoke-pulse",
                                              "pulse")}
    assert {"capture_armed", "capture_start",
            "capture_stop"} <= pulse_kinds, pulse_kinds
    assert "bundle_dump" in pulse_kinds, pulse_kinds

    bundle = _load_bundle(out_base, "smoke-pulse")
    assert bundle["trigger"]["reason"] == "fault", bundle["trigger"]
    assert bundle["iterations"], "bundle ring is empty"

    traces = glob.glob(os.path.join(
        run_dir, "pulse_traces", "**", "perfetto_trace.json.gz"),
        recursive=True)
    assert traces, f"no perfetto trace under {run_dir}/pulse_traces"
    assert all(os.path.getsize(t) > 0 for t in traces), "empty trace file"


def scenario_watchdog_bundle(out_base) -> None:
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--watchdog-child", out_base],
        capture_output=True, text=True, timeout=600,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 124, (
        f"child rc={proc.returncode}, expected the watchdog's 124\n"
        f"stderr tail: {proc.stderr[-2000:]}")
    bundle = _load_bundle(out_base, "smoke-watchdog")
    trig = bundle["trigger"]
    assert trig["reason"] == "fault", trig
    assert trig["kind"] == "watchdog_timeout", trig


def _watchdog_child(out_base) -> None:
    """Child half of scenario 2: run until the watchdog aborts us."""
    from symbolicregression_jl_tpu import equation_search
    from symbolicregression_jl_tpu.api.search import RuntimeOptions
    from symbolicregression_jl_tpu.shield import faults

    X, y = _problem()
    # compile-bearing iterations are unsupervised (compile_budget=None);
    # dispatch 5 is warm, hangs 30s against a 0.5s deadline → the
    # watchdog fires (0.25s poll) → watchdog_timeout fault → recorder
    # dump → os._exit(124). The 30s bound means a broken watchdog still
    # lets the child finish and exit 1 instead of wedging CI.
    faults.install(faults.FaultInjector(
        faults.FaultPlan(hang_on_dispatch=(5, 30.0))))
    equation_search(
        X, y, options=_options(out_base, iteration_deadline=0.5),
        runtime_options=RuntimeOptions(
            niterations=8, run_id="smoke-watchdog", seed=5, verbosity=0))
    raise SystemExit("search finished — watchdog never fired")


def main() -> int:
    if len(sys.argv) > 1 and sys.argv[1] == "--watchdog-child":
        _watchdog_child(sys.argv[2])
        return 0
    out_base = sys.argv[1] if len(sys.argv) > 1 else "/tmp/sr_pulse_smoke"
    scenarios = [
        ("anomaly+capture+bundle", scenario_anomaly_capture),
        ("watchdog-trip-bundle", scenario_watchdog_bundle),
    ]
    for name, fn in scenarios:
        try:
            fn(out_base)
        except Exception as e:  # noqa: BLE001 - report and fail the job
            print(f"FAIL [{name}]: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
        print(f"OK   [{name}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
