"""CI ledger smoke: graftledger's attribution + tracing contracts, end
to end on CPU over a real 2-request serve root (docs/OBSERVABILITY.md
"Cost attribution & tracing"; tools/check.sh and the CI ``ledger-smoke``
job)::

    python tools/ledger_smoke.py [out_base]

Checks, against an uninterrupted reference root AND a killed-and-
resumed root (SIGTERM mid-request via the serve fault harness):

1. every per-request ``ledger.jsonl`` validates against graftledger.v1
   and its attributed device+host seconds land within 20% of the
   request's measured wall time (attribution that doesn't add up is
   worse than none);
2. every event in every stream — serve lifecycle and per-request
   graftscope — carries the graftledger trace context, and the ids are
   exactly the deterministic mint for that request;
3. ``telemetry timeline`` exports the root as Chrome trace-event JSON
   that parses and passes the Perfetto shape check;
4. kill-restart-replay reproduces IDENTICAL deterministic ledger views:
   per-request fold fingerprints equal across the killed root and the
   reference root (and the server's rollup agrees), alongside the
   bit-identical hall-of-fame fingerprints serve_smoke already pins.

The subprocess phase reuses this file: ``--phase run`` creates (or
recovers) a server over ``--root``, submits the standard 2-request set
when the journal is empty, drains, and prints a JSON result map.
"""

import argparse
import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SYMBOLIC_REGRESSION_IS_TESTING", "true")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

SEEDS = (5, 9)
NITER = 4


def _problem():
    import numpy as np

    rng = np.random.default_rng(0)
    X = rng.uniform(-2.0, 2.0, (128, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options():
    return dict(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
    )


# ---------------------------------------------------------------------------
# subprocess phase
# ---------------------------------------------------------------------------


def phase_run(root: str, kill_at: int) -> int:
    """Create/recover a server over ``root``, drain it, print results."""
    from symbolicregression_jl_tpu.serve import SearchServer
    from symbolicregression_jl_tpu.shield import faults

    if kill_at:
        faults.install_serve(faults.ServeFaultInjector(
            faults.ServeFaultPlan(kill_server_at_request=kill_at)))
    X, y = _problem()
    srv = SearchServer(root, capacity=8, workers=1)
    if not srv.requests():  # fresh root: submit the standard set
        for seed in SEEDS:
            srv.submit(X, y, options=_options(), niterations=NITER,
                       seed=seed, request_id=f"req-seed{seed}")
    srv.start()
    deadline = time.monotonic() + 600
    while time.monotonic() < deadline:
        if srv._preempt_requested():
            srv.stop(drain=False)
            break
        if srv.wait_idle(timeout=0.5):
            srv.stop(drain=True)
            break
    out = {
        s["request_id"]: {
            "state": s["state"],
            "fingerprint": (s["result"] or {}).get("fingerprint"),
            "resumed": s["resumed"],
        }
        for s in srv.requests()
    }
    print(json.dumps(out))
    return 0


def _run_subprocess(root: str, kill_at: int = 0) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__),
           "--phase", "run", "--root", root]
    if kill_at:
        cmd += ["--kill-at", str(kill_at)]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=900,
        env=dict(os.environ))
    if proc.returncode != 0:
        raise AssertionError(
            f"phase run failed rc={proc.returncode}:\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ---------------------------------------------------------------------------
# per-root checks
# ---------------------------------------------------------------------------


def _ledger_paths(root: str) -> dict:
    from symbolicregression_jl_tpu.ledger import request_ledger_paths

    paths = {}
    for p in request_ledger_paths(root):
        rid = os.path.basename(os.path.dirname(p))
        paths[rid] = p
    expected = {f"req-seed{s}" for s in SEEDS}
    assert set(paths) == expected, (
        f"ledger files {sorted(paths)} != requests {sorted(expected)}")
    return paths


def check_accounts_and_attribution(root: str) -> dict:
    """Check 1: accounts validate; device+host within 20% of wall.
    Returns {request_id: fold fingerprint}."""
    from symbolicregression_jl_tpu.ledger import (
        ledger_fingerprint,
        load_accounts,
        validate_account,
    )

    fingerprints = {}
    for rid, path in _ledger_paths(root).items():
        accounts = load_accounts(path)  # raises on any invalid segment
        for a in accounts:
            assert validate_account(a) == [], (rid, a)
        attributed = sum(
            a["wall"]["device_s"] + a["wall"]["host_s"] for a in accounts)
        wall = sum(a["wall"]["elapsed_s"] for a in accounts)
        assert wall > 0, f"{rid}: zero wall time in ledger"
        # 20% relative envelope, with a 100ms absolute floor: a request
        # whose executables were all cache hits finishes in tens of
        # milliseconds, where scheduler jitter swamps any ratio
        gap = abs(attributed - wall)
        assert gap <= max(0.2 * wall, 0.1), (
            f"{rid}: attributed {attributed:.2f}s vs wall {wall:.2f}s "
            f"(gap {gap:.3f}s) — attribution out of the 20% envelope")
        fingerprints[rid] = ledger_fingerprint(path)
    return fingerprints


def check_trace_propagation(root: str) -> None:
    """Check 2: every emitted event carries the deterministic trace."""
    from symbolicregression_jl_tpu.ledger import mint_trace
    from symbolicregression_jl_tpu.telemetry.schema import (
        load_events_tolerant,
    )

    expected = {
        f"req-seed{s}": mint_trace(
            f"req-seed{s}", seed=s, niterations=NITER).trace_id
        for s in SEEDS
    }
    serve_stream = os.path.join(root, "serve_telemetry.jsonl")
    events, _ = load_events_tolerant(serve_stream)
    assert events, f"empty serve stream {serve_stream}"
    for e in events:
        trace = e.get("trace")
        assert isinstance(trace, dict) and trace.get("trace_id"), (
            f"serve event without trace context: {e}")
        rid = e.get("request_id") or e.get("detail", {}).get("request_id")
        if rid in expected:
            assert trace["trace_id"] == expected[rid], (
                f"{rid}: serve event trace_id {trace['trace_id']} is not "
                f"the deterministic mint {expected[rid]}")
    for rid, tid in expected.items():
        stream = os.path.join(root, "requests", rid, rid,
                              "telemetry.jsonl")
        events, _ = load_events_tolerant(stream)
        assert events, f"empty request stream {stream}"
        for e in events:
            trace = e.get("trace")
            assert isinstance(trace, dict), (
                f"{rid}: event without trace: {e.get('event')}")
            assert trace.get("trace_id") == tid, (
                f"{rid}: {e.get('event')} trace_id {trace.get('trace_id')}"
                f" != minted {tid}")


def check_timeline_export(root: str, out_path: str) -> None:
    """Check 3: the timeline CLI emits parseable, valid Chrome trace."""
    from symbolicregression_jl_tpu.ledger import validate_chrome_trace

    proc = subprocess.run(
        [sys.executable, "-m", "symbolicregression_jl_tpu.telemetry",
         "timeline", root, "--out", out_path],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ))
    assert proc.returncode == 0, (
        f"timeline CLI rc={proc.returncode}: {proc.stderr[-1000:]}")
    with open(out_path) as f:
        doc = json.load(f)  # must parse as plain JSON
    errors = validate_chrome_trace(doc)
    assert errors == [], f"invalid Chrome trace: {errors[:5]}"
    names = {e["name"] for e in doc["traceEvents"]}
    assert any(n.startswith("iteration ") for n in names), names
    assert any(n.startswith("ledger segment") for n in names), names
    assert any(n.startswith("serve:") for n in names), names


def check_rollup(root: str, fingerprints: dict) -> None:
    """The server-written rollup agrees with the per-request files."""
    from symbolicregression_jl_tpu.ledger import load_rollup

    rollup = load_rollup(root)
    assert rollup is not None, f"no ledger rollup under {root}"
    assert rollup["errors"] == [], rollup["errors"]
    assert set(rollup["requests"]) == set(fingerprints)
    for rid, fp in fingerprints.items():
        assert rollup["requests"][rid]["fingerprint"] == fp, rid
        assert rollup["requests"][rid]["iterations"] == NITER, rid
    assert rollup["totals"]["device_s"] > 0


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def scenario_reference_root(out_base: str) -> dict:
    root = os.path.join(out_base, "ref")
    ref = _run_subprocess(root)
    assert all(v["state"] == "done" for v in ref.values()), ref
    fingerprints = check_accounts_and_attribution(root)
    check_trace_propagation(root)
    check_rollup(root, fingerprints)
    check_timeline_export(root, os.path.join(out_base, "ref_timeline.json"))
    return {"hof": {r: v["fingerprint"] for r, v in ref.items()},
            "ledger": fingerprints}


def scenario_kill_restart_replay(out_base: str, ref: dict) -> None:
    root = os.path.join(out_base, "kill")
    partial = _run_subprocess(root, kill_at=2)
    unfinished = [r for r, v in partial.items() if v["state"] != "done"]
    assert unfinished, f"kill fired too late — nothing in flight: {partial}"

    resumed = _run_subprocess(root)
    assert all(v["state"] == "done" for v in resumed.values()), resumed
    for rid, fp in ref["hof"].items():
        assert resumed[rid]["fingerprint"] == fp, (
            f"{rid}: killed-and-restarted HoF differs from reference")

    fingerprints = check_accounts_and_attribution(root)
    check_trace_propagation(root)
    check_rollup(root, fingerprints)
    check_timeline_export(root, os.path.join(out_base,
                                             "kill_timeline.json"))
    # the headline: deterministic ledger views are root-independent AND
    # kill-independent — the resumed request's folded account equals the
    # uninterrupted reference's, fingerprint for fingerprint
    assert fingerprints == ref["ledger"], (
        f"ledger fingerprints diverged across kill-restart-replay:\n"
        f"  ref:  {ref['ledger']}\n  kill: {fingerprints}")


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("out_base", nargs="?",
                        default="/tmp/sr_ledger_smoke")
    parser.add_argument("--phase", choices=["run"], default=None)
    parser.add_argument("--root", default=None)
    parser.add_argument("--kill-at", type=int, default=0)
    args = parser.parse_args()

    if args.phase == "run":
        return phase_run(args.root, args.kill_at)

    # idempotent re-runs: stale journals would replay into this run
    import shutil

    for sub in ("ref", "kill"):
        shutil.rmtree(os.path.join(args.out_base, sub), ignore_errors=True)

    try:
        ref = scenario_reference_root(args.out_base)
    except Exception as e:  # noqa: BLE001 - report and fail the job
        print(f"FAIL [ledger-reference-root]: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 1
    print("OK   [ledger-reference-root]")
    try:
        scenario_kill_restart_replay(args.out_base, ref)
    except Exception as e:  # noqa: BLE001
        print(f"FAIL [ledger-kill-restart-replay]: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1
    print("OK   [ledger-kill-restart-replay]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
