"""CI fault-injection smoke: the graftshield recovery paths, end to end
on CPU (docs/ROBUSTNESS.md; tools/check.sh step 4 and the CI
``fault-injection-smoke`` job)::

    python tools/fault_smoke.py [out_dir]

Three scenarios, each a full ``equation_search`` driven through the
deterministic fault harness (shield/faults.py):

1. **preempt**: a real SIGTERM at iteration 2 → graceful stop, emergency
   checkpoint, then ``resume="auto"`` continues to the 4-iteration
   target and the final hall of fame is BIT-IDENTICAL to an
   uninterrupted reference run (the ISSUE-9 acceptance criterion).
2. **corrupt-checkpoint**: the newest rolling checkpoint gets a flipped
   byte → resume falls back to the previous valid generation.
3. **quarantine**: island 0 is NaN-poisoned → the collapsed island is
   reseeded from the hall of fame and the search finishes finite, with
   the ``quarantine`` fault event in the telemetry stream.

Exits nonzero on the first failed scenario; telemetry JSONL files are
left under ``<out_dir>`` as the CI artifact either way.
"""

import json
import os
import sys
import warnings

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SYMBOLIC_REGRESSION_IS_TESTING", "true")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def _problem():
    import numpy as np

    rng = np.random.default_rng(0)
    X = rng.uniform(-2.0, 2.0, (128, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options(out_base, **kw):
    from symbolicregression_jl_tpu import Options

    base = dict(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        output_directory=out_base,
        telemetry=True,
    )
    base.update(kw)
    return Options(**base)


def _fault_kinds(out_base, run_id):
    path = os.path.join(out_base, run_id, "telemetry.jsonl")
    with open(path) as f:
        return {
            json.loads(l)["kind"] for l in f if '"event": "fault"' in l
        }


def scenario_preempt(out_base) -> None:
    import numpy as np

    from symbolicregression_jl_tpu import equation_search
    from symbolicregression_jl_tpu.api.search import RuntimeOptions
    from symbolicregression_jl_tpu.shield import faults

    X, y = _problem()
    ref_state, _ = equation_search(
        X, y, options=_options(out_base),
        runtime_options=RuntimeOptions(
            niterations=4, run_id="smoke-ref", seed=5, verbosity=0),
        return_state=True)

    faults.install(faults.FaultInjector(
        faults.FaultPlan(sigterm_at_iteration=2)))
    try:
        equation_search(
            X, y, options=_options(out_base),
            runtime_options=RuntimeOptions(
                niterations=4, run_id="smoke-preempt", seed=5, verbosity=0))
    finally:
        faults.clear()
    kinds = _fault_kinds(out_base, "smoke-preempt")
    assert {"preempt_signal", "emergency_checkpoint"} <= kinds, kinds

    res_state, _ = equation_search(
        X, y, options=_options(
            out_base,
            output_directory=os.path.join(out_base, "smoke-preempt")),
        resume="auto",
        runtime_options=RuntimeOptions(
            niterations=4, run_id="smoke-resume", seed=31, verbosity=0),
        return_state=True)
    a, c = ref_state.device_states[0], res_state.device_states[0]
    for f in ("arity", "op", "feat", "const", "length"):
        np.testing.assert_array_equal(
            np.asarray(getattr(a.hof.trees, f)),
            np.asarray(getattr(c.hof.trees, f)))
    np.testing.assert_array_equal(np.asarray(a.hof.cost),
                                  np.asarray(c.hof.cost))
    np.testing.assert_array_equal(np.asarray(a.pops.cost),
                                  np.asarray(c.pops.cost))


def scenario_corrupt_checkpoint(out_base) -> None:
    from symbolicregression_jl_tpu import equation_search
    from symbolicregression_jl_tpu.api.search import RuntimeOptions
    from symbolicregression_jl_tpu.shield import faults

    X, y = _problem()
    equation_search(
        X, y, options=_options(out_base),
        runtime_options=RuntimeOptions(
            niterations=3, run_id="smoke-corrupt", seed=5, verbosity=0,
            checkpoint_every_n=1))
    run_dir = os.path.join(out_base, "smoke-corrupt")
    faults.flip_byte(os.path.join(run_dir, "search_state.pkl"))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        state, _ = equation_search(
            X, y, options=_options(out_base, output_directory=run_dir),
            resume="auto",
            runtime_options=RuntimeOptions(
                niterations=4, run_id="smoke-corrupt-resume", seed=5,
                verbosity=0),
            return_state=True)
    assert any("corrupt" in str(w.message) for w in caught), (
        "no corruption warning surfaced")
    assert state.iterations_done == 4


def scenario_quarantine(out_base) -> None:
    import numpy as np

    from symbolicregression_jl_tpu import equation_search
    from symbolicregression_jl_tpu.api.search import RuntimeOptions
    from symbolicregression_jl_tpu.shield import faults

    X, y = _problem()
    faults.install(faults.FaultInjector(
        faults.FaultPlan(nan_poison_island=(0, 1))))
    try:
        state, hof = equation_search(
            X, y, options=_options(out_base),
            runtime_options=RuntimeOptions(
                niterations=3, run_id="smoke-quarantine", seed=5,
                verbosity=0),
            return_state=True)
    finally:
        faults.clear()
    kinds = _fault_kinds(out_base, "smoke-quarantine")
    assert "quarantine" in kinds, kinds
    loss = np.asarray(state.device_states[0].pops.loss)
    assert np.isfinite(loss[0]).any(), "quarantined island still dead"
    assert len(hof.entries) > 0


def main() -> int:
    out_base = sys.argv[1] if len(sys.argv) > 1 else "/tmp/sr_fault_smoke"
    scenarios = [
        ("preempt+resume-bit-identical", scenario_preempt),
        ("corrupt-checkpoint-fallback", scenario_corrupt_checkpoint),
        ("nan-storm-quarantine", scenario_quarantine),
    ]
    for name, fn in scenarios:
        try:
            fn(out_base)
        except Exception as e:  # noqa: BLE001 - report and fail the job
            print(f"FAIL [{name}]: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
        print(f"OK   [{name}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
