#!/usr/bin/env python
"""graftwarden race-replay smoke — CI's warden-smoke job (docs/LINT.md).

Replays the three races PR 6 fixed by hand, each under its SR_RACE_PLAN
deterministic context-switch schedule (lint/racecheck.py), twice:

1. on CURRENT code — the invariant must hold (ok=True);
2. on a minimal revert shim of the historical fix — the same schedule
   must now expose the bug (ok=False). A replay that passes either way
   pins nothing, so the shim leg is what makes this a regression gate.

Runs on CPU in a few minutes (two legs drive a real mini search). Exits
nonzero on any unexpected outcome.

    JAX_PLATFORMS=cpu python tools/race_smoke.py [workdir]
"""

import json
import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from symbolicregression_jl_tpu.lint.racecheck import (  # noqa: E402
    SCENARIOS,
    replay_scenario,
)


def main() -> int:
    workdir = sys.argv[1] if len(sys.argv) > 1 else None
    ctx = (tempfile.TemporaryDirectory() if workdir is None
           else _Keep(workdir))
    failures = []
    with ctx as base:
        for name in SCENARIOS:
            for shim in (False, True):
                leg = "shim" if shim else "current"
                root = os.path.join(base, f"{name}-{leg}")
                r = replay_scenario(name, root, shim=shim)
                expect_ok = not shim
                status = "PASS" if r["ok"] == expect_ok else "FAIL"
                print(f"[race_smoke] {status} {name} ({leg}): "
                      f"ok={r['ok']} detail={json.dumps(r['detail'])}")
                if r["ok"] != expect_ok:
                    failures.append(f"{name}/{leg}")
    if failures:
        print(f"[race_smoke] FAILED: {', '.join(failures)}",
              file=sys.stderr)
        return 1
    print(f"[race_smoke] OK: {len(SCENARIOS)} scenarios x "
          f"(current passes, reverted shim detected)")
    return 0


class _Keep:
    """Context manager keeping an explicit workdir (CI artifacts)."""

    def __init__(self, path: str) -> None:
        self.path = os.path.abspath(path)

    def __enter__(self) -> str:
        os.makedirs(self.path, exist_ok=True)
        return self.path

    def __exit__(self, *exc) -> None:
        pass


if __name__ == "__main__":
    sys.exit(main())
