"""CI serve smoke: graftserve's crash-safety + backpressure contracts,
end to end on CPU (docs/SERVING.md; tools/check.sh and the CI
``serve-smoke`` job)::

    python tools/serve_smoke.py [out_base]

Scenarios:

1. **kill-restart-replay**: a subprocess server accepts 3 deterministic
   requests and is SIGTERM'd (via the serve fault harness,
   ``kill_server_at_request=2``) while request 2 is in flight. A fresh
   subprocess over the same root replays the journal, resumes the
   interrupted search from its shield checkpoints, and finishes all 3 —
   every hall-of-fame fingerprint must be BIT-IDENTICAL to an unkilled
   reference server's.
2. **overload-reject**: a saturated queue (workers=0) rejects with a
   structured :class:`ServerSaturated` carrying retry-after — no hang,
   no unbounded queueing — and the rejection is audited as a ``serve``
   telemetry event.
3. **executable-cache**: N same-bucket repeat requests after a cold one
   must all hit the engine cache (repeat hit rate 100%, overall >= 90%),
   and ``telemetry report`` must agree.
4. **packed-tenancy**: a pack-enabled server takes a storm of
   near-miss row counts in ONE shape bucket: every request pads to the
   bucket, at least one launch is genuinely multi-tenant, the padded
   shapes share the warmed executable (cache hit rate >= 90%), and the
   drain leaks no admission slot.

The subprocess phases reuse this file: ``--phase run`` creates (or
recovers) a server over ``--root``, submits the standard request set
when the journal is empty, drains, and prints a JSON result map.
"""

import argparse
import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SYMBOLIC_REGRESSION_IS_TESTING", "true")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))

SEEDS = (5, 7, 9)
NITER = 4


def _problem():
    import numpy as np

    rng = np.random.default_rng(0)
    X = rng.uniform(-2.0, 2.0, (128, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1] * X[:, 1]).astype(np.float32)
    return X, y


def _options():
    return dict(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
    )


# ---------------------------------------------------------------------------
# subprocess phase
# ---------------------------------------------------------------------------


def phase_run(root: str, kill_at: int) -> int:
    """Create/recover a server over ``root``, drain it, print results."""
    from symbolicregression_jl_tpu.serve import SearchServer
    from symbolicregression_jl_tpu.shield import faults

    if kill_at:
        faults.install_serve(faults.ServeFaultInjector(
            faults.ServeFaultPlan(kill_server_at_request=kill_at)))
    X, y = _problem()
    srv = SearchServer(root, capacity=8, workers=1)
    if not srv.requests():  # fresh root: submit the standard set
        for seed in SEEDS:
            srv.submit(X, y, options=_options(), niterations=NITER,
                       seed=seed, request_id=f"req-seed{seed}")
    srv.start()
    deadline = time.monotonic() + 600
    while time.monotonic() < deadline:
        if srv._preempt_requested():
            # SIGTERM landed: stop at the boundary (emergency
            # checkpoints written) and report the partial state
            srv.stop(drain=False)
            break
        if srv.wait_idle(timeout=0.5):
            srv.stop(drain=True)
            break
    out = {
        s["request_id"]: {
            "state": s["state"],
            "fingerprint": (s["result"] or {}).get("fingerprint"),
            "resumed": s["resumed"],
        }
        for s in srv.requests()
    }
    print(json.dumps(out))
    return 0


def _run_subprocess(root: str, kill_at: int = 0) -> dict:
    cmd = [sys.executable, os.path.abspath(__file__),
           "--phase", "run", "--root", root]
    if kill_at:
        cmd += ["--kill-at", str(kill_at)]
    env = dict(os.environ)
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=900, env=env)
    if proc.returncode != 0:
        raise AssertionError(
            f"phase run failed rc={proc.returncode}:\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------


def scenario_kill_restart_replay(out_base: str) -> None:
    ref_root = os.path.join(out_base, "ref")
    kill_root = os.path.join(out_base, "kill")

    ref = _run_subprocess(ref_root)
    assert all(v["state"] == "done" for v in ref.values()), ref

    partial = _run_subprocess(kill_root, kill_at=2)
    unfinished = [r for r, v in partial.items() if v["state"] != "done"]
    assert unfinished, f"kill fired too late — nothing in flight: {partial}"

    resumed = _run_subprocess(kill_root)
    assert all(v["state"] == "done" for v in resumed.values()), resumed
    for rid, v in ref.items():
        assert resumed[rid]["fingerprint"] == v["fingerprint"], (
            f"{rid}: killed-and-restarted fingerprint differs from the "
            f"unkilled run")

    # recovery must be audited: replay serve events + journal intact
    from symbolicregression_jl_tpu.telemetry.report import summarize
    from symbolicregression_jl_tpu.telemetry.schema import load_events

    events = load_events(os.path.join(kill_root, "serve_telemetry.jsonl"))
    summary = summarize(events)
    kinds = summary["serve"]["by_kind"]
    assert kinds.get("replay", 0) >= 1, kinds
    assert set(partial) <= set(summary["requests"]), summary["requests"]


def scenario_overload_reject(out_base: str) -> None:
    from symbolicregression_jl_tpu.serve import SearchServer, ServerSaturated

    from symbolicregression_jl_tpu.shield.faults import active_serve_injector

    X, y = _problem()
    root = os.path.join(out_base, "overload")
    srv = SearchServer(root, capacity=2, workers=0)  # never drains
    for i in range(2):
        srv.submit(X, y, options=_options(), niterations=2, seed=i)
    # storm size: the queue_overflow_storm knob of an active
    # SR_SERVE_FAULT_PLAN, else a default burst — EVERY storm submit
    # must reject promptly (no hang, no queue growth)
    inj = active_serve_injector()
    storm = (inj.plan.queue_overflow_storm
             if inj is not None and inj.plan.queue_overflow_storm
             else 5)
    t0 = time.monotonic()
    for k in range(storm):
        try:
            srv.submit(X, y, options=_options(), niterations=2,
                       seed=99 + k)
        except ServerSaturated as e:
            assert e.retry_after_s > 0 and e.queue_depth == 2, e.to_dict()
        else:
            raise AssertionError("saturated queue did not reject")
    assert time.monotonic() - t0 < 5.0 * storm, "reject path blocked"
    assert srv.admission.depth == 2, "storm leaked admission slots"
    with open(os.path.join(root, "serve_telemetry.jsonl")) as f:
        assert any('"kind": "reject"' in l for l in f), (
            "reject not audited in serve telemetry")


def scenario_cache_hit_rate(out_base: str) -> None:
    from symbolicregression_jl_tpu.serve import SearchServer
    from symbolicregression_jl_tpu.telemetry.report import summarize
    from symbolicregression_jl_tpu.telemetry.schema import load_events

    X, y = _problem()
    root = os.path.join(out_base, "cache")
    srv = SearchServer(root, capacity=16, workers=1).start()
    n_repeat = 10
    rids = [
        srv.submit(X, y, options=_options(), niterations=2, seed=100 + i)
        for i in range(1 + n_repeat)
    ]
    for rid in rids:
        s = srv.wait(rid, timeout=600)
        assert s["state"] == "done", s
    srv.stop(drain=True)
    stats = srv.cache.stats()
    assert stats["misses"] == 1 and stats["hits"] == n_repeat, stats
    summary = summarize(
        load_events(os.path.join(root, "serve_telemetry.jsonl")))
    rate = summary["serve"]["cache"]["hit_rate"]
    assert rate is not None and rate >= 0.9, (
        f"reported executable-cache hit rate {rate} < 90%")


def scenario_packed_tenancy(out_base: str) -> None:
    import numpy as np

    from symbolicregression_jl_tpu.pack import PackPolicy
    from symbolicregression_jl_tpu.serve import SearchServer
    from symbolicregression_jl_tpu.telemetry.report import summarize
    from symbolicregression_jl_tpu.telemetry.schema import load_events

    def problem(n, seed):
        r = np.random.default_rng(seed)
        X = r.uniform(-2.0, 2.0, (n, 2)).astype(np.float32)
        y = (X[:, 0] * 2.0 + X[:, 1] * X[:, 1]).astype(np.float32)
        return X, y

    root = os.path.join(out_base, "packed")
    srv = SearchServer(root, capacity=16, workers=1,
                       pack=PackPolicy()).start()
    # warm the executable with ONE cold request first: simultaneous
    # cold-start tenants race get_engine (build-outside-lock,
    # serve/cache.py), so a cold storm would double-count misses
    Xc, yc = problem(200, 42)
    s = srv.wait(srv.submit(Xc, yc, options=_options(), niterations=2,
                            seed=100), timeout=600)
    assert s["state"] == "done", s
    # the storm: near-miss row counts, all in shape bucket 256 — every
    # request pads to the bucket and shares the warmed executable
    n_storm = 10
    rids = [
        srv.submit(*problem(190 + 5 * i, seed=i), options=_options(),
                   niterations=2, seed=200 + i)
        for i in range(n_storm)
    ]
    for rid in rids:
        s = srv.wait(rid, timeout=600)
        assert s["state"] == "done", s
        assert s["pad_rows"] > 0, f"storm request ran unpadded: {s}"
    srv.stop(drain=True)
    assert srv.admission.depth == 0, "packed storm leaked admission slots"
    stats = srv.cache.stats()
    assert stats["misses"] == 1 and stats["hits"] >= n_storm, stats

    events = load_events(os.path.join(root, "serve_telemetry.jsonl"))
    multi = [e for e in events
             if e.get("kind") == "pack_launch"
             and len((e.get("detail") or {}).get("tenants", [])) >= 2]
    assert multi, "no multi-tenant pack_launch in the storm"
    summary = summarize(events)
    rate = summary["serve"]["cache"]["hit_rate"]
    assert rate is not None and rate >= 0.9, (
        f"padded near-miss shapes hit the cache at {rate} < 90%")
    packing = summary["serve"].get("packing") or {}
    assert packing.get("multi_tenant_launches", 0) >= 1, packing


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("out_base", nargs="?", default="/tmp/sr_serve_smoke")
    parser.add_argument("--phase", choices=["run"], default=None)
    parser.add_argument("--root", default=None)
    parser.add_argument("--kill-at", type=int, default=0)
    args = parser.parse_args()

    if args.phase == "run":
        return phase_run(args.root, args.kill_at)

    # idempotent re-runs (tools/check.sh is run repeatedly on one box):
    # every scenario rebuilds its root from scratch — a stale journal
    # from a previous run would otherwise replay into this one
    import shutil

    for sub in ("ref", "kill", "overload", "cache", "packed"):
        shutil.rmtree(os.path.join(args.out_base, sub),
                      ignore_errors=True)

    scenarios = [
        ("kill-restart-replay-bit-identical", scenario_kill_restart_replay),
        ("overload-structured-reject", scenario_overload_reject),
        ("executable-cache-hit-rate", scenario_cache_hit_rate),
        ("packed-tenancy-storm", scenario_packed_tenancy),
    ]
    for name, fn in scenarios:
        try:
            fn(args.out_base)
        except Exception as e:  # noqa: BLE001 - report and fail the job
            print(f"FAIL [{name}]: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 1
        print(f"OK   [{name}]")
    return 0


if __name__ == "__main__":
    sys.exit(main())
