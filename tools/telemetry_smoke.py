"""CI telemetry smoke: 2-iteration search with telemetry=True, schema
validation of every emitted JSONL line, and a report-CLI pass.

Run from the repo root (tools/check.sh step 3 and the CI
``telemetry-smoke`` job)::

    python tools/telemetry_smoke.py [out_dir]

Writes ``<out_dir>/telemetry-smoke/telemetry.jsonl`` (default out_dir:
``/tmp/sr_telemetry_smoke``) and exits nonzero on any schema violation
or report failure — the file is uploaded as a CI build artifact either
way, so a red run leaves the evidence behind.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("SYMBOLIC_REGRESSION_IS_TESTING", "true")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir))


def main() -> int:
    import numpy as np

    from symbolicregression_jl_tpu import Options, equation_search
    from symbolicregression_jl_tpu.telemetry.report import main as report_main
    from symbolicregression_jl_tpu.telemetry.schema import validate_lines

    out_base = sys.argv[1] if len(sys.argv) > 1 else "/tmp/sr_telemetry_smoke"
    rng = np.random.default_rng(0)
    X = rng.uniform(-2.0, 2.0, (64, 2)).astype(np.float32)
    y = (X[:, 0] * 2.0 + X[:, 1]).astype(np.float32)
    options = Options(
        binary_operators=["+", "*"],
        unary_operators=[],
        maxsize=8,
        populations=2,
        population_size=8,
        ncycles_per_iteration=2,
        tournament_selection_n=4,
        optimizer_probability=0.0,
        output_directory=out_base,
        telemetry=True,
    )
    equation_search(
        X, y, options=options, niterations=2, verbosity=0,
        run_id="telemetry-smoke", seed=0,
    )
    path = os.path.join(out_base, "telemetry-smoke", "telemetry.jsonl")
    if not os.path.exists(path):
        print(f"FAIL: {path} was not written", file=sys.stderr)
        return 1
    with open(path) as f:
        lines = f.readlines()
    errors = validate_lines(lines)
    if errors:
        for e in errors:
            print(f"schema violation: {e}", file=sys.stderr)
        return 1
    print(f"{path}: {len(lines)} events, schema valid")
    rc = report_main(["report", path])
    if rc != 0:
        print("FAIL: telemetry report CLI failed", file=sys.stderr)
        return rc
    return 0


if __name__ == "__main__":
    sys.exit(main())
