#!/usr/bin/env bash
# Repo check entry point: graftlint static analysis + fast-tier tests
# + the graftscope/graftshield/graftserve smokes + the graftbench
# perf/quality regression gate. CI runs exactly this; run it locally
# before pushing.
#
#   tools/check.sh            # lint + fast tests + smokes + bench gate
#   tools/check.sh --lint     # lint only (fast, no JAX compile)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== graftlint: JAX-hazard static analysis =="
python -m symbolicregression_jl_tpu.lint symbolicregression_jl_tpu/

if [[ "${1:-}" == "--lint" ]]; then
    exit 0
fi

echo "== fast-tier tests (pytest -m 'not slow') =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider

echo "== graftscope: telemetry JSONL schema check (docs/OBSERVABILITY.md) =="
JAX_PLATFORMS=cpu python tools/telemetry_smoke.py

echo "== graftshield: fault-injection smoke (docs/ROBUSTNESS.md) =="
JAX_PLATFORMS=cpu python tools/fault_smoke.py

echo "== graftpulse: anomaly-capture + watchdog-bundle smoke (docs/OBSERVABILITY.md) =="
JAX_PLATFORMS=cpu python tools/pulse_smoke.py

echo "== graftwarden: deterministic race-replay smoke (docs/LINT.md) =="
JAX_PLATFORMS=cpu python tools/race_smoke.py

echo "== graftserve: kill-restart-replay + overload smoke (docs/SERVING.md) =="
JAX_PLATFORMS=cpu python tools/serve_smoke.py

echo "== graftledger: cost attribution + trace + timeline smoke (docs/OBSERVABILITY.md) =="
JAX_PLATFORMS=cpu python tools/ledger_smoke.py

echo "== graftgauge: capacity observability smoke (docs/OBSERVABILITY.md) =="
JAX_PLATFORMS=cpu python tools/gauge_smoke.py

echo "== graftmesh: mesh dryrun fast tier (docs/SCALING.md) =="
JAX_PLATFORMS=cpu python -m symbolicregression_jl_tpu.mesh.dryrun \
    --devices 8 --fast --out "${TMPDIR:-/tmp}/graftmesh/dryrun.json"

# The gate's default matrix includes the graftstage cells
# (plain-staged / plain-bf16 / plain-staged-bf16, docs/PRECISION.md) —
# staged + bf16 quality regressions beyond band fail right here.
echo "== graftbench: benchmark-matrix gate + serve load smoke (docs/BENCHMARKING.md) =="
JAX_PLATFORMS=cpu python -m symbolicregression_jl_tpu.bench gate \
    --baseline benchmarks/baseline.json \
    --out "${TMPDIR:-/tmp}/graftbench/gate_result.json"
JAX_PLATFORMS=cpu python -m symbolicregression_jl_tpu.bench load \
    --requests 8 --workers 2 --capacity 3 \
    --root "${TMPDIR:-/tmp}/graftbench/load_root" \
    --out "${TMPDIR:-/tmp}/graftbench/load_result.json"
