"""graftledger unified timeline: one Perfetto view per serve root.

``build_timeline(root)`` merges everything a serve root (or a single
run directory) recorded — the server's ``serve_telemetry.jsonl``
lifecycle stream, every request's graftscope stream, and the per-request
cost-ledger accounts — into one causally-ordered Chrome trace-event
JSON document (the ``{"traceEvents": [...]}`` format Perfetto and
``chrome://tracing`` open directly):

- one *process* (pid) per request/run, named after it, plus pid 0 for
  the server's own lifecycle events that match no request;
- per process, a ``serve`` thread (lifecycle instants), an
  ``iterations`` thread (one complete slice per iteration with nested
  ``device`` / ``host`` child slices), an ``events`` thread
  (fault/anomaly/pulse/mesh instants), and a ``ledger`` thread (one
  slice per account segment carrying the cost totals in its args);
- every slice's ``args`` carry the graftledger ``trace_id``/``span_id``
  when the stream recorded them, so the exported timeline correlates
  with on-device profiler captures (spans.py stamps the same ids onto
  ``sr:iteration`` StepTraceAnnotations).

CLI: ``python -m symbolicregression_jl_tpu.telemetry timeline <root>
--out t.json`` (telemetry/report.py).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["build_timeline", "write_timeline", "validate_chrome_trace"]

_TID_SERVE = 0
_TID_ITER = 1
_TID_EVENTS = 2
_TID_LEDGER = 3

_THREAD_NAMES = {
    _TID_SERVE: "serve",
    _TID_ITER: "iterations",
    _TID_EVENTS: "events",
    _TID_LEDGER: "ledger",
}


def _load_stream(path: str) -> List[dict]:
    from ..telemetry.schema import load_events_tolerant

    try:
        events, _notes = load_events_tolerant(path)
    except OSError:
        return []
    return events


def _trace_args(e: dict) -> Dict[str, Any]:
    trace = e.get("trace")
    if not isinstance(trace, dict):
        return {}
    out = {}
    for k in ("trace_id", "span_id", "parent_id"):
        if trace.get(k) is not None:
            out[k] = trace[k]
    return out


def _meta(pid: int, name: str) -> dict:
    return {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": name}}


def _thread_meta(pid: int) -> List[dict]:
    return [
        {"ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
         "args": {"name": tname}}
        for tid, tname in _THREAD_NAMES.items()
    ]


def _instant(name: str, t: float, pid: int, tid: int,
             args: Dict[str, Any]) -> dict:
    return {"ph": "i", "name": name, "ts": t * 1e6, "pid": pid,
            "tid": tid, "s": "t", "args": args}


def _slice(name: str, start: float, dur_s: float, pid: int, tid: int,
           args: Dict[str, Any]) -> dict:
    return {"ph": "X", "name": name, "ts": start * 1e6,
            "dur": max(dur_s, 0.0) * 1e6, "pid": pid, "tid": tid,
            "args": args}


def _run_stream_events(events: List[dict], pid: int) -> List[dict]:
    out: List[dict] = []
    for e in events:
        kind = e.get("event")
        t = float(e.get("t", 0.0))
        args = _trace_args(e)
        if kind == "iteration":
            device_s = float(e.get("device_s", 0.0))
            host_s = float(e.get("host_s", 0.0))
            start = t - device_s - host_s
            it = e.get("iteration")
            out.append(_slice(
                f"iteration {it}", start, device_s + host_s, pid,
                _TID_ITER, {
                    **args,
                    "iteration": it,
                    "num_evals": e.get("num_evals"),
                    "evals_per_sec": e.get("evals_per_sec"),
                }))
            # nested by containment: Perfetto stacks same-thread
            # complete slices whose intervals nest
            out.append(_slice("device", start, device_s, pid,
                              _TID_ITER, dict(args)))
            out.append(_slice("host", start + device_s, host_s, pid,
                              _TID_ITER, dict(args)))
        elif kind in ("run_start", "run_end"):
            extra = {"stop_reason": e["stop_reason"]} \
                if kind == "run_end" else {}
            out.append(_instant(kind, t, pid, _TID_ITER,
                                {**args, **extra}))
        elif kind in ("fault", "pulse"):
            out.append(_instant(f"{kind}:{e.get('kind')}", t, pid,
                                _TID_EVENTS, {**args,
                                              "detail": e.get("detail")}))
        elif kind == "anomaly":
            out.append(_instant(f"anomaly:{e.get('metric')}", t, pid,
                                _TID_EVENTS, {**args,
                                              "detail": e.get("detail")}))
        elif kind == "mesh":
            out.append(_instant(
                f"mesh:exchange@{e.get('iteration')}", t, pid,
                _TID_EVENTS, {**args, "shards": e.get("shards")}))
        elif kind == "gauge":
            d = e.get("detail") or {}
            if e.get("kind") == "memory":
                # Chrome counter track ("C"): Perfetto renders the
                # per-iteration live-bytes series as a graph alongside
                # the iteration slices
                counters = {"live_bytes": d.get("live_bytes", 0)}
                if d.get("bytes_in_use") is not None:
                    counters["bytes_in_use"] = d["bytes_in_use"]
                out.append({"ph": "C", "name": "memory",
                            "ts": t * 1e6, "pid": pid,
                            "tid": _TID_EVENTS, "args": counters})
            else:
                out.append(_instant(
                    f"gauge:{e.get('kind')}", t, pid, _TID_EVENTS,
                    {**args, "detail": d}))
    return out


def _ledger_events(path: str, pid: int) -> List[dict]:
    from .ledger import load_accounts

    try:
        accounts = load_accounts(path)
    except (OSError, ValueError):
        return []
    out: List[dict] = []
    for seg, a in enumerate(accounts):
        wall = a.get("wall", {})
        t0, t1 = wall.get("t_start"), wall.get("t_end")
        if t0 is None or t1 is None:
            continue
        out.append(_slice(
            f"ledger segment {seg}", float(t0), float(t1) - float(t0),
            pid, _TID_LEDGER, {
                **_trace_args(a),
                "device_s": wall.get("device_s"),
                "host_s": wall.get("host_s"),
                "compile": wall.get("compile"),
                "checkpoints": wall.get("checkpoints"),
                "iterations": a.get("deterministic", {}).get("iterations"),
                "num_evals": a.get("deterministic", {}).get("num_evals"),
            }))
    return out


def _discover(root: str) -> Tuple[Optional[str], List[Tuple[str, str]]]:
    """-> (serve stream path or None, [(key, run telemetry path)...])."""
    serve_path = os.path.join(root, "serve_telemetry.jsonl")
    if not os.path.exists(serve_path):
        serve_path = None
    runs: List[Tuple[str, str]] = []
    for p in sorted(glob.glob(
            os.path.join(root, "requests", "*", "*", "telemetry.jsonl"))):
        runs.append((os.path.basename(os.path.dirname(p)), p))
    if not runs:  # a plain run directory works too
        solo = os.path.join(root, "telemetry.jsonl")
        if os.path.exists(solo):
            runs.append((os.path.basename(os.path.abspath(root)), solo))
    return serve_path, runs


def build_timeline(root: str) -> Dict[str, Any]:
    """Merge a serve root's streams into one Chrome trace document."""
    serve_path, runs = _discover(root)
    events: List[dict] = []
    pid_of: Dict[str, int] = {}
    for i, (key, path) in enumerate(runs):
        pid = i + 1
        pid_of[key] = pid
        events.append(_meta(pid, f"request {key}"))
        events.extend(_thread_meta(pid))
        stream = _load_stream(path)
        events.extend(_run_stream_events(stream, pid))
        events.extend(_ledger_events(
            os.path.join(os.path.dirname(path), "ledger.jsonl"), pid))
    if serve_path is not None:
        server_pid_used = False
        for e in _load_stream(serve_path):
            kind = e.get("event")
            t = float(e.get("t", 0.0))
            args = _trace_args(e)
            rid = e.get("request_id") or e.get(
                "detail", {}).get("request_id")
            pid = pid_of.get(rid, 0)
            server_pid_used = server_pid_used or pid == 0
            if kind == "serve":
                events.append(_instant(
                    f"serve:{e.get('kind')}", t, pid, _TID_SERVE,
                    {**args, "request_id": rid,
                     "detail": e.get("detail")}))
            elif kind == "fault":
                events.append(_instant(
                    f"fault:{e.get('kind')}", t, pid, _TID_EVENTS,
                    {**args, "detail": e.get("detail")}))
        if server_pid_used:
            events.append(_meta(0, "graftserve"))
            events.extend(_thread_meta(0))
    meta = [e for e in events if e["ph"] == "M"]
    timed = sorted((e for e in events if e["ph"] != "M"),
                   key=lambda e: e["ts"])
    return {"traceEvents": meta + timed, "displayTimeUnit": "ms"}


def write_timeline(root: str, out: str) -> Dict[str, Any]:
    doc = build_timeline(root)
    d = os.path.dirname(out)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(out, "w") as f:
        json.dump(doc, f)
    return doc


_PHASES = {"X", "i", "I", "M", "B", "E", "C", "b", "e", "n", "s", "t",
           "f"}


def validate_chrome_trace(doc: Any) -> List[str]:
    """Check the Perfetto-required shape of an exported timeline:
    a ``traceEvents`` list whose members each carry ``ph``/``name``/
    ``pid``/``tid``, a numeric ``ts`` on every non-metadata event, and
    a numeric ``dur`` on complete (``X``) slices."""
    errors: List[str] = []
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        return ["document must be an object with a traceEvents list"]
    for i, e in enumerate(doc["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = e.get("ph")
        if not isinstance(ph, str) or ph not in _PHASES:
            errors.append(f"{where}: bad ph {ph!r}")
        if not isinstance(e.get("name"), str):
            errors.append(f"{where}: missing name")
        for k in ("pid", "tid"):
            if not isinstance(e.get(k), int):
                errors.append(f"{where}: missing {k}")
        if ph != "M" and not isinstance(e.get("ts"), (int, float)):
            errors.append(f"{where}: missing ts")
        if ph == "X" and not isinstance(e.get("dur"), (int, float)):
            errors.append(f"{where}: complete slice missing dur")
    return errors
