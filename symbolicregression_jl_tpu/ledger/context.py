"""graftledger trace context: deterministic causal ids for one request.

A :class:`TraceContext` is the W3C-traceparent-shaped triple
``(trace_id, span_id, parent_id)`` that ties every graftscope event a
request causes — serve lifecycle, engine iterations, mesh exchanges,
faults, anomalies, pulse audits — into one causal tree reconstructable
from the JSONL streams alone (docs/OBSERVABILITY.md).

Determinism is the design constraint, not an accident: ids are minted
by hashing request *content* (request id, seed, iteration budget), so

- a kill-restart-replay reconstructs byte-identical trace ids from the
  journal (`serve/journal.py` stores the minted context in the submit
  detail, and :meth:`TraceContext.from_detail` reads it back verbatim —
  the hash is only the minting rule, never re-derived on replay), and
- two servers running the same request set over different roots agree
  on every id, which is what lets `tools/ledger_smoke.py` compare
  deterministic ledger fingerprints across an uninterrupted root and a
  killed-and-resumed one.

No RNG, no wall clock, no filesystem paths feed the hash.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Dict, Optional

__all__ = ["TraceContext", "mint_trace", "mint_run_trace"]

_MINT_DOMAIN = "graftledger"


def _hex(material: str, nchars: int) -> str:
    return hashlib.sha256(material.encode()).hexdigest()[:nchars]


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """One span in a request's causal tree.

    ``trace_id`` (32 hex chars) names the whole request tree; ``span_id``
    (16 hex chars) names this node; ``parent_id`` is the parent node's
    span_id (None at the root).
    """

    trace_id: str
    span_id: str
    parent_id: Optional[str] = None

    def child(self, name: str) -> "TraceContext":
        """Deterministic child span (e.g. the search under a request)."""
        return TraceContext(
            trace_id=self.trace_id,
            span_id=_hex(f"{self.trace_id}:{self.span_id}:{name}", 16),
            parent_id=self.span_id,
        )

    def to_dict(self) -> Dict[str, Any]:
        """The ``trace`` field stamped onto graftscope.v2 events."""
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
        }

    @classmethod
    def from_dict(cls, obj: Optional[Dict[str, Any]]
                  ) -> Optional["TraceContext"]:
        """Inverse of :meth:`to_dict`; None/malformed input -> None (old
        journals and pre-v2 streams carry no trace)."""
        if not isinstance(obj, dict):
            return None
        trace_id = obj.get("trace_id")
        span_id = obj.get("span_id")
        if not isinstance(trace_id, str) or not isinstance(span_id, str):
            return None
        parent = obj.get("parent_id")
        return cls(trace_id=trace_id, span_id=span_id,
                   parent_id=parent if isinstance(parent, str) else None)


def mint_trace(request_id: str, *, seed: int, niterations: int
               ) -> TraceContext:
    """Root span for one served request, minted at ``submit()``.

    Hashes only request content — never the serve root path — so
    identical request sets over different roots mint identical ids.
    """
    trace_id = _hex(
        f"{_MINT_DOMAIN}:{request_id}:{seed}:{niterations}", 32)
    return TraceContext(
        trace_id=trace_id,
        span_id=_hex(f"{trace_id}:root", 16),
        parent_id=None,
    )


def mint_run_trace(run_id: str) -> TraceContext:
    """Root span for a plain (serverless) search, minted from its
    run_id by ``equation_search`` when no context was threaded in."""
    trace_id = _hex(f"{_MINT_DOMAIN}:run:{run_id}", 32)
    return TraceContext(
        trace_id=trace_id,
        span_id=_hex(f"{trace_id}:root", 16),
        parent_id=None,
    )
