"""graftledger cost accounts: per-request, per-phase attribution.

A :class:`CostLedger` is a telemetry-hub sink (telemetry/hub.py) that
folds what the loop already materializes — per-iteration device/host
seconds, ``jax.monitoring`` compile seconds, the timed host-phase spans
(telemetry/spans.py observer), checkpoint byte counts — into one
``graftledger.v1`` *account* per search segment, appended to
``<run_dir>/ledger.jsonl``. Append (not truncate, unlike the hub's
stream): a killed-and-resumed request accumulates one account segment
per attempt in the same file, and :func:`fold_accounts` reduces them to
the same deterministic view an uninterrupted run produces.

The deterministic/wall split follows graftpulse's bundles
(pulse/recorder.py): the ``deterministic`` subtree holds only values
that are pure functions of the search content — final iteration count,
final cumulative evals, the stop reason, the trace ids — so
``ledger_fingerprint`` hashes identically across kill-restart-replay.
Everything clocked (device/host/compile seconds, phase timings,
checkpoint bytes — re-saves make even byte counts schedule-dependent)
lives under ``wall``.

Bit-neutrality: the sink only *reads* host-side values; it draws no
RNG and feeds nothing back into the search (pinned by the on/off A/B
in tests/test_ledger.py).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from .context import TraceContext

__all__ = [
    "LEDGER_SCHEMA",
    "LATENCY_BUCKETS_S",
    "CostLedger",
    "bucket_latency",
    "validate_account",
    "load_accounts",
    "fold_accounts",
    "ledger_fingerprint",
]

LEDGER_SCHEMA = "graftledger.v1"

# log-spaced iteration-latency bucket upper bounds (seconds); the
# histogram counts one sample per iteration of device_s + host_s.
# Rendered on /metrics as a Prometheus histogram (serve/metrics.py),
# so the last implicit bucket is +Inf.
LATENCY_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0,
)


def bucket_latency(seconds: float,
                   counts: Optional[List[int]] = None) -> List[int]:
    """Add one sample to a bucket-count list (len = len(bounds)+1, the
    final slot counting samples above the last bound)."""
    if counts is None:
        counts = [0] * (len(LATENCY_BUCKETS_S) + 1)
    for i, le in enumerate(LATENCY_BUCKETS_S):
        if seconds <= le:
            counts[i] += 1
            return counts
    counts[-1] += 1
    return counts


class CostLedger:
    """Hub sink accumulating one account segment for one search.

    Wire-up (api/search.py): registered with ``hub.add_sink``; the loop
    also points the thread's span observer at :meth:`note_phase` and
    reports checkpoint writes through :meth:`note_checkpoint`.
    """

    def __init__(
        self,
        path: Optional[str],
        *,
        run_id: str,
        trace: TraceContext,
        request_id: Optional[str] = None,
        hub=None,
    ) -> None:
        self.path = path
        self.run_id = run_id
        self.request_id = request_id or run_id
        self.trace = trace
        self.hub = hub
        self._t_start: Optional[float] = None
        self._t_end: Optional[float] = None
        self._iterations = 0
        self._num_evals = 0.0
        self._elapsed_s = 0.0
        self._device_s = 0.0
        self._host_s = 0.0
        self._compile0: Optional[Dict[str, float]] = None
        self._compile: Dict[str, float] = {
            "trace_s": 0.0, "backend_compile_s": 0.0}
        self._phases: Dict[str, Dict[str, float]] = {}
        self._checkpoints = 0
        self._checkpoint_bytes = 0
        self._latency = [0] * (len(LATENCY_BUCKETS_S) + 1)
        self._stop_reason = ""

    # -- hub sink protocol ---------------------------------------------
    def on_iteration(self, ctx) -> None:
        import time

        now = time.time()
        if self._t_start is None:
            self._t_start = now
        self._t_end = now
        self._iterations = max(self._iterations, int(ctx.iteration))
        self._num_evals = float(ctx.num_evals)
        self._elapsed_s = float(ctx.elapsed)
        self._device_s += float(ctx.device_s)
        self._host_s += float(ctx.host_s)
        bucket_latency(float(ctx.device_s) + float(ctx.host_s),
                       self._latency)
        if self.hub is not None:
            snap = self.hub.compile_seconds_snapshot()
            if self._compile0 is None:
                # first observed snapshot anchors the diff: setup-time
                # compiles (engine init) are attributed to this segment
                self._compile0 = {k: 0.0 for k in snap}
            self._compile = {
                k: snap[k] - self._compile0[k] for k in snap}

    def on_end(self, summary: Dict[str, Any]) -> None:
        self._stop_reason = str(summary.get("stop_reason", ""))
        self._elapsed_s = float(summary.get("elapsed_s", self._elapsed_s))
        self._num_evals = float(summary.get("num_evals", self._num_evals))
        self.write()

    # -- phase / checkpoint feeds --------------------------------------
    def note_phase(self, name: str, seconds: float) -> None:
        """Span-observer callback: one completed ``sr:host:<name>``."""
        acc = self._phases.setdefault(name, {"count": 0, "seconds": 0.0})
        acc["count"] += 1
        acc["seconds"] += float(seconds)

    def note_checkpoint(self, nbytes: int) -> None:
        """One full-state checkpoint write of ``nbytes`` bytes."""
        self._checkpoints += 1
        self._checkpoint_bytes += int(nbytes)

    # -- the account record --------------------------------------------
    def account(self) -> Dict[str, Any]:
        return {
            "schema": LEDGER_SCHEMA,
            "run_id": self.run_id,
            "request_id": self.request_id,
            "trace": self.trace.to_dict(),
            "deterministic": {
                "iterations": int(self._iterations),
                "num_evals": float(self._num_evals),
                "stop_reason": self._stop_reason,
            },
            "wall": {
                "t_start": self._t_start,
                "t_end": self._t_end,
                "elapsed_s": self._elapsed_s,
                "device_s": self._device_s,
                "host_s": self._host_s,
                "compile": dict(self._compile),
                "phases": {
                    k: {"count": int(v["count"]),
                        "seconds": float(v["seconds"])}
                    for k, v in sorted(self._phases.items())
                },
                "checkpoints": {
                    "count": self._checkpoints,
                    "bytes": self._checkpoint_bytes,
                },
                "iteration_latency": {
                    "le": list(LATENCY_BUCKETS_S),
                    "counts": list(self._latency),
                },
            },
        }

    def write(self) -> Optional[str]:
        """Append this segment's account; never raises into the loop."""
        if self.path is None:
            return None
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(self.account()) + "\n")
            return self.path
        except OSError:  # accounting must never break the search
            return None


# ---------------------------------------------------------------------------
# validation / folding / fingerprints (the consumer side)
# ---------------------------------------------------------------------------

_NUM = (int, float)

_WALL_FIELDS: Dict[str, Any] = {
    "elapsed_s": _NUM,
    "device_s": _NUM,
    "host_s": _NUM,
    "compile": dict,
    "phases": dict,
    "checkpoints": dict,
    "iteration_latency": dict,
}

_DET_FIELDS: Dict[str, Any] = {
    "iterations": int,
    "num_evals": _NUM,
    "stop_reason": str,
}


def validate_account(obj: Any) -> List[str]:
    """Table-driven account check; returns violations (empty = valid)."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return [f"account is {type(obj).__name__}, expected object"]
    if obj.get("schema") != LEDGER_SCHEMA:
        errors.append(
            f"schema is {obj.get('schema')!r}, expected {LEDGER_SCHEMA!r}")
    for field in ("run_id", "request_id"):
        if not isinstance(obj.get(field), str):
            errors.append(f"{field}: missing/not str")
    trace = obj.get("trace")
    if not isinstance(trace, dict) or not isinstance(
            trace.get("trace_id"), str) or not isinstance(
            trace.get("span_id"), str):
        errors.append("trace: missing/malformed trace context")
    det = obj.get("deterministic")
    if not isinstance(det, dict):
        errors.append("deterministic: missing/not object")
    else:
        for name, spec in _DET_FIELDS.items():
            v = det.get(name)
            if not isinstance(v, spec) or isinstance(v, bool):
                errors.append(f"deterministic.{name}: missing/bad type")
    wall = obj.get("wall")
    if not isinstance(wall, dict):
        errors.append("wall: missing/not object")
    else:
        for name, spec in _WALL_FIELDS.items():
            v = wall.get(name)
            if not isinstance(v, spec) or isinstance(v, bool):
                errors.append(f"wall.{name}: missing/bad type")
        hist = wall.get("iteration_latency")
        if isinstance(hist, dict) and (
                not isinstance(hist.get("le"), list)
                or not isinstance(hist.get("counts"), list)
                or len(hist.get("counts", [])) !=
                len(hist.get("le", [])) + 1):
            errors.append(
                "wall.iteration_latency: counts must be len(le)+1")
    return errors


def load_accounts(path: str) -> List[dict]:
    """Load + validate a per-request ledger JSONL; raises ValueError."""
    accounts: List[dict] = []
    errors: List[str] = []
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                errors.append(f"line {lineno}: invalid JSON ({e})")
                continue
            errors.extend(
                f"line {lineno}: {m}" for m in validate_account(obj))
            accounts.append(obj)
    if errors:
        raise ValueError(
            f"{path} failed {LEDGER_SCHEMA} validation:\n  "
            + "\n  ".join(errors[:20]))
    if not accounts:
        raise ValueError(f"{path}: no ledger accounts found")
    return accounts


def fold_accounts(accounts: List[dict]) -> Dict[str, Any]:
    """Reduce one request's account segments (file order = attempt
    order) to the deterministic view: final-value semantics, so a
    killed-and-resumed request folds to exactly what its uninterrupted
    twin writes — segment counts, re-saved checkpoints, and every
    clocked value stay out."""
    if not accounts:
        raise ValueError("fold_accounts: no accounts")
    last = accounts[-1]
    return {
        "schema": LEDGER_SCHEMA,
        "run_id": last.get("run_id"),
        "request_id": last.get("request_id"),
        "trace": last.get("trace"),
        "iterations": max(
            int(a.get("deterministic", {}).get("iterations", 0))
            for a in accounts),
        "num_evals": float(
            last.get("deterministic", {}).get("num_evals", 0.0)),
        "stop_reason": last.get("deterministic", {}).get(
            "stop_reason", ""),
    }


def ledger_fingerprint(path: str) -> str:
    """sha256 over the folded deterministic view of one request's
    ledger file — byte-stable across kill-restart-replay."""
    import hashlib

    view = fold_accounts(load_accounts(path))
    blob = json.dumps(view, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()
