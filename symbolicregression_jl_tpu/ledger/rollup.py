"""graftledger server rollup: the per-tenant view over a serve root.

``build_rollup(root)`` rescans every request's ``ledger.jsonl`` under
``<root>/requests/<rid>/<rid>/`` and reduces them to one
``graftledger.rollup.v1`` document: per-request wall totals (summed
across resume segments), the folded deterministic view's identity
fields, and fleet totals. ``write_rollup`` persists it as
``<root>/ledger_rollup.json`` — a full rewrite on every request
completion (``SearchServer._finish``), so a crash between writes
loses nothing: the next rewrite rebuilds from the per-request files,
which are the source of truth.

Consumers: the per-tenant counters + histograms on ``/metrics``
(serve/metrics.py) and ``bench load``'s fairness-spread report
(bench/load.py).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, List, Optional

from .ledger import (
    LATENCY_BUCKETS_S,
    fold_accounts,
    ledger_fingerprint,
    load_accounts,
)

__all__ = ["ROLLUP_SCHEMA", "ROLLUP_NAME", "build_rollup", "write_rollup",
           "load_rollup", "request_ledger_paths"]

ROLLUP_SCHEMA = "graftledger.rollup.v1"
ROLLUP_NAME = "ledger_rollup.json"


def request_ledger_paths(root: str) -> List[str]:
    """Every per-request ledger file under a serve root, sorted for a
    deterministic rollup ordering."""
    return sorted(
        glob.glob(os.path.join(root, "requests", "*", "*", "ledger.jsonl")))


def _sum_hist(acc: Optional[List[int]], counts: List[int]) -> List[int]:
    if acc is None:
        return list(counts)
    return [a + b for a, b in zip(acc, counts)]


def build_rollup(root: str) -> Dict[str, Any]:
    """Scan + fold every request ledger under ``root``; unreadable or
    invalid files are reported under ``errors`` instead of raising —
    the rollup writer runs on the server's hot completion path."""
    requests: Dict[str, Any] = {}
    errors: List[str] = []
    totals = {
        "device_s": 0.0, "host_s": 0.0, "compile_s": 0.0,
        "num_evals": 0.0, "iterations": 0,
        "checkpoint_bytes": 0, "checkpoints": 0,
    }
    hist_total: Optional[List[int]] = None
    for path in request_ledger_paths(root):
        try:
            accounts = load_accounts(path)
            folded = fold_accounts(accounts)
            fingerprint = ledger_fingerprint(path)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: {e}")
            continue
        rid = folded["request_id"]
        device_s = host_s = compile_s = 0.0
        ckpt_bytes = ckpt_count = 0
        hist: Optional[List[int]] = None
        for a in accounts:
            wall = a.get("wall", {})
            device_s += float(wall.get("device_s", 0.0))
            host_s += float(wall.get("host_s", 0.0))
            compile_s += sum(
                float(v) for v in wall.get("compile", {}).values())
            ck = wall.get("checkpoints", {})
            ckpt_bytes += int(ck.get("bytes", 0))
            ckpt_count += int(ck.get("count", 0))
            counts = wall.get("iteration_latency", {}).get("counts")
            if isinstance(counts, list):
                hist = _sum_hist(hist, counts)
        requests[rid] = {
            "trace_id": (folded.get("trace") or {}).get("trace_id"),
            "run_id": folded.get("run_id"),
            "iterations": folded["iterations"],
            "num_evals": folded["num_evals"],
            "stop_reason": folded["stop_reason"],
            "segments": len(accounts),
            "fingerprint": fingerprint,
            "device_s": device_s,
            "host_s": host_s,
            "compile_s": compile_s,
            "checkpoint_bytes": ckpt_bytes,
            "checkpoints": ckpt_count,
            "iteration_latency": {
                "le": list(LATENCY_BUCKETS_S),
                "counts": hist or [0] * (len(LATENCY_BUCKETS_S) + 1),
            },
        }
        totals["device_s"] += device_s
        totals["host_s"] += host_s
        totals["compile_s"] += compile_s
        totals["num_evals"] += folded["num_evals"]
        totals["iterations"] += folded["iterations"]
        totals["checkpoint_bytes"] += ckpt_bytes
        totals["checkpoints"] += ckpt_count
        hist_total = _sum_hist(hist_total, requests[rid][
            "iteration_latency"]["counts"])
    return {
        "schema": ROLLUP_SCHEMA,
        "root": os.path.abspath(root),
        "requests": requests,
        "totals": totals,
        "iteration_latency": {
            "le": list(LATENCY_BUCKETS_S),
            "counts": hist_total or [0] * (len(LATENCY_BUCKETS_S) + 1),
        },
        "errors": errors,
    }


def write_rollup(root: str) -> Optional[str]:
    """Rebuild + atomically replace ``<root>/ledger_rollup.json``."""
    path = os.path.join(root, ROLLUP_NAME)
    try:
        rollup = build_rollup(root)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rollup, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path
    except OSError:  # accounting must never break serving
        return None


def load_rollup(root: str) -> Optional[Dict[str, Any]]:
    """Read the persisted rollup; None when absent/unreadable."""
    try:
        with open(os.path.join(root, ROLLUP_NAME)) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return obj if obj.get("schema") == ROLLUP_SCHEMA else None
