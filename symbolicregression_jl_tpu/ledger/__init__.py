"""graftledger — per-tenant cost attribution and causal tracing.

Three pieces (docs/OBSERVABILITY.md, "Cost attribution & tracing"):

- :mod:`.context` — deterministic ``TraceContext`` ids minted at
  ``SearchServer.submit()`` (journaled) or from a plain search's
  run_id, stamped by the telemetry hub onto every graftscope.v2 event;
- :mod:`.ledger` — the ``CostLedger`` hub sink folding device/host/
  compile seconds, host-phase spans, evals, and checkpoint bytes into
  per-request ``graftledger.v1`` accounts with a deterministic/wall
  split, plus :mod:`.rollup`'s server-level per-tenant view;
- :mod:`.timeline` — the unified Chrome-trace (Perfetto) exporter
  behind ``python -m symbolicregression_jl_tpu.telemetry timeline``.
"""

from .context import TraceContext, mint_run_trace, mint_trace
from .ledger import (
    LATENCY_BUCKETS_S,
    LEDGER_SCHEMA,
    CostLedger,
    fold_accounts,
    ledger_fingerprint,
    load_accounts,
    validate_account,
)
from .rollup import (
    ROLLUP_NAME,
    ROLLUP_SCHEMA,
    build_rollup,
    load_rollup,
    request_ledger_paths,
    write_rollup,
)
from .timeline import build_timeline, validate_chrome_trace, write_timeline

__all__ = [
    "TraceContext",
    "mint_trace",
    "mint_run_trace",
    "LEDGER_SCHEMA",
    "LATENCY_BUCKETS_S",
    "CostLedger",
    "validate_account",
    "load_accounts",
    "fold_accounts",
    "ledger_fingerprint",
    "ROLLUP_SCHEMA",
    "ROLLUP_NAME",
    "build_rollup",
    "write_rollup",
    "load_rollup",
    "request_ledger_paths",
    "build_timeline",
    "write_timeline",
    "validate_chrome_trace",
]
