"""symbolicregression_jl_tpu — a TPU-native symbolic regression framework.

A from-scratch JAX/XLA re-design of the capabilities of
SymbolicRegression.jl: tensorized populations, a vmapped postfix tree
interpreter, device-side regularized evolution, batched constant
optimization via `jax.grad`, and island parallelism over
`jax.sharding.Mesh` devices.
"""

__version__ = "0.1.0"

import jax as _jax


def search_key(seed) -> "_jax.Array":
    """PRNG key for the search engine, using the hardware "rbg" impl.

    The evolution step draws thousands of small random samples per cycle
    (tournaments, mutation kinds, speculative attempts). JAX's default
    threefry PRNG computes each as a multi-round hash — profiled at ~50%
    of per-cycle device time on TPU. The counter-based RngBitGenerator
    impl is near-free with the same split/fold_in API; GP search needs
    statistical, not cryptographic, randomness. The impl rides the typed
    key (no global config mutation), so user code is unaffected.
    """
    return _jax.random.key(seed, impl="rbg")


from .core.dataset import Dataset, make_dataset
from .core.losses import LOSS_REGISTRY, resolve_loss
from .core.options import ComplexityMapping, MutationWeights, Options
from .ops.operators import Op, OperatorSet
from .ops.tree import Node, parse_expression, string_tree

__all__ = [
    "Dataset",
    "make_dataset",
    "Options",
    "MutationWeights",
    "ComplexityMapping",
    "Op",
    "OperatorSet",
    "Node",
    "parse_expression",
    "string_tree",
    "LOSS_REGISTRY",
    "resolve_loss",
]


def __getattr__(name):
    # Lazily expose the heavier API surface to keep import light.
    if name in ("equation_search", "SearchState", "RuntimeOptions"):
        from .api import search

        return getattr(search, name)
    if name in (
        "eval_tree_array",
        "eval_diff_tree_array",
        "eval_grad_tree_array",
        "differentiable_eval_tree_array",
        "D",
    ):
        from .ops import diff

        return getattr(diff, name)
    if name in ("SRRegressor", "MultitargetSRRegressor"):
        from .api import regressor

        return getattr(regressor, name)
    if name in ("ExpressionSpec", "ParametricExpressionSpec"):
        from . import models

        return getattr(models, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
