"""symbolicregression_jl_tpu — a TPU-native symbolic regression framework.

A from-scratch JAX/XLA re-design of the capabilities of
SymbolicRegression.jl: tensorized populations, a vmapped postfix tree
interpreter, device-side regularized evolution, batched constant
optimization via `jax.grad`, and island parallelism over
`jax.sharding.Mesh` devices.
"""

__version__ = "0.1.0"

import jax as _jax


def search_key(seed) -> "_jax.Array":
    """PRNG key for the search engine (default threefry impl).

    An earlier revision used the hardware "rbg" impl for speed, but on
    TPU rbg's ``split``/``fold_in`` propagate entropy weakly (a
    documented JAX caveat) and the resulting correlated per-slot streams
    measurably degraded search quality: on the reference benchmark
    problem, 4/4 seeds plateaued at loss ~0.90 under rbg vs 0.50-0.77
    under threefry or on CPU. With the bulk-uniform batching in
    evolve/rng.py (one big draw per slot instead of ~1000 chained
    sampler calls) the PRNG left the critical path, so threefry now
    costs nothing measurable: 235k evals/s on the bench config vs 249k
    peak with rbg, both >= the 2e5 north star.
    """
    return _jax.random.key(seed)


from .core.dataset import Dataset, make_dataset
from .core.losses import LOSS_REGISTRY, resolve_loss
from .core.options import ComplexityMapping, MutationWeights, Options
from .ops.operators import Op, OperatorSet
from .ops.tree import Node, parse_expression, string_tree

__all__ = [
    "Dataset",
    "make_dataset",
    "Options",
    "MutationWeights",
    "ComplexityMapping",
    "Op",
    "OperatorSet",
    "Node",
    "parse_expression",
    "string_tree",
    "LOSS_REGISTRY",
    "resolve_loss",
    # lazily exposed via __getattr__ (api.search) — listed so
    # star-imports and IDE completion see them:
    "equation_search",
    "warmup",
    # lazily exposed via __getattr__ (serve) — graftserve service layer
    "SearchServer",
    "ServerSaturated",
]


def __getattr__(name):
    # Lazily expose the heavier API surface to keep import light.
    if name in ("equation_search", "SearchState", "RuntimeOptions",
                "warmup"):
        from .api import search

        return getattr(search, name)
    if name in (
        "eval_tree_array",
        "eval_diff_tree_array",
        "eval_grad_tree_array",
        "differentiable_eval_tree_array",
    ):
        from .ops import diff

        return getattr(diff, name)
    if name == "D":
        return _dispatch_D
    if name in ("SRRegressor", "MultitargetSRRegressor"):
        from .api import regressor

        return getattr(regressor, name)
    if name in ("SearchServer", "ServerSaturated"):
        from . import serve

        return getattr(serve, name)
    if name in ("ExpressionSpec", "ParametricExpressionSpec"):
        from . import models

        return getattr(models, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def _dispatch_D(obj, arg=1):
    """The exported ``D`` (reference src/SymbolicRegression.jl:172).

    - On a host ``Node``: symbolic derivative w.r.t. variable index
      ``arg`` (0-based feature, ops.diff.D semantics).
    - On template/composable subexpression callables: a derivative
      callable w.r.t. argument slot ``arg`` (1-based, matching the
      reference's template idiom ``D(V, 1)(x)``); see models.template.D.
    """
    from .ops.tree import Node

    if isinstance(obj, Node):
        from .ops import diff

        return diff.D(obj, arg)
    from .models import template as _template

    return _template.D(obj, arg)
