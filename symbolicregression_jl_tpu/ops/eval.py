"""Vmapped postfix tree interpreter — the framework's hot kernel.

Replaces DynamicExpressions' recursive fused interpreter
(/root/reference/src/InterfaceDynamicExpressions.jl:32-44) with an iterative
slot-buffer interpreter: a `lax.scan` over tree slots, each step gathering
child rows from the value buffer, applying the operator tables, and writing
back. One XLA launch evaluates ``population × rows`` values (SURVEY.md §7).

NaN/Inf early-exit semantics (invalid => loss Inf,
/root/reference/src/LossFunctions.jl:96-99) are replaced by an equivalent
masked validity reduction: a tree is invalid iff *any* node's output
contains a non-finite value over the evaluated rows — matching the
reference, which checks each op's output buffer before continuing.

`jax.grad` through this interpreter (w.r.t. the `const` leaf array) powers
constant optimization, replacing Enzyme/Mooncake reverse-mode AD
(/root/reference/src/ConstantOptimization.jl:136-167).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .encoding import (
    LEAF_CONST,
    LEAF_PARAM,
    LEAF_VAR,
    MAX_ARITY,
    TreeBatch,
    tree_structure_arrays,
)
from .operators import OperatorSet

__all__ = ["eval_tree_batch", "eval_single_tree"]


def _apply_tables(operators: OperatorSet, a, o, leaf, children):
    """Value of one node: select over arity and operator index.

    Computes every operator of the relevant arity and selects by index —
    under vmap a `lax.switch` would lower to the same select tree, so we
    generate it directly and let XLA fuse the elementwise ops.
    """
    val = leaf
    unary_ops = operators.unary
    binary_ops = operators.binary
    if unary_ops:
        un_stack = jnp.stack([op.fn(children[0]) for op in unary_ops])
        un = jax.lax.dynamic_index_in_dim(
            un_stack, jnp.clip(o, 0, len(unary_ops) - 1), axis=0, keepdims=False
        )
        val = jnp.where(a == 1, un, val)
    if binary_ops:
        bi_stack = jnp.stack([op.fn(children[0], children[1]) for op in binary_ops])
        bi = jax.lax.dynamic_index_in_dim(
            bi_stack, jnp.clip(o, 0, len(binary_ops) - 1), axis=0, keepdims=False
        )
        val = jnp.where(a == 2, bi, val)
    return val


def eval_single_tree(
    arity: jax.Array,
    op: jax.Array,
    feat: jax.Array,
    const: jax.Array,
    length: jax.Array,
    child: jax.Array,
    X: jax.Array,  # [F, n]
    operators: OperatorSet,
    params: Optional[jax.Array] = None,  # [n_params, n] (pre-gathered by class)
) -> Tuple[jax.Array, jax.Array]:
    """Evaluate one postfix tree over all rows. Returns (y[n], valid)."""
    L = arity.shape[0]
    n = X.shape[1]
    dtype = const.dtype

    def step(carry, k):
        buf, valid = carry
        a = arity[k]
        o = op[k]
        children = [
            jax.lax.dynamic_index_in_dim(buf, child[k, j], axis=0, keepdims=False)
            for j in range(MAX_ARITY)
        ]
        x_row = jax.lax.dynamic_index_in_dim(X, feat[k], axis=0, keepdims=False)
        leaf = jnp.where(o == LEAF_CONST, jnp.broadcast_to(const[k], (n,)), x_row)
        if params is not None:
            p_row = jax.lax.dynamic_index_in_dim(
                params, jnp.clip(feat[k], 0, params.shape[0] - 1), axis=0, keepdims=False
            )
            leaf = jnp.where(o == LEAF_PARAM, p_row, leaf)
        else:
            # A parameter leaf evaluated without parameters is invalid, not
            # a silent read of X[feat].
            leaf = jnp.where((a == 0) & (o == LEAF_PARAM), jnp.nan, leaf)
        val = _apply_tables(operators, a, o, leaf, children)
        val = val.astype(dtype)
        in_tree = k < length
        valid = valid & (jnp.all(jnp.isfinite(val)) | ~in_tree)
        buf = buf.at[k].set(val)
        return (buf, valid), None

    buf0 = jnp.zeros((L, n), dtype)
    (buf, valid), _ = jax.lax.scan(
        step, (buf0, jnp.bool_(True)), jnp.arange(L, dtype=jnp.int32)
    )
    y = jax.lax.dynamic_index_in_dim(buf, length - 1, axis=0, keepdims=False)
    return y, valid


@partial(jax.jit, static_argnames=("operators",))
def eval_tree_batch(
    batch: TreeBatch,
    X: jax.Array,  # [F, n]
    operators: OperatorSet,
    params: Optional[jax.Array] = None,  # [..., n_params, n] or None
) -> Tuple[jax.Array, jax.Array]:
    """Evaluate a batch of trees over all rows.

    Returns ``(y[..., n], valid[...])`` with the batch's leading dims.
    """
    batch_shape = batch.batch_shape
    L = batch.max_nodes
    flat = batch.reshape(-1)
    child, _, _ = tree_structure_arrays(flat, need_depth=False)

    if params is None:
        f = jax.vmap(
            lambda a, o, ft, c, ln, ch: eval_single_tree(
                a, o, ft, c, ln, ch, X, operators
            )
        )
        y, valid = f(flat.arity, flat.op, flat.feat, flat.const, flat.length, child)
    else:
        p_flat = params.reshape(-1, *params.shape[-2:])
        f = jax.vmap(
            lambda a, o, ft, c, ln, ch, p: eval_single_tree(
                a, o, ft, c, ln, ch, X, operators, p
            )
        )
        y, valid = f(
            flat.arity, flat.op, flat.feat, flat.const, flat.length, child, p_flat
        )
    return y.reshape(*batch_shape, X.shape[1]), valid.reshape(batch_shape)
