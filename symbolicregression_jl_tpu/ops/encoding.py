"""Postfix tensor encoding of expression-tree populations.

This replaces the reference's pointer-based `Node` populations with padded
arrays so that a whole population is evaluated/mutated in a single XLA
launch (SURVEY.md §7 design delta 1). Trees are stored in depth-first
*post-order* ("postfix"), which has the key property that **every subtree
occupies a contiguous slot range** ``[k - size_k + 1, k]`` — structural
mutations (insert/delete/crossover/rotate) become gather index arithmetic
instead of pointer surgery.

Per-tree arrays (slot axis L = maxsize, padded):

- ``arity[L]``  int32: 0 for leaves, d for arity-d operator nodes. Padding
  slots (``k >= length``) hold arity 0.
- ``op[L]``     int32: for leaves: 0=constant, 1=variable, 2=parameter
  (LEAF_CONST/LEAF_VAR/LEAF_PARAM); for operator nodes: index into the
  OperatorSet's arity-d table.
- ``feat[L]``   int32: feature index for variable leaves (0-based);
  parameter index for parameter leaves.
- ``const[L]``  float: constant value for constant leaves.
- ``length``    int32 scalar: number of used slots; root is ``length - 1``.

A batch stacks these with arbitrary leading dims (population, island, ...).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .operators import OperatorSet
from .tree import Node

__all__ = [
    "LEAF_CONST",
    "LEAF_VAR",
    "LEAF_PARAM",
    "TreeBatch",
    "encode_tree",
    "decode_tree",
    "encode_population",
    "tree_structure_arrays",
]

LEAF_CONST = 0
LEAF_VAR = 1
LEAF_PARAM = 2

MAX_ARITY = 2  # reference default node degree; bump for n-ary operator sets


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TreeBatch:
    """A batch of postfix-encoded trees (pytree of arrays).

    All fields share leading batch dims; the final axis of the per-slot
    fields is the slot axis L.
    """

    arity: jax.Array  # int32 [..., L]
    op: jax.Array     # int32 [..., L]
    feat: jax.Array   # int32 [..., L]
    const: jax.Array  # float [..., L]
    length: jax.Array  # int32 [...]

    @property
    def max_nodes(self) -> int:
        return self.arity.shape[-1]

    @property
    def batch_shape(self) -> Tuple[int, ...]:
        return self.arity.shape[:-1]

    def reshape(self, *batch_shape) -> "TreeBatch":
        L = self.max_nodes
        return TreeBatch(
            arity=self.arity.reshape(*batch_shape, L),
            op=self.op.reshape(*batch_shape, L),
            feat=self.feat.reshape(*batch_shape, L),
            const=self.const.reshape(*batch_shape, L),
            length=self.length.reshape(*batch_shape),
        )

    def __getitem__(self, idx) -> "TreeBatch":
        return TreeBatch(
            arity=self.arity[idx],
            op=self.op[idx],
            feat=self.feat[idx],
            const=self.const[idx],
            length=self.length[idx],
        )

    @staticmethod
    def empty(batch_shape: Tuple[int, ...], max_nodes: int, dtype=jnp.float32) -> "TreeBatch":
        """All-padding batch of single-constant (0.0) trees."""
        shape = (*batch_shape, max_nodes)
        return TreeBatch(
            arity=jnp.zeros(shape, jnp.int32),
            op=jnp.zeros(shape, jnp.int32),
            feat=jnp.zeros(shape, jnp.int32),
            const=jnp.zeros(shape, dtype),
            length=jnp.ones(batch_shape, jnp.int32),
        )


# ---------------------------------------------------------------------------
# Host encode / decode
# ---------------------------------------------------------------------------


def encode_tree(
    tree: Node, max_nodes: int, operators: OperatorSet, dtype=np.float32
):
    """Encode a host `Node` into per-slot numpy arrays (postfix order)."""
    arity = np.zeros(max_nodes, np.int32)
    op = np.zeros(max_nodes, np.int32)
    feat = np.zeros(max_nodes, np.int32)
    const = np.zeros(max_nodes, dtype)
    k = 0
    for n in tree.nodes():
        if k >= max_nodes:
            raise ValueError(
                f"Tree has more than max_nodes={max_nodes} nodes: "
                f"{tree.count_nodes()}"
            )
        arity[k] = n.degree
        if n.degree == 0:
            if n.is_parameter:
                op[k] = LEAF_PARAM
                feat[k] = n.parameter
            elif n.constant:
                op[k] = LEAF_CONST
                const[k] = n.val
            else:
                op[k] = LEAF_VAR
                feat[k] = n.feature
        else:
            ops_d = operators[n.degree]
            idx = None
            for i, o in enumerate(ops_d):
                if o.name == n.op.name:
                    idx = i
                    break
            if idx is None:
                raise ValueError(
                    f"Operator {n.op.name!r}/{n.degree} not in operator set"
                )
            op[k] = idx
        k += 1
    return arity, op, feat, const, np.int32(k)


def decode_tree(arity, op, feat, const, length, operators: OperatorSet) -> Node:
    """Decode per-slot arrays back into a host `Node` (inverse of encode)."""
    arity = np.asarray(arity)
    op = np.asarray(op)
    feat = np.asarray(feat)
    const = np.asarray(const)
    length = int(length)
    stack: List[Node] = []
    for k in range(length):
        a = int(arity[k])
        if a == 0:
            code = int(op[k])
            if code == LEAF_CONST:
                stack.append(Node.const(float(const[k])))
            elif code == LEAF_VAR:
                stack.append(Node.var(int(feat[k])))
            else:
                stack.append(Node.param(int(feat[k])))
        else:
            children = stack[-a:]
            del stack[-a:]
            stack.append(Node(op=operators[a][int(op[k])], children=children))
    if len(stack) != 1:
        raise ValueError(f"Malformed postfix encoding (stack={len(stack)})")
    return stack[0]


def encode_population(
    trees: Sequence[Node], max_nodes: int, operators: OperatorSet, dtype=np.float32
) -> TreeBatch:
    n = len(trees)
    arity = np.zeros((n, max_nodes), np.int32)
    op = np.zeros((n, max_nodes), np.int32)
    feat = np.zeros((n, max_nodes), np.int32)
    const = np.zeros((n, max_nodes), dtype)
    length = np.zeros((n,), np.int32)
    for i, t in enumerate(trees):
        arity[i], op[i], feat[i], const[i], length[i] = encode_tree(
            t, max_nodes, operators, dtype
        )
    return TreeBatch(
        arity=jnp.asarray(arity),
        op=jnp.asarray(op),
        feat=jnp.asarray(feat),
        const=jnp.asarray(const),
        length=jnp.asarray(length),
    )


def decode_population(batch: TreeBatch, operators: OperatorSet) -> List[Node]:
    """Decode a TreeBatch (flattened over leading dims) into host Nodes."""
    flat = batch.reshape(int(np.prod(batch.batch_shape)) if batch.batch_shape else 1)
    arity = np.asarray(flat.arity)
    op = np.asarray(flat.op)
    feat = np.asarray(flat.feat)
    const = np.asarray(flat.const)
    length = np.asarray(flat.length)
    return [
        decode_tree(arity[i], op[i], feat[i], const[i], length[i], operators)
        for i in range(arity.shape[0])
    ]


# ---------------------------------------------------------------------------
# Device-side structural derivation
# ---------------------------------------------------------------------------


def _tree_structure_single(arity: jax.Array, length: jax.Array):
    """Derive (child, size, depth) for one postfix tree — O(L) scan.

    child[k, j] = slot index of the j-th child of node k (0 where unused);
    size[k] = subtree node count; depth[k] = subtree depth. Padding slots
    produce size 1 / depth 1 / children 0 and are never read by consumers
    that respect ``length``.
    """
    L = arity.shape[0]

    def step(carry, k):
        stack_idx, stack_size, stack_depth, sp = carry
        a = arity[k]
        # children are the top `a` stack entries; child j (1-based left..right)
        # sits at stack position sp - a + j.
        child_k = jnp.zeros((MAX_ARITY,), jnp.int32)
        size_k = jnp.int32(1)
        depth_k = jnp.int32(0)
        for j in range(MAX_ARITY):
            pos = sp - a + j
            valid = j < a
            idx = jnp.where(valid, stack_idx[jnp.maximum(pos, 0)], 0)
            child_k = child_k.at[j].set(jnp.where(valid, idx, 0))
            size_k = size_k + jnp.where(valid, stack_size[jnp.maximum(pos, 0)], 0)
            depth_k = jnp.maximum(
                depth_k, jnp.where(valid, stack_depth[jnp.maximum(pos, 0)], 0)
            )
        depth_k = depth_k + 1
        new_sp = sp - a + 1
        top = new_sp - 1
        stack_idx = stack_idx.at[top].set(k)
        stack_size = stack_size.at[top].set(size_k)
        stack_depth = stack_depth.at[top].set(depth_k)
        return (stack_idx, stack_size, stack_depth, new_sp), (child_k, size_k, depth_k)

    init = (
        jnp.zeros((L,), jnp.int32),
        jnp.zeros((L,), jnp.int32),
        jnp.zeros((L,), jnp.int32),
        jnp.int32(0),
    )
    # Partial unroll: L is small (maxsize ~30) and each step is scalar
    # work; unrolling amortizes loop overhead without the compile-time
    # blowup of a full unroll at every call site.
    _, (child, size, depth) = jax.lax.scan(
        step, init, jnp.arange(L, dtype=jnp.int32), unroll=8
    )
    return child, size, depth


def tree_structure_arrays(batch: TreeBatch):
    """Batched (child, size, depth) derivation; auto-vmaps leading dims."""
    batch_shape = batch.batch_shape
    flat_arity = batch.arity.reshape(-1, batch.max_nodes)
    flat_len = batch.length.reshape(-1)
    child, size, depth = jax.vmap(_tree_structure_single)(flat_arity, flat_len)
    return (
        child.reshape(*batch_shape, batch.max_nodes, MAX_ARITY),
        size.reshape(*batch_shape, batch.max_nodes),
        depth.reshape(*batch_shape, batch.max_nodes),
    )
