"""Postfix tensor encoding of expression-tree populations.

This replaces the reference's pointer-based `Node` populations with padded
arrays so that a whole population is evaluated/mutated in a single XLA
launch (SURVEY.md §7 design delta 1). Trees are stored in depth-first
*post-order* ("postfix"), which has the key property that **every subtree
occupies a contiguous slot range** ``[k - size_k + 1, k]`` — structural
mutations (insert/delete/crossover/rotate) become gather index arithmetic
instead of pointer surgery.

Per-tree arrays (slot axis L = maxsize, padded):

- ``arity[L]``  int32: 0 for leaves, d for arity-d operator nodes. Padding
  slots (``k >= length``) hold arity 0.
- ``op[L]``     int32: for leaves: 0=constant, 1=variable, 2=parameter
  (LEAF_CONST/LEAF_VAR/LEAF_PARAM); for operator nodes: index into the
  OperatorSet's arity-d table.
- ``feat[L]``   int32: feature index for variable leaves (0-based);
  parameter index for parameter leaves.
- ``const[L]``  float: constant value for constant leaves.
- ``length``    int32 scalar: number of used slots; root is ``length - 1``.

A batch stacks these with arbitrary leading dims (population, island, ...).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .operators import OperatorSet
from .tree import Node

__all__ = [
    "LEAF_CONST",
    "LEAF_VAR",
    "LEAF_PARAM",
    "TreeBatch",
    "encode_tree",
    "decode_tree",
    "encode_population",
    "postfix_valid",
    "tree_structure_arrays",
    "lane_take",
]


def lane_take(vals: jax.Array, idx: jax.Array) -> jax.Array:
    """``take_along_axis(vals, idx, axis=-1)`` via one-hot contraction.

    XLA lowers per-lane dynamic gathers on TPU to a serialized custom
    fusion (~70M elements/s measured on v5e — it dominated the mutation
    machinery's cycle cost); for the small minor axes used here (tree
    slot axes, L <= ~64) a compare + masked-sum is bandwidth-bound
    instead, ~50x faster. Out-of-range indices yield 0 (callers clip).

    ``vals`` [..., S], ``idx`` [..., K] (leading dims broadcastable) ->
    [..., K] with vals' dtype.
    """
    S = vals.shape[-1]
    oh = idx[..., :, None] == jnp.arange(S, dtype=idx.dtype)   # [..., K, S]
    return jnp.sum(jnp.where(oh, vals[..., None, :], 0), axis=-1)

LEAF_CONST = 0
LEAF_VAR = 1
LEAF_PARAM = 2

MAX_ARITY = 2  # reference default node degree; bump for n-ary operator sets


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TreeBatch:
    """A batch of postfix-encoded trees (pytree of arrays).

    All fields share leading batch dims; the final axis of the per-slot
    fields is the slot axis L.
    """

    arity: jax.Array  # int32 [..., L]
    op: jax.Array     # int32 [..., L]
    feat: jax.Array   # int32 [..., L]
    const: jax.Array  # float [..., L]
    length: jax.Array  # int32 [...]

    @property
    def max_nodes(self) -> int:
        return self.arity.shape[-1]

    @property
    def batch_shape(self) -> Tuple[int, ...]:
        return self.arity.shape[:-1]

    def reshape(self, *batch_shape) -> "TreeBatch":
        L = self.max_nodes
        return TreeBatch(
            arity=self.arity.reshape(*batch_shape, L),
            op=self.op.reshape(*batch_shape, L),
            feat=self.feat.reshape(*batch_shape, L),
            const=self.const.reshape(*batch_shape, L),
            length=self.length.reshape(*batch_shape),
        )

    def __getitem__(self, idx) -> "TreeBatch":
        return TreeBatch(
            arity=self.arity[idx],
            op=self.op[idx],
            feat=self.feat[idx],
            const=self.const[idx],
            length=self.length[idx],
        )

    @staticmethod
    def empty(batch_shape: Tuple[int, ...], max_nodes: int, dtype=jnp.float32) -> "TreeBatch":
        """All-padding batch of single-constant (0.0) trees."""
        shape = (*batch_shape, max_nodes)
        return TreeBatch(
            arity=jnp.zeros(shape, jnp.int32),
            op=jnp.zeros(shape, jnp.int32),
            feat=jnp.zeros(shape, jnp.int32),
            const=jnp.zeros(shape, dtype),
            length=jnp.ones(batch_shape, jnp.int32),
        )


# ---------------------------------------------------------------------------
# Host encode / decode
# ---------------------------------------------------------------------------


def encode_tree(
    tree: Node, max_nodes: int, operators: OperatorSet, dtype=np.float32
):
    """Encode a host `Node` into per-slot numpy arrays (postfix order)."""
    arity = np.zeros(max_nodes, np.int32)
    op = np.zeros(max_nodes, np.int32)
    feat = np.zeros(max_nodes, np.int32)
    const = np.zeros(max_nodes, dtype)
    k = 0
    for n in tree.nodes():
        if k >= max_nodes:
            raise ValueError(
                f"Tree has more than max_nodes={max_nodes} nodes: "
                f"{tree.count_nodes()}"
            )
        arity[k] = n.degree
        if n.degree == 0:
            if n.is_parameter:
                op[k] = LEAF_PARAM
                feat[k] = n.parameter
            elif n.constant:
                op[k] = LEAF_CONST
                const[k] = n.val
            else:
                op[k] = LEAF_VAR
                feat[k] = n.feature
        else:
            ops_d = operators[n.degree]
            idx = None
            for i, o in enumerate(ops_d):
                if o.name == n.op.name:
                    idx = i
                    break
            if idx is None:
                raise ValueError(
                    f"Operator {n.op.name!r}/{n.degree} not in operator set"
                )
            op[k] = idx
        k += 1
    return arity, op, feat, const, np.int32(k)


def decode_tree(arity, op, feat, const, length, operators: OperatorSet) -> Node:
    """Decode per-slot arrays back into a host `Node` (inverse of encode)."""
    arity = np.asarray(arity)
    op = np.asarray(op)
    feat = np.asarray(feat)
    const = np.asarray(const)
    length = int(length)
    stack: List[Node] = []
    for k in range(length):
        a = int(arity[k])
        if a == 0:
            code = int(op[k])
            if code == LEAF_CONST:
                stack.append(Node.const(float(const[k])))
            elif code == LEAF_VAR:
                stack.append(Node.var(int(feat[k])))
            else:
                stack.append(Node.param(int(feat[k])))
        else:
            children = stack[-a:]
            del stack[-a:]
            stack.append(Node(op=operators[a][int(op[k])], children=children))
    if len(stack) != 1:
        raise ValueError(f"Malformed postfix encoding (stack={len(stack)})")
    return stack[0]


def encode_population(
    trees: Sequence[Node], max_nodes: int, operators: OperatorSet, dtype=np.float32
) -> TreeBatch:
    n = len(trees)
    arity = np.zeros((n, max_nodes), np.int32)
    op = np.zeros((n, max_nodes), np.int32)
    feat = np.zeros((n, max_nodes), np.int32)
    const = np.zeros((n, max_nodes), dtype)
    length = np.zeros((n,), np.int32)
    for i, t in enumerate(trees):
        arity[i], op[i], feat[i], const[i], length[i] = encode_tree(
            t, max_nodes, operators, dtype
        )
    return TreeBatch(
        arity=jnp.asarray(arity),
        op=jnp.asarray(op),
        feat=jnp.asarray(feat),
        const=jnp.asarray(const),
        length=jnp.asarray(length),
    )


def decode_population(batch: TreeBatch, operators: OperatorSet) -> List[Node]:
    """Decode a TreeBatch (flattened over leading dims) into host Nodes."""
    flat = batch.reshape(int(np.prod(batch.batch_shape)) if batch.batch_shape else 1)
    arity = np.asarray(flat.arity)
    op = np.asarray(flat.op)
    feat = np.asarray(flat.feat)
    const = np.asarray(flat.const)
    length = np.asarray(flat.length)
    return [
        decode_tree(arity[i], op[i], feat[i], const[i], length[i], operators)
        for i in range(arity.shape[0])
    ]


def postfix_valid(arity: jax.Array, length: jax.Array) -> jax.Array:
    """Device-side postfix validity predicate, ``[..., L] -> bool [...]``.

    True iff the length is in bounds, every used slot's arity is in
    ``[0, MAX_ARITY]``, padding slots hold arity 0, and the running
    postfix stack height ``D(k) = sum_{j<=k} (1 - arity_j)`` stays >= 1
    over used slots and ends at exactly 1 — equivalently, every subtree
    occupies the contiguous span ``[k - size_k + 1, k]`` and exactly one
    root remains.

    This is the device-cheap structural subset of
    ``lint.runtime.check_programs`` (which also checks op-code/leaf
    payload ranges and produces per-tree diagnoses, at the cost of a
    host pull): usable inside jitted debug paths, e.g. to gate a
    mutation output with ``jnp.where(postfix_valid(...), new, old)`` or
    feed an ``equinox``-style runtime assert.
    """
    L = arity.shape[-1]
    k = jnp.arange(L, dtype=jnp.int32)
    used = k < length[..., None]
    arity_ok = jnp.all(
        jnp.where(used, (arity >= 0) & (arity <= MAX_ARITY), arity == 0),
        axis=-1,
    )
    D = jnp.cumsum(jnp.where(used, 1 - arity, 0), axis=-1)
    no_underflow = jnp.all(jnp.where(used, D >= 1, True), axis=-1)
    root = jnp.clip(length[..., None] - 1, 0, L - 1)
    final = jnp.take_along_axis(D, root, axis=-1)[..., 0]
    len_ok = (length >= 1) & (length <= L)
    return len_ok & arity_ok & no_underflow & (final == 1)


# ---------------------------------------------------------------------------
# Device-side structural derivation
# ---------------------------------------------------------------------------


def _structure_from_arity(arity: jax.Array, need_depth: bool = True):
    """Closed-form (child, size, depth) for postfix trees — no scan.

    Works on any leading batch shape (slot axis last). The postfix stack
    walk is replaced by prefix-sum algebra so the whole derivation is a
    handful of wide ops (plus one [L,L] matmul for depth) instead of an
    O(L) sequential scan — this is on the mutation hot path, where the
    scan version dominated per-cycle time.

    Identities (D = inclusive prefix sum of ``1 - arity``, the running
    postfix stack height):
    - subtree span start: ``s(k) = max{ j <= k : D(j-1) == D(k) - 1 }``
    - subtree size: ``k - s(k) + 1``
    - children (binary): right child root at ``k-1``, left child root at
      ``k - 1 - size(k-1)``; (unary): child at ``k-1``.
    - depth(k) = 1 + max over nodes i in span(k) of the number of
      ancestors of i inside span(k); the ancestor indicator
      ``anc[i,j] = (j > i) & (s(j) <= i)`` makes that one matmul.

    Padding slots (arity 0) yield size 1 / depth 1 / children 0 and are
    never read by consumers that respect ``length``.
    """
    L = arity.shape[-1]
    step = 1 - arity                       # [..., L]
    D = jnp.cumsum(step, axis=-1)          # inclusive
    Dm1 = D - step                         # exclusive (D at k-1)
    j = jnp.arange(L, dtype=jnp.int32)

    # start[k] = last j <= k with Dm1[j] == D[k]-1
    hit = (j <= j[:, None]) & (Dm1[..., None, :] == (D[..., :, None] - 1))
    start = jnp.max(jnp.where(hit, j, -1), axis=-1)
    start = jnp.clip(start, 0, j)          # malformed inputs degrade safely
    size = j - start + 1

    # children from span arithmetic
    size_prev = jnp.roll(size, 1, axis=-1).at[..., 0].set(0)
    right = jnp.maximum(j - 1, 0)
    left = jnp.maximum(j - 1 - size_prev, 0)
    child0 = jnp.where(arity == 2, left, jnp.where(arity == 1, right, 0))
    child1 = jnp.where(arity == 2, right, 0)
    child = jnp.stack([child0, child1], axis=-1).astype(jnp.int32)

    if not need_depth:
        return child, size.astype(jnp.int32), None

    # depth(k) = 1 + max_{i in span(k)} A(i) - A(k), where A(i) is the
    # total proper-ancestor count of node i: ancestors of i inside
    # span(k) are exactly its ancestors beyond those of k itself.
    # (Padding slots j have start[j] = j so they are nobody's ancestor.)
    anc = (j[:, None] < j) & (start[..., None, :] <= j[:, None])  # [..., i, j]
    A_cnt = jnp.sum(anc, axis=-1).astype(jnp.int32)               # [..., i]
    within = (start[..., :, None] <= j) & (j <= j[:, None])       # [..., k, i]
    span_max = jnp.max(
        jnp.where(within, A_cnt[..., None, :], 0), axis=-1
    )
    depth = 1 + span_max - A_cnt
    return child, size.astype(jnp.int32), depth


def _tree_structure_single(arity: jax.Array, length: jax.Array,
                           need_depth: bool = False):
    """(child, size, depth) for one unbatched postfix tree.

    ``depth`` is None unless requested — it is the only output needing
    [L,L] intermediates beyond the span computation, and most callers
    (the mutation kernels) don't use it.
    """
    return _structure_from_arity(arity, need_depth=need_depth)


def tree_structure_arrays(batch: TreeBatch, need_depth: bool = True):
    """Batched (child, size, depth) derivation over any leading dims."""
    return _structure_from_arity(batch.arity, need_depth=need_depth)
