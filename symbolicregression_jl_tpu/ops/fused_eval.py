"""Fused Pallas TPU kernels over compiled tree programs.

This is the framework's hot-op layer (the role LoopVectorization plays
in the reference, /root/reference/src/InterfaceDynamicExpressions.jl:71-81).
The jnp interpreter in ops/eval.py materializes a [T, L, n] value buffer
in HBM and computes *every* operator at every slot; these kernels run
leaf-free TreePrograms (ops/program.py) over a unified VMEM value
buffer instead — one merged-opcode dispatch per internal node, fused
loss/gradient reductions, and HBM traffic limited to the X/y row tiles
plus per-tree scalars.

Kernel families (all sharing the program interpreter):
- `fused_loss` / `fused_loss_program`: mean elementwise loss per tree
  with the reference's invalid ⇒ Inf semantics
  (/root/reference/src/LossFunctions.jl:96-99).
- `fused_loss_multi` / `fused_grad_multi`: a variants axis evaluates V
  constant vectors per compiled tree in ONE instruction-stream dispatch
  — the BFGS line search and restart gradients ride it.
- `fused_grad_program` / `fused_loss_and_const_grad`: forward+backward
  in one kernel, gradients w.r.t. constant leaves (the reference's
  Enzyme/Mooncake role, /root/reference/src/ConstantOptimization.jl:136-167).
- `fused_predict` / `fused_predict_ad`: raw row predictions for
  template call sites, with a custom VJP whose per-member mode also
  emits argument cotangents (composition chains, the template D
  operator).
"""

from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core.losses import baseline_normalization
from .encoding import TreeBatch
from .operators import OperatorSet
from .program import TreeProgram, compile_program

__all__ = ["fused_loss", "fused_loss_program", "fused_loss_multi",
           "fused_loss_dedup", "fused_cost", "fused_cost_program",
           "fused_grad_program", "fused_grad_multi",
           "fused_loss_and_const_grad", "fused_predict",
           "fused_predict_program", "fused_predict_ad",
           "supports_fused_eval", "strided_sample_indices"]


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _pick_tile(n: int, tile_cap: int, vmem_rows: int, bytes_per: int,
               budget: int = 10 * 2**20) -> int:
    """Row-tile size: prefer one tile covering all rows (padded to 1024)
    so the per-slot scalar dispatch overhead is paid once per tree, not
    once per (tree, tile); fall back to smaller tiles on VMEM pressure.

    ``vmem_rows`` = number of TILE-wide scratch rows the kernel keeps
    resident (stack/buffer/adjoint for all trees of a block).
    """
    tile = min(_round_up(n, 1024), _round_up(tile_cap, 1024))
    while tile > 1024 and vmem_rows * tile * bytes_per > budget:
        tile = _round_up(tile // 2, 1024)
    return tile  # floor is 1024 (every branch rounds up to 1024)


def supports_fused_eval(operators: OperatorSet) -> bool:
    """The kernel handles arity <= 2 operator sets (current encoding)."""
    return all(d in (1, 2) for d in operators.ops.keys())


def strided_sample_indices(n_rows: int, sample_rows: int) -> np.ndarray:
    """[sample_rows] int32 row indices for the graftstage screening
    launch: an even stride over the dataset, ``(k * n) // sample_rows``.

    This is the SAME selection the serve overload ladder's sample-shed
    uses (serve/server.py) — deterministic in (n_rows, sample_rows),
    no RNG — so staged screening is replay-stable: a journal replay or
    checkpoint resume re-derives the identical sample from the shapes
    alone. Host-side (static under jit: callers bake the constant into
    the traced program)."""
    k = int(min(sample_rows, n_rows))
    if k <= 0:
        raise ValueError("sample_rows must be positive")
    return ((np.arange(k, dtype=np.int64) * int(n_rows)) // k).astype(
        np.int32)


# ---------------------------------------------------------------------------
# Program kernel: leaf-free interpreter over a unified VMEM value buffer
# ---------------------------------------------------------------------------
#
# See ops/program.py for the lowering. The interpreter state is one
# buffer of row vectors:
#   buf[0:F]        X feature rows (copied once per grid step)
#   buf[F:BASE]     this tree's constant leaves, broadcast across rows
#   buf[BASE+k]     result of program step k
# Steps dispatch ONE merged opcode (identity | unary ops | binary ops)
# via lax.switch; operands are uniform dynamic reads buf[src], so leaf
# handling, the arity switch, and the per-operand source selects all
# disappear from the inner loop. Steps per tree = internal nodes only.


def _merged_branches(operators: OperatorSet, read, i1, i2):
    """Branch list for the legacy opcode switch at one program step.

    Order matches ops/program.py's code assignment: 0 = identity (for
    leaf-only trees), then binary ops (the most frequent class — the
    switch tests codes in order), then unary. Operand reads (``read`` is
    the kernel's buffer accessor) live inside each branch so unary steps
    never touch src2.
    """
    branches = [lambda: read(i1)]
    for o in operators.binary:
        branches.append(lambda f=o.fn: f(read(i1), read(i2)))
    for o in operators.unary:
        branches.append(lambda f=o.fn: f(read(i1)))
    return branches


def _unpack(w):
    """Instruction word -> (opcode, src1, src2); see pack in the wrappers."""
    return w >> 24, (w >> 12) & 0xFFF, w & 0xFFF


class _DispatchPlan(NamedTuple):
    """Branch layout of the in-kernel opcode switch.

    Each switch branch costs real scalar-core time per step whether or
    not it's taken (measured ~1 ms per branch per 65k steps at 10k rows
    — profiling/kernel_variants.py), so the plan trims the branch list
    where operator algebra allows:

    - ``merged``: '+' is present, so the identity branch (used only by
      leaf-only trees / pad rows) is eliminated by rewriting `copy(a)`
      as `a + ZERO` against a guaranteed-zero buffer row, and — when '-'
      is present too — `a - b` rides the SAME branch as `a + b` via a
      sign bit in the instruction word (`a + sgn*b`, one FMA).
    - non-merged (no '+'): the legacy layout (identity, binaries,
      unaries) is kept unchanged.

    Packed word, merged: sign << 30 | code << 24 | src1 << 12 | src2
    (codes 6-bit; bit 31 stays clear for the arithmetic unpack shift).
    """

    merged: bool
    has_sub: bool
    n_branches: int                 # total switch branches
    nb_class: int                   # branches in the binary class
    other_bin: Tuple[int, ...]      # operators.binary indices, new-code order
    old2new: Tuple[int, ...]        # legacy code -> new code
    sign_old: Tuple[int, ...]       # legacy code -> sign bit


@functools.lru_cache(maxsize=None)
def _dispatch_plan(operators: OperatorSet) -> _DispatchPlan:
    names = [op.name for op in operators.binary]
    B, U = len(operators.binary), len(operators.unary)
    n_old = 1 + B + U
    if "+" not in names:
        return _DispatchPlan(False, False, n_old, 1 + B,
                             tuple(range(B)), tuple(range(n_old)),
                             (0,) * n_old)
    add_i = names.index("+")
    sub_i = names.index("-") if "-" in names else None
    old2new = [0] * n_old
    sign_old = [0] * n_old
    other = []
    nxt = 1
    for j in range(B):
        if j == add_i:
            old2new[1 + j] = 0
        elif sub_i is not None and j == sub_i:
            old2new[1 + j] = 0
            sign_old[1 + j] = 1
        else:
            old2new[1 + j] = nxt
            other.append(j)
            nxt += 1
    nb_class = nxt
    for u in range(U):
        old2new[1 + B + u] = nb_class + u
    return _DispatchPlan(True, sub_i is not None, nb_class + U, nb_class,
                         tuple(other), tuple(old2new), tuple(sign_old))


def _pack_instr(prog: TreeProgram, operators: OperatorSet,
                zero_addr: int) -> jax.Array:
    """[T, L] int32 instruction words for the plan's dispatch layout."""
    plan = _dispatch_plan(operators)
    if not plan.merged:
        return (prog.code << 24) | (prog.src1 << 12) | prog.src2
    # Where-chain remap, NOT jnp.take: an XLA gather over [T, L] lanes
    # serializes on TPU (~20 ms per pack at the bench shapes — measured
    # as a net bench regression before this was switched).
    code = prog.code
    new_code = jnp.zeros_like(code)
    for old, nc in enumerate(plan.old2new):
        if nc != 0:
            new_code = jnp.where(code == old, jnp.int32(nc), new_code)
    sign = jnp.zeros_like(code)
    for old, sg in enumerate(plan.sign_old):
        if sg:
            sign = jnp.where(code == old, jnp.int32(1), sign)
    # identity (legacy code 0, leaf-only trees and pad rows) becomes
    # `src1 + ZERO`; the kernels keep a zeroed row at ``zero_addr``.
    src2 = jnp.where(code == 0, jnp.int32(zero_addr), prog.src2)
    return (sign << 30) | (new_code << 24) | (prog.src1 << 12) | src2


def _fwd_dispatch(operators: OperatorSet, read, w, dtype):
    """One program step's value: unpack ``w``, dispatch the merged opcode.

    With a bfloat16 value buffer the step COMPUTES in f32 (Mosaic's
    transcendentals and comparisons are f32-only, and VPU arithmetic
    runs at f32 width anyway) — operands upcast on read and the f32
    result is returned; the caller downcasts at the buffer store, so
    only the VMEM residency is halved.
    """
    compute32 = dtype == jnp.bfloat16
    if compute32:
        rd = lambda i: read(i).astype(jnp.float32)
        cdt = jnp.float32
    else:
        rd = read
        cdt = dtype
    plan = _dispatch_plan(operators)
    if not plan.merged:
        o, i1, i2 = _unpack(w)
        return jax.lax.switch(o, _merged_branches(operators, rd, i1, i2))
    o = (w >> 24) & 0x3F
    i1 = (w >> 12) & 0xFFF
    i2 = w & 0xFFF
    if plan.has_sub:
        s = (w >> 30) & 1
        addsub = lambda: rd(i1) + (1 - 2 * s).astype(cdt) * rd(i2)
    else:
        addsub = lambda: rd(i1) + rd(i2)
    branches = [addsub]
    for j in plan.other_bin:
        branches.append(lambda f=operators.binary[j].fn: f(rd(i1), rd(i2)))
    for op in operators.unary:
        branches.append(lambda f=op.fn: f(rd(i1)))
    return jax.lax.switch(o, branches)


def _bwd_dispatch(operators: OperatorSet, read, w, ct, mask_row,
                  store1, store2):
    """Adjoint of one program step: cotangents for its operand(s).

    ``store1(addr, val)`` / ``store2(addr, val)`` write the operand
    cotangents (the two backward kernels differ in store semantics —
    plain vs X-region-accumulating). Padded rows carry zero cotangents
    but arbitrary operand values, so vjps can produce 0/0 = NaN there;
    values are masked with ``mask_row`` before storing (one NaN would
    poison the gradient sums).
    """
    plan = _dispatch_plan(operators)
    binary_fns = tuple(op.fn for op in operators.binary)
    unary_fns = tuple(op.fn for op in operators.unary)
    mask01 = lambda v: jnp.where(mask_row, v, 0.0)

    if not plan.merged:
        o, i1, i2 = _unpack(w)
        B = len(binary_fns)

        @pl.when(o == 0)
        def _():
            store1(i1, ct)

        if binary_fns:
            @pl.when((o >= 1) & (o <= B))
            def _():
                x1 = read(i1)
                x2 = read(i2)
                if len(binary_fns) == 1:
                    db1, db2 = _vjp_binary(binary_fns[0], x1, x2, ct)
                else:
                    db1, db2 = jax.lax.switch(
                        o - 1,
                        [lambda xx, yy, cc, f=f: _vjp_binary(f, xx, yy, cc)
                         for f in binary_fns], x1, x2, ct)
                store1(i1, mask01(db1))
                store2(i2, mask01(db2))

        if unary_fns:
            @pl.when(o > B)
            def _():
                x1 = read(i1)
                if len(unary_fns) == 1:
                    du = _vjp_unary(unary_fns[0], x1, ct)
                else:
                    du = jax.lax.switch(
                        o - 1 - B,
                        [lambda xx, cc, f=f: _vjp_unary(f, xx, cc)
                         for f in unary_fns], x1, ct)
                store1(i1, mask01(du))
        return

    o = (w >> 24) & 0x3F
    i1 = (w >> 12) & 0xFFF
    i2 = w & 0xFFF
    s = (w >> 30) & 1
    NBc = plan.nb_class

    @pl.when(o < NBc)
    def _():
        x1 = read(i1)
        x2 = read(i2)

        def addsub_vjp(xx, yy, cc):
            # d(a + sgn*b) = (ct, sgn*ct); identity rows (b = ZERO) send
            # sgn*ct into the zero row's adjoint, which is never read.
            del xx, yy
            if plan.has_sub:
                return cc, (1 - 2 * s).astype(cc.dtype) * cc
            return cc, cc

        fns = [addsub_vjp] + [
            lambda xx, yy, cc, f=binary_fns[j]: _vjp_binary(f, xx, yy, cc)
            for j in plan.other_bin]
        if len(fns) == 1:
            db1, db2 = fns[0](x1, x2, ct)
        else:
            db1, db2 = jax.lax.switch(o, fns, x1, x2, ct)
        store1(i1, mask01(db1))
        store2(i2, mask01(db2))

    if unary_fns:
        @pl.when(o >= NBc)
        def _():
            x1 = read(i1)
            if len(unary_fns) == 1:
                du = _vjp_unary(unary_fns[0], x1, ct)
            else:
                du = jax.lax.switch(
                    o - NBc,
                    [lambda xx, cc, f=f: _vjp_unary(f, xx, cc)
                     for f in unary_fns], x1, ct)
            store1(i1, mask01(du))


def _zero_rows(operators: OperatorSet) -> int:
    """Extra buffer rows for the dispatch plan (1 zero row when merged)."""
    return 1 if _dispatch_plan(operators).merged else 0


def _check_packable(operators: OperatorSet, base: int, max_steps: int) -> None:
    """Fail loudly (at trace time) when a configuration overflows the
    packed fields: 12-bit operand addresses (incl. the zero row at
    ``base + max_steps`` for merged plans), 6-bit opcodes when merged /
    7-bit legacy (bit 31 must stay clear — the unpack uses an
    arithmetic shift)."""
    plan = _dispatch_plan(operators)
    if base + max_steps + _zero_rows(operators) > 4096:
        raise ValueError(
            f"Buffer address space {base + max_steps + _zero_rows(operators)} "
            f"exceeds the packed 12-bit operand field "
            f"(nfeatures + cmax + max_nodes <= 4096)."
        )
    if plan.merged and plan.n_branches > 63:
        raise ValueError(
            f"{plan.n_branches} merged opcodes exceed the packed 6-bit field.")
    if not plan.merged and plan.n_branches > 127:
        raise ValueError(
            f"{plan.n_branches} opcodes exceed the packed 7-bit field.")


def _make_program_kernel(
    operators: OperatorSet,
    loss_fn: Callable,
    tree_block: int,
    nfeat: int,
    cmax: int,
    nparam: int = 0,
    nclass: int = 0,
    cost_epilogue: bool = False,
):
    CBASE = nfeat + nparam
    BASE = CBASE + cmax

    def kernel(*refs):
        i = 4
        instr_ref, nstep_ref, cvals_ref, ok_ref = refs[:4]
        if nparam > 0:
            pbank_ref = refs[i]  # SMEM [TB, NP * NC] f32 param banks
            i += 1
        x_ref = refs[i]
        i += 1
        if nparam > 0:
            clsoh_ref = refs[i]  # VMEM [NC, TILE] f32 class one-hots
            i += 1
        y_ref, w_ref, mask_ref = refs[i:i + 3]
        i += 3
        if cost_epilogue:
            # SMEM: per-tree complexity (as the buffer dtype) and the
            # [denom, normalization, parsimony] scalar triple.
            cx_ref, scal_ref = refs[i:i + 2]
            i += 2
        loss_ref, valid_ref = refs[i:i + 2]
        i += 2
        if cost_epilogue:
            cost_ref = refs[i]
            i += 1
        buf_ref = refs[i]
        j = pl.program_id(1)
        y_row = y_ref[0, :]
        mask_row = mask_ref[0, :] > 0
        w_row = w_ref[0, :] * mask_ref[0, :]
        tile = y_row.shape[0]
        L = instr_ref.shape[-1]

        # The value buffer may be bfloat16 (graftstage eval_precision,
        # docs/PRECISION.md): steps then COMPUTE in f32 (_fwd_dispatch
        # upcasts on read) and downcast at the buffer store, while y/w
        # and the loss/cost accumulators keep the operand dtype — the
        # f32 reduction spine. With an f32 buffer every astype below is
        # a no-op, keeping that path bit-identical.
        bdt = buf_ref.dtype
        buf_ref[0:nfeat, :] = x_ref[...]
        if _dispatch_plan(operators).merged:
            buf_ref[BASE + L, :] = jnp.zeros((tile,), bdt)

        for t in range(tree_block):
            if nparam > 0:
                # Param region: per-row values selected by class —
                # bank[t, p, c] summed over the class one-hot rows
                # (ParametricExpression eval,
                # /root/reference/src/ParametricExpression.jl:63-73).
                for p_i in range(nparam):
                    row = clsoh_ref[0, :] * pbank_ref[t, p_i * nclass]
                    for c in range(1, nclass):
                        row = row + (clsoh_ref[c, :]
                                     * pbank_ref[t, p_i * nclass + c])
                    buf_ref[nfeat + p_i, :] = row.astype(bdt)

            # Static-unrolled const preload: at nconst == cmax the
            # dynamic fori_loop(0, nconst) costs ~420 ns/tree of scalar
            # loop bookkeeping (profiling/kernel_variants.py `custatic`,
            # 1.21x); evolved programs average 2-3 consts so the
            # in-engine effect is neutral-to-positive. Rows past nconst
            # hold zero-padding and are never addressed.
            for c in range(cmax):
                buf_ref[CBASE + c, :] = jnp.full(
                    (tile,), cvals_ref[t, c], dtype=bdt)

            def step(k, vmask):
                val = _fwd_dispatch(
                    operators, lambda i: buf_ref[i, :], instr_ref[t, k],
                    bdt)
                buf_ref[BASE + k, :] = val.astype(bdt)
                return vmask * jnp.isfinite(val).astype(vmask.dtype)

            m = nstep_ref[t, 0]
            # Plain loop: a 2x pair-unroll with a min-clamped tail was
            # measured SLOWER than the loop bookkeeping it saves
            # (profiling/kernel_variants.py, `nounroll`).
            vmask0 = jnp.ones((tile,), y_row.dtype)
            vmask = jax.lax.fori_loop(0, m, step, vmask0)
            valid = jnp.all((vmask > 0) | jnp.logical_not(mask_row))
            pred = buf_ref[BASE + m - 1, :].astype(y_row.dtype)
            elt = loss_fn(pred, y_row)
            elt = jnp.where(w_row > 0, elt, 0.0)
            partial = jnp.sum(elt * w_row)
            partial_ok = jnp.int32(valid & jnp.isfinite(partial)) * ok_ref[t, 0]

            @pl.when(j == 0)
            def _():
                loss_ref[t, 0] = partial
                valid_ref[t, 0] = partial_ok

            @pl.when(j != 0)
            def _():
                loss_ref[t, 0] = loss_ref[t, 0] + partial
                valid_ref[t, 0] = valid_ref[t, 0] & partial_ok

            if cost_epilogue:
                # Cost epilogue, run once per tree on the LAST row tile
                # (grid dim 1 iterates innermost, so the accumulators
                # above are complete): finalize the mean, apply the
                # invalid => inf contract, and emit
                # cost = loss / normalization + parsimony * complexity
                # (core.losses.loss_to_cost, same op order for bit
                # parity) — the [T]-shaped XLA dispatch chain that
                # otherwise runs per evolve cycle disappears into the
                # kernel's scalar core.
                @pl.when(j == pl.num_programs(1) - 1)
                def _():
                    ok = valid_ref[t, 0] > 0
                    mean = loss_ref[t, 0] / scal_ref[0, 0]
                    lossf = jnp.where(
                        ok & jnp.isfinite(mean), mean,
                        jnp.asarray(jnp.inf, mean.dtype))
                    loss_ref[t, 0] = lossf
                    cost_ref[t, 0] = (lossf / scal_ref[0, 1]
                                      + scal_ref[0, 2] * cx_ref[t, 0])

    return kernel


def _program_launch(
    prog: TreeProgram,          # flat [T, L] program
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    params: Optional[jax.Array],     # [T, NP, NC] member banks
    class_oh: Optional[jax.Array],   # [NC, n] class one-hots
    complexity: Optional[jax.Array],  # [T] — enables the cost epilogue
    cost_scal: Optional[jax.Array],   # [1, 3] (denom, norm, parsimony)
    tree_block: int,
    tile_rows: int,
    bf16: bool,
    interpret: bool,
):
    """Shared single-variant launch: the loss path (complexity=None)
    returns (loss, valid); the cost-epilogue path also returns cost.

    ``bf16`` runs the value buffer (X rows, constants, step results) in
    bfloat16 — VMEM residency halves so row tiles grow under the same
    budget — while the per-step arithmetic upcasts to f32 (Mosaic
    transcendentals are f32-only anyway) and the loss/cost epilogue
    keeps the f32 reduction spine; see fused_loss_multi's bf16 contract:
    losses RANK reliably (f32 exponent range, ~3 significant digits) but
    are not bit-exact — quality-gated callers only (docs/PRECISION.md)."""
    T, L = prog.code.shape
    CMAX = prog.cmax
    F, n = X.shape
    dtype = X.dtype
    buf_dtype = jnp.bfloat16 if bf16 else dtype
    NP = 0 if params is None else params.shape[-2]
    NC = 0 if params is None else params.shape[-1]
    BASE = nfeatures + NP + CMAX
    _check_packable(operators, BASE, L)

    TB = tree_block
    bytes_per = jnp.dtype(buf_dtype).itemsize
    ZR = _zero_rows(operators)
    TILE = _pick_tile(n, tile_rows, BASE + L + ZR, bytes_per)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_t(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    instr = pad_t(_pack_instr(prog, operators, BASE + L))
    nsteps = pad_t(prog.nsteps.reshape(-1, 1), fill=1)
    cvals = pad_t(prog.cvals).astype(dtype)
    ok = pad_t(prog.const_ok.astype(jnp.int32).reshape(-1, 1), fill=1)

    Xp = jnp.pad(X.astype(buf_dtype), ((0, 0), (0, n_pad - n)))
    yp = jnp.pad(y.reshape(1, n), ((0, 0), (0, n_pad - n)))
    w = (jnp.ones((1, n), dtype) if weights is None
         else weights.reshape(1, n).astype(dtype))
    wp = jnp.pad(w, ((0, 0), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    fuse_cost = complexity is not None
    kernel = _make_program_kernel(operators, loss_fn, TB, nfeatures, CMAX,
                                  NP, NC, cost_epilogue=fuse_cost)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )
    row_spec = pl.BlockSpec((1, TILE), lambda i, j: (0, j))

    in_specs = [
        smem_i32((TB, L)),                       # instr
        smem_i32((TB, 1)),                       # nsteps
        pl.BlockSpec((TB, CMAX), lambda i, j: (i, 0),
                     memory_space=pltpu.SMEM),   # cvals
        smem_i32((TB, 1)),                       # const_ok
    ]
    operands = [instr, nsteps, cvals, ok]
    if NP > 0:
        in_specs.append(pl.BlockSpec((TB, NP * NC), lambda i, j: (i, 0),
                                     memory_space=pltpu.SMEM))  # pbank
        operands.append(pad_t(params.reshape(T, NP * NC)).astype(dtype))
    in_specs.append(pl.BlockSpec((F, TILE), lambda i, j: (0, j)))  # X
    operands.append(Xp)
    if NP > 0:
        in_specs.append(pl.BlockSpec((NC, TILE), lambda i, j: (0, j)))
        operands.append(
            jnp.pad(class_oh.astype(buf_dtype), ((0, 0), (0, n_pad - n))))
    in_specs += [row_spec, row_spec, row_spec]   # y, w, mask
    operands += [yp, wp, maskp]
    if fuse_cost:
        in_specs.append(pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                                     memory_space=pltpu.SMEM))  # complexity
        operands.append(pad_t(complexity.reshape(-1, 1).astype(dtype)))
        in_specs.append(pl.BlockSpec((1, 3), lambda i, j: (0, 0),
                                     memory_space=pltpu.SMEM))  # scalars
        operands.append(cost_scal.astype(dtype))

    out_specs = [
        pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                     memory_space=pltpu.SMEM),
        pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                     memory_space=pltpu.SMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((T_pad, 1), dtype),
        jax.ShapeDtypeStruct((T_pad, 1), jnp.int32),
    ]
    if fuse_cost:
        out_specs.append(pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                                      memory_space=pltpu.SMEM))
        out_shape.append(jax.ShapeDtypeStruct((T_pad, 1), dtype))

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((BASE + L + ZR, TILE), buf_dtype)],
        interpret=interpret,
    )(*operands)

    valid = out[1][:T, 0].astype(jnp.bool_)
    if fuse_cost:
        # loss/cost were finalized in-kernel (mean + invalid => inf).
        return out[2][:T, 0], out[0][:T, 0], valid
    loss_sum = out[0][:T, 0]
    denom = jnp.sum(w) if weights is not None else jnp.asarray(n, dtype)
    loss = loss_sum / denom
    loss = jnp.where(valid & jnp.isfinite(loss), loss, jnp.inf)
    return loss, valid


@functools.partial(
    jax.jit,
    static_argnames=(
        "nfeatures", "operators", "loss_fn", "tree_block", "tile_rows",
        "bf16", "interpret",
    ),
)
def fused_loss_program(
    prog: TreeProgram,          # flat [T, L] program
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    params: Optional[jax.Array] = None,     # [T, NP, NC] member banks
    class_oh: Optional[jax.Array] = None,   # [NC, n] class one-hots
    tree_block: int = 16,
    tile_rows: int = 16384,
    bf16: bool = False,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Mean elementwise loss per compiled tree program (flat [T]).

    Parametric trees pass per-member banks + class one-hot rows; the
    program must have been compiled with the matching ``n_params``."""
    return _program_launch(
        prog, X, y, weights, nfeatures, operators, loss_fn, params,
        class_oh, None, None, tree_block, tile_rows, bf16, interpret)


@functools.partial(
    jax.jit,
    static_argnames=(
        "nfeatures", "operators", "loss_fn", "tree_block", "tile_rows",
        "bf16", "interpret",
    ),
)
def fused_cost_program(
    prog: TreeProgram,          # flat [T, L] program
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    complexity: jax.Array,      # [T] int32 per-tree complexity
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    baseline_loss: jax.Array,   # scalar (dataset baseline)
    use_baseline: jax.Array,    # bool scalar
    parsimony,                  # float (or scalar array)
    tree_block: int = 16,
    tile_rows: int = 16384,
    bf16: bool = False,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(cost, loss, valid) per compiled program, cost fused in-kernel.

    The cost epilogue replicates ``core.losses.loss_to_cost`` (baseline
    normalization with the 0.01 floor + the parsimony complexity
    penalty) on the kernel's final row tile, so candidate evaluation
    emits (T,)-shaped cost/loss with no post-kernel XLA dispatches.
    Non-parametric programs only (the parametric const_ok fixup needs
    the loss before the inf mapping)."""
    dtype = X.dtype
    n = X.shape[1]
    # Same reshape/astype-then-sum as the loss path's denominator so the
    # two paths stay bit-identical.
    denom = (jnp.sum(weights.reshape(1, n).astype(dtype))
             if weights is not None else jnp.asarray(n, dtype))
    norm = baseline_normalization(baseline_loss, use_baseline, dtype)
    scal = jnp.stack([
        denom.astype(dtype), norm.astype(dtype),
        jnp.asarray(parsimony, dtype),
    ]).reshape(1, 3)
    return _program_launch(
        prog, X, y, weights, nfeatures, operators, loss_fn, None, None,
        complexity, scal, tree_block, tile_rows, bf16, interpret)


# ---------------------------------------------------------------------------
# Multi-variant program kernel: one dispatch, V constant vectors
# ---------------------------------------------------------------------------
#
# The BFGS line search evaluates every selected tree with R*C different
# constant vectors per iteration; replicating the tree per variant pays
# the (dominant) per-step scalar dispatch cost V times for identical
# instruction streams. Here the value buffer grows a variants axis —
# buf[slot, v, rows] — so each step's single dispatch drives V row
# vectors: dispatch cost per *eval* drops ~V-fold while the vector work
# is the same total. X rows are replicated across v (variant-independent
# but kept in the unified address space); only the const region differs.


def _make_multi_kernel(
    operators: OperatorSet,
    loss_fn: Callable,
    tree_block: int,
    nfeat: int,
    cmax: int,
    nvar: int,
):
    BASE = nfeat + cmax
    V = nvar

    def kernel(
        instr_ref,   # SMEM [TB, L]
        nstep_ref,   # SMEM [TB, 1]
        nconst_ref,  # SMEM [TB, 1]
        cvals_ref,   # SMEM [TB, V * CMAX] f32 (variant-major)
        x_ref,       # VMEM [F, TILE] (buffer dtype)
        y_ref,       # VMEM [1, TILE] f32
        w_ref,       # VMEM [1, TILE] f32
        mask_ref,    # VMEM [1, TILE] f32
        loss_ref,    # VMEM out [TB, V] f32
        valid_ref,   # VMEM out [TB, V] int32
        buf_ref,     # VMEM scratch [BASE + L + 1, V, TILE] (f32 or bf16)
    ):
        j = pl.program_id(1)
        y_row = y_ref[0, :]
        mask_row = mask_ref[0, :] > 0
        w_row = w_ref[0, :] * mask_ref[0, :]
        tile = y_row.shape[0]
        L = instr_ref.shape[-1]
        bdt = buf_ref.dtype

        buf_ref[0:nfeat, :, :] = jnp.broadcast_to(
            x_ref[...][:, None, :], (nfeat, V, tile))
        if _dispatch_plan(operators).merged:
            buf_ref[BASE + L, :, :] = jnp.zeros((V, tile), bdt)

        for t in range(tree_block):
            # Dynamic const preload: the single-variant kernels win by
            # static-unrolling this loop, but here the V-variant stores
            # already amortize the scalar loop bookkeeping and the
            # stacked-scalar broadcast variant measured SLOWER (phase
            # optimizer 4.61 -> 4.89 s/iter; profiling/RESULTS.md r4).
            def cbody(c, _):
                for v in range(V):
                    buf_ref[nfeat + c, v, :] = jnp.full(
                        (tile,), cvals_ref[t, v * cmax + c], dtype=bdt)
                return 0

            jax.lax.fori_loop(0, nconst_ref[t, 0], cbody, 0)

            def step(k, vmask):
                # dispatch computes in f32; the store downcasts. The
                # finiteness check runs on the f32 value (bf16 compares
                # don't lower) — a value that only overflows at the bf16
                # downcast surfaces one step later, or in the final loss.
                val = _fwd_dispatch(
                    operators, lambda i: buf_ref[i, :, :], instr_ref[t, k],
                    bdt)
                buf_ref[BASE + k, :, :] = val.astype(bdt)
                return vmask * jnp.isfinite(val).astype(vmask.dtype)

            m = nstep_ref[t, 0]
            vmask0 = jnp.ones((V, tile), y_row.dtype)
            vmask = jax.lax.fori_loop(0, m, step, vmask0)
            validv = jnp.all(
                (vmask > 0) | jnp.logical_not(mask_row)[None, :], axis=1)
            # Loss in f32 regardless of the buffer dtype: the tree is
            # evaluated in ``bdt``, the elementwise loss and row
            # reduction accumulate at full precision.
            pred = buf_ref[BASE + m - 1, :, :].astype(y_row.dtype)
            elt = loss_fn(pred, y_row[None, :])
            elt = jnp.where(w_row[None, :] > 0, elt, 0.0)
            partial = jnp.sum(elt * w_row[None, :], axis=1)  # [V]
            partial_ok = (validv & jnp.isfinite(partial)).astype(jnp.int32)

            @pl.when(j == 0)
            def _():
                loss_ref[t, :] = partial
                valid_ref[t, :] = partial_ok

            @pl.when(j != 0)
            def _():
                loss_ref[t, :] = loss_ref[t, :] + partial
                valid_ref[t, :] = valid_ref[t, :] & partial_ok

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "nfeatures", "operators", "loss_fn", "tree_block", "bf16",
        "interpret", "tile_budget", "v_chunk",
    ),
)
def fused_loss_multi(
    prog: TreeProgram,          # flat [T, L] program
    cvals_v: jax.Array,         # [T, V, CMAX] constant vectors per variant
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    bf16: bool = False,
    interpret: bool = False,
    tile_budget: int = 8 * 2**20,
    v_chunk: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Mean loss for every (tree, constant-variant) pair: [T, V] each.

    One instruction-stream dispatch per tree serves all V variants;
    invalid pairs (non-finite eval or non-finite constants) get inf.

    The dominant cost is per-step dispatch, paid once per (V-chunk ×
    row-tile); VMEM caps V_chunk × TILE. Large V is processed in chunks
    of 8 (f32) — measured sweet spot on v5e.

    ``bf16``: the value buffer (and the tree evaluation) run in
    bfloat16, halving VMEM per variant so chunks double to 16 and the
    per-step dispatch cost per eval halves; the elementwise loss and row
    reduction still accumulate in f32. bf16 carries f32's exponent range
    (~3 significant digits), so losses rank reliably but fine
    loss *differences* are noisy — callers must re-verify accepted
    points at f32 (the BFGS line search recomputes f at the accepted
    step via the f32 gradient kernel and rejects non-descent).
    """
    V = cvals_v.shape[1]
    T, L = prog.code.shape
    CMAX = prog.cmax
    F, n = X.shape
    dtype = X.dtype
    buf_dtype = jnp.bfloat16 if bf16 else dtype
    BASE = nfeatures + CMAX
    rows = BASE + L + _zero_rows(operators)
    bytes_per = jnp.dtype(buf_dtype).itemsize

    # Chunks of 8 (f32) / 16 (bf16): the obvious "fewer dispatch passes"
    # alternatives were measured NEUTRAL-or-worse on the bench at the
    # 8 MB budget — one f32 V=24 chunk at 2.5k-row tiles (4 passes vs 6)
    # lands within noise of this plan (per-pass fixed costs offset the
    # saved dispatches), and bf16 V=16 chunks lose outright to per-step
    # bf16<->f32 relayouts. ``v_chunk`` overrides for callers that pair
    # it with a larger ``tile_budget`` (see OptimizerConfig).
    VCH = v_chunk if v_chunk is not None else (16 if bf16 else 8)
    if V > VCH:
        outs = [
            fused_loss_multi(
                prog, cvals_v[:, v0:v0 + VCH], X, y, weights, nfeatures,
                operators, loss_fn, tree_block=tree_block, bf16=bf16,
                interpret=interpret, tile_budget=tile_budget,
                v_chunk=v_chunk)
            for v0 in range(0, V, VCH)
        ]
        return (jnp.concatenate([o[0] for o in outs], axis=1),
                jnp.concatenate([o[1] for o in outs], axis=1))
    _check_packable(operators, BASE, L)

    TB = tree_block
    # bf16 tiles the (V, TILE) plane in (16, 128) blocks — size VMEM by
    # the sublane-padded variant count.
    V_phys = _round_up(V, 16) if bf16 else V
    TILE = _pick_tile(n, n, rows * V_phys, bytes_per, budget=tile_budget)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_t(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    instr = pad_t(_pack_instr(prog, operators, BASE + L))
    nsteps = pad_t(prog.nsteps.reshape(-1, 1), fill=1)
    nconst = pad_t(prog.nconst.reshape(-1, 1))
    cflat = pad_t(cvals_v.reshape(T, V * CMAX)).astype(dtype)

    Xp = jnp.pad(X.astype(buf_dtype), ((0, 0), (0, n_pad - n)))
    yp = jnp.pad(y.reshape(1, n), ((0, 0), (0, n_pad - n)))
    w = (jnp.ones((1, n), dtype) if weights is None
         else weights.reshape(1, n).astype(dtype))
    wp = jnp.pad(w, ((0, 0), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_multi_kernel(operators, loss_fn, TB, nfeatures, CMAX, V)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )
    row_spec = pl.BlockSpec((1, TILE), lambda i, j: (0, j))

    loss_sum, valid = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, L)),                       # instr
            smem_i32((TB, 1)),                       # nsteps
            smem_i32((TB, 1)),                       # nconst
            pl.BlockSpec((TB, V * CMAX), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),   # cvals
            pl.BlockSpec((F, TILE), lambda i, j: (0, j)),  # X
            row_spec,                                # y
            row_spec,                                # w
            row_spec,                                # mask
        ],
        out_specs=[
            pl.BlockSpec((TB, V), lambda i, j: (i, 0)),
            pl.BlockSpec((TB, V), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, V), dtype),
            jax.ShapeDtypeStruct((T_pad, V), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((rows, V, TILE), buf_dtype)],
        interpret=interpret,
    )(instr, nsteps, nconst, cflat, Xp, yp, wp, maskp)

    loss_sum = loss_sum[:T]
    valid = valid[:T].astype(jnp.bool_)
    # const_ok per variant, applied outside the kernel
    used = (jnp.arange(CMAX, dtype=jnp.int32)[None, None, :]
            < prog.nconst[:, None, None])
    ok_v = jnp.all(jnp.isfinite(cvals_v) | ~used, axis=-1)
    valid = valid & ok_v
    denom = jnp.sum(w) if weights is not None else jnp.asarray(n, dtype)
    loss = loss_sum / denom
    loss = jnp.where(valid & jnp.isfinite(loss), loss, jnp.inf)
    return loss, valid


# ---------------------------------------------------------------------------
# Identical-program dedup: evaluate each unique (structure, constants) once
# ---------------------------------------------------------------------------
#
# Evolved candidate batches repeat programs heavily (tournament
# re-picks, kept-parent fallbacks, migration copies, converged
# populations): profiling/dup_rate.py measures ~50% duplicate
# (code, src1, src2, nsteps) rows and ~33% FULLY identical rows
# (constants included) across the bench config's flattened per-cycle
# eval batch. Fully identical rows produce bit-identical losses, so
# only group leaders need to execute: duplicates degenerate to 1-step
# programs and copy the leader's (loss, valid) via a segment
# fill-forward scan. No compaction — the row count stays T (static
# shapes), only dispatch/vector work shrinks.
#
# (A variants-axis packing of structure-only duplicates through
# `fused_loss_multi` was built and measured first: the multi kernel's
# per-variant marginal cost is ~41% of a full dispatch stream at
# TILE=10k — V=4 packing LOSES on the ~80% of rows that are unique.
# Full-identity dedup has zero per-row overhead and is exact.)

# Fixed odd multipliers for the 3 independent linear hashes (int32
# wraparound math; hash collisions only affect sort adjacency — the
# grouping below is exact-verified on the sorted rows). Module-level
# fixed-seed constant, deterministic by construction — not search RNG.
_HASH_R = np.random.default_rng(0xC0FFEE).integers(  # graftlint: disable=GL002
    1, 2**31, size=(3, 4096), dtype=np.int64).astype(np.int32) | 1


def _sort_rows_by(keys3, payloads, width):
    """Stable-sort [T, width] payload rows by three [T] int32 keys.

    Broadcasting the keys across the row axis and sorting along axis 0
    permutes every column identically (stable sort, equal keys per
    column) — the TPU-friendly way to co-permute rows without a
    serialized gather."""
    ops = [jnp.broadcast_to(k[:, None], (k.shape[0], width))
           for k in keys3] + list(payloads)
    out = jax.lax.sort(ops, dimension=0, num_keys=3, is_stable=True)
    return out[3:]


def _fill_forward_segments(start, values):
    """Propagate each segment leader's values to the whole segment.

    ``start`` [T] bool marks segment starts in sorted order; ``values``
    is a pytree of [T] arrays whose entries are meaningful at starts.
    Associative "last leader wins" scan — no gathers."""
    def combine(a, b):
        a_vals, a_start = a
        b_vals, b_start = b
        vals = jax.tree.map(
            lambda av, bv: jnp.where(b_start, bv, av), a_vals, b_vals)
        return vals, a_start | b_start
    out, _ = jax.lax.associative_scan(combine, (values, start))
    return out


def fused_loss_dedup(
    prog: TreeProgram,          # flat [T, L] program
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 16,
    tile_rows: int = 16384,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """`fused_loss_program` semantics, executing each unique program once.

    Returns (loss [T], valid [T]) in the original row order, bit-equal
    to the plain path (leaders run the identical kernel; duplicates
    copy the leader's result). f32, non-parametric programs only (the
    caller gates).
    """
    T, L = prog.code.shape
    CMAX = prog.cmax
    step = jnp.arange(L, dtype=jnp.int32)[None, :]
    live = step < prog.nsteps[:, None]
    # One word encodes a step exactly (code < 128, addresses < 4096);
    # padding steps are zeroed so residual leaf-address content can't
    # split groups.
    word = jnp.where(
        live, (prog.code << 24) | (prog.src1 << 12) | prog.src2, 0)
    cbits = jax.lax.bitcast_convert_type(
        prog.cvals.astype(jnp.float32), jnp.int32)
    cused = jnp.arange(CMAX, dtype=jnp.int32)[None, :] < prog.nconst[:, None]
    cbits = jnp.where(cused, cbits, 0)

    R = jnp.asarray(_HASH_R[:, :L])
    Rc = jnp.asarray(_HASH_R[:, L:L + CMAX])
    S = jnp.asarray(_HASH_R[:, L + CMAX:L + CMAX + 1])
    h = [jnp.sum(word * R[k][None, :], axis=1)
         + jnp.sum(cbits * Rc[k][None, :], axis=1)
         + prog.nsteps * S[k, 0]
         for k in range(3)]

    word_s, = _sort_rows_by(h, [word], L)
    cbits_s, = _sort_rows_by(h, [cbits], CMAX)
    scal = _sort_rows_by(
        h, [prog.nsteps[:, None], prog.nconst[:, None],
            prog.const_ok.astype(jnp.int32)[:, None],
            jnp.arange(T, dtype=jnp.int32)[:, None]], 1)
    nsteps_s = scal[0][:, 0]
    nconst_s = scal[1][:, 0]
    ok_s = scal[2][:, 0]
    orig_s = scal[3][:, 0]
    cvals_s = jax.lax.bitcast_convert_type(
        cbits_s, jnp.float32).astype(prog.cvals.dtype)

    # Exact grouping on the sorted neighbors (hash only drives adjacency).
    prev = lambda x: jnp.concatenate([x[:1], x[:-1]], axis=0)
    eq = (jnp.all(word_s == prev(word_s), axis=1)
          & jnp.all(cbits_s == prev(cbits_s), axis=1)
          & (nsteps_s == prev(nsteps_s)))
    eq = eq.at[0].set(False)
    start = ~eq

    prog_s = TreeProgram(
        code=(word_s >> 24) & 0x7F,
        src1=(word_s >> 12) & 0xFFF,
        src2=word_s & 0xFFF,
        nsteps=jnp.where(start, nsteps_s, 1),    # duplicates: 1 cheap step
        cvals=cvals_s,
        cslot=jnp.zeros((T, CMAX), jnp.int32),   # unused by this kernel
        nconst=jnp.where(start, nconst_s, 0),
        const_ok=(ok_s == 1) | ~start,
    )
    loss_s, valid_s = fused_loss_program(
        prog_s, X, y, weights, nfeatures, operators, loss_fn,
        tree_block=tree_block, tile_rows=tile_rows, interpret=interpret)

    loss_f, valid_f = _fill_forward_segments(
        start, (loss_s, valid_s.astype(jnp.int32)))

    # Un-permute to the original row order (sort by original index).
    _, loss_o, valid_o = jax.lax.sort(
        [orig_s, loss_f, valid_f], dimension=0, num_keys=1, is_stable=True)
    return loss_o, valid_o.astype(jnp.bool_)


# ---------------------------------------------------------------------------
# Program kernel, forward + backward: loss and d(loss)/d(const) fused
# ---------------------------------------------------------------------------
#
# The adjoint sweep mirrors the forward program in reverse over the same
# unified buffer addressing: step k's cotangent lives at adj[BASE+k],
# operand contributions accumulate at adj[src] — which for constant-leaf
# operands IS the const region, so per-constant gradients fall out as
# row sums of adj[F : F+CMAX] with no slot bookkeeping in the kernel.
# (X-region adjoint rows accumulate too and are simply never read.)


def _make_multi_grad_kernel(
    operators: OperatorSet,
    loss_fn: Callable,
    tree_block: int,
    nfeat: int,
    cmax: int,
    nvar: int,
):
    BASE = nfeat + cmax
    V = nvar

    def kernel(
        instr_ref,   # SMEM [TB, L] packed instruction words
        nstep_ref,   # SMEM [TB, 1]
        nconst_ref,  # SMEM [TB, 1]
        cvals_ref,   # SMEM [TB, V * CMAX] f32 (variant-major)
        x_ref,       # VMEM [F, TILE]
        y_ref,       # VMEM [1, TILE]
        w_ref,       # VMEM [1, TILE]
        mask_ref,    # VMEM [1, TILE]
        loss_ref,    # VMEM out [TB, V] f32
        valid_ref,   # VMEM out [TB, V] int32
        gcomp_ref,   # VMEM out [TB, CMAX, V] — d loss_sum / d cvals
        buf_ref,     # VMEM scratch [BASE + L + 1, V, TILE]
        adj_ref,     # VMEM scratch [BASE + L + 1, V, TILE] (last row: the
                     # zero row's adjoint — written, never read)
    ):
        j = pl.program_id(1)
        y_row = y_ref[0, :]
        mask_row = mask_ref[0, :] > 0
        w_row = w_ref[0, :] * mask_ref[0, :]
        tile = y_row.shape[0]
        L = instr_ref.shape[-1]
        read = lambda i: buf_ref[i, :, :]

        buf_ref[0:nfeat, :, :] = jnp.broadcast_to(
            x_ref[...][:, None, :], (nfeat, V, tile))
        if _dispatch_plan(operators).merged:
            buf_ref[BASE + L, :, :] = jnp.zeros((V, tile), y_row.dtype)

        for t in range(tree_block):
            # Dynamic const preload (see _make_multi_kernel's note); the
            # ADJOINT reduce below is also dynamic — rows past nconst
            # hold stale adjoints from earlier trees.
            def cbody(c, _):
                for v in range(V):
                    buf_ref[nfeat + c, v, :] = jnp.full(
                        (tile,), cvals_ref[t, v * cmax + c],
                        dtype=y_row.dtype)
                return 0

            jax.lax.fori_loop(0, nconst_ref[t, 0], cbody, 0)

            def fwd(k, vmask):
                val = _fwd_dispatch(
                    operators, read, instr_ref[t, k], y_row.dtype)
                buf_ref[BASE + k, :, :] = val
                return vmask * jnp.isfinite(val).astype(vmask.dtype)

            m = nstep_ref[t, 0]
            vmask = jax.lax.fori_loop(
                0, m, fwd, jnp.ones((V, tile), y_row.dtype))
            validv = jnp.all(
                (vmask > 0) | jnp.logical_not(mask_row)[None, :], axis=1)

            pred = buf_ref[BASE + m - 1, :, :]             # [V, TILE]
            elt, loss_vjp = jax.vjp(
                lambda p: loss_fn(p, y_row[None, :]), pred)
            elt = jnp.where(w_row[None, :] > 0, elt, 0.0)
            partial = jnp.sum(elt * w_row[None, :], axis=1)  # [V]
            partial_ok = (validv & jnp.isfinite(partial)).astype(jnp.int32)
            (dpred,) = loss_vjp(jnp.broadcast_to(w_row[None, :], (V, tile)))
            dpred = jnp.where(w_row[None, :] > 0, dpred, 0.0)

            # Every node of a tree has exactly ONE parent, so each adjoint
            # slot is written exactly once during the sweep — plain stores,
            # no zero-init of the adjoint buffer, no read-modify-write.
            # (Two operands of one step can only collide in the X region,
            # whose adjoint rows are never read.) Unused const rows hold
            # stale data from earlier trees; the final reduction loops
            # only over the nconst used rows.
            adj_ref[BASE + m - 1, :, :] = dpred

            def bwd(i, _):
                k = m - 1 - i
                ct = adj_ref[BASE + k, :, :]

                def store(a, v):
                    adj_ref[a, :, :] = v

                _bwd_dispatch(operators, read, instr_ref[t, k], ct,
                              mask_row[None, :], store, store)
                return 0

            jax.lax.fori_loop(0, m, bwd, 0)

            @pl.when(j == 0)
            def _():
                gcomp_ref[t, :, :] = jnp.zeros(
                    (cmax, V), dtype=y_row.dtype)
                loss_ref[t, :] = partial
                valid_ref[t, :] = partial_ok

            @pl.when(j != 0)
            def _():
                loss_ref[t, :] = loss_ref[t, :] + partial
                valid_ref[t, :] = valid_ref[t, :] & partial_ok

            # Reduce only the USED const rows (dynamic loop over nconst):
            # a full-CMAX masked reduce costs ~CMAX * TILE/1024 vector
            # registers per tree, dominating short trees.
            def gbody(c, _):
                grow = jnp.sum(adj_ref[nfeat + c, :, :], axis=1)  # [V]
                gcomp_ref[t, c, :] = gcomp_ref[t, c, :] + grow
                return 0

            jax.lax.fori_loop(0, nconst_ref[t, 0], gbody, 0)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "nfeatures", "operators", "loss_fn", "tree_block", "interpret",
        "tile_budget", "v_chunk",
    ),
)
def fused_grad_multi(
    prog: TreeProgram,          # flat [T, L] program
    cvals_v: jax.Array,         # [T, V, CMAX]
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    interpret: bool = False,
    tile_budget: int = 8 * 2**20,
    v_chunk: int = 4,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(loss [T, V], valid [T, V], dloss/dcvals [T, V, CMAX]) per
    (tree, constant-variant) pair — one instruction dispatch per tree.

    V is chunked like `fused_loss_multi` (the grad kernel holds TWO
    (BASE+L) x V x TILE scratch buffers, so it hits the VMEM ceiling at
    half the variant count)."""
    V = cvals_v.shape[1]
    if V > v_chunk:
        outs = [
            fused_grad_multi(
                prog, cvals_v[:, v0:v0 + v_chunk], X, y, weights, nfeatures,
                operators, loss_fn, tree_block=tree_block,
                interpret=interpret, tile_budget=tile_budget,
                v_chunk=v_chunk)
            for v0 in range(0, V, v_chunk)
        ]
        return (jnp.concatenate([o[0] for o in outs], axis=1),
                jnp.concatenate([o[1] for o in outs], axis=1),
                jnp.concatenate([o[2] for o in outs], axis=1))
    T, L = prog.code.shape
    CMAX = prog.cmax
    V = cvals_v.shape[1]
    F, n = X.shape
    dtype = X.dtype
    BASE = nfeatures + CMAX
    _check_packable(operators, BASE, L)

    TB = tree_block
    bytes_per = jnp.dtype(dtype).itemsize
    ZR = _zero_rows(operators)
    TILE = _pick_tile(n, n, 2 * (BASE + L + ZR) * V, bytes_per,
                      budget=tile_budget)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_t(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    instr = pad_t(_pack_instr(prog, operators, BASE + L))
    nsteps = pad_t(prog.nsteps.reshape(-1, 1), fill=1)
    nconst = pad_t(prog.nconst.reshape(-1, 1))
    cflat = pad_t(cvals_v.reshape(T, V * CMAX)).astype(dtype)

    Xp = jnp.pad(X, ((0, 0), (0, n_pad - n)))
    yp = jnp.pad(y.reshape(1, n), ((0, 0), (0, n_pad - n)))
    w = (jnp.ones((1, n), dtype) if weights is None
         else weights.reshape(1, n).astype(dtype))
    wp = jnp.pad(w, ((0, 0), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_multi_grad_kernel(operators, loss_fn, TB, nfeatures,
                                     CMAX, V)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )
    row_spec = pl.BlockSpec((1, TILE), lambda i, j: (0, j))

    loss_sum, valid, gcomp = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, L)),                       # instr
            smem_i32((TB, 1)),                       # nsteps
            smem_i32((TB, 1)),                       # nconst
            pl.BlockSpec((TB, V * CMAX), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),   # cvals
            pl.BlockSpec((F, TILE), lambda i, j: (0, j)),  # X
            row_spec,                                # y
            row_spec,                                # w
            row_spec,                                # mask
        ],
        out_specs=[
            pl.BlockSpec((TB, V), lambda i, j: (i, 0)),
            pl.BlockSpec((TB, V), lambda i, j: (i, 0)),
            pl.BlockSpec((TB, CMAX, V), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, V), dtype),
            jax.ShapeDtypeStruct((T_pad, V), jnp.int32),
            jax.ShapeDtypeStruct((T_pad, CMAX, V), dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((BASE + L + ZR, V, TILE), dtype),
            pltpu.VMEM((BASE + L + ZR, V, TILE), dtype),
        ],
        interpret=interpret,
    )(instr, nsteps, nconst, cflat, Xp, yp, wp, maskp)

    loss_sum = loss_sum[:T]
    valid = valid[:T].astype(jnp.bool_)
    gcomp = jnp.swapaxes(gcomp[:T], 1, 2)              # [T, V, CMAX]
    used = (jnp.arange(CMAX, dtype=jnp.int32)[None, None, :]
            < prog.nconst[:, None, None])
    ok_v = jnp.all(jnp.isfinite(cvals_v) | ~used, axis=-1)
    valid = valid & ok_v
    denom = jnp.sum(w) if weights is not None else jnp.asarray(n, dtype)
    loss = loss_sum / denom
    grad = gcomp / denom
    bad = ~(valid & jnp.isfinite(loss))
    loss = jnp.where(bad, jnp.inf, loss)
    grad = jnp.where(bad[..., None] | ~jnp.isfinite(grad), 0.0, grad)
    return loss, valid, grad


def fused_grad_program(
    prog: TreeProgram,          # flat [T, L] program
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    tile_rows: int = 16384,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(loss [T], valid [T], dloss/dcvals [T, CMAX]) — the single-variant
    view of `fused_grad_multi` (V = 1, constants from ``prog.cvals``)."""
    del tile_rows
    loss, valid, grad = fused_grad_multi(
        prog, prog.cvals[:, None, :], X, y, weights, nfeatures, operators,
        loss_fn, tree_block=tree_block, interpret=interpret,
    )
    return loss[:, 0], valid[:, 0], grad[:, 0]


@functools.partial(
    jax.jit,
    static_argnames=(
        "operators", "loss_fn", "tree_block", "tile_rows", "bf16",
        "interpret", "dedup",
    ),
)
def fused_loss(
    trees: TreeBatch,
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],  # [n] or None
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    params: Optional[jax.Array] = None,     # [..., NP, NC] member banks
    class_idx: Optional[jax.Array] = None,  # [n] int class per row
    tree_block: int = 8,
    tile_rows: int = 16384,
    bf16: bool = False,
    interpret: bool = False,
    dedup: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Mean elementwise loss per tree, fused on TPU.

    ``dedup``: evaluate each fully identical (structure, constants)
    program once and share the result (bit-equal; see
    `fused_loss_dedup`). Worth it for large flat batches with repeated
    members (the finalize eval over whole converged populations);
    ignored for parametric batches.

    Returns ``(loss[...], valid[...])`` with the TreeBatch's batch dims;
    invalid trees get loss=inf (matching aggregate_loss semantics).
    Compiles the batch to a leaf-free TreeProgram (ops/program.py) and
    runs the unified-buffer kernel; callers that re-evaluate the same
    structures with different constants (line searches) should compile
    once and use `fused_loss_program` + `update_consts` directly.

    Parametric members pass their banks ``params`` and the dataset's
    per-row ``class_idx``; LEAF_PARAM leaves then read per-row values
    from the buffer's parameter region.
    """
    batch_shape = trees.batch_shape
    flat = trees.reshape(-1) if batch_shape else trees.reshape(1)
    F = X.shape[0]
    NP = 0 if params is None else params.shape[-2]
    prog = compile_program(flat, F, len(operators.binary), n_params=NP)
    p_flat = None
    class_oh = None
    if NP > 0:
        NC = params.shape[-1]
        p_flat = params.reshape(-1, NP, NC)
        class_oh = (class_idx[None, :] == jnp.arange(NC)[:, None]).astype(
            X.dtype)
    # dedup groups constants through a float32 bitcast — gate on f32 so
    # f64 runs never merge members distinct only below f32 resolution.
    # (bf16 keeps the dedup grouping valid — identical f32 constants stay
    # identical after the downcast — but the dedup kernel has no bf16
    # buffer variant, so bf16 callers take the plain program launch.)
    if dedup and NP == 0 and prog.cvals.dtype == jnp.float32 and not bf16:
        loss, valid = fused_loss_dedup(
            prog, X, y, weights, F, operators, loss_fn,
            tree_block=tree_block, tile_rows=tile_rows, interpret=interpret,
        )
    else:
        loss, valid = fused_loss_program(
            prog, X, y, weights, F, operators, loss_fn,
            params=p_flat, class_oh=class_oh,
            tree_block=tree_block, tile_rows=tile_rows, bf16=bf16,
            interpret=interpret,
        )
    if NP > 0:
        # const_ok analogue for the parameter region: a non-finite bank
        # value absorbed by an op (exp(-inf) = 0) would otherwise pass
        # as valid where the interpreter flags the param node itself.
        p_ok = jnp.all(jnp.isfinite(p_flat), axis=(-2, -1))
        valid = valid & p_ok
        loss = jnp.where(valid, loss, jnp.inf)
    if batch_shape:
        return loss.reshape(batch_shape), valid.reshape(batch_shape)
    return loss[0], valid[0]


@functools.partial(
    jax.jit,
    static_argnames=(
        "operators", "loss_fn", "tree_block", "tile_rows", "bf16",
        "interpret",
    ),
)
def fused_cost(
    trees: TreeBatch,
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],  # [n] or None
    complexity: jax.Array,      # [...] int32, the TreeBatch's batch dims
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    baseline_loss: jax.Array,
    use_baseline: jax.Array,
    parsimony,
    tree_block: int = 8,
    tile_rows: int = 16384,
    bf16: bool = False,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(cost, loss, valid) per tree with the loss→cost epilogue fused
    into the eval kernel (see `fused_cost_program`).

    The candidate-eval hot path of the evolve cycle: one kernel launch
    returns final (programs,)-shaped costs — the per-cycle [T]-shaped
    mean/validity/normalization/parsimony dispatch chain of the
    materializing path collapses into the kernel's last grid step.
    Plain (non-parametric, non-template) expressions only; callers gate
    exactly like the turbo gate (evolve.step.eval_cost_batch).
    """
    batch_shape = trees.batch_shape
    flat = trees.reshape(-1) if batch_shape else trees.reshape(1)
    F = X.shape[0]
    prog = compile_program(flat, F, len(operators.binary))
    cost, loss, valid = fused_cost_program(
        prog, X, y, weights, complexity.reshape(-1), F, operators, loss_fn,
        baseline_loss=baseline_loss, use_baseline=use_baseline,
        parsimony=parsimony, tree_block=tree_block, tile_rows=tile_rows,
        bf16=bf16, interpret=interpret,
    )
    if batch_shape:
        return (cost.reshape(batch_shape), loss.reshape(batch_shape),
                valid.reshape(batch_shape))
    return cost[0], loss[0], valid[0]


# ---------------------------------------------------------------------------
# Program predict kernels: per-tree row outputs (no loss reduction)
# ---------------------------------------------------------------------------
#
# Used by template expressions: each subexpression call site evaluates a
# whole member-batch of subtrees and needs the raw predictions back for
# the combiner's ValidVector algebra (models/template.py). Two X modes
# share one kernel factory:
#   shared     — X [F, n]: dataset columns, identical for every member;
#   per-member — X [T, F, n]: arguments that are themselves member
#                outputs (composition chains like g(f(x))), loaded per
#                tree. The VJP in this mode also emits d/dX row
#                cotangents so gradients flow back through the chain.


def _make_program_predict_kernel(
    operators: OperatorSet,
    tree_block: int,
    nfeat: int,
    cmax: int,
    per_member: bool,
):
    BASE = nfeat + cmax

    def kernel(
        instr_ref,   # SMEM [TB, L]
        nstep_ref,   # SMEM [TB, 1]
        cvals_ref,   # SMEM [TB, CMAX] f32
        ok_ref,      # SMEM [TB, 1] int32
        x_ref,       # VMEM [F, TILE] or [TB, F, TILE]
        mask_ref,    # VMEM [1, TILE]
        pred_ref,    # VMEM out [TB, TILE]
        valid_ref,   # SMEM out [TB, 1] int32
        buf_ref,     # VMEM scratch [BASE + L + 1, TILE]
    ):
        j = pl.program_id(1)
        mask_row = mask_ref[0, :] > 0
        tile = mask_ref.shape[-1]
        dtype = buf_ref.dtype
        L = instr_ref.shape[-1]

        if not per_member:
            buf_ref[0:nfeat, :] = x_ref[...]
        if _dispatch_plan(operators).merged:
            buf_ref[BASE + L, :] = jnp.zeros((tile,), dtype)

        for t in range(tree_block):
            if per_member:
                buf_ref[0:nfeat, :] = x_ref[t]

            # static-unrolled const preload (see the program kernel)
            for c in range(cmax):
                buf_ref[nfeat + c, :] = jnp.full(
                    (tile,), cvals_ref[t, c], dtype=dtype)

            def step(k, vmask):
                val = _fwd_dispatch(
                    operators, lambda i: buf_ref[i, :], instr_ref[t, k],
                    dtype)
                buf_ref[BASE + k, :] = val
                return vmask * jnp.isfinite(val).astype(vmask.dtype)

            m = nstep_ref[t, 0]
            vmask = jax.lax.fori_loop(
                0, m, step, jnp.ones((tile,), dtype))
            valid = jnp.all((vmask > 0) | jnp.logical_not(mask_row))
            pred_ref[t, :] = buf_ref[BASE + m - 1, :]
            partial_ok = jnp.int32(valid) * ok_ref[t, 0]

            @pl.when(j == 0)
            def _():
                valid_ref[t, 0] = partial_ok

            @pl.when(j != 0)
            def _():
                valid_ref[t, 0] = valid_ref[t, 0] & partial_ok

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("nfeatures", "operators", "tree_block", "interpret"),
)
def fused_predict_program(
    prog: TreeProgram,          # flat [T, L]
    X: jax.Array,               # [F, n] shared or [T, F, n] per-member
    nfeatures: int,
    operators: OperatorSet,
    *,
    tree_block: int = 16,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Per-tree row predictions (pred [T, n], valid [T]) for compiled
    programs; X may be shared dataset columns or per-member argument
    rows."""
    T, L = prog.code.shape
    CMAX = prog.cmax
    per_member = X.ndim == 3
    F = X.shape[-2]
    n = X.shape[-1]
    dtype = X.dtype
    BASE = nfeatures + CMAX
    _check_packable(operators, BASE, L)

    # Per-member mode streams [TB, F, TILE] X tiles; cap the block so the
    # doubled-buffered input tiles don't crowd VMEM.
    TB = min(tree_block, 8) if per_member else tree_block
    bytes_per = jnp.dtype(dtype).itemsize
    ZR = _zero_rows(operators)
    TILE = _pick_tile(n, 16384, BASE + L + ZR, bytes_per)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_t(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    instr = pad_t(_pack_instr(prog, operators, BASE + L))
    nsteps = pad_t(prog.nsteps.reshape(-1, 1), fill=1)
    cvals = pad_t(prog.cvals).astype(dtype)
    ok = pad_t(prog.const_ok.astype(jnp.int32).reshape(-1, 1), fill=1)

    if per_member:
        Xp = jnp.pad(X, ((0, T_pad - T), (0, 0), (0, n_pad - n)))
        x_spec = pl.BlockSpec((TB, F, TILE), lambda i, j: (i, 0, j))
    else:
        Xp = jnp.pad(X, ((0, 0), (0, n_pad - n)))
        x_spec = pl.BlockSpec((F, TILE), lambda i, j: (0, j))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_program_predict_kernel(operators, TB, F, CMAX, per_member)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )

    pred, valid = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, L)),
            smem_i32((TB, 1)),
            pl.BlockSpec((TB, CMAX), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            smem_i32((TB, 1)),
            x_spec,
            pl.BlockSpec((1, TILE), lambda i, j: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((TB, TILE), lambda i, j: (i, j)),
            pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, n_pad), dtype),
            jax.ShapeDtypeStruct((T_pad, 1), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((BASE + L + ZR, TILE), dtype)],
        interpret=interpret,
    )(instr, nsteps, cvals, ok, Xp, maskp)

    return pred[:T, :n], valid[:T, 0].astype(jnp.bool_)


def fused_predict(
    trees: TreeBatch,
    X: jax.Array,               # [F, n]
    operators: OperatorSet,
    *,
    tree_block: int = 8,
    tile_rows: int = 16384,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Per-tree predictions over all rows, fused on TPU.

    Returns ``(pred[..., n], valid[...])`` with the TreeBatch's batch
    dims; validity matches the interpreter (any non-finite node output
    over the rows invalidates the tree, and non-finite constants
    invalidate it outright).
    """
    del tile_rows
    batch_shape = trees.batch_shape
    flat = trees.reshape(-1) if batch_shape else trees.reshape(1)
    F, n = X.shape
    prog = compile_program(flat, F, len(operators.binary))
    pred, valid = fused_predict_program(
        prog, X, F, operators, tree_block=tree_block, interpret=interpret)
    if batch_shape:
        return pred.reshape(*batch_shape, n), valid.reshape(batch_shape)
    return pred[0], valid[0]


# ---------------------------------------------------------------------------
# Predict VJP: cotangent-seeded constant (and argument) gradients
# ---------------------------------------------------------------------------
#
# Differentiable prediction powers template constant optimization: the
# combiner's elementwise algebra is differentiated by JAX as usual, and
# each fused call site's backward contracts the incoming row cotangent
# with the subtree's adjoint sweep in one kernel. In per-member mode the
# X-region adjoint rows ARE the argument cotangents (composition chains
# need them); a subtree may reference the same argument at several
# leaves, so X-region adjoints accumulate (+=) over a zeroed region
# while the write-once tree regions stay plain stores.


def _make_program_predict_vjp_kernel(
    operators: OperatorSet,
    tree_block: int,
    nfeat: int,
    cmax: int,
    per_member: bool,
):
    BASE = nfeat + cmax

    def kernel(
        instr_ref,   # SMEM [TB, L]
        nstep_ref,   # SMEM [TB, 1]
        nconst_ref,  # SMEM [TB, 1]
        cvals_ref,   # SMEM [TB, CMAX] f32
        x_ref,       # VMEM [F, TILE] or [TB, F, TILE]
        ct_ref,      # VMEM [TB, TILE] — incoming row cotangents
        mask_ref,    # VMEM [1, TILE]
        gcomp_ref,   # SMEM out [TB, CMAX] (scalar stores)
        gx_ref,      # VMEM out [TB, F, TILE] (dummy [TB, 1, TILE] if shared)
        buf_ref,     # VMEM scratch [BASE + L + 1, TILE]
        adj_ref,     # VMEM scratch [BASE + L + 1, TILE]
    ):
        j = pl.program_id(1)
        mask_row = mask_ref[0, :] > 0
        tile = mask_ref.shape[-1]
        dtype = buf_ref.dtype
        L = instr_ref.shape[-1]
        read = lambda i: buf_ref[i, :]

        if not per_member:
            buf_ref[0:nfeat, :] = x_ref[...]
        if _dispatch_plan(operators).merged:
            buf_ref[BASE + L, :] = jnp.zeros((tile,), dtype)

        for t in range(tree_block):
            if per_member:
                buf_ref[0:nfeat, :] = x_ref[t]

            # static-unrolled const preload (see the program kernel)
            for c in range(cmax):
                buf_ref[nfeat + c, :] = jnp.full(
                    (tile,), cvals_ref[t, c], dtype=dtype)

            def fwd(k, _):
                buf_ref[BASE + k, :] = _fwd_dispatch(
                    operators, read, instr_ref[t, k], dtype)
                return 0

            m = nstep_ref[t, 0]
            jax.lax.fori_loop(0, m, fwd, 0)

            # X-region adjoints accumulate (same argument can appear at
            # several leaves); tree regions are written exactly once.
            adj_ref[0:nfeat, :] = jnp.zeros((nfeat, tile), dtype)
            adj_ref[BASE + m - 1, :] = jnp.where(mask_row, ct_ref[t, :], 0.0)

            def store_adj(iaddr, val):
                @pl.when(iaddr < nfeat)
                def _():
                    adj_ref[iaddr, :] = adj_ref[iaddr, :] + val

                @pl.when(iaddr >= nfeat)
                def _():
                    adj_ref[iaddr, :] = val

            def bwd(i, _):
                k = m - 1 - i
                ct = adj_ref[BASE + k, :]
                _bwd_dispatch(operators, read, instr_ref[t, k], ct,
                              mask_row, store_adj, store_adj)
                return 0

            jax.lax.fori_loop(0, m, bwd, 0)

            @pl.when(j == 0)
            def _():
                for c in range(cmax):  # SMEM: scalar stores only
                    gcomp_ref[t, c] = 0.0

            def gbody(c, _):
                gcomp_ref[t, c] = gcomp_ref[t, c] + jnp.sum(
                    adj_ref[nfeat + c, :])
                return 0

            jax.lax.fori_loop(0, nconst_ref[t, 0], gbody, 0)

            if per_member:
                gx_ref[t] = adj_ref[0:nfeat, :]

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("nfeatures", "operators", "tree_block", "interpret"),
)
def _fused_predict_vjp_program(
    prog: TreeProgram,
    X: jax.Array,               # [F, n] or [T, F, n]
    ct: jax.Array,              # [T, n] row cotangents
    nfeatures: int,
    operators: OperatorSet,
    *,
    tree_block: int = 8,
    interpret: bool = False,
):
    """d(sum(ct * pred)) / d(cvals) [T, CMAX] and, in per-member mode,
    d/dX [T, F, n]; non-finite contributions zeroed."""
    T, L = prog.code.shape
    CMAX = prog.cmax
    per_member = X.ndim == 3
    F = X.shape[-2]
    n = X.shape[-1]
    dtype = X.dtype
    BASE = nfeatures + CMAX
    _check_packable(operators, BASE, L)

    TB = tree_block
    bytes_per = jnp.dtype(dtype).itemsize
    ZR = _zero_rows(operators)
    TILE = _pick_tile(n, 16384, 2 * (BASE + L + ZR), bytes_per)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_t(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    instr = pad_t(_pack_instr(prog, operators, BASE + L))
    nsteps = pad_t(prog.nsteps.reshape(-1, 1), fill=1)
    nconst = pad_t(prog.nconst.reshape(-1, 1))
    cvals = pad_t(prog.cvals).astype(dtype)

    if per_member:
        Xp = jnp.pad(X, ((0, T_pad - T), (0, 0), (0, n_pad - n)))
        x_spec = pl.BlockSpec((TB, F, TILE), lambda i, j: (i, 0, j))
        FG = F
    else:
        Xp = jnp.pad(X, ((0, 0), (0, n_pad - n)))
        x_spec = pl.BlockSpec((F, TILE), lambda i, j: (0, j))
        FG = 1
    ctp = jnp.pad(ct.astype(dtype), ((0, T_pad - T), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_program_predict_vjp_kernel(
        operators, TB, F, CMAX, per_member)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )

    gcomp, gx = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, L)),
            smem_i32((TB, 1)),
            smem_i32((TB, 1)),
            pl.BlockSpec((TB, CMAX), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            x_spec,
            pl.BlockSpec((TB, TILE), lambda i, j: (i, j)),    # ct
            pl.BlockSpec((1, TILE), lambda i, j: (0, j)),     # mask
        ],
        out_specs=[
            pl.BlockSpec((TB, CMAX), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((TB, FG, TILE), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, CMAX), dtype),
            jax.ShapeDtypeStruct((T_pad, FG, n_pad), dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((BASE + L + ZR, TILE), dtype),
            pltpu.VMEM((BASE + L + ZR, TILE), dtype),
        ],
        interpret=interpret,
    )(instr, nsteps, nconst, cvals, Xp, ctp, maskp)

    gcomp = gcomp[:T]
    gcomp = jnp.where(jnp.isfinite(gcomp), gcomp, 0.0)
    if per_member:
        # gx stays RAW (no non-finite masking): downstream consumers
        # either mask (the optimizer's masked_grad) or use NaN as a
        # validity signal (the template D operator) — matching the jnp
        # interpreter's autodiff semantics.
        return gcomp, gx[:T, :, :n]
    return gcomp, None


_PREDICT_AD_CACHE: dict = {}


def _predict_ad_impl(operators: OperatorSet, interpret: bool, per_member: bool):
    key = (operators, interpret, per_member)
    if key not in _PREDICT_AD_CACHE:
        def primal(arity, op, feat, const, length, X):
            trees = TreeBatch(arity, op, feat, const, length)
            F = X.shape[-2]
            prog = compile_program(trees, F, len(operators.binary))
            return fused_predict_program(
                prog, X, F, operators, interpret=interpret)

        f = jax.custom_vjp(primal)

        def fwd(arity, op, feat, const, length, X):
            out = primal(arity, op, feat, const, length, X)
            return out, (arity, op, feat, const, length, X)

        def bwd(res, cts):
            arity, op, feat, const, length, X = res
            ct_pred, _ = cts  # valid output is boolean (float0 cotangent)
            trees = TreeBatch(arity, op, feat, const, length)
            F = X.shape[-2]
            L = arity.shape[-1]
            prog = compile_program(trees, F, len(operators.binary))
            gcomp, gx = _fused_predict_vjp_program(
                prog, X, ct_pred, F, operators, interpret=interpret)
            from .program import scatter_const_grads

            gconst = scatter_const_grads(prog, gcomp, L)
            f0 = lambda x: np.zeros(x.shape, jax.dtypes.float0)
            if gx is None:
                gx = jnp.zeros_like(X)
            return (f0(arity), f0(op), f0(feat), gconst, f0(length), gx)

        f.defvjp(fwd, bwd)
        _PREDICT_AD_CACHE[key] = f
    return _PREDICT_AD_CACHE[key]


def fused_predict_ad(trees: TreeBatch, X: jax.Array, operators: OperatorSet,
                     *, interpret: bool = False):
    """`fused_predict` with a custom VJP.

    Gradients flow into ``trees.const``; with shared X [F, n] (dataset
    columns) X gets a zero cotangent, while per-member X [T, F, n]
    (composition-chain arguments) receives real row cotangents from the
    adjoint sweep so chains like g(f(x)) differentiate end to end.
    Flat [T, L] trees only.
    """
    f = _predict_ad_impl(operators, interpret, X.ndim == 3)
    return f(trees.arity, trees.op, trees.feat, trees.const, trees.length, X)


def _vjp_unary(fn, x, ct):
    _, vjp = jax.vjp(fn, x)
    (dx,) = vjp(ct)
    return dx


def _vjp_binary(fn, x, y, ct):
    _, vjp = jax.vjp(fn, x, y)
    dx, dy = vjp(ct)
    return dx, dy


@functools.partial(
    jax.jit,
    static_argnames=(
        "operators", "loss_fn", "tree_block", "tile_rows", "interpret",
    ),
)
def fused_loss_and_const_grad(
    trees: TreeBatch,
    child: jax.Array,           # [..., L, 2] from tree_structure_arrays
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    tile_rows: int = 16384,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(loss, valid, dloss/dconst) per tree, in one fused TPU kernel.

    ``loss`` is the mean elementwise loss (invalid => inf, matching
    `fused_loss`); the gradient is w.r.t. every constant-leaf slot of
    ``trees.const`` (zero elsewhere, zero for invalid trees).

    Compatibility wrapper over the program path: compiles the batch and
    scatters the compressed gradient back to slot order. ``child`` is
    accepted for signature stability but unused (the program lowering
    derives structure itself); optimizer loops should hoist the compile
    and call `fused_grad_program` + `update_consts` directly.
    """
    from .program import scatter_const_grads

    del child
    batch_shape = trees.batch_shape
    flat = trees.reshape(-1) if batch_shape else trees.reshape(1)
    L = flat.arity.shape[-1]
    F = X.shape[0]
    prog = compile_program(flat, F, len(operators.binary))
    loss, valid, gcomp = fused_grad_program(
        prog, X, y, weights, F, operators, loss_fn,
        tree_block=tree_block, tile_rows=tile_rows, interpret=interpret,
    )
    grad = scatter_const_grads(prog, gcomp, L)
    if batch_shape:
        return (loss.reshape(batch_shape), valid.reshape(batch_shape),
                grad.reshape(*batch_shape, L))
    return loss[0], valid[0], grad[0]
