"""Fused Pallas TPU kernel: postfix tree eval + loss reduction per tree.

This is the framework's hot op (the "turbo" layer — the role
LoopVectorization plays in the reference,
/root/reference/src/InterfaceDynamicExpressions.jl:71-81). The jnp
interpreter in ops/eval.py materializes a [T, L, n] value buffer in HBM
and computes *every* operator at every slot; this kernel instead:

- keeps a per-tree evaluation **stack** in VMEM (postfix order means each
  node's operands are the top of the stack — no child-index gathers);
- dispatches exactly one operator per node via `lax.switch` on the SMEM
  op code;
- fuses the elementwise-loss + row reduction, so HBM traffic is just the
  X/y row tiles (shared across all trees) and one scalar pair per tree.

Outputs per tree: (loss_sum, valid) accumulated over row tiles; the
wrapper converts to mean loss with the reference's invalid ⇒ Inf
semantics (/root/reference/src/LossFunctions.jl:96-99).

Stack destinations are data, not control: dst[k] = (exclusive-cumsum of
(1 - arity))[k] - arity[k] is precomputed with jnp before the kernel, so
the kernel's only dynamic indexing is the stack-slot store/load.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .encoding import LEAF_CONST, LEAF_VAR, TreeBatch
from .operators import OperatorSet

__all__ = ["fused_loss", "stack_positions", "supports_fused_eval"]


def stack_positions(arity: jax.Array) -> jax.Array:
    """dst[k]: stack slot written by postfix slot k (see module doc)."""
    one_minus_a = 1 - arity
    excl = jnp.cumsum(one_minus_a, axis=-1) - one_minus_a
    return excl - arity


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def supports_fused_eval(operators: OperatorSet) -> bool:
    """The kernel handles arity <= 2 operator sets (current encoding)."""
    return all(d in (1, 2) for d in operators.ops.keys())


def _tree_kernel_body(
    t: int,
    k,
    arity_ref,
    op_ref,
    feat_ref,
    dst_ref,
    const_ref,
    x_ref,
    stack_ref,
    vmask,
    unary_fns,
    binary_fns,
):
    """Evaluate slot k of tree t (one step of the fori_loop).

    No in-tree guard: padding slots are arity-0 const-0 leaves whose
    (clamped) stack writes land above the live region — slot 0, where the
    root value ends up, is never touched by them (the running stack
    pointer after the root is >= 1). Validity is accumulated as a per-row
    vector mask (one cross-lane reduction at the end instead of one per
    slot); a row is valid iff every node output at that row is finite —
    equivalent to the reference's per-node buffer check
    (/root/reference/src/LossFunctions.jl:96-99 semantics).
    """
    a = arity_ref[t, k]
    o = op_ref[t, k]
    d = dst_ref[t, k]
    tile = stack_ref.shape[-1]

    def leaf_val():
        x_row = x_ref[feat_ref[t, k], :]
        c = jnp.full((tile,), const_ref[t, k], dtype=x_ref.dtype)
        return jnp.where(o == LEAF_CONST, c, x_row)

    def unary_val():
        child = stack_ref[t, d, :]
        if len(unary_fns) == 1:
            return unary_fns[0](child)
        return jax.lax.switch(o, unary_fns, child)

    def binary_val():
        l = stack_ref[t, d, :]
        r = stack_ref[t, d + 1, :]
        if len(binary_fns) == 1:
            return binary_fns[0](l, r)
        return jax.lax.switch(o, binary_fns, l, r)

    branches = [leaf_val]
    branches.append(unary_val if unary_fns else leaf_val)
    branches.append(binary_val if binary_fns else leaf_val)
    val = jax.lax.switch(a, branches)

    stack_ref[t, d, :] = val
    # float accumulator: Mosaic miscompiles bool vectors as loop carries
    return vmask * jnp.isfinite(val).astype(vmask.dtype)


def _make_kernel(
    operators: OperatorSet,
    loss_fn: Callable,
    max_nodes: int,
    tree_block: int,
    weighted: bool,
):
    unary_fns = tuple(op.fn for op in operators.unary)
    binary_fns = tuple(op.fn for op in operators.binary)

    def kernel(
        arity_ref,   # SMEM [TB, L]
        op_ref,      # SMEM [TB, L]
        feat_ref,    # SMEM [TB, L]
        dst_ref,     # SMEM [TB, L] (clamped to stack size by the wrapper)
        const_ref,   # SMEM [TB, L] f32
        x_ref,       # VMEM [F, TILE]
        y_ref,       # VMEM [1, TILE]
        w_ref,       # VMEM [1, TILE] (ones when unweighted)
        mask_ref,    # VMEM [1, TILE] f32: 1.0 for real rows, 0.0 padding
        loss_ref,    # SMEM out [TB, 1] f32
        valid_ref,   # SMEM out [TB, 1] int32
        stack_ref,   # VMEM scratch [TB, S, TILE]
    ):
        j = pl.program_id(1)
        y_row = y_ref[0, :]
        mask_row = mask_ref[0, :] > 0
        w_row = w_ref[0, :] * mask_ref[0, :]
        tile = y_row.shape[0]

        for t in range(tree_block):
            def body(k, vmask):
                return _tree_kernel_body(
                    t, k, arity_ref, op_ref, feat_ref, dst_ref, const_ref,
                    x_ref, stack_ref, vmask,
                    unary_fns, binary_fns,
                )

            vmask = jax.lax.fori_loop(
                0, max_nodes, body, jnp.ones((tile,), y_row.dtype)
            )
            valid = jnp.all((vmask > 0) | jnp.logical_not(mask_row))
            pred = stack_ref[t, 0, :]
            elt = loss_fn(pred, y_row)
            # Zero padded/invalid rows *before* the sum so NaN padding
            # can't poison the accumulator; validity is tracked separately.
            elt = jnp.where(w_row > 0, elt, 0.0)
            partial = jnp.sum(elt * w_row)
            partial_ok = jnp.int32(valid & jnp.isfinite(partial))

            @pl.when(j == 0)
            def _():
                loss_ref[t, 0] = partial
                valid_ref[t, 0] = partial_ok

            @pl.when(j != 0)
            def _():
                loss_ref[t, 0] = loss_ref[t, 0] + partial
                valid_ref[t, 0] = valid_ref[t, 0] & partial_ok

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "operators", "loss_fn", "tree_block", "tile_rows", "interpret",
    ),
)
def fused_loss(
    trees: TreeBatch,
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],  # [n] or None
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    tile_rows: int = 2048,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Mean elementwise loss per tree, fused on TPU.

    Returns ``(loss[...], valid[...])`` with the TreeBatch's batch dims;
    invalid trees get loss=inf (matching aggregate_loss semantics).
    """
    batch_shape = trees.batch_shape
    flat = trees.reshape(-1) if batch_shape else trees.reshape(1)
    T = flat.length.shape[0]
    L = flat.arity.shape[-1]
    F, n = X.shape
    dtype = X.dtype

    TB = tree_block
    TILE = min(tile_rows, _round_up(n, 128))
    # Keep the stack scratch + row tiles inside the ~16MB VMEM budget.
    S_est = L // 2 + 2
    bytes_per = jnp.dtype(dtype).itemsize
    while TB * S_est * TILE * bytes_per > 10 * 2**20 and TILE > 512:
        TILE //= 2
    while TB * S_est * TILE * bytes_per > 10 * 2**20 and TB > 8:
        TB //= 2
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_trees(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    S = L // 2 + 2  # max postfix stack depth for L slots
    arity = pad_trees(flat.arity)
    op = pad_trees(flat.op)
    feat = jnp.clip(pad_trees(flat.feat), 0, F - 1)
    const = pad_trees(flat.const).astype(dtype)
    # Padding slots' running stack positions keep growing past the live
    # region; clamp into the scratch slot so their writes are in-bounds
    # (they never touch slot 0 — see kernel docstring).
    dst = jnp.clip(stack_positions(arity), 0, S - 1)

    Xp = jnp.pad(X, ((0, 0), (0, n_pad - n)))
    yp = jnp.pad(y.reshape(1, n), ((0, 0), (0, n_pad - n)))
    w = jnp.ones((1, n), dtype) if weights is None else weights.reshape(1, n).astype(dtype)
    wp = jnp.pad(w, ((0, 0), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_kernel(operators, loss_fn, L, TB, weights is not None)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )
    row_spec = pl.BlockSpec((1, TILE), lambda i, j: (0, j))

    loss_sum, valid = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, L)),                       # arity
            smem_i32((TB, L)),                       # op
            smem_i32((TB, L)),                       # feat
            smem_i32((TB, L)),                       # dst
            pl.BlockSpec((TB, L), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),   # const
            pl.BlockSpec((F, TILE), lambda i, j: (0, j)),  # X
            row_spec,                                # y
            row_spec,                                # w
            row_spec,                                # mask
        ],
        out_specs=[
            pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, 1), dtype),
            jax.ShapeDtypeStruct((T_pad, 1), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((TB, S, TILE), dtype)],
        interpret=interpret,
    )(arity, op, feat, dst, const, Xp, yp, wp, maskp)

    loss_sum = loss_sum[:T, 0]
    valid = valid[:T, 0].astype(jnp.bool_)
    denom = jnp.sum(w) if weights is not None else jnp.asarray(n, dtype)
    loss = loss_sum / denom
    loss = jnp.where(valid & jnp.isfinite(loss), loss, jnp.inf)
    if batch_shape:
        return loss.reshape(batch_shape), valid.reshape(batch_shape)
    return loss[0], valid[0]
