"""Fused Pallas TPU kernel: postfix tree eval + loss reduction per tree.

This is the framework's hot op (the "turbo" layer — the role
LoopVectorization plays in the reference,
/root/reference/src/InterfaceDynamicExpressions.jl:71-81). The jnp
interpreter in ops/eval.py materializes a [T, L, n] value buffer in HBM
and computes *every* operator at every slot; this kernel instead:

- keeps a per-tree evaluation **stack** in VMEM (postfix order means each
  node's operands are the top of the stack — no child-index gathers);
- dispatches exactly one operator per node via `lax.switch` on the SMEM
  op code;
- fuses the elementwise-loss + row reduction, so HBM traffic is just the
  X/y row tiles (shared across all trees) and one scalar pair per tree.

Outputs per tree: (loss_sum, valid) accumulated over row tiles; the
wrapper converts to mean loss with the reference's invalid ⇒ Inf
semantics (/root/reference/src/LossFunctions.jl:96-99).

Stack destinations are data, not control: dst[k] = (exclusive-cumsum of
(1 - arity))[k] - arity[k] is precomputed with jnp before the kernel, so
the kernel's only dynamic indexing is the stack-slot store/load.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .encoding import LEAF_CONST, LEAF_VAR, TreeBatch, tree_structure_arrays
from .operators import OperatorSet
from .program import TreeProgram, compile_program

__all__ = ["fused_loss", "fused_loss_program", "fused_loss_and_const_grad",
           "fused_predict", "fused_predict_ad", "stack_positions",
           "supports_fused_eval"]


def stack_positions(arity: jax.Array) -> jax.Array:
    """dst[k]: stack slot written by postfix slot k (see module doc)."""
    one_minus_a = 1 - arity
    excl = jnp.cumsum(one_minus_a, axis=-1) - one_minus_a
    return excl - arity


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _pick_tile(n: int, tile_cap: int, vmem_rows: int, bytes_per: int,
               budget: int = 10 * 2**20) -> int:
    """Row-tile size: prefer one tile covering all rows (padded to 1024)
    so the per-slot scalar dispatch overhead is paid once per tree, not
    once per (tree, tile); fall back to smaller tiles on VMEM pressure.

    ``vmem_rows`` = number of TILE-wide scratch rows the kernel keeps
    resident (stack/buffer/adjoint for all trees of a block).
    """
    tile = min(_round_up(n, 1024), _round_up(tile_cap, 1024))
    while tile > 1024 and vmem_rows * tile * bytes_per > budget:
        tile = _round_up(tile // 2, 1024)
    return tile  # floor is 1024 (every branch rounds up to 1024)


def supports_fused_eval(operators: OperatorSet) -> bool:
    """The kernel handles arity <= 2 operator sets (current encoding)."""
    return all(d in (1, 2) for d in operators.ops.keys())


def _tree_kernel_body(
    t: int,
    k,
    arity_ref,
    op_ref,
    feat_ref,
    dst_ref,
    const_ref,
    x_ref,
    stack_ref,
    vmask,
    unary_fns,
    binary_fns,
):
    """Evaluate slot k of tree t (one step of the fori_loop).

    No in-tree guard: padding slots are arity-0 const-0 leaves whose
    (clamped) stack writes land above the live region — slot 0, where the
    root value ends up, is never touched by them (the running stack
    pointer after the root is >= 1). Validity is accumulated as a per-row
    vector mask (one cross-lane reduction at the end instead of one per
    slot); a row is valid iff every node output at that row is finite —
    equivalent to the reference's per-node buffer check
    (/root/reference/src/LossFunctions.jl:96-99 semantics).
    """
    a = arity_ref[t, k]
    o = op_ref[t, k]
    d = dst_ref[t, k]
    tile = stack_ref.shape[-1]

    def leaf_val():
        x_row = x_ref[feat_ref[t, k], :]
        c = jnp.full((tile,), const_ref[t, k], dtype=x_ref.dtype)
        return jnp.where(o == LEAF_CONST, c, x_row)

    def unary_val():
        child = stack_ref[t, d, :]
        if len(unary_fns) == 1:
            return unary_fns[0](child)
        return jax.lax.switch(o, unary_fns, child)

    def binary_val():
        l = stack_ref[t, d, :]
        r = stack_ref[t, d + 1, :]
        if len(binary_fns) == 1:
            return binary_fns[0](l, r)
        return jax.lax.switch(o, binary_fns, l, r)

    branches = [leaf_val]
    branches.append(unary_val if unary_fns else leaf_val)
    branches.append(binary_val if binary_fns else leaf_val)
    val = jax.lax.switch(a, branches)

    stack_ref[t, d, :] = val
    # float accumulator: Mosaic miscompiles bool vectors as loop carries
    return vmask * jnp.isfinite(val).astype(vmask.dtype)


# ---------------------------------------------------------------------------
# Program kernel: leaf-free interpreter over a unified VMEM value buffer
# ---------------------------------------------------------------------------
#
# See ops/program.py for the lowering. The interpreter state is one
# buffer of row vectors:
#   buf[0:F]        X feature rows (copied once per grid step)
#   buf[F:BASE]     this tree's constant leaves, broadcast across rows
#   buf[BASE+k]     result of program step k
# Steps dispatch ONE merged opcode (identity | unary ops | binary ops)
# via lax.switch; operands are uniform dynamic reads buf[src], so leaf
# handling, the arity switch, and the per-operand source selects all
# disappear from the inner loop. Steps per tree = internal nodes only.


def _merged_branches(operators: OperatorSet, read, i1, i2):
    """Branch list for the merged opcode switch at one program step.

    Order matches ops/program.py's code assignment: 0 = identity (for
    leaf-only trees), then binary ops (the most frequent class — the
    switch tests codes in order), then unary. Operand reads (``read`` is
    the kernel's buffer accessor) live inside each branch so unary steps
    never touch src2.
    """
    branches = [lambda: read(i1)]
    for o in operators.binary:
        branches.append(lambda f=o.fn: f(read(i1), read(i2)))
    for o in operators.unary:
        branches.append(lambda f=o.fn: f(read(i1)))
    return branches


def _unpack(w):
    """Instruction word -> (opcode, src1, src2); see pack in the wrappers."""
    return w >> 24, (w >> 12) & 0xFFF, w & 0xFFF


def _pack_instr(prog: TreeProgram) -> jax.Array:
    """[T, L] int32 instruction words (op << 24 | src1 << 12 | src2)."""
    return (prog.code << 24) | (prog.src1 << 12) | prog.src2


def _check_packable(operators: OperatorSet, base: int, max_steps: int) -> None:
    """Fail loudly (at trace time) when a configuration overflows the
    packed fields: 12-bit operand addresses, 7-bit opcodes (bit 31 must
    stay clear — the unpack uses an arithmetic shift)."""
    n_codes = 1 + len(operators.binary) + len(operators.unary)
    if base + max_steps > 4096:
        raise ValueError(
            f"Buffer address space {base + max_steps} exceeds the packed "
            f"12-bit operand field (nfeatures + cmax + max_nodes <= 4096)."
        )
    if n_codes > 127:
        raise ValueError(
            f"{n_codes} merged opcodes exceed the packed 7-bit field.")


def _make_program_kernel(
    operators: OperatorSet,
    loss_fn: Callable,
    tree_block: int,
    nfeat: int,
    cmax: int,
):
    BASE = nfeat + cmax

    def kernel(
        instr_ref,   # SMEM [TB, L] packed instruction words
        nstep_ref,   # SMEM [TB, 1]
        nconst_ref,  # SMEM [TB, 1]
        cvals_ref,   # SMEM [TB, CMAX] f32
        ok_ref,      # SMEM [TB, 1] int32 — const_ok from the program
        x_ref,       # VMEM [F, TILE]
        y_ref,       # VMEM [1, TILE]
        w_ref,       # VMEM [1, TILE]
        mask_ref,    # VMEM [1, TILE] f32: 1.0 real rows
        loss_ref,    # SMEM out [TB, 1] f32
        valid_ref,   # SMEM out [TB, 1] int32
        buf_ref,     # VMEM scratch [BASE + L, TILE]
    ):
        j = pl.program_id(1)
        y_row = y_ref[0, :]
        mask_row = mask_ref[0, :] > 0
        w_row = w_ref[0, :] * mask_ref[0, :]
        tile = y_row.shape[0]
        L = instr_ref.shape[-1]

        buf_ref[0:nfeat, :] = x_ref[...]

        for t in range(tree_block):
            def cbody(c, _):
                buf_ref[nfeat + c, :] = jnp.full(
                    (tile,), cvals_ref[t, c], dtype=y_row.dtype)
                return 0

            jax.lax.fori_loop(0, nconst_ref[t, 0], cbody, 0)

            def step(k, vmask):
                o, i1, i2 = _unpack(instr_ref[t, k])
                val = jax.lax.switch(
                    o, _merged_branches(
                        operators, lambda i: buf_ref[i, :], i1, i2))
                buf_ref[BASE + k, :] = val
                return vmask * jnp.isfinite(val).astype(vmask.dtype)

            m = nstep_ref[t, 0]

            # 2x-unrolled loop: the scalar-core loop overhead is a real
            # fraction of the ~hundreds of cycles each step costs. Odd
            # tails re-execute a clamped step idempotently (identity-coded
            # padding rows read a real, finite address).
            def pair(k2, vmask):
                vmask = step(2 * k2, vmask)
                vmask = step(jnp.minimum(2 * k2 + 1, L - 1), vmask)
                return vmask

            vmask0 = jnp.ones((tile,), y_row.dtype)
            vmask = jax.lax.fori_loop(0, (m + 1) >> 1, pair, vmask0)
            valid = jnp.all((vmask > 0) | jnp.logical_not(mask_row))
            pred = buf_ref[BASE + m - 1, :]
            elt = loss_fn(pred, y_row)
            elt = jnp.where(w_row > 0, elt, 0.0)
            partial = jnp.sum(elt * w_row)
            partial_ok = jnp.int32(valid & jnp.isfinite(partial)) * ok_ref[t, 0]

            @pl.when(j == 0)
            def _():
                loss_ref[t, 0] = partial
                valid_ref[t, 0] = partial_ok

            @pl.when(j != 0)
            def _():
                loss_ref[t, 0] = loss_ref[t, 0] + partial
                valid_ref[t, 0] = valid_ref[t, 0] & partial_ok

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "nfeatures", "operators", "loss_fn", "tree_block", "tile_rows",
        "interpret",
    ),
)
def fused_loss_program(
    prog: TreeProgram,          # flat [T, L] program
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    tile_rows: int = 16384,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Mean elementwise loss per compiled tree program (flat [T])."""
    T, L = prog.code.shape
    CMAX = prog.cmax
    F, n = X.shape
    dtype = X.dtype
    BASE = nfeatures + CMAX
    _check_packable(operators, BASE, L)

    TB = tree_block
    bytes_per = jnp.dtype(dtype).itemsize
    TILE = _pick_tile(n, tile_rows, BASE + L, bytes_per)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_t(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    instr = pad_t(_pack_instr(prog))
    nsteps = pad_t(prog.nsteps.reshape(-1, 1), fill=1)
    nconst = pad_t(prog.nconst.reshape(-1, 1))
    cvals = pad_t(prog.cvals).astype(dtype)
    ok = pad_t(prog.const_ok.astype(jnp.int32).reshape(-1, 1), fill=1)

    Xp = jnp.pad(X, ((0, 0), (0, n_pad - n)))
    yp = jnp.pad(y.reshape(1, n), ((0, 0), (0, n_pad - n)))
    w = (jnp.ones((1, n), dtype) if weights is None
         else weights.reshape(1, n).astype(dtype))
    wp = jnp.pad(w, ((0, 0), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_program_kernel(operators, loss_fn, TB, nfeatures, CMAX)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )
    row_spec = pl.BlockSpec((1, TILE), lambda i, j: (0, j))

    loss_sum, valid = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, L)),                       # instr
            smem_i32((TB, 1)),                       # nsteps
            smem_i32((TB, 1)),                       # nconst
            pl.BlockSpec((TB, CMAX), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),   # cvals
            smem_i32((TB, 1)),                       # const_ok
            pl.BlockSpec((F, TILE), lambda i, j: (0, j)),  # X
            row_spec,                                # y
            row_spec,                                # w
            row_spec,                                # mask
        ],
        out_specs=[
            pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, 1), dtype),
            jax.ShapeDtypeStruct((T_pad, 1), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((BASE + L, TILE), dtype)],
        interpret=interpret,
    )(instr, nsteps, nconst, cvals, ok, Xp, yp, wp, maskp)

    loss_sum = loss_sum[:T, 0]
    valid = valid[:T, 0].astype(jnp.bool_)
    denom = jnp.sum(w) if weights is not None else jnp.asarray(n, dtype)
    loss = loss_sum / denom
    loss = jnp.where(valid & jnp.isfinite(loss), loss, jnp.inf)
    return loss, valid


# ---------------------------------------------------------------------------
# Multi-variant program kernel: one dispatch, V constant vectors
# ---------------------------------------------------------------------------
#
# The BFGS line search evaluates every selected tree with R*C different
# constant vectors per iteration; replicating the tree per variant pays
# the (dominant) per-step scalar dispatch cost V times for identical
# instruction streams. Here the value buffer grows a variants axis —
# buf[slot, v, rows] — so each step's single dispatch drives V row
# vectors: dispatch cost per *eval* drops ~V-fold while the vector work
# is the same total. X rows are replicated across v (variant-independent
# but kept in the unified address space); only the const region differs.


def _make_multi_kernel(
    operators: OperatorSet,
    loss_fn: Callable,
    tree_block: int,
    nfeat: int,
    cmax: int,
    nvar: int,
):
    BASE = nfeat + cmax
    V = nvar

    def kernel(
        instr_ref,   # SMEM [TB, L]
        nstep_ref,   # SMEM [TB, 1]
        nconst_ref,  # SMEM [TB, 1]
        cvals_ref,   # SMEM [TB, V * CMAX] f32 (variant-major)
        x_ref,       # VMEM [F, TILE]
        y_ref,       # VMEM [1, TILE]
        w_ref,       # VMEM [1, TILE]
        mask_ref,    # VMEM [1, TILE]
        loss_ref,    # VMEM out [TB, V] f32
        valid_ref,   # VMEM out [TB, V] int32
        buf_ref,     # VMEM scratch [BASE + L, V, TILE]
    ):
        j = pl.program_id(1)
        y_row = y_ref[0, :]
        mask_row = mask_ref[0, :] > 0
        w_row = w_ref[0, :] * mask_ref[0, :]
        tile = y_row.shape[0]
        L = instr_ref.shape[-1]

        buf_ref[0:nfeat, :, :] = jnp.broadcast_to(
            x_ref[...][:, None, :], (nfeat, V, tile))

        for t in range(tree_block):
            def cbody(c, _):
                for v in range(V):
                    buf_ref[nfeat + c, v, :] = jnp.full(
                        (tile,), cvals_ref[t, v * cmax + c],
                        dtype=y_row.dtype)
                return 0

            jax.lax.fori_loop(0, nconst_ref[t, 0], cbody, 0)

            def step(k, vmask):
                o, i1, i2 = _unpack(instr_ref[t, k])
                val = jax.lax.switch(
                    o, _merged_branches(
                        operators, lambda i: buf_ref[i, :, :], i1, i2))
                buf_ref[BASE + k, :, :] = val
                return vmask * jnp.isfinite(val).astype(vmask.dtype)

            m = nstep_ref[t, 0]

            def pair(k2, vmask):
                vmask = step(2 * k2, vmask)
                return step(jnp.minimum(2 * k2 + 1, L - 1), vmask)

            vmask0 = jnp.ones((V, tile), y_row.dtype)
            vmask = jax.lax.fori_loop(0, (m + 1) >> 1, pair, vmask0)
            validv = jnp.all(
                (vmask > 0) | jnp.logical_not(mask_row)[None, :], axis=1)
            pred = buf_ref[BASE + m - 1, :, :]            # [V, TILE]
            elt = loss_fn(pred, y_row[None, :])
            elt = jnp.where(w_row[None, :] > 0, elt, 0.0)
            partial = jnp.sum(elt * w_row[None, :], axis=1)  # [V]
            partial_ok = (validv & jnp.isfinite(partial)).astype(jnp.int32)

            @pl.when(j == 0)
            def _():
                loss_ref[t, :] = partial
                valid_ref[t, :] = partial_ok

            @pl.when(j != 0)
            def _():
                loss_ref[t, :] = loss_ref[t, :] + partial
                valid_ref[t, :] = valid_ref[t, :] & partial_ok

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "nfeatures", "operators", "loss_fn", "tree_block", "interpret",
    ),
)
def fused_loss_multi(
    prog: TreeProgram,          # flat [T, L] program
    cvals_v: jax.Array,         # [T, V, CMAX] constant vectors per variant
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Mean loss for every (tree, constant-variant) pair: [T, V] each.

    One instruction-stream dispatch per tree serves all V variants;
    invalid pairs (non-finite eval or non-finite constants) get inf.
    """
    T, L = prog.code.shape
    CMAX = prog.cmax
    V = cvals_v.shape[1]
    F, n = X.shape
    dtype = X.dtype
    BASE = nfeatures + CMAX
    _check_packable(operators, BASE, L)

    TB = tree_block
    bytes_per = jnp.dtype(dtype).itemsize
    TILE = _pick_tile(n, n, (BASE + L) * V, bytes_per, budget=8 * 2**20)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_t(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    instr = pad_t(_pack_instr(prog))
    nsteps = pad_t(prog.nsteps.reshape(-1, 1), fill=1)
    nconst = pad_t(prog.nconst.reshape(-1, 1))
    cflat = pad_t(cvals_v.reshape(T, V * CMAX)).astype(dtype)

    Xp = jnp.pad(X, ((0, 0), (0, n_pad - n)))
    yp = jnp.pad(y.reshape(1, n), ((0, 0), (0, n_pad - n)))
    w = (jnp.ones((1, n), dtype) if weights is None
         else weights.reshape(1, n).astype(dtype))
    wp = jnp.pad(w, ((0, 0), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_multi_kernel(operators, loss_fn, TB, nfeatures, CMAX, V)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )
    row_spec = pl.BlockSpec((1, TILE), lambda i, j: (0, j))

    loss_sum, valid = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, L)),                       # instr
            smem_i32((TB, 1)),                       # nsteps
            smem_i32((TB, 1)),                       # nconst
            pl.BlockSpec((TB, V * CMAX), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),   # cvals
            pl.BlockSpec((F, TILE), lambda i, j: (0, j)),  # X
            row_spec,                                # y
            row_spec,                                # w
            row_spec,                                # mask
        ],
        out_specs=[
            pl.BlockSpec((TB, V), lambda i, j: (i, 0)),
            pl.BlockSpec((TB, V), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, V), dtype),
            jax.ShapeDtypeStruct((T_pad, V), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((BASE + L, V, TILE), dtype)],
        interpret=interpret,
    )(instr, nsteps, nconst, cflat, Xp, yp, wp, maskp)

    loss_sum = loss_sum[:T]
    valid = valid[:T].astype(jnp.bool_)
    # const_ok per variant, applied outside the kernel
    used = (jnp.arange(CMAX, dtype=jnp.int32)[None, None, :]
            < prog.nconst[:, None, None])
    ok_v = jnp.all(jnp.isfinite(cvals_v) | ~used, axis=-1)
    valid = valid & ok_v
    denom = jnp.sum(w) if weights is not None else jnp.asarray(n, dtype)
    loss = loss_sum / denom
    loss = jnp.where(valid & jnp.isfinite(loss), loss, jnp.inf)
    return loss, valid


# ---------------------------------------------------------------------------
# Program kernel, forward + backward: loss and d(loss)/d(const) fused
# ---------------------------------------------------------------------------
#
# The adjoint sweep mirrors the forward program in reverse over the same
# unified buffer addressing: step k's cotangent lives at adj[BASE+k],
# operand contributions accumulate at adj[src] — which for constant-leaf
# operands IS the const region, so per-constant gradients fall out as
# row sums of adj[F : F+CMAX] with no slot bookkeeping in the kernel.
# (X-region adjoint rows accumulate too and are simply never read.)


def _make_multi_grad_kernel(
    operators: OperatorSet,
    loss_fn: Callable,
    tree_block: int,
    nfeat: int,
    cmax: int,
    nvar: int,
):
    unary_fns = tuple(op.fn for op in operators.unary)
    binary_fns = tuple(op.fn for op in operators.binary)
    BASE = nfeat + cmax
    V = nvar

    def kernel(
        instr_ref,   # SMEM [TB, L] packed instruction words
        nstep_ref,   # SMEM [TB, 1]
        nconst_ref,  # SMEM [TB, 1]
        cvals_ref,   # SMEM [TB, V * CMAX] f32 (variant-major)
        x_ref,       # VMEM [F, TILE]
        y_ref,       # VMEM [1, TILE]
        w_ref,       # VMEM [1, TILE]
        mask_ref,    # VMEM [1, TILE]
        loss_ref,    # VMEM out [TB, V] f32
        valid_ref,   # VMEM out [TB, V] int32
        gcomp_ref,   # VMEM out [TB, CMAX, V] — d loss_sum / d cvals
        buf_ref,     # VMEM scratch [BASE + L, V, TILE]
        adj_ref,     # VMEM scratch [BASE + L, V, TILE]
    ):
        j = pl.program_id(1)
        y_row = y_ref[0, :]
        mask_row = mask_ref[0, :] > 0
        w_row = w_ref[0, :] * mask_ref[0, :]
        tile = y_row.shape[0]
        B = len(binary_fns)
        L = instr_ref.shape[-1]
        read = lambda i: buf_ref[i, :, :]

        buf_ref[0:nfeat, :, :] = jnp.broadcast_to(
            x_ref[...][:, None, :], (nfeat, V, tile))

        for t in range(tree_block):
            def cbody(c, _):
                for v in range(V):
                    buf_ref[nfeat + c, v, :] = jnp.full(
                        (tile,), cvals_ref[t, v * cmax + c],
                        dtype=y_row.dtype)
                return 0

            jax.lax.fori_loop(0, nconst_ref[t, 0], cbody, 0)

            def fwd(k, vmask):
                o, i1, i2 = _unpack(instr_ref[t, k])
                val = jax.lax.switch(
                    o, _merged_branches(operators, read, i1, i2))
                buf_ref[BASE + k, :, :] = val
                return vmask * jnp.isfinite(val).astype(vmask.dtype)

            m = nstep_ref[t, 0]

            def fwd_pair(k2, vmask):
                vmask = fwd(2 * k2, vmask)
                return fwd(jnp.minimum(2 * k2 + 1, L - 1), vmask)

            vmask = jax.lax.fori_loop(
                0, (m + 1) >> 1, fwd_pair, jnp.ones((V, tile), y_row.dtype))
            validv = jnp.all(
                (vmask > 0) | jnp.logical_not(mask_row)[None, :], axis=1)

            pred = buf_ref[BASE + m - 1, :, :]             # [V, TILE]
            elt, loss_vjp = jax.vjp(
                lambda p: loss_fn(p, y_row[None, :]), pred)
            elt = jnp.where(w_row[None, :] > 0, elt, 0.0)
            partial = jnp.sum(elt * w_row[None, :], axis=1)  # [V]
            partial_ok = (validv & jnp.isfinite(partial)).astype(jnp.int32)
            (dpred,) = loss_vjp(jnp.broadcast_to(w_row[None, :], (V, tile)))
            dpred = jnp.where(w_row[None, :] > 0, dpred, 0.0)

            # Every node of a tree has exactly ONE parent, so each adjoint
            # slot is written exactly once during the sweep — plain stores,
            # no zero-init of the adjoint buffer, no read-modify-write.
            # (Two operands of one step can only collide in the X region,
            # whose adjoint rows are never read.) Unused const rows hold
            # stale data from earlier trees; the final reduction loops
            # only over the nconst used rows.
            adj_ref[BASE + m - 1, :, :] = dpred

            def bwd(k):
                o, i1, i2 = _unpack(instr_ref[t, k])
                ct = adj_ref[BASE + k, :, :]

                # Padded rows carry zero cotangents but arbitrary operand
                # values, so vjps can produce 0/0 = NaN there; mask before
                # storing or one NaN poisons the gradient sums.
                @pl.when(o == 0)
                def _():
                    adj_ref[i1, :, :] = ct

                if binary_fns:
                    @pl.when((o >= 1) & (o <= B))
                    def _():
                        x1 = read(i1)
                        x2 = read(i2)
                        if len(binary_fns) == 1:
                            db1, db2 = _vjp_binary(binary_fns[0], x1, x2, ct)
                        else:
                            db1, db2 = jax.lax.switch(
                                o - 1,
                                [lambda xx, yy, cc, f=f:
                                 _vjp_binary(f, xx, yy, cc)
                                 for f in binary_fns], x1, x2, ct)
                        adj_ref[i1, :, :] = jnp.where(
                            mask_row[None, :], db1, 0.0)
                        adj_ref[i2, :, :] = jnp.where(
                            mask_row[None, :], db2, 0.0)

                if unary_fns:
                    @pl.when(o > B)
                    def _():
                        x1 = read(i1)
                        if len(unary_fns) == 1:
                            du = _vjp_unary(unary_fns[0], x1, ct)
                        else:
                            du = jax.lax.switch(
                                o - 1 - B,
                                [lambda xx, cc, f=f: _vjp_unary(f, xx, cc)
                                 for f in unary_fns], x1, ct)
                        adj_ref[i1, :, :] = jnp.where(
                            mask_row[None, :], du, 0.0)

            def bwd_pair(i2x, _):
                # descending, 2x-unrolled; the odd tail re-executes step 0
                # idempotently (pure assignments make that safe).
                bwd(m - 1 - 2 * i2x)
                bwd(jnp.maximum(m - 2 - 2 * i2x, 0))
                return 0

            jax.lax.fori_loop(0, (m + 1) >> 1, bwd_pair, 0)

            @pl.when(j == 0)
            def _():
                gcomp_ref[t, :, :] = jnp.zeros(
                    (cmax, V), dtype=y_row.dtype)
                loss_ref[t, :] = partial
                valid_ref[t, :] = partial_ok

            @pl.when(j != 0)
            def _():
                loss_ref[t, :] = loss_ref[t, :] + partial
                valid_ref[t, :] = valid_ref[t, :] & partial_ok

            # Reduce only the USED const rows (dynamic loop over nconst):
            # a full-CMAX masked reduce costs ~CMAX * TILE/1024 vector
            # registers per tree, dominating short trees.
            def gbody(c, _):
                grow = jnp.sum(adj_ref[nfeat + c, :, :], axis=1)  # [V]
                gcomp_ref[t, c, :] = gcomp_ref[t, c, :] + grow
                return 0

            jax.lax.fori_loop(0, nconst_ref[t, 0], gbody, 0)

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "nfeatures", "operators", "loss_fn", "tree_block", "interpret",
    ),
)
def fused_grad_multi(
    prog: TreeProgram,          # flat [T, L] program
    cvals_v: jax.Array,         # [T, V, CMAX]
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(loss [T, V], valid [T, V], dloss/dcvals [T, V, CMAX]) per
    (tree, constant-variant) pair — one instruction dispatch per tree."""
    T, L = prog.code.shape
    CMAX = prog.cmax
    V = cvals_v.shape[1]
    F, n = X.shape
    dtype = X.dtype
    BASE = nfeatures + CMAX
    _check_packable(operators, BASE, L)

    TB = tree_block
    bytes_per = jnp.dtype(dtype).itemsize
    TILE = _pick_tile(n, n, 2 * (BASE + L) * V, bytes_per, budget=8 * 2**20)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_t(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    instr = pad_t(_pack_instr(prog))
    nsteps = pad_t(prog.nsteps.reshape(-1, 1), fill=1)
    nconst = pad_t(prog.nconst.reshape(-1, 1))
    cflat = pad_t(cvals_v.reshape(T, V * CMAX)).astype(dtype)

    Xp = jnp.pad(X, ((0, 0), (0, n_pad - n)))
    yp = jnp.pad(y.reshape(1, n), ((0, 0), (0, n_pad - n)))
    w = (jnp.ones((1, n), dtype) if weights is None
         else weights.reshape(1, n).astype(dtype))
    wp = jnp.pad(w, ((0, 0), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_multi_grad_kernel(operators, loss_fn, TB, nfeatures,
                                     CMAX, V)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )
    row_spec = pl.BlockSpec((1, TILE), lambda i, j: (0, j))

    loss_sum, valid, gcomp = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, L)),                       # instr
            smem_i32((TB, 1)),                       # nsteps
            smem_i32((TB, 1)),                       # nconst
            pl.BlockSpec((TB, V * CMAX), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),   # cvals
            pl.BlockSpec((F, TILE), lambda i, j: (0, j)),  # X
            row_spec,                                # y
            row_spec,                                # w
            row_spec,                                # mask
        ],
        out_specs=[
            pl.BlockSpec((TB, V), lambda i, j: (i, 0)),
            pl.BlockSpec((TB, V), lambda i, j: (i, 0)),
            pl.BlockSpec((TB, CMAX, V), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, V), dtype),
            jax.ShapeDtypeStruct((T_pad, V), jnp.int32),
            jax.ShapeDtypeStruct((T_pad, CMAX, V), dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((BASE + L, V, TILE), dtype),
            pltpu.VMEM((BASE + L, V, TILE), dtype),
        ],
        interpret=interpret,
    )(instr, nsteps, nconst, cflat, Xp, yp, wp, maskp)

    loss_sum = loss_sum[:T]
    valid = valid[:T].astype(jnp.bool_)
    gcomp = jnp.swapaxes(gcomp[:T], 1, 2)              # [T, V, CMAX]
    used = (jnp.arange(CMAX, dtype=jnp.int32)[None, None, :]
            < prog.nconst[:, None, None])
    ok_v = jnp.all(jnp.isfinite(cvals_v) | ~used, axis=-1)
    valid = valid & ok_v
    denom = jnp.sum(w) if weights is not None else jnp.asarray(n, dtype)
    loss = loss_sum / denom
    grad = gcomp / denom
    bad = ~(valid & jnp.isfinite(loss))
    loss = jnp.where(bad, jnp.inf, loss)
    grad = jnp.where(bad[..., None] | ~jnp.isfinite(grad), 0.0, grad)
    return loss, valid, grad


def fused_grad_program(
    prog: TreeProgram,          # flat [T, L] program
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    nfeatures: int,
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    tile_rows: int = 16384,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(loss [T], valid [T], dloss/dcvals [T, CMAX]) — the single-variant
    view of `fused_grad_multi` (V = 1, constants from ``prog.cvals``)."""
    del tile_rows
    loss, valid, grad = fused_grad_multi(
        prog, prog.cvals[:, None, :], X, y, weights, nfeatures, operators,
        loss_fn, tree_block=tree_block, interpret=interpret,
    )
    return loss[:, 0], valid[:, 0], grad[:, 0]


@functools.partial(
    jax.jit,
    static_argnames=(
        "operators", "loss_fn", "tree_block", "tile_rows", "interpret",
    ),
)
def fused_loss(
    trees: TreeBatch,
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],  # [n] or None
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    tile_rows: int = 16384,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Mean elementwise loss per tree, fused on TPU.

    Returns ``(loss[...], valid[...])`` with the TreeBatch's batch dims;
    invalid trees get loss=inf (matching aggregate_loss semantics).
    Compiles the batch to a leaf-free TreeProgram (ops/program.py) and
    runs the unified-buffer kernel; callers that re-evaluate the same
    structures with different constants (line searches) should compile
    once and use `fused_loss_program` + `update_consts` directly.
    """
    batch_shape = trees.batch_shape
    flat = trees.reshape(-1) if batch_shape else trees.reshape(1)
    F = X.shape[0]
    prog = compile_program(flat, F, len(operators.binary))
    loss, valid = fused_loss_program(
        prog, X, y, weights, F, operators, loss_fn,
        tree_block=tree_block, tile_rows=tile_rows, interpret=interpret,
    )
    if batch_shape:
        return loss.reshape(batch_shape), valid.reshape(batch_shape)
    return loss[0], valid[0]


# ---------------------------------------------------------------------------
# Fused predictions: per-tree row outputs (no loss reduction)
# ---------------------------------------------------------------------------
#
# Used by template expressions: each subexpression call site evaluates a
# whole member-batch of subtrees over a shared argument matrix and needs
# the raw predictions back for the combiner's ValidVector algebra
# (models/template.py). Same VMEM-stack interpreter as `fused_loss`, but
# the root rows stream out instead of folding into a loss scalar.


def _make_predict_kernel(operators: OperatorSet, max_nodes: int,
                         tree_block: int):
    unary_fns = tuple(op.fn for op in operators.unary)
    binary_fns = tuple(op.fn for op in operators.binary)

    def kernel(
        arity_ref,   # SMEM [TB, L]
        op_ref,      # SMEM [TB, L]
        feat_ref,    # SMEM [TB, L]
        dst_ref,     # SMEM [TB, L]
        length_ref,  # SMEM [TB, 1]
        const_ref,   # SMEM [TB, L] f32
        x_ref,       # VMEM [F, TILE]
        mask_ref,    # VMEM [1, TILE] f32: 1.0 real rows
        pred_ref,    # VMEM out [TB, TILE]
        valid_ref,   # SMEM out [TB, 1] int32
        stack_ref,   # VMEM scratch [TB, S, TILE]
    ):
        j = pl.program_id(1)
        mask_row = mask_ref[0, :] > 0
        tile = mask_row.shape[0]

        for t in range(tree_block):
            def body(k, vmask):
                return _tree_kernel_body(
                    t, k, arity_ref, op_ref, feat_ref, dst_ref, const_ref,
                    x_ref, stack_ref, vmask,
                    unary_fns, binary_fns,
                )

            vmask = jax.lax.fori_loop(
                0, length_ref[t, 0], body,
                jnp.ones((tile,), x_ref.dtype),
            )
            valid = jnp.all((vmask > 0) | jnp.logical_not(mask_row))
            pred_ref[t, :] = stack_ref[t, 0, :]
            partial_ok = jnp.int32(valid)

            @pl.when(j == 0)
            def _():
                valid_ref[t, 0] = partial_ok

            @pl.when(j != 0)
            def _():
                valid_ref[t, 0] = valid_ref[t, 0] & partial_ok

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("operators", "tree_block", "tile_rows", "interpret"),
)
def fused_predict(
    trees: TreeBatch,
    X: jax.Array,               # [F, n]
    operators: OperatorSet,
    *,
    tree_block: int = 8,
    tile_rows: int = 16384,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Per-tree predictions over all rows, fused on TPU.

    Returns ``(pred[..., n], valid[...])`` with the TreeBatch's batch
    dims; validity matches the interpreter (any non-finite node output
    over the rows invalidates the tree).
    """
    batch_shape = trees.batch_shape
    flat = trees.reshape(-1) if batch_shape else trees.reshape(1)
    T = flat.length.shape[0]
    L = flat.arity.shape[-1]
    F, n = X.shape
    dtype = X.dtype

    TB = tree_block
    S = L // 2 + 2
    bytes_per = jnp.dtype(dtype).itemsize
    TILE = _pick_tile(n, tile_rows, TB * S, bytes_per)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_trees(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    arity = pad_trees(flat.arity)
    op = pad_trees(flat.op)
    feat = jnp.clip(pad_trees(flat.feat), 0, F - 1)
    const = pad_trees(flat.const).astype(dtype)
    length = jnp.clip(pad_trees(flat.length.reshape(-1, 1), fill=1), 1, L)
    dst = jnp.clip(stack_positions(arity), 0, S - 1)

    Xp = jnp.pad(X, ((0, 0), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_predict_kernel(operators, L, TB)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )

    pred, valid = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, L)),                       # arity
            smem_i32((TB, L)),                       # op
            smem_i32((TB, L)),                       # feat
            smem_i32((TB, L)),                       # dst
            smem_i32((TB, 1)),                       # length
            pl.BlockSpec((TB, L), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),   # const
            pl.BlockSpec((F, TILE), lambda i, j: (0, j)),  # X
            pl.BlockSpec((1, TILE), lambda i, j: (0, j)),  # mask
        ],
        out_specs=[
            pl.BlockSpec((TB, TILE), lambda i, j: (i, j)),
            pl.BlockSpec((TB, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T_pad, n_pad), dtype),
            jax.ShapeDtypeStruct((T_pad, 1), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((TB, S, TILE), dtype)],
        interpret=interpret,
    )(arity, op, feat, dst, length, const, Xp, maskp)

    pred = pred[:T, :n]
    valid = valid[:T, 0].astype(jnp.bool_)
    if batch_shape:
        return pred.reshape(*batch_shape, n), valid.reshape(batch_shape)
    return pred[0], valid[0]


# ---------------------------------------------------------------------------
# fused_predict VJP: cotangent-seeded constant gradients
# ---------------------------------------------------------------------------
#
# Differentiable prediction powers template constant optimization: the
# combiner's elementwise algebra is differentiated by JAX as usual, and
# each fused call site's backward contracts the incoming row cotangent
# with the subtree's adjoint sweep in one kernel — no [M, L, n]
# interpreter buffers. ``X`` is treated as constant data (zero
# cotangent): fused call sites only ever receive dataset columns (the
# batched template evaluator routes member-dependent arguments through
# the jnp interpreter, which differentiates natively).


def _make_predict_vjp_kernel(operators: OperatorSet, max_nodes: int,
                             tree_block: int):
    unary_fns = tuple(op.fn for op in operators.unary)
    binary_fns = tuple(op.fn for op in operators.binary)
    L = max_nodes

    def kernel(
        arity_ref,   # SMEM [TB, L]
        op_ref,      # SMEM [TB, L]
        feat_ref,    # SMEM [TB, L]
        child1_ref,  # SMEM [TB, L]
        child2_ref,  # SMEM [TB, L]
        root_ref,    # SMEM [TB, 1]
        const_ref,   # SMEM [TB, L] f32
        cmask_ref,   # VMEM [TB, L] f32
        x_ref,       # VMEM [F, TILE]
        ct_ref,      # VMEM [TB, TILE] — incoming row cotangents
        mask_ref,    # VMEM [1, TILE]
        gconst_ref,  # VMEM out [TB, L]
        buf_ref,     # VMEM scratch [L, TILE]
        adj_ref,     # VMEM scratch [L, TILE]
    ):
        j = pl.program_id(1)
        mask_row = mask_ref[0, :] > 0
        tile = mask_ref.shape[-1]

        for t in range(tree_block):
            root = root_ref[t, 0]

            def fwd(k, _):
                a = arity_ref[t, k]
                o = op_ref[t, k]

                def leaf_val():
                    x_row = x_ref[feat_ref[t, k], :]
                    c = jnp.full((tile,), const_ref[t, k], dtype=x_ref.dtype)
                    return jnp.where(o == LEAF_CONST, c, x_row)

                def unary_val():
                    child = buf_ref[child1_ref[t, k], :]
                    if len(unary_fns) == 1:
                        return unary_fns[0](child)
                    return jax.lax.switch(o, unary_fns, child)

                def binary_val():
                    l = buf_ref[child1_ref[t, k], :]
                    r = buf_ref[child2_ref[t, k], :]
                    if len(binary_fns) == 1:
                        return binary_fns[0](l, r)
                    return jax.lax.switch(o, binary_fns, l, r)

                branches = [leaf_val]
                branches.append(unary_val if unary_fns else leaf_val)
                branches.append(binary_val if binary_fns else leaf_val)
                buf_ref[k, :] = jax.lax.switch(a, branches)
                return 0

            jax.lax.fori_loop(0, root + 1, fwd, 0)

            adj_ref[...] = jnp.zeros((L, tile), dtype=x_ref.dtype)
            adj_ref[root, :] = jnp.where(mask_row, ct_ref[t, :], 0.0)

            def bwd(i, _):
                k = root - i
                a = arity_ref[t, k]
                o = op_ref[t, k]
                c1 = child1_ref[t, k]
                c2 = child2_ref[t, k]
                ct = adj_ref[k, :]
                x1 = buf_ref[c1, :]
                x2 = buf_ref[c2, :]

                if unary_fns:
                    @pl.when(a == 1)
                    def _():
                        if len(unary_fns) == 1:
                            du = _vjp_unary(unary_fns[0], x1, ct)
                        else:
                            du = jax.lax.switch(
                                o, [lambda xx, cc, f=f: _vjp_unary(f, xx, cc)
                                    for f in unary_fns], x1, ct)
                        du = jnp.where(mask_row, du, 0.0)
                        adj_ref[c1, :] = adj_ref[c1, :] + du

                if binary_fns:
                    @pl.when(a == 2)
                    def _():
                        if len(binary_fns) == 1:
                            db1, db2 = _vjp_binary(binary_fns[0], x1, x2, ct)
                        else:
                            db1, db2 = jax.lax.switch(
                                o, [lambda xx, yy, cc, f=f:
                                    _vjp_binary(f, xx, yy, cc)
                                    for f in binary_fns], x1, x2, ct)
                        db1 = jnp.where(mask_row, db1, 0.0)
                        db2 = jnp.where(mask_row, db2, 0.0)
                        adj_ref[c1, :] = adj_ref[c1, :] + db1
                        adj_ref[c2, :] = adj_ref[c2, :] + db2
                return 0

            jax.lax.fori_loop(0, root + 1, bwd, 0)
            grow = jnp.sum(adj_ref[...], axis=1) * cmask_ref[t, :]

            @pl.when(j == 0)
            def _():
                gconst_ref[t, :] = grow

            @pl.when(j != 0)
            def _():
                gconst_ref[t, :] = gconst_ref[t, :] + grow

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("operators", "tree_block", "tile_rows", "interpret"),
)
def _fused_predict_vjp(
    trees: TreeBatch,           # [T, L] flat
    X: jax.Array,               # [F, n]
    ct: jax.Array,              # [T, n] row cotangents
    operators: OperatorSet,
    *,
    tree_block: int = 8,
    tile_rows: int = 16384,
    interpret: bool = False,
) -> jax.Array:
    """d(sum(ct * pred)) / d(trees.const) — [T, L], zero off constant
    leaves, non-finite contributions zeroed."""
    T, L = trees.arity.shape
    F, n = X.shape
    dtype = X.dtype
    child, _, _ = tree_structure_arrays(trees, need_depth=False)

    TB = tree_block
    bytes_per = jnp.dtype(dtype).itemsize
    TILE = _pick_tile(n, tile_rows, 2 * L + TB, bytes_per)
    T_pad = _round_up(T, TB)
    n_pad = _round_up(n, TILE)

    def pad_trees(x, fill=0):
        return jnp.pad(x, ((0, T_pad - T),) + ((0, 0),) * (x.ndim - 1),
                       constant_values=fill)

    arity = pad_trees(trees.arity)
    op = pad_trees(trees.op)
    feat = jnp.clip(pad_trees(trees.feat), 0, F - 1)
    const = pad_trees(trees.const).astype(dtype)
    child1 = jnp.clip(pad_trees(child[..., 0]), 0, L - 1)
    child2 = jnp.clip(pad_trees(child[..., 1]), 0, L - 1)
    root = jnp.clip(pad_trees(trees.length.reshape(-1, 1), fill=1) - 1, 0, L - 1)
    slot = jnp.arange(L)
    cmask = (
        (slot[None, :] < trees.length[:, None])
        & (trees.arity == 0)
        & (trees.op == LEAF_CONST)
    ).astype(dtype)
    cmask = pad_trees(cmask)

    Xp = jnp.pad(X, ((0, 0), (0, n_pad - n)))
    ctp = jnp.pad(ct.astype(dtype), ((0, T_pad - T), (0, n_pad - n)))
    maskp = jnp.pad(jnp.ones((1, n), dtype), ((0, 0), (0, n_pad - n)))

    grid = (T_pad // TB, n_pad // TILE)
    kernel = _make_predict_vjp_kernel(operators, L, TB)

    smem_i32 = lambda shape: pl.BlockSpec(
        shape, lambda i, j: (i, 0), memory_space=pltpu.SMEM
    )

    (gconst,) = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            smem_i32((TB, L)),
            smem_i32((TB, L)),
            smem_i32((TB, L)),
            smem_i32((TB, L)),
            smem_i32((TB, L)),
            smem_i32((TB, 1)),
            pl.BlockSpec((TB, L), lambda i, j: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((TB, L), lambda i, j: (i, 0)),       # cmask
            pl.BlockSpec((F, TILE), lambda i, j: (0, j)),     # X
            pl.BlockSpec((TB, TILE), lambda i, j: (i, j)),    # ct
            pl.BlockSpec((1, TILE), lambda i, j: (0, j)),     # mask
        ],
        out_specs=[
            pl.BlockSpec((TB, L), lambda i, j: (i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((T_pad, L), dtype)],
        scratch_shapes=[
            pltpu.VMEM((L, TILE), dtype),
            pltpu.VMEM((L, TILE), dtype),
        ],
        interpret=interpret,
    )(arity, op, feat, child1, child2, root, const, cmask, Xp, ctp, maskp)

    gconst = gconst[:T]
    return jnp.where(jnp.isfinite(gconst), gconst, 0.0)


_PREDICT_AD_CACHE: dict = {}


def fused_predict_ad(trees: TreeBatch, X: jax.Array, operators: OperatorSet,
                     *, interpret: bool = False):
    """`fused_predict` with a custom VJP w.r.t. the constant leaves.

    Gradients flow into ``trees.const`` only; ``X`` and the structural
    int fields get zero cotangents (fused template call sites receive
    dataset columns, which are constants of the optimization).
    Flat [T, L] trees only.
    """
    key = (operators, interpret)
    if key not in _PREDICT_AD_CACHE:
        def primal(arity, op, feat, const, length, X):
            return fused_predict(
                TreeBatch(arity, op, feat, const, length), X, operators,
                interpret=interpret,
            )

        f = jax.custom_vjp(primal)

        def fwd(arity, op, feat, const, length, X):
            out = primal(arity, op, feat, const, length, X)
            return out, (arity, op, feat, const, length, X)

        def bwd(res, cts):
            arity, op, feat, const, length, X = res
            ct_pred, _ = cts  # valid output is boolean (float0 cotangent)
            gconst = _fused_predict_vjp(
                TreeBatch(arity, op, feat, const, length), X, ct_pred,
                operators, interpret=interpret,
            )
            f0 = lambda x: np.zeros(x.shape, jax.dtypes.float0)
            return (f0(arity), f0(op), f0(feat), gconst, f0(length),
                    jnp.zeros_like(X))

        f.defvjp(fwd, bwd)
        _PREDICT_AD_CACHE[key] = f
    f = _PREDICT_AD_CACHE[key]
    return f(trees.arity, trees.op, trees.feat, trees.const, trees.length, X)


# ---------------------------------------------------------------------------
# Fused forward + backward: loss and d(loss)/d(const) in one kernel
# ---------------------------------------------------------------------------
#
# This replaces `jax.grad` through the jnp interpreter for constant
# optimization (the reference's Enzyme/Mooncake reverse pass,
# /root/reference/src/ConstantOptimization.jl:136-167). The jnp/AD path
# materializes [T, L, n] forward buffers in HBM per gradient evaluation —
# the dominant cost (and OOM source) of the whole search iteration. Here
# the per-tree value buffer and adjoint live in VMEM, derivative code for
# each operator is generated at trace time with `jax.vjp` on the op's own
# fn (so custom traceable operators differentiate automatically), and the
# only HBM traffic is the X/y row tiles plus a [T, L] gradient output.


def _vjp_unary(fn, x, ct):
    _, vjp = jax.vjp(fn, x)
    (dx,) = vjp(ct)
    return dx


def _vjp_binary(fn, x, y, ct):
    _, vjp = jax.vjp(fn, x, y)
    dx, dy = vjp(ct)
    return dx, dy


def _make_grad_kernel(
    operators: OperatorSet,
    loss_fn: Callable,
    max_nodes: int,
    tree_block: int,
):
    unary_fns = tuple(op.fn for op in operators.unary)
    binary_fns = tuple(op.fn for op in operators.binary)
    L = max_nodes

    def kernel(
        arity_ref,   # SMEM [TB, L]
        op_ref,      # SMEM [TB, L]
        feat_ref,    # SMEM [TB, L]
        child1_ref,  # SMEM [TB, L]
        child2_ref,  # SMEM [TB, L]
        root_ref,    # SMEM [TB, 1] (length - 1)
        const_ref,   # SMEM [TB, L] f32
        cmask_ref,   # VMEM [TB, L] f32: 1.0 at constant-leaf slots
        x_ref,       # VMEM [F, TILE]
        y_ref,       # VMEM [1, TILE]
        w_ref,       # VMEM [1, TILE]
        mask_ref,    # VMEM [1, TILE]
        loss_ref,    # SMEM out [TB, 1] f32 (loss sum over rows)
        valid_ref,   # SMEM out [TB, 1] int32
        gconst_ref,  # VMEM out [TB, L] f32 (d loss_sum / d const)
        buf_ref,     # VMEM scratch [L, TILE] — forward values per slot
        adj_ref,     # VMEM scratch [L, TILE] — adjoints per slot
    ):
        j = pl.program_id(1)
        y_row = y_ref[0, :]
        mask_row = mask_ref[0, :] > 0
        w_row = w_ref[0, :] * mask_ref[0, :]
        tile = y_row.shape[0]

        for t in range(tree_block):
            root = root_ref[t, 0]

            # ---- forward: slot-indexed buffer interpreter ----
            def fwd(k, vmask):
                a = arity_ref[t, k]
                o = op_ref[t, k]

                def leaf_val():
                    x_row = x_ref[feat_ref[t, k], :]
                    c = jnp.full((tile,), const_ref[t, k], dtype=x_ref.dtype)
                    return jnp.where(o == LEAF_CONST, c, x_row)

                def unary_val():
                    child = buf_ref[child1_ref[t, k], :]
                    if len(unary_fns) == 1:
                        return unary_fns[0](child)
                    return jax.lax.switch(o, unary_fns, child)

                def binary_val():
                    l = buf_ref[child1_ref[t, k], :]
                    r = buf_ref[child2_ref[t, k], :]
                    if len(binary_fns) == 1:
                        return binary_fns[0](l, r)
                    return jax.lax.switch(o, binary_fns, l, r)

                branches = [leaf_val]
                branches.append(unary_val if unary_fns else leaf_val)
                branches.append(binary_val if binary_fns else leaf_val)
                val = jax.lax.switch(a, branches)
                buf_ref[k, :] = val
                return vmask * jnp.isfinite(val).astype(vmask.dtype)

            # Dynamic trip counts (see fused_loss): only the tree's used
            # slots are interpreted, forward and backward.
            vmask = jax.lax.fori_loop(
                0, root + 1, fwd, jnp.ones((tile,), y_row.dtype)
            )
            valid = jnp.all((vmask > 0) | jnp.logical_not(mask_row))

            # ---- loss + dloss/dpred ----
            pred = buf_ref[root, :]
            elt, loss_vjp = jax.vjp(lambda p: loss_fn(p, y_row), pred)
            elt = jnp.where(w_row > 0, elt, 0.0)
            partial = jnp.sum(elt * w_row)
            partial_ok = jnp.int32(valid & jnp.isfinite(partial))
            (dpred,) = loss_vjp(w_row)
            dpred = jnp.where(w_row > 0, dpred, 0.0)

            # ---- backward: adjoint sweep root -> leaves ----
            # Padding slots (arity 0) clip children to slot 0 and carry
            # zero cotangents, so their accumulates are no-ops; pure value
            # switches + masked adds avoid side effects under lax.switch.
            adj_ref[...] = jnp.zeros((L, tile), dtype=y_row.dtype)
            adj_ref[root, :] = dpred

            def bwd(i, _):
                k = root - i
                a = arity_ref[t, k]
                o = op_ref[t, k]
                c1 = child1_ref[t, k]
                c2 = child2_ref[t, k]
                ct = adj_ref[k, :]
                x1 = buf_ref[c1, :]
                x2 = buf_ref[c2, :]

                # Gate each arity's vjp behind pl.when: a scalar branch
                # per slot skips the other arity's derivative entirely
                # (computing both and selecting doubled the backward
                # cost). Padded rows carry zero cotangents but arbitrary
                # operand values, so op vjps can produce 0/0 = NaN there;
                # mask before accumulating or one NaN poisons the sums.
                if unary_fns:
                    @pl.when(a == 1)
                    def _():
                        if len(unary_fns) == 1:
                            du = _vjp_unary(unary_fns[0], x1, ct)
                        else:
                            du = jax.lax.switch(
                                o, [lambda xx, cc, f=f: _vjp_unary(f, xx, cc)
                                    for f in unary_fns], x1, ct)
                        du = jnp.where(mask_row, du, 0.0)
                        adj_ref[c1, :] = adj_ref[c1, :] + du

                if binary_fns:
                    @pl.when(a == 2)
                    def _():
                        if len(binary_fns) == 1:
                            db1, db2 = _vjp_binary(binary_fns[0], x1, x2, ct)
                        else:
                            db1, db2 = jax.lax.switch(
                                o, [lambda xx, yy, cc, f=f:
                                    _vjp_binary(f, xx, yy, cc)
                                    for f in binary_fns], x1, x2, ct)
                        db1 = jnp.where(mask_row, db1, 0.0)
                        db2 = jnp.where(mask_row, db2, 0.0)
                        adj_ref[c1, :] = adj_ref[c1, :] + db1
                        adj_ref[c2, :] = adj_ref[c2, :] + db2
                return 0

            jax.lax.fori_loop(0, root + 1, bwd, 0)

            # ---- per-slot constant gradients (sum over rows) ----
            grow = jnp.sum(adj_ref[...], axis=1) * cmask_ref[t, :]

            @pl.when(j == 0)
            def _():
                gconst_ref[t, :] = grow

            @pl.when(j != 0)
            def _():
                gconst_ref[t, :] = gconst_ref[t, :] + grow

            @pl.when(j == 0)
            def _():
                loss_ref[t, 0] = partial
                valid_ref[t, 0] = partial_ok

            @pl.when(j != 0)
            def _():
                loss_ref[t, 0] = loss_ref[t, 0] + partial
                valid_ref[t, 0] = valid_ref[t, 0] & partial_ok

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "operators", "loss_fn", "tree_block", "tile_rows", "interpret",
    ),
)
def fused_loss_and_const_grad(
    trees: TreeBatch,
    child: jax.Array,           # [..., L, 2] from tree_structure_arrays
    X: jax.Array,               # [F, n]
    y: jax.Array,               # [n]
    weights: Optional[jax.Array],
    operators: OperatorSet,
    loss_fn: Callable,
    *,
    tree_block: int = 8,
    tile_rows: int = 16384,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(loss, valid, dloss/dconst) per tree, in one fused TPU kernel.

    ``loss`` is the mean elementwise loss (invalid => inf, matching
    `fused_loss`); the gradient is w.r.t. every constant-leaf slot of
    ``trees.const`` (zero elsewhere, zero for invalid trees).

    Compatibility wrapper over the program path: compiles the batch and
    scatters the compressed gradient back to slot order. ``child`` is
    accepted for signature stability but unused (the program lowering
    derives structure itself); optimizer loops should hoist the compile
    and call `fused_grad_program` + `update_consts` directly.
    """
    from .program import scatter_const_grads

    del child
    batch_shape = trees.batch_shape
    flat = trees.reshape(-1) if batch_shape else trees.reshape(1)
    L = flat.arity.shape[-1]
    F = X.shape[0]
    prog = compile_program(flat, F, len(operators.binary))
    loss, valid, gcomp = fused_grad_program(
        prog, X, y, weights, F, operators, loss_fn,
        tree_block=tree_block, tile_rows=tile_rows, interpret=interpret,
    )
    grad = scatter_const_grads(prog, gcomp, L)
    if batch_shape:
        return (loss.reshape(batch_shape), valid.reshape(batch_shape),
                grad.reshape(*batch_shape, L))
    return loss[0], valid[0], grad[0]
