"""Compressed internal-node programs for the fused TPU interpreters.

The round-2 kernels interpreted every postfix slot — including leaves —
with one `lax.switch` dispatch per slot. Leaves are ~half the slots of a
binary-heavy tree, and each dispatch costs far more scalar-core time
than the ~10 vector registers of row work it controls, so the kernels
ran at a few percent of VPU throughput.

This module "compiles" a TreeBatch into a leaf-free program over a
single unified VMEM value buffer:

    buf[0 : F]               — the X feature rows (written once per block)
    buf[F : F+CMAX]          — the tree's constant-leaf values, broadcast
                               across the row tile (one vector store)
    buf[BASE : BASE+L]       — internal-node results, one slot per step
                               (BASE = F + CMAX)

Each program step k is an internal node in postfix order: a merged
opcode (0 = identity/copy, 1..B = binary, B+1..B+U = unary — binary
first because it's the most frequent class and the dispatch switch
tests codes in order) plus one or two *unified buffer addresses* for
its operands, packed into one int32 instruction word
(op << 24 | src1 << 12 | src2) so the kernel issues a single SMEM read
per step. Leaves vanish from the
instruction stream — a VAR child is just an address < F, a CONST child
an address in [F, BASE). The kernel's inner loop becomes: one switch,
one or two uniform dynamic VMEM reads, one store. Steps per tree drop
from `length` to the internal-node count (≈ length/2 for binary-heavy
trees), and the arity switch disappears entirely.

Validity semantics: the kernel checks finiteness of every *internal*
node's output per row (matching the reference's per-node buffer check,
/root/reference/src/LossFunctions.jl:96-99, for those nodes). Leaf
outputs are X columns (finite datasets) and constants; non-finite
constants are caught by `const_ok` computed here and ANDed into the
kernel's verdict, so e.g. `exp(c)` with c = -inf (output 0.0, finite)
is still invalid — same verdict as the reference, which flags the
constant node itself. (A dataset containing non-finite rows is the one
case that can diverge for pathological trees; `Dataset` inputs are
expected finite.)

The program is **constant-independent** except for `cvals`/`const_ok`:
line searches and optimizer loops compile once per structure and call
`update_consts` per candidate constant vector (a [T, CMAX] gather).
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp

from .encoding import (LEAF_CONST, LEAF_PARAM, LEAF_VAR, TreeBatch,
                       _structure_from_arity, lane_take)

__all__ = ["TreeProgram", "compile_program", "update_consts",
           "const_mask_compressed", "scatter_const_grads", "program_cmax"]


def program_cmax(max_nodes: int) -> int:
    """Max constant leaves a tree of `max_nodes` slots can hold."""
    return (max_nodes + 1) // 2


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TreeProgram:
    """Leaf-free postfix program for a flat [T] batch of trees (pytree).

    ``code``/``src1``/``src2`` are [T, L] (step axis padded with identity
    steps past ``nsteps``); ``cvals``/``cslot`` are [T, CMAX] with
    ``cslot == L`` marking unused constant slots; ``nsteps >= 1``.
    """

    code: jax.Array      # int32 [T, L] merged opcode per step
    src1: jax.Array      # int32 [T, L] unified buffer address, operand 1
    src2: jax.Array      # int32 [T, L] unified buffer address, operand 2
    nsteps: jax.Array    # int32 [T]    executed steps (>= 1)
    cvals: jax.Array     # float [T, CMAX] constant-leaf values
    cslot: jax.Array     # int32 [T, CMAX] original slot of each const (L = unused)
    nconst: jax.Array    # int32 [T]    used constant slots
    const_ok: jax.Array  # bool  [T]    all live constant leaves finite

    @property
    def max_steps(self) -> int:
        return self.code.shape[-1]

    @property
    def cmax(self) -> int:
        return self.cvals.shape[-1]


def compile_program(trees: TreeBatch, nfeatures: int, n_binary: int,
                    n_params: int = 0) -> TreeProgram:
    """Lower a flat [T, L] TreeBatch to a TreeProgram (all jnp, jittable).

    Single-leaf trees compile to one identity step copying the leaf's
    address; `nsteps` is therefore always >= 1 and the root value lives
    at buffer slot ``BASE + nsteps - 1``.

    With ``n_params > 0`` the buffer gains a parameter region between
    the X rows and the const region — ``[X(F) | params(NP) | consts |
    internal]`` — and LEAF_PARAM leaves address it by parameter index;
    the kernels materialize those rows per tree from the member's
    parameter bank and the dataset's class one-hots. With ``n_params ==
    0`` LEAF_PARAM leaves alias constant leaves (their `const` field) —
    the historical contract for callers that pre-materialize.
    """
    from .encoding import LEAF_CONST, LEAF_PARAM

    arity, op, feat, const, length = (
        trees.arity, trees.op, trees.feat, trees.const, trees.length)
    T, L = arity.shape
    cmax = program_cmax(L)
    CBASE = nfeatures + n_params
    BASE = CBASE + cmax
    slot = jnp.arange(L, dtype=jnp.int32)

    live = slot[None, :] < length[:, None]
    internal = live & (arity > 0)
    ci = jnp.cumsum(internal, axis=-1) - internal          # compressed idx
    if n_params > 0:
        is_cleaf = live & (arity == 0) & (op == LEAF_CONST)
    else:
        is_cleaf = live & (arity == 0) & (op != LEAF_VAR)
    cj = jnp.cumsum(is_cleaf, axis=-1) - is_cleaf          # const idx

    # Unified buffer address of every slot's value.
    leaf_addr = jnp.where(
        op == LEAF_VAR, jnp.clip(feat, 0, nfeatures - 1),
        CBASE + jnp.clip(cj, 0, cmax - 1))
    if n_params > 0:
        leaf_addr = jnp.where(
            op == LEAF_PARAM,
            nfeatures + jnp.clip(feat, 0, n_params - 1), leaf_addr)
    addr = jnp.where(internal, BASE + ci, leaf_addr).astype(jnp.int32)

    child, _, _ = _structure_from_arity(arity, need_depth=False)
    code_slot = jnp.where(
        arity == 2, 1 + op,
        jnp.where(arity == 1, 1 + n_binary + op, 0),
    ).astype(jnp.int32)
    src1_slot = lane_take(addr, child[..., 0])
    src2_slot = jnp.where(
        arity == 2, lane_take(addr, child[..., 1]),
        src1_slot,
    )

    # Compress: internal slots first, in postfix order (keys are unique).
    order = jnp.argsort(jnp.where(internal, slot[None, :], L + slot[None, :]),
                        axis=-1)
    code = lane_take(code_slot, order)
    src1 = lane_take(src1_slot, order)
    src2 = lane_take(src2_slot, order)

    m = jnp.sum(internal, axis=-1)
    root_slot = jnp.clip(length - 1, 0, L - 1)
    root_addr = lane_take(addr, root_slot[:, None])[:, 0]
    leaf_only = m == 0
    code = code.at[:, 0].set(jnp.where(leaf_only, 0, code[:, 0]))
    src1 = src1.at[:, 0].set(jnp.where(leaf_only, root_addr, src1[:, 0]))
    src2 = src2.at[:, 0].set(jnp.where(leaf_only, root_addr, src2[:, 0]))
    nsteps = jnp.maximum(m, 1).astype(jnp.int32)

    # Constant-leaf table, gather-only (XLA scatters lower poorly on TPU):
    # a second argsort lists const-leaf slots first in slot order.
    nconst = jnp.sum(is_cleaf, axis=-1).astype(jnp.int32)
    order_c = jnp.argsort(
        jnp.where(is_cleaf, slot[None, :], L + slot[None, :]), axis=-1)
    used = jnp.arange(cmax, dtype=jnp.int32)[None, :] < nconst[:, None]
    cslot = jnp.where(used, order_c[:, :cmax], L).astype(jnp.int32)
    cvals = jnp.where(
        used,
        lane_take(const, jnp.clip(cslot, 0, L - 1)),
        0.0,
    ).astype(const.dtype)
    const_ok = jnp.all(jnp.isfinite(const) | ~is_cleaf, axis=-1)

    return TreeProgram(code=code, src1=src1, src2=src2, nsteps=nsteps,
                       cvals=cvals, cslot=cslot, nconst=nconst,
                       const_ok=const_ok)


def update_consts(prog: TreeProgram, const: jax.Array) -> TreeProgram:
    """Re-bind a program to new constant vectors ``const`` [T, L].

    Structure fields are reused untouched — this is the hoisted path for
    line searches / optimizer iterations where only constants move.
    """
    L = const.shape[-1]
    used = prog.cslot < L
    gathered = lane_take(const, jnp.clip(prog.cslot, 0, L - 1))
    cvals = jnp.where(used, gathered, 0.0).astype(const.dtype)
    const_ok = jnp.all(jnp.isfinite(gathered) | ~used, axis=-1)
    return dataclasses.replace(prog, cvals=cvals, const_ok=const_ok)


def const_mask_compressed(prog: TreeProgram) -> jax.Array:
    """[T, CMAX] float mask of used constant slots."""
    return (prog.cslot < prog.max_steps).astype(prog.cvals.dtype)


def scatter_const_grads(prog: TreeProgram, gcomp: jax.Array,
                        max_nodes: int) -> jax.Array:
    """Scatter compressed per-constant gradients [T, CMAX] → [T, L]."""
    T = gcomp.shape[0]
    out = jnp.zeros((T, max_nodes), gcomp.dtype)
    return out.at[jnp.arange(T)[:, None], prog.cslot].add(gcomp, mode="drop")
