"""Host-side expression trees: the `Node` equivalent of DynamicExpressions.jl.

This is the *host* representation used for parsing, printing, simplification
and (de)serialization. The device representation is the postfix tensor
encoding in :mod:`..ops.encoding`; evolution and evaluation run entirely on
the tensor form. Mirrors the `Node{T,D}` surface enumerated at
/root/reference/src/SymbolicRegression.jl:101-144 (copy_node, count_nodes,
count_depth, string_tree, parse_expression, simplify_tree!,
combine_operators, get_scalar_constants / set_scalar_constants!).
"""

from __future__ import annotations

import math
import re
from typing import Callable, List, Optional, Sequence, Tuple, Union

from .operators import Op, OperatorSet, resolve_operator

__all__ = ["Node", "parse_expression", "string_tree"]


class Node:
    """An expression tree node.

    ``degree == 0``: leaf. Either a constant (``constant=True``, value in
    ``val``) or a variable referencing feature index ``feature`` (0-based).
    ``degree >= 1``: operator node with ``op`` an :class:`Op` and
    ``children`` a tuple of Nodes.

    A leaf may also be a *parameter* node (``is_parameter=True`` with
    ``parameter`` index) for ParametricExpression support, mirroring
    `ParametricNode` (/root/reference/src/ParametricExpression.jl:126-135).
    """

    __slots__ = ("degree", "constant", "val", "feature", "op", "children",
                 "is_parameter", "parameter")

    def __init__(
        self,
        *,
        val: Optional[float] = None,
        feature: Optional[int] = None,
        op: Optional[Op] = None,
        children: Sequence["Node"] = (),
        is_parameter: bool = False,
        parameter: int = 0,
    ):
        if op is not None:
            self.degree = len(children)
            assert self.degree == op.arity, (op, children)
            self.op = op
            self.children = tuple(children)
            self.constant = False
            self.val = None
            self.feature = 0
            self.is_parameter = False
            self.parameter = 0
        elif is_parameter:
            self.degree = 0
            self.op = None
            self.children = ()
            self.constant = False
            self.val = None
            self.feature = 0
            self.is_parameter = True
            self.parameter = parameter
        elif feature is not None:
            self.degree = 0
            self.op = None
            self.children = ()
            self.constant = False
            self.val = None
            self.feature = feature
            self.is_parameter = False
            self.parameter = 0
        else:
            self.degree = 0
            self.op = None
            self.children = ()
            self.constant = True
            self.val = float(val) if val is not None else 0.0
            self.feature = 0
            self.is_parameter = False
            self.parameter = 0

    # -- constructors --------------------------------------------------
    @staticmethod
    def const(val: float) -> "Node":
        return Node(val=val)

    @staticmethod
    def var(feature: int) -> "Node":
        return Node(feature=feature)

    @staticmethod
    def param(parameter: int) -> "Node":
        return Node(is_parameter=True, parameter=parameter)

    # -- traversal -----------------------------------------------------
    def nodes(self):
        """Depth-first post-order iteration (children before parents)."""
        for c in self.children:
            yield from c.nodes()
        yield self

    def copy(self) -> "Node":
        if self.degree > 0:
            return Node(op=self.op, children=[c.copy() for c in self.children])
        if self.is_parameter:
            return Node.param(self.parameter)
        if self.constant:
            return Node.const(self.val)
        return Node.var(self.feature)

    def count_nodes(self) -> int:
        return 1 + sum(c.count_nodes() for c in self.children)

    def count_depth(self) -> int:
        if self.degree == 0:
            return 1
        return 1 + max(c.count_depth() for c in self.children)

    def has_constants(self) -> bool:
        return any(n.degree == 0 and n.constant for n in self.nodes())

    def has_operators(self) -> bool:
        return self.degree > 0

    # -- constants API (get/set_scalar_constants,
    #    /root/reference/src/ConstantOptimization.jl:64-76) -------------
    def get_scalar_constants(self) -> List[float]:
        return [n.val for n in self.nodes() if n.degree == 0 and n.constant]

    def set_scalar_constants(self, values: Sequence[float]) -> None:
        it = iter(values)
        for n in self.nodes():
            if n.degree == 0 and n.constant:
                n.val = float(next(it))

    # -- evaluation (host; for tests/golden values) --------------------
    def eval_scalar(self, x: Sequence[float], params: Optional[Sequence[float]] = None) -> float:
        import numpy as np

        if self.degree == 0:
            if self.is_parameter:
                return float(params[self.parameter])
            if self.constant:
                return float(self.val)
            return float(x[self.feature])
        args = [c.eval_scalar(x, params) for c in self.children]
        out = self.op.fn(*[np.float64(a) for a in args])
        return float(out)

    def __eq__(self, other):
        if not isinstance(other, Node):
            return NotImplemented
        if self.degree != other.degree:
            return False
        if self.degree == 0:
            if self.is_parameter != other.is_parameter or self.constant != other.constant:
                return False
            if self.is_parameter:
                return self.parameter == other.parameter
            if self.constant:
                return self.val == other.val or (
                    math.isnan(self.val) and math.isnan(other.val)
                )
            return self.feature == other.feature
        return self.op.name == other.op.name and all(
            a == b for a, b in zip(self.children, other.children)
        )

    def __hash__(self):
        if self.degree == 0:
            if self.is_parameter:
                return hash(("p", self.parameter))
            if self.constant:
                return hash(("c", self.val))
            return hash(("v", self.feature))
        return hash((self.op.name, self.children))

    def __repr__(self) -> str:
        return f"Node({string_tree(self)})"


# ---------------------------------------------------------------------------
# Printing (string_tree, /root/reference/src/InterfaceDynamicExpressions.jl:199-317)
# ---------------------------------------------------------------------------

_PRECEDENCE = {"+": 1, "-": 1, "*": 2, "/": 2, "^": 3}


def _fmt_const(v: float, precision: int) -> str:
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e12:
        return str(float(v))
    return f"{v:.{precision}g}"


def string_tree(
    tree: Node,
    variable_names: Optional[Sequence[str]] = None,
    *,
    pretty: bool = False,
    precision: int = 5,
) -> str:
    """Render a tree as an infix string (round-trippable by parse_expression)."""

    def varname(i: int) -> str:
        if variable_names is not None and i < len(variable_names):
            return variable_names[i]
        return f"x{i + 1}"

    def go(n: Node, parent_prec: int, side: str) -> str:
        if n.degree == 0:
            if n.is_parameter:
                return f"p{n.parameter + 1}"
            if n.constant:
                return _fmt_const(n.val, precision)
            return varname(n.feature)
        name = n.op.display if pretty else n.op.name
        if n.op.infix and n.degree == 2:
            prec = _PRECEDENCE.get(n.op.name, 1)
            if n.op.name == "^":  # right-associative
                left = go(n.children[0], prec + 1, "l")
                right = go(n.children[1], prec, "r")
            else:  # left-associative
                left = go(n.children[0], prec, "l")
                right = go(n.children[1], prec + 1, "r")
            s = f"{left} {name} {right}"
            if prec < parent_prec:
                return f"({s})"
            return s
        args = ", ".join(go(c, 0, "f") for c in n.children)
        return f"{name}({args})"

    return go(tree, 0, "f")


# ---------------------------------------------------------------------------
# Parsing (parse_expression analogue)
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"\s*(?:(?P<num>(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?)"
    r"|(?P<name>[A-Za-z_][A-Za-z_0-9]*)"
    r"|(?P<sym>\*\*|>=|<=|[-+*/^(),<>#]))"
)


def _tokenize(s: str):
    pos, out = 0, []
    while pos < len(s):
        m = _TOKEN_RE.match(s, pos)
        if m is None:
            if s[pos:].strip() == "":
                break
            raise ValueError(f"Cannot tokenize {s[pos:]!r}")
        pos = m.end()
        if m.group("num") is not None:
            out.append(("num", float(m.group("num"))))
        elif m.group("name") is not None:
            out.append(("name", m.group("name")))
        else:
            sym = m.group("sym")
            out.append(("sym", "^" if sym == "**" else sym))
    out.append(("end", None))
    return out


class _Parser:
    """Pratt parser for infix expressions over an OperatorSet."""

    def __init__(self, tokens, operators: OperatorSet, variable_names):
        self.toks = tokens
        self.i = 0
        self.operators = operators
        self.variable_names = list(variable_names) if variable_names else None

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, sym):
        t = self.next()
        if t != ("sym", sym):
            raise ValueError(f"Expected {sym!r}, got {t!r}")

    def _binop(self, name: str) -> Op:
        for op in self.operators.binary:
            if op.name == name or op.display == name:
                return op
        # Fall back to registry so parsing works even if the op isn't in the
        # search's set (e.g. printing round-trips of guesses).
        return resolve_operator(name, 2)

    def parse(self, min_prec: int = 0) -> Node:
        node = self.parse_unary()
        while True:
            kind, value = self.peek()
            if kind != "sym" or value not in _PRECEDENCE and value not in (">", "<", ">=", "<="):
                break
            prec = _PRECEDENCE.get(value, 0)
            if prec < min_prec:
                break
            self.next()
            if value == "^":  # right-assoc
                rhs = self.parse(prec)
            else:
                rhs = self.parse(prec + 1)
            node = Node(op=self._binop(value), children=[node, rhs])
        return node

    def parse_unary(self) -> Node:
        kind, value = self.next()
        if kind == "num":
            return Node.const(value)
        if kind == "sym" and value == "-":
            child = self.parse_unary()
            if child.degree == 0 and child.constant:
                return Node.const(-child.val)
            for op in self.operators.unary:
                if op.name == "neg":
                    return Node(op=op, children=[child])
            neg_op = resolve_operator("neg", 1)
            return Node(op=neg_op, children=[child])
        if kind == "sym" and value == "+":
            return self.parse_unary()
        if kind == "sym" and value == "(":
            node = self.parse()
            self.expect(")")
            return node
        if kind == "sym" and value == "#":
            # TemplateExpression placeholder syntax `#N`
            # (/root/reference/src/TemplateExpression.jl:1014+)
            k, v = self.next()
            if k != "num":
                raise ValueError("Expected number after '#'")
            return Node.var(int(v) - 1)
        if kind == "name":
            nxt = self.peek()
            if nxt == ("sym", "("):
                self.next()
                args = [self.parse()]
                while self.peek() == ("sym", ","):
                    self.next()
                    args.append(self.parse())
                self.expect(")")
                # Find op with matching name & arity:
                for d, ops in self.operators.ops.items():
                    for op in ops:
                        if (op.name == value or op.display == value) and op.arity == len(args):
                            return Node(op=op, children=args)
                op = resolve_operator(value, len(args))
                return Node(op=op, children=args)
            return self._leaf_name(value)
        raise ValueError(f"Unexpected token {(kind, value)!r}")

    def _leaf_name(self, name: str) -> Node:
        if self.variable_names is not None and name in self.variable_names:
            return Node.var(self.variable_names.index(name))
        m = re.fullmatch(r"x(\d+)", name)
        if m:
            return Node.var(int(m.group(1)) - 1)
        m = re.fullmatch(r"p(\d+)", name)
        if m:
            return Node.param(int(m.group(1)) - 1)
        if name in ("pi", "π"):
            return Node.const(math.pi)
        if name == "e":
            return Node.const(math.e)
        if name in ("NaN", "nan"):
            return Node.const(float("nan"))
        if name in ("Inf", "inf"):
            return Node.const(float("inf"))
        raise ValueError(f"Unknown variable {name!r}")


def parse_expression(
    s: str,
    operators: Optional[OperatorSet] = None,
    variable_names: Optional[Sequence[str]] = None,
) -> Node:
    """Parse an infix expression string into a :class:`Node` tree."""
    operators = operators or OperatorSet()
    p = _Parser(_tokenize(s), operators, variable_names)
    node = p.parse()
    if p.peek()[0] != "end":
        raise ValueError(f"Trailing tokens in expression: {s!r}")
    return node


# ---------------------------------------------------------------------------
# Simplification (simplify_tree! + combine_operators analogues)
# ---------------------------------------------------------------------------


def simplify_tree(tree: Node, operators: Optional[OperatorSet] = None) -> Node:
    """Constant folding: collapse any all-constant subtree to a constant."""
    if tree.degree == 0:
        return tree
    children = [simplify_tree(c, operators) for c in tree.children]
    if all(c.degree == 0 and c.constant for c in children):
        import numpy as np

        with np.errstate(all="ignore"):
            val = tree.op.fn(*[np.float64(c.val) for c in children])
        return Node.const(float(val))
    return Node(op=tree.op, children=children)


def combine_operators(tree: Node, operators: Optional[OperatorSet] = None) -> Node:
    """Merge nested +/* with constant operands, and fold `-`/`/` chains.

    Port of the *behavior* of DynamicExpressions' `combine_operators`:
    e.g. `(x + 1.5) + 2.5 -> x + 4.0`, `(x * 2) * 3 -> x * 6`,
    `(x - 1) - 2 -> x - 3`.
    """
    if tree.degree == 0:
        return tree
    children = [combine_operators(c, operators) for c in tree.children]
    tree = Node(op=tree.op, children=children)
    name = tree.op.name

    def is_const(n):
        return n.degree == 0 and n.constant

    if name in ("+", "*") and tree.degree == 2:
        a, b = tree.children
        # normalize constant to the right
        if is_const(a) and not is_const(b):
            a, b = b, a
        if is_const(b) and a.degree == 2 and a.op.name == name:
            inner_a, inner_b = a.children
            if is_const(inner_b):
                combined = inner_b.val + b.val if name == "+" else inner_b.val * b.val
                return Node(op=tree.op, children=[inner_a, Node.const(combined)])
            if is_const(inner_a):
                combined = inner_a.val + b.val if name == "+" else inner_a.val * b.val
                return Node(op=tree.op, children=[inner_b, Node.const(combined)])
        return Node(op=tree.op, children=[a, b])
    if name == "-" and tree.degree == 2:
        a, b = tree.children
        if is_const(b) and a.degree == 2 and a.op.name == "-" and is_const(a.children[1]):
            return Node(op=tree.op,
                        children=[a.children[0], Node.const(a.children[1].val + b.val)])
        if is_const(b) and a.degree == 2 and a.op.name == "+" and is_const(a.children[1]):
            return Node(op=a.op, children=[a.children[0], Node.const(a.children[1].val - b.val)])
    return tree
