"""Operator registry with domain-safe semantics.

TPU-native analogue of the reference's operator layer
(/root/reference/src/Operators.jl:35-124 and DynamicExpressions' OperatorEnum).
Every operator is a JAX-traceable elementwise function returning NaN outside
its domain, so that invalid expressions are detected by a masked validity
reduction instead of the reference's early-exit interpreter
(/root/reference/src/InterfaceDynamicExpressions.jl:32-44).

Operators are organized by arity into an :class:`OperatorSet` (the
`OperatorEnum` equivalent); mutation sampling uses `OperatorSet.nops` the
same way the reference uses `options.nops`
(/root/reference/src/MutationFunctions.jl:209-225).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

__all__ = [
    "Op",
    "OperatorSet",
    "resolve_operator",
    "DEFAULT_BINARY",
    "DEFAULT_UNARY",
    "OPERATOR_REGISTRY",
]


def _nan_like(x):
    return jnp.full_like(x, jnp.nan)


# ---------------------------------------------------------------------------
# Safe scalar operators (NaN outside domain), mirroring
# /root/reference/src/Operators.jl:35-124.
# ---------------------------------------------------------------------------


def safe_pow(x, y):
    """`x^y` with NaN where the real power is undefined.

    Mirrors /root/reference/src/Operators.jl:35-49: integer exponents allow
    negative bases (0^negative is NaN); non-integer exponents require a
    positive base (or zero base with positive exponent).
    """
    is_int = y == jnp.round(y)
    is_odd = jnp.abs(jnp.mod(y, 2.0)) == 1.0
    # Integer-exponent path: compute |x|^y and restore sign for odd powers.
    mag = jnp.abs(x) ** y
    signed = jnp.where(is_odd & (x < 0), -mag, mag)
    int_res = jnp.where((y < 0) & (x == 0), jnp.nan, signed)
    # Non-integer path: domain requires x > 0 (x == 0 ok for y > 0).
    bad = ((y > 0) & (x < 0)) | ((y < 0) & (x <= 0))
    nonint_res = jnp.where(bad, jnp.nan, jnp.abs(x) ** y)
    return jnp.where(is_int, int_res, nonint_res)


def safe_log(x):
    return jnp.where(x > 0, jnp.log(jnp.where(x > 0, x, 1.0)), jnp.nan)


def safe_log2(x):
    return jnp.where(x > 0, jnp.log2(jnp.where(x > 0, x, 1.0)), jnp.nan)


def safe_log10(x):
    return jnp.where(x > 0, jnp.log10(jnp.where(x > 0, x, 1.0)), jnp.nan)


def safe_log1p(x):
    return jnp.where(x > -1, jnp.log1p(jnp.where(x > -1, x, 0.0)), jnp.nan)


def safe_sqrt(x):
    return jnp.where(x >= 0, jnp.sqrt(jnp.where(x >= 0, x, 0.0)), jnp.nan)


def safe_asin(x):
    ok = (x >= -1) & (x <= 1)
    return jnp.where(ok, jnp.arcsin(jnp.clip(x, -1, 1)), jnp.nan)


def safe_acos(x):
    ok = (x >= -1) & (x <= 1)
    return jnp.where(ok, jnp.arccos(jnp.clip(x, -1, 1)), jnp.nan)


def safe_acosh(x):
    return jnp.where(x >= 1, jnp.arccosh(jnp.where(x >= 1, x, 1.0)), jnp.nan)


def safe_atanh(x):
    ok = (x >= -1) & (x <= 1)
    return jnp.where(ok, jnp.arctanh(jnp.clip(x, -1, 1)), jnp.nan)


def atanh_clip(x):
    """atanh((x + 1) % 2 - 1), always defined (src/Operators.jl:19)."""
    return jnp.arctanh(jnp.mod(x + 1.0, 2.0) - 1.0)


def gamma(x):
    """Gamma function with inf->NaN (src/Operators.jl:14-17).

    Computed via exp(lgamma) with the reflection sign for negative inputs.
    """
    sign = jnp.where(x > 0, 1.0, jnp.sign(jnp.sin(jnp.pi * x)))
    out = sign * jnp.exp(jax.lax.lgamma(x.astype(jnp.float32)).astype(x.dtype))
    return jnp.where(jnp.isinf(out), jnp.nan, out)


def erf(x):
    return jax.scipy.special.erf(x)


def erfc(x):
    return jax.scipy.special.erfc(x)


def square(x):
    return x * x


def cube(x):
    return x * x * x


def neg(x):
    return -x


def inv(x):
    return 1.0 / x


def relu(x):
    return jnp.where(x > 0, x, 0.0)


def greater(x, y):
    return (x > y).astype(x.dtype) if hasattr(x, "dtype") else float(x > y)


def less(x, y):
    return (x < y).astype(x.dtype) if hasattr(x, "dtype") else float(x < y)


def greater_equal(x, y):
    return (x >= y).astype(x.dtype) if hasattr(x, "dtype") else float(x >= y)


def less_equal(x, y):
    return (x <= y).astype(x.dtype) if hasattr(x, "dtype") else float(x <= y)


def cond(x, y):
    """(x > 0) * y (src/Operators.jl:113-115)."""
    return jnp.where(x > 0, y, 0.0)


def logical_or(x, y):
    return ((x > 0) | (y > 0)).astype(jnp.result_type(x))


def logical_and(x, y):
    return ((x > 0) & (y > 0)).astype(jnp.result_type(x))


# ---------------------------------------------------------------------------
# Operator descriptors
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Op:
    """A single operator: a JAX-traceable elementwise function plus metadata.

    `name` is the canonical (file-save) name; `pretty_name` is used for
    terminal printing (mirrors DE.get_op_name / get_pretty_op_name,
    /root/reference/src/Operators.jl:126-160).
    """

    name: str
    arity: int
    fn: Callable
    infix: bool = False
    pretty_name: Union[str, None] = None
    commutative: bool = False

    @property
    def display(self) -> str:
        return self.pretty_name if self.pretty_name is not None else self.name

    def __call__(self, *args):
        return self.fn(*args)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Op({self.name}/{self.arity})"


def _binary(name, fn, **kw):
    return Op(name=name, arity=2, fn=fn, **kw)


def _unary(name, fn, **kw):
    return Op(name=name, arity=1, fn=fn, **kw)


_BUILTIN_OPS = [
    # Binary
    _binary("+", lambda x, y: x + y, infix=True, commutative=True),
    _binary("-", lambda x, y: x - y, infix=True),
    _binary("*", lambda x, y: x * y, infix=True, commutative=True),
    _binary("/", lambda x, y: x / y, infix=True),
    _binary("^", safe_pow, infix=True),
    _binary("mod", jnp.mod),
    _binary("max", jnp.maximum, commutative=True),
    _binary("min", jnp.minimum, commutative=True),
    _binary("atan2", jnp.arctan2),
    _binary("greater", greater, pretty_name=">"),
    _binary("less", less, pretty_name="<"),
    _binary("greater_equal", greater_equal, pretty_name=">="),
    _binary("less_equal", less_equal, pretty_name="<="),
    _binary("cond", cond),
    _binary("logical_or", logical_or),
    _binary("logical_and", logical_and),
    # Unary
    _unary("exp", jnp.exp),
    _unary("abs", jnp.abs),
    _unary("log", safe_log),
    _unary("log2", safe_log2),
    _unary("log10", safe_log10),
    _unary("log1p", safe_log1p),
    _unary("sqrt", safe_sqrt),
    _unary("cbrt", jnp.cbrt),
    _unary("sin", jnp.sin),
    _unary("cos", jnp.cos),
    _unary("tan", jnp.tan),
    _unary("sinh", jnp.sinh),
    _unary("cosh", jnp.cosh),
    _unary("tanh", jnp.tanh),
    _unary("asin", safe_asin),
    _unary("acos", safe_acos),
    _unary("atan", jnp.arctan),
    _unary("asinh", jnp.arcsinh),
    _unary("acosh", safe_acosh),
    _unary("atanh", safe_atanh),
    _unary("atanh_clip", atanh_clip),
    _unary("erf", erf),
    _unary("erfc", erfc),
    _unary("gamma", gamma),
    _unary("square", square),
    _unary("cube", cube),
    _unary("neg", neg),
    _unary("inv", inv),
    _unary("relu", relu),
    _unary("round", jnp.round),
    _unary("floor", jnp.floor),
    _unary("ceil", jnp.ceil),
    _unary("sign", jnp.sign),
]

OPERATOR_REGISTRY = {op.name: op for op in _BUILTIN_OPS}

# Aliases mapping "unsafe"/Julia-style names to safe versions (get_safe_op,
# /root/reference/src/Operators.jl:171-185, plus print-name aliases).
_ALIASES = {
    "plus": "+",
    "sub": "-",
    "mult": "*",
    "div": "/",
    "pow": "^",
    "safe_pow": "^",
    "pow_abs": "^",
    "safe_log": "log",
    "safe_log2": "log2",
    "safe_log10": "log10",
    "safe_log1p": "log1p",
    "safe_sqrt": "sqrt",
    "safe_asin": "asin",
    "safe_acos": "acos",
    "safe_acosh": "acosh",
    "safe_atanh": "atanh",
    ">": "greater",
    "<": "less",
    ">=": "greater_equal",
    "<=": "less_equal",
    "maximum": "max",
    "minimum": "min",
}


def resolve_operator(spec, arity: Union[int, None] = None) -> Op:
    """Resolve a user operator spec (name string, Op, or callable) to an Op.

    Plain callables must be JAX-traceable elementwise functions; they are
    wrapped with the callable's ``__name__``.
    """
    if isinstance(spec, Op):
        return spec
    if isinstance(spec, str):
        name = _ALIASES.get(spec, spec)
        if name not in OPERATOR_REGISTRY:
            raise ValueError(
                f"Unknown operator {spec!r}. Register it by passing an "
                f"`Op(name=..., arity=..., fn=...)` instead."
            )
        op = OPERATOR_REGISTRY[name]
        if arity is not None and op.arity != arity:
            raise ValueError(f"Operator {spec!r} has arity {op.arity}, expected {arity}.")
        return op
    if callable(spec):
        if arity is None:
            raise ValueError(
                "When passing a bare callable as an operator you must place it "
                "in the correct arity list."
            )
        name = getattr(spec, "__name__", None) or f"custom_{arity}ary"
        return Op(name=name, arity=arity, fn=spec)
    raise TypeError(f"Cannot interpret operator spec: {spec!r}")


DEFAULT_BINARY = ("+", "-", "/", "*")  # default_options(), src/Options.jl:1163
DEFAULT_UNARY = ()


class OperatorSet:
    """Operators grouped by arity — the `OperatorEnum` equivalent.

    ``ops[d]`` is the tuple of operators of arity ``d`` (1-based, matching
    `operators.ops[degree]` in the reference). ``nops`` gives per-arity
    counts used by mutation sampling.
    """

    def __init__(
        self,
        binary_operators: Sequence = DEFAULT_BINARY,
        unary_operators: Sequence = DEFAULT_UNARY,
        *,
        ops_by_arity: Union[dict, None] = None,
    ):
        if ops_by_arity is None:
            ops_by_arity = {
                1: tuple(resolve_operator(o, 1) for o in unary_operators),
                2: tuple(resolve_operator(o, 2) for o in binary_operators),
            }
        self._ops = {d: tuple(ops) for d, ops in sorted(ops_by_arity.items())}
        self.max_arity = max([d for d, ops in self._ops.items() if ops], default=2)
        # Flat index tables for the tensorized interpreter.
        for d, ops in self._ops.items():
            for op in ops:
                if op.arity != d:
                    raise ValueError(f"{op} placed in arity-{d} slot")

    @property
    def ops(self):
        return self._ops

    def __getitem__(self, arity: int):
        return self._ops.get(arity, ())

    @property
    def unary(self):
        return self._ops.get(1, ())

    @property
    def binary(self):
        return self._ops.get(2, ())

    @property
    def nops(self):
        return {d: len(ops) for d, ops in self._ops.items()}

    def nops_tuple(self, max_arity: Union[int, None] = None):
        ma = max_arity or self.max_arity
        return tuple(len(self._ops.get(d, ())) for d in range(1, ma + 1))

    def index_of(self, spec, arity: Union[int, None] = None):
        """Return (arity, index) of an operator within this set."""
        if isinstance(spec, (Op, str)):
            target = resolve_operator(spec, arity)
            target_name = target.name
        elif callable(spec):
            target = spec
            target_name = getattr(spec, "__name__", None)
        else:
            raise TypeError(f"Cannot look up operator {spec!r}")
        for d, ops in self._ops.items():
            for i, op in enumerate(ops):
                if op is target or op.fn is target or op.name == target_name:
                    return d, i
        raise KeyError(f"Operator {spec!r} not in OperatorSet")

    def _key(self):
        # Two same-named ops with different fns must not collide in jit
        # caches keyed on this set.
        return tuple(
            (d, tuple((o.name, id(o.fn)) for o in ops)) for d, ops in self._ops.items()
        )

    def __eq__(self, other):
        if not isinstance(other, OperatorSet):
            return NotImplemented
        return self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def __repr__(self) -> str:  # pragma: no cover
        parts = []
        for d, ops in self._ops.items():
            parts.append(f"{d}: [" + ", ".join(o.name for o in ops) + "]")
        return "OperatorSet(" + "; ".join(parts) + ")"
