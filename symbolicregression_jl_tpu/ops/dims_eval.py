"""Device-side dimensional-constraint check.

TPU-native redesign of the reference's dimensional analysis
(/root/reference/src/DimensionalAnalysis.jl:46-275): instead of abstract
interpretation with `WildcardQuantity` objects on the host, we propagate a
``(value, dims[7], wildcard)`` triple through the postfix slot buffer in a
single `lax.scan` — the same structure as the eval kernel — over ONE data
sample (the reference also uses a single-sample check,
src/DimensionalAnalysis.jl:223-257). One launch checks a whole population.

Lattice semantics (mirroring src/DimensionalAnalysis.jl:64-195):
- constants (and parameters) are *wildcards* — their dimensions are free,
  so any op can absorb them (disabled by ``dimensionless_constants_only``);
- `+`/`-`/`min`/`max`/`mod` require matching dims (a wildcard side adopts
  the other's dims);
- `*`/`/` add/subtract exponents; a wildcard side keeps the result wildcard;
- `^` requires a dimensionless exponent and scales the base dims by the
  exponent's *numeric value* at the sample (this is why values are carried);
- comparisons require matching dims and return dimensionless;
- `sqrt`/`cbrt`/`square`/`cube`/`inv` scale exponents; `neg`/`abs`/… are
  dimension-preserving; all other scalar functions (sin, exp, log, custom
  ops, …) require dimensionless (or wildcard) input and return
  dimensionless.

A violation anywhere, or a root whose dims cannot match ``y``'s, flags the
tree; the search adds ``dimensional_constraint_penalty`` (default 1000,
src/LossFunctions.jl:236-245) to that member's cost.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .encoding import LEAF_CONST, LEAF_PARAM, MAX_ARITY, TreeBatch, tree_structure_arrays
from .operators import OperatorSet

__all__ = [
    "dimensional_violations_batch",
    "classify_operators",
    "violates_dimensional_constraints",
]

N_DIMS = 7
_TOL = 1e-4

# Unary classes
U_GENERIC = 0   # dimensionless in, dimensionless out
U_IDENT = 1     # dims-preserving
U_SQRT = 2
U_CBRT = 3
U_SQUARE = 4
U_CUBE = 5
U_INV = 6
U_SIGN = 7      # any dims in, dimensionless out

_UNARY_CLASS = {
    "neg": U_IDENT, "abs": U_IDENT, "relu": U_IDENT, "round": U_IDENT,
    "floor": U_IDENT, "ceil": U_IDENT,
    "sqrt": U_SQRT, "cbrt": U_CBRT, "square": U_SQUARE, "cube": U_CUBE,
    "inv": U_INV, "sign": U_SIGN,
}

# Binary classes
B_GENERIC = 0   # both dimensionless in, dimensionless out
B_ADD = 1       # matching dims, same dims out
B_MUL = 2
B_DIV = 3
B_POW = 4
B_CMP = 5       # matching dims in, dimensionless out
B_COND = 6      # (x > 0) * y : any x, y dims out

_BINARY_CLASS = {
    "+": B_ADD, "-": B_ADD, "max": B_ADD, "min": B_ADD, "mod": B_ADD,
    "*": B_MUL, "/": B_DIV, "^": B_POW,
    "greater": B_CMP, "less": B_CMP, "greater_equal": B_CMP,
    "less_equal": B_CMP, "logical_or": B_CMP, "logical_and": B_CMP,
    "atan2": B_CMP,
    "cond": B_COND,
}


def classify_operators(operators: OperatorSet) -> Tuple[np.ndarray, np.ndarray]:
    """Static per-op dimension-semantics class tables (unary, binary)."""
    ucls = np.asarray(
        [_UNARY_CLASS.get(op.name, U_GENERIC) for op in operators.unary]
        or [U_GENERIC],
        np.int32,
    )
    bcls = np.asarray(
        [_BINARY_CLASS.get(op.name, B_GENERIC) for op in operators.binary]
        or [B_GENERIC],
        np.int32,
    )
    return ucls, bcls


def _dims_match(d1, d2):
    return jnp.all(jnp.abs(d1 - d2) <= _TOL)


def _dimless(d):
    return jnp.all(jnp.abs(d) <= _TOL)


def _single_tree_violation(
    arity, op, feat, const, length, child,
    x_sample,      # [F] one row of X
    x_dims,        # [F, 7]
    y_dims,        # [7]
    check_y,       # bool scalar
    operators: OperatorSet,
    wildcard_constants: bool,
):
    L = arity.shape[0]
    ucls_np, bcls_np = classify_operators(operators)
    ucls = jnp.asarray(ucls_np)
    bcls = jnp.asarray(bcls_np)

    def step(carry, k):
        val_buf, dim_buf, wild_buf, viol = carry
        a = arity[k]
        o = op[k]
        cvals = [
            jax.lax.dynamic_index_in_dim(val_buf, child[k, j], 0, keepdims=False)
            for j in range(MAX_ARITY)
        ]
        cdims = [
            jax.lax.dynamic_index_in_dim(dim_buf, child[k, j], 0, keepdims=False)
            for j in range(MAX_ARITY)
        ]
        cwild = [
            jax.lax.dynamic_index_in_dim(wild_buf, child[k, j], 0, keepdims=False)
            for j in range(MAX_ARITY)
        ]

        # ---- leaf ----
        is_const_leaf = (o == LEAF_CONST) | (o == LEAF_PARAM)
        x_val = jax.lax.dynamic_index_in_dim(x_sample, feat[k], 0, keepdims=False)
        xd = jax.lax.dynamic_index_in_dim(x_dims, feat[k], 0, keepdims=False)
        # Parameter leaves have no single value at dims-check time (one per
        # class): NaN marks the value unknown, which propagates through
        # _node_value and makes any pow using it wildcard below.
        leaf_val = jnp.where(
            o == LEAF_PARAM, jnp.float32(jnp.nan),
            jnp.where(is_const_leaf, const[k].astype(jnp.float32), x_val),
        )
        leaf_dims = jnp.where(is_const_leaf, jnp.zeros((N_DIMS,), jnp.float32), xd)
        leaf_wild = is_const_leaf & jnp.bool_(wildcard_constants)

        # ---- unary ----
        c0v, c0d, c0w = cvals[0], cdims[0], cwild[0]
        uc = ucls[jnp.clip(o, 0, ucls.shape[0] - 1)]
        u_exp_scale = jnp.select(
            [uc == U_SQRT, uc == U_CBRT, uc == U_SQUARE, uc == U_CUBE,
             uc == U_INV, uc == U_IDENT],
            [0.5, 1.0 / 3.0, 2.0, 3.0, -1.0, 1.0],
            0.0,  # generic / sign: dimensionless out
        )
        u_dims = c0d * u_exp_scale
        u_preserves = (uc == U_IDENT) | (uc == U_SQRT) | (uc == U_CBRT) | \
            (uc == U_SQUARE) | (uc == U_CUBE) | (uc == U_INV)
        u_wild = c0w & u_preserves
        u_viol = (uc == U_GENERIC) & ~c0w & ~_dimless(c0d)

        # ---- binary ----
        c1v, c1d, c1w = cvals[1], cdims[1], cwild[1]
        bc = bcls[jnp.clip(o, 0, bcls.shape[0] - 1)]
        both_wild = c0w & c1w
        either_wild = c0w | c1w
        add_dims = jnp.where(c0w, c1d, c0d)
        add_viol = ~c0w & ~c1w & ~_dims_match(c0d, c1d)
        mul_dims = c0d + c1d
        div_dims = c0d - c1d
        # Unknown exponent value (NaN, e.g. a parameter leaf): the output
        # dims base^t are undetermined — treat as wildcard, never violate.
        exp_unknown = jnp.isnan(c1v)
        pow_dims = c0d * jnp.where(exp_unknown, 0.0, c1v)
        pow_viol = ~c1w & ~_dimless(c1d)
        gen_viol = (~c0w & ~_dimless(c0d)) | (~c1w & ~_dimless(c1d))

        b_dims = jnp.select(
            [bc == B_ADD, bc == B_MUL, bc == B_DIV, bc == B_POW,
             bc == B_COND],
            [add_dims, mul_dims, div_dims, pow_dims, c1d],
            jnp.zeros((N_DIMS,), jnp.float32),  # generic / cmp
        )
        b_wild = jnp.select(
            [bc == B_ADD, bc == B_MUL, bc == B_DIV, bc == B_POW,
             bc == B_COND],
            [both_wild, either_wild, either_wild, c0w | exp_unknown, c1w],
            jnp.bool_(False),
        )
        b_viol = jnp.select(
            [bc == B_ADD, bc == B_CMP, bc == B_POW, bc == B_GENERIC],
            [add_viol, add_viol, pow_viol, gen_viol],
            jnp.bool_(False),
        )

        # wildcard output dims are canonically zero (free to rescale)
        out_dims = jnp.where(
            a == 0, leaf_dims, jnp.where(a == 1, u_dims, b_dims)
        )
        out_wild = jnp.where(a == 0, leaf_wild, jnp.where(a == 1, u_wild, b_wild))
        out_dims = jnp.where(out_wild, jnp.zeros((N_DIMS,), jnp.float32), out_dims)
        node_viol = jnp.where(
            a == 0, jnp.bool_(False), jnp.where(a == 1, u_viol, b_viol)
        )

        # value propagation (single sample) for pow exponents
        cval = _node_value(operators, a, o, leaf_val, cvals)

        in_tree = k < length
        viol = viol | (node_viol & in_tree)
        val_buf = val_buf.at[k].set(cval)
        dim_buf = dim_buf.at[k].set(out_dims)
        wild_buf = wild_buf.at[k].set(out_wild)
        return (val_buf, dim_buf, wild_buf, viol), None

    carry0 = (
        jnp.zeros((L,), jnp.float32),
        jnp.zeros((L, N_DIMS), jnp.float32),
        jnp.zeros((L,), jnp.bool_),
        jnp.bool_(False),
    )
    (val_buf, dim_buf, wild_buf, viol), _ = jax.lax.scan(
        step, carry0, jnp.arange(L, dtype=jnp.int32)
    )
    root = length - 1
    root_dims = jax.lax.dynamic_index_in_dim(dim_buf, root, 0, keepdims=False)
    root_wild = jax.lax.dynamic_index_in_dim(wild_buf, root, 0, keepdims=False)
    y_viol = check_y & ~root_wild & ~_dims_match(root_dims, y_dims)
    return viol | y_viol


def _node_value(operators: OperatorSet, a, o, leaf, cvals):
    """Single-sample value of one node (f32), for `^` exponent lookup."""
    val = leaf
    if operators.unary:
        un = jnp.stack(
            [op.fn(cvals[0]).astype(jnp.float32) for op in operators.unary]
        )
        val = jnp.where(
            a == 1,
            jax.lax.dynamic_index_in_dim(
                un, jnp.clip(o, 0, len(operators.unary) - 1), 0, keepdims=False
            ),
            val,
        )
    if operators.binary:
        bi = jnp.stack(
            [
                op.fn(cvals[0], cvals[1]).astype(jnp.float32)
                for op in operators.binary
            ]
        )
        val = jnp.where(
            a == 2,
            jax.lax.dynamic_index_in_dim(
                bi, jnp.clip(o, 0, len(operators.binary) - 1), 0, keepdims=False
            ),
            val,
        )
    return val


@partial(jax.jit, static_argnames=("operators", "wildcard_constants"))
def dimensional_violations_batch(
    batch: TreeBatch,
    x_sample: jax.Array,   # [F]
    x_dims: jax.Array,     # [F, 7]
    y_dims: jax.Array,     # [7]
    check_y,               # bool scalar
    operators: OperatorSet,
    wildcard_constants: bool = True,
) -> jax.Array:
    """``violates[...batch]`` — True where a tree breaks unit constraints."""
    batch_shape = batch.batch_shape
    flat = batch.reshape(-1)
    child, _, _ = tree_structure_arrays(flat, need_depth=False)
    f = jax.vmap(
        lambda a, o, ft, c, ln, ch: _single_tree_violation(
            a, o, ft, c, ln, ch,
            x_sample.astype(jnp.float32), x_dims, y_dims, check_y,
            operators, wildcard_constants,
        )
    )
    viol = f(flat.arity, flat.op, flat.feat, flat.const, flat.length, child)
    return viol.reshape(batch_shape)


def violates_dimensional_constraints(tree, dataset, options=None) -> bool:
    """Host API: does this expression break the dataset's unit constraints?

    (`violates_dimensional_constraints`,
    /root/reference/src/DimensionalAnalysis.jl:223-275.) ``tree`` is a host
    :class:`..ops.tree.Node`; ``dataset`` a :class:`..core.dataset.Dataset`
    with units. Returns False when the dataset has no units.
    """
    from ..core.options import Options
    from .encoding import encode_population

    data = dataset.data
    if data.x_dims is None:
        return False
    options = options or Options()
    operators = options.operators
    max_nodes = max(tree.count_nodes(), 1)
    batch = encode_population(
        [tree], max_nodes, operators, np.dtype(np.float32)
    )
    viol = dimensional_violations_batch(
        batch, data.Xt[:, 0], data.x_dims,
        (jnp.zeros((N_DIMS,), jnp.float32) if data.y_dims is None
         else data.y_dims),
        jnp.bool_(data.y_dims is not None),
        operators,
        wildcard_constants=not options.dimensionless_constants_only,
    )
    return bool(viol[0])
