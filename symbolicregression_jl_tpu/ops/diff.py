"""Public evaluation + differentiation API over expression trees.

TPU-native equivalents of the reference's DynamicExpressions surface
(imported at /root/reference/src/SymbolicRegression.jl:101-144 and wrapped
at /root/reference/src/InterfaceDynamicExpressions.jl:58-183):

- ``eval_tree_array``      — evaluate one host tree over a dataset.
- ``eval_diff_tree_array`` — forward-mode derivative w.r.t. one feature.
- ``eval_grad_tree_array`` — gradient w.r.t. all features or all constants.
- ``differentiable_eval_tree_array`` — alias; the interpreter is natively
  differentiable (``jax.grad`` flows through it), which replaces the
  reference's dedicated differentiable evaluator
  (src/InterfaceDynamicExpressions.jl:172-183).
- ``D``                    — symbolic differentiation operator on host
  trees (the DynamicDiff.D analogue used by template structures,
  /root/reference/src/SymbolicRegression.jl:172).

Derivatives are computed by ``jax.jvp``/``jax.jacfwd`` through the postfix
interpreter — no hand-written tree differentiator on the eval path. The
symbolic ``D`` exists for the template-structure API where a *tree-valued*
derivative is required.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .encoding import LEAF_CONST, encode_tree, tree_structure_arrays, TreeBatch
from .eval import eval_single_tree
from .operators import Op, OperatorSet, resolve_operator
from .tree import Node

__all__ = [
    "eval_tree_array",
    "eval_diff_tree_array",
    "eval_grad_tree_array",
    "differentiable_eval_tree_array",
    "D",
]


def _as_xt(X) -> jax.Array:
    """User arrays are (n_rows, n_features); the interpreter wants [F, n]."""
    X = jnp.asarray(X)
    if X.ndim != 2:
        raise ValueError(f"X must be 2D (n_rows, n_features); got {X.shape}")
    return X.T


def extend_operators_for(tree: Node, operators: OperatorSet) -> OperatorSet:
    """Extend ``operators`` with any ops used by ``tree`` but absent from
    the set. Symbolic derivatives (``D``) introduce helper operators
    (``neg``, ``sign``, comparison ops, …) outside the search vocabulary;
    evaluation transparently widens the operator tables for them."""
    have = {(d, o.name) for d, ops in operators.ops.items() for o in ops}
    extra = {}
    for n in tree.nodes():
        if n.degree > 0 and (n.degree, n.op.name) not in have:
            extra[(n.degree, n.op.name)] = n.op
    if not extra:
        return operators
    ops_by_arity = {d: list(ops) for d, ops in operators.ops.items()}
    for (d, _), op in extra.items():
        ops_by_arity.setdefault(d, []).append(op)
    return OperatorSet(ops_by_arity={d: tuple(v) for d, v in ops_by_arity.items()})


def _encode_single(tree: Node, operators: OperatorSet, dtype):
    n_nodes = tree.count_nodes()
    arity, op, feat, const, length = encode_tree(
        tree, n_nodes, operators, dtype
    )
    batch = TreeBatch(
        arity=jnp.asarray(arity)[None],
        op=jnp.asarray(op)[None],
        feat=jnp.asarray(feat)[None],
        const=jnp.asarray(const)[None],
        length=jnp.asarray(length)[None],
    )
    child, _, _ = tree_structure_arrays(batch, need_depth=False)
    return (
        batch.arity[0], batch.op[0], batch.feat[0], batch.const[0],
        batch.length[0], child[0],
    )


def eval_tree_array(
    tree: Node,
    X,
    operators: OperatorSet,
    params: Optional[jax.Array] = None,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array]:
    """Evaluate ``tree`` over ``X`` (n_rows, n_features).

    Returns ``(y[n_rows], completed)`` where ``completed`` is False iff a
    non-finite value appeared anywhere in the evaluation (the reference's
    early-exit flag, src/InterfaceDynamicExpressions.jl:32-44).
    """
    Xt = _as_xt(X).astype(dtype)
    operators = extend_operators_for(tree, operators)
    a, o, f, c, ln, ch = _encode_single(tree, operators, np.dtype(dtype))
    y, valid = eval_single_tree(a, o, f, c, ln, ch, Xt, operators, params=params)
    return y, valid


def eval_diff_tree_array(
    tree: Node,
    X,
    operators: OperatorSet,
    direction: int,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Forward-mode derivative w.r.t. feature ``direction`` (0-based).

    Returns ``(y[n], dy_dx[n], completed)`` — the
    ``eval_diff_tree_array`` analogue
    (src/InterfaceDynamicExpressions.jl:118-130).
    """
    Xt = _as_xt(X).astype(dtype)
    operators = extend_operators_for(tree, operators)
    a, o, f, c, ln, ch = _encode_single(tree, operators, np.dtype(dtype))

    def run(Xt_):
        y, valid = eval_single_tree(a, o, f, c, ln, ch, Xt_, operators)
        return y, valid

    seed = jnp.zeros_like(Xt).at[direction].set(1.0)
    (y, valid), (dy, _) = jax.jvp(run, (Xt,), (seed,))
    return y, dy, valid


def eval_grad_tree_array(
    tree: Node,
    X,
    operators: OperatorSet,
    variable: bool = False,
    dtype=jnp.float32,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Gradient of the tree's output at every row.

    ``variable=True``: w.r.t. all features — returns grad ``[F, n]``.
    ``variable=False``: w.r.t. the tree's scalar constants in depth-first
    post-order — returns grad ``[n_constants, n]``. Mirrors
    ``eval_grad_tree_array`` (src/InterfaceDynamicExpressions.jl:153-165).
    """
    Xt = _as_xt(X).astype(dtype)
    operators = extend_operators_for(tree, operators)
    a, o, f, c, ln, ch = _encode_single(tree, operators, np.dtype(dtype))

    if variable:
        def run(Xt_):
            y, valid = eval_single_tree(a, o, f, c, ln, ch, Xt_, operators)
            # float-typed so it can ride through jvp as a primal output
            return y, valid.astype(Xt_.dtype)

        # One JVP per feature: dy_i/dX[f, i] (diagonal of the per-row
        # Jacobian — each output row only depends on its own input row).
        # The primal (y, valid) comes along with the first JVP for free,
        # so the tree is evaluated exactly F times, not F+1.
        def per_feature(fidx):
            seed = jnp.zeros_like(Xt).at[fidx].set(1.0)
            (y_, valid_), (dy, _) = jax.jvp(run, (Xt,), (seed,))
            return y_, valid_, dy

        ys, valids, grad = jax.vmap(per_feature)(jnp.arange(Xt.shape[0]))
        return ys[0], grad, valids[0] > 0

    # w.r.t. constants: differentiate the const slot vector, then gather
    # the rows belonging to actual constant leaves.
    const_slots = np.asarray(
        [k for k, n in enumerate(tree.nodes())
         if n.degree == 0 and n.constant and not n.is_parameter]
    )

    def run_c(c_):
        y, _ = eval_single_tree(a, o, f, c_, ln, ch, Xt, operators)
        return y

    y, valid = eval_single_tree(a, o, f, c, ln, ch, Xt, operators)
    if const_slots.size == 0:
        return y, jnp.zeros((0, Xt.shape[1]), Xt.dtype), valid
    jac = jax.jacfwd(run_c)(c)  # [n, L]
    grad = jac.T[const_slots]   # [n_constants, n]
    return y, grad, valid


# The interpreter is pure JAX: it IS the differentiable evaluator.
differentiable_eval_tree_array = eval_tree_array


# ---------------------------------------------------------------------------
# Symbolic differentiation (DynamicDiff.D analogue)
# ---------------------------------------------------------------------------


def _op(name: str) -> Op:
    return resolve_operator(name)


def _c(v: float) -> Node:
    return Node.const(float(v))


def _is_const(n: Node, v: Optional[float] = None) -> bool:
    return (
        n.degree == 0 and n.constant and not n.is_parameter
        and (v is None or n.val == v)
    )


def _add(a: Node, b: Node) -> Node:
    if _is_const(a, 0.0):
        return b
    if _is_const(b, 0.0):
        return a
    if _is_const(a) and _is_const(b):
        return _c(a.val + b.val)
    return Node(op=_op("+"), children=[a, b])


def _sub(a: Node, b: Node) -> Node:
    if _is_const(b, 0.0):
        return a
    if _is_const(a) and _is_const(b):
        return _c(a.val - b.val)
    if _is_const(a, 0.0):
        return Node(op=_op("neg"), children=[b])
    return Node(op=_op("-"), children=[a, b])


def _mul(a: Node, b: Node) -> Node:
    if _is_const(a, 0.0) or _is_const(b, 0.0):
        return _c(0.0)
    if _is_const(a, 1.0):
        return b
    if _is_const(b, 1.0):
        return a
    if _is_const(a) and _is_const(b):
        return _c(a.val * b.val)
    return Node(op=_op("*"), children=[a, b])


def _div(a: Node, b: Node) -> Node:
    if _is_const(a, 0.0):
        return _c(0.0)
    if _is_const(b, 1.0):
        return a
    if _is_const(a) and _is_const(b) and b.val != 0:
        return _c(a.val / b.val)
    return Node(op=_op("/"), children=[a, b])


def _pow(a: Node, b: Node) -> Node:
    if _is_const(b, 1.0):
        return a
    if _is_const(b, 0.0):
        return _c(1.0)
    return Node(op=_op("^"), children=[a, b])


def _un(name: str, a: Node) -> Node:
    return Node(op=_op(name), children=[a])


def D(tree: Node, feature: int) -> Node:
    """Symbolic derivative of ``tree`` w.r.t. variable ``feature`` (0-based).

    Returns a new tree (inputs are not mutated). Supports the operator
    vocabulary of the builtin registry; raises ``ValueError`` for operators
    with no registered derivative rule. The result is lightly simplified
    (constant folding, 0/1 identities) so that iterated application stays
    compact — the behavior template structures rely on when using the
    reference's ``D`` (src/SymbolicRegression.jl:172).
    """
    if tree.degree == 0:
        if tree.is_parameter or tree.constant:
            return _c(0.0)
        return _c(1.0 if tree.feature == feature else 0.0)

    name = tree.op.name
    if tree.degree == 2:
        a, b = tree.children
        da, db = D(a, feature), D(b, feature)
        ac, bc = a.copy(), b.copy()
        if name == "+":
            return _add(da, db)
        if name == "-":
            return _sub(da, db)
        if name == "*":
            return _add(_mul(da, bc), _mul(ac, db))
        if name == "/":
            return _div(
                _sub(_mul(da, bc), _mul(ac, db)), _mul(b.copy(), b.copy())
            )
        if name == "^":
            if b.degree == 0 and b.constant:
                # Constant exponent: d(a^c) = c*a^(c-1)*da — valid at a=0
                # and for negative bases with integer c, where the log(a)
                # form below would be NaN.
                return _mul(_mul(bc, _pow(a.copy(), _c(b.val - 1.0))), da)
            # d(a^b) = a^b * (db*log(a) + b*da/a)
            term1 = _mul(db, _un("log", ac))
            term2 = _div(_mul(bc, da), a.copy())
            return _mul(_pow(a.copy(), b.copy()), _add(term1, term2))
        if name == "max":
            ge = Node(op=_op("greater_equal"), children=[ac, bc])
            one_minus = _sub(_c(1.0), ge.copy())
            return _add(_mul(ge, da), _mul(one_minus, db))
        if name == "min":
            le = Node(op=_op("less_equal"), children=[ac, bc])
            one_minus = _sub(_c(1.0), le.copy())
            return _add(_mul(le, da), _mul(one_minus, db))
        if name == "atan2":
            denom = _add(_mul(a.copy(), a.copy()), _mul(b.copy(), b.copy()))
            return _div(_sub(_mul(bc, da), _mul(ac, db)), denom)
        raise ValueError(f"No derivative rule for binary operator {name!r}")

    (a,) = tree.children
    da = D(a, feature)
    ac = a.copy()
    rules = {
        "sin": lambda: _un("cos", ac),
        "cos": lambda: _un("neg", _un("sin", ac)),
        "tan": lambda: _add(_c(1.0), _mul(_un("tan", ac), _un("tan", a.copy()))),
        "sinh": lambda: _un("cosh", ac),
        "cosh": lambda: _un("sinh", ac),
        "tanh": lambda: _sub(
            _c(1.0), _mul(_un("tanh", ac), _un("tanh", a.copy()))
        ),
        "exp": lambda: _un("exp", ac),
        "log": lambda: _div(_c(1.0), ac),
        "log2": lambda: _div(_c(1.0 / np.log(2.0)), ac),
        "log10": lambda: _div(_c(1.0 / np.log(10.0)), ac),
        "log1p": lambda: _div(_c(1.0), _add(_c(1.0), ac)),
        "sqrt": lambda: _div(_c(0.5), _un("sqrt", ac)),
        "cbrt": lambda: _div(
            _c(1.0 / 3.0), _mul(_un("cbrt", ac), _un("cbrt", a.copy()))
        ),
        "abs": lambda: _un("sign", ac),
        "neg": lambda: _c(-1.0),
        "square": lambda: _mul(_c(2.0), ac),
        "cube": lambda: _mul(_c(3.0), _mul(ac, a.copy())),
        "inv": lambda: _un("neg", _div(_c(1.0), _mul(ac, a.copy()))),
        "asin": lambda: _div(
            _c(1.0), _un("sqrt", _sub(_c(1.0), _mul(ac, a.copy())))
        ),
        "acos": lambda: _un(
            "neg",
            _div(_c(1.0), _un("sqrt", _sub(_c(1.0), _mul(ac, a.copy())))),
        ),
        "atan": lambda: _div(_c(1.0), _add(_c(1.0), _mul(ac, a.copy()))),
        "asinh": lambda: _div(
            _c(1.0), _un("sqrt", _add(_c(1.0), _mul(ac, a.copy())))
        ),
        "acosh": lambda: _div(
            _c(1.0), _un("sqrt", _sub(_mul(ac, a.copy()), _c(1.0)))
        ),
        "atanh": lambda: _div(_c(1.0), _sub(_c(1.0), _mul(ac, a.copy()))),
        "erf": lambda: _mul(
            _c(2.0 / np.sqrt(np.pi)),
            _un("exp", _un("neg", _mul(ac, a.copy()))),
        ),
        "erfc": lambda: _mul(
            _c(-2.0 / np.sqrt(np.pi)),
            _un("exp", _un("neg", _mul(ac, a.copy()))),
        ),
        "relu": lambda: Node(op=_op("greater"), children=[ac, _c(0.0)]),
        "sign": lambda: _c(0.0),
        "round": lambda: _c(0.0),
        "floor": lambda: _c(0.0),
        "ceil": lambda: _c(0.0),
    }
    if name not in rules:
        raise ValueError(f"No derivative rule for unary operator {name!r}")
    outer = rules[name]()
    return _mul(outer, da)
