"""Device-side complexity and constraint checking.

Tensorized equivalents of src/Complexity.jl (compute_complexity over a
ComplexityMapping) and src/CheckConstraints.jl (maxsize / maxdepth /
per-operator argument-size constraints / nested-operator constraints).
All checks run batched over candidate trees inside the jitted generation
step — the reference's post-mutation rejection loop becomes a boolean mask.

The postfix encoding makes subtree aggregates cheap: a subtree is the
contiguous slot range ``[k - size_k + 1, k]``, so subtree sums are prefix
sum differences; "max along any path" quantities use one O(L) stack scan.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.options import Options
from .encoding import LEAF_CONST, LEAF_PARAM, LEAF_VAR, MAX_ARITY, TreeBatch

__all__ = ["ComplexityTables", "build_complexity_tables", "compute_complexity_batch",
           "check_constraints_batch"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ComplexityTables:
    unary_w: jax.Array    # [max(U,1)]
    binary_w: jax.Array   # [max(B,1)]
    variable_w: jax.Array  # [nfeatures]
    constant_w: jax.Array  # scalar


def build_complexity_tables(options: Options, nfeatures: int) -> ComplexityTables:
    cm = options.complexity_mapping
    U = max(len(options.operators.unary), 1)
    B = max(len(options.operators.binary), 1)
    un = np.ones(U, np.float32)
    bi = np.ones(B, np.float32)
    if cm.use:
        for i, w in enumerate(cm.op_complexities.get(1, [])):
            un[i] = w
        for i, w in enumerate(cm.op_complexities.get(2, [])):
            bi[i] = w
    if isinstance(cm.variable_complexity, list):
        var = np.asarray(cm.variable_complexity, np.float32)
        if var.shape[0] != nfeatures:
            raise ValueError(
                f"complexity_of_variables has {var.shape[0]} entries; expected {nfeatures}"
            )
    else:
        var = np.full(nfeatures, cm.variable_complexity, np.float32)
    return ComplexityTables(
        unary_w=jnp.asarray(un),
        binary_w=jnp.asarray(bi),
        variable_w=jnp.asarray(var),
        constant_w=jnp.asarray(np.float32(cm.constant_complexity)),
    )


def _node_weights(batch: TreeBatch, tables: ComplexityTables) -> jax.Array:
    """Per-slot complexity weight (garbage at padded slots; callers mask)."""
    a, o, f = batch.arity, batch.op, batch.feat
    nF = tables.variable_w.shape[0]
    leaf_w = jnp.where(
        o == LEAF_CONST,
        tables.constant_w,
        tables.variable_w[jnp.clip(f, 0, nF - 1)],
    )
    un_w = tables.unary_w[jnp.clip(o, 0, tables.unary_w.shape[0] - 1)]
    bi_w = tables.binary_w[jnp.clip(o, 0, tables.binary_w.shape[0] - 1)]
    return jnp.where(a == 0, leaf_w, jnp.where(a == 1, un_w, bi_w))


def compute_complexity_batch(batch: TreeBatch, tables: ComplexityTables) -> jax.Array:
    """Rounded-int complexity per tree (src/Complexity.jl:20-63)."""
    w = _node_weights(batch, tables)
    L = batch.max_nodes
    mask = jnp.arange(L) < batch.length[..., None]
    raw = jnp.sum(jnp.where(mask, w, 0.0), axis=-1)
    return jnp.round(raw).astype(jnp.int32)


def _postfix_max_plus(vals: jax.Array, arity: jax.Array) -> jax.Array:
    """r[k] = vals[k] + max(r[children of k], default 0) — one stack scan.

    Computes, for each node, the maximum sum of `vals` along any root-to-leaf
    path *within its subtree* (the tree_mapreduce pattern at
    /root/reference/src/CheckConstraints.jl:34-46). Unbatched [L] arrays.
    """
    L = arity.shape[0]

    def step(carry, k):
        stack, sp = carry
        a = arity[k]
        best = jnp.zeros((), vals.dtype)
        for j in range(MAX_ARITY):
            pos = sp - a + j
            valid = j < a
            best = jnp.maximum(best, jnp.where(valid, stack[jnp.maximum(pos, 0)], 0))
        r_k = vals[k] + best
        new_sp = sp - a + 1
        stack = stack.at[new_sp - 1].set(r_k)
        return (stack, new_sp), r_k

    init = (jnp.zeros((L,), vals.dtype), jnp.int32(0))
    _, r = jax.lax.scan(step, init, jnp.arange(L, dtype=jnp.int32), unroll=True)
    return r


def _subtree_sums(w: jax.Array, size: jax.Array) -> jax.Array:
    """Subtree sums via the contiguous-span prefix-sum trick. Unbatched [L]."""
    csum = jnp.concatenate([jnp.zeros((1,), w.dtype), jnp.cumsum(w)])
    k = jnp.arange(w.shape[0])
    start = k - size + 1
    return csum[k + 1] - csum[jnp.clip(start, 0, None)]


def check_constraints_batch(
    batch: TreeBatch,
    options: Options,
    tables: ComplexityTables,
    cur_maxsize: jax.Array,
    child: jax.Array = None,
    size: jax.Array = None,
    depth: jax.Array = None,
) -> jax.Array:
    """Vectorized check_constraints (src/CheckConstraints.jl:66-96).

    `child/size/depth` may be precomputed by the caller; otherwise they
    are derived here *only if* the configured constraints need them.
    Returns bool[...] (True = satisfies all constraints).
    """
    from .encoding import tree_structure_arrays

    L = batch.max_nodes
    batch_shape = batch.batch_shape
    slot = jnp.arange(L)
    mask = slot < batch.length[..., None]

    complexity = compute_complexity_batch(batch, tables)
    ok = complexity <= cur_maxsize

    has_op_cons = any(
        any(c != -1 for c in cons)
        for d, conslist in options.op_constraints.items()
        for cons in conslist
    )

    if options.maxdepth < L:
        if depth is None:
            child, size, depth = tree_structure_arrays(batch, need_depth=True)
        root_depth = jnp.max(jnp.where(mask, depth, 0), axis=-1)
        ok = ok & (root_depth <= options.maxdepth)

    # Per-operator argument-size constraints
    # (flag_operator_complexity, src/CheckConstraints.jl:14-32).
    if has_op_cons or options.nested_constraints:
        if size is None:
            child, size, _ = tree_structure_arrays(batch, need_depth=False)
        w = _node_weights(batch, tables)
        flat_w = w.reshape(-1, L)
        flat_size = size.reshape(-1, L)
        sub_cx = jax.vmap(_subtree_sums)(flat_w, flat_size).reshape(*batch_shape, L)

    if has_op_cons:
        for d, conslist in options.op_constraints.items():
            for op_idx, cons in enumerate(conslist):
                if all(c == -1 for c in cons):
                    continue
                is_target = mask & (batch.arity == d) & (batch.op == op_idx)
                for j, limit in enumerate(cons):
                    if limit == -1:
                        continue
                    cj = child[..., j]
                    child_cx = jnp.take_along_axis(sub_cx, cj, axis=-1)
                    violation = is_target & (jnp.round(child_cx) > limit)
                    ok = ok & ~jnp.any(violation, axis=-1)

    # Nested-operator constraints (flag_illegal_nests, :49-63).
    for (d, op_idx, inners) in options.nested_constraints:
        is_outer = mask & (batch.arity == d) & (batch.op == op_idx)
        for (nd, ni, max_nest) in inners:
            is_inner = (mask & (batch.arity == nd) & (batch.op == ni)).astype(jnp.int32)
            flat_inner = is_inner.reshape(-1, L)
            flat_arity = batch.arity.reshape(-1, L)
            r = jax.vmap(_postfix_max_plus)(flat_inner, flat_arity)
            r = r.reshape(*batch_shape, L)
            nestedness = r - is_inner  # exclude self-match (:44-45)
            violation = is_outer & (nestedness > max_nest)
            ok = ok & ~jnp.any(violation, axis=-1)

    return ok
